"""DCGAN with multi-model / multi-optimizer / multi-loss amp (reference:
examples/dcgan/main_amp.py, 274 LoC — the example exercising
``amp.initialize([netD, netG], [optD, optG], num_losses=3)`` with per-loss
``loss_id`` 0/1/2, reference :214-253).

Three scaled losses per iteration: D-real (loss_id 0), D-fake (1), G (2),
each with its own LossScaler so one loss overflowing doesn't shrink the
others' scales.  ``--synthetic`` (default) trains on noise images.

``--fused`` runs the same iteration through ``make_gan_train_step``
instead: the whole alternating D/G update compiles into one XLA
executable (per-network scalers, same reference ordering).
"""
import argparse

import jax.numpy as jnp
import numpy as np

import apex_tpu.nn as nn
from apex_tpu import amp
from apex_tpu.optimizers import FusedAdam


def parse_args():
    p = argparse.ArgumentParser()
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--image-size", type=int, default=32)
    p.add_argument("--nz", type=int, default=64, help="latent dim")
    p.add_argument("--ngf", type=int, default=32)
    p.add_argument("--ndf", type=int, default=32)
    p.add_argument("--iters", type=int, default=10)
    p.add_argument("--lr", type=float, default=2e-4)
    p.add_argument("--opt-level", default="O1",
                   choices=["O0", "O1", "O2", "O3"])
    p.add_argument("--fused", action="store_true",
                   help="one-executable GAN iteration (make_gan_train_step)")
    return p.parse_args()


def build_generator(nz, ngf):
    # 4x4 -> 8x8 -> 16x16 -> 32x32
    return nn.Sequential(
        nn.ConvTranspose2d(nz, ngf * 4, 4, stride=1, padding=0),
        nn.BatchNorm2d(ngf * 4), nn.ReLU(),
        nn.ConvTranspose2d(ngf * 4, ngf * 2, 4, stride=2, padding=1),
        nn.BatchNorm2d(ngf * 2), nn.ReLU(),
        nn.ConvTranspose2d(ngf * 2, ngf, 4, stride=2, padding=1),
        nn.BatchNorm2d(ngf), nn.ReLU(),
        nn.ConvTranspose2d(ngf, 3, 4, stride=2, padding=1),
        nn.Tanh())


def build_discriminator(ndf):
    return nn.Sequential(
        nn.Conv2d(3, ndf, 4, stride=2, padding=1), nn.LeakyReLU(0.2),
        nn.Conv2d(ndf, ndf * 2, 4, stride=2, padding=1),
        nn.BatchNorm2d(ndf * 2), nn.LeakyReLU(0.2),
        nn.Conv2d(ndf * 2, ndf * 4, 4, stride=2, padding=1),
        nn.BatchNorm2d(ndf * 4), nn.LeakyReLU(0.2),
        nn.Conv2d(ndf * 4, 1, 4, stride=1, padding=0),
        nn.Flatten(0))


def run_fused(args, netD, netG, optD, optG):
    """The same three-loss iteration as one compiled executable."""
    from apex_tpu.nn import functional as F
    from apex_tpu.training import make_gan_train_step

    def d_loss(out_r, out_f):
        ones = jnp.ones_like(out_r)
        zeros = jnp.zeros_like(out_f)
        return (F.binary_cross_entropy_with_logits(out_r, ones)
                + F.binary_cross_entropy_with_logits(out_f, zeros))

    def g_loss(out_f):
        return F.binary_cross_entropy_with_logits(
            out_f, jnp.ones_like(out_f))

    half = jnp.bfloat16 if args.opt_level in ("O2", "O3") else None
    scale = 1.0 if args.opt_level in ("O0", "O3") else "dynamic"
    step = make_gan_train_step(netD, netG, optD, optG, d_loss, g_loss,
                               half_dtype=half, loss_scale=scale)
    rng = np.random.default_rng(0)
    for it in range(args.iters):
        real = jnp.asarray(
            rng.standard_normal(
                (args.batch_size, 3, args.image_size, args.image_size)),
            jnp.float32)
        noise = jnp.asarray(
            rng.standard_normal((args.batch_size, args.nz, 1, 1)),
            jnp.float32)
        errD, errG = step(real, noise)
        print(f"[{it}/{args.iters}] Loss_D {float(errD):.4f} "
              f"Loss_G {float(errG):.4f}")


def main():
    args = parse_args()
    nn.manual_seed(0)
    netG = build_generator(args.nz, args.ngf)
    netD = build_discriminator(args.ndf)
    optG = FusedAdam(list(netG.parameters()), lr=args.lr, betas=(0.5, 0.999))
    optD = FusedAdam(list(netD.parameters()), lr=args.lr, betas=(0.5, 0.999))

    if args.fused:
        return run_fused(args, netD, netG, optD, optG)

    # the multi-model/multi-optimizer/multi-loss form (reference :214-215)
    [netD, netG], [optD, optG] = amp.initialize(
        [netD, netG], [optD, optG], opt_level=args.opt_level, num_losses=3)

    criterion = nn.BCEWithLogitsLoss()
    rng = np.random.default_rng(0)
    real_label, fake_label = 1.0, 0.0

    for it in range(args.iters):
        real = jnp.asarray(
            rng.standard_normal(
                (args.batch_size, 3, args.image_size, args.image_size)),
            jnp.float32)
        noise = jnp.asarray(
            rng.standard_normal((args.batch_size, args.nz, 1, 1)),
            jnp.float32)

        # --- D on real (loss_id 0, reference :230) ---
        optD.zero_grad()
        out = netD(real)
        lbl = jnp.full((args.batch_size,), real_label, jnp.float32)
        errD_real = criterion(out, lbl)
        with amp.scale_loss(errD_real, optD, loss_id=0) as errD_real_scaled:
            errD_real_scaled.backward()

        # --- D on fake (loss_id 1, reference :240) ---
        fake = netG(noise)
        out = netD(fake.detach())
        lbl = jnp.full((args.batch_size,), fake_label, jnp.float32)
        errD_fake = criterion(out, lbl)
        with amp.scale_loss(errD_fake, optD, loss_id=1) as errD_fake_scaled:
            errD_fake_scaled.backward()
        optD.step()

        # --- G (loss_id 2, reference :253) ---
        optG.zero_grad()
        out = netD(fake)
        lbl = jnp.full((args.batch_size,), real_label, jnp.float32)
        errG = criterion(out, lbl)
        with amp.scale_loss(errG, optG, loss_id=2) as errG_scaled:
            errG_scaled.backward()
        optG.step()

        print(f"[{it}/{args.iters}] Loss_D {float(errD_real) + float(errD_fake):.4f} "
              f"Loss_G {float(errG):.4f}")


if __name__ == "__main__":
    main()
