"""Minimal distributed example (reference:
examples/simple/distributed/distributed_data_parallel.py, 67 LoC — O1 amp +
DDP on a toy model).

On TPU the "launcher" is the mesh: a single process drives all local
devices; multi-host runs add ``parallel.init_distributed()`` (the
``apex.parallel.multiproc`` role).  Run: ``python
distributed_data_parallel.py`` (uses every visible device).
"""
import jax.numpy as jnp
import numpy as np

import apex_tpu.nn as nn
from apex_tpu import amp
from apex_tpu.optimizers import FusedSGD
from apex_tpu.parallel import DistributedDataParallel


def main():
    nn.manual_seed(0)
    model = nn.Sequential(nn.Linear(10, 32), nn.ReLU(), nn.Linear(32, 2))
    optimizer = FusedSGD(list(model.parameters()), lr=0.1, momentum=0.9)
    model, optimizer = amp.initialize(model, optimizer, opt_level="O1")
    model = DistributedDataParallel(model)
    criterion = nn.MSELoss()

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((32, 10)), jnp.float32)
    y = jnp.asarray(rng.standard_normal((32, 2)), jnp.float32)

    for step in range(20):
        out = model(x)
        loss = criterion(out, y)
        optimizer.zero_grad()
        with amp.scale_loss(loss, optimizer) as scaled_loss:
            scaled_loss.backward()
        optimizer.step()
        if step % 5 == 0:
            print(f"step {step}: loss {float(loss):.5f}")
    print("final loss:", float(loss))


if __name__ == "__main__":
    main()
