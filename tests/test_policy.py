"""O1 policy engine: dtype-propagation matrix per op category
(mirrors tests/L0/run_amp/test_basic_casts.py:14-100 in the reference —
linear ALWAYS_HALF, softmax ALWAYS_FLOAT, promotion to widest, banned raises).
"""
import types

import numpy as np

import jax.numpy as jnp
import pytest

from apex_tpu.amp import policy
from apex_tpu.amp.policy import CastPolicy, apply_op_policy, autocast


@pytest.fixture
def pol():
    return CastPolicy(half_dtype=jnp.float16)


def test_half_op_casts_down(pol):
    x = jnp.ones((4, 4), jnp.float32)
    with autocast(pol):
        (args, _) = apply_op_policy("linear", (x,))[0], None
    assert args[0].dtype == jnp.float16


def test_float_op_casts_up(pol):
    x = jnp.ones((4, 4), jnp.float16)
    with autocast(pol):
        args, _ = apply_op_policy("softmax", (x,))
    assert args[0].dtype == jnp.float32


def test_match_input_untouched(pol):
    # ops in no list (e.g. relu) pass through
    x = jnp.ones((4,), jnp.float16)
    with autocast(pol):
        args, _ = apply_op_policy("relu", (x,))
    assert args[0].dtype == jnp.float16


def test_promotion_to_widest(pol):
    a = jnp.ones((4,), jnp.float16)
    b = jnp.ones((4,), jnp.float32)
    with autocast(pol):
        args, _ = apply_op_policy("add", (a, b))
    assert args[0].dtype == jnp.float32 and args[1].dtype == jnp.float32


def test_promotion_same_dtype_stays(pol):
    a = jnp.ones((4,), jnp.float16)
    b = jnp.ones((4,), jnp.float16)
    with autocast(pol):
        args, _ = apply_op_policy("add", (a, b))
    assert args[0].dtype == jnp.float16


def test_int_args_untouched(pol):
    idx = jnp.ones((4,), jnp.int32)
    x = jnp.ones((4,), jnp.float32)
    with autocast(pol):
        args, _ = apply_op_policy("linear", (x, idx))
    assert args[1].dtype == jnp.int32


def test_banned_raises(pol):
    x = jnp.ones((4,), jnp.float16)
    with autocast(pol):
        with pytest.raises(NotImplementedError):
            apply_op_policy("binary_cross_entropy", (x,))


def test_banned_allowed_when_opted_in():
    pol = CastPolicy(allow_banned=True)
    x = jnp.ones((4,), jnp.float16)
    with autocast(pol):
        args, _ = apply_op_policy("binary_cross_entropy", (x,))
    assert args[0].dtype == jnp.float16


def test_no_policy_is_noop():
    x = jnp.ones((4,), jnp.float32)
    args, _ = apply_op_policy("linear", (x,))
    assert args[0].dtype == jnp.float32


def test_disable_casts_inside_policy(pol):
    x = jnp.ones((4, 4), jnp.float32)
    with autocast(pol):
        with policy.disable_casts():
            args, _ = apply_op_policy("linear", (x,))
    assert args[0].dtype == jnp.float32


def test_bfloat16_policy():
    pol = CastPolicy(half_dtype=jnp.bfloat16)
    x = jnp.ones((4, 4), jnp.float32)
    with autocast(pol):
        args, _ = apply_op_policy("conv2d", (x,))
    assert args[0].dtype == jnp.bfloat16


def test_register_half_function_on_user_module(pol):
    mod = types.SimpleNamespace(myop=lambda x: x)
    policy.register_half_function(mod, "myop")
    x = jnp.ones((4,), jnp.float32)
    with autocast(pol):
        y = mod.myop(x)
    assert y.dtype == jnp.float32  # pol predates registration? no — stack reg
    # a policy created after registration picks it up via replay
    pol2 = CastPolicy()
    policy.replay_registrations(pol2)
    with autocast(pol2):
        y2 = mod.myop(x)
    assert y2.dtype == jnp.float16


def test_decorators(pol):
    @policy.half_function
    def h(x):
        return x

    @policy.float_function
    def f(x):
        return x

    x32 = jnp.ones((2,), jnp.float32)
    x16 = jnp.ones((2,), jnp.float16)
    with autocast(pol):
        assert h(x32).dtype == jnp.float16
        assert f(x16).dtype == jnp.float32
    # inactive outside policy
    assert h(x32).dtype == jnp.float32
    assert f(x16).dtype == jnp.float16

