"""Fused GAN train step (training/gan.py) vs the imperative multi-model
path — the fused-path analogue of the reference's DCGAN multi-model /
multi-loss amp config (examples/dcgan/main_amp.py:214-253)."""
import jax.numpy as jnp
import numpy as np
import pytest

import apex_tpu.nn as nn
from apex_tpu.nn import functional as F
from apex_tpu.optimizers import FusedAdam
from apex_tpu.training import make_gan_train_step

ZDIM = 8


class _Reshape(nn.Module):
    def __init__(self, shape):
        super().__init__()
        self.shape = shape

    def forward(self, ctx, x):
        return x.reshape((x.shape[0],) + self.shape)


def _gan():
    nn.manual_seed(11)
    netD = nn.Sequential(
        nn.Conv2d(1, 8, 3, stride=2, padding=1, bias=False),
        nn.BatchNorm2d(8), nn.LeakyReLU(0.2),
        nn.Flatten(), nn.Linear(8 * 4 * 4, 1), nn.Sigmoid())
    netG = nn.Sequential(
        nn.Linear(ZDIM, 64), nn.ReLU(), nn.Linear(64, 64), nn.Tanh(),
        _Reshape((1, 8, 8)))
    return netD, netG


def _losses():
    """BCE-style GAN losses, written against the common Tensor/array math
    surface so the same fns drive both the fused step (raw jnp arrays) and
    the imperative tape path (autograd Tensors)."""
    eps = 1e-6

    def _mean_log(x):
        return x.log().mean() if hasattr(x, "backward") \
            else jnp.mean(jnp.log(x))

    def d_loss(out_r, out_f):
        return -(_mean_log(out_r + eps) + _mean_log(1.0 - out_f + eps))

    def g_loss(out_f):
        return -_mean_log(out_f + eps)
    return d_loss, g_loss


def _data(rng, n=8):
    real = jnp.asarray(rng.standard_normal((n, 1, 8, 8)), jnp.float32)
    z = jnp.asarray(rng.standard_normal((n, ZDIM)), jnp.float32)
    return real, z


def test_gan_step_runs_and_updates_both_nets(rng):
    netD, netG = _gan()
    d_loss, g_loss = _losses()
    optD = FusedAdam(list(netD.parameters()), lr=2e-3, betas=(0.5, 0.999))
    optG = FusedAdam(list(netG.parameters()), lr=2e-3, betas=(0.5, 0.999))
    step = make_gan_train_step(netD, netG, optD, optG, d_loss, g_loss,
                               loss_scale=1.0)
    d0 = [np.asarray(m) for m in step.state.d.master_params]
    g0 = [np.asarray(m) for m in step.state.g.master_params]
    real, z = _data(rng)
    for _ in range(3):
        errD, errG = step(real, z)
        assert np.isfinite(float(errD)) and np.isfinite(float(errG))
    assert any(not np.allclose(a, np.asarray(b))
               for a, b in zip(d0, step.state.d.master_params))
    assert any(not np.allclose(a, np.asarray(b))
               for a, b in zip(g0, step.state.g.master_params))
    assert int(step.state.d.step) == 3 and int(step.state.g.step) == 3


def test_gan_step_matches_imperative(rng):
    """The fused GAN iteration must match the tape-driven loop exactly
    (same ordering: errG computed through the post-step discriminator)."""
    real, z = _data(rng)
    d_loss, g_loss = _losses()

    # imperative path
    netD_a, netG_a = _gan()
    optD_a = FusedAdam(list(netD_a.parameters()), lr=2e-3)
    optG_a = FusedAdam(list(netG_a.parameters()), lr=2e-3)
    errD_hist, errG_hist = [], []
    for _ in range(3):
        # reference DCGAN ordering: zero D at iteration start (errG.backward
        # deposits grads through D as well; they must be discarded)
        optD_a.zero_grad()
        fake = netG_a(z)
        errD = d_loss(netD_a(real), netD_a(fake.detach()))
        errD.backward()
        optD_a.step()
        optG_a.zero_grad()
        errG = g_loss(netD_a(fake))
        errG.backward()
        optG_a.step()
        errD_hist.append(float(errD))
        errG_hist.append(float(errG))

    # fused path
    netD_b, netG_b = _gan()
    optD_b = FusedAdam(list(netD_b.parameters()), lr=2e-3)
    optG_b = FusedAdam(list(netG_b.parameters()), lr=2e-3)
    step = make_gan_train_step(netD_b, netG_b, optD_b, optG_b,
                               d_loss, g_loss, loss_scale=1.0)
    for i in range(3):
        errD, errG = step(real, z)
        np.testing.assert_allclose(float(errD), errD_hist[i],
                                   rtol=2e-4, atol=1e-6)
        np.testing.assert_allclose(float(errG), errG_hist[i],
                                   rtol=2e-4, atol=1e-6)

    for pa, mb in zip(netD_a.parameters(), step.state.d.master_params):
        np.testing.assert_allclose(np.asarray(pa.data), np.asarray(mb),
                                   rtol=2e-4, atol=2e-6)
    for pa, mb in zip(netG_a.parameters(), step.state.g.master_params):
        np.testing.assert_allclose(np.asarray(pa.data), np.asarray(mb),
                                   rtol=2e-4, atol=2e-6)


def test_gan_step_overflow_skips_only_that_net(rng):
    """A D overflow must leave D untouched while G still updates (per-loss
    scalers, reference dcgan loss_id semantics)."""
    netD, netG = _gan()
    d_loss, g_loss = _losses()

    def d_loss_inf(out_r, out_f):
        return d_loss(out_r, out_f) * jnp.float32(1e38) * jnp.float32(1e38)

    optD = FusedAdam(list(netD.parameters()), lr=2e-3)
    optG = FusedAdam(list(netG.parameters()), lr=2e-3)
    step = make_gan_train_step(netD, netG, optD, optG, d_loss_inf, g_loss,
                               loss_scale="dynamic")
    real, z = _data(rng)
    d0 = [np.asarray(m) for m in step.state.d.master_params]
    scale0 = float(step.state.d.scaler.loss_scale)
    step(real, z)
    for a, b in zip(d0, step.state.d.master_params):
        np.testing.assert_array_equal(a, np.asarray(b))
    assert float(step.state.d.scaler.loss_scale) == scale0 / 2
    assert int(step.state.d.step) == 0
    assert int(step.state.g.step) == 1


def test_gan_step_sync_to_objects(rng):
    netD, netG = _gan()
    d_loss, g_loss = _losses()
    optD = FusedAdam(list(netD.parameters()), lr=2e-3)
    optG = FusedAdam(list(netG.parameters()), lr=2e-3)
    step = make_gan_train_step(netD, netG, optD, optG, d_loss, g_loss,
                               loss_scale=1.0, half_dtype=jnp.bfloat16)
    real, z = _data(rng)
    step(real, z)
    step.sync_to_objects()
    # non-BN params got the half value; BN stayed fp32
    assert netD[0].weight.dtype == jnp.bfloat16
    assert netD[1].weight.dtype == jnp.float32
    # BN running stats advanced
    assert not np.allclose(np.asarray(netD[1].running_mean.data), 0.0)


def test_gan_step_with_dropout_discriminator(rng):
    """A D containing Dropout must train through the fused GAN step, each
    of the three D forwards drawing its own mask (per-forward keys)."""
    nn.manual_seed(13)
    netD = nn.Sequential(
        nn.Flatten(), nn.Linear(64, 32), nn.LeakyReLU(0.2), nn.Dropout(0.5),
        nn.Linear(32, 1), nn.Sigmoid())
    netG = nn.Sequential(nn.Linear(ZDIM, 64), nn.Tanh(), _Reshape((1, 8, 8)))
    d_loss, g_loss = _losses()
    optD = FusedAdam(list(netD.parameters()), lr=2e-3)
    optG = FusedAdam(list(netG.parameters()), lr=2e-3)
    step = make_gan_train_step(netD, netG, optD, optG, d_loss, g_loss,
                               loss_scale=1.0)
    real, z = _data(rng)
    for _ in range(3):
        errD, errG = step(real, z)
        assert np.isfinite(float(errD)) and np.isfinite(float(errG))
    assert int(step.state.d.step) == 3 and int(step.state.g.step) == 3


def test_gan_step_lr_schedule_applies(rng):
    """A 0.1x schedule multiplier shrinks both networks' first-step
    updates vs the unscheduled run."""
    import jax.numpy as jnp
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.training import make_gan_train_step

    def build(sched):
        nn.manual_seed(0)
        netD = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 1))
        netG = nn.Sequential(nn.Linear(4, 16), nn.ReLU(), nn.Linear(16, 8))
        optD = FusedAdam(list(netD.parameters()), lr=1e-2)
        optG = FusedAdam(list(netG.parameters()), lr=1e-2)

        def d_loss(dr, df):
            return jnp.mean((dr - 1.0) ** 2) + jnp.mean(df ** 2)

        def g_loss(df):
            return jnp.mean((df - 1.0) ** 2)

        return make_gan_train_step(netD, netG, optD, optG, d_loss, g_loss,
                                   half_dtype=None, loss_scale=1.0,
                                   donate_state=False, lr_schedule=sched)

    real = jnp.asarray(rng.standard_normal((8, 8)), jnp.float32)
    z = jnp.asarray(rng.standard_normal((8, 4)), jnp.float32)

    def first_deltas(sched):
        step = build(sched)
        d0 = np.asarray(step.state.d.master_params[0])
        g0 = np.asarray(step.state.g.master_params[0])
        state, _ = step._step_fn(step.state, real, z)
        return (np.abs(np.asarray(state.d.master_params[0]) - d0).max(),
                np.abs(np.asarray(state.g.master_params[0]) - g0).max())

    full_d, full_g = first_deltas(None)
    s_d, s_g = first_deltas(lambda s: jnp.asarray(0.1, jnp.float32))
    assert s_d < full_d * 0.5 and s_g < full_g * 0.5
