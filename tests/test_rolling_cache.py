"""Rolling sliding-window KV cache (inference/rolling.py + the Llama
family's windowed decode): a ``sliding_window=w`` model allocates
exactly ``w`` cache slots (decode cache HBM O(window), not
O(context)), writes modularly, and attends [pre-write cache | fresh
chunk] so every query sees its whole band.

Oracles: the closed-form slot positions vs a naive full-history numpy
simulation; decode == teacher-forced forward (the banded flash
forward is exact at any length); speculative exactness; existing
Mistral-window suites (tests/test_llama.py) run against the same
rolling path.  Reference analogue: none (training-side library,
SURVEY.md §2) — the rolling buffer is banded attention's standard
serving companion."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import apex_tpu.nn as nn
from apex_tpu.inference.rolling import (rolling_kv_write,
                                        rolling_slot_positions)
from apex_tpu.models.gpt import generate
from apex_tpu.models.llama import LlamaModel
from apex_tpu.nn.modules import Ctx

V = 89
W = 8


def _model(**kw):
    nn.manual_seed(5)
    return LlamaModel(vocab_size=V, hidden=32, layers=2, heads=4,
                      kv_heads=2, max_positions=64, sliding_window=W,
                      **kw)


def test_windowed_cache_allocates_window_slots():
    from apex_tpu.inference.rolling import ROLLING_SLACK

    m = _model()
    caches = m.init_caches(1, 64)
    for kc, vc in caches:
        # window + the speculative-rewind margin, not the context
        assert kc.shape[2] == W + ROLLING_SLACK
        assert vc.shape[2] == W + ROLLING_SLACK
    # window wider than the context: cache stays context-sized
    nn.manual_seed(5)
    wide = LlamaModel(vocab_size=V, hidden=32, layers=2, heads=4,
                      kv_heads=2, max_positions=16, sliding_window=100)
    assert wide.init_caches(1, 12)[0][0].shape[2] == 12


def test_rolling_write_and_slots_match_naive_simulation(rng):
    """Write chunks of assorted lengths; the W-slot cache + closed-form
    positions must equal keeping full history and taking, per slot s,
    the latest position == s (mod W)."""
    full = np.zeros((1, 2, 40, 4), np.float32)
    cache = jnp.zeros((1, 2, W, 4))
    t = 0
    for length in (3, 1, W, 5, 2, 11):
        chunk = rng.standard_normal((1, 2, length, 4)).astype(np.float32)
        full[:, :, t:t + length] = chunk
        cache = rolling_kv_write(cache, jnp.asarray(chunk), t)
        t += length
        slots = np.asarray(rolling_slot_positions(W, t))
        for s in range(W):
            p = slots[s]
            written = [q for q in range(t) if q % W == s]
            if not written:
                assert p < 0          # never-written sentinel
                continue
            assert p == max(written)
            np.testing.assert_allclose(np.asarray(cache)[:, :, s],
                                       full[:, :, p], rtol=1e-6)


def test_windowed_decode_matches_teacher_forced_forward(rng):
    """Long generation (context far beyond the window): greedy decode
    must agree with the exact banded-flash FORWARD re-scoring of its
    own output at every generated position."""
    m = _model()
    m.eval()
    prompt = jnp.asarray(rng.integers(0, V, (1, 20)))
    out = generate(m, prompt, 30)
    ctx = Ctx(training=False)
    logits = m.forward(ctx, out)
    redo = np.asarray(jnp.argmax(logits, axis=-1))
    got = np.asarray(out)
    # position t's forward argmax must be the token decoded at t+1
    np.testing.assert_array_equal(got[0, 20:], redo[0, 19:-1])


def test_windowed_speculative_exactness(rng):
    """40 new tokens: the speculative buffer (6+40+5=51 positions)
    exceeds the W+ROLLING_SLACK=40-slot cache, so rejected-chunk writes
    DO alias mod the cache size — the rewind-margin masking argument
    (inference/rolling.py ROLLING_SLACK) is exercised, not just
    stated."""
    from apex_tpu.inference.rolling import ROLLING_SLACK
    from apex_tpu.inference.speculative import speculative_generate

    m = _model()
    m.eval()
    nn.manual_seed(9)
    draft = LlamaModel(vocab_size=V, hidden=16, layers=1, heads=2,
                       max_positions=64)
    draft.eval()
    prompt = jnp.asarray(rng.integers(0, V, (1, 6)))
    assert 6 + 40 + 5 > W + ROLLING_SLACK    # the cache must wrap
    want = np.asarray(generate(m, prompt, 40))
    got = np.asarray(speculative_generate(m, draft, prompt, 40, k=4))
    np.testing.assert_array_equal(got, want)


def test_windowed_int8_and_beam_run(rng):
    from apex_tpu.inference import beam_generate

    m = _model()
    m.eval()
    prompt = jnp.asarray(rng.integers(0, V, (1, 6)))
    out = generate(m, prompt, 12, cache_dtype="int8")
    assert out.shape == (1, 18)
    assert (np.asarray(out)[:, :6] == np.asarray(prompt)).all()
    b = beam_generate(m, prompt, 10, num_beams=3)
    assert b.shape == (1, 16)


def test_windowed_decode_chunk_longer_than_window(rng):
    """A direct decode_chunk longer than the window works in one call
    (in-chunk keys come from the fresh rows, not the cache) and agrees
    with the teacher-forced forward."""
    m = _model()
    m.eval()
    toks = jnp.asarray(rng.integers(0, V, (1, 21)))
    ctx = Ctx(training=False)
    caches = m.init_caches(1, 32)
    got, caches = m.decode_chunk(ctx, toks, caches, 0)
    want = m.forward(Ctx(training=False), toks)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)
    # and the cache is correctly positioned for a follow-up chunk
    nxt = jnp.asarray(rng.integers(0, V, (1, 3)))
    got2, _ = m.decode_chunk(ctx, nxt, caches, 21)
    full = m.forward(Ctx(training=False),
                     jnp.concatenate([toks, nxt], axis=1))
    np.testing.assert_allclose(np.asarray(got2),
                               np.asarray(full[:, 21:]),
                               rtol=2e-4, atol=2e-4)


def test_undersized_windowed_cache_refuses_wrap(rng):
    """A cache allocated smaller than the rolling size (the caller
    declared fewer positions) must refuse writes past its slots instead
    of wrapping — wrapping would evict keys still inside the wide
    band."""
    nn.manual_seed(5)
    wide = LlamaModel(vocab_size=V, hidden=32, layers=2, heads=4,
                      kv_heads=2, max_positions=64, sliding_window=100)
    wide.eval()
    caches = wide.init_caches(1, 12)          # 12 slots, band is 100
    ctx = Ctx(training=False)
    toks = jnp.asarray(rng.integers(0, V, (1, 3)))
    _, caches = wide.decode_chunk(ctx, toks, caches, 0)
    with pytest.raises(ValueError, match="cache capacity"):
        wide.decode_chunk(ctx, toks, caches, 12)


def test_random_chunk_schedules_match_forward(rng):
    """Property-style: several random decode_chunk interleavings
    (assorted chunk lengths, incl. window-straddling and
    longer-than-window) must all reproduce the teacher-forced banded
    forward — the cache protocol is schedule-invariant."""
    m = _model()
    m.eval()
    toks = jnp.asarray(rng.integers(0, V, (1, 40)))
    want = np.asarray(m.forward(Ctx(training=False), toks))
    for trial in range(3):
        sizes = []
        left = 40
        while left:
            c = int(rng.integers(1, min(left, 13) + 1))
            sizes.append(c)
            left -= c
        caches = m.init_caches(1, 40)
        ctx = Ctx(training=False)
        outs = []
        t = 0
        for c in sizes:
            lg, caches = m.decode_chunk(ctx, toks[:, t:t + c], caches, t)
            outs.append(np.asarray(lg))
            t += c
        got = np.concatenate(outs, axis=1)
        np.testing.assert_allclose(got, want, rtol=3e-4, atol=3e-4,
                                   err_msg=f"schedule {sizes}")
