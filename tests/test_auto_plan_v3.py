"""Planner v3: the joint pp × remat × offload × ep search.

Pins the ISSUE-19 contracts:
  * ``plan_from_key(static_plan_key(p)) == p`` over randomized plans
    including every new axis, and unknown segments are a clear error;
  * a toy deep-GPT profile where every dp×tp×zero-only plan predicts
    OOM on a v5e still gets a feasible pp×remat plan from the joint
    search, under a wall-clock budget on CPU;
  * heterogeneous fleets pipeline with stages apportioned via
    ``apportion_shares``, the slowest member's stage time bounds the
    step, and ``describe()`` names the per-member placement;
  * ``describe()`` for a pp×remat×ep plan reports bubble fraction,
    recompute FLOPs, offload bytes, and per-stage HBM.
"""
import dataclasses
import random
import time

import jax
import jax.numpy as jnp
import pytest

from apex_tpu.parallel import auto
from apex_tpu.runtime.step_cache import static_plan_key


def _profile(**kw):
    """A hand-built analytic profile (the planner only reads fields)."""
    base = dict(
        n_params=500_000_000,
        param_shapes=((500_000_000,),),
        param_bytes_fp32=2_000_000_000,
        half_itemsize=2,
        slots_per_param=2,
        batch_ref=8,
        batch_bytes_per_example=8192.0,
        flops_per_example=3.0e12,
        flops_fixed=0.0,
        act_bytes_per_example=50_000_000.0,
        act_bytes_fixed=0.0,
        hbm_bytes_per_example=1.0e8,
        hbm_bytes_fixed=2.0e9,
        logits_bytes_per_example=0.0,
        seq_len=2048, vocab=50257, hidden=4096, layers=16, heads=16,
        tp_axis=None, sp_axis=None, source="analytic")
    base.update(kw)
    return auto.ModelProfile(**base)


# ---------------------------------------------------------------------------
# Satellite 1: key round-trip + unknown-segment rejection
# ---------------------------------------------------------------------------


def test_plan_key_roundtrip_property():
    """plan_from_key(static_plan_key(p)) == p over randomized plans
    covering every v3 axis (offload fractions drawn from the ladder so
    the %g text form is exact)."""
    rng = random.Random(19)
    remats = list(auto.REMAT_POLICIES)
    offs = [0.0, 0.25, 0.5, 0.75, 1.0]
    for _ in range(300):
        pp = rng.choice([1, 1, 2, 4, 8])
        plan = auto.Plan(
            dp=rng.choice([1, 2, 4, 8]) if pp == 1 else 1,
            tp=rng.choice([1, 1, 2]) if pp == 1 else 1,
            sp=rng.choice([1, 1, 2]) if pp == 1 else 1,
            zero_stage=rng.choice([0, 1, 2, 3]) if pp == 1 else 0,
            accum=rng.choice([1, 2, 8]) if pp == 1 else 1,
            chunked_loss=rng.choice([False, True]),
            pp=pp,
            micro=rng.choice([pp, 2 * pp, 4 * pp]) if pp > 1 else 1,
            remat=rng.choice(remats),
            ep=1, offload_opt=rng.choice(offs),
            offload_act=rng.choice(offs),
            n_devices=rng.choice([8, 16, 32]))
        if plan.pp == 1 and plan.tp == 1 and plan.sp == 1 and \
                rng.random() < 0.3:
            plan = dataclasses.replace(plan, ep=plan.dp, zero_stage=0)
        back = auto.plan_from_key(static_plan_key(plan),
                                  n_devices=plan.n_devices)
        assert back == plan, (plan.key(), back, plan)


def test_plan_key_prev_format_unchanged():
    """A default-v3 plan keys to the historical 6-tuple — old ledgers,
    manifests and step-cache keys stay valid verbatim."""
    p = auto.Plan(dp=4, zero_stage=2, accum=2, chunked_loss=True,
                  n_devices=8)
    assert p.key() == (4, 1, 1, 2, 2, True)
    assert auto.plan_from_key(p.key(), n_devices=8) == p


def test_plan_from_key_rejects_unknown_segment():
    with pytest.raises(ValueError, match="zz9"):
        auto.plan_from_key((1, 1, 1, 0, 1, False, "zz9"), n_devices=1)
    with pytest.raises(ValueError, match="remat"):
        auto.plan_from_key((1, 1, 1, 0, 1, False, "remat=sometimes"),
                           n_devices=1)
    # repeated fields are as corrupt as unknown ones
    with pytest.raises(ValueError, match="pp"):
        auto.plan_from_key((1, 1, 1, 0, 1, False, "pp2", "pp4"),
                           n_devices=8)


def test_ledger_plan_key_str_carries_v3_segments():
    from apex_tpu.kernels.ledger import _plan_key_str
    p = auto.Plan(pp=4, micro=8, remat="full", offload_opt=1.0,
                  n_devices=4)
    s = _plan_key_str(p.key())
    assert s == "1/1/1/0/1/0/pp4/micro8/remat=full/offopt=1"


# ---------------------------------------------------------------------------
# Satellite 5: joint search rescues a profile every dp×tp plan OOMs on
# ---------------------------------------------------------------------------


def _deep_profile():
    """Deep-GPT toy: 32 GB of batch-independent activations plus an
    8 GB fp32 parameter set — no dp×tp×zero split fits one v5e (~14.7 GB
    usable) even with the deepest offload rung (at most half the
    activations can move to host), but a 1F1B pipeline holds one stage
    slice and a recompute ring."""
    return _profile(
        n_params=2_000_000_000,
        param_shapes=((2_000_000_000,),),
        param_bytes_fp32=8_000_000_000,
        act_bytes_per_example=50_000_000.0,
        act_bytes_fixed=32_000_000_000.0,
        pp_axis="pp", remat_capable=False)


def test_joint_search_finds_pp_remat_when_dp_tp_oom():
    prof = _deep_profile()
    ids = jnp.zeros((8, 16), jnp.int32)
    t0 = time.perf_counter()
    rep = auto.plan_training(None, None, None, (ids, ids),
                             profile=prof, fleet="v5e:8", accum_max=8)
    wall_s = time.perf_counter() - t0
    assert rep.best is not None, rep.describe()
    assert rep.best.pp > 1 and rep.best.remat == "full", rep.best.name()
    # every feasible plan pipelines: nothing dp/tp-only survived the
    # HBM model, and the OOM prunes are counted, not silent
    assert all(p.pp > 1 for p in rep.ranked)
    assert rep.pruned_oom > 0
    assert rep.explored >= rep.pruned_oom + len(rep.ranked)
    assert any(r.startswith("memory-infeasible") for _, r in rep.rejected)
    # search telemetry: recorded on the report and the registry, and
    # the whole joint enumeration stays cheap on CPU
    assert 0.0 < rep.search_ms < 30_000.0
    assert wall_s < 60.0
    from apex_tpu.observe import registry as obs
    assert obs.gauge("plan.explored").value == float(rep.explored)
    assert obs.gauge("plan.pruned_oom").value == float(rep.pruned_oom)
    # the winner's describe() explains the pipeline choice
    text = rep.best.describe()
    assert "pipeline:" in text and "bubble fraction" in text
    assert "per-stage HBM" in text


def test_pp_memory_model_orders_remat_policies():
    """More aggressive remat → strictly less activation memory, and
    offload moves bytes to host without changing the HBM-side params."""
    prof = _deep_profile()
    mems = []
    for remat in ("none", "selective", "full"):
        plan = auto.Plan(pp=4, micro=8, remat=remat, n_devices=8)
        mem, _ = auto.predict_memory(plan, prof, auto.CHIPS["v5e"], 8)
        mems.append(mem)
    assert mems[0] > mems[1] > mems[2]
    base = auto.Plan(pp=4, micro=8, remat="full", n_devices=8)
    off = dataclasses.replace(base, offload_opt=1.0)
    m0, _ = auto.predict_memory(base, prof, auto.CHIPS["v5e"], 8)
    m1, bd1 = auto.predict_memory(off, prof, auto.CHIPS["v5e"], 8)
    assert m1 < m0
    assert dict(bd1)["host_opt_bytes"] > 0


def test_offload_priced_not_free():
    """An offload rung costs predicted time (H2D/D2H traffic at the
    chip's h2d_bw, ≥25% exposed) — it only wins when memory demands it."""
    prof = _deep_profile()
    spec = auto.CHIPS["v5e"]
    base = auto.Plan(pp=4, micro=8, remat="full", n_devices=8)
    off = dataclasses.replace(base, offload_opt=1.0, offload_act=0.5)
    ms0, _, _ = auto.predict_time(base, prof, spec, 8)
    ms1, bd1, _ = auto.predict_time(off, prof, spec, 8)
    bd1 = dict(bd1)
    assert ms1 > ms0
    assert bd1["offload_bytes"] > 0 and bd1["offload_ms"] > 0


# ---------------------------------------------------------------------------
# Satellite 6: heterogeneous-fleet pipeline stages
# ---------------------------------------------------------------------------


def test_hetero_fleet_pipeline_stage_apportionment():
    prof = _profile(pp_axis="pp", layers=13,
                    act_bytes_fixed=32_000_000_000.0,
                    param_shapes=((2_000_000_000,),),
                    param_bytes_fp32=8_000_000_000,
                    n_params=2_000_000_000)
    fleet = auto.parse_fleet("v5e:4+v4:4")
    ids = jnp.zeros((8, 16), jnp.int32)
    rep = auto.plan_training(None, None, None, (ids, ids),
                             profile=prof, fleet=fleet, accum_max=8)
    assert rep.best is not None, rep.describe()
    best = rep.best
    assert best.pp > 1, best.name()
    # stages apportioned over the first pp members by sustained flops,
    # covering all 13 layers — apportion_shares semantics
    assert len(best.stage_layers) == best.pp
    assert sum(best.stage_layers) == 13
    members = fleet.specs[:best.pp]
    expected = auto.apportion_shares(
        [s.sustained_flops() for s in members], 13)
    assert best.stage_layers == tuple(expected)
    assert best.stage_members == tuple(s.name for s in members)
    # the slowest member's stage time bounds the step: warmup/drain
    # multiplies it, collectives/overhead only add
    bd = dict(best.breakdown)
    assert "stage_ms_bound" in bd and "bound_member" in bd
    assert best.predicted_ms >= bd["stage_ms_bound"]
    ticks = best.micro + best.pp - 1
    assert best.predicted_ms >= bd["stage_ms_bound"] * ticks / best.micro
    # describe() names the per-member placement
    text = best.describe()
    assert "stage placement:" in text
    for i, s in enumerate(members):
        assert f"stage {i} → {s.name}" in text


def test_hetero_fleet_rejects_pp_dp_composition():
    prof = _profile(pp_axis="pp")
    plan = auto.Plan(dp=2, pp=2, micro=2, n_devices=4)
    fleet = auto.parse_fleet("v5e:2+v4:2")
    reason = auto._structural_reject(plan, prof, 8, fleet=fleet)
    assert reason is not None and "pp" in reason


# ---------------------------------------------------------------------------
# describe() for the full pp × remat × ep composition
# ---------------------------------------------------------------------------


def _moe_pp_plan_described():
    prof = _profile(
        n_params=1_300_000_000, param_shapes=((1_300_000_000,),),
        param_bytes_fp32=5_200_000_000,
        act_bytes_per_example=900_000_000.0,
        flops_per_example=2.6e13, layers=48, hidden=2048,
        pp_axis="pp", remat_capable=True, moe_axis="data",
        n_experts=8, moe_layers=24, moe_param_frac=0.55)
    spec = auto.CHIPS["v5e"]
    plan = auto.Plan(dp=8, ep=8, pp=4, micro=8, remat="selective",
                     offload_opt=1.0, offload_act=0.0,
                     pp_axis="pp", dp_axis="data", n_devices=32)
    mem, mem_bd = auto.predict_memory(plan, prof, spec, 64)
    ms, time_bd, colls = auto.predict_time(plan, prof, spec, 64)
    return dataclasses.replace(
        plan, predicted_ms=ms, predicted_hbm=mem,
        breakdown=tuple(time_bd + mem_bd), collectives=tuple(colls))


def test_describe_pp_remat_ep_plan_reports_everything():
    plan = _moe_pp_plan_described()
    text = plan.describe()
    assert "bubble fraction" in text
    assert "recompute" in text and "GFLOP/step" in text
    assert "offload bytes" in text
    assert "per-stage HBM" in text
    assert "expert parallel: ep=8" in text
    assert "all-to-all" in text
    d = dict(plan.breakdown)
    assert d["bubble_frac"] == pytest.approx(3 / 11)
    assert d["recompute_gflops"] > 0
    assert d["host_opt_bytes"] > 0


def test_moe_a2a_term_scales_with_ep():
    """The all-to-all term prices (ep-1)/ep of the routed tokens — more
    experts move more of the batch across the axis."""
    prof = _profile(moe_axis="data", n_experts=8, moe_layers=6,
                    moe_param_frac=0.4)
    spec = auto.CHIPS["v5e"]
    times = {}
    for ep in (2, 8):
        plan = auto.Plan(dp=ep, ep=ep, dp_axis="data", n_devices=8)
        ms, _, colls = auto.predict_time(plan, prof, spec, 8)
        times[ep] = ms
        assert any("all-to-all" in c for c in colls)
    dense2 = auto.Plan(dp=2, dp_axis="data", n_devices=8)
    dense_ms, _, dense_colls = auto.predict_time(dense2, prof, spec, 8)
    assert not any("all-to-all" in c for c in dense_colls)
    assert times[2] > dense_ms


def test_enumerate_includes_ep_twin_for_moe_profile():
    prof = _profile(moe_axis="data", n_experts=4, moe_layers=2,
                    moe_param_frac=0.3)
    ids = jnp.zeros((8, 16), jnp.int32)
    rep = auto.plan_training(None, None, None, (ids, ids),
                             profile=prof, fleet="v5e:4", accum_max=4)
    assert any(p.ep == 4 for p in rep.ranked), \
        [p.name() for p in rep.ranked[:10]]
    ep_best = [p for p in rep.ranked if p.ep == 4][0]
    assert ep_best.dp_axis == "data"
    assert ep_best.step_kwargs().get("axis_name") == "data"


# ---------------------------------------------------------------------------
# apply_plan wires pp plans into the pipeline entry points
# ---------------------------------------------------------------------------


def _toy_stack(rng, n_stages, n_micro, remat_stage=False):
    import numpy as np
    from apex_tpu.parallel import PipelinedStack

    d = 8

    def stage_fn(params, x):
        w, b = params
        return jnp.tanh(x @ w + b)

    w = jnp.asarray(rng.standard_normal((n_stages, d, d)) * 0.5,
                    jnp.float32)
    b = jnp.asarray(rng.standard_normal((n_stages, d)) * 0.1,
                    jnp.float32)
    stack = PipelinedStack(stage_fn, (w, b), "pp", n_micro=n_micro,
                           remat_stage=remat_stage)
    x = jnp.asarray(rng.standard_normal((16, d)), jnp.float32)
    y = jnp.asarray(rng.standard_normal((16, d)), jnp.float32)
    return stack, x, y


@pytest.mark.parametrize("remat,schedule", [("none", "gpipe"),
                                            ("full", "1f1b")])
def test_apply_plan_runs_pipeline_schedules(remat, schedule):
    import numpy as np
    from apex_tpu.optimizers import FusedAdam

    if len(jax.devices()) < 4:
        pytest.skip("needs 4 devices")
    rng = np.random.default_rng(0)
    stack, x, y = _toy_stack(rng, n_stages=4, n_micro=4)
    opt = FusedAdam(list(stack.parameters()), lr=1e-2)

    def loss_fn(out, y):
        return jnp.mean((out - y) ** 2)

    plan = auto.Plan(pp=4, micro=4, remat=remat, pp_axis="pp",
                     n_devices=4)
    step = auto.apply_plan(plan, stack, opt, loss_fn,
                           half_dtype=None, loss_scale=1.0)
    assert step.plan is plan
    losses = [float(step(x, y)) for _ in range(4)]
    assert all(jnp.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]       # it actually trains


def test_apply_plan_pp_validates_stack_shape():
    import numpy as np
    from apex_tpu.optimizers import FusedAdam

    rng = np.random.default_rng(0)
    stack, x, y = _toy_stack(rng, n_stages=4, n_micro=4)
    opt = FusedAdam(list(stack.parameters()), lr=1e-2)

    def loss_fn(out, y):
        return jnp.mean((out - y) ** 2)

    with pytest.raises(ValueError, match="n_micro"):
        auto.apply_plan(auto.Plan(pp=4, micro=8, remat="full",
                                  pp_axis="pp", n_devices=4),
                        stack, opt, loss_fn)
    with pytest.raises(ValueError, match="PipelinedStack"):
        auto.apply_plan(auto.Plan(pp=4, micro=4, n_devices=4),
                        object(), opt, loss_fn)
    with pytest.raises(ValueError, match="remat_stage"):
        auto.apply_plan(auto.Plan(pp=4, micro=4, remat="selective",
                                  pp_axis="pp", n_devices=4),
                        stack, opt, loss_fn)


def test_executor_h2d_ewma_feeds_planner():
    from apex_tpu.runtime import executor as ex
    ex.reset_h2d_bw()
    try:
        assert ex.measured_h2d_bw() is None
        ex.note_h2d(1 << 20, 1e-3)          # 1 MiB in 1 ms ≈ 1 GB/s
        bw1 = ex.measured_h2d_bw()
        assert bw1 == pytest.approx((1 << 20) / 1e-3)
        ex.note_h2d(1 << 20, 2e-3)
        bw2 = ex.measured_h2d_bw()
        assert bw2 == pytest.approx(0.8 * bw1 + 0.2 * (1 << 20) / 2e-3)
        ex.note_h2d(16, 1e-3)               # tiny: latency, not bandwidth
        assert ex.measured_h2d_bw() == bw2
    finally:
        ex.reset_h2d_bw()


def test_planner_telemetry_cataloged():
    from apex_tpu.observe import catalog
    for name in ("plan.search_ms", "plan.explored", "plan.pruned_oom",
                 "plan.bubble_frac"):
        entry = catalog.describe(name)
        assert entry is not None, name
        assert entry["kind"] == "gauge"
        assert entry["unit"] and entry["description"]
