"""Beam-search decoding (inference/beam.py): fixed-width beam search
over the LM families' cache protocol.

Oracles: (1) num_beams=1 must equal greedy generate token-for-token;
(2) an independently-written numpy reference beam search — scoring
candidates with the model's TEACHER-FORCED forward (no caches, no
scan) — must emit the same best sequence; (3) eos freezes a beam's
score while it keeps competing.  Reference analogue: none (the
reference is training-side, SURVEY.md §2); oracle style per §4."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

import apex_tpu.nn as nn
from apex_tpu.inference import beam_generate
from apex_tpu.models import GptModel
from apex_tpu.models.gpt import generate
from apex_tpu.models.llama import LlamaModel
from apex_tpu.nn.modules import Ctx

V = 23


def _gpt(**kw):
    nn.manual_seed(3)
    return GptModel(vocab_size=V, hidden=32, layers=2, heads=4,
                    max_positions=32, dropout=0.0, attn_dropout=0.0, **kw)


def _llama(**kw):
    nn.manual_seed(3)
    return LlamaModel(vocab_size=V, hidden=32, layers=2, heads=4,
                      kv_heads=2, max_positions=32, **kw)


def _np_beam_reference(model, prompt, n_new, k, eos_id=None, alpha=0.0):
    """Plain-python beam search scoring every candidate with the
    model's teacher-forced forward — no caches, no scan, no top_k —
    the independent oracle for the compiled implementation.  ``alpha``
    is the GNMT length penalty: ranking (and the final pick) uses
    score / ((5 + len)/6)**alpha with ``len`` the generated-token
    count, frozen at eos."""
    ctx = Ctx(training=False)
    p_len = prompt.shape[1]

    def next_logp(seq):
        ids = jnp.asarray(np.asarray(seq)[None, :])
        logits = model.forward(ctx, ids)
        return np.asarray(jax.nn.log_softmax(
            logits[0, -1].astype(jnp.float32)))

    def norm(score, length):
        return score / (((5.0 + length) / 6.0) ** alpha)

    outs = []
    for row in np.asarray(prompt):
        beams = [(list(row), 0.0, True, 0)]  # (seq, score, alive, len)
        for _ in range(n_new):
            cand = []
            for seq, score, alive, ln in beams:
                if not alive:
                    cand.append((seq + [eos_id], score, False, ln))
                    continue
                lp = next_logp(seq)
                for v in range(V):
                    a = not (eos_id is not None and v == eos_id)
                    cand.append((seq + [v], score + lp[v], a, ln + 1))
            cand.sort(key=lambda c: -norm(c[1], c[3]))
            beams = cand[:k]
        beams.sort(key=lambda c: -norm(c[1], c[3]))
        outs.append(beams[0][0])
        assert all(len(s) == p_len + n_new for s, *_ in beams)
    return np.asarray(outs)


def test_beam1_equals_greedy(rng):
    m = _gpt()
    m.eval()
    prompt = jnp.asarray(rng.integers(0, V, (2, 4)))
    want = np.asarray(generate(m, prompt, 8))
    got = np.asarray(beam_generate(m, prompt, 8, num_beams=1))
    np.testing.assert_array_equal(got, want)


def test_beam_matches_numpy_reference(rng):
    m = _gpt()
    m.eval()
    prompt = jnp.asarray(rng.integers(0, V, (2, 3)))
    got = np.asarray(beam_generate(m, prompt, 5, num_beams=3))
    want = _np_beam_reference(m, prompt, 5, 3)
    np.testing.assert_array_equal(got, want)


def test_beam_llama_matches_numpy_reference(rng):
    m = _llama()
    m.eval()
    prompt = jnp.asarray(rng.integers(0, V, (1, 3)))
    got = np.asarray(beam_generate(m, prompt, 4, num_beams=4))
    want = _np_beam_reference(m, prompt, 4, 4)
    np.testing.assert_array_equal(got, want)


def test_beam_eos_freezes_and_pads(rng):
    """With eos in vocab, a finished beam pads with eos and its frozen
    score still competes — match the numpy reference with the same
    convention."""
    m = _gpt()
    m.eval()
    eos = 5
    prompt = jnp.asarray(rng.integers(0, V, (2, 3)))
    got = np.asarray(beam_generate(m, prompt, 5, num_beams=3,
                                   eos_id=eos))
    want = _np_beam_reference(m, prompt, 5, 3, eos_id=eos)
    np.testing.assert_array_equal(got, want)
    # every token after an eos is eos
    for row in got:
        tail = row[3:]
        hits = np.where(tail == eos)[0]
        if hits.size:
            assert (tail[hits[0]:] == eos).all()


def test_beam_beats_or_ties_greedy_logprob(rng):
    """The point of the search: the beam result's total log-prob is
    >= greedy's on the same model (scored teacher-forced)."""
    m = _gpt()
    m.eval()
    prompt = jnp.asarray(rng.integers(0, V, (1, 3)))
    n = 6

    def total_logp(seq):
        ctx = Ctx(training=False)
        ids = jnp.asarray(seq[None, :])
        logits = m.forward(ctx, ids)
        lp = np.asarray(jax.nn.log_softmax(
            logits[0].astype(jnp.float32)))
        return sum(lp[t, seq[t + 1]] for t in range(2, 2 + n))

    greedy = np.asarray(generate(m, prompt, n))[0]
    beam = np.asarray(beam_generate(m, prompt, n, num_beams=4))[0]
    assert total_logp(beam) >= total_logp(greedy) - 1e-5


def test_beam_int8_cache_runs(rng):
    m = _gpt()
    m.eval()
    prompt = jnp.asarray(rng.integers(0, V, (1, 3)))
    out = beam_generate(m, prompt, 4, num_beams=2, cache_dtype="int8")
    assert out.shape == (1, 7)
    assert (np.asarray(out)[:, :3] == np.asarray(prompt)).all()


def test_beam_tp_matches_single_shard(rng):
    m_ref = _gpt()
    m_ref.eval()
    m_tp = _gpt(tp_axis="tp")
    m_tp.eval()
    for a, b in zip(m_ref.parameters(), m_tp.parameters()):
        b.data = a.data
    mesh = Mesh(np.array(jax.devices())[:2].reshape(2), ("tp",))
    prompt = jnp.asarray(rng.integers(0, V, (2, 4)))
    want = np.asarray(beam_generate(m_ref, prompt, 6, num_beams=3))
    got = np.asarray(beam_generate(m_tp, prompt, 6, num_beams=3,
                                   mesh=mesh))
    np.testing.assert_array_equal(got, want)


def test_beam_sp_matches_single_shard(rng):
    m_ref = _gpt()
    m_ref.eval()
    m_sp = _gpt(sp_axis="sp")
    m_sp.eval()
    for a, b in zip(m_ref.parameters(), m_sp.parameters()):
        b.data = a.data
    mesh = Mesh(np.array(jax.devices())[:2].reshape(2), ("sp",))
    prompt = jnp.asarray(rng.integers(0, V, (1, 4)))
    want = np.asarray(beam_generate(m_ref, prompt, 6, num_beams=3))
    got = np.asarray(beam_generate(m_sp, prompt, 6, num_beams=3,
                                   mesh=mesh))
    np.testing.assert_array_equal(got, want)


def test_beam_validation():
    m = _gpt()
    m.eval()
    toks = jnp.zeros((1, 3), jnp.int32)
    with pytest.raises(ValueError, match="num_beams"):
        beam_generate(m, toks, 4, num_beams=0)
    with pytest.raises(ValueError, match="max_new_tokens"):
        beam_generate(m, toks, 0, num_beams=2)
    with pytest.raises(ValueError, match="exceeds"):
        beam_generate(m, toks, 40, num_beams=2)
    with pytest.raises(ValueError, match="eos_id"):
        beam_generate(m, toks, 4, num_beams=2, eos_id=V)
    m_sp = _gpt(sp_axis="sp")
    m_sp.eval()
    with pytest.raises(ValueError, match="mesh"):
        beam_generate(m_sp, toks, 4, num_beams=2)


def test_beam_length_penalty_matches_numpy_reference(rng):
    """GNMT length normalization with eos in play (beam lengths
    diverge, so the penalty actually reorders candidates): the
    compiled search matches the oracle under the same formula."""
    from apex_tpu.inference import beam_generate as bg

    m = _gpt()
    m.eval()
    eos = 3
    prompt = jnp.asarray(rng.integers(0, V, (2, 3)))
    for alpha in (0.6, 1.2):
        got = np.asarray(bg(m, prompt, 6, num_beams=4, eos_id=eos,
                            length_penalty=alpha))
        want = _np_beam_reference(m, prompt, 6, 4, eos_id=eos,
                                  alpha=alpha)
        np.testing.assert_array_equal(got, want, err_msg=f"alpha={alpha}")
    with pytest.raises(ValueError, match="length_penalty"):
        bg(m, prompt, 4, num_beams=2, length_penalty=-1.0)
