"""apex_tpu.cluster end to end: the KV substrate, heartbeat membership
with epoch-numbered views, the detect→agree→replan→reshard cycle under
seeded chaos (host loss mid-run, coordinator loss, the delayed-heartbeat
false-positive guard), schema-3 streaming shard IO (kill-mid-shard
durability, streamed ≡ gathered bitwise), and heterogeneity-aware
planning (mixed fleets, per-device batch shares, slowest-member bound)
— all on the 8-virtual-CPU-device mesh in one process."""
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import apex_tpu.nn as nn
from apex_tpu.nn import functional as F
from apex_tpu.cluster import (ClusterTrainer, Coordinator, FileKV, Member,
                              MemoryKV, PREFIX, SimClock, current_epoch,
                              current_view, fleet_for_members,
                              spawn_member_process)
from apex_tpu.optimizers import FusedSGD
from apex_tpu.parallel import auto
from apex_tpu.runtime import CheckpointManager, chaos, resilience
from apex_tpu.runtime import executor as _executor
from apex_tpu.runtime.elastic import ElasticTrainer
from apex_tpu.training import make_train_step

pytestmark = pytest.mark.cluster

DIM, CLASSES = 16, 10
#: divisible by every dp degree the shrink tests visit (8, 6, 4, 3, 2)
BATCH = 24


@pytest.fixture(autouse=True)
def _clean_cluster_state():
    yield
    chaos.uninstall()
    _executor.set_cluster_epoch(None)


def _mlp(seed=0):
    nn.manual_seed(seed)
    model = nn.Sequential(nn.Linear(DIM, 32), nn.GELU(),
                          nn.Linear(32, CLASSES))
    opt = FusedSGD(list(model.parameters()), lr=0.1, momentum=0.9)
    return model, opt


def _loss(o, t):
    return F.cross_entropy(o, t)


def _batch(seed, b=BATCH):
    rng = np.random.default_rng(seed)
    return (jnp.asarray(rng.standard_normal((b, DIM)), jnp.float32),
            jnp.asarray(rng.integers(0, CLASSES, (b,))))


#: pin the plan family so shrink trajectories are deterministic: pure
#: data parallel over every surviving device, no ZeRO, no accum
def _dp_only(p):
    return (p.dp == p.n_devices and p.zero_stage == 0 and p.accum == 1
            and not p.chunked_loss)


def _cluster(path, seed=0, **kw):
    model, opt = _mlp(seed)
    kw.setdefault("n_hosts", 4)
    kw.setdefault("plan_filter", _dp_only)
    return ClusterTrainer(str(path), model, opt, _loss,
                          example_batch=_batch(0), half_dtype=None,
                          loss_scale=1.0, **kw)


def _kill_member(member_id):
    """Chaos action for ``host.loss``: this one host's process dies."""
    def act(ctx):
        if ctx.get("member") == member_id:
            raise chaos.ChaosKilled(f"{member_id} died")
    return act


# ---------------------------------------------------------------------------
# KV substrate
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("make_kv", [lambda tmp: MemoryKV(),
                                     lambda tmp: FileKV(str(tmp / "kv"))],
                         ids=["memory", "file"])
def test_kvstore_roundtrip_and_scan(tmp_path, make_kv):
    kv = make_kv(tmp_path)
    assert kv.get("absent") is None
    kv.set(f"{PREFIX}hb/host0", "1.5")
    kv.set(f"{PREFIX}hb/host1", "2.5")
    kv.set(f"{PREFIX}epoch", "3")
    assert kv.get(f"{PREFIX}hb/host0") == "1.5"
    got = kv.scan(f"{PREFIX}hb/")
    assert got == {f"{PREFIX}hb/host0": "1.5",
                   f"{PREFIX}hb/host1": "2.5"}
    kv.delete(f"{PREFIX}hb/host0")
    assert kv.get(f"{PREFIX}hb/host0") is None
    assert set(kv.scan(f"{PREFIX}hb/")) == {f"{PREFIX}hb/host1"}
    kv.delete(f"{PREFIX}hb/host0")          # idempotent


def test_file_kv_crosses_instances_and_skips_tmp_debris(tmp_path):
    a = FileKV(str(tmp_path / "kv"))
    b = FileKV(str(tmp_path / "kv"))        # a second "process"
    a.set(f"{PREFIX}members/h0", '{"chip": "cpu"}')
    assert b.get(f"{PREFIX}members/h0") == '{"chip": "cpu"}'
    # a torn write (tmp file left by a killed writer) never scans
    (tmp_path / "kv" / "whatever.tmp.123").write_text("partial")
    assert set(b.scan(PREFIX)) == {f"{PREFIX}members/h0"}


# ---------------------------------------------------------------------------
# membership + coordinator protocol (no trainer)
# ---------------------------------------------------------------------------


def test_join_scan_publishes_epoch1_and_acks():
    kv, clock = MemoryKV(), SimClock()
    members = [Member(kv, f"h{i}", clock=clock).join() for i in range(3)]
    coord = Coordinator(kv, deadline_s=1.0, miss_threshold=2, clock=clock)
    view = coord.scan()
    assert view.epoch == 1 and view.members == ("h0", "h1", "h2")
    assert current_epoch(kv) == 1
    assert not coord.acked(view)
    for m in members:
        m.ack(view)
    assert coord.acked(view)
    # steady state: no change, no new epoch
    clock.advance(0.5)
    for m in members:
        m.beat()
    assert coord.scan().epoch == 1


def test_graceful_leave_drops_without_waiting_out_misses():
    kv, clock = MemoryKV(), SimClock()
    members = [Member(kv, f"h{i}", clock=clock).join() for i in range(3)]
    coord = Coordinator(kv, deadline_s=1.0, miss_threshold=5, clock=clock)
    assert coord.scan().members == ("h0", "h1", "h2")
    members[1].leave()
    view = coord.scan()                     # no 5-scan wait: deregistered
    assert view.epoch == 2 and view.members == ("h0", "h2")


def test_consecutive_miss_detection_and_heartbeat_delay_guard():
    kv, clock = MemoryKV(), SimClock()
    m0 = Member(kv, "h0", clock=clock).join()
    m1 = Member(kv, "h1", clock=clock).join()
    coord = Coordinator(kv, deadline_s=0.25, miss_threshold=2, clock=clock)
    assert coord.scan().epoch == 1

    # one stale scan is NOT death (miss 1 of 2) ...
    clock.advance(0.3)
    m0.beat()
    assert coord.scan().members == ("h0", "h1")
    # ... and a fresh beat resets the counter: h1 keeps its seat forever
    # under the beat-then-pause-then-beat pattern
    clock.advance(0.3)
    m0.beat()
    m1.beat()
    assert coord.scan().epoch == 1

    # the chaos heartbeat.delay action skews h1's stamp backwards — a
    # paused-but-alive host.  miss_threshold=2 absorbs it: no new epoch.
    with chaos.session(seed=0) as c:
        c.on("heartbeat.delay",
             action=lambda ctx: 10.0 if ctx["member"] == "h1" else None,
             times=1)
        clock.advance(0.1)
        m0.beat()
        m1.beat()                           # lands skewed 10s backwards
        assert coord.scan().epoch == 1      # miss 1 only
        clock.advance(0.1)
        m0.beat()
        m1.beat()                           # fresh again: counter resets
        assert coord.scan().epoch == 1

    # two CONSECUTIVE stale scans do fell a member
    clock.advance(0.3)
    m0.beat()
    coord.scan()
    clock.advance(0.3)
    m0.beat()
    view = coord.scan()
    assert view.epoch == 2 and view.members == ("h0",)


def test_epoch_survives_coordinator_loss_without_resurrection():
    """A successor coordinator over the same store continues the
    persisted epoch counter and must NOT re-admit a dead-but-still-
    registered member for a bogus epoch (its empty miss counters seed
    from the published view)."""
    kv, clock = MemoryKV(), SimClock()
    m0 = Member(kv, "h0", clock=clock).join()
    Member(kv, "h1", clock=clock).join()    # joins, then silently dies
    coord = Coordinator(kv, deadline_s=0.25, miss_threshold=2, clock=clock)
    assert coord.scan().epoch == 1
    for _ in range(2):
        clock.advance(0.3)
        m0.beat()
        view = coord.scan()
    assert view.epoch == 2 and view.members == ("h0",)

    # coordinator dies; the successor rebuilds soft state from scratch
    successor = Coordinator(kv, deadline_s=0.25, miss_threshold=2,
                            clock=clock)
    clock.advance(0.1)
    m0.beat()
    view2 = successor.scan()
    assert view2.epoch == 2 and view2.members == ("h0",)
    assert current_epoch(kv) == 2
    # only a FRESH beat readmits h1
    m1b = Member(kv, "h1", clock=clock)
    m1b.alive = True
    m1b.beat()
    view3 = successor.scan()
    assert view3.epoch == 3 and view3.members == ("h0", "h1")


# ---------------------------------------------------------------------------
# the full cycle: detect → agree → replan → reshard, under chaos
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_host_loss_shrink_replan_resume_loss_parity(tmp_path):
    """Acceptance: a host dies mid-run; the cluster detects it within
    miss_threshold scans, agrees on a new epoch, re-plans 8→6 devices,
    streams the newest checkpoint into the new layout, and the resumed
    loss trajectory matches an uninterrupted run (fp32 SGD; the shrink
    segment runs a different dp degree, so parity is to reduction-order
    tolerance)."""
    n = len(jax.devices())
    assert n == 8
    batches = [_batch(10 + i) for i in range(8)]

    model, opt = _mlp()
    ref = ElasticTrainer(str(tmp_path / "ref"), model, opt, _loss,
                         example_batch=_batch(0), half_dtype=None,
                         loss_scale=1.0, plan_filter=_dp_only)
    ref.restore()
    ref_losses = [float(ref(*b)) for b in batches]

    ct = _cluster(tmp_path / "cl")
    view = ct.join()
    assert view.epoch == 1 and len(view.members) == 4
    assert ct.recover() == 0 and ct.plan.dp == n
    assert _executor.cluster_epoch() == 1
    got = [float(ct(*b)) for b in batches[:3]]
    ct.save(2)
    for b in batches[3:5]:
        ct(*b)                  # steps 3-4 run but die un-checkpointed

    # host3's process dies mid-beat; two stale scans fell it
    with chaos.session(seed=0) as c:
        c.on("host.loss", action=_kill_member("host3"), times=-1)
        ct.tick(0.3)
        view = ct.tick(0.3)
    assert ct.membership_changed()
    assert view.epoch == 2 and set(view.members) == {"host0", "host1",
                                                     "host2"}
    assert not ct.hosts[3].alive

    resume = ct.recover()
    assert resume == 3          # replays exactly the un-checkpointed steps
    assert ct.plan.dp == 6 and len(ct.trainer.devices) == 6
    assert _executor.cluster_epoch() == 2
    tel = ct.telemetry
    assert tel["epoch"] == 2 and tel["n_devices"] == 6
    assert tel["restore_mode"] == "streamed"
    assert tel["detect_ms"] >= 0 and tel["replan_ms"] > 0
    assert tel["resume_step"] == 2
    got += [float(ct(*b)) for b in batches[3:]]

    np.testing.assert_allclose(got, ref_losses, rtol=2e-5, atol=1e-6)


@pytest.mark.chaos
def test_coordinator_loss_epoch_monotonic_across_successor(tmp_path):
    """coordinator.loss mid-duty: the successor (same KV store) inherits
    the persisted epoch — never rewinds, never resurrects the member the
    published view already dropped."""
    ct = _cluster(tmp_path / "cl")
    ct.join()
    ct.recover()
    with chaos.session(seed=0) as c:
        c.on("host.loss", action=_kill_member("host2"), times=-1)
        ct.tick(0.3)
        view = ct.tick(0.3)
    assert view.epoch == 2 and "host2" not in view.members
    first_coord = ct.coordinator

    with chaos.session(seed=0) as c:
        c.on("coordinator.loss", action="kill", at=0)
        ct.tick()               # dies mid-scan; tick rebuilds over same kv
    assert ct.coordinator is not first_coord
    view2 = ct.tick()
    assert view2.epoch == 2 and "host2" not in view2.members
    assert current_epoch(ct.kv) == 2
    ct.recover()
    assert ct.plan.dp == 6 and _executor.cluster_epoch() == 2


@pytest.mark.chaos
def test_heartbeat_delay_does_not_cost_a_seat(tmp_path):
    """A delayed (skewed-backwards) heartbeat under miss_threshold=2 is
    a false-positive guard: no epoch change, no replan needed."""
    ct = _cluster(tmp_path / "cl")
    view = ct.join()
    with chaos.session(seed=1) as c:
        c.on("heartbeat.delay",
             action=lambda ctx: 10.0 if ctx["member"] == "host1" else None,
             times=1)
        ct.tick()
    after = ct.tick()
    assert after.epoch == view.epoch
    assert "host1" in after.members
    assert not ct.membership_changed()


# ---------------------------------------------------------------------------
# streaming shard IO (schema 3)
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_kill_mid_shard_previous_epoch_restorable(tmp_path):
    """A kill during a shard-file write leaves only an orphan shard
    directory — no manifest, so the previous checkpoint stays the newest
    valid one, and the next save's sweep collects the debris."""
    ct = _cluster(tmp_path / "cl")
    ct.join()
    ct.recover()
    for i in range(3):
        ct(*_batch(60 + i))
    ct.save(1)
    mgr = ct.trainer.manager
    masters_before = [np.asarray(a) for a in
                      ct.trainer.step.state.master_params]

    ct(*_batch(63))
    with chaos.session(seed=0) as c:
        c.on("ckpt.shard_write", action="kill", after=2)
        with pytest.raises(chaos.ChaosKilled):
            ct.save(2)
    # debris: some shard files for step 2, but no committed manifest
    assert mgr.all_steps() == [1]
    assert not resilience.os.path.exists(mgr.path_for(2))

    ct2 = _cluster(tmp_path / "cl", seed=1)
    ct2.join()
    assert ct2.recover() == 2       # resumes from step-1 checkpoint
    for a, b in zip(ct2.trainer.step.state.master_params, masters_before):
        np.testing.assert_array_equal(np.asarray(a), b)
    # the next sharded save sweeps the orphan step-2 shard dir
    ct2.save(2)
    assert sorted(ct2.trainer.manager.all_steps()) == [1, 2]


def test_streamed_restore_bitwise_equals_gathered(tmp_path):
    """Acceptance: the streaming reshard (per-block shard reads) is
    bitwise-equal to the gathered path on the same checkpoint, and its
    host-bytes high-water mark is strictly below the gathered full-state
    size."""
    model, opt = _mlp()
    src = make_train_step(model, opt, _loss, half_dtype=None,
                          loss_scale=1.0,
                          parallel=auto.Plan(dp=8, zero_stage=3,
                                             n_devices=8))
    src(*_batch(1))
    src(*_batch(2))
    mgr = CheckpointManager(str(tmp_path), keep_n=2)
    mgr.save_sharded(5, src, epoch=9)
    assert mgr.last_save_stats["shard_bytes_peak_host"] > 0

    target_plan = auto.Plan(dp=4, zero_stage=1, n_devices=8)

    # streamed: blocks assembled from only the overlapping shard files
    m2, o2 = _mlp(seed=1)
    streamed = make_train_step(m2, o2, _loss, half_dtype=None,
                               loss_scale=1.0, parallel=target_plan)
    got, extras = mgr.restore_resharded(streamed)
    assert got == 5 and extras == {"epoch": 9}
    stats = mgr.last_restore_stats
    assert stats["mode"] == "streamed" and stats["schema"] == 3
    assert stats["shard_reads"] > 0

    # gathered: assemble the full host arrays, reshard_state them in
    host, manifest = resilience.read_checkpoint_file(
        mgr.path_for(5), return_manifest=True)
    assert manifest["schema"] == 3
    gathered_bytes = sum(
        x.nbytes for x in jax.tree_util.tree_leaves(host["state"])
        if isinstance(x, np.ndarray))
    m3, o3 = _mlp(seed=2)
    gathered = make_train_step(m3, o3, _loss, half_dtype=None,
                               loss_scale=1.0, parallel=target_plan)
    gathered.state = resilience.reshard_state(host["state"],
                                              gathered.state)

    for a, b in zip(jax.tree_util.tree_leaves(streamed.state),
                    jax.tree_util.tree_leaves(gathered.state)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    # restore never materialized the full state on this host
    assert 0 < stats["peak_host_bytes"] < gathered_bytes

    # resumed steps agree bitwise too
    np.testing.assert_array_equal(float(streamed(*_batch(3))),
                                  float(gathered(*_batch(3))))


def test_reshard_layout_identical_fast_path_is_zero_copy():
    """Live source arrays whose sharding already matches the target pass
    through reshard_state AS-IS — the identical buffers, no host
    round-trip (the eager cousin of the streaming-restore block reads)."""
    plan = auto.Plan(dp=4, zero_stage=1, n_devices=8)
    model, opt = _mlp()
    a = make_train_step(model, opt, _loss, half_dtype=None,
                        loss_scale=1.0, parallel=plan)
    a(*_batch(7))
    m2, o2 = _mlp(seed=1)
    b = make_train_step(m2, o2, _loss, half_dtype=None,
                        loss_scale=1.0, parallel=plan)
    out = resilience.reshard_state(a.state, b.state)
    for src, got in zip(jax.tree_util.tree_leaves(a.state),
                        jax.tree_util.tree_leaves(out)):
        if isinstance(src, jax.Array):
            assert got is src


# ---------------------------------------------------------------------------
# heterogeneity-aware planning
# ---------------------------------------------------------------------------


def test_mixed_fleet_shares_sum_and_slowest_member_bound():
    fleet = auto.parse_fleet("cpu:3+cpu*0.5:1")
    assert fleet.n_devices == 4 and fleet.heterogeneous
    assert fleet.name() == "cpu:3+cpu*0.5:1"

    model, opt = _mlp()
    rep = auto.plan_training(model, opt, _loss, _batch(0, b=8),
                             fleet=fleet)
    assert rep.best is not None and rep.fleet is fleet
    for p in rep.ranked:
        # heterogeneous fleets are dp-only: stragglers absorbed by batch
        # shares, never by layer shards
        assert p.tp == 1 and p.sp == 1
        assert sum(p.device_shares) == 8
        assert len(p.device_shares) == p.dp
    assert any("heterogeneous fleets are dp-only" in reason
               for _, reason in rep.rejected)

    dp4 = [p for p in rep.ranked if p.dp == 4]
    assert dp4, "no dp=4 plan feasible on the mixed fleet"
    shares = dp4[0].device_shares
    # the half-speed straggler gets the smallest share
    assert shares[3] == min(shares) and shares[3] < shares[0]

    # uniform split is bound by the straggler; weighted shares beat it
    prof = rep.profile
    ms_w, bd_w, _, _ = auto.predict_time_fleet(dp4[0], prof, fleet, 8)
    ms_u, bd_u, _, _ = auto.predict_time_fleet(dp4[0], prof, fleet, 8,
                                               shares=(2, 2, 2, 2))
    assert ms_w < ms_u
    assert dict(bd_u)["bound_member"] == 3.0


def test_fleet_predicted_order_matches_measured_order():
    """Ground the slowest-member model in a REAL measured step: time an
    actual dp step on the CPU mesh, derive each member's time as
    measured-per-sample × share ÷ declared speed, and check the
    planner's predicted ordering (weighted shares beat uniform) is the
    measured ordering."""
    fleet = auto.parse_fleet("cpu:3+cpu*0.5:1")
    model, opt = _mlp()
    rep = auto.plan_training(model, opt, _loss, _batch(0, b=8),
                             fleet=fleet)
    dp4 = [p for p in rep.ranked if p.dp == 4][0]
    weighted = dp4.device_shares
    uniform = (2, 2, 2, 2)
    scales = (1.0, 1.0, 1.0, 0.5)

    ms_w = auto.predict_time_fleet(dp4, rep.profile, fleet, 8)[0]
    ms_u = auto.predict_time_fleet(dp4, rep.profile, fleet, 8,
                                   shares=uniform)[0]

    step = make_train_step(model, opt, _loss, half_dtype=None,
                           loss_scale=1.0,
                           parallel=auto.Plan(dp=4, n_devices=4),
                           devices=jax.devices()[:4])
    x, y = _batch(3, b=8)
    step(x, y)                              # compile
    t0 = time.perf_counter()
    for _ in range(3):
        step(x, y)
    per_sample_s = (time.perf_counter() - t0) / 3 / 8
    assert per_sample_s > 0

    def makespan(shares):
        return max(per_sample_s * s / sc for s, sc in zip(shares, scales))

    measured = {"weighted": makespan(weighted),
                "uniform": makespan(uniform)}
    predicted = {"weighted": ms_w, "uniform": ms_u}
    assert (predicted["weighted"] < predicted["uniform"]) == \
        (measured["weighted"] < measured["uniform"])
    assert measured["weighted"] < measured["uniform"]


def test_cluster_trainer_heterogeneous_fleet_from_member_specs(tmp_path):
    """host_scales declare a straggler host; its registered spec flows
    through the KV into fleet_for_members, and the recovered plan
    carries per-device batch shares summing to the global batch."""
    ct = _cluster(tmp_path / "cl", n_hosts=4,
                  host_scales=[1.0, 1.0, 1.0, 0.5])
    view = ct.join()
    fleet = fleet_for_members(ct.kv, view.members)
    assert fleet.n_devices == 8 and fleet.heterogeneous
    assert "cpu*0.5" in fleet.name()

    ct.recover()
    plan = ct.plan
    assert plan.dp == 8 and len(plan.device_shares) == 8
    assert sum(plan.device_shares) == BATCH
    # the straggler host's two devices carry the smallest shares
    assert plan.device_shares[6] == min(plan.device_shares)
    assert plan.device_shares[6] < plan.device_shares[0]
    assert plan.device_shares[6] == plan.device_shares[7]
    assert np.isfinite(float(ct(*_batch(1))))


# ---------------------------------------------------------------------------
# real OS processes over FileKV
# ---------------------------------------------------------------------------


def test_spawned_member_process_joins_and_is_detected_lost(tmp_path):
    """spawn_member_process heartbeats over a FileKV from a REAL child
    process; a coordinator in this process admits it, then detects the
    loss when the child's beats run out."""
    kv = FileKV(str(tmp_path / "kv"))
    proc = spawn_member_process(str(tmp_path / "kv"), "proc0",
                                interval_s=0.05, beats=30,
                                spec='{"chip": "cpu", "n_devices": 1}')
    try:
        coord = Coordinator(kv, deadline_s=1.0, miss_threshold=2)
        deadline = time.monotonic() + 120.0     # child pays jax import
        view = None
        while time.monotonic() < deadline:
            view = coord.scan()
            if "proc0" in view.members:
                break
            time.sleep(0.2)
        assert view is not None and "proc0" in view.members
        assert proc.wait(timeout=60.0) == 0     # beats run out, clean exit
        deadline = time.monotonic() + 30.0
        while time.monotonic() < deadline:
            view = coord.scan()
            if "proc0" not in view.members:
                break
            time.sleep(0.3)
        assert "proc0" not in view.members
        assert view.epoch >= 2
    finally:
        if proc.poll() is None:
            proc.kill()
            proc.wait()
