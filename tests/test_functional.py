"""Functional-op numerics vs torch oracles (ops with no dedicated
suite; the cast-policy behavior tests live in test_policy.py)."""
import jax.numpy as jnp
import numpy as np
def test_conv_transpose2d_matches_torch(rng):
    import torch
    from apex_tpu.nn import functional as F
    x = rng.standard_normal((2, 4, 5, 5)).astype(np.float32)
    w = rng.standard_normal((4, 6, 3, 3)).astype(np.float32)
    b = rng.standard_normal((6,)).astype(np.float32)
    for stride, pad, opad in [(2, 1, 1), (1, 0, 0), (3, 2, 1)]:
        ours = F.conv_transpose2d(jnp.asarray(x), jnp.asarray(w),
                                  jnp.asarray(b), stride=stride,
                                  padding=pad, output_padding=opad)
        ref = torch.nn.functional.conv_transpose2d(
            torch.tensor(x), torch.tensor(w), torch.tensor(b),
            stride=stride, padding=pad, output_padding=opad)
        assert ours.shape == tuple(ref.shape)
        np.testing.assert_allclose(np.asarray(ours), ref.numpy(),
                                   rtol=1e-4, atol=1e-5)


def test_conv_transpose2d_groups_matches_torch(rng):
    """Grouped transposed conv (a round-1 NotImplementedError hole)."""
    import torch
    from apex_tpu.nn import functional as F
    x = rng.standard_normal((2, 4, 5, 5)).astype(np.float32)
    w = rng.standard_normal((4, 3, 3, 3)).astype(np.float32)  # g=2: 4->6
    b = rng.standard_normal((6,)).astype(np.float32)
    for stride, pad, opad, dil in [(2, 1, 1, 1), (1, 0, 0, 1), (2, 1, 0, 2)]:
        ours = F.conv_transpose2d(jnp.asarray(x), jnp.asarray(w),
                                  jnp.asarray(b), stride=stride, padding=pad,
                                  output_padding=opad, groups=2,
                                  dilation=dil)
        ref = torch.nn.functional.conv_transpose2d(
            torch.tensor(x), torch.tensor(w), torch.tensor(b),
            stride=stride, padding=pad, output_padding=opad, groups=2,
            dilation=dil)
        assert ours.shape == tuple(ref.shape)
        np.testing.assert_allclose(np.asarray(ours), ref.numpy(),
                                   rtol=1e-4, atol=1e-5)


def test_adaptive_avg_pool2d_matches_torch(rng):
    """Arbitrary (incl. non-divisible) output sizes (round-1: global only)."""
    import torch
    from apex_tpu.nn import functional as F
    x = rng.standard_normal((2, 3, 11, 7)).astype(np.float32)
    for out in [(1, 1), (4, 4), (5, 3), (11, 7), (3, 5), 2]:
        ours = F.adaptive_avg_pool2d(jnp.asarray(x), out)
        ref = torch.nn.functional.adaptive_avg_pool2d(torch.tensor(x), out)
        assert ours.shape == tuple(ref.shape)
        np.testing.assert_allclose(np.asarray(ours), ref.numpy(),
                                   rtol=1e-5, atol=1e-6)


def test_group_norm_matches_torch(rng):
    import torch
    import apex_tpu.nn as nn
    from apex_tpu.nn import functional as F

    x = rng.standard_normal((4, 12, 5, 7)).astype(np.float32)
    w = rng.standard_normal((12,)).astype(np.float32)
    b = rng.standard_normal((12,)).astype(np.float32)
    want = torch.nn.functional.group_norm(
        torch.from_numpy(x), 3, torch.from_numpy(w), torch.from_numpy(b),
        eps=1e-5).numpy()
    got = F.group_norm(jnp.asarray(x), 3, jnp.asarray(w), jnp.asarray(b))
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-5, atol=2e-5)

    # module form
    m = nn.GroupNorm(3, 12)
    m.weight.data = jnp.asarray(w)
    m.bias.data = jnp.asarray(b)
    got = m(jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-5, atol=2e-5)


def test_group_norm_rejects_indivisible():
    import pytest
    from apex_tpu.nn import functional as F
    with pytest.raises(ValueError, match="divisible"):
        F.group_norm(jnp.zeros((2, 10, 4, 4)), 3)


def test_instance_norm_matches_torch(rng):
    import torch
    import apex_tpu.nn as nn
    from apex_tpu.nn import functional as F

    x = rng.standard_normal((4, 6, 8, 8)).astype(np.float32)
    w = rng.standard_normal((6,)).astype(np.float32)
    b = rng.standard_normal((6,)).astype(np.float32)
    want = torch.nn.functional.instance_norm(
        torch.from_numpy(x), weight=torch.from_numpy(w),
        bias=torch.from_numpy(b), eps=1e-5).numpy()
    got, _, _ = F.instance_norm(jnp.asarray(x), weight=jnp.asarray(w),
                                bias=jnp.asarray(b))
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-5, atol=2e-5)

    m = nn.InstanceNorm2d(6, affine=True)
    m.weight.data = jnp.asarray(w)
    m.bias.data = jnp.asarray(b)
    got = m(jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-5, atol=2e-5)


def test_instance_norm_running_stats_match_torch(rng):
    import torch
    import apex_tpu.nn as nn

    x1 = rng.standard_normal((4, 6, 8, 8)).astype(np.float32)
    x2 = rng.standard_normal((4, 6, 8, 8)).astype(np.float32)

    tm = torch.nn.InstanceNorm2d(6, track_running_stats=True)
    tm.train()
    tm(torch.from_numpy(x1))
    tm(torch.from_numpy(x2))
    tm.eval()
    x3 = rng.standard_normal((2, 6, 8, 8)).astype(np.float32)
    want = tm(torch.from_numpy(x3)).numpy()

    m = nn.InstanceNorm2d(6, track_running_stats=True)
    m.train()
    m(jnp.asarray(x1))
    m(jnp.asarray(x2))
    np.testing.assert_allclose(np.asarray(m.running_mean.data),
                               tm.running_mean.numpy(), rtol=1e-5,
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(m.running_var.data),
                               tm.running_var.numpy(), rtol=1e-5,
                               atol=1e-6)
    m.eval()
    got = m(jnp.asarray(x3))
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-5, atol=2e-5)


def test_group_norm_grads_flow(rng):
    import apex_tpu.nn as nn
    model = nn.Sequential(nn.Conv2d(3, 8, 3, padding=1), nn.GroupNorm(2, 8),
                          nn.ReLU(), nn.Flatten(), nn.Linear(8 * 64, 4))
    x = jnp.asarray(rng.standard_normal((2, 3, 8, 8)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 4, (2,)))
    loss = nn.CrossEntropyLoss()(model(x), y)
    loss.backward()
    assert all(p.grad is not None for p in model.parameters())


def test_instance_norm_rejects_degenerate_spatial():
    import pytest
    import apex_tpu.nn as nn
    with pytest.raises(ValueError, match="spatial"):
        nn.InstanceNorm2d(6)(jnp.zeros((4, 6, 1, 1)))
    with pytest.raises(ValueError, match="spatial"):
        from apex_tpu.nn import functional as F
        F.instance_norm(jnp.zeros((4, 6)))
