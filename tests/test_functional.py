"""Functional-op numerics vs torch oracles (ops with no dedicated
suite; the cast-policy behavior tests live in test_policy.py)."""
import jax.numpy as jnp
import numpy as np
def test_conv_transpose2d_matches_torch(rng):
    import torch
    from apex_tpu.nn import functional as F
    x = rng.standard_normal((2, 4, 5, 5)).astype(np.float32)
    w = rng.standard_normal((4, 6, 3, 3)).astype(np.float32)
    b = rng.standard_normal((6,)).astype(np.float32)
    for stride, pad, opad in [(2, 1, 1), (1, 0, 0), (3, 2, 1)]:
        ours = F.conv_transpose2d(jnp.asarray(x), jnp.asarray(w),
                                  jnp.asarray(b), stride=stride,
                                  padding=pad, output_padding=opad)
        ref = torch.nn.functional.conv_transpose2d(
            torch.tensor(x), torch.tensor(w), torch.tensor(b),
            stride=stride, padding=pad, output_padding=opad)
        assert ours.shape == tuple(ref.shape)
        np.testing.assert_allclose(np.asarray(ours), ref.numpy(),
                                   rtol=1e-4, atol=1e-5)


def test_conv_transpose2d_groups_rejected(rng):
    import pytest
    from apex_tpu.nn import functional as F
    x = jnp.asarray(rng.standard_normal((2, 4, 5, 5)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((4, 3, 3, 3)), jnp.float32)
    with pytest.raises(NotImplementedError, match="groups"):
        F.conv_transpose2d(x, w, groups=2)
