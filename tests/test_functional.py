"""Functional-op numerics vs torch oracles (ops with no dedicated
suite; the cast-policy behavior tests live in test_policy.py)."""
import jax.numpy as jnp
import numpy as np
def test_conv_transpose2d_matches_torch(rng):
    import torch
    from apex_tpu.nn import functional as F
    x = rng.standard_normal((2, 4, 5, 5)).astype(np.float32)
    w = rng.standard_normal((4, 6, 3, 3)).astype(np.float32)
    b = rng.standard_normal((6,)).astype(np.float32)
    for stride, pad, opad in [(2, 1, 1), (1, 0, 0), (3, 2, 1)]:
        ours = F.conv_transpose2d(jnp.asarray(x), jnp.asarray(w),
                                  jnp.asarray(b), stride=stride,
                                  padding=pad, output_padding=opad)
        ref = torch.nn.functional.conv_transpose2d(
            torch.tensor(x), torch.tensor(w), torch.tensor(b),
            stride=stride, padding=pad, output_padding=opad)
        assert ours.shape == tuple(ref.shape)
        np.testing.assert_allclose(np.asarray(ours), ref.numpy(),
                                   rtol=1e-4, atol=1e-5)


def test_conv_transpose2d_groups_matches_torch(rng):
    """Grouped transposed conv (a round-1 NotImplementedError hole)."""
    import torch
    from apex_tpu.nn import functional as F
    x = rng.standard_normal((2, 4, 5, 5)).astype(np.float32)
    w = rng.standard_normal((4, 3, 3, 3)).astype(np.float32)  # g=2: 4->6
    b = rng.standard_normal((6,)).astype(np.float32)
    for stride, pad, opad, dil in [(2, 1, 1, 1), (1, 0, 0, 1), (2, 1, 0, 2)]:
        ours = F.conv_transpose2d(jnp.asarray(x), jnp.asarray(w),
                                  jnp.asarray(b), stride=stride, padding=pad,
                                  output_padding=opad, groups=2,
                                  dilation=dil)
        ref = torch.nn.functional.conv_transpose2d(
            torch.tensor(x), torch.tensor(w), torch.tensor(b),
            stride=stride, padding=pad, output_padding=opad, groups=2,
            dilation=dil)
        assert ours.shape == tuple(ref.shape)
        np.testing.assert_allclose(np.asarray(ours), ref.numpy(),
                                   rtol=1e-4, atol=1e-5)


def test_adaptive_avg_pool2d_matches_torch(rng):
    """Arbitrary (incl. non-divisible) output sizes (round-1: global only)."""
    import torch
    from apex_tpu.nn import functional as F
    x = rng.standard_normal((2, 3, 11, 7)).astype(np.float32)
    for out in [(1, 1), (4, 4), (5, 3), (11, 7), (3, 5), 2]:
        ours = F.adaptive_avg_pool2d(jnp.asarray(x), out)
        ref = torch.nn.functional.adaptive_avg_pool2d(torch.tensor(x), out)
        assert ours.shape == tuple(ref.shape)
        np.testing.assert_allclose(np.asarray(ours), ref.numpy(),
                                   rtol=1e-5, atol=1e-6)


def test_group_norm_matches_torch(rng):
    import torch
    import apex_tpu.nn as nn
    from apex_tpu.nn import functional as F

    x = rng.standard_normal((4, 12, 5, 7)).astype(np.float32)
    w = rng.standard_normal((12,)).astype(np.float32)
    b = rng.standard_normal((12,)).astype(np.float32)
    want = torch.nn.functional.group_norm(
        torch.from_numpy(x), 3, torch.from_numpy(w), torch.from_numpy(b),
        eps=1e-5).numpy()
    got = F.group_norm(jnp.asarray(x), 3, jnp.asarray(w), jnp.asarray(b))
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-5, atol=2e-5)

    # module form
    m = nn.GroupNorm(3, 12)
    m.weight.data = jnp.asarray(w)
    m.bias.data = jnp.asarray(b)
    got = m(jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-5, atol=2e-5)


def test_group_norm_rejects_indivisible():
    import pytest
    from apex_tpu.nn import functional as F
    with pytest.raises(ValueError, match="divisible"):
        F.group_norm(jnp.zeros((2, 10, 4, 4)), 3)


def test_instance_norm_matches_torch(rng):
    import torch
    import apex_tpu.nn as nn
    from apex_tpu.nn import functional as F

    x = rng.standard_normal((4, 6, 8, 8)).astype(np.float32)
    w = rng.standard_normal((6,)).astype(np.float32)
    b = rng.standard_normal((6,)).astype(np.float32)
    want = torch.nn.functional.instance_norm(
        torch.from_numpy(x), weight=torch.from_numpy(w),
        bias=torch.from_numpy(b), eps=1e-5).numpy()
    got, _, _ = F.instance_norm(jnp.asarray(x), weight=jnp.asarray(w),
                                bias=jnp.asarray(b))
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-5, atol=2e-5)

    m = nn.InstanceNorm2d(6, affine=True)
    m.weight.data = jnp.asarray(w)
    m.bias.data = jnp.asarray(b)
    got = m(jnp.asarray(x))
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-5, atol=2e-5)


def test_instance_norm_running_stats_match_torch(rng):
    import torch
    import apex_tpu.nn as nn

    x1 = rng.standard_normal((4, 6, 8, 8)).astype(np.float32)
    x2 = rng.standard_normal((4, 6, 8, 8)).astype(np.float32)

    tm = torch.nn.InstanceNorm2d(6, track_running_stats=True)
    tm.train()
    tm(torch.from_numpy(x1))
    tm(torch.from_numpy(x2))
    tm.eval()
    x3 = rng.standard_normal((2, 6, 8, 8)).astype(np.float32)
    want = tm(torch.from_numpy(x3)).numpy()

    m = nn.InstanceNorm2d(6, track_running_stats=True)
    m.train()
    m(jnp.asarray(x1))
    m(jnp.asarray(x2))
    np.testing.assert_allclose(np.asarray(m.running_mean.data),
                               tm.running_mean.numpy(), rtol=1e-5,
                               atol=1e-6)
    np.testing.assert_allclose(np.asarray(m.running_var.data),
                               tm.running_var.numpy(), rtol=1e-5,
                               atol=1e-6)
    m.eval()
    got = m(jnp.asarray(x3))
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-5, atol=2e-5)


def test_group_norm_grads_flow(rng):
    import apex_tpu.nn as nn
    model = nn.Sequential(nn.Conv2d(3, 8, 3, padding=1), nn.GroupNorm(2, 8),
                          nn.ReLU(), nn.Flatten(), nn.Linear(8 * 64, 4))
    x = jnp.asarray(rng.standard_normal((2, 3, 8, 8)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 4, (2,)))
    loss = nn.CrossEntropyLoss()(model(x), y)
    loss.backward()
    assert all(p.grad is not None for p in model.parameters())


def test_instance_norm_rejects_degenerate_spatial():
    import pytest
    import apex_tpu.nn as nn
    with pytest.raises(ValueError, match="spatial"):
        nn.InstanceNorm2d(6)(jnp.zeros((4, 6, 1, 1)))
    with pytest.raises(ValueError, match="spatial"):
        from apex_tpu.nn import functional as F
        F.instance_norm(jnp.zeros((4, 6)))


def test_dropout_mask_rbg_semantics(rng, monkeypatch):
    """The fast (RngBitGenerator) mask path: deterministic per key,
    independent across keys, keep-fraction ~ keep, zeros where dropped and
    exact 1/keep scaling where kept."""
    import jax
    from apex_tpu.nn import functional as F
    monkeypatch.setenv("APEX_TPU_DROPOUT_IMPL", "rbg")
    key = jax.random.PRNGKey(3)
    x = jnp.asarray(rng.standard_normal((64, 256)), jnp.float32)
    out1 = F.dropout(x, p=0.3, training=True, key=key)
    out2 = F.dropout(x, p=0.3, training=True, key=key)
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
    out3 = F.dropout(x, p=0.3, training=True, key=jax.random.PRNGKey(4))
    assert (np.asarray(out1) != np.asarray(out3)).any()
    kept = np.asarray(out1) != 0
    assert abs(kept.mean() - 0.7) < 0.02
    np.testing.assert_allclose(np.asarray(out1)[kept],
                               (np.asarray(x) / 0.7)[kept], rtol=1e-6)


def test_dropout_mask_impl_switch(monkeypatch):
    """APEX_TPU_DROPOUT_IMPL=threefry restores jax.random.bernoulli masks
    bit-for-bit; both impls accept typed keys."""
    import jax
    from apex_tpu.nn import functional as F
    key = jax.random.PRNGKey(9)
    monkeypatch.setenv("APEX_TPU_DROPOUT_IMPL", "threefry")
    m = F.dropout_mask(key, 0.8, (32, 32))
    want = jax.random.bernoulli(key, 0.8, (32, 32))
    np.testing.assert_array_equal(np.asarray(m), np.asarray(want))
    monkeypatch.setenv("APEX_TPU_DROPOUT_IMPL", "rbg")
    typed = jax.random.key(9)
    m_raw = F.dropout_mask(key, 0.8, (32, 32))
    m_typed = F.dropout_mask(typed, 0.8, (32, 32))
    # typed and raw forms of the same key seed the generator identically
    np.testing.assert_array_equal(np.asarray(m_raw), np.asarray(m_typed))


def test_dropout_mask_under_jit_and_grad(rng):
    """The mask is a non-differentiable residual: grad is 1/keep on kept
    elements, 0 on dropped, and fwd/bwd agree on the mask under jit."""
    import jax
    from apex_tpu.nn import functional as F
    key = jax.random.PRNGKey(11)
    x = jnp.asarray(rng.standard_normal((16, 64)), jnp.float32)

    def loss(x):
        return jnp.sum(F.dropout(x, p=0.5, training=True, key=key))

    g = jax.jit(jax.grad(loss))(x)
    out = F.dropout(x, p=0.5, training=True, key=key)
    kept = np.asarray(out) != 0
    np.testing.assert_allclose(np.asarray(g)[kept], 2.0, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(g)[~kept], 0.0)


def test_dropout_mask_rejects_stacked_keys(monkeypatch):
    """A stacked key array must fail fast under both impls (the rbg path
    used to silently collapse it into one seed)."""
    import jax
    import pytest
    from apex_tpu.nn import functional as F
    stacked_raw = jnp.stack(jax.random.split(jax.random.PRNGKey(0)))
    stacked_typed = jax.random.split(jax.random.key(0))
    monkeypatch.setenv("APEX_TPU_DROPOUT_IMPL", "rbg")
    with pytest.raises(ValueError, match="vmap"):
        F.dropout_mask(stacked_raw, 0.5, (8, 8))
    with pytest.raises(ValueError, match="vmap"):
        F.dropout_mask(stacked_typed, 0.5, (8, 8))


def test_dropout_mask_edge_cases(monkeypatch):
    """keep endpoints are exact, traced keep works under jit (bernoulli
    parity), and a bad impl env value fails fast."""
    import jax
    import pytest
    from apex_tpu.nn import functional as F
    key = jax.random.PRNGKey(0)
    monkeypatch.setenv("APEX_TPU_DROPOUT_IMPL", "rbg")
    assert np.asarray(F.dropout_mask(key, 1.0, (64, 64))).all()
    assert not np.asarray(F.dropout_mask(key, 0.0, (64, 64))).any()

    # traced keep probability (bernoulli accepted a tracer here too)
    f = jax.jit(lambda p, k: F.dropout_mask(k, 1.0 - p, (128, 128)))
    m = np.asarray(f(jnp.float32(0.3), key))
    assert abs(m.mean() - 0.7) < 0.03
    assert np.asarray(f(jnp.float32(0.0), key)).all()
    assert not np.asarray(f(jnp.float32(1.0), key)).any()

    monkeypatch.setenv("APEX_TPU_DROPOUT_IMPL", "threefy")
    with pytest.raises(ValueError, match="APEX_TPU_DROPOUT_IMPL"):
        F.dropout_mask(key, 0.5, (8, 8))
