"""Functional-op numerics vs torch oracles (ops with no dedicated
suite; the cast-policy behavior tests live in test_policy.py)."""
import jax.numpy as jnp
import numpy as np
def test_conv_transpose2d_matches_torch(rng):
    import torch
    from apex_tpu.nn import functional as F
    x = rng.standard_normal((2, 4, 5, 5)).astype(np.float32)
    w = rng.standard_normal((4, 6, 3, 3)).astype(np.float32)
    b = rng.standard_normal((6,)).astype(np.float32)
    for stride, pad, opad in [(2, 1, 1), (1, 0, 0), (3, 2, 1)]:
        ours = F.conv_transpose2d(jnp.asarray(x), jnp.asarray(w),
                                  jnp.asarray(b), stride=stride,
                                  padding=pad, output_padding=opad)
        ref = torch.nn.functional.conv_transpose2d(
            torch.tensor(x), torch.tensor(w), torch.tensor(b),
            stride=stride, padding=pad, output_padding=opad)
        assert ours.shape == tuple(ref.shape)
        np.testing.assert_allclose(np.asarray(ours), ref.numpy(),
                                   rtol=1e-4, atol=1e-5)


def test_conv_transpose2d_groups_matches_torch(rng):
    """Grouped transposed conv (a round-1 NotImplementedError hole)."""
    import torch
    from apex_tpu.nn import functional as F
    x = rng.standard_normal((2, 4, 5, 5)).astype(np.float32)
    w = rng.standard_normal((4, 3, 3, 3)).astype(np.float32)  # g=2: 4->6
    b = rng.standard_normal((6,)).astype(np.float32)
    for stride, pad, opad, dil in [(2, 1, 1, 1), (1, 0, 0, 1), (2, 1, 0, 2)]:
        ours = F.conv_transpose2d(jnp.asarray(x), jnp.asarray(w),
                                  jnp.asarray(b), stride=stride, padding=pad,
                                  output_padding=opad, groups=2,
                                  dilation=dil)
        ref = torch.nn.functional.conv_transpose2d(
            torch.tensor(x), torch.tensor(w), torch.tensor(b),
            stride=stride, padding=pad, output_padding=opad, groups=2,
            dilation=dil)
        assert ours.shape == tuple(ref.shape)
        np.testing.assert_allclose(np.asarray(ours), ref.numpy(),
                                   rtol=1e-4, atol=1e-5)


def test_adaptive_avg_pool2d_matches_torch(rng):
    """Arbitrary (incl. non-divisible) output sizes (round-1: global only)."""
    import torch
    from apex_tpu.nn import functional as F
    x = rng.standard_normal((2, 3, 11, 7)).astype(np.float32)
    for out in [(1, 1), (4, 4), (5, 3), (11, 7), (3, 5), 2]:
        ours = F.adaptive_avg_pool2d(jnp.asarray(x), out)
        ref = torch.nn.functional.adaptive_avg_pool2d(torch.tensor(x), out)
        assert ours.shape == tuple(ref.shape)
        np.testing.assert_allclose(np.asarray(ours), ref.numpy(),
                                   rtol=1e-5, atol=1e-6)
