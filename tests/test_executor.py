"""The one-runtime executor (apex_tpu/runtime/executor.py).

Pins the tentpole contract of the unified dispatch path:

* the eager optimizer surface and the fused train step run through the
  SAME executor — shared stats, shared donation policy, loss/param
  parity between the two surfaces (bitwise for fp32 SGD);
* 1 compile + 1 dispatch per window on BOTH surfaces under an lr
  schedule (the step-cache invariant, now executor-owned);
* ZeRO-1/3 all-gather prefetch is a pure schedule transformation:
  overlap on vs off is bitwise-identical (on this cpu backend XLA runs
  the collectives synchronously, so the parity is provable in-tree);
* ``Executor.drive`` + ``DataPrefetcher`` issue exactly one H2D
  transfer per accumulation window, double-buffered;
* resilience (BadStepGuard, elastic load_state) composes with
  executor-dispatched steps;
* the telemetry carry works across mesh plans (dp×tp) — the satellite
  fix for ``make_train_step(telemetry=True)`` refusing tp plans.
"""
import math

import jax.numpy as jnp
import numpy as np
import pytest

import apex_tpu.nn as nn
from apex_tpu.nn import functional as F
from apex_tpu.observe import get_registry
from apex_tpu.optimizers import FusedAdam, FusedSGD
from apex_tpu.parallel import auto
from apex_tpu.runtime import executor as rex
from apex_tpu.runtime import resilience, step_cache
from apex_tpu.runtime.resilience import BadStepGuard
from apex_tpu.training import make_train_step


@pytest.fixture(autouse=True)
def _fresh():
    step_cache.clear()
    step_cache.reset_stats()
    get_registry().clear_events()
    yield
    step_cache.clear()
    step_cache.reset_stats()


def _model(seed=7):
    nn.manual_seed(seed)
    return nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))


def _data(rng, b=8):
    x = jnp.asarray(rng.standard_normal((b, 16)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 4, (b,)))
    return x, y


def _loss(o, t):
    return F.cross_entropy(o, t)


# ---------------------------------------------------------------------------
# Program / submit / policy unit surface
# ---------------------------------------------------------------------------


def test_program_jit_memoized_and_uncounted():
    """executor.jit is the diagnostic surface: one jitted callable per
    Program (memoized), and building it never counts a dispatch."""
    prog = rex.Program("train_step", ("t-memo",), lambda a, b: a + b)
    f1 = rex.executor.jit(prog)
    f2 = rex.executor.jit(prog)
    assert f1 is f2
    s = rex.executor.stats()
    assert s["dispatches"] == 0 and s["compiles"] == 0


def test_submit_counts_compiles_and_dispatches():
    prog = rex.Program("train_step", ("t-count",), lambda a, b: a + b)
    a, b = jnp.ones((3,)), jnp.ones((3,))
    out1 = rex.executor.submit(prog, (a, b), step=1)
    out2 = rex.executor.submit(prog, (a, b), step=2)
    np.testing.assert_array_equal(np.asarray(out1), np.asarray(out2))
    st = rex.executor.stats()["by_kind"]["train_step"]
    assert st["compiles"] == 1
    assert st["dispatches"] == 2
    assert st["cache_hits"] == 1
    # train-kind dispatches opened spans and heartbeat the watchdog
    spans = [e for e in get_registry().events("span")
             if e["span"] == "dispatch" and e["kind"] == "train_step"]
    assert len(spans) == 2


def test_donation_policy_resolution():
    d = rex.DonationPolicy()
    assert d.mode == "auto"
    assert d.enabled is False          # cpu test backend: auto is off
    assert d.resolve(True) is True
    assert d.resolve(False) is False
    assert d.resolve("auto") is False
    d.set(True)
    assert d.enabled is True and d.resolve("auto") is True
    with pytest.raises(ValueError, match="donation mode"):
        d.set("maybe")


def test_step_cache_donation_is_executor_delegate():
    """set_donation/donation_enabled are thin views of the ONE policy on
    the executor — no second copy to drift."""
    assert step_cache.donation_enabled() is rex.donation.enabled is False
    step_cache.set_donation(True)
    try:
        assert rex.donation.enabled is True
        assert step_cache.donation_enabled() is True
    finally:
        step_cache.set_donation("auto")
    assert rex.donation.mode == "auto"


def test_overlap_knobs_resolution_and_validation():
    # cpu backend: "auto" resolves off for both dimensions
    assert rex.overlap_enabled("gather") is False
    assert rex.overlap_enabled("h2d") is False
    rex.set_overlap(gather=True)
    try:
        assert rex.overlap_enabled("gather") is True
        # a per-call override wins over the process knob
        assert rex.overlap_enabled("gather", override=False) is False
        # None/"auto" overrides defer to the knob
        assert rex.overlap_enabled("gather", override="auto") is True
        assert rex.overlap_enabled("h2d") is False   # other knob untouched
    finally:
        rex.set_overlap(gather="auto")
    assert rex.overlap_enabled("gather") is False
    with pytest.raises(ValueError, match="overlap gather"):
        rex.set_overlap(gather="sometimes")


# ---------------------------------------------------------------------------
# donation: input->output aliasing in the lowered HLO (executor-level —
# relocated from test_step_cache.py: the policy lives on the executor now)
# ---------------------------------------------------------------------------


def test_donation_alias_in_lowered_hlo(rng):
    # donation is "auto" (off on the copy-on-donate cpu backend); force it
    # on to inspect the aliasing the accelerator path compiles with
    rex.donation.set(True)
    try:
        from apex_tpu.nn import Parameter
        params = []
        for s in [(7,), (5, 3)]:
            p = Parameter(jnp.asarray(rng.standard_normal(s), jnp.float32))
            p.grad = jnp.asarray(rng.standard_normal(s), jnp.float32)
            params.append(p)
        opt = FusedAdam(params, lr=1e-2)
        opt.step()
        (entry,) = [e for e in rex.executor.cache.entries()
                    if e["kind"] == "fused_adam"]
        txt = entry["fn"].lower(*entry["example"]).as_text()
        # donated leaves: params + exp_avg + exp_avg_sq per bucket + the
        # step counter — every one must alias an output buffer
        n_donated = 3 * len(params) + 1
        assert txt.count("tf.aliasing_output") >= n_donated
    finally:
        rex.donation.set("auto")


# ---------------------------------------------------------------------------
# one executor, two surfaces: eager optimizer.step() vs fused train step
# ---------------------------------------------------------------------------


def test_eager_and_fused_sgd_match_bitwise(rng):
    """fp32 SGD, loss_scale=1.0: the eager backward+optimizer.step()
    surface and the fused train step — both dispatched by the one
    executor — produce bitwise-identical parameters."""
    x, y = _data(rng)
    crit = nn.CrossEntropyLoss()

    model_a = _model()
    opt_a = FusedSGD(list(model_a.parameters()), lr=0.05, momentum=0.9)
    for _ in range(4):
        loss = crit(model_a(x), y)
        loss.backward()
        opt_a.step()
        opt_a.zero_grad()

    model_b = _model()
    opt_b = FusedSGD(list(model_b.parameters()), lr=0.05, momentum=0.9)
    step = make_train_step(model_b, opt_b, _loss, half_dtype=None,
                           loss_scale=1.0)
    for _ in range(4):
        step(x, y)

    for pa, mb in zip(model_a.parameters(), step.state.master_params):
        np.testing.assert_array_equal(np.asarray(pa.data), np.asarray(mb))

    # both surfaces were counted by the SAME executor
    by = rex.executor.stats()["by_kind"]
    assert by["fused_sgd"]["dispatches"] == 4
    assert by["train_step"]["dispatches"] == 4


def test_eager_and_fused_adam_match(rng):
    x, y = _data(rng)
    crit = nn.CrossEntropyLoss()

    model_a = _model()
    opt_a = FusedAdam(list(model_a.parameters()), lr=1e-2)
    eager = []
    for _ in range(4):
        loss = crit(model_a(x), y)
        loss.backward()
        opt_a.step()
        opt_a.zero_grad()
        eager.append(float(loss))

    model_b = _model()
    opt_b = FusedAdam(list(model_b.parameters()), lr=1e-2)
    step = make_train_step(model_b, opt_b, _loss, half_dtype=None,
                           loss_scale=1.0)
    fused = [float(step(x, y)) for _ in range(4)]

    # tolerance, not bitwise: Adam's eps/sqrt denominator amplifies the
    # one-executable fusion's reassociation by a few ulp per step
    np.testing.assert_allclose(fused, eager, rtol=1e-5, atol=1e-6)
    for pa, mb in zip(model_a.parameters(), step.state.master_params):
        np.testing.assert_allclose(np.asarray(pa.data), np.asarray(mb),
                                   rtol=1e-4, atol=1e-5)


def test_one_compile_per_window_both_surfaces_under_cosine_lr(rng):
    """The retrace pin, at the executor: a cosine lr schedule keys NO new
    program on either surface — 1 compile, 1 dispatch per window."""
    lr_of = lambda i: 1e-2 * 0.5 * (1 + math.cos(math.pi * i / 10))  # noqa: E731

    # eager surface
    model_a = _model()
    opt_a = FusedAdam(list(model_a.parameters()), lr=1e-2)
    crit = nn.CrossEntropyLoss()
    x, y = _data(rng)
    for i in range(10):
        opt_a.param_groups[0]["lr"] = lr_of(i)
        loss = crit(model_a(x), y)
        loss.backward()
        opt_a.step()
        opt_a.zero_grad()
    st = rex.executor.stats()["by_kind"]["fused_adam"]
    assert st["compiles"] == 1 and st["dispatches"] == 10

    # fused surface, K=4 accumulation windows
    model_b = _model()
    opt_b = FusedAdam(list(model_b.parameters()), lr=1e-2)
    step = make_train_step(model_b, opt_b, _loss, half_dtype=None,
                           loss_scale=1.0, accum_steps=4,
                           accum_stacked=True)
    rng2 = np.random.default_rng(0)
    xb = jnp.asarray(rng2.standard_normal((4, 4, 16)), jnp.float32)
    yb = jnp.asarray(rng2.integers(0, 4, (4, 4)))
    for i in range(6):
        opt_b.param_groups[0]["lr"] = lr_of(i)
        step(xb, yb)
    st = rex.executor.stats()["by_kind"]["train_step"]
    assert st["compiles"] == 1
    assert st["dispatches"] == 6       # windows, not microbatches
    assert st["cache_hits"] == 5


# ---------------------------------------------------------------------------
# ZeRO all-gather prefetch: overlap on == overlap off, bitwise
# ---------------------------------------------------------------------------


def _zero_build(stage, overlap, lr=1e-2):
    nn.manual_seed(11)
    model = nn.Sequential(nn.Linear(16, 64), nn.ReLU(), nn.Linear(64, 8))
    opt = FusedAdam(list(model.parameters()), lr=lr)
    step = make_train_step(model, opt, _loss, half_dtype=None,
                           loss_scale=1.0, zero_sharding=True,
                           zero_stage=stage, accum_steps=4,
                           donate_state=False, overlap=overlap)
    return step


@pytest.mark.parametrize("stage", [1, 3])
def test_zero_gather_prefetch_bitwise_parity(stage, rng):
    """The prefetch pipelines the replicated parameter view one scan
    iteration early — a pure schedule change.  Forced on (the cpu "auto"
    default is off) it must be bitwise-identical to overlap off."""
    x = jnp.asarray(rng.standard_normal((32, 16)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 8, (32,)))

    off = _zero_build(stage, overlap=False)
    off_losses = [float(off(x, y)) for _ in range(3)]

    # the process-wide knob spelling: set_overlap + overlap="auto"
    rex.set_overlap(gather=True)
    try:
        on = _zero_build(stage, overlap="auto")
        on_losses = [float(on(x, y)) for _ in range(3)]
    finally:
        rex.set_overlap(gather="auto")

    assert on_losses == off_losses     # float() of bitwise-equal scalars
    for a, b in zip(on.state.master_params, off.state.master_params):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    st = rex.executor.stats()["by_kind"]["zero_train_step"]
    assert st["dispatches"] == 6       # 3 windows each build, 1 per window
    assert st["compiles"] == 2         # one program per build token


# ---------------------------------------------------------------------------
# Executor.drive: async H2D double-buffering, one transfer per window
# ---------------------------------------------------------------------------


def test_drive_one_h2d_per_window(rng):
    """drive() wraps a host iterable in a DataPrefetcher: K loader
    batches stack into one (K, B, ...) block and cross H2D as exactly ONE
    span("h2d") transfer per accumulation window."""
    model = _model()
    opt = FusedSGD(list(model.parameters()), lr=0.05)
    step = make_train_step(model, opt, _loss, half_dtype=None,
                           loss_scale=1.0, accum_steps=4,
                           accum_stacked=True)
    host_rng = np.random.default_rng(5)
    batches = [(host_rng.standard_normal((8, 16)).astype(np.float32),
                host_rng.integers(0, 4, (8,)))
               for _ in range(20)]                    # 20 batches = 5 windows

    rex.set_overlap(h2d=True)                         # force double-buffering
    try:
        losses = rex.executor.drive(step, batches, accum_steps=4)
    finally:
        rex.set_overlap(h2d="auto")

    assert len(losses) == 5
    assert all(np.isfinite(float(l)) for l in losses)
    h2d = [e for e in get_registry().events("span") if e["span"] == "h2d"]
    assert len(h2d) == 5                              # ONE transfer per window
    assert all(e["accum_steps"] == 4 for e in h2d)
    st = rex.executor.stats()["by_kind"]["train_step"]
    assert st["compiles"] == 1 and st["dispatches"] == 5

    # the pipeline is numerically inert: a plain loop over the same
    # blocks gives the same losses bitwise
    model2 = _model()
    opt2 = FusedSGD(list(model2.parameters()), lr=0.05)
    step2 = make_train_step(model2, opt2, _loss, half_dtype=None,
                            loss_scale=1.0, accum_steps=4,
                            accum_stacked=True)
    ref = []
    for w in range(5):
        blk = batches[4 * w:4 * w + 4]
        xb = jnp.asarray(np.stack([b[0] for b in blk]))
        yb = jnp.asarray(np.stack([b[1] for b in blk]))
        ref.append(float(step2(xb, yb)))
    assert [float(l) for l in losses] == ref


def test_drive_respects_max_steps(rng):
    model = _model()
    opt = FusedSGD(list(model.parameters()), lr=0.05)
    step = make_train_step(model, opt, _loss, half_dtype=None,
                           loss_scale=1.0)
    host_rng = np.random.default_rng(5)
    batches = [(host_rng.standard_normal((8, 16)).astype(np.float32),
                host_rng.integers(0, 4, (8,)))
               for _ in range(10)]
    losses = rex.executor.drive(step, batches, max_steps=3)
    assert len(losses) == 3
    assert rex.executor.stats()["by_kind"]["train_step"]["dispatches"] == 3


# ---------------------------------------------------------------------------
# resilience through the executor
# ---------------------------------------------------------------------------


def test_guard_observes_through_zero_step(rng):
    """BadStepGuard attaches to the (executor-dispatched) ZeRO wrapper:
    clean windows observed, overflow windows counted and escalated."""
    nn.manual_seed(3)
    model = nn.Sequential(nn.Linear(16, 64), nn.ReLU(), nn.Linear(64, 8))
    opt = FusedAdam(list(model.parameters()), lr=1e-3)
    step = make_train_step(model, opt, _loss, half_dtype=jnp.float16,
                           loss_scale="dynamic", zero_sharding=True,
                           donate_state=False)
    guard = BadStepGuard(patience=2, policy="warn")
    guard.attach(step)
    x = jnp.asarray(rng.standard_normal((32, 16)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 8, (32,)))
    step(x, y)
    step(x, y)
    guard.flush()
    assert guard.stats["observed"] == 2
    assert guard.stats["skipped"] == 0

    bad = x.at[0, 0].set(np.inf)
    with pytest.warns(UserWarning, match="BadStepGuard"):
        step(bad, y)
        step(bad, y)
        guard.flush()
    assert guard.stats["skipped"] == 2
    assert guard.stats["escalations"] == 1


def test_elastic_load_state_resumes_bitwise(rng):
    """snapshot -> fresh build -> load_state: the restored step continues
    bitwise-identically to the uninterrupted run, still 1 dispatch per
    window through the executor."""
    x = jnp.asarray(rng.standard_normal((32, 16)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 8, (32,)))
    plan = auto.Plan(dp=4, zero_stage=1, n_devices=8)

    def build(seed):
        nn.manual_seed(seed)
        model = nn.Sequential(nn.Linear(16, 64), nn.ReLU(),
                              nn.Linear(64, 8))
        opt = FusedAdam(list(model.parameters()), lr=1e-2)
        return make_train_step(model, opt, _loss, half_dtype=None,
                               loss_scale=1.0, parallel=plan)

    z = build(0)
    for _ in range(3):
        z(x, y)
    host = resilience.snapshot_state(z.state)

    z2 = build(1)                      # different init: restore must win
    z2.load_state(host)
    cont = [float(z2(x, y)) for _ in range(2)]
    ref = [float(z(x, y)) for _ in range(2)]
    assert cont == ref
    st = rex.executor.stats()["by_kind"]["zero_train_step"]
    assert st["dispatches"] == 7       # 3 + 2 + 2, one per window
    assert st["compiles"] == 2         # one program per build


# ---------------------------------------------------------------------------
# telemetry across mesh plans (the dp×tp carry fix)
# ---------------------------------------------------------------------------


def _tp_model():
    from apex_tpu.models import GptModel
    nn.manual_seed(11)
    return GptModel(vocab_size=64, hidden=32, layers=1, heads=4,
                    max_positions=8, dropout=0.0, attn_dropout=0.0,
                    tp_axis="tp")


def _lm_batch(b=8):
    host = np.random.default_rng(3)
    ids = jnp.asarray(host.integers(0, 64, (b, 8)))
    return ids, jnp.asarray(np.roll(np.asarray(ids), -1, axis=1))


def _lm_loss(logits, tgt):
    return F.cross_entropy(logits.reshape((-1, 64)), tgt.reshape((-1,)))


def test_telemetry_dp2_tp2_grad_norm_parity():
    """telemetry=True on a dp2×tp2 plan (which used to be refused): the
    drained loss_mean is the GLOBAL pmean — bitwise equal to the step's
    returned loss — and the grad norm (computed on the replicated
    post-exchange gradients, no extra collective) is bitwise reproducible
    across an independent rebuild."""
    ids, tgt = _lm_batch()
    plan = auto.Plan(dp=2, tp=2, tp_axis="tp", n_devices=4)

    def build(telemetry):
        m = _tp_model()
        opt = FusedAdam(list(m.parameters()), lr=1e-2)
        return make_train_step(m, opt, _lm_loss, half_dtype=None,
                               loss_scale=1.0, parallel=plan,
                               telemetry=telemetry, drain_every=1)

    step = build(telemetry=True)
    losses = [float(step(ids, tgt)) for _ in range(3)]
    recs = get_registry().events("train.telemetry")
    assert [r["step"] for r in recs] == [1, 2, 3]
    for r, l in zip(recs, losses):
        assert r["windows"] == 1
        # the accumulator pmeans the shard-local loss over the plan's
        # batch axes — same reduction as the returned loss: bitwise
        assert r["loss_mean"] == l
        assert np.isfinite(r["grad_norm"]) and r["grad_norm"] > 0
        assert r["loss_scale"] == 1.0 and r["overflow_count"] == 0

    # grad_norm is deterministic: an independent identical build drains
    # bitwise-equal norms
    get_registry().clear_events()
    step2 = build(telemetry=True)
    for _ in range(3):
        step2(ids, tgt)
    recs2 = get_registry().events("train.telemetry")
    assert [r["grad_norm"] for r in recs2] == \
        [r["grad_norm"] for r in recs]

    # and the carry is numerically inert: telemetry off, same trajectory
    step3 = build(telemetry=False)
    off_losses = [float(step3(ids, tgt)) for _ in range(3)]
    assert off_losses == losses
