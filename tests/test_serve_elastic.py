"""Elastic serving end to end: a membership-backed ServeFleet under
seeded chaos.  Pins the acceptance surface of the serve.elastic
subsystem — live session migration is BITWISE the uninterrupted
engine's continuation (fp32, int8, and mid-speculative-decode), shrink
sheds the batch tier first (re-queued, never dropped) while the
latency tier migrates, stale-epoch submissions are refused, kill
mid-snapshot leaves only rejectable debris, kill mid-migrate fells the
adopter but the session still completes, a coordinator felled
mid-migration is succeeded without losing the recovery queue, a
delayed-but-alive replica never triggers migration, and fleet-wide
FIFO admission order survives re-homing.  All on CPU with SimClock +
MemoryKV, like test_cluster.py."""
import os

import pytest

from apex_tpu import nn
from apex_tpu.inference import make_self_draft
from apex_tpu.models.gpt import GptModel
from apex_tpu.runtime import chaos
from apex_tpu.runtime.resilience import (CheckpointCorruptError,
                                         read_kv_handoff_meta,
                                         stream_kv_handoff)
from apex_tpu.serve import (Request, SLO_CLASSES, ServeEngine, ServeFleet,
                            StaleEpochError)
from apex_tpu.serve.pool import BlockPool, init_pool_buffer

pytestmark = pytest.mark.elastic_serve

PROMPTS = [[5, 9, 11, 3], [7, 2], [1, 2, 3, 4, 5, 6, 7, 8, 9], [12, 30, 4]]
MAX_NEW = 6
SLOS = ["latency", "batch", "latency", "batch"]


@pytest.fixture(autouse=True)
def _clean_chaos():
    yield
    chaos.uninstall()


@pytest.fixture(scope="module")
def model():
    nn.manual_seed(6)
    m = GptModel(vocab_size=73, hidden=32, layers=2, heads=4,
                 max_positions=96, dropout=0.0, attn_dropout=0.0)
    return m.eval()


def _reqs():
    return [Request(f"r{i}", tuple(p), MAX_NEW)
            for i, p in enumerate(PROMPTS)]


@pytest.fixture(scope="module")
def base(model):
    return _unified_out(model)


def _unified_out(model, *, cache_dtype=None, draft=None):
    eng = ServeEngine(model, num_blocks=64, block_size=8, max_batch=4,
                      prefill_chunk=4, cache_dtype=cache_dtype,
                      draft=draft)
    out = eng.run(_reqs())
    eng.block_pool.check_no_leaks()
    return out


def _fleet(model, tmp_path, **kw):
    kw.setdefault("n_engines", 2)
    kw.setdefault("num_blocks", 32)
    kw.setdefault("block_size", 8)
    kw.setdefault("max_batch", 4)
    kw.setdefault("prefill_chunk", 4)
    kw.setdefault("snapshot_every", 2)
    kw.setdefault("miss_threshold", 2)
    kw.setdefault("snapshot_dir", str(tmp_path / "snaps"))
    return ServeFleet(model, **kw)


def _kill_member(member_id):
    def act(ctx):
        if ctx.get("member") == member_id:
            raise chaos.ChaosKilled(f"chaos: felled {member_id}")
    return act


def _assert_no_leaks(fleet):
    for m in fleet.members.values():
        if not m.closed:
            m.engine.block_pool.check_no_leaks()


# -- fleet basics ----------------------------------------------------------

def test_fleet_parity_no_chaos(model, base, tmp_path):
    with _fleet(model, tmp_path) as fleet:
        fleet.join()
        out = fleet.run(_reqs(), slos=SLOS)
        m = fleet.metrics()
        assert out == base
        assert m["epoch"] == 1
        assert m["completed"] == len(PROMPTS)
        assert m["sessions_migrated"] == 0
        assert m["snapshot_bytes_peak_host"] > 0
        _assert_no_leaks(fleet)


def test_submit_validation(model, tmp_path):
    with _fleet(model, tmp_path) as fleet:
        with pytest.raises(RuntimeError, match="join"):
            fleet.submit(Request("x", (1, 2), 2))
        fleet.join()
        fleet.submit(Request("a", (1, 2), 2), slo="batch")
        with pytest.raises(ValueError, match="duplicate"):
            fleet.submit(Request("a", (1, 2), 2))
        with pytest.raises(ValueError, match="slo"):
            fleet.submit(Request("b", (1, 2), 2), slo="bulk")
        assert set(SLO_CLASSES) == {"latency", "batch"}


def test_stale_epoch_refused(model, tmp_path):
    with chaos.session(seed=0) as c:
        c.on("host.loss", _kill_member("serve0"), after=4, times=-1)
        with _fleet(model, tmp_path) as fleet:
            fleet.join()
            # epoch 1 is current: an epoch-addressed submit is accepted
            fleet.submit(Request("e1", (3, 4, 5), 3), epoch=1)
            while fleet.metrics()["epoch"] < 2:
                fleet.step()
            with pytest.raises(StaleEpochError):
                fleet.submit(Request("e2", (3, 4), 3), epoch=1)
            fleet.submit(Request("e3", (3, 4), 3), epoch=2)
            while fleet.has_work():
                fleet.step()
            assert set(fleet.results) == {"e1", "e3"}
            _assert_no_leaks(fleet)


# -- migration parity ------------------------------------------------------

@pytest.mark.parametrize("cache_dtype", [None, "int8"],
                         ids=["fp32", "int8"])
def test_migration_bitwise_parity(model, base, tmp_path, cache_dtype):
    if cache_dtype is not None:
        base = _unified_out(model, cache_dtype=cache_dtype)
    with chaos.session(seed=0) as c:
        c.on("host.loss", _kill_member("serve0"), after=10, times=-1)
        with _fleet(model, tmp_path, cache_dtype=cache_dtype) as fleet:
            fleet.join()
            out = fleet.run(_reqs(), slos=SLOS)
            m = fleet.metrics()
    assert out == base
    assert m["epoch"] >= 2
    assert m["sessions_migrated"] >= 1
    assert m["detect_ms"] >= 0.0 and m["migrate_ms"] > 0.0


def test_migration_mid_spec_decode(model, tmp_path):
    """A session migrated mid-speculative-decode restores its target
    KV verbatim, gets an EMPTY draft table, catches up through the
    survivor's prefill slot, and continues bitwise."""
    draft = make_self_draft(model)
    # spec decode emits up to k+1 tokens a tick — longer generations
    # keep sessions mid-flight across the detection window
    reqs = [Request(f"s{i}", tuple(p), 16)
            for i, p in enumerate(PROMPTS)]
    eng = ServeEngine(model, num_blocks=64, block_size=8, max_batch=4,
                      prefill_chunk=4, draft=draft)
    base = eng.run([Request(f"s{i}", tuple(p), 16)
                    for i, p in enumerate(PROMPTS)])
    eng.block_pool.check_no_leaks()
    with chaos.session(seed=0) as c:
        c.on("host.loss", _kill_member("serve0"), after=6, times=-1)
        with _fleet(model, tmp_path, num_blocks=48,
                    draft=draft) as fleet:
            fleet.join()
            out = fleet.run(reqs, slos=SLOS)
            m = fleet.metrics()
    assert out == base
    assert m["sessions_migrated"] >= 1


def test_shrink_sheds_batch_tier_first(model, base, tmp_path):
    """Capacity loss: batch tier is re-queued (NEVER dropped), latency
    tier migrates; everyone still completes, bitwise."""
    with chaos.session(seed=0) as c:
        # serve1 is where headroom routing homes the batch tier here
        c.on("host.loss", _kill_member("serve1"), after=8, times=-1)
        with _fleet(model, tmp_path, num_blocks=24) as fleet:
            fleet.join()
            for r, s in zip(_reqs(), SLOS):
                fleet.submit(r, slo=s)
            shed_rids = set()
            while fleet.has_work():
                fleet.step()
                shed_rids |= {rid for rid, mid
                              in fleet.assignments().items()
                              if mid is None and rid in fleet._queue}
            out = dict(fleet.results)
            m = fleet.metrics()
    assert out == base                       # zero requests dropped
    assert m["completed"] == len(PROMPTS)
    assert m["sessions_shed_requeued"] >= 1  # batch tier shed, re-queued
    # shedding only ever names the batch tier: no latency-tier session
    # was ever returned to the fleet queue as shed
    assert all(fleet.slo_of(rid) == "batch" for rid in shed_rids)


def test_stale_snapshot_falls_back_to_recompute(model, base, tmp_path):
    """snapshot_max_age_ticks=0 declares every snapshot stale: no
    migration happens, every lost latency session recomputes — still
    bitwise (the recompute path is the preemption path, already
    pinned)."""
    with chaos.session(seed=0) as c:
        c.on("host.loss", _kill_member("serve0"), after=10, times=-1)
        with _fleet(model, tmp_path,
                    snapshot_max_age_ticks=0) as fleet:
            fleet.join()
            out = fleet.run(_reqs(), slos=SLOS)
            m = fleet.metrics()
    assert out == base
    assert m["sessions_migrated"] == 0
    assert m["sessions_recomputed"] >= 1


# -- chaos durability ------------------------------------------------------

def test_kill_mid_snapshot_debris_rejected(model, base, tmp_path):
    """A replica killed half-way through a session snapshot leaves a
    manifest-less shard directory.  Recovery finds it, rejects it
    (CheckpointCorruptError → debris counter), and completes the
    session through an older snapshot or recompute — bitwise either
    way.  Debris is never adopted."""
    with chaos.session(seed=0) as c:
        # the 1st block file of the 1st snapshot round dies mid-stream
        c.on("serve.kv_handoff", "kill", at=0)
        with _fleet(model, tmp_path) as fleet:
            fleet.join()
            out = fleet.run(_reqs(), slos=SLOS)
            m = fleet.metrics()
            assert any(p == "serve.kv_handoff" for p, _, _ in c.log)
    assert out == base
    assert m["epoch"] >= 2
    assert m["debris_rejected"] >= 1
    assert m["sessions_recomputed"] + m["sessions_migrated"] >= 1


def test_kill_mid_migrate_adopter_fells(model, base, tmp_path):
    """The ADOPTING replica dies mid-restore: the snapshot stays on
    shared storage, recovery resumes on whoever survives, and the
    session completes bitwise."""
    with chaos.session(seed=0) as c:
        c.on("host.loss", _kill_member("serve0"), after=15, times=-1)
        c.on("serve.migrate", "kill", at=0)
        with _fleet(model, tmp_path, n_engines=3,
                    num_blocks=24) as fleet:
            fleet.join()
            out = fleet.run(_reqs(), slos=SLOS)
            m = fleet.metrics()
            felled = [mid for mid, mm in fleet.members.items()
                      if mm.closed]
    assert out == base
    assert len(felled) >= 2          # the victim AND the adopter died
    assert m["completed"] == len(PROMPTS)


def test_migrate_fail_abandons_cleanly(model, base, tmp_path):
    """An injected recoverable fault during restore abandons the
    migration cleanly — the session falls back to recompute and still
    completes bitwise."""
    with chaos.session(seed=0) as c:
        c.on("host.loss", _kill_member("serve0"), after=10, times=-1)
        c.on("serve.migrate", "fail", at=0)
        with _fleet(model, tmp_path) as fleet:
            fleet.join()
            out = fleet.run(_reqs(), slos=SLOS)
            m = fleet.metrics()
    assert out == base
    assert m["sessions_recomputed"] >= 1


def test_coordinator_loss_mid_migration(model, base, tmp_path):
    """The coordinator dies while the recovery queue is still
    draining (migrate_per_tick=1 spreads the drain over ticks).  The
    successor keeps epochs monotonic and the front-end's recovery
    queue survives the succession: every migration completes (or
    cleanly falls back) — manifest-commits-last means no half-adopted
    session can exist."""
    with chaos.session(seed=0) as c:
        c.on("host.loss", _kill_member("serve0"), after=10, times=-1)
        # scans 0.. are join + steps; fell the coordinator a few scans
        # after the death is detectable, i.e. mid-recovery
        c.on("coordinator.loss", "kill", at=8)
        with _fleet(model, tmp_path, migrate_per_tick=1) as fleet:
            fleet.join()
            out = fleet.run(_reqs(), slos=SLOS)
            m = fleet.metrics()
            assert any(p == "coordinator.loss" for p, _, _ in c.log)
    assert out == base
    assert m["epoch"] >= 2           # monotonic across the succession
    assert m["completed"] == len(PROMPTS)


def test_heartbeat_delay_never_migrates(model, base, tmp_path):
    """A delayed-but-alive replica (skew under miss_threshold
    consecutive misses) must NOT produce a new epoch or trigger
    migration — the false-positive guard holds through the serve
    fleet."""
    with chaos.session(seed=0) as c:
        c.on("heartbeat.delay",
             lambda ctx: 0.3 if ctx.get("member") == "serve0" else None,
             at=4)
        with _fleet(model, tmp_path) as fleet:
            fleet.join()
            out = fleet.run(_reqs(), slos=SLOS)
            m = fleet.metrics()
            assert any(p == "heartbeat.delay" for p, _, _ in c.log)
    assert out == base
    assert m["epoch"] == 1
    assert m["sessions_migrated"] == 0
    assert m["sessions_recomputed"] == 0


# -- FIFO fairness (property-style) ----------------------------------------

@pytest.mark.parametrize("seed", [0, 1])
def test_fifo_order_preserved_across_rehoming(model, tmp_path, seed):
    """Fleet-wide FIFO: at every tick, every engine's admission queue
    is sorted by fleet submission order — a re-homed session with an
    older seat admits AHEAD of a survivor's younger native entries.
    Randomized SLO mix and kill timing per seed."""
    import random
    rng = random.Random(seed)
    n = 6
    reqs = [Request(f"p{i}", tuple(rng.randrange(1, 70)
                                   for _ in range(rng.randrange(2, 8))),
                    4) for i in range(n)]
    slos = [rng.choice(SLO_CLASSES) for _ in range(n)]
    kill_after = rng.randrange(6, 14)
    with chaos.session(seed=seed) as c:
        c.on("host.loss", _kill_member("serve1"), after=kill_after,
             times=-1)
        with _fleet(model, tmp_path, num_blocks=20,
                    max_batch=2) as fleet:
            fleet.join()
            for r, s in zip(reqs, slos):
                fleet.submit(r, slo=s)
            seq_of = {rid: rec.seq for rid, rec in fleet._recs.items()}
            ticks = 0
            while fleet.has_work():
                fleet.step()
                ticks += 1
                assert ticks < 500, "fleet failed to converge"
                for m in fleet.members.values():
                    if m.closed:
                        continue
                    seqs = [seq_of[s.rid]
                            for s in m.engine.scheduler.queue]
                    assert seqs == sorted(seqs), (
                        f"engine queue out of fleet FIFO order: {seqs}")
            assert set(fleet.results) == {r.rid for r in reqs}
            _assert_no_leaks(fleet)


# -- snapshot meta plumbing ------------------------------------------------

def test_kv_handoff_extra_meta_roundtrip(tmp_path):
    """extra_meta rides in the manifest (commits LAST, so committed
    meta implies committed KV) and read_kv_handoff_meta validates
    without touching block files; a manifest-less dir is debris."""
    pool = init_pool_buffer(layers=1, heads=2, head_dim=4,
                            num_blocks=8, block_size=4, dtype=None)
    bp = BlockPool(8, 4)
    table = bp.alloc(2)
    d = str(tmp_path / "snap0")
    meta = {"rid": "r0", "out": [1, 2, 3], "pending_tok": 3,
            "position": 7, "slo": "latency", "tick": 4, "epoch": 2}
    manifest, _peak = stream_kv_handoff(d, pool, table,
                                        source="snapshot:r0",
                                        extra_meta=meta)
    assert manifest["meta"] == meta
    back = read_kv_handoff_meta(d)
    assert back["meta"] == meta and back["n_blocks"] == 2
    os.remove(os.path.join(d, "KV_MANIFEST.pkl"))
    with pytest.raises(CheckpointCorruptError, match="manifest"):
        read_kv_handoff_meta(d)
    bp.free(table)
    bp.check_no_leaks()
