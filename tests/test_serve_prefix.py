"""Prefix cache (content-addressed KV block reuse): the pool's
refcount/cached-tier/hash-index invariants, and the acceptance pins —
warm-cache continuations BITWISE identical to the cold-prefill engine
for fp32 and int8 pools, with and without speculative decoding, under
forced preemption-recompute, across a weight-epoch invalidation, and
through a migration that re-links the hash chain into the survivor's
index."""
import os

import numpy as np
import pytest

import jax.numpy as jnp

from apex_tpu import nn
from apex_tpu.inference import make_self_draft
from apex_tpu.inference.session import PagedSession
from apex_tpu.models.gpt import GptModel
from apex_tpu.observe import registry as obs
from apex_tpu.serve import Request, ServeEngine
from apex_tpu.serve.disagg import DisaggregatedEngine
from apex_tpu.serve.pool import BlockPool, chain_key, chain_keys

pytestmark = pytest.mark.serve

#: 16 tokens = 2 full blocks at block_size 8 — block-aligned, so a
#: repeat submission is a FULL-chain hit and exercises the CoW fork
SHARED = list(range(1, 17))


@pytest.fixture(scope="module")
def model():
    nn.manual_seed(6)
    m = GptModel(vocab_size=73, hidden=32, layers=2, heads=4,
                 max_positions=96, dropout=0.0, attn_dropout=0.0)
    m.eval()
    return m


def _trace():
    """Three requests over one shared prefix: two suffix extensions
    (partial hits) and one exact block-aligned repeat (full hit →
    copy-on-write fork).  Staggered arrivals so each admission sees the
    previous request's committed blocks."""
    return ([Request("r0", SHARED + [20], 6),
             Request("r1", SHARED + [21, 22], 6),
             Request("r2", SHARED, 6)],
            [0, 6, 12])


def _run(model, prefix_cache, **kw):
    eng = ServeEngine(model, num_blocks=64, block_size=8, max_batch=4,
                      prefill_chunk=4, prefix_cache=prefix_cache, **kw)
    reqs, arrivals = _trace()
    out = eng.run(reqs, arrivals=arrivals)
    m = eng.metrics()["prefix_cache"]
    eng.close()
    return out, m


# ---------------------------------------------------------------------------
# the rolling chain key
# ---------------------------------------------------------------------------


def test_chain_keys_roll_over_full_blocks_only():
    assert chain_keys([1, 2, 3], 4, "t") == []          # no full block
    k1 = chain_keys([1, 2, 3, 4], 4, "t")
    k2 = chain_keys([1, 2, 3, 4, 5, 6, 7, 8, 9], 4, "t")
    assert len(k1) == 1 and len(k2) == 2                # partial tail out
    assert k2[0] == k1[0]                               # rolling prefix
    # the parent key, the tokens, and the tag each change the key
    assert chain_key("", [1, 2, 3, 4], "t") == k1[0]
    assert chain_key(k1[0], [5, 6, 7, 8], "t") == k2[1]
    assert chain_keys([1, 2, 3, 5], 4, "t") != k1
    assert chain_keys([1, 2, 3, 4], 4, "other") != k1


# ---------------------------------------------------------------------------
# pool: refcounts, cached tier, LRU eviction, leak accounting
# ---------------------------------------------------------------------------


def test_pool_shared_refcounts_and_double_free_raises():
    pool = BlockPool(num_blocks=8, block_size=4)
    ids = pool.alloc(2)
    keys = chain_keys([1, 2, 3, 4, 5, 6, 7, 8], 4, "t")
    assert pool.commit(ids[0], keys[0])
    assert pool.commit(ids[1], keys[1])
    # a second session adopts the whole chain: refcount 2 on both
    shared = pool.acquire_prefix(keys)
    assert shared == ids
    assert pool.refcount(ids[0]) == 2
    assert pool.in_use == 2               # held blocks, not references
    pool.free(shared)                     # second session done
    assert pool.refcount(ids[0]) == 1
    pool.free(ids)                        # first session done -> cached
    assert pool.in_use == 0 and pool.cached_count == 2
    # sharing never grants extra frees: the books are at zero
    with pytest.raises(ValueError):
        pool.free([ids[0]])
    pool.check_no_leaks()                 # cached blocks are not leaks


def test_pool_partial_chain_walk_stops_at_first_miss():
    pool = BlockPool(num_blocks=8, block_size=4)
    ids = pool.alloc(2)
    keys = chain_keys(list(range(1, 13)), 4, "t")       # 3 full blocks
    pool.commit(ids[0], keys[0])
    pool.commit(ids[1], keys[1])
    assert pool.acquire_prefix(keys) == ids             # 2 of 3 matched
    pool.free(ids)
    # a diverging chain shares only the first block
    other = chain_keys([1, 2, 3, 4, 9, 9, 9, 9], 4, "t")
    assert pool.acquire_prefix(other) == [ids[0]]
    pool.free([ids[0]])
    pool.free(ids)
    pool.check_no_leaks()


def test_pool_commit_first_writer_wins():
    pool = BlockPool(num_blocks=8, block_size=4)
    a, b = pool.alloc(2)
    assert pool.commit(a, "k")
    assert not pool.commit(b, "k")        # key taken
    assert not pool.commit(a, "k2")       # block already hashed
    assert not pool.commit(99, "k3")      # not held
    pool.free([a, b])
    assert pool.cached_count == 1         # only the hashed block retires
    pool.check_no_leaks()


def test_pool_lru_eviction_under_allocation_pressure():
    pool = BlockPool(num_blocks=6, block_size=4)        # 5 allocatable
    ids = pool.alloc(5)
    for i, b in enumerate(ids):
        pool.commit(b, f"k{i}")
    pool.free(ids)                        # retire in order: k0 oldest
    assert pool.cached_count == 5 and pool.free_count == 5
    got = pool.alloc(2)                   # evicts k0, k1 (LRU first)
    assert pool.cache_evictions == 2
    assert pool.acquire_prefix(["k0"]) == []            # entry gone
    assert pool.acquire_prefix(["k2"]) == [ids[2]]      # survivor lives
    pool.free([ids[2]])
    pool.free(got)
    pool.check_no_leaks()
    assert pool.alloc(6) is None          # capacity still all-or-nothing


def test_pool_flush_cache_reclaims_and_invalidates():
    pool = BlockPool(num_blocks=6, block_size=4)
    ids = pool.alloc(2)
    pool.commit(ids[0], "k0")
    pool.free(ids)
    assert pool.cached_count == 1
    assert pool.flush_cache() == 1
    assert pool.cached_count == 0 and pool.free_exact == 5
    assert pool.acquire_prefix(["k0"]) == []
    pool.check_no_leaks()


# ---------------------------------------------------------------------------
# acceptance: warm-cache continuations bitwise-equal to cold prefill
# ---------------------------------------------------------------------------


def test_warm_cache_bitwise_fp32(model):
    warm, mw = _run(model, True)
    cold, mc = _run(model, False)
    assert warm == cold                   # bitwise greedy parity
    assert mw["prefill_tokens_saved"] == 31   # 16 (partial) + 15 (full)
    assert mw["hit_rate"] > 0.5
    assert mw["cow_forks"] >= 1           # the full-chain hit forked
    assert mc == {"hit_rate": 0.0, "prefill_tokens_saved": 0,
                  "cached_blocks": 0, "cow_forks": 0,
                  "cache_evictions": 0}


def test_warm_cache_bitwise_int8(model):
    warm, mw = _run(model, True, cache_dtype="int8")
    cold, _ = _run(model, False, cache_dtype="int8")
    assert warm == cold
    assert mw["prefill_tokens_saved"] == 31 and mw["cow_forks"] >= 1


def test_warm_cache_bitwise_speculative(model):
    draft = make_self_draft(model)
    warm, mw = _run(model, True, draft=draft, spec_k=3)
    cold, _ = _run(model, False, draft=draft, spec_k=3)
    assert warm == cold
    assert mw["prefill_tokens_saved"] == 31 and mw["cow_forks"] >= 1


def test_preemption_recompute_with_cache_bitwise(model):
    """A pool too small for the live set forces preemption; recompute
    re-admission walks the chain and typically re-adopts its own
    just-retired blocks from the cached tier — either way the
    continuation is bitwise the no-preemption engine's."""
    obs.get_registry().reset()
    reqs = [Request(f"p{i}", [3 + i, 5, 7, 9], 8) for i in range(6)]
    small = ServeEngine(model, num_blocks=10, block_size=4, max_batch=4,
                        prefill_chunk=4)
    out = small.run(reqs)
    assert obs.counter("serve.preemptions").value > 0
    small.close()
    big = ServeEngine(model, num_blocks=64, block_size=4, max_batch=4,
                      prefill_chunk=4, prefix_cache=False)
    assert out == big.run(reqs)
    big.close()


def test_cache_eviction_pressure_no_leaks(model):
    """Distinct prompts churning through a small pool force cached-tier
    evictions; the drained pool still balances to zero leaks."""
    eng = ServeEngine(model, num_blocks=12, block_size=4, max_batch=2,
                      prefill_chunk=8)
    reqs = [Request(f"e{i}", [10 + i, 20 + i, 30 + i, 40 + i, 5 + i], 4)
            for i in range(12)]
    out = eng.run(reqs)
    assert len(out) == 12
    assert eng.metrics()["prefix_cache"]["cache_evictions"] > 0
    eng.close()                           # runs check_no_leaks


def test_epoch_invalidation_on_publish_weights(model):
    """publish_weights(target) re-tags the chain keys and flushes the
    cached tier: a post-swap duplicate of a pre-swap prompt must NOT
    hit (the entries describe KV computed under the old weights)."""
    eng = ServeEngine(model, num_blocks=64, block_size=8, max_batch=2,
                      prefill_chunk=8)
    eng.run([Request("a", SHARED + [20], 4)])
    assert eng.block_pool.cached_count > 0
    saved0 = eng.metrics()["prefix_cache"]["prefill_tokens_saved"]
    tag0 = eng.scheduler.cache_tag
    eng.publish_weights([p.data for p in model.parameters()])
    assert eng.scheduler.cache_tag != tag0
    assert eng.block_pool.cached_count == 0             # flushed
    eng.run([Request("b", SHARED + [20], 4)])
    m = eng.metrics()["prefix_cache"]
    assert m["prefill_tokens_saved"] == saved0          # no stale hit
    # the re-prefilled blocks re-commit under the NEW tag
    assert eng.block_pool.cached_count > 0
    eng.close()


def test_decode_stays_recompile_free_with_cache(model):
    from apex_tpu.runtime import step_cache as sc
    sc.reset_stats()
    sc.clear()
    eng = ServeEngine(model, num_blocks=64, block_size=8, max_batch=4,
                      prefill_chunk=4)
    reqs, arrivals = _trace()
    eng.run(reqs, arrivals=arrivals)
    eng.run([Request(f"x{i}", SHARED + [40 + i], 5) for i in range(4)])
    stats = sc.kind_stats("decode_step")
    # same bucket bound as the cache-off engine pins: occupancy
    # buckets {1,2,4} x table buckets — prefix hits change which rows
    # are warm, never the program shapes
    assert stats["compiles"] <= 6
    eng.close()


# ---------------------------------------------------------------------------
# PagedSession: a conversation replay is a natural prefix hit
# ---------------------------------------------------------------------------


def test_paged_session_replay_and_extension_hit(model):
    ref_eng = ServeEngine(model, num_blocks=64, block_size=8,
                          prefix_cache=False)
    with PagedSession(ref_eng) as rs:
        rs.append(SHARED)
        ref = np.asarray(rs.generate(6)).tolist()
    eng = ServeEngine(model, num_blocks=64, block_size=8)
    with PagedSession(eng) as s1:
        s1.append(SHARED)
        assert np.asarray(s1.generate(6)).tolist() == ref
    assert eng.block_pool.cached_count >= 2   # committed blocks retired
    with PagedSession(eng) as s2:             # exact replay: full hit
        s2.append(SHARED)
        assert s2.position == len(SHARED)     # one re-ingested token
        assert np.asarray(s2.generate(6)).tolist() == ref
    assert eng._cow_forks >= 1
    with PagedSession(eng) as s3:             # extension: partial hit
        s3.append(SHARED + [20, 21])
        got = np.asarray(s3.generate(4)).tolist()
    with PagedSession(ref_eng) as rs2:
        rs2.append(SHARED + [20, 21])
        assert got == np.asarray(rs2.generate(4)).tolist()
    eng.block_pool.check_no_leaks()
    ref_eng.block_pool.check_no_leaks()


# ---------------------------------------------------------------------------
# disaggregation + migration: the chain rides the manifest
# ---------------------------------------------------------------------------


def test_disagg_shared_prefix_bitwise_and_hits(model):
    reqs, arrivals = _trace()
    dis = DisaggregatedEngine(model, num_blocks=64, block_size=8,
                              max_batch=4, prefill_chunk=4)
    out = dis.run(reqs, arrivals=arrivals)
    cold, _ = _run(model, False)
    assert out == cold
    # the PREFILL engine is where admission walks the chain
    pm = dis.prefill.metrics()["prefix_cache"]
    assert pm["prefill_tokens_saved"] == 31
    # handed-off chains re-linked into the decode engine's index and
    # retired to its cached tier as sessions finished
    assert dis.decode.block_pool.cached_count > 0
    dis.prefill.close()
    dis.decode.close()


def test_migration_relinks_chain_into_survivor(model, tmp_path):
    """Stream a mid-decode session off engine A (manifest carries its
    hash chain + weight epoch) and adopt it on engine B: the chain
    re-links into B's index, so B's next same-prefix admission hits —
    and the continuation is bitwise the uninterrupted engine's."""
    from apex_tpu.runtime.resilience import stream_kv_handoff
    ref = ServeEngine(model, num_blocks=64, block_size=8,
                      prefix_cache=False)
    full = ref.run([Request("m", SHARED + [20], 8)])["m"]
    ref.close()

    a = ServeEngine(model, num_blocks=64, block_size=8, prefill_chunk=8)
    a.submit(Request("m", SHARED + [20], 8))
    for _ in range(6):
        a.step()
    (s,) = a.scheduler.sessions
    assert s.committed_blocks >= 2 and 0 < len(s.out) < 8
    d = os.path.join(str(tmp_path), "mig")
    stream_kv_handoff(d, a.pool, s.table, source="test:mig")
    chain, epoch, out, pend, pos = (list(s.hash_chain), s.weight_epoch,
                                    list(s.out), s.pending_tok,
                                    s.position)
    a.close()

    b = ServeEngine(model, num_blocks=64, block_size=8, prefill_chunk=8)
    sess = b.ingest_handoff(Request("m", SHARED + [20], 8), out=out,
                            pending_tok=pend, position=pos,
                            handoff_dir=d, n_blocks=len(s.table) or None,
                            hash_chain=chain, weight_epoch=epoch)
    assert sess is not None and sess.cacheable
    assert sess.committed_blocks == len(chain)
    while b.scheduler.has_work():
        b.step()
    assert b.results["m"] == full         # bitwise continuation
    # the re-linked chain is live in B's index: a same-prefix request
    # admits with its prefix already cached
    b.run([Request("m2", SHARED + [20], 4)])
    assert b.metrics()["prefix_cache"]["prefill_tokens_saved"] > 0
    b.close()


def test_migration_epoch_mismatch_never_cached(model, tmp_path):
    """An adopted session whose chain was built under a DIFFERENT
    target epoch keeps serving (mixed-weight semantics) but its blocks
    must never enter the survivor's hash index."""
    from apex_tpu.runtime.resilience import stream_kv_handoff
    a = ServeEngine(model, num_blocks=64, block_size=8, prefill_chunk=8)
    a.submit(Request("m", SHARED + [20], 8))
    for _ in range(6):
        a.step()
    (s,) = a.scheduler.sessions
    d = os.path.join(str(tmp_path), "mig2")
    stream_kv_handoff(d, a.pool, s.table, source="test:mig2")
    chain, out, pend, pos, nb = (list(s.hash_chain), list(s.out),
                                 s.pending_tok, s.position, len(s.table))
    a.close()
    b = ServeEngine(model, num_blocks=64, block_size=8, prefill_chunk=8)
    sess = b.ingest_handoff(Request("m", SHARED + [20], 8), out=out,
                            pending_tok=pend, position=pos,
                            handoff_dir=d, n_blocks=nb,
                            hash_chain=chain, weight_epoch=7)   # stale
    assert sess is not None and not sess.cacheable
    while b.scheduler.has_work():
        b.step()
    assert b.block_pool.cached_count == 0     # nothing was published
    b.block_pool.check_no_leaks()
    b.close()
