"""Tensor-parallel cached decode (models/llama.py, models/gpt.py with
``tp_axis`` + ``generate(mesh=...)``): the whole decode program runs
inside shard_map with replicated weights/tokens/key, head-sharded KV
caches, and row-parallel psums — the emitted tokens must be
BIT-IDENTICAL to the single-shard decode of the same weights (greedy
argmax over replicated logits).

Reference analogue: none (the reference is training-side only,
SURVEY.md §2); oracle methodology mirrors tests/test_tp_models.py
(sharded vs unsharded build must agree)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

import apex_tpu.nn as nn
from apex_tpu.models import GptModel
from apex_tpu.models.gpt import generate
from apex_tpu.models.llama import LlamaModel
from apex_tpu.nn.modules import Ctx

V = 97


def _mesh(n):
    return Mesh(np.array(jax.devices())[:n].reshape(n), ("tp",))


def _llama(**kw):
    nn.manual_seed(7)
    return LlamaModel(vocab_size=V, hidden=32, layers=2, heads=4,
                      kv_heads=2, max_positions=64, **kw)


def _gpt(**kw):
    nn.manual_seed(7)
    return GptModel(vocab_size=V, hidden=32, layers=2, heads=4,
                    max_positions=64, dropout=0.0, attn_dropout=0.0, **kw)


def _sync_params(src, dst):
    """Copy src's parameter values into dst (same architecture, the tp
    flag differs only in how forward slices)."""
    for ps, pd in zip(src.parameters(), dst.parameters()):
        pd.data = ps.data


def test_llama_tp_greedy_decode_matches_single_shard(rng):
    m_ref = _llama()
    m_ref.eval()
    m_tp = _llama(tp_axis="tp")
    m_tp.eval()
    _sync_params(m_ref, m_tp)

    prompt = jnp.asarray(rng.integers(0, V, (2, 5)))
    want = np.asarray(generate(m_ref, prompt, 10))
    got = np.asarray(generate(m_tp, prompt, 10, mesh=_mesh(2)))
    np.testing.assert_array_equal(got, want)


def test_llama_tp_gqa_full_ratio(rng):
    """tp size == kv_heads: each device holds exactly ONE kv head (the
    minimal-cache corner) and heads/kv ratio stays 2 locally."""
    m_ref = _llama()
    m_ref.eval()
    m_tp = _llama(tp_axis="tp")
    m_tp.eval()
    _sync_params(m_ref, m_tp)
    prompt = jnp.asarray(rng.integers(0, V, (1, 4)))
    # kv_heads=2 -> n=2 leaves 1 kv head, 2 q heads per device
    want = np.asarray(generate(m_ref, prompt, 8))
    got = np.asarray(generate(m_tp, prompt, 8, mesh=_mesh(2)))
    np.testing.assert_array_equal(got, want)


def test_gpt_tp_greedy_decode_matches_single_shard(rng):
    m_ref = _gpt()
    m_ref.eval()
    m_tp = _gpt(tp_axis="tp")
    m_tp.eval()
    _sync_params(m_ref, m_tp)

    prompt = jnp.asarray(rng.integers(0, V, (2, 5)))
    want = np.asarray(generate(m_ref, prompt, 10))
    got = np.asarray(generate(m_tp, prompt, 10, mesh=_mesh(4)))
    np.testing.assert_array_equal(got, want)


def test_tp_decode_chunk_matches_single_shard(rng):
    """The speculative-verification primitive under TP: chunk logits
    against a prefilled cache agree with the single-shard chunk (close
    in float; the psum reorders reductions)."""
    from jax.sharding import PartitionSpec as P

    m_ref = _llama()
    m_ref.eval()
    m_tp = _llama(tp_axis="tp")
    m_tp.eval()
    _sync_params(m_ref, m_tp)
    params = list(m_tp.parameters())
    vals = [p.data for p in params]
    prompt = jnp.asarray(rng.integers(0, V, (1, 6)))
    chunk = jnp.asarray(rng.integers(0, V, (1, 3)))

    ctx = Ctx(training=False)
    caches = m_ref.init_caches(1, 16)
    _, caches = m_ref.prefill(ctx, prompt, caches)
    want, _ = m_ref.decode_chunk(ctx, chunk, caches, jnp.int32(6))

    def run(vals, prompt, chunk):
        ctx = Ctx(env={id(p): v for p, v in zip(params, vals)},
                  training=False)
        caches = m_tp.init_caches(1, 16)
        _, caches = m_tp.prefill(ctx, prompt, caches)
        out, _ = m_tp.decode_chunk(ctx, chunk, caches, jnp.int32(6))
        return out

    got = jax.jit(jax.shard_map(
        run, mesh=_mesh(2), in_specs=(P(), P(), P()), out_specs=P(),
        check_vma=False))(vals, prompt, chunk)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_tp_caches_are_head_sharded(rng):
    """The point of TP decode: per-device cache HBM shrinks by the mesh
    factor (KVH/n-wide caches)."""
    from jax.sharding import PartitionSpec as P

    m_tp = _llama(tp_axis="tp")

    def shapes(_):
        caches = m_tp.init_caches(2, 16)
        return jnp.zeros((caches[0][0].shape[1],))

    out = jax.jit(jax.shard_map(
        lambda x: shapes(x), mesh=_mesh(2), in_specs=(P(),),
        out_specs=P(), check_vma=False))(jnp.zeros((2,)))
    # kv_heads=2 over 2 devices -> each device caches 1 local kv head
    assert out.shape == (1,)


def test_tp_generate_requires_mesh(rng):
    m_tp = _llama(tp_axis="tp")
    m_tp.eval()
    prompt = jnp.zeros((1, 4), jnp.int32)
    with pytest.raises(ValueError, match="mesh"):
        generate(m_tp, prompt, 4)
    m = _llama()
    m.eval()
    with pytest.raises(ValueError, match="no tp_axis"):
        generate(m, prompt, 4, mesh=_mesh(2))
    # a mesh that does not carry the model's axis fails at the argument
    # check, not deep inside shard_map tracing
    wrong = Mesh(np.array(jax.devices())[:2].reshape(2), ("x",))
    with pytest.raises(ValueError, match="do not include"):
        generate(m_tp, prompt, 4, mesh=wrong)


def test_tp_decode_loud_guards(rng):
    """The paths that cannot run TP as called refuse with clear
    messages instead of unbound-axis trace errors."""
    from apex_tpu.inference import speculative_generate

    m_tp = _llama(tp_axis="tp")
    m_tp.eval()
    # init_caches outside shard_map: clear error, not NameError
    with pytest.raises(ValueError, match="inside shard_map"):
        m_tp.init_caches(1, 16)
    g_tp = _gpt(tp_axis="tp")
    g_tp.eval()
    with pytest.raises(ValueError, match="inside shard_map"):
        g_tp.init_caches(1, 16)
    # speculative decoding with a tp model needs the mesh
    draft = _llama()
    draft.eval()
    prompt = jnp.zeros((1, 4), jnp.int32)
    with pytest.raises(ValueError, match="mesh"):
        speculative_generate(m_tp, draft, prompt, 4)
    with pytest.raises(ValueError, match="mesh"):
        speculative_generate(draft, m_tp, prompt, 4)
    with pytest.raises(ValueError, match="needs no mesh"):
        speculative_generate(draft, _llama(), prompt, 4, mesh=_mesh(2))


def test_tp_speculative_greedy_exact(rng):
    """The greedy exactness guarantee survives TP: a TP target with a
    replicated draft (the serving shape) emits the same tokens as the
    single-shard target's own generate."""
    from apex_tpu.inference import speculative_generate

    m_ref = _llama()
    m_ref.eval()
    m_tp = _llama(tp_axis="tp")
    m_tp.eval()
    _sync_params(m_ref, m_tp)
    nn.manual_seed(99)
    draft = LlamaModel(vocab_size=V, hidden=16, layers=1, heads=2,
                       max_positions=64)
    draft.eval()

    prompt = jnp.asarray(rng.integers(0, V, (1, 5)))
    want = np.asarray(generate(m_ref, prompt, 12))
    got = np.asarray(speculative_generate(m_tp, draft, prompt, 12, k=3,
                                          mesh=_mesh(2)))
    np.testing.assert_array_equal(got, want)


def test_tp_speculative_sampled_runs(rng):
    """Sampled (Leviathan) speculative decode under TP compiles and
    emits in-range tokens (distribution exactness is established
    single-shard in tests/test_speculative.py; TP logits are the same
    values psum-assembled)."""
    from apex_tpu.inference import speculative_generate

    m_tp = _llama(tp_axis="tp")
    m_tp.eval()
    nn.manual_seed(98)
    draft = LlamaModel(vocab_size=V, hidden=16, layers=1, heads=2,
                       max_positions=64)
    draft.eval()
    prompt = jnp.asarray(rng.integers(0, V, (1, 4)))
    out = np.asarray(speculative_generate(
        m_tp, draft, prompt, 8, k=2, temperature=0.7,
        key=jax.random.PRNGKey(3), mesh=_mesh(2)))
    assert out.shape == (1, 12)
    assert ((out >= 0) & (out < V)).all()


def test_tp_decode_int8_quantized(rng):
    """TP decode composes with weight-only int8: ctx.value dequantizes
    the full table, the trace-time slice takes the device's block."""
    from apex_tpu.inference import quantize_int8

    m_ref = _llama()
    m_ref.eval()
    m_tp = _llama(tp_axis="tp")
    m_tp.eval()
    _sync_params(m_ref, m_tp)
    quantize_int8(m_ref, min_size=1)
    # quantize the tp copy from the SAME full-precision values
    m_src = _llama()
    _sync_params(m_src, m_tp)
    quantize_int8(m_tp, min_size=1)

    prompt = jnp.asarray(rng.integers(0, V, (1, 5)))
    want = np.asarray(generate(m_ref, prompt, 8))
    got = np.asarray(generate(m_tp, prompt, 8, mesh=_mesh(2)))
    np.testing.assert_array_equal(got, want)


def test_tp_decode_sliding_window(rng):
    """Mistral banded decode under TP: the band mask is position math,
    orthogonal to the head sharding."""
    m_ref = _llama(sliding_window=8)
    m_ref.eval()
    m_tp = _llama(sliding_window=8, tp_axis="tp")
    m_tp.eval()
    _sync_params(m_ref, m_tp)
    prompt = jnp.asarray(rng.integers(0, V, (1, 6)))
    want = np.asarray(generate(m_ref, prompt, 12))
    got = np.asarray(generate(m_tp, prompt, 12, mesh=_mesh(2)))
    np.testing.assert_array_equal(got, want)


def test_seq2seq_tp_decode_matches_single_shard(rng):
    """The encoder-decoder family decodes under TP too: its layers
    already shard in forward, so the shard_map-wrapped generate must
    reproduce the single-shard tokens."""
    from apex_tpu.models.seq2seq import (TransformerSeq2Seq,
                                         seq2seq_generate)

    def build(**kw):
        nn.manual_seed(17)
        return TransformerSeq2Seq(vocab_size=V, hidden=32, enc_layers=1,
                                  dec_layers=1, heads=4,
                                  max_positions=32, dropout=0.0,
                                  attn_dropout=0.0, **kw)

    m_ref = build()
    m_ref.eval()
    m_tp = build(tp_axis="tp")
    m_tp.eval()
    _sync_params(m_ref, m_tp)
    src = jnp.asarray(rng.integers(1, V, (2, 6)))
    want = np.asarray(seq2seq_generate(m_ref, src, 8))
    got = np.asarray(seq2seq_generate(m_tp, src, 8, mesh=_mesh(2)))
    np.testing.assert_array_equal(got, want)
    # guards
    with pytest.raises(ValueError, match="mesh"):
        seq2seq_generate(m_tp, src, 4)
    with pytest.raises(ValueError, match="no tp_axis"):
        seq2seq_generate(m_ref, src, 4, mesh=_mesh(2))


def test_seq2seq_generate_cache_misses_on_param_swap(rng):
    """Swapping the model's Parameter set (the LoRA apply/merge shape)
    must miss the compiled-run cache — a stale hit would zip the old
    closure params against new values and decode from wrong weights."""
    from apex_tpu.models.seq2seq import (TransformerSeq2Seq,
                                         seq2seq_generate)
    from apex_tpu.nn.parameter import Parameter

    nn.manual_seed(21)
    m = TransformerSeq2Seq(vocab_size=V, hidden=32, enc_layers=1,
                           dec_layers=1, heads=4, max_positions=32,
                           dropout=0.0, attn_dropout=0.0)
    m.eval()
    src = jnp.asarray(rng.integers(1, V, (1, 5)))
    out1 = np.asarray(seq2seq_generate(m, src, 6))
    # replace the embedding Parameter OBJECT with shuffled rows: same
    # shapes, different identity and values
    perm = np.asarray(rng.permutation(V))
    m.tok_emb.weight = Parameter(
        jnp.asarray(np.asarray(m.tok_emb.weight.data)[perm]))
    out2 = np.asarray(seq2seq_generate(m, src, 6))
    assert not np.array_equal(out1, out2), \
        "stale cache entry decoded with the old parameter set"


def test_gpt_tp_vocab_decode_matches_single_shard(rng):
    """tp_vocab shards the tied table for TRAINING logits; decode reads
    the full replicated table (sampling needs all-vocab argmax), so a
    vocab-parallel model must still decode to the single-shard tokens."""
    m_ref = _gpt()
    m_ref.eval()
    m_tp = _gpt(tp_axis="tp", tp_vocab=True)
    m_tp.eval()
    _sync_params(m_ref, m_tp)
    prompt = jnp.asarray(rng.integers(0, V, (1, 5)))
    want = np.asarray(generate(m_ref, prompt, 8))
    got = np.asarray(generate(m_tp, prompt, 8, mesh=_mesh(2)))
    np.testing.assert_array_equal(got, want)
