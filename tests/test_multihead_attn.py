"""Fused attention tests — mirrors the reference's
tests/L0/run_contrib (self/encdec multihead attn vs reference math) plus the
flash-kernel interpret-vs-fallback oracle."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import apex_tpu.nn as nn
from apex_tpu.contrib.multihead_attn import (
    EncdecMultiheadAttn, SelfMultiheadAttn, flash_attention, self_attn_func)
from apex_tpu.contrib.multihead_attn.attn_funcs import attention_reference
from apex_tpu.ops.pallas import force_mode


def _qkv(rng, b=2, h=4, sq=48, sk=72, d=32, dtype=jnp.float32):
    q = jnp.asarray(rng.standard_normal((b, h, sq, d)), dtype)
    k = jnp.asarray(rng.standard_normal((b, h, sk, d)), dtype)
    v = jnp.asarray(rng.standard_normal((b, h, sk, d)), dtype)
    return q, k, v


@pytest.mark.parametrize("causal", [False, True])
def test_flash_interpret_matches_reference(rng, causal):
    q, k, v = _qkv(rng, sq=48, sk=48)
    scale = 1.0 / np.sqrt(q.shape[-1])

    def loss_flash(q, k, v):
        return jnp.sum(jnp.sin(flash_attention(q, k, v, causal=causal)))

    def loss_ref(q, k, v):
        return jnp.sum(jnp.sin(
            attention_reference(q, k, v, None, causal, scale)))

    with force_mode("interpret"):
        out = flash_attention(q, k, v, causal=causal)
        g = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    ref = attention_reference(q, k, v, None, causal, scale)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)
    for a, r in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                   rtol=1e-3, atol=1e-4)


def test_flash_padding_and_bias(rng):
    # uneven seq lens exercise block padding; key-padding bias masks keys
    q, k, v = _qkv(rng, b=2, h=2, sq=40, sk=56, d=16)
    kp = np.zeros((2, 56), bool)
    kp[0, 50:] = True
    kp[1, 20:30] = True
    bias = jnp.where(jnp.asarray(kp), -1e30, 0.0)[:, None, :]
    scale = 0.25
    with force_mode("interpret"):
        out = flash_attention(q, k, v, bias=bias, scale=scale)
    ref = attention_reference(q, k, v, bias, False, scale)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)


def test_self_attn_func_fast_matches_default(rng):
    t, b, e, h = 24, 3, 32, 4
    x = jnp.asarray(rng.standard_normal((t, b, e)), jnp.float32)
    wi = jnp.asarray(rng.standard_normal((3 * e, e)) * 0.1, jnp.float32)
    wo = jnp.asarray(rng.standard_normal((e, e)) * 0.1, jnp.float32)
    scale = (e // h) ** -0.5
    out_default = self_attn_func(False, False, h, scale, x, wi, wo,
                                 use_flash=False)
    with force_mode("interpret"):
        out_fast = self_attn_func(False, False, h, scale, x, wi, wo,
                                  use_flash=True)
    np.testing.assert_allclose(np.asarray(out_fast), np.asarray(out_default),
                               rtol=1e-4, atol=1e-5)


def test_self_attn_module_masks(rng):
    nn.manual_seed(0)
    t, b, e = 16, 2, 32
    m = SelfMultiheadAttn(e, 4, dropout=0.0, impl="default").eval()
    x = jnp.asarray(rng.standard_normal((t, b, e)), jnp.float32)
    out, w = m(x, x, x)
    assert w is None
    assert out.shape == (t, b, e)
    # time mask upper-triangular: masked queries can't see future keys
    tri = np.triu(np.ones((t, t), bool), 1)
    out_m, _ = m(x, x, x, attn_mask=jnp.asarray(tri))
    assert out_m.shape == (t, b, e)
    with pytest.raises(AssertionError):
        m(x, x, x, key_padding_mask=jnp.zeros((b, t), bool),
          attn_mask=jnp.asarray(tri))


def test_norm_add_residual(rng):
    nn.manual_seed(0)
    t, b, e = 8, 2, 16
    m = SelfMultiheadAttn(e, 2, dropout=0.0, include_norm_add=True,
                          impl="default").eval()
    # zero projection weights → attention contributes 0; output == residual
    m.out_proj_weight.data = jnp.zeros_like(m.out_proj_weight.data)
    x = jnp.asarray(rng.standard_normal((t, b, e)), jnp.float32)
    out, _ = m(x, x, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(x), rtol=1e-6)


def test_encdec_module(rng):
    nn.manual_seed(0)
    tq, tk, b, e = 12, 20, 2, 32
    m = EncdecMultiheadAttn(e, 4, dropout=0.0, impl="default").eval()
    q = jnp.asarray(rng.standard_normal((tq, b, e)), jnp.float32)
    kv = jnp.asarray(rng.standard_normal((tk, b, e)), jnp.float32)
    out, _ = m(q, kv, kv)
    assert out.shape == (tq, b, e)
    kp = np.zeros((b, tk), bool)
    kp[:, 15:] = True
    out_m, _ = m(q, kv, kv, key_padding_mask=jnp.asarray(kp))
    assert np.isfinite(np.asarray(out_m)).all()


def test_dropout_path_runs(rng):
    nn.manual_seed(0)
    t, b, e = 8, 2, 16
    m = SelfMultiheadAttn(e, 2, dropout=0.5, impl="fast")
    x = jnp.asarray(rng.standard_normal((t, b, e)), jnp.float32)
    out, _ = m(x, x, x)
    assert np.isfinite(np.asarray(out)).all()


@pytest.mark.parametrize("impl", ["default", "fast"])
def test_causal_flag_matches_explicit_time_mask(rng, impl):
    """SelfMultiheadAttn(causal=True) must equal the same module fed an
    explicit upper-triangle time mask (the in-kernel triangle vs the
    materialized O(S^2) operand)."""
    t, b, e = 16, 2, 32
    nn.manual_seed(9)
    m_causal = SelfMultiheadAttn(e, 4, dropout=0.0, impl=impl,
                                 causal=True).eval()
    nn.manual_seed(9)
    m_masked = SelfMultiheadAttn(e, 4, dropout=0.0, impl=impl).eval()
    x = jnp.asarray(rng.standard_normal((t, b, e)), jnp.float32)
    tri = np.triu(np.ones((t, t), bool), k=1)  # True = excluded
    with force_mode("interpret"):
        out_c, _ = m_causal(x)
        out_m, _ = m_masked(x, attn_mask=jnp.asarray(tri))
    np.testing.assert_allclose(np.asarray(out_c), np.asarray(out_m),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_flash_inkernel_dropout_matches_reference(rng, causal):
    """In-kernel dropout (the reference's fused-dropout feature,
    apex/contrib/csrc/multihead_attn/dropout.cuh) must agree with the
    XLA oracle applying the SAME counter-based hash mask
    (dropout_keep_reference) — fwd and grads, across block boundaries
    (sq 320 > bq 256 forces a multi-q-block grid)."""
    q, k, v = _qkv(rng, b=1, h=2, sq=320, sk=320, d=16)
    scale = 1.0 / np.sqrt(q.shape[-1])
    seed = jnp.int32(424242)

    def loss_flash(q, k, v):
        return jnp.sum(flash_attention(q, k, v, causal=causal,
                                       dropout_p=0.3,
                                       dropout_seed=seed) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(
            q, k, v, None, causal, scale, dropout_p=0.3,
            dropout_seed=seed) ** 2)

    with force_mode("interpret"):
        out = flash_attention(q, k, v, causal=causal, dropout_p=0.3,
                              dropout_seed=seed)
        g = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    ref = attention_reference(q, k, v, None, causal, scale,
                              dropout_p=0.3, dropout_seed=seed)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)
    for a, r in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                   rtol=1e-3, atol=1e-4)


def test_flash_dropout_mask_properties(rng):
    """The hash mask is seed-deterministic, seed-sensitive, and drops
    ~p of the positions with inverted scaling on the rest."""
    from apex_tpu.ops.pallas.attention import dropout_keep_reference

    m1 = np.asarray(dropout_keep_reference(4, 64, 64, jnp.int32(7), 0.25))
    m2 = np.asarray(dropout_keep_reference(4, 64, 64, jnp.int32(7), 0.25))
    m3 = np.asarray(dropout_keep_reference(4, 64, 64, jnp.int32(8), 0.25))
    assert (m1 == m2).all()
    assert not (m1 == m3).all()
    assert set(np.unique(m1)).issubset({0.0, np.float32(1.0 / 0.75)})
    drop_frac = (m1 == 0.0).mean()
    assert abs(drop_frac - 0.25) < 0.02
    # distinct heads get distinct masks
    assert not (m1[0] == m1[1]).all()


def test_flash_dropout_zero_p_is_plain_attention(rng):
    q, k, v = _qkv(rng, sq=48, sk=48)
    with force_mode("interpret"):
        a = flash_attention(q, k, v, causal=True)
        b = flash_attention(q, k, v, causal=True, dropout_p=0.0,
                            dropout_seed=jnp.int32(1))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_flash_dropout_requires_seed():
    q = jnp.zeros((1, 1, 8, 8), jnp.float32)
    with pytest.raises(ValueError, match="dropout_seed"):
        flash_attention(q, q, q, dropout_p=0.1)


@pytest.mark.parametrize("shape", [(256, 256), (192, 320)])
def test_flash_causal_block_skip_multi_block(rng, shape, monkeypatch):
    """The causal block-skip must be exercised across MANY q/k blocks
    (the default 256/512 blocks make small tests single-block, where
    skipping never triggers): shrink blocks to 64x64 so the grid has
    fully-masked, diagonal, and fully-valid blocks, and assert fwd+bwd
    against the reference — skipped blocks contribute exactly p=0, so
    agreement must be as tight as the unskipped kernel's."""
    from apex_tpu.ops.pallas import attention as A

    monkeypatch.setattr(A, "_block_sizes", lambda sq, sk, d: (64, 64))
    sq, sk = shape
    q, k, v = _qkv(rng, sq=sq, sk=sk, d=32)
    scale = 1.0 / np.sqrt(q.shape[-1])

    def loss_flash(q, k, v):
        return jnp.sum(jnp.sin(flash_attention(q, k, v, causal=True)))

    def loss_ref(q, k, v):
        return jnp.sum(jnp.sin(
            attention_reference(q, k, v, None, True, scale)))

    with force_mode("interpret"):
        out = flash_attention(q, k, v, causal=True)
        g = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
    ref = attention_reference(q, k, v, None, True, scale)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-5)
    for a, r in zip(g, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                   rtol=1e-3, atol=1e-4)
