"""FusedRMSNorm (normalization/rms_norm.py): numerics vs a from-scratch
jnp RMSNorm and vs jax.grad of that reference, the pallas-interpret vs
jnp-fallback cross-build oracle (tests/L1/common/compare.py:34-40
analogue, as test_fused_layer_norm.py does for LN), and torch parity."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import apex_tpu.nn as nn
from apex_tpu.normalization import (FusedRMSNorm, fused_rms_norm,
                                    fused_rms_norm_affine)
from apex_tpu.ops.pallas import force_mode


def _ref_rms(x, norm_shape, w=None, eps=1e-6):
    ns = int(np.prod(norm_shape))
    x2 = x.reshape(-1, ns).astype(jnp.float32)
    rstd = jax.lax.rsqrt(jnp.mean(x2 * x2, axis=1, keepdims=True) + eps)
    y = x2 * rstd
    if w is not None:
        y = y * w.reshape(ns).astype(jnp.float32)
    return y.reshape(x.shape).astype(x.dtype)


@pytest.mark.parametrize("shape,norm_shape", [
    ((8, 16, 32), (32,)),
    ((4, 6, 8, 10), (8, 10)),
    ((64, 96), (96,)),
])
def test_forward_matches_reference(rng, shape, norm_shape):
    x = jnp.asarray(rng.standard_normal(shape), jnp.float32)
    w = jnp.asarray(1 + 0.1 * rng.standard_normal(norm_shape), jnp.float32)
    y = fused_rms_norm_affine(x, w, norm_shape, 1e-6)
    np.testing.assert_allclose(np.asarray(y),
                               np.asarray(_ref_rms(x, norm_shape, w)),
                               rtol=1e-5, atol=1e-5)
    y2 = fused_rms_norm(x, norm_shape, 1e-6)
    np.testing.assert_allclose(np.asarray(y2),
                               np.asarray(_ref_rms(x, norm_shape)),
                               rtol=1e-5, atol=1e-5)


def test_backward_matches_autodiff_of_reference(rng):
    x = jnp.asarray(rng.standard_normal((32, 48)), jnp.float32)
    w = jnp.asarray(1 + 0.1 * rng.standard_normal((48,)), jnp.float32)

    def fused_loss(x, w):
        return jnp.sum(fused_rms_norm_affine(x, w, (48,), 1e-6) ** 2)

    def ref_loss(x, w):
        return jnp.sum(_ref_rms(x, (48,), w) ** 2)

    gf = jax.grad(fused_loss, argnums=(0, 1))(x, w)
    gr = jax.grad(ref_loss, argnums=(0, 1))(x, w)
    for a, r in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                   rtol=1e-4, atol=1e-4)


def test_half_input_fp32_stats(rng):
    x = jnp.asarray(rng.standard_normal((16, 64)), jnp.bfloat16)
    w = jnp.ones((64,), jnp.float32)
    y = fused_rms_norm_affine(x, w, (64,), 1e-6)
    assert y.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(y, np.float32),
                               np.asarray(_ref_rms(x, (64,), w), np.float32),
                               rtol=2e-2, atol=2e-2)


def test_pallas_interpret_matches_fallback(rng):
    """Kernel logic vs jnp fallback, fwd + bwd, with row padding (40 rows
    is not a multiple of the 16-row sublane block)."""
    x = jnp.asarray(rng.standard_normal((40, 136)), jnp.float32)
    w = jnp.asarray(1 + 0.1 * rng.standard_normal((136,)), jnp.float32)

    def loss(x, w):
        return jnp.sum(jnp.sin(fused_rms_norm_affine(x, w, (136,))))

    with force_mode("off"):
        y0 = fused_rms_norm_affine(x, w, (136,))
        g0 = jax.grad(loss, argnums=(0, 1))(x, w)
    with force_mode("interpret"):
        y1 = fused_rms_norm_affine(x, w, (136,))
        g1 = jax.grad(loss, argnums=(0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(y0), np.asarray(y1),
                               rtol=1e-5, atol=1e-6)
    for a, r in zip(g1, g0):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                   rtol=1e-4, atol=1e-5)

    # plain (no-affine) path through the kernel too
    with force_mode("interpret"):
        yp = fused_rms_norm(x, (136,))
    np.testing.assert_allclose(np.asarray(yp),
                               np.asarray(_ref_rms(x, (136,))),
                               rtol=1e-5, atol=1e-6)


def test_torch_parity(rng):
    torch = pytest.importorskip("torch")
    if not hasattr(torch.nn, "RMSNorm"):
        pytest.skip("torch too old for nn.RMSNorm")
    x = rng.standard_normal((12, 80)).astype(np.float32)
    w = (1 + 0.1 * rng.standard_normal(80)).astype(np.float32)
    m = torch.nn.RMSNorm(80, eps=1e-6)
    with torch.no_grad():
        m.weight.copy_(torch.from_numpy(w))
    want = m(torch.from_numpy(x)).detach().numpy()
    got = np.asarray(fused_rms_norm_affine(
        jnp.asarray(x), jnp.asarray(w), (80,), 1e-6))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_module_trains(rng):
    nn.manual_seed(0)
    m = FusedRMSNorm(24)
    x = jnp.asarray(rng.standard_normal((8, 24)), jnp.float32)
    y = m(x).value
    assert y.shape == (8, 24)
    # unit RMS per row at weight=1
    np.testing.assert_allclose(
        np.asarray(jnp.sqrt(jnp.mean(y * y, axis=1))), 1, atol=1e-3)
    assert m.weight.data.shape == (24,)
    assert not hasattr(m, "bias") or m.bias is None
