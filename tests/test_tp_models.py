"""Megatron-style tensor parallelism integrated into the model families
(models/gpt.py, models/bert.py ``tp_axis``): head-sharded attention +
column→row MLPs against the unsharded oracle, gradient assembly through
the fused train step, and composition with data/sequence parallelism.

Reference analogue: none (SURVEY.md §2.3 — the reference's only strategy
is data parallelism); oracle methodology mirrors tests/L1 (sharded vs
unsharded build must agree)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import apex_tpu.nn as nn
from apex_tpu.nn import functional as F
from apex_tpu.nn.modules import Ctx
from apex_tpu.models import BertModel, GptModel
from apex_tpu.optimizers import FusedAdam
from apex_tpu.training import make_train_step

V, H, L, HEADS, S = 97, 32, 2, 4, 16


def _gpt(**kw):
    nn.manual_seed(5)
    return GptModel(vocab_size=V, hidden=H, layers=L, heads=HEADS,
                    max_positions=64, dropout=0.0, attn_dropout=0.0, **kw)


def test_tp_gpt_forward_and_grads_match_unsharded(rng):
    """4-way TP: logits match, and the step's gradient contract holds —
    after psum'ing the tp_sharded_params' block-sparse grads over the
    axis, every device holds the unsharded model's full gradients."""
    ids = jnp.asarray(rng.integers(0, V, (2, S)))
    w = jnp.asarray(rng.standard_normal((2, S, V)), jnp.float32)

    m_ref = _gpt()
    params_ref = list(m_ref.parameters())

    def ref_loss(vals):
        ctx = Ctx(env={id(p): v for p, v in zip(params_ref, vals)},
                  training=False)
        return jnp.sum(m_ref.forward(ctx, ids) * w)

    vals = [p.data for p in params_ref]
    ref_out = m_ref(ids).value
    ref_grads = jax.grad(ref_loss)(vals)

    m_tp = _gpt(tp_axis="tp")
    params_tp = list(m_tp.parameters())
    tp_ids_set = {id(p) for p in m_tp.tp_sharded_params()}
    mesh = Mesh(np.array(jax.devices())[:4].reshape(4), ("tp",))

    def tp_fwd(vals, ids):
        ctx = Ctx(env={id(p): v for p, v in zip(params_tp, vals)},
                  training=False)
        return m_tp.forward(ctx, ids)

    shard_fwd = jax.jit(jax.shard_map(
        tp_fwd, mesh=mesh, in_specs=(P(), P()), out_specs=P(),
        check_vma=False))
    np.testing.assert_allclose(np.asarray(shard_fwd(vals, ids)),
                               np.asarray(ref_out), rtol=2e-4, atol=2e-4)

    # gradients, assembled the way training/step.py assembles them
    def tp_grads(vals, ids, w):
        def f(vals, ids, w):
            def loss(vals):
                return jnp.sum(tp_fwd(vals, ids) * w)
            gs = jax.grad(loss)(vals)
            return [jax.lax.psum(g, "tp") if id(p) in tp_ids_set else g
                    for p, g in zip(params_tp, gs)]
        return jax.jit(jax.shard_map(
            f, mesh=mesh, in_specs=(P(), P(), P()), out_specs=P(),
            check_vma=False))(vals, ids, w)

    for a, b in zip(ref_grads, tp_grads(vals, ids, w)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-4, atol=3e-4)


def test_tp_gpt_fused_step_matches_unsharded():
    """Pure-TP training through make_train_step(tp_axis="tp"): the
    per-step losses track the unsharded run (same seed, same data)."""
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, V, (2, S)))
    tgt = jnp.asarray(np.roll(np.asarray(ids), -1, axis=1))

    def lm_loss(logits, tgt):
        return F.cross_entropy(logits.reshape((-1, V)),
                               tgt.reshape((-1,)))

    def run_ref(n):
        m = _gpt()
        opt = FusedAdam(list(m.parameters()), lr=1e-2)
        step = make_train_step(m, opt, lm_loss, half_dtype=None,
                               loss_scale=1.0)
        return [float(step(ids, tgt)) for _ in range(n)]

    def run_tp(n):
        m = _gpt(tp_axis="tp")
        opt = FusedAdam(list(m.parameters()), lr=1e-2)
        step = make_train_step(m, opt, lm_loss, half_dtype=None,
                               loss_scale=1.0, tp_axis="tp")
        mesh = Mesh(np.array(jax.devices())[:4].reshape(4), ("tp",))
        sharded = jax.jit(jax.shard_map(
            step._step_fn, mesh=mesh, in_specs=(P(), P(), P()),
            out_specs=(P(), P()), check_vma=False))
        state, losses = step.state, []
        for _ in range(n):
            state, l = sharded(state, ids, tgt)
            losses.append(float(l))
        return losses

    ref, tp = run_ref(8), run_tp(8)
    np.testing.assert_allclose(tp, ref, rtol=2e-3, atol=2e-3)
    assert tp[-1] < tp[0]


def test_tp_gpt_attention_dropout_trains():
    """TP x attention dropout (refusal lifted): each head-shard folds
    its axis index into the in-kernel mask seed, so the sharded dropped
    step runs, the loss is finite and trains, dropout is demonstrably
    ACTIVE (train loss differs from the dropout-free TP run), and eval
    logits — dropout off — still match the unsharded oracle exactly."""
    rng = np.random.default_rng(3)
    ids = jnp.asarray(rng.integers(0, V, (2, S)))
    tgt = jnp.asarray(np.roll(np.asarray(ids), -1, axis=1))
    mesh = Mesh(np.array(jax.devices())[:4].reshape(4), ("tp",))

    def lm_loss(logits, tgt):
        return F.cross_entropy(logits.reshape((-1, V)),
                               tgt.reshape((-1,)))

    def run_tp(attn_dropout, n=4):
        nn.manual_seed(5)
        m = GptModel(vocab_size=V, hidden=H, layers=L, heads=HEADS,
                     max_positions=64, dropout=0.0,
                     attn_dropout=attn_dropout, tp_axis="tp")
        opt = FusedAdam(list(m.parameters()), lr=1e-2)
        step = make_train_step(m, opt, lm_loss, half_dtype=None,
                               loss_scale=1.0, tp_axis="tp")
        sharded = jax.jit(jax.shard_map(
            step._step_fn, mesh=mesh, in_specs=(P(), P(), P()),
            out_specs=(P(), P()), check_vma=False))
        state, losses = step.state, []
        for _ in range(n):
            state, l = sharded(state, ids, tgt)
            losses.append(float(l))
        return m, losses

    m_drop, dropped = run_tp(0.3)
    _, clean = run_tp(0.0)
    assert np.isfinite(dropped).all()
    assert dropped[-1] < dropped[0]          # still trains
    assert abs(dropped[1] - clean[1]) > 1e-6  # dropout is active

    # eval (dropout off): sharded logits == unsharded oracle
    m_drop.eval()
    params = list(m_drop.parameters())
    vals = [p.data for p in params]

    def tp_fwd(vals, ids):
        ctx = Ctx(env={id(p): v for p, v in zip(params, vals)},
                  training=False)
        return m_drop.forward(ctx, ids)

    out_tp = jax.jit(jax.shard_map(
        tp_fwd, mesh=mesh, in_specs=(P(), P()), out_specs=P(),
        check_vma=False))(vals, ids)
    nn.manual_seed(5)
    m_ref = GptModel(vocab_size=V, hidden=H, layers=L, heads=HEADS,
                     max_positions=64, dropout=0.0,
                     attn_dropout=0.3).eval()
    # same seed sequence -> same initial draw; but m_drop has TRAINED
    # params, so evaluate the reference with m_drop's weights instead
    params_ref = list(m_ref.parameters())
    ctx = Ctx(env={id(pr): v for pr, v in zip(params_ref, vals)},
              training=False)
    out_ref = m_ref.forward(ctx, ids)
    np.testing.assert_allclose(np.asarray(out_tp), np.asarray(out_ref),
                               rtol=2e-4, atol=2e-4)


def test_tp_default_impl_attention_dropout_refused(rng):
    """The materializing 'default' impl cannot decorrelate masks across
    head shards (one shared key) — TP + dropout must refuse loudly
    there, while the flash path composes (test above)."""
    from apex_tpu.contrib.multihead_attn import self_attn_func

    t, b, e, heads = 8, 2, 16, 4
    x = jnp.asarray(rng.standard_normal((t, b, e)), jnp.float32)
    iw = jnp.asarray(rng.standard_normal((3 * e, e)), jnp.float32)
    ow = jnp.asarray(rng.standard_normal((e, e)), jnp.float32)
    mesh = Mesh(np.array(jax.devices())[:4].reshape(4), ("tp",))

    def fwd(x):
        return self_attn_func(False, True, heads, 0.5, x, iw, ow,
                              dropout_prob=0.1,
                              key=jax.random.PRNGKey(0), use_flash=False,
                              tensor_parallel_axis="tp")

    with pytest.raises(NotImplementedError, match="flash path"):
        jax.jit(jax.shard_map(fwd, mesh=mesh, in_specs=(P(),),
                              out_specs=P(), check_vma=False))(x)


def test_dp_x_tp_2d_mesh_training():
    """2-D composition on a (2, 4) mesh: batch sharded over 'data',
    heads/MLP sharded over 'tp'; per-step losses track the single-device
    oracle on the same global batch."""
    rng = np.random.default_rng(1)
    ids = jnp.asarray(rng.integers(0, V, (4, S)))
    tgt = jnp.asarray(np.roll(np.asarray(ids), -1, axis=1))

    def lm_loss(logits, tgt):
        return F.cross_entropy(logits.reshape((-1, V)),
                               tgt.reshape((-1,)))

    def run_ref(n):
        m = _gpt()
        opt = FusedAdam(list(m.parameters()), lr=1e-2)
        step = make_train_step(m, opt, lm_loss, half_dtype=None,
                               loss_scale=1.0)
        return [float(step(ids, tgt)) for _ in range(n)]

    def run_dp_tp(n):
        m = _gpt(tp_axis="tp")
        opt = FusedAdam(list(m.parameters()), lr=1e-2)
        step = make_train_step(m, opt, lm_loss, half_dtype=None,
                               loss_scale=1.0, axis_name="data",
                               tp_axis="tp")
        mesh = Mesh(np.array(jax.devices()).reshape(2, 4), ("data", "tp"))
        sharded = jax.jit(jax.shard_map(
            step._step_fn, mesh=mesh,
            in_specs=(P(), P("data"), P("data")),
            out_specs=(P(), P()), check_vma=False))
        state, losses = step.state, []
        for _ in range(n):
            state, l = sharded(state, ids, tgt)
            # the reported loss is one data-shard's half-batch mean; the
            # grads are exact (psum-mean over 'data' + tp assembly), so
            # compare the global mean
            losses.append(float(jax.jit(jax.shard_map(
                lambda s, i, t: jax.lax.pmean(
                    lm_loss(_fwd_eval(m, s, i), t), "data"),
                mesh=mesh, in_specs=(P(), P("data"), P("data")),
                out_specs=P(), check_vma=False))(state, ids, tgt)))
        return losses

    def _fwd_eval(m, state, ids):
        params = [p for p in m.parameters() if p is not None]
        env = {id(p): v for p, v in zip(params, state.master_params)}
        ctx = Ctx(env=env, training=False)
        return m.forward(ctx, ids)

    # compare the post-update eval losses instead of the in-step training
    # losses (those are per-shard); oracle does the same eval
    def run_ref_eval(n):
        m = _gpt()
        opt = FusedAdam(list(m.parameters()), lr=1e-2)
        step = make_train_step(m, opt, lm_loss, half_dtype=None,
                               loss_scale=1.0)
        losses = []
        for _ in range(n):
            step(ids, tgt)
            losses.append(float(lm_loss(_fwd_eval(m, step.state, ids), tgt)))
        return losses

    ref, dptp = run_ref_eval(5), run_dp_tp(5)
    np.testing.assert_allclose(dptp, ref, rtol=3e-3, atol=3e-3)


def test_sp_x_tp_composition_matches_unsharded(rng):
    """Ring-SP × TP on a (2, 4) mesh: sequence sharded over 'sp', heads
    over 'tp' — forward logits match the unsharded oracle."""
    S_G = 32
    ids = jnp.asarray(rng.integers(0, V, (2, S_G)))

    nn.manual_seed(5)
    m_ref = GptModel(vocab_size=V, hidden=H, layers=L, heads=HEADS,
                     max_positions=S_G, dropout=0.0, attn_dropout=0.0)
    ref_out = m_ref(ids).value

    nn.manual_seed(5)
    m = GptModel(vocab_size=V, hidden=H, layers=L, heads=HEADS,
                 max_positions=S_G, dropout=0.0, attn_dropout=0.0,
                 sp_axis="sp", tp_axis="tp")
    params = list(m.parameters())
    vals = [p.data for p in params]
    mesh = Mesh(np.array(jax.devices()).reshape(2, 4), ("sp", "tp"))

    def fwd(vals, ids_l):
        ctx = Ctx(env={id(p): v for p, v in zip(params, vals)},
                  training=False)
        return m.forward(ctx, ids_l)

    shard_fwd = jax.jit(jax.shard_map(
        fwd, mesh=mesh, in_specs=(P(), P(None, "sp")),
        out_specs=P(None, "sp", None), check_vma=False))
    np.testing.assert_allclose(np.asarray(shard_fwd(vals, ids)),
                               np.asarray(ref_out), rtol=3e-4, atol=3e-4)


def test_sp_x_tp_bert_ulysses_composition(rng):
    """Ulysses-SP × TP on a (2, 2) mesh for the BERT encoder: TP slices
    the head blocks first (2 local heads), then Ulysses scatters those
    over the sp axis while gathering the sequence — output matches the
    unsharded oracle."""
    S_G = 16

    def build(sp, tp):
        nn.manual_seed(3)
        return BertModel(vocab_size=V, hidden=H, layers=L, heads=HEADS,
                         intermediate=64, max_positions=S_G, dropout=0.0,
                         attn_dropout=0.0, sp_axis=sp, tp_axis=tp)

    ids = jnp.asarray(rng.integers(0, V, (2, S_G)))
    m_ref = build(None, None)
    ref_out = m_ref(ids).value

    m = build("sp", "tp")
    params = list(m.parameters())
    vals = [p.data for p in params]
    mesh = Mesh(np.array(jax.devices())[:4].reshape(2, 2), ("sp", "tp"))

    def fwd(vals, ids_l):
        ctx = Ctx(env={id(p): v for p, v in zip(params, vals)},
                  training=False)
        return m.forward(ctx, ids_l)

    out = jax.jit(jax.shard_map(
        fwd, mesh=mesh, in_specs=(P(), P(None, "sp")),
        out_specs=P(None, "sp", None), check_vma=False))(vals, ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out),
                               rtol=3e-4, atol=3e-4)


def test_tp_bert_forward_matches_unsharded(rng):
    """BERT encoder under 4-way TP with a padding mask: sequence output
    matches unsharded."""
    def build(tp_axis):
        nn.manual_seed(3)
        return BertModel(vocab_size=V, hidden=H, layers=L, heads=HEADS,
                         intermediate=64, max_positions=64, dropout=0.0,
                         attn_dropout=0.0, tp_axis=tp_axis)

    ids = jnp.asarray(rng.integers(0, V, (2, S)))
    mask = np.ones((2, S), np.int32)
    mask[:, 10:] = 0
    mask = jnp.asarray(mask)

    m_ref = build(None)
    ref_out = m_ref(ids, None, mask).value

    m_tp = build("tp")
    params = list(m_tp.parameters())
    vals = [p.data for p in params]
    mesh = Mesh(np.array(jax.devices())[:4].reshape(4), ("tp",))

    def fwd(vals, ids, mask):
        ctx = Ctx(env={id(p): v for p, v in zip(params, vals)},
                  training=False)
        return m_tp.forward(ctx, ids, None, mask)

    out = jax.jit(jax.shard_map(
        fwd, mesh=mesh, in_specs=(P(), P(), P()), out_specs=P(),
        check_vma=False))(vals, ids, mask)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out),
                               rtol=2e-4, atol=2e-4)


def test_tp_seq2seq_matches_unsharded(rng):
    """Encoder-decoder under 4-way TP (self + cross attention head
    sharding, column→row MLPs in both stacks): logits match unsharded,
    and the fused step tracks the unsharded losses."""
    from apex_tpu.models import TransformerSeq2Seq

    def build(tp_axis):
        nn.manual_seed(9)
        return TransformerSeq2Seq(vocab_size=V, hidden=H, enc_layers=1,
                                  dec_layers=1, heads=HEADS,
                                  max_positions=32, dropout=0.0,
                                  attn_dropout=0.0, tp_axis=tp_axis)

    src = jnp.asarray(rng.integers(1, V, (2, 12)))
    tgt_in = jnp.concatenate(
        [jnp.zeros((2, 1), src.dtype), src[:, :-1]], axis=1)

    m_ref = build(None)
    ref_out = m_ref(src, tgt_in).value

    m_tp = build("tp")
    params = list(m_tp.parameters())
    vals = [p.data for p in params]
    mesh = Mesh(np.array(jax.devices())[:4].reshape(4), ("tp",))

    def fwd(vals, src, tgt_in):
        ctx = Ctx(env={id(p): v for p, v in zip(params, vals)},
                  training=False)
        return m_tp.forward(ctx, (src, tgt_in))

    out = jax.jit(jax.shard_map(
        fwd, mesh=mesh, in_specs=(P(), P(), P()), out_specs=P(),
        check_vma=False))(vals, src, tgt_in)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out),
                               rtol=3e-4, atol=3e-4)

    # fused-step loss parity over a few updates
    def loss_fn(logits, tgt_out):
        return F.cross_entropy(logits.reshape((-1, V)),
                               tgt_out.reshape((-1,)))

    def run_ref(n):
        m = build(None)
        opt = FusedAdam(list(m.parameters()), lr=1e-2)
        step = make_train_step(m, opt, loss_fn, half_dtype=None,
                               loss_scale=1.0)
        return [float(step((src, tgt_in), src)) for _ in range(n)]

    def run_tp(n):
        m = build("tp")
        opt = FusedAdam(list(m.parameters()), lr=1e-2)
        step = make_train_step(m, opt, loss_fn, half_dtype=None,
                               loss_scale=1.0, tp_axis="tp")
        sharded = jax.jit(jax.shard_map(
            step._step_fn, mesh=mesh, in_specs=(P(), P(), P()),
            out_specs=(P(), P()), check_vma=False))
        state, losses = step.state, []
        for _ in range(n):
            state, l = sharded(state, (src, tgt_in), src)
            losses.append(float(l))
        return losses

    np.testing.assert_allclose(run_tp(6), run_ref(6), rtol=2e-3,
                               atol=2e-3)


def test_vocab_parallel_cross_entropy_matches_dense(rng):
    """Megatron parallel cross entropy over vocab-sharded logits: loss
    and logits-gradients match the dense log-softmax oracle (backward is
    softmax_local - onehot_local per shard, assembled by concat)."""
    from apex_tpu.parallel import vocab_parallel_cross_entropy

    V_G, T = 32, 24
    logits = jnp.asarray(rng.standard_normal((T, V_G)), jnp.float32)
    tgt = jnp.asarray(rng.integers(0, V_G, (T,)))
    mesh = Mesh(np.array(jax.devices())[:4].reshape(4), ("tp",))

    def dense_loss(logits):
        return F.cross_entropy(logits, tgt)

    ref_l = float(dense_loss(logits))
    ref_g = np.asarray(jax.grad(dense_loss)(logits))

    def f(logits):
        def loss(lg_shard):
            return vocab_parallel_cross_entropy(lg_shard, tgt, "tp")
        n = jax.lax.psum(1, "tp")
        i = jax.lax.axis_index("tp")
        shard = jax.lax.dynamic_slice_in_dim(
            logits, i * (V_G // n), V_G // n, axis=1)
        l, g = jax.value_and_grad(loss)(shard)
        return l, g

    l, g_shards = jax.jit(jax.shard_map(
        f, mesh=mesh, in_specs=P(), out_specs=(P(), P(None, "tp")),
        check_vma=False))(logits)
    np.testing.assert_allclose(float(l), ref_l, rtol=1e-6)
    np.testing.assert_allclose(np.asarray(g_shards), ref_g,
                               rtol=1e-5, atol=1e-6)


def test_tp_vocab_gpt_matches_unsharded(rng):
    """GptModel(tp_vocab=True): vocab-sharded logits concat to the
    unsharded logits, and the fused step with the vocab-parallel loss
    tracks the unsharded run (embedding grads assemble from vocab-row
    scatters)."""
    from apex_tpu.parallel import vocab_parallel_cross_entropy

    mesh = Mesh(np.array(jax.devices())[:4].reshape(4), ("tp",))
    # V=97 is prime; use a divisible vocab for the sharded build
    V_G = 96
    ids = jnp.asarray(rng.integers(0, V_G, (2, S)))
    tgt = jnp.asarray(np.roll(np.asarray(ids), -1, axis=1))

    def build(tp_axis, tp_vocab):
        nn.manual_seed(5)
        return GptModel(vocab_size=V_G, hidden=H, layers=L, heads=HEADS,
                        max_positions=64, dropout=0.0, attn_dropout=0.0,
                        tp_axis=tp_axis, tp_vocab=tp_vocab)

    m_ref = build(None, False)
    ref_out = m_ref(ids).value

    m_tp = build("tp", True)
    params = list(m_tp.parameters())
    vals = [p.data for p in params]

    def fwd(vals, ids):
        ctx = Ctx(env={id(p): v for p, v in zip(params, vals)},
                  training=False)
        return m_tp.forward(ctx, ids)

    out = jax.jit(jax.shard_map(
        fwd, mesh=mesh, in_specs=(P(), P()),
        out_specs=P(None, None, "tp"), check_vma=False))(vals, ids)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref_out),
                               rtol=2e-4, atol=2e-4)

    # fused-step parity: vocab-parallel loss vs dense loss
    def run_ref(n):
        m = build(None, False)
        opt = FusedAdam(list(m.parameters()), lr=1e-2)
        step = make_train_step(
            m, opt,
            lambda lg, t: F.cross_entropy(lg.reshape((-1, V_G)),
                                          t.reshape((-1,))),
            half_dtype=None, loss_scale=1.0)
        return [float(step(ids, tgt)) for _ in range(6)]

    def run_tp(n):
        m = build("tp", True)
        opt = FusedAdam(list(m.parameters()), lr=1e-2)
        step = make_train_step(
            m, opt,
            lambda lg, t: vocab_parallel_cross_entropy(
                lg.reshape((-1, lg.shape[-1])), t.reshape((-1,)), "tp"),
            half_dtype=None, loss_scale=1.0, tp_axis="tp")
        sharded = jax.jit(jax.shard_map(
            step._step_fn, mesh=mesh, in_specs=(P(), P(), P()),
            out_specs=(P(), P()), check_vma=False))
        state, losses = step.state, []
        for _ in range(6):
            state, l = sharded(state, ids, tgt)
            losses.append(float(l))
        return losses

    np.testing.assert_allclose(run_tp(6), run_ref(6), rtol=2e-3,
                               atol=2e-3)


def test_tp_vocab_requires_tp_axis():
    with pytest.raises(ValueError, match="tp_vocab requires tp_axis"):
        GptModel(vocab_size=V, hidden=H, layers=1, heads=HEADS,
                 attn_dropout=0.0, tp_vocab=True)


def test_tp_config_validation():
    # tp_axis with the default attn_dropout=0.1 constructs since the
    # in-kernel per-shard mask streams landed (the old refusal is gone)
    GptModel(vocab_size=V, hidden=H, layers=1, heads=HEADS, tp_axis="tp")
    BertModel(vocab_size=V, hidden=H, layers=1, heads=HEADS,
              intermediate=64, tp_axis="tp")
    # heads not divisible by the axis size fails loudly at trace time
    m = _gpt(tp_axis="tp")
    params = list(m.parameters())
    vals = [p.data for p in params]
    mesh = Mesh(np.array(jax.devices()), ("tp",))  # 8 devices, 4 heads

    def fwd(vals, ids):
        ctx = Ctx(env={id(p): v for p, v in zip(params, vals)},
                  training=False)
        return m.forward(ctx, ids)

    with pytest.raises(ValueError, match="not divisible"):
        jax.jit(jax.shard_map(
            fwd, mesh=mesh, in_specs=(P(), P()), out_specs=P(),
            check_vma=False))(vals, jnp.zeros((2, S), jnp.int32))


def test_tp_step_requires_model_support():
    nn.manual_seed(0)
    m = nn.Sequential(nn.Linear(8, 8))
    opt = FusedAdam(list(m.parameters()), lr=1e-3)
    with pytest.raises(ValueError, match="tp_sharded_params"):
        make_train_step(m, opt, lambda o, t: jnp.sum(o), tp_axis="tp")
