"""Sequence-parallel (context-parallel) cached decode
(parallel/context_parallel.py + models/{gpt,llama}.py ``sp_axis`` decode):
the KV cache's TIME axis shards over the mesh, chunk writes land on the
owning device, and partial attention lse-merges over the axis — emitted
tokens must match the single-shard decode of the same weights.

Reference analogue: none (the reference is training-side and
single-device, SURVEY.md §5 long-context row); oracle methodology
mirrors tests/test_tp_decode.py (sharded vs unsharded build agree).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

import apex_tpu.nn as nn
from apex_tpu.models import GptModel
from apex_tpu.models.gpt import generate
from apex_tpu.models.llama import LlamaModel
from apex_tpu.nn.modules import Ctx

V = 97


def _sp_mesh(n):
    return Mesh(np.array(jax.devices())[:n].reshape(n), ("sp",))


def _llama(**kw):
    nn.manual_seed(7)
    return LlamaModel(vocab_size=V, hidden=32, layers=2, heads=4,
                      kv_heads=2, max_positions=64, **kw)


def _gpt(**kw):
    nn.manual_seed(7)
    return GptModel(vocab_size=V, hidden=32, layers=2, heads=4,
                    max_positions=64, dropout=0.0, attn_dropout=0.0, **kw)


def _sync_params(src, dst):
    for ps, pd in zip(src.parameters(), dst.parameters()):
        pd.data = ps.data


def test_gpt_sp_greedy_decode_matches_single_shard(rng):
    m_ref = _gpt()
    m_ref.eval()
    m_sp = _gpt(sp_axis="sp")
    m_sp.eval()
    _sync_params(m_ref, m_sp)

    prompt = jnp.asarray(rng.integers(0, V, (2, 5)))
    want = np.asarray(generate(m_ref, prompt, 10))
    got = np.asarray(generate(m_sp, prompt, 10, mesh=_sp_mesh(4)))
    np.testing.assert_array_equal(got, want)


def test_gpt_sp_prompt_straddles_cache_blocks(rng):
    """Prompt longer than one device's cache block: the chunked prefill
    must split it and the windowed writes must handle chunks straddling
    two owners (s_total=50 over sp=4 -> 13-slot blocks, prompt 40)."""
    m_ref = _gpt()
    m_ref.eval()
    m_sp = _gpt(sp_axis="sp")
    m_sp.eval()
    _sync_params(m_ref, m_sp)

    prompt = jnp.asarray(rng.integers(0, V, (1, 40)))
    want = np.asarray(generate(m_ref, prompt, 10))
    got = np.asarray(generate(m_sp, prompt, 10, mesh=_sp_mesh(4)))
    np.testing.assert_array_equal(got, want)


def test_llama_sp_gqa_greedy_decode_matches_single_shard(rng):
    m_ref = _llama()
    m_ref.eval()
    m_sp = _llama(sp_axis="sp")
    m_sp.eval()
    _sync_params(m_ref, m_sp)

    prompt = jnp.asarray(rng.integers(0, V, (2, 5)))
    want = np.asarray(generate(m_ref, prompt, 10))
    got = np.asarray(generate(m_sp, prompt, 10, mesh=_sp_mesh(2)))
    np.testing.assert_array_equal(got, want)


def test_gpt_sp_tp_composed_decode(rng):
    """SP (time-sharded caches) x TP (head-sharded projections) on a
    2x2 mesh: the two merges ride different axes and must compose."""
    m_ref = _gpt()
    m_ref.eval()
    m_2d = _gpt(sp_axis="sp", tp_axis="tp")
    m_2d.eval()
    _sync_params(m_ref, m_2d)

    mesh = Mesh(np.array(jax.devices())[:4].reshape(2, 2), ("tp", "sp"))
    prompt = jnp.asarray(rng.integers(0, V, (2, 5)))
    want = np.asarray(generate(m_ref, prompt, 10))
    got = np.asarray(generate(m_2d, prompt, 10, mesh=mesh))
    np.testing.assert_array_equal(got, want)


def test_llama_sp_int8_kv_matches_single_shard_int8(rng):
    """QuantKV under SP quantizes each written position against its own
    absmax — bit-identical STORED values to the single-shard int8 write
    — so the only cross-sharding difference is the lse merge's float
    reassociation: compare chunk LOGITS (token streams can flip at the
    near-ties int8-coarsened caches make likelier).  The oracle
    prefills through decode_chunk, not prefill: blk.prefill attends the
    prompt with UNQUANTIZED flash K/V while every cache-mediated path
    (including SP's chunked prefill) attends the quantized rows — the
    comparable single-shard int8 program is the cache-mediated one."""
    from jax.sharding import PartitionSpec as P

    m_ref = _llama()
    m_ref.eval()
    m_sp = _llama(sp_axis="sp")
    m_sp.eval()
    _sync_params(m_ref, m_sp)
    params = list(m_sp.parameters())
    prompt = jnp.asarray(rng.integers(0, V, (1, 6)))
    chunk = jnp.asarray(rng.integers(0, V, (1, 3)))

    ctx = Ctx(training=False)
    caches = m_ref.init_caches(1, 16, dtype="int8")
    _, caches = m_ref.decode_chunk(ctx, prompt, caches, 0)
    want, _ = m_ref.decode_chunk(ctx, chunk, caches, 6)

    def run(vals, prompt, chunk):
        ctx = Ctx(env={id(p): v for p, v in zip(params, vals)},
                  training=False)
        caches = m_sp.init_caches(1, 16, dtype="int8")
        _, caches = m_sp.prefill(ctx, prompt, caches)
        out, _ = m_sp.decode_chunk(ctx, chunk, caches, 6)
        return out

    got = jax.jit(jax.shard_map(
        run, mesh=_sp_mesh(2), in_specs=(P(), P(), P()), out_specs=P(),
        check_vma=False))([p.data for p in params], prompt, chunk)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_sp_decode_chunk_matches_single_shard(rng):
    """The speculative-verification primitive under SP: chunk logits
    against a prefilled time-sharded cache agree with the single-shard
    chunk (close in float; the lse merge reassociates)."""
    from jax.sharding import PartitionSpec as P

    m_ref = _llama()
    m_ref.eval()
    m_sp = _llama(sp_axis="sp")
    m_sp.eval()
    _sync_params(m_ref, m_sp)
    params = list(m_sp.parameters())
    prompt = jnp.asarray(rng.integers(0, V, (1, 6)))
    chunk = jnp.asarray(rng.integers(0, V, (1, 3)))

    ctx = Ctx(training=False)
    caches = m_ref.init_caches(1, 16)
    _, caches = m_ref.prefill(ctx, prompt, caches)
    want, _ = m_ref.decode_chunk(ctx, chunk, caches, 6)

    def run(vals, prompt, chunk):
        ctx = Ctx(env={id(p): v for p, v in zip(params, vals)},
                  training=False)
        caches = m_sp.init_caches(1, 16)   # 8-slot blocks on sp=2
        _, caches = m_sp.prefill(ctx, prompt, caches)
        out, _ = m_sp.decode_chunk(ctx, chunk, caches, 6)
        return out

    got = jax.jit(jax.shard_map(
        run, mesh=_sp_mesh(2), in_specs=(P(), P(), P()), out_specs=P(),
        check_vma=False))([p.data for p in params], prompt, chunk)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_speculative_sp_target_exactness(rng):
    """Greedy speculative decoding with an SP-sharded target and a
    replicated draft emits exactly the target's own greedy stream (the
    exactness guarantee is sharding-invariant)."""
    from apex_tpu.inference.speculative import speculative_generate

    target_ref = _gpt()
    target_ref.eval()
    target_sp = _gpt(sp_axis="sp")
    target_sp.eval()
    _sync_params(target_ref, target_sp)
    nn.manual_seed(11)
    draft = GptModel(vocab_size=V, hidden=16, layers=1, heads=2,
                     max_positions=64, dropout=0.0, attn_dropout=0.0)
    draft.eval()

    prompt = jnp.asarray(rng.integers(0, V, (1, 5)))
    want = np.asarray(generate(target_ref, prompt, 10))
    got = np.asarray(speculative_generate(
        target_sp, draft, prompt, 10, mesh=_sp_mesh(2)))
    np.testing.assert_array_equal(got, want)


def test_sp_decode_requires_mesh():
    m = _gpt(sp_axis="sp")
    m.eval()
    with pytest.raises(ValueError, match="mesh"):
        generate(m, jnp.zeros((1, 4), jnp.int32), 4)


def test_sp_moe_decode_refuses():
    nn.manual_seed(7)
    m = _gpt(sp_axis="sp", moe_axis="data", moe_num_experts=2)
    m.eval()
    with pytest.raises(NotImplementedError, match="sp_axis"):
        generate(m, jnp.zeros((1, 4), jnp.int32), 4,
                 mesh=_sp_mesh(2))


def test_sp_train_then_sp_decode_bridge(rng):
    """The long-context workflow end to end on ONE mesh axis: train the
    model under ring sequence parallelism (time-sharded activations),
    write the trained state back, then serve it under context-parallel
    decode (time-sharded KV caches) — and the served stream matches a
    plain single-shard model carrying the same trained weights."""
    from jax.sharding import Mesh, PartitionSpec as P
    from apex_tpu.nn import functional as F
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.training import make_train_step

    m = _gpt(sp_axis="sp")
    opt = FusedAdam(list(m.parameters()), lr=1e-2)

    def lm_loss(logits, tgt):
        return F.cross_entropy(logits.reshape((-1, V)),
                               tgt.reshape((-1,)))

    step = make_train_step(m, opt, lm_loss, half_dtype=None,
                           loss_scale=1.0, axis_name="sp")
    mesh = Mesh(np.array(jax.devices())[:4].reshape(4), ("sp",))
    ids = jnp.asarray(rng.integers(0, V, (2, 32)))
    tgt = jnp.asarray(np.roll(np.asarray(ids), -1, axis=1))
    sharded = jax.jit(jax.shard_map(
        step._step_fn, mesh=mesh,
        in_specs=(P(), P(None, "sp"), P(None, "sp")),
        out_specs=(P(), P()), check_vma=False))
    state, l0 = sharded(step.state, ids, tgt)
    for _ in range(3):
        state, l = sharded(state, ids, tgt)
    assert np.isfinite(float(l)) and float(l) < float(l0)

    step.state = state
    step.sync_to_objects()
    m.eval()
    prompt = jnp.asarray(rng.integers(0, V, (1, 6)))
    got = np.asarray(generate(m, prompt, 10, mesh=mesh))

    # oracle: a plain (no-sp) model loaded with the trained weights
    ref = _gpt()
    for a, b in zip(m.parameters(), ref.parameters()):
        b.data = a.data
    for a, b in zip(m.buffers(), ref.buffers()):
        b.data = a.data
    ref.eval()
    want = np.asarray(generate(ref, prompt, 10))
    np.testing.assert_array_equal(got, want)


def test_sp_random_chunk_schedules_match_forward(rng):
    """Property-style: random decode_chunk interleavings under SP
    (chunks bounded by the per-device cache block, straddling owners
    arbitrarily) all reproduce the single-shard teacher-forced
    forward — the sharded cache protocol is schedule-invariant."""
    from jax.sharding import PartitionSpec as P

    m_ref = _gpt()
    m_ref.eval()
    m_sp = _gpt(sp_axis="sp")
    m_sp.eval()
    _sync_params(m_ref, m_sp)
    params = list(m_sp.parameters())
    toks = jnp.asarray(rng.integers(0, V, (1, 32)))
    want = np.asarray(m_ref.forward(Ctx(training=False), toks))

    for trial in range(2):
        sizes = []
        left = 32
        while left:
            c = int(rng.integers(1, min(left, 8) + 1))  # block = 8
            sizes.append(c)
            left -= c

        def run(vals, toks):
            ctx = Ctx(env={id(p): v for p, v in zip(params, vals)},
                      training=False)
            caches = m_sp.init_caches(1, 32)   # 8-slot blocks on sp=4
            outs = []
            t = 0
            for c in sizes:
                lg, caches = m_sp.decode_chunk(
                    ctx, toks[:, t:t + c], caches, t)
                outs.append(lg)
                t += c
            return jnp.concatenate(outs, axis=1)

        got = jax.jit(jax.shard_map(
            run, mesh=_sp_mesh(4), in_specs=(P(), P()), out_specs=P(),
            check_vma=False))([p.data for p in params], toks)
        np.testing.assert_allclose(np.asarray(got), want,
                                   rtol=3e-4, atol=3e-4,
                                   err_msg=f"schedule {sizes}")
