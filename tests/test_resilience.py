"""Resilience runtime (apex_tpu/runtime/{resilience,chaos}.py): atomic
checkpoint writes that survive a mid-write kill, manifest/checksum
validation with fallback past corrupt files, async save with error
surfacing, BadStepGuard escalation over the scaler's skip logic, and
bounded-retry distributed init — every recovery path driven by the
deterministic chaos harness."""
import os
import pickle
import warnings

import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.runtime import chaos
from apex_tpu.runtime.resilience import (
    BadStepGuard, CheckpointCorruptError, CheckpointManager,
    CollectiveTimeoutError, DistributedInitError, SCHEMA_VERSION,
    TrainingDivergedError, read_checkpoint_file, restore_state,
    write_checkpoint_file)


@pytest.fixture(autouse=True)
def _no_leftover_controller():
    yield
    chaos.uninstall()


# ---------------------------------------------------------------------------
# chaos harness semantics
# ---------------------------------------------------------------------------


def test_chaos_deterministic_at_times_after():
    c = chaos.ChaosController(seed=0)
    c.on("p", action="fail", at=(1, 3))
    c.on("q", action="fail", after=2, times=2)
    fired = []
    for i in range(5):
        try:
            c.fire("p")
            fired.append(0)
        except chaos.ChaosInjectedFailure:
            fired.append(1)
    assert fired == [0, 1, 0, 1, 0]
    fired = []
    for i in range(6):
        try:
            c.fire("q")
            fired.append(0)
        except chaos.ChaosInjectedFailure:
            fired.append(1)
    # after=2, times=2: fires on calls 2 and 3 only
    assert fired == [0, 0, 1, 1, 0, 0]
    assert [entry[0] for entry in c.log] == ["p", "p", "q", "q"]


def test_chaos_session_installs_and_uninstalls():
    assert not chaos.active()
    with chaos.session() as c:
        assert chaos.active()
        c.on("x", action="kill")
        with pytest.raises(chaos.ChaosKilled):
            chaos.hook("x")
    assert not chaos.active()
    assert chaos.hook("x") is None  # no controller → no-op


def test_chaos_callable_action_gets_context():
    seen = {}
    with chaos.session() as c:
        c.on("pt", action=lambda ctx: seen.update(ctx) or "custom")
        assert chaos.hook("pt", foo=7) == "custom"
    assert seen["foo"] == 7 and seen["point"] == "pt" and seen["call"] == 0


# ---------------------------------------------------------------------------
# atomic writes + validation
# ---------------------------------------------------------------------------


def test_atomic_write_leaves_no_tmp_and_roundtrips(tmp_path):
    path = str(tmp_path / "c.pkl")
    write_checkpoint_file(path, {"model": {"w": jnp.arange(4.0)}, "step": 7})
    assert [f for f in os.listdir(tmp_path) if ".tmp." in f] == []
    out = read_checkpoint_file(path)
    assert out["step"] == 7
    np.testing.assert_array_equal(out["model"]["w"], np.arange(4.0))
    assert isinstance(out["model"]["w"], np.ndarray)  # host numpy


@pytest.mark.chaos
@pytest.mark.parametrize("point", ["ckpt.mid_write", "ckpt.pre_rename"])
def test_kill_during_save_preserves_previous_checkpoint(tmp_path, point):
    """THE atomicity claim: a save killed mid-write (or pre-rename) leaves
    the previous checkpoint at the final path, bit-for-bit loadable."""
    path = str(tmp_path / "c.pkl")
    write_checkpoint_file(path, {"v": 1})
    with chaos.session() as c:
        c.on(point, action="kill")
        with pytest.raises(chaos.ChaosKilled):
            write_checkpoint_file(path, {"v": 2})
    assert read_checkpoint_file(path)["v"] == 1


def test_corrupt_checkpoint_raises_typed_error(tmp_path):
    path = str(tmp_path / "c.pkl")
    write_checkpoint_file(path, {"model": {"w": np.zeros(64)}})
    blob = bytearray(open(path, "rb").read())
    blob[-30] ^= 0xFF                      # bit rot inside the payload
    open(path, "wb").write(bytes(blob))
    with pytest.raises(CheckpointCorruptError):
        read_checkpoint_file(path)


def test_truncated_checkpoint_raises_typed_error(tmp_path):
    path = str(tmp_path / "c.pkl")
    write_checkpoint_file(path, {"model": {"w": np.zeros(64)}})
    blob = open(path, "rb").read()
    open(path, "wb").write(blob[:len(blob) // 2])
    with pytest.raises(CheckpointCorruptError):
        read_checkpoint_file(path)


def test_future_schema_raises(tmp_path):
    path = str(tmp_path / "c.pkl")
    with open(path, "wb") as f:
        pickle.dump({"__apex_tpu_checkpoint__": SCHEMA_VERSION + 1,
                     "manifest": {}, "payload": {}}, f)
    with pytest.raises(CheckpointCorruptError, match="schema"):
        read_checkpoint_file(path)


def test_legacy_manifestless_pickle_loads_with_warning(tmp_path):
    path = str(tmp_path / "legacy.pkl")
    with open(path, "wb") as f:
        pickle.dump({"model": {"w": np.ones(3)}, "epoch": 2}, f)
    with pytest.warns(UserWarning, match="legacy"):
        out = read_checkpoint_file(path)
    assert out["epoch"] == 2


# ---------------------------------------------------------------------------
# CheckpointManager
# ---------------------------------------------------------------------------


def test_manager_retention_keeps_newest(tmp_path):
    m = CheckpointManager(str(tmp_path), keep_n=2)
    for s in range(1, 6):
        m.save(s, value=s)
    assert m.all_steps() == [4, 5]
    assert m.latest_step() == 5
    assert m.restore()["value"] == 5
    assert m.restore(step=4)["value"] == 4


def test_manager_restore_or_initialize_empty(tmp_path):
    m = CheckpointManager(str(tmp_path))
    step, out = m.restore_or_initialize(lambda: {"fresh": True})
    assert step is None and out == {"fresh": True}
    step, out = m.restore_or_initialize()
    assert step is None and out is None


@pytest.mark.chaos
def test_manager_survives_midwrite_kill_and_sweeps_tmp(tmp_path):
    m = CheckpointManager(str(tmp_path), keep_n=3)
    m.save(1, value=1)
    with chaos.session() as c:
        c.on("ckpt.mid_write", action="kill")
        with pytest.raises(chaos.ChaosKilled):
            m.save(2, value=2)
    # honest kill debris: a partial tmp file, the final path untouched
    assert any(".tmp." in f for f in os.listdir(tmp_path))
    assert m.all_steps() == [1]
    step, out = m.restore_or_initialize()
    assert (step, out["value"]) == (1, 1)
    m.save(3, value=3)                     # next save sweeps the debris
    assert not any(".tmp." in f for f in os.listdir(tmp_path))


def test_manager_falls_back_past_corrupt_to_latest_valid(tmp_path):
    m = CheckpointManager(str(tmp_path), keep_n=5)
    for s in (1, 2, 3):
        m.save(s, value=s)
    blob = bytearray(open(m.path_for(3), "rb").read())
    blob[-10] ^= 0xFF
    open(m.path_for(3), "wb").write(bytes(blob))
    with pytest.warns(UserWarning, match="corrupt"):
        step, out = m.restore_or_initialize()
    assert (step, out["value"]) == (2, 2)


def test_async_save_returns_immediately_and_surfaces_result(tmp_path):
    m = CheckpointManager(str(tmp_path), keep_n=4)
    handles = [m.save_async(s, value=jnp.full((8,), float(s)))
               for s in (1, 2, 3)]
    for h in handles:
        h.wait(timeout=30)
    assert m.all_steps() == [1, 2, 3]
    np.testing.assert_array_equal(m.restore(2)["value"], np.full((8,), 2.0))
    m.close()


@pytest.mark.chaos
def test_async_save_error_surfaces_on_wait(tmp_path):
    m = CheckpointManager(str(tmp_path))
    with chaos.session() as c:
        c.on("ckpt.mid_write", action="fail")
        h = m.save_async(1, value=1)
        with pytest.raises(chaos.ChaosInjectedFailure):
            h.wait(timeout=30)
    assert m.all_steps() == []             # failed write cleaned its tmp
    assert not any(".tmp." in f for f in os.listdir(tmp_path))


def test_async_save_snapshot_isolated_from_later_mutation(tmp_path):
    """The device→host transfer happens on the caller thread at submit
    time: mutating the source dict (or advancing training) afterwards
    must not change what lands on disk."""
    m = CheckpointManager(str(tmp_path))
    tree = {"w": jnp.ones((4,))}
    h = m.save_async(1, model=tree)
    tree["w"] = jnp.zeros((4,))
    h.wait(timeout=30)
    np.testing.assert_array_equal(m.restore(1)["model"]["w"], np.ones(4))
    m.close()


# ---------------------------------------------------------------------------
# end-to-end: preemption-safe resume of a fused train step
# ---------------------------------------------------------------------------


def _fused_step():
    import apex_tpu.nn as nn
    from apex_tpu.nn import functional as F
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.training import make_train_step

    nn.manual_seed(11)
    model = nn.Sequential(nn.Linear(16, 32), nn.GELU(), nn.Linear(32, 8))
    opt = FusedAdam(list(model.parameters()), lr=5e-3)
    return make_train_step(model, opt, lambda o, t: F.cross_entropy(o, t),
                           half_dtype=jnp.bfloat16, loss_scale="dynamic")


def _batch(seed=0):
    rng = np.random.default_rng(seed)
    return (jnp.asarray(rng.standard_normal((32, 16)), jnp.float32),
            jnp.asarray(rng.integers(0, 8, (32,))))


@pytest.mark.chaos
def test_chaos_resume_matches_uninterrupted_run(tmp_path):
    """The acceptance scenario: periodic saves, one killed mid-write by
    chaos, 'process restart', restore_or_initialize() lands on the last
    valid checkpoint and the resumed run's losses equal the uninterrupted
    run's exactly."""
    x, y = _batch()

    base = _fused_step()
    ref = [float(base(x, y)) for _ in range(8)]

    m = CheckpointManager(str(tmp_path), keep_n=3)
    s1 = _fused_step()
    for i in range(1, 6):
        s1(x, y)
        if i == 3:
            m.save(i, state=s1.state)
        if i == 5:                         # preempted mid-save at step 5
            with chaos.session() as c:
                c.on("ckpt.mid_write", action="kill")
                with pytest.raises(chaos.ChaosKilled):
                    m.save(i, state=s1.state)
    del s1                                 # the process is gone

    s2 = _fused_step()                     # restart: fresh objects
    step, comp = m.restore_or_initialize()
    assert step == 3
    s2.state = restore_state(comp["state"])
    resumed = [float(s2(x, y)) for _ in range(5)]
    np.testing.assert_array_equal(resumed, ref[3:])


# ---------------------------------------------------------------------------
# BadStepGuard
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_guard_escalates_warn_rollback_raise_on_fused_step():
    step = _fused_step()
    x, y = _batch(1)
    guard = BadStepGuard(patience=3, policy=("warn", "rollback", "raise"),
                         snapshot_interval=2)
    guard.attach(step)
    for _ in range(5):
        step(x, y)
    guard.flush()
    assert guard.stats == {"observed": 5, "skipped": 0, "escalations": 0,
                           "rollbacks": 0}
    step_before_storm = int(step.state.step)

    with chaos.session() as c:
        c.on("train.step", action="nonfinite_grads", after=0, times=6)
        with pytest.warns(UserWarning, match="BadStepGuard"):
            for _ in range(6):
                step(x, y)
            guard.flush()
    assert guard.stats["skipped"] == 6
    assert guard.stats["escalations"] == 2     # warn, then rollback
    assert guard.stats["rollbacks"] == 1
    # rollback restored the last clean snapshot: the step counter is back
    # at (or before) the pre-storm count, never past it
    assert int(step.state.step) <= step_before_storm
    # ...but the halved loss scale is KEPT (no immediate re-entry)
    assert float(step.state.scaler.loss_scale) == 2.0 ** 16 / 2 ** 6

    with chaos.session() as c:
        c.on("train.step", action="nonfinite_grads", after=0, times=-1)
        with pytest.raises(TrainingDivergedError), \
                warnings.catch_warnings():
            warnings.simplefilter("ignore")
            for _ in range(8):
                step(x, y)
            guard.flush()


@pytest.mark.chaos
def test_guard_rollback_resumes_trainable_state():
    """After a rollback the step must keep training (state shapes/donation
    intact) and losses must be finite again once the storm passes."""
    step = _fused_step()
    x, y = _batch(2)
    guard = BadStepGuard(patience=2, policy="rollback", snapshot_interval=1)
    guard.attach(step)
    for _ in range(3):
        step(x, y)
    with chaos.session() as c:
        c.on("train.step", action="nonfinite_grads", after=0, times=2)
        with pytest.warns(UserWarning, match="BadStepGuard"):
            for _ in range(2):
                step(x, y)
            guard.flush()
    assert guard.stats["rollbacks"] == 1
    post = [float(step(x, y)) for _ in range(3)]
    guard.flush()
    assert np.all(np.isfinite(post))
    assert guard.stats["skipped"] == 2


def test_guard_policy_validation():
    with pytest.raises(ValueError):
        BadStepGuard(patience=0)
    with pytest.raises(ValueError):
        BadStepGuard(policy="retrain-from-scratch")
    with pytest.raises(ValueError):
        BadStepGuard(policy=())


def test_guard_single_stage_policy_is_sticky():
    g = BadStepGuard(patience=2, policy="warn")
    with pytest.warns(UserWarning, match="BadStepGuard"):
        for _ in range(8):
            g.observe(1)
    assert g.stats["escalations"] == 4     # every 2 skips, never raises


@pytest.mark.chaos
def test_guard_adds_no_step_cache_dispatches_on_clean_path():
    """Acceptance: the guard on the eager step-cache surface must not add
    dispatches (= no extra cached executables launched) to the clean-step
    hot path.  Runs the same loop with and without the guard and compares
    step_cache dispatch counts."""
    import apex_tpu.nn as nn
    from apex_tpu import amp
    from apex_tpu.amp._amp_state import reset
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.runtime import step_cache

    def loop(guarded, steps=6):
        reset()
        nn.manual_seed(7)
        model = nn.Sequential(nn.Linear(16, 32), nn.ReLU(),
                              nn.Linear(32, 4))
        opt = FusedAdam(list(model.parameters()), lr=1e-3)
        model, opt = amp.initialize(model, opt, opt_level="O2",
                                    verbosity=0, defer_scale_update=True)
        guard = BadStepGuard(patience=3, policy="raise")
        if guarded:
            guard.attach_optimizer(opt)
        crit = nn.CrossEntropyLoss()
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.standard_normal((8, 16)), jnp.float32)
        y = jnp.asarray(rng.integers(0, 4, (8,)))
        step_cache.reset_stats()
        for _ in range(steps):
            loss = crit(model(x), y)
            with amp.scale_loss(loss, opt) as scaled:
                scaled.backward()
            opt.step()
            opt.zero_grad()
        guard.flush()
        reset()
        return step_cache.stats()["dispatches"], guard

    base_dispatches, _ = loop(False)
    guarded_dispatches, guard = loop(True)
    assert guarded_dispatches == base_dispatches
    assert guard.stats["observed"] == 6 and guard.stats["skipped"] == 0


@pytest.mark.chaos
def test_guard_escalates_on_eager_overflow_storm():
    """Forced non-finite grads on the eager amp surface (chaos
    ``amp.backward`` hook) drive the scaler's real skip machinery and the
    guard's escalation."""
    import apex_tpu.nn as nn
    from apex_tpu import amp
    from apex_tpu.amp._amp_state import _amp_state, reset
    from apex_tpu.optimizers import FusedAdam

    reset()
    nn.manual_seed(7)
    model = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))
    opt = FusedAdam(list(model.parameters()), lr=1e-3)
    model, opt = amp.initialize(model, opt, opt_level="O2", verbosity=0,
                                defer_scale_update=True)
    guard = BadStepGuard(patience=3, policy=("warn", "raise"))
    guard.attach_optimizer(opt)
    crit = nn.CrossEntropyLoss()
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((8, 16)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 4, (8,)))

    with chaos.session() as c:
        c.on("amp.backward", action="nonfinite_grads", after=0, times=-1)
        with pytest.raises(TrainingDivergedError), \
                warnings.catch_warnings():
            warnings.simplefilter("ignore")
            for _ in range(10):
                loss = crit(model(x), y)
                with amp.scale_loss(loss, opt) as scaled:
                    scaled.backward()
                opt.step()
                opt.zero_grad()
            guard.flush()
    # the storm really went through the scaler: scale halved per skip
    assert _amp_state.loss_scalers[0].loss_scale() < 2.0 ** 16
    assert guard.stats["skipped"] >= 6
    reset()


# ---------------------------------------------------------------------------
# scaler edge dynamics the guard depends on
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_overflow_streak_clamps_at_min_loss_scale():
    """A streak longer than any patience keeps halving only down to the
    min_loss_scale floor — the state BadStepGuard escalates out of."""
    import apex_tpu.nn as nn
    from apex_tpu.nn import functional as F
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.training import make_train_step

    nn.manual_seed(5)
    model = nn.Sequential(nn.Linear(8, 8))
    opt = FusedAdam(list(model.parameters()), lr=1e-3)
    step = make_train_step(model, opt,
                           lambda o, t: F.cross_entropy(o, t),
                           half_dtype=jnp.float16, loss_scale="dynamic",
                           min_loss_scale=2.0 ** 10)
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.standard_normal((16, 8)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 8, (16,)))
    with chaos.session() as c:
        c.on("train.step", action="nonfinite_grads", after=0, times=-1)
        for _ in range(12):                # 12 > log2(2^16/2^10) = 6
            step(x, y)
    assert float(step.state.scaler.loss_scale) == 2.0 ** 10
    assert int(step.state.step) == 0       # every step skipped


def test_scale_window_doubling_boundary():
    """Growth fires at EXACTLY scale_window clean steps (and the counter
    resets); an overflow at window-1 resets the streak without growth."""
    from apex_tpu.amp import init_scaler_state, update_scale_state

    state = init_scaler_state("dynamic")
    for i in range(4):
        state, skip = update_scale_state(state, dynamic=True, scale_window=5)
        assert float(state.loss_scale) == 2.0 ** 16
    state, skip = update_scale_state(state, dynamic=True, scale_window=5)
    assert float(state.loss_scale) == 2.0 ** 17     # the boundary step
    assert int(state.unskipped) == 0

    # overflow one step short of the next window: halve + reset, no growth
    for i in range(4):
        state, _ = update_scale_state(state, dynamic=True, scale_window=5)
    state = state._replace(overflow=jnp.ones((), jnp.int32))
    state, skip = update_scale_state(state, dynamic=True, scale_window=5)
    assert bool(skip)
    assert float(state.loss_scale) == 2.0 ** 16
    assert int(state.unskipped) == 0


def test_long_streak_then_recovery_counts():
    """update_scale_state over an overflow streak longer than a guard's
    patience: scale halves per overflow (clamped), and the first clean
    step restarts the unskipped counter from zero."""
    from apex_tpu.amp import init_scaler_state, update_scale_state

    state = init_scaler_state("dynamic")
    for i in range(9):
        state = state._replace(overflow=jnp.ones((), jnp.int32))
        state, skip = update_scale_state(
            state, dynamic=True, min_loss_scale=2.0 ** 12)
        assert bool(skip)
    assert float(state.loss_scale) == 2.0 ** 12     # clamped after 4 halvings
    state, skip = update_scale_state(state, dynamic=True)
    assert not bool(skip)
    assert int(state.unskipped) == 1


# ---------------------------------------------------------------------------
# bounded-retry distributed init + collective timeout
# ---------------------------------------------------------------------------


def test_init_distributed_retries_until_success():
    from apex_tpu.parallel import distributed as D

    calls = []

    def stub(**kw):
        calls.append(kw)
        if len(calls) < 3:
            raise RuntimeError("coordinator not up yet")

    D.init_distributed(coordinator_address="host:1234", num_processes=2,
                       process_id=0, timeout_s=30, backoff_s=0.01,
                       _initialize=stub)
    assert len(calls) == 3
    # per-attempt timeout is capped by the remaining overall deadline
    assert all(kw["initialization_timeout"] <= 30 for kw in calls)
    assert calls[0]["coordinator_address"] == "host:1234"


def test_init_distributed_exhaustion_names_the_coordinator():
    from apex_tpu.parallel import distributed as D

    calls = []

    def stub(**kw):
        calls.append(kw)
        raise RuntimeError("connection refused")

    with pytest.raises(DistributedInitError) as ei:
        D.init_distributed(coordinator_address="badhost:99",
                           num_processes=4, process_id=2, timeout_s=5,
                           max_retries=2, backoff_s=0.01, _initialize=stub)
    assert len(calls) == 3                 # max_retries+1 attempts
    msg = str(ei.value)
    assert "badhost:99" in msg and "process_id=2" in msg \
        and "connection refused" in msg


def test_init_distributed_deadline_bounds_attempts():
    from apex_tpu.parallel import distributed as D

    def stub(**kw):
        raise RuntimeError("down")

    with pytest.raises(DistributedInitError):
        # zero budget: must raise immediately, not sleep through retries
        D.init_distributed(coordinator_address="h:1", num_processes=2,
                           process_id=0, timeout_s=0.0, _initialize=stub)


@pytest.mark.chaos
def test_init_distributed_absorbs_chaos_failures_and_dies_to_kill():
    from apex_tpu.parallel import distributed as D

    calls = []
    with chaos.session() as c:
        c.on("dist.init", action="fail", times=2)
        D.init_distributed(coordinator_address="h:1", num_processes=2,
                           process_id=0, timeout_s=30, backoff_s=0.01,
                           _initialize=lambda **kw: calls.append(kw))
    assert len(calls) == 1                 # two injected failures absorbed

    with chaos.session() as c:
        c.on("dist.init", action="kill")
        with pytest.raises(chaos.ChaosKilled):   # preemption ≠ flaky init
            D.init_distributed(coordinator_address="h:1", num_processes=2,
                               process_id=0, timeout_s=30, backoff_s=0.01,
                               _initialize=lambda **kw: None)


@pytest.mark.chaos
def test_timed_flat_dist_call_timeout_names_missing_ranks():
    from apex_tpu.parallel import distributed as D

    tensors = [jnp.ones((4,)), jnp.ones((2, 2))]
    D._PRESENCE_PROBE = lambda: [1, 3]
    try:
        with chaos.session() as c:
            c.on("dist.collective", action="delay", delay_s=5.0)
            with pytest.raises(CollectiveTimeoutError) as ei:
                D.timed_flat_dist_call(tensors, lambda t: t * 2,
                                       timeout_s=0.2)
        assert "[1, 3]" in str(ei.value)
    finally:
        D._PRESENCE_PROBE = None


def test_timed_flat_dist_call_passes_through():
    from apex_tpu.parallel import distributed as D

    tensors = [jnp.ones((4,)), jnp.full((2, 2), 3.0)]
    out = D.timed_flat_dist_call(tensors, lambda t: t * 2, timeout_s=30)
    np.testing.assert_array_equal(out[0], np.full((4,), 2.0))
    np.testing.assert_array_equal(out[1], np.full((2, 2), 6.0))


def test_timed_flat_dist_call_propagates_worker_errors():
    from apex_tpu.parallel import distributed as D

    def bad_call(t):
        raise ValueError("boom")

    with pytest.raises(ValueError, match="boom"):
        D.timed_flat_dist_call([jnp.ones((4,))], bad_call, timeout_s=30)
