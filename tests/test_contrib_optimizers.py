"""Contrib (deprecated-API) optimizers vs oracles.

Oracles mirror the reference test style: contrib FusedAdam against
torch.optim.Adam with the scale folded in by hand; the two-stage FusedLAMB
against a numpy LAMB; contrib FP16_Optimizer end-to-end (overflow skip,
half write-out, scale update).
"""
import jax.numpy as jnp
import numpy as np
import pytest
import torch

import apex_tpu.nn as nn
from apex_tpu.contrib.optimizers import (FP16_Optimizer, FusedAdam,
                                         FusedLAMB)
from apex_tpu.nn.parameter import Parameter


def _pairs(rng, shapes=((4, 3), (5,)), scale=1.0):
    ours, theirs = [], []
    for s in shapes:
        w = rng.standard_normal(s).astype(np.float32)
        g = rng.standard_normal(s).astype(np.float32)
        p = Parameter(jnp.asarray(w))
        p.grad = jnp.asarray(g * scale)   # scaled grads, legacy style
        ours.append(p)
        tp = torch.nn.Parameter(torch.tensor(w))
        tp.grad = torch.tensor(g)
        theirs.append(tp)
    return ours, theirs


@pytest.mark.parametrize("eps_inside_sqrt", [False, True])
def test_contrib_adam_matches_torch_with_scale(rng, eps_inside_sqrt):
    scale = 64.0
    ours, theirs = _pairs(rng, scale=scale)
    opt = FusedAdam(ours, lr=1e-2, weight_decay=0.0,
                    eps_inside_sqrt=eps_inside_sqrt)
    topt = torch.optim.Adam(theirs, lr=1e-2)
    for _ in range(3):
        opt.step(scale=scale)
        if not eps_inside_sqrt:
            topt.step()
    if eps_inside_sqrt:
        return  # torch has no eps-inside-sqrt mode; smoke only
    for p, tp in zip(ours, theirs):
        np.testing.assert_allclose(np.asarray(p.data),
                                   tp.detach().numpy(), rtol=2e-5, atol=2e-6)


def test_contrib_adam_weight_decay_matches_numpy_replay(rng):
    # the contrib kernel adds wd·p to the update AFTER the moments (unlike
    # torch Adam's grad-side L2), so the oracle is an explicit replay of
    # that rule (fused_adam_cuda_kernel: update = mhat/denom + decay*p)
    lr, b1, b2, eps, wd = 1e-2, 0.9, 0.999, 1e-8, 0.1
    w = rng.standard_normal((4, 3)).astype(np.float32)
    g1 = rng.standard_normal((4, 3)).astype(np.float32)
    g2 = rng.standard_normal((4, 3)).astype(np.float32)
    p = Parameter(jnp.asarray(w))
    opt = FusedAdam([p], lr=lr, weight_decay=wd)
    m = np.zeros_like(w)
    v = np.zeros_like(w)
    ref = w.copy()
    for t, g in enumerate([g1, g2], start=1):
        p.grad = jnp.asarray(g)
        opt.step()
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * g * g
        mhat = m / (1 - b1 ** t)
        vhat = v / (1 - b2 ** t)
        ref = ref - lr * (mhat / (np.sqrt(vhat) + eps) + wd * ref)
        np.testing.assert_allclose(np.asarray(p.data), ref,
                                   rtol=2e-5, atol=2e-6)


def test_contrib_adam_explicit_grads_and_output_params(rng):
    ours, _ = _pairs(rng)
    half_outs = [Parameter(p.data.astype(jnp.bfloat16)) for p in ours]
    grads = [p.grad for p in ours]
    for p in ours:
        p.grad = None
    opt = FusedAdam(ours, lr=1e-2)
    opt.step(grads=grads, output_params=half_outs, scale=1.0)
    for p, h in zip(ours, half_outs):
        assert h.data.dtype == jnp.bfloat16
        np.testing.assert_allclose(
            np.asarray(h.data, np.float32), np.asarray(p.data),
            rtol=1e-2, atol=1e-2)


def test_contrib_adam_max_grad_norm_clips(rng):
    w = rng.standard_normal((4, 3)).astype(np.float32)
    g = rng.standard_normal((4, 3)).astype(np.float32)
    gnorm = float(np.linalg.norm(g))

    def run(max_grad_norm, grad_norms):
        p = Parameter(jnp.asarray(w))
        p.grad = jnp.asarray(g)
        opt = FusedAdam([p], lr=1e-2, max_grad_norm=max_grad_norm)
        opt.step(grad_norms=grad_norms)
        return np.asarray(opt.state[p]["exp_avg"])

    # Adam's param update is nearly invariant to uniform grad scaling, so
    # the observable effect of the combined clip scale is on the moments:
    # grads divided by clip≈4 before entering exp_avg
    m_unclipped = run(0.0, None)
    m_clipped = run(gnorm / 4, [gnorm])
    np.testing.assert_allclose(m_clipped * 4.0, m_unclipped,
                               rtol=1e-4, atol=1e-6)


def test_contrib_lamb_matches_numpy_oracle(rng):
    shapes = [(4, 3), (6,)]
    ws = [rng.standard_normal(s).astype(np.float32) for s in shapes]
    gs = [rng.standard_normal(s).astype(np.float32) for s in shapes]
    params = [Parameter(jnp.asarray(w)) for w in ws]
    for p, g in zip(params, gs):
        p.grad = jnp.asarray(g)
    lr, b1, b2, eps, wd = 1e-2, 0.9, 0.999, 1e-6, 0.01
    opt = FusedLAMB(params, lr=lr, betas=(b1, b2), eps=eps, weight_decay=wd,
                    max_grad_norm=0.0)
    opt.step()
    ms = [np.zeros_like(w) for w in ws]
    vs = [np.zeros_like(w) for w in ws]
    for i, (w, g) in enumerate(zip(ws, gs)):
        m = b1 * ms[i] + (1 - b1) * g
        v = b2 * vs[i] + (1 - b2) * g * g
        u = (m / (1 - b1)) / (np.sqrt(v / (1 - b2)) + eps) + wd * w
        ratio = np.linalg.norm(w) / np.linalg.norm(u)
        exp = w - lr * ratio * u
        np.testing.assert_allclose(np.asarray(params[i].data), exp,
                                   rtol=3e-5, atol=3e-6)


def test_contrib_lamb_global_clip_changes_update(rng):
    shapes = [(4, 3)]
    w = rng.standard_normal(shapes[0]).astype(np.float32)
    g = 100.0 * rng.standard_normal(shapes[0]).astype(np.float32)

    def run(max_norm):
        p = Parameter(jnp.asarray(w))
        p.grad = jnp.asarray(g)
        opt = FusedLAMB([p], lr=1e-2, max_grad_norm=max_norm)
        opt.step()
        return np.asarray(opt.state[p]["exp_avg"])

    # the trust-ratio apply makes LAMB's param update scale-invariant, so
    # (as with Adam) the clip is observable in the moments: clip scale =
    # max_norm/gnorm divides the grads entering exp_avg
    gnorm = float(np.linalg.norm(g))
    m_clipped = run(1.0)
    m_unclipped = run(0.0)
    np.testing.assert_allclose(m_clipped * gnorm, m_unclipped,
                               rtol=1e-3, atol=1e-5)


def test_contrib_fp16_optimizer_end_to_end(rng):
    nn.manual_seed(0)
    x = jnp.asarray(rng.standard_normal((16, 8)), jnp.bfloat16)
    y = jnp.asarray(rng.standard_normal((16, 2)), jnp.float32)
    model = nn.Linear(8, 2)
    for p in model.parameters():
        p.data = p.data.astype(jnp.bfloat16)
    inner = FusedAdam(list(model.parameters()), lr=1e-2)
    opt = FP16_Optimizer(inner, static_loss_scale=128.0, verbose=False)

    losses = []
    for _ in range(20):
        opt.zero_grad()
        out = model(x)
        loss = ((out.float() - y) ** 2.0).mean()
        opt.backward(loss)
        opt.step()
        losses.append(float(loss.value))
    assert losses[-1] < losses[0] * 0.7
    # masters stay fp32, model stays bf16, and they track each other
    for g16, g32 in zip(opt.fp16_groups, opt.fp32_groups):
        for p16, p32 in zip(g16, g32):
            assert p16.data.dtype == jnp.bfloat16
            assert p32.data.dtype == jnp.float32
            np.testing.assert_allclose(
                np.asarray(p16.data, np.float32), np.asarray(p32.data),
                rtol=1e-2, atol=1e-2)


def test_contrib_fp16_optimizer_overflow_skips_and_halves(rng):
    nn.manual_seed(0)
    model = nn.Linear(4, 2)
    for p in model.parameters():
        p.data = p.data.astype(jnp.bfloat16)
    inner = FusedAdam(list(model.parameters()), lr=1e-2)
    opt = FP16_Optimizer(inner, dynamic_loss_scale=True,
                         dynamic_loss_args={"init_scale": 2 ** 8},
                         verbose=False)
    before = [np.asarray(p.data, np.float32).copy()
              for p in model.parameters()]
    for p in model.parameters():
        p.grad = jnp.full_like(p.data, jnp.inf)
    opt.step()
    assert opt.overflow
    assert opt.loss_scale == 2 ** 7  # halved
    for p, b in zip(model.parameters(), before):
        np.testing.assert_array_equal(np.asarray(p.data, np.float32), b)


def test_contrib_adam_per_param_bias_correction(rng):
    # param A frozen for 5 steps then unfrozen must not reset B's correction
    wa = rng.standard_normal((3,)).astype(np.float32)
    wb = rng.standard_normal((3,)).astype(np.float32)
    gb = rng.standard_normal((3,)).astype(np.float32)
    a, b = Parameter(jnp.asarray(wa)), Parameter(jnp.asarray(wb))
    opt = FusedAdam([a, b], lr=1e-2)
    for _ in range(5):
        a.grad = None
        b.grad = jnp.asarray(gb)
        opt.step()
    a.grad = jnp.asarray(gb)
    b.grad = jnp.asarray(gb)
    opt.step()
    assert opt.state[a]["step"] == 1 and opt.state[b]["step"] == 6
    # replay B alone: its trajectory must be unaffected by A's freeze
    b2 = Parameter(jnp.asarray(wb))
    opt2 = FusedAdam([b2], lr=1e-2)
    for _ in range(6):
        b2.grad = jnp.asarray(gb)
        opt2.step()
    np.testing.assert_allclose(np.asarray(b.data), np.asarray(b2.data),
                               rtol=1e-5, atol=1e-7)


def test_contrib_fp16_scale_growth_happens_after_step(rng):
    nn.manual_seed(0)
    model = nn.Linear(4, 2)
    for p in model.parameters():
        p.data = p.data.astype(jnp.bfloat16)
    inner = FusedAdam(list(model.parameters()), lr=0.0)  # lr=0: isolate m
    opt = FP16_Optimizer(inner, dynamic_loss_scale=True,
                         dynamic_loss_args={"init_scale": 4.0,
                                            "scale_window": 1},
                         verbose=False)
    # grads scaled by 4 (the scale backward would have applied)
    for p in model.parameters():
        p.grad = jnp.full_like(p.data, 4.0)
    opt.step()
    # window=1: scale doubles AFTER the step; the unscale must have used 4
    assert opt.loss_scale == 8.0
    for p in inner.param_groups[0]["params"]:
        np.testing.assert_allclose(np.asarray(inner.state[p]["exp_avg"]),
                                   0.1, rtol=1e-5)  # (1-b1)*g/4 = 0.1


def test_contrib_adam_bf16_output_no_f16_intermediate(rng):
    # a value valid in bf16 but above f16 max must survive the write-out
    w = np.full((2,), 70000.0, np.float32)
    p = Parameter(jnp.asarray(w))
    out = Parameter(jnp.zeros((2,), jnp.bfloat16))
    opt = FusedAdam([p], lr=1e-3)
    opt.step(grads=[jnp.zeros((2,), jnp.float32)], output_params=[out])
    assert np.isfinite(np.asarray(out.data, np.float32)).all()


def test_contrib_fp16_forwards_grad_norms_for_clipping(rng):
    nn.manual_seed(0)
    model = nn.Linear(4, 2)
    for p in model.parameters():
        p.data = p.data.astype(jnp.bfloat16)
    inner = FusedAdam(list(model.parameters()), lr=1e-2, max_grad_norm=1e-3)
    opt = FP16_Optimizer(inner, static_loss_scale=1.0, verbose=False)
    for p in model.parameters():
        p.grad = jnp.ones_like(p.data)
    opt.step()
    # with grad_norms forwarded, the clip divides moments by clip>>1
    for p in inner.param_groups[0]["params"]:
        m = np.abs(np.asarray(inner.state[p]["exp_avg"]))
        assert m.max() < 0.01  # unclipped would be (1-b1)*1 = 0.1
