"""Expert-parallel Switch MoE (parallel/expert_parallel.py) vs a dense
single-device oracle replicating the same routing math, on the CPU mesh."""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.parallel import switch_moe

D, DFF, TLOC = 8, 16, 12


def _mesh(n):
    return Mesh(np.array(jax.devices()[:n]), ("ep",))


def _expert_fn(params, x):
    w1, w2 = params
    return jnp.maximum(x @ w1[0], 0) @ w2[0]


def _weights(rng, n):
    router = jnp.asarray(rng.standard_normal((D, n)), jnp.float32)
    w1 = jnp.asarray(rng.standard_normal((n, D, DFF)) * 0.3, jnp.float32)
    w2 = jnp.asarray(rng.standard_normal((n, DFF, D)) * 0.3, jnp.float32)
    return router, w1, w2


def _dense_oracle(x_shards, router, w1, w2, capacity_factor):
    """Same routing math per source shard, dense expert apply."""
    n = w1.shape[0]
    outs = []
    for xs in x_shards:
        t_loc = xs.shape[0]
        probs = jax.nn.softmax(xs @ router, axis=-1)
        eidx = np.asarray(jnp.argmax(probs, axis=-1))
        gate = np.asarray(jnp.take_along_axis(
            probs, jnp.asarray(eidx)[:, None], axis=-1)[:, 0])
        import math
        cap = max(1, math.ceil(t_loc / n * capacity_factor))
        counts = {e: 0 for e in range(n)}
        y = np.zeros_like(np.asarray(xs))
        for t in range(t_loc):
            e = int(eidx[t])
            if counts[e] < cap:
                counts[e] += 1
                h = np.maximum(np.asarray(xs[t]) @ np.asarray(w1[e]), 0)
                y[t] = (h @ np.asarray(w2[e])) * gate[t]
        outs.append(y)
    return np.concatenate(outs, axis=0)


@pytest.mark.parametrize("n,capacity_factor", [(4, 4.0), (8, 4.0),
                                               (4, 0.5)])
def test_switch_moe_matches_dense_oracle(rng, n, capacity_factor):
    """capacity_factor 4.0: nothing dropped — exact dense equality.
    0.5: overflow tokens must come back as exactly zero."""
    mesh = _mesh(n)
    router, w1, w2 = _weights(rng, n)
    x = jnp.asarray(rng.standard_normal((n * TLOC, D)), jnp.float32)

    def f(x, router, w1, w2):
        y, _aux = switch_moe(x, router, (w1, w2), _expert_fn, "ep",
                             capacity_factor=capacity_factor)
        return y

    got = jax.jit(jax.shard_map(
        f, mesh=mesh, in_specs=(P("ep"), P(), P("ep"), P("ep")),
        out_specs=P("ep"), check_vma=False))(x, router, w1, w2)
    want = _dense_oracle(
        [x[i * TLOC:(i + 1) * TLOC] for i in range(n)],
        router, w1, w2, capacity_factor)
    np.testing.assert_allclose(np.asarray(got), want, rtol=3e-5, atol=3e-5)


def test_switch_moe_grads_flow_to_router_and_experts(rng):
    n = 4
    mesh = _mesh(n)
    router, w1, w2 = _weights(rng, n)
    x = jnp.asarray(rng.standard_normal((n * TLOC, D)), jnp.float32)
    w_out = jnp.asarray(rng.standard_normal(x.shape), jnp.float32)

    def loss(router, w1, w2):
        def f(x, router, w1, w2):
            y, _aux = switch_moe(x, router, (w1, w2), _expert_fn, "ep",
                                 capacity_factor=4.0)
            return y
        shard = jax.shard_map(
            f, mesh=mesh, in_specs=(P("ep"), P(), P("ep"), P("ep")),
            out_specs=P("ep"), check_vma=False)
        return jnp.sum(shard(x, router, w1, w2) * w_out)

    g = jax.jit(jax.grad(loss, argnums=(0, 1, 2)))(router, w1, w2)
    for name, arr in zip(("router", "w1", "w2"), g):
        a = np.asarray(arr)
        assert np.isfinite(a).all(), name
        assert np.abs(a).max() > 0, f"no gradient reached {name}"


def test_switch_moe_rejects_mismatched_expert_count(rng):
    mesh = _mesh(4)
    router = jnp.asarray(rng.standard_normal((D, 6)), jnp.float32)
    w1 = jnp.asarray(rng.standard_normal((4, D, DFF)), jnp.float32)
    w2 = jnp.asarray(rng.standard_normal((4, DFF, D)), jnp.float32)
    x = jnp.asarray(rng.standard_normal((4 * TLOC, D)), jnp.float32)

    def f(x, router, w1, w2):
        return switch_moe(x, router, (w1, w2), _expert_fn, "ep")[0]

    with pytest.raises(Exception):
        jax.jit(jax.shard_map(
            f, mesh=mesh, in_specs=(P("ep"), P(), P("ep"), P("ep")),
            out_specs=P("ep"), check_vma=False))(x, router, w1, w2)
