"""A REAL multi-process distributed test (VERDICT r2 missing #4): two
``jax.distributed``-initialized CPU processes spawned through the
``apex_tpu.parallel.multiproc`` launcher, gloo collectives between them,
each feeding its own half of the batch to the DP fused step.

Fails if ``init_distributed`` / the launcher's env plumbing
(APEX_TPU_COORDINATOR / _NUM_PROCESSES / _PROCESS_ID) breaks, if
cross-process collectives diverge, or if the two processes' updated
master parameters drift.  Reference analogue:
/root/reference/tests/distributed/amp_master_params/run.sh:2 (2-process
``torch.distributed.launch`` + master-param equality assertions).
"""
import os
import subprocess
import sys

import numpy as np

REPO = os.path.abspath(os.path.join(os.path.dirname(__file__), "..", ".."))


def test_two_process_dp_step_grads_agree(tmp_path):
    env = dict(os.environ, PYTHONPATH=REPO,
               APEX_TPU_COORD_PORT="12517")
    # children pin their own platform/devices
    env.pop("XLA_FLAGS", None)
    env.pop("JAX_PLATFORMS", None)
    worker = os.path.join(REPO, "tests", "distributed",
                          "two_process_worker.py")
    out = subprocess.run(
        [sys.executable, "-m", "apex_tpu.parallel.multiproc",
         "--nproc", "2", worker, "--outdir", str(tmp_path)],
        capture_output=True, text=True, timeout=280, env=env)
    assert out.returncode == 0, \
        f"stdout: {out.stdout[-1500:]}\nstderr: {out.stderr[-1500:]}"

    r0 = np.load(tmp_path / "rank0.npz")
    r1 = np.load(tmp_path / "rank1.npz")

    # the DP state is replicated: after psum-averaged gradient steps both
    # processes must hold bit-identical master parameters
    assert np.array_equal(r0["m0"], r1["m0"]), \
        np.abs(r0["m0"] - r1["m0"]).max()

    # each process reports its own half-batch loss; the global mean must
    # match a single-process oracle on the full batch
    import jax
    import jax.numpy as jnp

    import apex_tpu.nn as nn
    from apex_tpu.nn import functional as F
    from apex_tpu.optimizers import FusedSGD
    from apex_tpu.training import make_train_step

    nn.manual_seed(7)
    model = nn.Sequential(nn.Linear(16, 32), nn.GELU(), nn.Linear(32, 8))
    opt = FusedSGD(list(model.parameters()), lr=0.05, momentum=0.9)
    step = make_train_step(model, opt,
                           lambda o, t: F.cross_entropy(o, t),
                           half_dtype=jnp.bfloat16, loss_scale=1.0)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((8, 16)).astype(np.float32))
    y = jnp.asarray(rng.integers(0, 8, (8,)).astype(np.int32))
    ref_losses = [float(step(x, y)) for _ in range(len(r0["losses"]))]

    mean_losses = (r0["losses"] + r1["losses"]) / 2
    np.testing.assert_allclose(mean_losses, ref_losses, rtol=2e-2,
                               atol=2e-2)
    ref_m0 = np.asarray(step.state.master_params[0])
    np.testing.assert_allclose(r0["m0"], ref_m0, rtol=2e-2, atol=2e-2)
