"""Worker for the REAL 2-process distributed test (launched by
``apex_tpu.parallel.multiproc``): each process owns one CPU device,
``init_distributed()`` wires them through ``jax.distributed``, and a DP
fused train step runs over the global 2-device mesh with each process
feeding its own half of the batch.

Writes ``rank<i>.npz`` (losses + the first fp32 master parameter after
training) into ``--outdir``; the parent test asserts cross-process
equality and parity with a single-process oracle — the
``tests/distributed/test_amp_master_params.py`` oracle, actually
multi-process (reference analogue:
/root/reference/tests/distributed/amp_master_params/run.sh:2, which runs
``torch.distributed.launch`` with 2 GPUs).
"""
import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--outdir", required=True)
    ap.add_argument("--steps", type=int, default=4)
    ap.add_argument("--local_rank", type=int, default=0)
    args = ap.parse_args()

    import jax
    # the axon TPU plugin ignores JAX_PLATFORMS; pin CPU via config (the
    # tests/conftest.py trick), one local CPU device per process
    jax.config.update("jax_platforms", "cpu")
    # cross-process collectives on the CPU backend ride gloo
    jax.config.update("jax_cpu_collectives_implementation", "gloo")

    from apex_tpu.parallel import init_distributed
    init_distributed()   # consumes APEX_TPU_* exported by the launcher

    assert jax.process_count() == 2, jax.process_count()
    assert len(jax.devices()) == 2, jax.devices()
    rank = jax.process_index()

    import jax.numpy as jnp
    import numpy as np
    from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

    import apex_tpu.nn as nn
    from apex_tpu.nn import functional as F
    from apex_tpu.optimizers import FusedSGD
    from apex_tpu.training import make_train_step

    nn.manual_seed(7)
    model = nn.Sequential(nn.Linear(16, 32), nn.GELU(), nn.Linear(32, 8))
    opt = FusedSGD(list(model.parameters()), lr=0.05, momentum=0.9)
    step = make_train_step(model, opt,
                           lambda o, t: F.cross_entropy(o, t),
                           half_dtype=jnp.bfloat16, loss_scale=1.0,
                           axis_name="data")

    mesh = Mesh(np.array(jax.devices()), ("data",))
    sharded = jax.jit(jax.shard_map(
        step._step_fn, mesh=mesh,
        in_specs=(P(), P("data"), P("data")),
        out_specs=(P(), P()), check_vma=False))

    # deterministic global batch; THIS process materializes only its own
    # half and contributes it as its device's shard of the global array
    rng = np.random.default_rng(0)
    xg = rng.standard_normal((8, 16)).astype(np.float32)
    yg = rng.integers(0, 8, (8,))
    bsh = NamedSharding(mesh, P("data"))

    def globalize(arr):
        local = arr[rank * 4:(rank + 1) * 4]
        return jax.make_array_from_process_local_data(
            bsh, local, arr.shape)

    x, y = globalize(xg), globalize(yg.astype(np.int32))

    # the state is replicated: every leaf must become a global array
    # before the multi-process jit consumes it
    rep = NamedSharding(mesh, P())
    state = jax.tree.map(
        lambda a: jax.make_array_from_callback(
            a.shape, rep, lambda idx: np.asarray(a)[idx]), step.state)

    losses = []
    for _ in range(args.steps):
        state, loss = sharded(state, x, y)
        losses.append(float(loss))   # fully-replicated: fetchable anywhere

    # the first master param is replicated; this process's addressable
    # shard is the full array
    m0 = np.asarray(state.master_params[0].addressable_data(0))
    np.savez(os.path.join(args.outdir, f"rank{rank}.npz"),
             losses=np.asarray(losses), m0=m0)
    print(f"rank {rank}: ok, losses={losses}")


if __name__ == "__main__":
    main()
