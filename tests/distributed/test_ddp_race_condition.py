"""DDP stress test with analytic expected gradients (reference:
tests/distributed/DDP/ddp_race_condition_test.py:28-70 — message_size=1,
allreduce_trigger_params, multiple comm streams, exact per-iteration grad
sums).

The reference stresses overlap races between its grad-arrival hooks and the
NCCL streams.  Under XLA the exchange is compiled — there are no streams to
race — but the *observable contract* is identical and is what we assert:
with the same aggressive knobs, every iteration's gradient must equal the
analytic batch-mean value exactly, and params must remain bit-identical
(replicated) across the mesh.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

import apex_tpu.nn as nn
from apex_tpu.parallel import DistributedDataParallel


def _mesh():
    return Mesh(np.array(jax.devices()), ("data",))


class TwoLayer(nn.Module):
    def __init__(self):
        super().__init__()
        self.a = nn.Linear(4096, 2, bias=False)
        self.b = nn.Linear(4096, 2, bias=False)

    def forward(self, ctx, x):
        from apex_tpu.nn import functional as F
        return F.linear(x, ctx.value(self.a.weight)) + \
            F.linear(x, ctx.value(self.b.weight))


@pytest.mark.parametrize("delay_allreduce", [False, True])
def test_race_condition_analytic_grads(delay_allreduce):
    """Iteration-exact analytic grads under the reference's stress knobs."""
    nn.manual_seed(0)
    model = TwoLayer()
    for p in model.parameters():
        p.data = jnp.zeros_like(p.data)
    kwargs = dict(message_size=1)  # ship every bucket immediately
    if not delay_allreduce:
        kwargs.update(num_allreduce_streams=2,
                      allreduce_trigger_params=[model.a.weight])
    ddp = DistributedDataParallel(model, mesh=_mesh(),
                                  delay_allreduce=delay_allreduce, **kwargs)
    n_dev = jax.device_count()
    batch = 2 * n_dev

    for i in range(1, 5):
        # x[j] = (i + j) everywhere: grad of sum(out) wrt each weight row
        # is mean_j x[j] = i + (batch-1)/2, exactly representable
        x = jnp.broadcast_to(
            jnp.arange(batch, dtype=jnp.float32)[:, None] + i,
            (batch, 4096))
        out = ddp(x)
        loss = out.sum() * (1.0 / batch)
        loss.backward()
        expected = i + (batch - 1) / 2.0
        for name, p in [("a", model.a.weight), ("b", model.b.weight)]:
            g = np.asarray(p.grad)
            np.testing.assert_array_equal(
                g, np.full_like(g, expected),
                err_msg=f"iter {i} param {name}")
            assert p.grad.sharding.is_fully_replicated
            p.grad = None


def test_trigger_params_with_delay_rejected():
    nn.manual_seed(0)
    model = TwoLayer()
    with pytest.raises(ValueError):
        DistributedDataParallel(model, delay_allreduce=True,
                                allreduce_trigger_params=[model.a.weight],
                                mesh=_mesh())
