"""O2 + DDP master-param consistency (reference:
tests/distributed/amp_master_params/ — after training, params must be equal
across ranks and model halves must equal master fp32 within rtol .005,
compare.py:12-26).

On the SPMD mesh "cross-rank equality" is replication: every param/master
must be fully-replicated (one logical value on all devices) after real
training steps, and the bf16 model copy must track the fp32 masters.
"""
import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh

import apex_tpu.nn as nn
from apex_tpu import amp
from apex_tpu.optimizers import FusedSGD
from apex_tpu.parallel import DistributedDataParallel


def test_master_params_replicated_and_track_model():
    nn.manual_seed(0)
    model = nn.Sequential(nn.Linear(10, 16), nn.ReLU(), nn.Linear(16, 4))
    opt = FusedSGD(list(model.parameters()), lr=0.1, momentum=0.9)
    model, opt = amp.initialize(model, opt, opt_level="O2",
                                cast_model_type=jnp.bfloat16,
                                loss_scale=128.0, verbosity=0)
    ddp = DistributedDataParallel(model, mesh=Mesh(
        np.array(jax.devices()), ("data",)))

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((16, 10)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 4, (16,)))
    crit = nn.CrossEntropyLoss()
    for _ in range(5):
        out = ddp(x)
        loss = crit(out, y)
        with amp.scale_loss(loss, opt) as scaled:
            scaled.backward()
        opt.step()
        opt.zero_grad()

    masters = list(amp.master_params(opt))
    assert masters, "O2 must expose fp32 masters"
    model_params = [p for p in model.parameters()]
    assert len(masters) == len(model_params)
    for mp, p in zip(masters, model_params):
        # cross-"rank" equality: fully replicated on the mesh
        assert mp.data.sharding.is_fully_replicated
        assert p.data.sharding.is_fully_replicated
        assert mp.data.dtype == jnp.float32
        assert p.data.dtype == jnp.bfloat16
        # model == master.half() within the reference tolerance (0.005)
        np.testing.assert_allclose(
            np.asarray(p.data, np.float32), np.asarray(mp.data),
            rtol=5e-3, atol=5e-3)
        # and the halves are EXACTLY the cast of the masters (the step
        # writes both in one pass)
        np.testing.assert_array_equal(
            np.asarray(p.data),
            np.asarray(mp.data.astype(jnp.bfloat16)))
