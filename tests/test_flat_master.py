"""flat_master=True: the flat-buffer fused update must be numerically
identical to the per-tensor path — same losses, same synced params —
across SGD/Adam, bf16 half casts with BN-fp32 keep, dynamic-scaler skip
steps, and grad accumulation; invalid configs refuse loudly."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import apex_tpu.nn as nn
from apex_tpu.nn import functional as F
from apex_tpu.optimizers import FusedAdam, FusedLAMB, FusedSGD
from apex_tpu.training import make_train_step


class Net(nn.Module):
    def __init__(self):
        super().__init__()
        self.c = nn.Conv2d(3, 8, 3, padding=1)
        self.bn = nn.BatchNorm2d(8)
        self.fc = nn.Linear(8 * 4 * 4, 5)

    def forward(self, ctx, x):
        h = F.relu(self.bn.forward(ctx, self.c.forward(ctx, x)))
        return self.fc.forward(ctx, h.reshape(h.shape[0], -1))


def _build(opt_cls, flat, seed=11, **opt_kw):
    nn.manual_seed(seed)
    m = Net()
    opt = opt_cls(list(m.parameters()), **opt_kw)
    return m, opt


def _loss(out, y):
    return F.cross_entropy(out, y)


@pytest.mark.parametrize("opt_cls,opt_kw", [
    (FusedSGD, dict(lr=0.1, momentum=0.9, weight_decay=1e-4)),
    (FusedAdam, dict(lr=1e-3, weight_decay=0.01)),
])
@pytest.mark.parametrize("half", [None, jnp.bfloat16])
def test_flat_matches_per_tensor(rng, opt_cls, opt_kw, half):
    """fp32 steps must match tightly (the update math is identical);
    bf16 steps to bf16-training tolerance — the flat program's conv
    gradient reductions legitimately reassociate (XLA tiles the two
    programs differently), which shifts bf16 casts by an ulp."""
    x = jnp.asarray(rng.standard_normal((4, 3, 4, 4)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 5, (4,)))
    # f32: the only noise is conv-grad reduction reassociation between
    # the two program structures, amplified by Adam's early rsqrt(v)
    tol = dict(rtol=5e-4, atol=1e-6) if half is None \
        else dict(rtol=1e-2, atol=2e-3)

    steps = {}
    for flat in (False, True):
        m, opt = _build(opt_cls, flat, **opt_kw)
        s = make_train_step(m, opt, _loss, half_dtype=half,
                            loss_scale=1.0, flat_master=flat)
        losses = [float(s(x, y)) for _ in range(4)]
        s.sync_to_objects()
        steps[flat] = (losses, [np.asarray(p.data, np.float32)
                                for p in m.parameters()])

    np.testing.assert_allclose(steps[True][0], steps[False][0], **tol)
    for a, b in zip(steps[True][1], steps[False][1]):
        np.testing.assert_allclose(a, b, **tol)


def test_flat_dynamic_scaler_skip(rng):
    """An inf gradient must trip the overflow flag and skip the update
    on the flat path exactly as on the per-tensor path."""
    x = jnp.asarray(rng.standard_normal((4, 3, 4, 4)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 5, (4,)))

    def bad_loss(out, y_):
        return F.cross_entropy(out, y_) * jnp.float32(1e38) * 1e38

    m, opt = _build(FusedSGD, True, lr=0.1, momentum=0.9)
    s = make_train_step(m, opt, bad_loss, half_dtype=jnp.bfloat16,
                        loss_scale="dynamic", flat_master=True)
    before = np.asarray(s.state.master_params[0])
    scale0 = float(s.state.scaler.loss_scale)
    s(x, y)
    after = np.asarray(s.state.master_params[0])
    np.testing.assert_array_equal(after, before)        # update skipped
    assert float(s.state.scaler.loss_scale) < scale0    # scale backed off
    assert int(s.state.step) == 0


def test_flat_grad_accum_matches(rng):
    x = jnp.asarray(rng.standard_normal((8, 3, 4, 4)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 5, (8,)))
    losses = {}
    for flat in (False, True):
        m, opt = _build(FusedAdam, flat, lr=1e-3)
        s = make_train_step(m, opt, _loss, half_dtype=None,
                            loss_scale=1.0, grad_accum_steps=2,
                            flat_master=flat)
        losses[flat] = [float(s(x, y)) for _ in range(3)]
    np.testing.assert_allclose(losses[True], losses[False], rtol=2e-5)


def test_flat_multi_group_hyperparams(rng):
    """Per-group lr/wd stay per-group through the flat buffers."""
    x = jnp.asarray(rng.standard_normal((4, 3, 4, 4)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 5, (4,)))
    final = {}
    for flat in (False, True):
        nn.manual_seed(5)
        m = Net()
        ps = list(m.parameters())
        opt = FusedSGD([
            {"params": ps[:2], "lr": 0.05},
            {"params": ps[2:], "lr": 0.2, "weight_decay": 1e-3},
        ], lr=0.1, momentum=0.9)
        s = make_train_step(m, opt, _loss, half_dtype=None,
                            loss_scale=1.0, flat_master=flat)
        for _ in range(3):
            s(x, y)
        s.sync_to_objects()
        final[flat] = [np.asarray(p.data, np.float32) for p in ps]
    for a, b in zip(final[True], final[False]):
        np.testing.assert_allclose(a, b, rtol=2e-5, atol=1e-6)


def test_flat_refuses_lamb():
    m, opt = _build(FusedLAMB, True, lr=1e-3)
    with pytest.raises(TypeError, match="trust ratio"):
        make_train_step(m, opt, _loss, flat_master=True)


def test_flat_refuses_frozen_params():
    nn.manual_seed(2)
    m = Net()
    ps = list(m.parameters())
    opt = FusedSGD(ps[:-1], lr=0.1)     # last param frozen
    with pytest.raises(ValueError, match="param_group"):
        make_train_step(m, opt, _loss, flat_master=True)


def test_flat_refuses_zero():
    m, opt = _build(FusedSGD, True, lr=0.1)
    with pytest.raises(ValueError, match="zero_sharding"):
        make_train_step(m, opt, _loss, flat_master=True,
                        zero_sharding=True)


def test_flat_with_lr_schedule_matches(rng):
    """flat_master composes with on-device lr schedules (the lr_scale
    path through build_opt_update_flat)."""
    from apex_tpu.optimizers import warmup_cosine

    x = jnp.asarray(rng.standard_normal((4, 3, 4, 4)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 5, (4,)))
    final = {}
    for flat in (False, True):
        m, opt = _build(FusedAdam, flat, lr=1e-3)
        s = make_train_step(m, opt, _loss, half_dtype=None,
                            loss_scale=1.0, flat_master=flat,
                            lr_schedule=warmup_cosine(2, 10))
        for _ in range(4):
            s(x, y)
        s.sync_to_objects()
        final[flat] = [np.asarray(p.data, np.float32)
                       for p in m.parameters()]
    # conv-grad reassociation noise amplified by Adam's early rsqrt(v)
    # compounds over 4 scheduled steps; a missing lr_scale would
    # diverge by orders of magnitude, not 1e-3
    for a, b in zip(final[True], final[False]):
        np.testing.assert_allclose(a, b, rtol=5e-3, atol=1e-6)


def test_flat_state_checkpoint_roundtrip(rng, tmp_path):
    """save_train_state/restore_train_state round-trip the flat
    (stacked-bucket) StepState — training resumes bit-identically."""
    from apex_tpu.utils import restore_train_state, save_train_state

    x = jnp.asarray(rng.standard_normal((4, 3, 4, 4)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 5, (4,)))
    m, opt = _build(FusedSGD, True, lr=0.1, momentum=0.9)
    s = make_train_step(m, opt, _loss, half_dtype=jnp.bfloat16,
                        loss_scale=1.0, flat_master=True)
    s(x, y)
    path = str(tmp_path / "flat_ckpt")
    save_train_state(path, s)

    loss_next = float(s(x, y))
    m2, opt2 = _build(FusedSGD, True, lr=0.1, momentum=0.9)
    s2 = make_train_step(m2, opt2, _loss, half_dtype=jnp.bfloat16,
                         loss_scale=1.0, flat_master=True)
    restore_train_state(path, s2)
    assert int(s2.state.step) == 1
    loss_resumed = float(s2(x, y))
    np.testing.assert_allclose(loss_resumed, loss_next, rtol=1e-6)


def test_flat_under_dp_shard_map(rng):
    """flat_master composes with axis_name DP: grads psum per-tensor
    BEFORE bucket stacking, so the sharded step matches the
    single-device full-batch step."""
    import jax
    from jax.sharding import Mesh, PartitionSpec as P

    devs = jax.devices()[:4]
    mesh = Mesh(np.array(devs), ("data",))
    x = jnp.asarray(rng.standard_normal((8, 3, 4, 4)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 5, (8,)))

    m1, o1 = _build(FusedSGD, True, lr=0.1, momentum=0.9)
    ref = make_train_step(m1, o1, _loss, half_dtype=None,
                          loss_scale=1.0, flat_master=True)
    ref_losses = [float(ref(x, y)) for _ in range(3)]

    nn.manual_seed(11)
    m2 = Net()
    from apex_tpu.parallel import convert_syncbn_model
    m2 = convert_syncbn_model(m2)   # full-batch BN semantics across shards
    o2 = FusedSGD(list(m2.parameters()), lr=0.1, momentum=0.9)
    dp = make_train_step(m2, o2, _loss, half_dtype=None,
                         loss_scale=1.0, flat_master=True,
                         axis_name="data")
    sharded = jax.jit(jax.shard_map(
        dp._step_fn, mesh=mesh,
        in_specs=(P(), P("data"), P("data")),
        out_specs=(P(), P()), check_vma=False))
    state = dp.state
    dp_losses = []
    for _ in range(3):
        state, loss = sharded(state, x, y)
        dp_losses.append(float(loss))
    # per-shard mean losses average to the full-batch mean only when
    # shards are homogeneous; compare the training trajectory through
    # the PARAMS instead (psum-averaged grads == full-batch grads)
    np.testing.assert_allclose(
        np.asarray(state.master_params[0]),
        np.asarray(ref.state.master_params[0]), rtol=2e-5, atol=1e-6)
