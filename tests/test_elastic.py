"""Elastic training end to end (ROADMAP item 3): schema-3 manifests
with sharding layout + plan identity + per-shard file streaming,
cross-plan reshard-on-restore (ZeRO-3 dp8 → dp2×tp4, masters
bit-exact), legacy schema-1/2 compatibility, and the chaos-driven
preempt→shrink→replan→resume→regrow cycle on the 8-CPU-device mesh."""
import pickle
import warnings
import zlib

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import apex_tpu.nn as nn
from apex_tpu.nn import functional as F
from apex_tpu.optimizers import FusedAdam, FusedSGD
from apex_tpu.parallel import auto
from apex_tpu.runtime import CheckpointManager, chaos, resilience
from apex_tpu.runtime.elastic import (ElasticTrainer, current_devices,
                                      elastic_restore)
from apex_tpu.runtime.resilience import (CheckpointReshardError,
                                         reshard_state)
from apex_tpu.training import make_train_step

pytestmark = pytest.mark.elastic

DIM, CLASSES = 16, 10


@pytest.fixture(autouse=True)
def _no_leftover_controller():
    yield
    chaos.uninstall()


def _mlp(seed=0):
    nn.manual_seed(seed)
    model = nn.Sequential(nn.Linear(DIM, 32), nn.GELU(),
                          nn.Linear(32, CLASSES))
    opt = FusedSGD(list(model.parameters()), lr=0.1, momentum=0.9)
    return model, opt


def _loss(o, t):
    return F.cross_entropy(o, t)


def _batch(seed, b=8):
    rng = np.random.default_rng(seed)
    return (jnp.asarray(rng.standard_normal((b, DIM)), jnp.float32),
            jnp.asarray(rng.integers(0, CLASSES, (b,))))


#: pin the plan family so shrink/regrow trajectories are deterministic:
#: pure data-parallel over every surviving device, ZeRO-1, no accum
def _dp_only(p):
    return (p.dp == p.n_devices and p.zero_stage == 1 and p.accum == 1
            and not p.chunked_loss)


def _trainer(path, seed=0, **kw):
    model, opt = _mlp(seed)
    kw.setdefault("plan_filter", _dp_only)
    return ElasticTrainer(str(path), model, opt, _loss,
                          example_batch=_batch(0), half_dtype=None,
                          loss_scale=1.0, **kw)


# ---------------------------------------------------------------------------
# device.loss chaos hook
# ---------------------------------------------------------------------------


def test_device_loss_hook_shrinks_then_disarms():
    n = len(jax.devices())
    with chaos.session(seed=0) as c:
        c.on("device.loss", action=lambda ctx: ctx["n"] // 2, at=0)
        assert len(current_devices()) == n // 2
        # one-shot fault: the next detection sees the full mesh again
        assert len(current_devices()) == n
        assert c.log[0][0] == "device.loss"
    assert len(current_devices()) == n      # no controller, no filtering


def test_device_loss_hook_explicit_list_and_validation():
    devs = jax.devices()
    with chaos.session(seed=0) as c:
        c.on("device.loss", action=lambda ctx: list(ctx["devices"][2:5]),
             at=0)
        assert current_devices() == list(devs[2:5])
    with chaos.session(seed=0) as c:
        c.on("device.loss", action=lambda ctx: 0, at=0)
        with pytest.raises(ValueError, match="device.loss"):
            current_devices()


# ---------------------------------------------------------------------------
# schema 3 manifest: layout + plan metadata + shard streaming, legacy compat
# ---------------------------------------------------------------------------


def test_manifest_v3_records_layout_plan_and_streaming(tmp_path):
    model, opt = _mlp()
    plan = auto.Plan(dp=8, zero_stage=3, n_devices=8)
    step = make_train_step(model, opt, _loss, half_dtype=None,
                           loss_scale=1.0, parallel=plan)
    step(*_batch(1))
    mgr = CheckpointManager(str(tmp_path), keep_n=2)
    mgr.save_sharded(0, step, epoch=3)

    comps, manifest = resilience.read_checkpoint_file(
        mgr.path_for(0), return_manifest=True)
    assert manifest["schema"] == 3
    assert manifest["plan"]["key"] == list(plan.key())
    assert manifest["plan"]["zero_stage"] == 3
    assert manifest["plan"]["n_devices"] == 8
    # plan_from_key round-trips the structural identity
    rebuilt = auto.plan_from_key(manifest["plan"]["key"],
                                 manifest["plan"]["n_devices"])
    assert rebuilt.key() == plan.key()

    layout = manifest["components"]["state"]["layout"]
    assert layout["mesh_axes"] == ["data"]
    assert layout["mesh_shape"] == [8]
    # ZeRO-3: dim-0-divisible leaves carry the "data" partition spec
    assert any(spec == ["data"] for spec in layout["specs"])
    # schema-1 integrity fields unchanged
    meta = manifest["components"]["state"]
    assert meta["nbytes"] > 0 and isinstance(meta["crc32"], int)
    # non-array components carry no layout (and no shard files)
    assert "layout" not in manifest["components"]["epoch"]
    assert "streamed" not in manifest["components"]["epoch"]
    assert comps["epoch"] == 3
    # schema 3: the state's bytes live in per-shard files under
    # ckpt_<step>.shards/, and the manifest's "streamed" entry is how
    # the reader resolves them
    streamed = manifest["components"]["state"]["streamed"]
    sdir = mgr.shard_dir_for(0)
    assert streamed["dir"] == resilience.os.path.basename(sdir)
    first = next(m for m in streamed["leaves"] if m is not None)
    for sh in first["shards"]:
        assert resilience.os.path.exists(
            resilience.os.path.join(sdir, sh["file"]))
    # ... while read_checkpoint_file still hands back full host arrays
    # (assembled from the shard files)
    host = comps["state"]
    assert host.master_params[0].shape == \
        tuple(step.state.master_params[0].shape)


def _write_schema1(path, components):
    """A byte-accurate schema-1 (pre-layout) container, as the previous
    release wrote them."""
    payload = {k: pickle.dumps(v, protocol=pickle.HIGHEST_PROTOCOL)
               for k, v in components.items()}
    manifest = {"schema": 1,
                "components": {k: {"crc32": zlib.crc32(b),
                                   "nbytes": len(b)}
                               for k, b in payload.items()}}
    blob = pickle.dumps({"__apex_tpu_checkpoint__": 1,
                         "manifest": manifest, "payload": payload})
    with open(path, "wb") as f:
        f.write(blob)


def test_schema1_roundtrip_and_elastic_warning(tmp_path):
    """Backward compat both ways: a schema-1 checkpoint still loads via
    restore_or_initialize with no warning, restores elastically with a
    'predates sharding metadata' warning, and a fresh save through the
    same manager writes the current schema."""
    mgr = CheckpointManager(str(tmp_path), keep_n=3)
    model, opt = _mlp()
    step = make_train_step(model, opt, _loss, half_dtype=None,
                           loss_scale=1.0)
    step(*_batch(2))
    host = resilience.snapshot_state(step.state)
    _write_schema1(mgr.path_for(7), {"state": host, "epoch": 1})

    s, comps = mgr.restore_or_initialize()
    assert s == 7 and comps["epoch"] == 1
    np.testing.assert_array_equal(comps["state"].master_params[0],
                                  host.master_params[0])

    model2, opt2 = _mlp(seed=1)
    step2 = make_train_step(model2, opt2, _loss, half_dtype=None,
                            loss_scale=1.0)
    with pytest.warns(UserWarning, match="sharding metadata"):
        got, extras = mgr.restore_resharded(step2, step=7)
    assert got == 7 and extras == {"epoch": 1}
    np.testing.assert_array_equal(
        np.asarray(step2.state.master_params[0]), host.master_params[0])

    mgr.save(8, state=host)
    _, manifest = resilience.read_checkpoint_file(mgr.path_for(8),
                                                  return_manifest=True)
    assert manifest["schema"] == resilience.SCHEMA_VERSION


def _write_schema2(path, components, layouts=None, plan=None):
    """A byte-accurate schema-2 container (layout + plan metadata,
    gathered full-array payloads, no shard streaming), as the previous
    release wrote them."""
    components = {k: resilience._to_host(v) for k, v in components.items()}
    payload = {k: pickle.dumps(v, protocol=pickle.HIGHEST_PROTOCOL)
               for k, v in components.items()}
    comp_meta = {}
    for k, b in payload.items():
        comp_meta[k] = {"crc32": zlib.crc32(b), "nbytes": len(b)}
        if layouts and layouts.get(k) is not None:
            comp_meta[k]["layout"] = layouts[k]
    manifest = {"schema": 2, "components": comp_meta}
    if plan is not None:
        manifest["plan"] = resilience._plan_meta(plan)
    blob = pickle.dumps({"__apex_tpu_checkpoint__": 2,
                         "manifest": manifest, "payload": payload})
    with open(path, "wb") as f:
        f.write(blob)


def test_schema2_gathered_restore_and_resave_upgrade(tmp_path):
    """Pre-streaming compat: a schema-2 checkpoint (gathered full
    arrays, no shard files) still restores elastically — through the
    gathered reshard path, with a 'predates shard streaming' warning —
    and a re-save through the same manager upgrades it to the schema-3
    per-shard layout."""
    model, opt = _mlp()
    plan = auto.Plan(dp=8, zero_stage=1, n_devices=8)
    step = make_train_step(model, opt, _loss, half_dtype=None,
                           loss_scale=1.0, parallel=plan)
    step(*_batch(1))
    mgr = CheckpointManager(str(tmp_path), keep_n=3)
    layouts = {"state": resilience.capture_layout(step.state)}
    _write_schema2(mgr.path_for(4), {"state": step.state, "epoch": 2},
                   layouts=layouts, plan=plan)

    model2, opt2 = _mlp(seed=1)
    step2 = make_train_step(model2, opt2, _loss, half_dtype=None,
                            loss_scale=1.0, parallel=plan)
    with pytest.warns(UserWarning, match="predates shard streaming"):
        got, extras = mgr.restore_resharded(step2, step=4)
    assert got == 4 and extras == {"epoch": 2}
    assert mgr.last_restore_stats["mode"] == "gathered"
    assert mgr.last_restore_stats["schema"] == 2
    for a, b in zip(step2.state.master_params, step.state.master_params):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # re-save: same manager, same state — now schema 3, shard-streamed
    mgr.save_sharded(5, step2, epoch=2)
    _, manifest = resilience.read_checkpoint_file(mgr.path_for(5),
                                                  return_manifest=True)
    assert manifest["schema"] == 3
    assert manifest["components"]["state"]["streamed"] is not None
    import os as _os
    assert _os.path.isdir(mgr.shard_dir_for(5))
    # and the upgraded copy streams on the next restore — no warning
    model3, opt3 = _mlp(seed=2)
    step3 = make_train_step(model3, opt3, _loss, half_dtype=None,
                            loss_scale=1.0, parallel=plan)
    with warnings.catch_warnings():
        warnings.simplefilter("error")
        assert mgr.restore_resharded(step3, step=5)[0] == 5
    assert mgr.last_restore_stats["mode"] == "streamed"


# ---------------------------------------------------------------------------
# cross-plan reshard
# ---------------------------------------------------------------------------


def test_zero3_dp8_checkpoint_into_dp2_tp4(tmp_path):
    """Acceptance: a ZeRO-3 dp8 checkpoint restores into a dp2×tp4 plan
    — fp32 masters bit-exact vs the source, and the post-restore step
    output matches the same checkpoint restored into its native plan."""
    from apex_tpu.models import GptModel
    V, S = 64, 8

    def mk(tp_axis=None):
        nn.manual_seed(5)
        m = GptModel(vocab_size=V, hidden=32, layers=1, heads=4,
                     max_positions=S, dropout=0.0, attn_dropout=0.0,
                     tp_axis=tp_axis)
        return m, FusedAdam(list(m.parameters()), lr=1e-2)

    def lm_loss(logits, tgt):
        return F.cross_entropy(logits.reshape((-1, V)),
                               tgt.reshape((-1,)))

    rng = np.random.default_rng(9)
    ids = jnp.asarray(rng.integers(0, V, (8, S)))
    tgt = jnp.asarray(np.roll(np.asarray(ids), -1, axis=1))

    m1, o1 = mk()
    src = make_train_step(m1, o1, lm_loss, half_dtype=None,
                          loss_scale=1.0,
                          parallel=auto.Plan(dp=8, zero_stage=3,
                                             n_devices=8))
    src(ids, tgt)
    src(ids, tgt)
    mgr = CheckpointManager(str(tmp_path), keep_n=2)
    mgr.save_sharded(1, src)

    # native restore: the same plan, fresh objects
    m2, o2 = mk()
    native = make_train_step(m2, o2, lm_loss, half_dtype=None,
                             loss_scale=1.0,
                             parallel=auto.Plan(dp=8, zero_stage=3,
                                                n_devices=8))
    assert mgr.restore_resharded(native)[0] == 1

    # cross-plan restore: dp2×tp4 through the explicit shard_map path
    m3, o3 = mk(tp_axis="tp")
    cross = make_train_step(m3, o3, lm_loss, half_dtype=None,
                            loss_scale=1.0,
                            parallel=auto.Plan(dp=2, tp=4, tp_axis="tp",
                                               n_devices=8))
    got_step, _ = mgr.restore_resharded(cross)
    assert got_step == 1

    # fp32 masters bit-exact across the plan change (np.asarray gathers
    # the source's ZeRO shards)
    for a, b in zip(cross.state.master_params, src.state.master_params):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    # post-restore step parity: native continues bit-exact with the
    # source run; the tp plan tracks within the established tp-vs-oracle
    # numerics envelope (test_auto_parallel's rtol)
    l_src = float(src(ids, tgt))
    l_native = float(native(ids, tgt))
    l_cross = float(cross(ids, tgt))
    np.testing.assert_array_equal(l_native, l_src)
    np.testing.assert_allclose(l_cross, l_src, rtol=3e-3, atol=3e-3)


def test_load_state_reshards_into_current_layout():
    """TrainStep/ZeroTrainStep.load_state: a host snapshot lands back
    under the step's live shardings, not replicated."""
    model, opt = _mlp()
    plan = auto.Plan(dp=4, zero_stage=1, n_devices=8)
    z = make_train_step(model, opt, _loss, half_dtype=None,
                        loss_scale=1.0, parallel=plan)
    z(*_batch(4))
    host = resilience.snapshot_state(z.state)

    model2, opt2 = _mlp(seed=1)
    z2 = make_train_step(model2, opt2, _loss, half_dtype=None,
                         loss_scale=1.0, parallel=plan)
    z2.load_state(host)
    for a, b in zip(z2.state.master_params, z.state.master_params):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert (z2.state.master_params[0].sharding.spec
            == z.state.master_params[0].sharding.spec)


def test_reshard_error_names_incompatible_component(tmp_path):
    model, opt = _mlp()
    step = make_train_step(model, opt, _loss, half_dtype=None,
                           loss_scale=1.0)
    step(*_batch(3))
    mgr = CheckpointManager(str(tmp_path), keep_n=2)
    mgr.save_sharded(0, step)

    nn.manual_seed(1)
    other = nn.Sequential(nn.Linear(DIM, 48), nn.GELU(),
                          nn.Linear(48, CLASSES))    # different hidden
    opt2 = FusedSGD(list(other.parameters()), lr=0.1, momentum=0.9)
    tgt = make_train_step(other, opt2, _loss, half_dtype=None,
                          loss_scale=1.0)
    with pytest.raises(CheckpointReshardError) as ei:
        mgr.restore_resharded(tgt)
    msg = str(ei.value)
    assert "'state'" in msg                  # names the component
    assert "(48, 16)" in msg or "(32, 16)" in msg    # and the shapes
    # the failed reshard never touched the target's state
    assert np.isfinite(
        float(np.asarray(tgt.state.master_params[0]).sum()))


def test_reshard_rejects_dtype_change():
    a = {"w": jnp.zeros((4,), jnp.float32)}
    b = {"w": jnp.zeros((4,), jnp.bfloat16)}
    with pytest.raises(CheckpointReshardError, match="never casts"):
        reshard_state(resilience._to_host(a), b)


# ---------------------------------------------------------------------------
# the full elastic cycle
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_elastic_cycle_preempt_shrink_replan_resume_regrow(tmp_path):
    """Acceptance: deterministic preempt→shrink(8→4)→replan→reshard→
    resume→regrow(4→8) with loss-trajectory parity against an
    uninterrupted 8-device run (fp32 SGD; the shrink segment runs a
    different dp degree, so parity is to reduction-order tolerance)."""
    n = len(jax.devices())
    assert n == 8
    batches = [_batch(10 + i) for i in range(9)]

    ref = _trainer(tmp_path / "ref")
    assert ref.restore() == 0
    ref_losses = [float(ref(*b)) for b in batches]

    el = _trainer(tmp_path / "el")
    assert el.restore() == 0 and el.plan.dp == n
    got = [float(el(*b)) for b in batches[:3]]
    el.save(2)
    for b in batches[3:5]:
        el(*b)                  # steps 3-4 run but die un-checkpointed

    # preemption: the job restarts and the slice comes back at half size
    el2 = _trainer(tmp_path / "el")
    with chaos.session(seed=0) as c:
        c.on("device.loss", action=lambda ctx: ctx["n"] // 2, at=0)
        resume = el2.restore()
    assert resume == 3          # replays exactly the un-checkpointed steps
    assert el2.plan.dp == n // 2 and len(el2.devices) == n // 2
    assert el2.telemetry["reshard_ms"] > 0
    assert el2.telemetry["plan_key"] != el.plan.key()
    got += [float(el2(*b)) for b in batches[3:6]]
    el2.save(5)

    # regrow: the next restart sees the full mesh again
    el3 = _trainer(tmp_path / "el")
    resume = el3.restore()
    assert resume == 6
    assert el3.plan.dp == n and len(el3.devices) == n
    got += [float(el3(*b)) for b in batches[6:]]

    np.testing.assert_allclose(got, ref_losses, rtol=2e-5, atol=1e-6)


@pytest.mark.chaos
def test_same_topology_resume_is_bit_exact(tmp_path):
    """fp32-SGD acceptance arm: preempt + resume on the SAME topology is
    bit-exact — masters AND the continued loss trajectory — through
    save_sharded → schema-2 manifest → reshard."""
    batches = [_batch(30 + i) for i in range(6)]
    ref = _trainer(tmp_path / "ref")
    ref.restore()
    ref_losses = [float(ref(*b)) for b in batches]

    el = _trainer(tmp_path / "el")
    el.restore()
    for b in batches[:4]:
        el(*b)
    el.save(3)

    el2 = _trainer(tmp_path / "el", seed=1)    # fresh (different) init
    assert el2.restore() == 4
    for a, b in zip(el2.step.state.master_params,
                    el.step.state.master_params):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    tail = [float(el2(*b)) for b in batches[4:]]
    np.testing.assert_array_equal(tail, ref_losses[4:])


@pytest.mark.chaos
def test_kill_during_reshard_previous_checkpoint_survives(tmp_path):
    """Reshard is read-only on disk: a kill mid-reshard leaves the
    checkpoint byte-identical and the next restore succeeds from it."""
    el = _trainer(tmp_path / "el")
    el.restore()
    for i in range(3):
        el(*_batch(40 + i))
    el.save(2)
    ckpt_path = el.manager.path_for(2)
    with open(ckpt_path, "rb") as f:
        before = f.read()

    el2 = _trainer(tmp_path / "el", seed=1)
    with chaos.session(seed=0) as c:
        c.on("ckpt.reshard", action="kill", at=0)
        with pytest.raises(chaos.ChaosKilled):
            el2.restore()
        assert ("ckpt.reshard", 0, "kill") in [tuple(e) for e in c.log]

    with open(ckpt_path, "rb") as f:
        assert f.read() == before
    el3 = _trainer(tmp_path / "el", seed=1)
    assert el3.restore() == 3
    for a, b in zip(el3.step.state.master_params,
                    el.step.state.master_params):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.chaos
def test_elastic_scans_past_corrupt_newest(tmp_path):
    """restore_or_initialize semantics carry over: a corrupt newest
    checkpoint is skipped with a warning and the older valid one is
    resharded instead."""
    el = _trainer(tmp_path / "el")
    el.restore()
    for i in range(2):
        el(*_batch(50 + i))
    el.save(0)
    el(*_batch(52))
    el.save(1)
    path = el.manager.path_for(1)
    with open(path, "rb") as f:
        blob = bytearray(f.read())
    blob[len(blob) // 2] ^= 0xFF                # flip a payload bit
    with open(path, "wb") as f:
        f.write(bytes(blob))

    el2 = _trainer(tmp_path / "el", seed=1)
    with pytest.warns(UserWarning, match="skipping corrupt checkpoint"):
        resume = el2.restore()
    assert resume == 1 and el2.resume_step == 0


def test_elastic_restore_functional_entry(tmp_path):
    tr = elastic_restore(str(tmp_path / "ck"), *_mlp(), _loss,
                         example_batch=_batch(0), half_dtype=None,
                         loss_scale=1.0, plan_filter=_dp_only)
    assert tr.resume_step is None and tr.step is not None
    assert np.isfinite(float(tr(*_batch(1))))
    tr.save(0)
    assert tr.manager.all_steps() == [0]
