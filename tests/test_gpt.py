"""GPT-style causal decoder (models/gpt.py): shapes, strict causality,
weight-tied head gradients, and causal-LM training through the fused step."""
import jax.numpy as jnp
import numpy as np

import apex_tpu.nn as nn
from apex_tpu.models import GptModel

V, H, L, HEADS, S = 97, 32, 2, 4, 16


def _tiny_gpt():
    nn.manual_seed(5)
    return GptModel(vocab_size=V, hidden=H, layers=L, heads=HEADS,
                    max_positions=64, dropout=0.0, attn_dropout=0.0)


def _ids(rng, b=2, s=S):
    return jnp.asarray(rng.integers(0, V, (b, s)))


def test_logit_shapes(rng):
    m = _tiny_gpt()
    logits = m(_ids(rng))
    assert logits.shape == (2, S, V)
    assert logits.dtype == jnp.float32


def test_strict_causality(rng):
    """Logits at position i must not depend on tokens at positions > i."""
    m = _tiny_gpt()
    m.eval()
    ids = np.asarray(_ids(rng))
    out1 = np.asarray(m(jnp.asarray(ids)))
    ids2 = ids.copy()
    ids2[:, 10:] = (ids2[:, 10:] + 13) % V   # perturb the future
    out2 = np.asarray(m(jnp.asarray(ids2)))
    np.testing.assert_allclose(out1[:, :10], out2[:, :10],
                               rtol=1e-5, atol=1e-5)
    assert np.abs(out1[:, 10:] - out2[:, 10:]).max() > 1e-3


def test_tied_head_grads(rng):
    m = _tiny_gpt()
    ids = _ids(rng)
    logits = m(ids)
    labels = jnp.asarray(rng.integers(0, V, (2 * S,)))
    loss = nn.CrossEntropyLoss()(logits.reshape((-1, V)), labels)
    loss.backward()
    assert all(p.grad is not None for p in m.parameters())
    emb_grad = m.tok_emb.weight.grad
    assert np.isfinite(np.asarray(emb_grad)).all()
    assert float(jnp.abs(emb_grad).max()) > 0


def test_causal_lm_fused_step_converges(rng):
    from apex_tpu.nn import functional as F
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.training import make_train_step

    m = _tiny_gpt()
    opt = FusedAdam(list(m.parameters()), lr=1e-2)

    def lm_loss(logits, ids):
        # next-token prediction: shift by one
        flat = logits[:, :-1].reshape((-1, V))
        tgt = ids[:, 1:].reshape((-1,))
        return F.cross_entropy(flat, tgt)

    step = make_train_step(m, opt, lm_loss, half_dtype=jnp.bfloat16,
                           loss_scale=1.0)
    ids = _ids(rng, b=4)
    losses = [float(step(ids, ids)) for _ in range(8)]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_rejects_oversized_sequence(rng):
    m = _tiny_gpt()  # max_positions=64
    ids = jnp.asarray(np.random.default_rng(0).integers(0, V, (1, 65)))
    import pytest
    with pytest.raises(ValueError, match="max_positions"):
        m(ids)
