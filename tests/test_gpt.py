"""GPT-style causal decoder (models/gpt.py): shapes, strict causality,
weight-tied head gradients, and causal-LM training through the fused step."""
import jax.numpy as jnp
import numpy as np

import apex_tpu.nn as nn
from apex_tpu.models import GptModel

V, H, L, HEADS, S = 97, 32, 2, 4, 16


def _tiny_gpt():
    nn.manual_seed(5)
    return GptModel(vocab_size=V, hidden=H, layers=L, heads=HEADS,
                    max_positions=64, dropout=0.0, attn_dropout=0.0)


def _ids(rng, b=2, s=S):
    return jnp.asarray(rng.integers(0, V, (b, s)))


def test_logit_shapes(rng):
    m = _tiny_gpt()
    logits = m(_ids(rng))
    assert logits.shape == (2, S, V)
    assert logits.dtype == jnp.float32


def test_strict_causality(rng):
    """Logits at position i must not depend on tokens at positions > i."""
    m = _tiny_gpt()
    m.eval()
    ids = np.asarray(_ids(rng))
    out1 = np.asarray(m(jnp.asarray(ids)))
    ids2 = ids.copy()
    ids2[:, 10:] = (ids2[:, 10:] + 13) % V   # perturb the future
    out2 = np.asarray(m(jnp.asarray(ids2)))
    np.testing.assert_allclose(out1[:, :10], out2[:, :10],
                               rtol=1e-5, atol=1e-5)
    assert np.abs(out1[:, 10:] - out2[:, 10:]).max() > 1e-3


def test_tied_head_grads(rng):
    m = _tiny_gpt()
    ids = _ids(rng)
    logits = m(ids)
    labels = jnp.asarray(rng.integers(0, V, (2 * S,)))
    loss = nn.CrossEntropyLoss()(logits.reshape((-1, V)), labels)
    loss.backward()
    assert all(p.grad is not None for p in m.parameters())
    emb_grad = m.tok_emb.weight.grad
    assert np.isfinite(np.asarray(emb_grad)).all()
    assert float(jnp.abs(emb_grad).max()) > 0


def test_causal_lm_fused_step_converges(rng):
    from apex_tpu.nn import functional as F
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.training import make_train_step

    m = _tiny_gpt()
    opt = FusedAdam(list(m.parameters()), lr=1e-2)

    def lm_loss(logits, ids):
        # next-token prediction: shift by one
        flat = logits[:, :-1].reshape((-1, V))
        tgt = ids[:, 1:].reshape((-1,))
        return F.cross_entropy(flat, tgt)

    step = make_train_step(m, opt, lm_loss, half_dtype=jnp.bfloat16,
                           loss_scale=1.0)
    ids = _ids(rng, b=4)
    losses = [float(step(ids, ids)) for _ in range(8)]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_rejects_oversized_sequence(rng):
    m = _tiny_gpt()  # max_positions=64
    ids = jnp.asarray(np.random.default_rng(0).integers(0, V, (1, 65)))
    import pytest
    with pytest.raises(ValueError, match="max_positions"):
        m(ids)


def test_remat_grads_match_no_remat(rng):
    """remat=True must be a pure memory/compute tradeoff: losses and
    gradients identical to the non-remat model, including dropout masks
    (the checkpoint bridge replays the same fold_in keys)."""
    import jax

    def build(remat):
        nn.manual_seed(5)
        return GptModel(vocab_size=V, hidden=H, layers=L, heads=HEADS,
                        max_positions=64, dropout=0.1, attn_dropout=0.0,
                        remat=remat)

    ids = _ids(rng)
    key = jax.random.PRNGKey(3)
    outs = {}
    for remat in (False, True):
        m = build(remat)
        params = [p for p in m.parameters()]

        def loss_fn(vals):
            from apex_tpu.nn.modules import Ctx
            ctx = Ctx(env={id(p): v for p, v in zip(params, vals)},
                      stats_out={}, training=True, key=key)
            logits = m.forward(ctx, ids)
            return jnp.mean(logits.astype(jnp.float32) ** 2)

        vals = [p.data for p in params]
        loss, grads = jax.jit(jax.value_and_grad(
            lambda v: loss_fn(v)))(vals)
        outs[remat] = (float(loss), [np.asarray(g) for g in grads])

    assert outs[False][0] == outs[True][0]
    for a, b in zip(outs[False][1], outs[True][1]):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)


def test_remat_through_fused_step(rng):
    """A remat GPT trains through make_train_step and the loss decreases."""
    from apex_tpu.nn import functional as F
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.training import make_train_step

    nn.manual_seed(0)
    m = GptModel(vocab_size=V, hidden=H, layers=L, heads=HEADS,
                 max_positions=64, dropout=0.1, attn_dropout=0.0, remat=True)
    opt = FusedAdam(list(m.parameters()), lr=1e-3)

    def lm_loss(logits, ids):
        return F.cross_entropy(logits[:, :-1].reshape((-1, V)),
                               ids[:, 1:].reshape((-1,)))

    step = make_train_step(m, opt, lm_loss, half_dtype=jnp.bfloat16,
                           loss_scale=1.0)
    ids = _ids(rng, b=4)
    l0 = float(step(ids, ids))
    for _ in range(20):
        l = float(step(ids, ids))
    assert np.isfinite(l) and l < l0


def test_checkpoint_forward_rejects_batchnorm(rng):
    """Running-stat writes cannot cross the remat boundary."""
    import pytest
    from apex_tpu.nn.modules import Ctx

    nn.manual_seed(0)
    bn = nn.BatchNorm1d(8)
    x = jnp.asarray(rng.standard_normal((4, 8)), jnp.float32)
    ctx = Ctx(env={id(p): p.data for p in bn.parameters()},
              stats_out={}, training=True)
    for b in bn.buffers():
        ctx.env[id(b)] = b.data
    with pytest.raises(ValueError, match="remat"):
        nn.checkpoint_forward(bn, ctx, x)


def test_checkpoint_forward_reads_env_buffers(rng):
    """Buffer reads (eval-mode BN running stats substituted through the
    ctx env) must cross the checkpoint boundary — not fall back to the
    stale eager .data values."""
    from apex_tpu.nn.modules import Ctx

    nn.manual_seed(0)
    bn = nn.BatchNorm1d(8)
    bn.eval()
    x = jnp.asarray(rng.standard_normal((4, 8)), jnp.float32)
    env = {id(p): p.data for p in bn.parameters()}
    bufs = list(bn.buffers())
    # substitute non-trivial running stats through the env
    subst = {id(b): b.data + (i + 1) * 0.5 for i, b in enumerate(bufs)}
    env.update(subst)
    ctx = Ctx(env=dict(env), stats_out={}, training=False)
    want = bn.forward(ctx, x)
    ctx2 = Ctx(env=dict(env), stats_out={}, training=False)
    got = nn.checkpoint_forward(bn, ctx2, x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-6, atol=1e-7)


def test_gpt_pallas_vs_fallback_loss_parity(rng):
    """L1-style oracle on the causal stack: the Pallas build (interpret,
    causal flash kernel) and the jnp fallback must produce matching LM
    loss curves through the fused step — with remat on, so the checkpoint
    bridge is in the compared program too."""
    from apex_tpu.nn import functional as F
    from apex_tpu.ops.pallas import force_mode
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.training import make_train_step

    def run(mode):
        nn.manual_seed(5)
        m = GptModel(vocab_size=V, hidden=H, layers=L, heads=HEADS,
                     max_positions=64, dropout=0.0, attn_dropout=0.0,
                     remat=True)
        opt = FusedAdam(list(m.parameters()), lr=1e-2)

        def lm_loss(logits, ids):
            return F.cross_entropy(logits[:, :-1].reshape((-1, V)),
                                   ids[:, 1:].reshape((-1,)))

        step = make_train_step(m, opt, lm_loss, loss_scale=1.0)
        r = np.random.default_rng(7)
        ids = jnp.asarray(r.integers(0, V, (4, S)))
        with force_mode(mode):
            return [float(step(ids, ids)) for _ in range(4)]

    pallas_build = run("interpret")
    python_build = run("off")
    np.testing.assert_allclose(pallas_build, python_build,
                               rtol=2e-3, atol=2e-4)


def test_gpt_attn_dropout_loss_parity_across_modes(rng):
    """Attention dropout through the kernel: the in-kernel hash mask is a
    pure function of the per-step key, so the interpret-mode Pallas build
    and the jnp fallback drop the SAME probs and the training loss curves
    match — the dropped-path analogue of the parity test above."""
    from apex_tpu.nn import functional as F
    from apex_tpu.ops.pallas import force_mode
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.training import make_train_step

    def run(mode):
        nn.manual_seed(6)
        m = GptModel(vocab_size=V, hidden=H, layers=L, heads=HEADS,
                     max_positions=64, dropout=0.1, attn_dropout=0.1)
        opt = FusedAdam(list(m.parameters()), lr=1e-2)

        def lm_loss(logits, ids):
            return F.cross_entropy(logits[:, :-1].reshape((-1, V)),
                                   ids[:, 1:].reshape((-1,)))

        step = make_train_step(m, opt, lm_loss, loss_scale=1.0)
        r = np.random.default_rng(8)
        ids = jnp.asarray(r.integers(0, V, (4, S)))
        with force_mode(mode):
            return [float(step(ids, ids)) for _ in range(3)]

    pallas_build = run("interpret")
    python_build = run("off")
    np.testing.assert_allclose(pallas_build, python_build,
                               rtol=2e-3, atol=2e-4)


def test_sp_gpt_attn_dropout_matches_unsharded(rng):
    """Ring-SP GPT with ATTENTION DROPOUT ACTIVE: the mask hashes global
    coordinates under the replicated pre-shard key (Ctx.shared_key), so
    the sequence-sharded training forward drops the same probabilities
    as the unsharded run and the logits match — dropout does not break
    the SP oracle.  Residual dropout stays 0 (its per-shard keys differ
    by design)."""
    import jax
    from jax.sharding import Mesh, PartitionSpec as P
    from apex_tpu.nn.modules import Ctx

    S_GLOBAL = 32
    ids = jnp.asarray(rng.integers(0, V, (2, S_GLOBAL)))
    key = jax.random.PRNGKey(17)

    def build(sp_axis):
        nn.manual_seed(5)
        return GptModel(vocab_size=V, hidden=H, layers=L, heads=HEADS,
                        max_positions=S_GLOBAL, dropout=0.0,
                        attn_dropout=0.3, sp_axis=sp_axis)

    m_ref = build(None).train()
    params_ref = list(m_ref.parameters())
    vals = [p.data for p in params_ref]
    ctx = Ctx(env={id(p): v for p, v in zip(params_ref, vals)},
              training=True, key=key)
    ref_out = m_ref.forward(ctx, ids)

    m_sp = build("sp").train()
    params_sp = list(m_sp.parameters())
    mesh = Mesh(np.array(jax.devices()), ("sp",))

    def sp_fwd(vals, ids_l):
        c = Ctx(env={id(p): v for p, v in zip(params_sp, vals)},
                training=True, key=key)
        return m_sp.forward(c, ids_l)

    sp_out = jax.jit(jax.shard_map(
        sp_fwd, mesh=mesh, in_specs=(P(), P(None, "sp")),
        out_specs=P(None, "sp", None), check_vma=False))(vals, ids)
    np.testing.assert_allclose(np.asarray(sp_out), np.asarray(ref_out),
                               rtol=2e-4, atol=2e-4)
    # and the mask really is active: the dropout-free forward differs
    m_ref.eval()
    clean = m_ref(ids).value
    assert not np.allclose(np.asarray(clean), np.asarray(ref_out))


def test_sp_attn_dropout_through_fused_step_matches_unsharded():
    """The DOCUMENTED SP training recipe — make_train_step(...,
    axis_name="sp") under shard_map — with attention dropout active:
    the step excludes the model's own sp_axis from its key fold (the
    model folds it and stashes the pre-fold key as Ctx.shared_key), so
    the ring mask seed is sp-replicated and per-step losses equal the
    unsharded run's exactly-dropped losses."""
    import jax
    from jax.sharding import Mesh, PartitionSpec as P
    from apex_tpu.nn import functional as F
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.training import make_train_step

    S_GLOBAL = 32
    r = np.random.default_rng(2)
    ids = jnp.asarray(r.integers(0, V, (2, S_GLOBAL)))
    tgt = jnp.asarray(np.roll(np.asarray(ids), -1, axis=1))

    def lm_loss(logits, tgt):
        return F.cross_entropy(logits.reshape((-1, V)),
                               tgt.reshape((-1,)))

    def build(sp_axis):
        nn.manual_seed(9)
        return GptModel(vocab_size=V, hidden=H, layers=L, heads=HEADS,
                        max_positions=S_GLOBAL, dropout=0.0,
                        attn_dropout=0.3, sp_axis=sp_axis)

    m_ref = build(None)
    opt = FusedAdam(list(m_ref.parameters()), lr=1e-2)
    step_ref = make_train_step(m_ref, opt, lm_loss, half_dtype=None,
                               loss_scale=1.0)
    ref = [float(step_ref(ids, tgt)) for _ in range(3)]

    m_sp = build("sp")
    opt = FusedAdam(list(m_sp.parameters()), lr=1e-2)
    step_sp = make_train_step(m_sp, opt, lm_loss, half_dtype=None,
                              loss_scale=1.0, axis_name="sp")
    mesh = Mesh(np.array(jax.devices()), ("sp",))

    def stepper(state, ids_l, tgt_l):
        # the step returns the LOCAL shard's loss; pmean gives the
        # global token mean (uniform shard sizes) for the comparison
        state, l = step_sp._step_fn(state, ids_l, tgt_l)
        return state, jax.lax.pmean(l, "sp")

    sharded = jax.jit(jax.shard_map(
        stepper, mesh=mesh,
        in_specs=(P(), P(None, "sp"), P(None, "sp")),
        out_specs=(P(), P()), check_vma=False))
    state, sp_losses = step_sp.state, []
    for _ in range(3):
        state, l = sharded(state, ids, tgt)
        sp_losses.append(float(l))
    np.testing.assert_allclose(sp_losses, ref, rtol=2e-4, atol=2e-4)


def test_sequence_parallel_gpt_matches_unsharded(rng):
    """GptModel(sp_axis=...) under shard_map with the sequence dim sharded
    8-way: logits and parameter gradients match the unsharded model (ring
    attention with global causal offsets, global position embeddings)."""
    import jax
    from jax.sharding import Mesh, PartitionSpec as P
    from apex_tpu.nn.modules import Ctx

    S_GLOBAL = 32
    ids = jnp.asarray(rng.integers(0, V, (2, S_GLOBAL)))
    w = jnp.asarray(rng.standard_normal((2, S_GLOBAL, V)), jnp.float32)

    def build(sp_axis):
        nn.manual_seed(5)
        return GptModel(vocab_size=V, hidden=H, layers=L, heads=HEADS,
                        max_positions=S_GLOBAL, dropout=0.0,
                        attn_dropout=0.0, sp_axis=sp_axis)

    # oracle: unsharded
    m_ref = build(None)
    params_ref = list(m_ref.parameters())

    def ref_loss(vals):
        ctx = Ctx(env={id(p): v for p, v in zip(params_ref, vals)},
                  training=False)
        return jnp.sum(m_ref.forward(ctx, ids) * w)

    vals = [p.data for p in params_ref]
    ref_out = m_ref(ids).value
    ref_grads = jax.grad(ref_loss)(vals)

    # sequence-parallel: ids sharded on dim 1 over 8 devices
    m_sp = build("sp")
    params_sp = list(m_sp.parameters())
    mesh = Mesh(np.array(jax.devices()), ("sp",))

    def sp_fwd(vals, ids_l):
        ctx = Ctx(env={id(p): v for p, v in zip(params_sp, vals)},
                  training=False)
        return m_sp.forward(ctx, ids_l)

    shard_fwd = jax.jit(jax.shard_map(
        sp_fwd, mesh=mesh, in_specs=(P(), P(None, "sp")),
        out_specs=P(None, "sp", None), check_vma=False))
    sp_out = shard_fwd(vals, ids)
    np.testing.assert_allclose(np.asarray(sp_out), np.asarray(ref_out),
                               rtol=2e-4, atol=2e-4)

    def sp_loss(vals, ids, w):
        def f(vals, ids_l, w_l):
            out = sp_fwd(vals, ids_l)
            return jax.lax.psum(jnp.sum(out * w_l), "sp")
        shard = jax.shard_map(
            f, mesh=mesh,
            in_specs=(P(), P(None, "sp"), P(None, "sp", None)),
            out_specs=P(), check_vma=False)
        return shard(vals, ids, w)

    sp_grads = jax.jit(jax.grad(sp_loss))(vals, ids, w)
    for a, b in zip(ref_grads, sp_grads):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-4, atol=3e-4)


def test_sp_config_validation():
    import pytest
    # sp_axis with the default attn_dropout=0.1 constructs since ring
    # dropout landed (global hash mask; the old refusal is gone)
    GptModel(vocab_size=V, hidden=H, layers=1, heads=HEADS, sp_axis="sp")
    from apex_tpu.contrib.multihead_attn.attn_funcs import self_attn_func
    with pytest.raises(ValueError, match="seq_parallel_impl"):
        self_attn_func(False, False, 2, 1.0, jnp.zeros((4, 2, 8)),
                       jnp.zeros((24, 8)), jnp.zeros((8, 8)),
                       seq_parallel_axis="sp", seq_parallel_impl="rings")


def test_sp_training_through_fused_step():
    """Sequence-parallel GPT trains through make_train_step(axis_name=
    "sp") under shard_map: replicated-param grads are identical across
    shards (the psum-mean is then an identity), loss decreases."""
    import jax
    from jax.sharding import Mesh, PartitionSpec as P
    from apex_tpu.nn import functional as F
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.training import make_train_step

    nn.manual_seed(5)
    m = GptModel(vocab_size=V, hidden=H, layers=2, heads=HEADS,
                 max_positions=32, dropout=0.0, attn_dropout=0.0,
                 sp_axis="sp")
    opt = FusedAdam(list(m.parameters()), lr=1e-2)

    def lm_loss(logits, tgt):
        return F.cross_entropy(logits.reshape((-1, V)),
                               tgt.reshape((-1,)))

    step = make_train_step(m, opt, lm_loss, half_dtype=None,
                           loss_scale=1.0, axis_name="sp")
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, V, (2, 32)))
    tgt = jnp.asarray(np.roll(np.asarray(ids), -1, axis=1))  # global shift
    mesh = Mesh(np.array(jax.devices()), ("sp",))
    sharded = jax.jit(jax.shard_map(
        step._step_fn, mesh=mesh,
        in_specs=(P(), P(None, "sp"), P(None, "sp")),
        out_specs=(P(), P()), check_vma=False))
    state, l0 = sharded(step.state, ids, tgt)
    for _ in range(15):
        state, l = sharded(state, ids, tgt)
    assert np.isfinite(float(l)) and float(l) < float(l0)


def test_dp_x_sp_2d_mesh_training():
    """2-D composition: data parallelism x sequence parallelism on a
    (2, 4) mesh — batch sharded on dim 0 over 'data', sequence on dim 1
    over 'sp'; grads psum over BOTH axes."""
    import jax
    from jax.sharding import Mesh, PartitionSpec as P
    from apex_tpu.nn import functional as F
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.training import make_train_step

    nn.manual_seed(5)
    m = GptModel(vocab_size=V, hidden=H, layers=2, heads=HEADS,
                 max_positions=16, dropout=0.0, attn_dropout=0.0,
                 sp_axis="sp")
    opt = FusedAdam(list(m.parameters()), lr=1e-2)

    def lm_loss(logits, tgt):
        return F.cross_entropy(logits.reshape((-1, V)),
                               tgt.reshape((-1,)))

    step = make_train_step(m, opt, lm_loss, half_dtype=None,
                           loss_scale=1.0, axis_name=("data", "sp"))
    rng = np.random.default_rng(1)
    ids = jnp.asarray(rng.integers(0, V, (4, 16)))
    tgt = jnp.asarray(np.roll(np.asarray(ids), -1, axis=1))
    mesh = Mesh(np.array(jax.devices()).reshape(2, 4), ("data", "sp"))
    sharded = jax.jit(jax.shard_map(
        step._step_fn, mesh=mesh,
        in_specs=(P(), P("data", "sp"), P("data", "sp")),
        out_specs=(P(), P()), check_vma=False))
    state, l0 = sharded(step.state, ids, tgt)
    for _ in range(15):
        state, l = sharded(state, ids, tgt)
    assert np.isfinite(float(l)) and float(l) < float(l0)


def test_sequence_parallel_ulysses_matches_unsharded(rng):
    """The Ulysses (all-to-all) SP path at the model level: heads scatter
    over the axis while the sequence gathers; logits match unsharded."""
    import jax
    from jax.sharding import Mesh, PartitionSpec as P
    from apex_tpu.nn.modules import Ctx

    S_GLOBAL, HEADS8 = 32, 8  # heads must divide by the axis size

    def build(sp):
        nn.manual_seed(6)
        m = GptModel(vocab_size=V, hidden=H, layers=2, heads=HEADS8,
                     max_positions=S_GLOBAL, dropout=0.0, attn_dropout=0.0,
                     sp_axis=sp)
        if sp:
            for blk in m.blocks:
                blk.attn.seq_parallel_impl = "ulysses"
        return m

    ids = jnp.asarray(rng.integers(0, V, (2, S_GLOBAL)))
    m_ref = build(None)
    ref_out = m_ref(ids).value

    m_sp = build("sp")
    params = list(m_sp.parameters())
    mesh = Mesh(np.array(jax.devices()), ("sp",))

    def f(vals, ids_l):
        ctx = Ctx(env={id(p): v for p, v in zip(params, vals)},
                  training=False)
        return m_sp.forward(ctx, ids_l)

    got = jax.jit(jax.shard_map(
        f, mesh=mesh, in_specs=(P(), P(None, "sp")),
        out_specs=P(None, "sp", None), check_vma=False))(
            [p.data for p in params], ids)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref_out),
                               rtol=2e-4, atol=2e-4)


def test_decode_with_kv_cache_matches_full_forward(rng):
    """Teacher-forced decode over every position reproduces the training
    forward's logits — the KV-cache attention is exactly the causal
    attention, one row at a time."""
    import jax
    from apex_tpu.nn.modules import Ctx

    m = _tiny_gpt()
    m.eval()
    ids = _ids(rng)                       # (2, S)
    full = np.asarray(m(ids).value)       # (2, S, V)

    params = list(m.parameters())
    ctx = Ctx(env={id(p): p.data for p in params}, training=False)
    caches = m.init_caches(2, S)
    got = []
    for t in range(S):
        logits, caches = m.decode_step(ctx, ids[:, t],
                                       caches, jnp.asarray(t))
        got.append(np.asarray(logits))
    got = np.stack(got, axis=1)
    np.testing.assert_allclose(got, full, rtol=2e-4, atol=2e-4)


def test_decode_applies_attention_biases(rng):
    """A block carrying a biased (default-impl) attention must decode the
    same logits as its training forward — decode applies in/out projection
    biases when present instead of silently dropping them."""
    import jax
    from apex_tpu.contrib.multihead_attn import SelfMultiheadAttn
    from apex_tpu.nn.modules import Ctx

    m = _tiny_gpt()
    for blk in m.blocks:
        attn = SelfMultiheadAttn(H, HEADS, dropout=0.0, bias=True,
                                 impl="default", causal=True)
        # nonzero biases so a dropped bias is a loud mismatch
        attn.in_proj_bias.data = jnp.asarray(
            rng.normal(size=(3 * H,)), jnp.float32) * 0.1
        attn.out_proj_bias.data = jnp.asarray(
            rng.normal(size=(H,)), jnp.float32) * 0.1
        blk.attn = attn
    m.eval()
    ids = _ids(rng)
    full = np.asarray(m(ids).value)

    params = list(m.parameters())
    ctx = Ctx(env={id(p): p.data for p in params}, training=False)
    caches = m.init_caches(2, S)
    got = []
    for t in range(S):
        logits, caches = m.decode_step(ctx, ids[:, t],
                                       caches, jnp.asarray(t))
        got.append(np.asarray(logits))
    got = np.stack(got, axis=1)
    np.testing.assert_allclose(got, full, rtol=2e-4, atol=2e-4)


def test_generate_greedy_and_sampling(rng):
    """generate(): prompt is preserved, greedy decode is deterministic
    and matches step-by-step argmax; temperature sampling stays in-vocab
    and varies with the key."""
    import jax
    from apex_tpu.models import generate
    from apex_tpu.nn.modules import Ctx

    m = _tiny_gpt()
    m.eval()
    prompt = _ids(rng, b=2, s=4)
    out = generate(m, prompt, max_new_tokens=6)
    assert out.shape == (2, 10)
    np.testing.assert_array_equal(np.asarray(out[:, :4]),
                                  np.asarray(prompt))
    # oracle: manual greedy loop via decode_step
    params = list(m.parameters())
    ctx = Ctx(env={id(p): p.data for p in params}, training=False)
    caches = m.init_caches(2, 10)
    tok = prompt[:, 0]
    seq = [np.asarray(tok)]
    for t in range(9):
        logits, caches = m.decode_step(ctx, tok, caches, jnp.asarray(t))
        nxt = jnp.argmax(logits, axis=-1)
        tok = prompt[:, t + 1] if t + 1 < 4 else nxt
        seq.append(np.asarray(tok))
    np.testing.assert_array_equal(np.asarray(out), np.stack(seq, 1))

    s1 = generate(m, prompt, 6, temperature=1.0,
                  key=jax.random.PRNGKey(1))
    s2 = generate(m, prompt, 6, temperature=1.0,
                  key=jax.random.PRNGKey(2))
    assert (np.asarray(s1) != np.asarray(s2)).any()
    assert int(jnp.max(s1)) < V and int(jnp.min(s1)) >= 0
    s3 = generate(m, prompt, 6, temperature=1.0, top_k=5,
                  key=jax.random.PRNGKey(1))
    assert s3.shape == (2, 10)


def test_generate_bounds_checked(rng):
    import pytest
    from apex_tpu.models import generate
    m = _tiny_gpt()
    with pytest.raises(ValueError, match="max_positions"):
        generate(m, _ids(rng, b=1, s=60), max_new_tokens=10)
    with pytest.raises(ValueError, match="PRNG"):
        generate(m, _ids(rng, b=1, s=4), 2, temperature=0.5)


def test_generate_validation_and_jit_reuse(rng):
    import jax
    import pytest
    from apex_tpu.models import generate
    m = _tiny_gpt()
    prompt = _ids(rng, b=1, s=4)
    with pytest.raises(ValueError, match="temperature"):
        generate(m, prompt, 2, temperature=-1.0)
    with pytest.raises(ValueError, match="top_k"):
        generate(m, prompt, 2, temperature=1.0, top_k=0,
                 key=jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="top_k"):
        generate(m, prompt, 2, temperature=1.0, top_k=V + 1,
                 key=jax.random.PRNGKey(0))
    # same config twice: the compiled program is reused (one cache entry)
    generate(m, prompt, 3)
    generate(m, prompt, 3)
    assert len(m._generate_jit_cache) == 1
    # bf16 caches on request
    out = generate(m, prompt, 3, cache_dtype=jnp.bfloat16)
    assert out.shape == (1, 7)


def test_sp_training_bf16():
    """Sequence-parallel fused training in the production config (bf16
    model copies): finite decreasing loss over the ring."""
    import jax
    from jax.sharding import Mesh, PartitionSpec as P
    from apex_tpu.nn import functional as F
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.training import make_train_step

    nn.manual_seed(5)
    m = GptModel(vocab_size=V, hidden=H, layers=2, heads=HEADS,
                 max_positions=32, dropout=0.1, attn_dropout=0.0,
                 sp_axis="sp", remat=True)
    opt = FusedAdam(list(m.parameters()), lr=1e-2)

    def lm_loss(logits, tgt):
        return F.cross_entropy(logits.reshape((-1, V)),
                               tgt.reshape((-1,)))

    step = make_train_step(m, opt, lm_loss, half_dtype=jnp.bfloat16,
                           loss_scale=1.0, axis_name="sp")
    rng = np.random.default_rng(2)
    ids = jnp.asarray(rng.integers(0, V, (2, 32)))
    tgt = jnp.asarray(np.roll(np.asarray(ids), -1, axis=1))
    mesh = Mesh(np.array(jax.devices()), ("sp",))
    sharded = jax.jit(jax.shard_map(
        step._step_fn, mesh=mesh,
        in_specs=(P(), P(None, "sp"), P(None, "sp")),
        out_specs=(P(), P()), check_vma=False))
    state, l0 = sharded(step.state, ids, tgt)
    for _ in range(12):
        state, l = sharded(state, ids, tgt)
    assert np.isfinite(float(l)) and float(l) < float(l0)


def test_fold_shard_into_key_gives_per_shard_masks():
    """Under shard_map with a replicated key, fold_shard_into_key makes
    each shard draw a different dropout mask (identical masks would
    repeat the drop pattern every S_local positions globally)."""
    import jax
    from jax.sharding import Mesh, PartitionSpec as P
    from apex_tpu.nn import functional as F
    from apex_tpu.nn.modules import Ctx, fold_shard_into_key

    mesh = Mesh(np.array(jax.devices()), ("sp",))

    def f():
        ctx = Ctx(training=True, key=jax.random.PRNGKey(0))
        ctx = fold_shard_into_key(ctx, "sp")
        return F.dropout(jnp.ones((16,)), 0.5, training=True,
                         key=ctx.next_key())

    out = jax.jit(jax.shard_map(f, mesh=mesh, in_specs=(),
                                out_specs=P("sp"), check_vma=False))()
    chunks = np.asarray(out).reshape(8, 16)
    assert any((chunks[0] != chunks[i]).any() for i in range(1, 8))
    # no-op without a key
    ctx = Ctx(training=False)
    assert fold_shard_into_key(ctx, "sp") is ctx


def test_decode_chunk_rejects_out_of_range_t0(rng):
    """A concrete t0 past the position table must raise, not let
    lax.dynamic_slice clamp to wrong position embeddings."""
    import pytest
    from apex_tpu.nn.modules import Ctx

    m = _tiny_gpt()
    m.eval()
    caches = m.init_caches(batch=1, s_max=64)
    toks = jnp.zeros((1, 8), jnp.int32)
    with pytest.raises(ValueError, match="max_positions"):
        m.decode_chunk(Ctx(), toks, caches, 60)   # 60 + 8 > 64
    # in-range concrete t0 still works
    logits, _ = m.decode_chunk(Ctx(), toks, caches, 56)
    assert logits.shape == (1, 8, V)


def test_decode_chunk_rejects_negative_t0_and_short_cache(rng):
    import pytest
    from apex_tpu.nn.modules import Ctx

    m = _tiny_gpt()
    m.eval()
    toks = jnp.zeros((1, 8), jnp.int32)
    with pytest.raises(ValueError, match="out of range"):
        m.decode_chunk(Ctx(), toks, m.init_caches(1, 64), -1)
    # cache shorter than max_positions bounds the write window too
    with pytest.raises(ValueError, match="cache capacity"):
        m.decode_chunk(Ctx(), toks, m.init_caches(1, 32), 30)


def test_nucleus_filter_matches_numpy_reference(rng):
    """nucleus_filter vs a plain-python reference: keep the smallest
    probability-sorted prefix reaching top_p; everything else -1e30."""
    from apex_tpu.models.gpt import nucleus_filter

    logits = jnp.asarray(rng.standard_normal((5, 17)) * 3, jnp.float32)
    for p in (0.1, 0.5, 0.9, 1.0):
        got = np.asarray(nucleus_filter(logits, p))
        for row_l, row_g in zip(np.asarray(logits), got):
            order = np.argsort(-row_l)
            probs = np.exp(row_l[order] - row_l.max())
            probs = probs / probs.sum()
            keep = np.cumsum(probs) - probs < p          # prefix mass
            kept_set = set(order[keep])
            for v in range(17):
                if v in kept_set:
                    assert row_g[v] == row_l[v]
                else:
                    assert row_g[v] == -1e30


def test_generate_top_p(rng):
    """top_p tiny enough keeps only the argmax -> sampling reduces to
    greedy exactly; top_p=1.0 keeps the full distribution."""
    import jax
    from apex_tpu.models import generate

    m = _tiny_gpt()
    m.eval()
    prompt = _ids(rng, b=2, s=4)
    greedy = np.asarray(generate(m, prompt, 6))
    nucleus1 = np.asarray(generate(m, prompt, 6, temperature=1.0,
                                   top_p=1e-9,
                                   key=jax.random.PRNGKey(3)))
    np.testing.assert_array_equal(nucleus1, greedy)
    s = generate(m, prompt, 6, temperature=1.0, top_p=0.9,
                 key=jax.random.PRNGKey(3))
    assert s.shape == (2, 10)
    import pytest
    with pytest.raises(ValueError, match="top_p"):
        generate(m, prompt, 2, temperature=1.0, top_p=0.0,
                 key=jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="top_p"):
        generate(m, prompt, 2, temperature=1.0, top_p=1.5,
                 key=jax.random.PRNGKey(0))


def test_pad_vocab_multiple_exact_numerics(rng):
    """pad_vocab_multiple (Megatron make-vocab-size-divisible-by): the
    lane-padded head produces logits whose pad columns are -1e30-masked,
    so losses, argmax decode, and real-column logits are EXACT w.r.t.
    the logical vocab; the table copies row-for-row."""
    from apex_tpu.nn import functional as F
    from apex_tpu.models import generate

    nn.manual_seed(4)
    m_ref = GptModel(vocab_size=V, hidden=H, layers=L, heads=HEADS,
                     max_positions=64, dropout=0.0, attn_dropout=0.0)
    nn.manual_seed(4)
    m_pad = GptModel(vocab_size=V, hidden=H, layers=L, heads=HEADS,
                     max_positions=64, dropout=0.0, attn_dropout=0.0,
                     pad_vocab_multiple=64)
    vp = m_pad.padded_vocab
    assert vp == 128 and m_pad.vocab_size == V  # 97 -> 128
    # the padded build draws a bigger table: align by copying the
    # reference rows in (everything else drew identically up to the
    # table, so re-seed and copy defensively)
    for pr, pp in zip(m_ref.parameters(), m_pad.parameters()):
        if pp.data.shape != pr.data.shape:
            pp.data = pp.data.at[:pr.data.shape[0]].set(pr.data)
        else:
            pp.data = pr.data

    ids = jnp.asarray(rng.integers(0, V, (2, S)))
    lr = m_ref(ids).value
    lp = m_pad(ids).value
    assert lp.shape[-1] == vp
    np.testing.assert_allclose(np.asarray(lp[..., :V]), np.asarray(lr),
                               rtol=1e-5, atol=1e-5)
    assert float(jnp.max(lp[..., V:])) <= -1e29
    # losses over the padded width equal losses over the logical vocab
    ce_ref = float(F.cross_entropy(lr.reshape((-1, V)),
                                   ids.reshape((-1,))))
    ce_pad = float(F.cross_entropy(lp.reshape((-1, vp)),
                                   ids.reshape((-1,))))
    np.testing.assert_allclose(ce_pad, ce_ref, rtol=1e-6)
    # ... including under label smoothing (mask-aware smoothing spreads
    # no mass over the -1e30 pad columns — round-4 advisor finding)
    sm_ref = float(F.cross_entropy(lr.reshape((-1, V)),
                                   ids.reshape((-1,)), label_smoothing=0.1))
    sm_pad = float(F.cross_entropy(lp.reshape((-1, vp)),
                                   ids.reshape((-1,)), label_smoothing=0.1))
    np.testing.assert_allclose(sm_pad, sm_ref, rtol=1e-6)
    from apex_tpu.contrib.xentropy import softmax_cross_entropy_loss
    x_ref = float(jnp.mean(softmax_cross_entropy_loss(
        lr.reshape((-1, V)), ids.reshape((-1,)), 0.1, -1)))
    x_pad = float(jnp.mean(softmax_cross_entropy_loss(
        lp.reshape((-1, vp)), ids.reshape((-1,)), 0.1, -1)))
    np.testing.assert_allclose(x_pad, x_ref, rtol=1e-6)
    # greedy decode identical (pads never argmax)
    g_ref = generate(m_ref.eval(), ids[:, :4], 6)
    g_pad = generate(m_pad.eval(), ids[:, :4], 6)
    np.testing.assert_array_equal(np.asarray(g_pad), np.asarray(g_ref))


def test_pad_vocab_refuses_tp_vocab():
    import pytest
    with pytest.raises(ValueError, match="pad_vocab_multiple"):
        GptModel(vocab_size=V, hidden=H, layers=1, heads=HEADS,
                 tp_vocab=True, tp_axis="tp", attn_dropout=0.0,
                 pad_vocab_multiple=64)
