"""HuggingFace GPT-2 checkpoint interop (models/hf.py): logit parity
of the converted GptModel against transformers' own torch forward on a
randomly-initialized (no-download) GPT2LMHeadModel — proving a user's
existing GPT-2 checkpoint produces identical predictions here."""
import numpy as np
import pytest

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")

import jax.numpy as jnp

from apex_tpu.models import gpt2_from_hf
from apex_tpu.models.hf import _interleave_qkv, _interleave_qkv_bias


VOCAB, HIDDEN, LAYERS, HEADS, POS = 97, 64, 2, 4, 32


def _hf_model(seed=0):
    cfg = transformers.GPT2Config(
        vocab_size=VOCAB, n_embd=HIDDEN, n_layer=LAYERS, n_head=HEADS,
        n_positions=POS, activation_function="gelu_new",
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0)
    torch.manual_seed(seed)
    m = transformers.GPT2LMHeadModel(cfg)
    m.eval()
    return m


def _ids(rng, b=3, s=17):
    return rng.integers(0, VOCAB, (b, s))


def test_gpt2_logit_parity(rng):
    hf = _hf_model()
    ids = _ids(rng)
    with torch.no_grad():
        want = hf(torch.from_numpy(ids)).logits.numpy()

    model = gpt2_from_hf(hf)
    got = np.asarray(model(jnp.asarray(ids)).value)
    # fp32 end-to-end; differences are pure op-order noise
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_gpt2_from_state_dict_numpy(rng):
    """Conversion accepts a plain state-dict (incl. numpy values and the
    lm_head/causal-mask buffers HF serializes), not just a live module."""
    hf = _hf_model(seed=1)
    sd = {k: v.numpy() for k, v in hf.state_dict().items()}
    ids = _ids(rng, b=2, s=9)
    with torch.no_grad():
        want = hf(torch.from_numpy(ids)).logits.numpy()
    # a bare dict carries no config: nonstandard head_dim (16 here, not
    # GPT-2's 64) must be stated by the caller
    model = gpt2_from_hf(sd, heads=HEADS)
    got = np.asarray(model(jnp.asarray(ids)).value)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_gpt2_geometry_inferred():
    model = gpt2_from_hf(_hf_model())
    assert model.hidden == HIDDEN
    assert model.max_positions == POS
    assert len(model.blocks) == LAYERS
    assert model.blocks[0].attn.num_heads == HEADS
    assert model.tok_emb.weight.data.shape == (VOCAB, HIDDEN)
    # eval mode by default (imported checkpoints serve before they train)
    assert not model.training


def test_gpt2_converted_decodes(rng):
    """The KV-cache decode path reproduces the converted model's full
    forward — biases included (the interop config exercises exactly the
    biased-attention decode the advisor flagged in round 2)."""
    import jax
    from apex_tpu.nn.modules import Ctx

    model = gpt2_from_hf(_hf_model())
    ids = jnp.asarray(_ids(rng, b=2, s=11))
    full = np.asarray(model(ids).value)

    params = list(model.parameters())
    ctx = Ctx(env={id(p): p.data for p in params}, training=False)
    caches = model.init_caches(2, 11)
    got = []
    for t in range(11):
        logits, caches = model.decode_step(ctx, ids[:, t], caches,
                                           jnp.asarray(t))
        got.append(np.asarray(logits))
    np.testing.assert_allclose(np.stack(got, axis=1), full,
                               rtol=2e-4, atol=2e-4)


def test_interleave_roundtrip():
    """The QKV permutation maps HF's type-major packing onto the
    reference interleaved layout exactly (spot-check one head/type)."""
    heads, d = 4, 8
    e = heads * d
    rng = np.random.default_rng(0)
    w_t = rng.standard_normal((3 * e, e)).astype(np.float32)  # [Q|K|V] rows
    out = _interleave_qkv(w_t, heads, d)
    # head h, type k (0=q), feature f lives at HF row k*e + h*d + f
    for h in (0, 3):
        for k in (0, 2):
            np.testing.assert_array_equal(
                out[h * 3 * d + k * d: h * 3 * d + (k + 1) * d],
                w_t[k * e + h * d: k * e + h * d + d])
    b = rng.standard_normal((3 * e,)).astype(np.float32)
    ob = _interleave_qkv_bias(b, heads, d)
    assert ob[0 * 3 * d + 1 * d] == b[1 * e + 0]  # head0, k-bias, feat0


def test_shape_mismatch_raises():
    hf = _hf_model()
    sd = {k: v.numpy() for k, v in hf.state_dict().items()}
    sd["transformer.ln_f.weight"] = np.ones((HIDDEN + 1,), np.float32)
    with pytest.raises(ValueError, match="shape mismatch"):
        gpt2_from_hf(sd)


def test_converted_model_trains(rng):
    """Fine-tuning the imported model under the fused step: loss on a
    fixed batch decreases (biased default-impl attention through
    make_train_step)."""
    from apex_tpu.nn import functional as F
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.training import make_train_step

    model = gpt2_from_hf(_hf_model(), dropout=0.0)
    model.train()
    opt = FusedAdam(list(model.parameters()), lr=1e-4)

    def lm_loss(logits, ids):
        flat = logits[:, :-1].reshape((-1, VOCAB))
        tgt = ids[:, 1:].reshape((-1,))
        return jnp.mean(F.cross_entropy(flat, tgt))

    step = make_train_step(model, opt, lm_loss, half_dtype=None,
                           loss_scale=1.0)
    ids = jnp.asarray(_ids(rng, b=4, s=16))
    l0 = float(step(ids, ids))
    for _ in range(10):
        l = float(step(ids, ids))
    assert np.isfinite(l) and l < l0


def test_untied_head_rejected():
    """A checkpoint whose lm_head is genuinely untied from wte cannot be
    represented by the weight-tied family — it must refuse, not silently
    emit different logits."""
    hf = _hf_model()
    sd = {k: v.numpy().copy() for k, v in hf.state_dict().items()}
    sd["lm_head.weight"] = sd["lm_head.weight"] + 1.0
    with pytest.raises(ValueError, match="not tied"):
        gpt2_from_hf(sd, heads=HEADS)


def test_bf16_checkpoint_loads(rng):
    """bf16-dtype checkpoints (the default distribution dtype for real
    weights) convert without a numpy bf16 TypeError."""
    hf = _hf_model().to(torch.bfloat16)
    model = gpt2_from_hf(hf)
    ids = _ids(rng, b=1, s=7)
    got = np.asarray(model(jnp.asarray(ids)).value)
    assert np.isfinite(got).all()


# ---------------------------------------------------------------------------
# Llama / Mistral family (llama_from_hf)
# ---------------------------------------------------------------------------

L_VOCAB, L_HIDDEN, L_LAYERS, L_HEADS, L_KV = 211, 64, 2, 4, 2


def _hf_llama(seed=0, tied=False, **over):
    cfg = transformers.LlamaConfig(
        vocab_size=L_VOCAB, hidden_size=L_HIDDEN,
        num_hidden_layers=L_LAYERS, num_attention_heads=L_HEADS,
        num_key_value_heads=L_KV, intermediate_size=96,
        max_position_embeddings=64, rms_norm_eps=1e-6,
        rope_theta=10000.0, attention_dropout=0.0,
        tie_word_embeddings=tied, **over)
    torch.manual_seed(seed)
    m = transformers.LlamaForCausalLM(cfg)
    m.eval()
    return m


def _lids(rng, b=3, s=13):
    return rng.integers(0, L_VOCAB, (b, s))


def test_llama_logit_parity(rng):
    """Converted LlamaModel (GQA, rotate_half RoPE, RMSNorm, SwiGLU)
    reproduces transformers' torch forward logits."""
    from apex_tpu.models import llama_from_hf

    hf = _hf_llama()
    ids = _lids(rng)
    with torch.no_grad():
        want = hf(torch.from_numpy(ids)).logits.numpy()
    model = llama_from_hf(hf)
    got = np.asarray(model(jnp.asarray(ids)).value)
    np.testing.assert_allclose(got, want, rtol=3e-4, atol=3e-4)


def test_llama_from_state_dict_requires_heads(rng):
    from apex_tpu.models import llama_from_hf

    hf = _hf_llama(seed=1)
    sd = {k: v.numpy() for k, v in hf.state_dict().items()}
    with pytest.raises(ValueError, match="heads="):
        llama_from_hf(sd)
    ids = _lids(rng, b=2, s=9)
    with torch.no_grad():
        want = hf(torch.from_numpy(ids)).logits.numpy()
    model = llama_from_hf(sd, heads=L_HEADS)   # kv_heads from the tensors
    assert model.blocks[0].kv_heads == L_KV
    got = np.asarray(model(jnp.asarray(ids)).value)
    np.testing.assert_allclose(got, want, rtol=3e-4, atol=3e-4)


def test_llama_tied_checkpoint_parity(rng):
    """tie_word_embeddings checkpoints serialize no lm_head.weight; the
    embedding loads into the (untied here) head — the tied forward."""
    from apex_tpu.models import llama_from_hf

    hf = _hf_llama(seed=2, tied=True)
    sd = {k: v.numpy() for k, v in hf.state_dict().items()}
    # state_dict() materializes the tied alias; serialized tied
    # checkpoints (safetensors dedup) ship without the key — simulate
    sd.pop("lm_head.weight", None)
    ids = _lids(rng, b=2, s=8)
    with torch.no_grad():
        want = hf(torch.from_numpy(ids)).logits.numpy()
    got = np.asarray(llama_from_hf(sd, heads=L_HEADS)(
        jnp.asarray(ids)).value)
    np.testing.assert_allclose(got, want, rtol=3e-4, atol=3e-4)


def test_llama_converted_decodes(rng):
    """KV-cache greedy decode from the converted model matches its own
    full-forward argmax continuation (GQA cache path included)."""
    from apex_tpu.models import llama_from_hf
    from apex_tpu.models.gpt import generate

    model = llama_from_hf(_hf_llama(seed=3))
    prompt = jnp.asarray(_lids(rng, b=2, s=6))
    out = generate(model, prompt, max_new_tokens=5)
    assert out.shape == (2, 11)
    # oracle: re-run the full forward argmax step by step
    cur = prompt
    for _ in range(5):
        logits = model(cur).value
        nxt = jnp.argmax(logits[:, -1], axis=-1)
        cur = jnp.concatenate([cur, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(cur))


def test_llama_geometry_inferred():
    from apex_tpu.models import llama_from_hf

    model = llama_from_hf(_hf_llama())
    assert model.hidden == L_HIDDEN
    assert len(model.blocks) == L_LAYERS
    assert model.blocks[0].heads == L_HEADS
    assert model.blocks[0].kv_heads == L_KV
    assert not model.training


# ---------------------------------------------------------------------------
# export (to-HF round trips)
# ---------------------------------------------------------------------------

def test_gpt2_roundtrip_export(rng):
    """apex_tpu -> HF state dict -> transformers forward reproduces the
    exported model's logits (train here, serve anywhere)."""
    from apex_tpu.models import gpt2_to_hf_state_dict

    hf = _hf_model(seed=5)
    model = gpt2_from_hf(hf)           # carry known weights
    sd = gpt2_to_hf_state_dict(model)
    fresh = _hf_model(seed=6)          # different weights
    missing, unexpected = fresh.load_state_dict(
        {k: torch.from_numpy(v.copy()) for k, v in sd.items()},
        strict=False)
    assert not unexpected
    assert all("attn.bias" in k or "masked_bias" in k for k in missing)
    ids = _ids(rng, b=2, s=11)
    with torch.no_grad():
        got = fresh(torch.from_numpy(ids)).logits.numpy()
    want = np.asarray(model(jnp.asarray(ids)).value)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_llama_roundtrip_export(rng):
    from apex_tpu.models import llama_from_hf, llama_to_hf_state_dict

    hf = _hf_llama(seed=7)
    model = llama_from_hf(hf)
    sd = llama_to_hf_state_dict(model)
    fresh = _hf_llama(seed=8)
    missing, unexpected = fresh.load_state_dict(
        {k: torch.from_numpy(v.copy()) for k, v in sd.items()},
        strict=False)
    assert not unexpected and not missing
    ids = _lids(rng, b=2, s=9)
    with torch.no_grad():
        got = fresh(torch.from_numpy(ids)).logits.numpy()
    want = np.asarray(model(jnp.asarray(ids)).value)
    np.testing.assert_allclose(got, want, rtol=3e-4, atol=3e-4)


def test_llama_export_refuses_moe():
    from apex_tpu.models import LlamaModel, llama_to_hf_state_dict

    m = LlamaModel(vocab_size=64, hidden=32, layers=2, heads=2,
                   moe_axis="data", moe_num_experts=4)
    with pytest.raises(ValueError, match="MoE"):
        llama_to_hf_state_dict(m)


def test_gpt2_export_refuses_moe():
    from apex_tpu.models import GptModel, gpt2_to_hf_state_dict

    m = GptModel(vocab_size=64, hidden=32, layers=2, heads=2,
                 attn_dropout=0.0, moe_axis="data", moe_num_experts=4)
    with pytest.raises(ValueError, match="MoE"):
        gpt2_to_hf_state_dict(m)


def test_mixtral_logit_parity(rng):
    """MixtralForCausalLM -> Mixtral-shape MoE Llama: logits match
    transformers' torch forward on the 8-expert/8-device mesh (gating
    semantics identical — softmax, top-2, pair-normalized; capacity
    raised so the Switch dispatch drops nothing)."""
    import jax
    from jax.sharding import Mesh, PartitionSpec as P
    from apex_tpu.models import mixtral_from_hf
    from apex_tpu.nn.modules import Ctx

    cfg = transformers.MixtralConfig(
        vocab_size=131, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2,
        intermediate_size=48, num_local_experts=8,
        num_experts_per_tok=2, max_position_embeddings=64,
        rope_theta=10000.0, attention_dropout=0.0, sliding_window=None)
    torch.manual_seed(0)
    hf = transformers.MixtralForCausalLM(cfg).eval()
    ids = rng.integers(0, 131, (2, 9))
    with torch.no_grad():
        want = hf(torch.from_numpy(ids)).logits.numpy()

    model = mixtral_from_hf(hf, capacity_factor=16.0)
    assert len(model.blocks) == 2
    assert model.blocks[0].num_experts == 8
    params = list(model.parameters())
    mesh = Mesh(np.array(jax.devices()), ("data",))

    def f(vals, ids):
        ctx = Ctx(env={id(p): v for p, v in zip(params, vals)},
                  training=False)
        return model.forward(ctx, ids)

    got = jax.jit(jax.shard_map(
        f, mesh=mesh, in_specs=(P(), P()), out_specs=P(),
        check_vma=False))([p.data for p in params], jnp.asarray(ids))
    np.testing.assert_allclose(np.asarray(got), want, rtol=4e-4,
                               atol=4e-4)


# ---------------------------------------------------------------- resnet


def _torch_resnet_sd(model, dtype=None):
    """Export an apex_tpu ResNet's values as a torchvision-style torch
    state dict (the module trees share attribute names, so the key set
    IS torchvision's — including BN running stats and the int64
    num_batches_tracked counter)."""
    sd = {}
    for n, p in model.named_parameters():
        t = torch.from_numpy(np.asarray(p.data, np.float32))
        sd[n] = t.to(dtype) if dtype is not None else t
    for n, b in model.named_buffers():
        if n.endswith("num_batches_tracked"):
            sd[n] = torch.tensor(int(np.asarray(b.data)),
                                 dtype=torch.int64)
        else:
            t = torch.from_numpy(np.asarray(b.data, np.float32))
            sd[n] = t.to(dtype) if dtype is not None else t
    return sd


def _trained_stats_resnet(seed=3):
    """A resnet18 whose BN running stats are NOT the init zeros/ones
    (one train-mode forward), so stat loading is actually exercised."""
    import apex_tpu.nn as nn
    from apex_tpu.models import resnet18

    nn.manual_seed(seed)
    m = resnet18(num_classes=10, small_input=True)
    rng = np.random.default_rng(seed)
    m.train()
    m(jnp.asarray(rng.standard_normal((2, 3, 16, 16)), jnp.float32))
    m.eval()
    return m


def test_resnet_from_torch_logit_parity(rng):
    from apex_tpu.models import resnet_from_torch

    src = _trained_stats_resnet()
    x = jnp.asarray(rng.standard_normal((2, 3, 16, 16)), jnp.float32)
    want = np.asarray(src(x).value)

    got_model = resnet_from_torch(_torch_resnet_sd(src))
    assert not got_model.training
    got = np.asarray(got_model(x).value)
    np.testing.assert_array_equal(got, want)
    # running stats and the step counter came through
    np.testing.assert_array_equal(
        np.asarray(got_model.bn1.running_mean.data),
        np.asarray(src.bn1.running_mean.data))
    assert int(got_model.bn1.num_batches_tracked.data) \
        == int(src.bn1.num_batches_tracked.data)


def test_resnet_from_torch_geometry_inferred():
    from apex_tpu.models import resnet50, resnet_from_torch
    import apex_tpu.nn as nn

    nn.manual_seed(0)
    src = resnet50(num_classes=7)
    m = resnet_from_torch(_torch_resnet_sd(src))
    # bottleneck stages [3, 4, 6, 3], 7 classes, imagenet stem
    assert len(m.layer3) == 6 and hasattr(m.layer1[0], "conv3")
    assert m.fc.weight.shape == (7, 2048)
    assert m.conv1.weight.shape[-1] == 7      # 7x7 stem kernel


def test_resnet_from_torch_ddp_prefix_and_wrapper(rng):
    """torch.load of the reference imagenet example's checkpoint format:
    {'state_dict': {'module.conv1.weight': ...}} loads transparently
    (reference examples/imagenet/main_amp.py:180-195 resume)."""
    from apex_tpu.models import resnet_from_torch

    src = _trained_stats_resnet()
    sd = {"module." + k: v for k, v in _torch_resnet_sd(src).items()}
    ckpt = {"state_dict": sd, "epoch": 3, "best_prec1": 11.1}
    m = resnet_from_torch(ckpt)
    x = jnp.asarray(rng.standard_normal((1, 3, 16, 16)), jnp.float32)
    np.testing.assert_array_equal(np.asarray(m(x).value),
                                  np.asarray(src(x).value))


def test_resnet_from_torch_bf16_checkpoint(rng):
    from apex_tpu.models import resnet_from_torch

    src = _trained_stats_resnet()
    m = resnet_from_torch(_torch_resnet_sd(src, dtype=torch.bfloat16))
    x = jnp.asarray(rng.standard_normal((1, 3, 16, 16)), jnp.float32)
    got = np.asarray(m(x).value)
    want = np.asarray(src(x).value)
    np.testing.assert_allclose(got, want, rtol=0.1, atol=0.15)


def test_resnet_from_torch_rejects_bad_dicts():
    from apex_tpu.models import resnet_from_torch

    src = _trained_stats_resnet()
    sd = _torch_resnet_sd(src)
    with pytest.raises(ValueError, match="does not look like"):
        resnet_from_torch({"foo.weight": sd["conv1.weight"]})
    missing = dict(sd)
    del missing["layer1.0.bn1.weight"]
    with pytest.raises(ValueError, match="missing parameter"):
        resnet_from_torch(missing)
    extra = dict(sd)
    extra["layer9.0.conv1.weight"] = sd["conv1.weight"]
    with pytest.raises(ValueError, match="no slot"):
        resnet_from_torch(extra)
    # old checkpoints without num_batches_tracked still load
    old = {k: v for k, v in sd.items()
           if not k.endswith("num_batches_tracked")}
    m = resnet_from_torch(old)
    assert not m.training
