"""BERT encoder family (models/bert.py): shapes, padding-mask semantics,
gradient flow incl. the tied MLM decoder, and a FusedLAMB train-step
convergence check (BASELINE.md config 4 in miniature)."""
import jax.numpy as jnp
import numpy as np
import pytest

import apex_tpu.nn as nn
from apex_tpu.models import BertModel, BertForMaskedLM, bert_base

V, H, L, HEADS, I, S = 97, 32, 2, 4, 64, 16


def _tiny_bert(**kw):
    nn.manual_seed(3)
    return BertModel(vocab_size=V, hidden=H, layers=L, heads=HEADS,
                     intermediate=I, max_positions=64, dropout=0.0,
                     attn_dropout=0.0, **kw)


def _tiny_mlm():
    nn.manual_seed(3)
    return BertForMaskedLM(vocab_size=V, hidden=H, layers=L, heads=HEADS,
                           intermediate=I, max_positions=64, dropout=0.0,
                           attn_dropout=0.0)


def _ids(rng, b=2, s=S):
    return jnp.asarray(rng.integers(0, V, (b, s)))


def test_encoder_shapes(rng):
    m = _tiny_bert()
    out = m(_ids(rng))
    assert out.shape == (2, S, H)
    assert out.dtype == jnp.float32


def test_oversized_sequence_raises(rng):
    m = _tiny_bert()
    with pytest.raises(ValueError, match="max_positions"):
        m(_ids(rng, s=65))  # max_positions=64


def test_token_type_changes_output(rng):
    m = _tiny_bert()
    ids = _ids(rng)
    out0 = np.asarray(m(ids))
    tt = jnp.ones_like(ids)
    out1 = np.asarray(m(ids, tt))
    assert np.abs(out0 - out1).max() > 1e-4


def test_padding_mask_isolates_real_tokens(rng):
    """Outputs at real positions must not depend on what the padding
    token ids are, when the padding is masked out."""
    m = _tiny_bert()
    m.eval()
    ids = np.asarray(_ids(rng))
    mask = np.ones_like(ids)
    mask[:, 10:] = 0  # positions 10+ are padding
    ids2 = ids.copy()
    ids2[:, 10:] = (ids2[:, 10:] + 7) % V  # different padding content
    out1 = np.asarray(m(jnp.asarray(ids), None, jnp.asarray(mask)))
    out2 = np.asarray(m(jnp.asarray(ids2), None, jnp.asarray(mask)))
    np.testing.assert_allclose(out1[:, :10], out2[:, :10],
                               rtol=1e-5, atol=1e-5)
    assert np.abs(out1[:, 10:] - out2[:, 10:]).max() > 1e-4


def test_mlm_logits_and_tied_decoder_grads(rng):
    mlm = _tiny_mlm()
    ids = _ids(rng)
    logits = mlm(ids)
    assert logits.shape == (2, S, V)
    labels = jnp.asarray(rng.integers(0, V, (2 * S,)))
    loss = nn.CrossEntropyLoss()(logits.reshape((-1, V)), labels)
    loss.backward()
    grads = [p.grad for p in mlm.parameters()]
    assert all(g is not None for g in grads)
    # the tied embedding gets gradient from BOTH the input lookup and the
    # output projection; it must be finite and nonzero
    emb_grad = mlm.bert.tok_emb.weight.grad
    assert np.isfinite(np.asarray(emb_grad)).all()
    assert float(jnp.abs(emb_grad).max()) > 0


def test_fused_lamb_train_step_converges(rng):
    from apex_tpu.nn import functional as F
    from apex_tpu.optimizers import FusedLAMB
    from apex_tpu.training import make_train_step

    mlm = _tiny_mlm()
    opt = FusedLAMB(list(mlm.parameters()), lr=1e-2, weight_decay=0.01)

    def mlm_loss(logits, labels):
        flat = logits.reshape((-1, V))
        lab = labels.reshape((-1,))
        m = (lab >= 0).astype(jnp.float32)
        losses = F.cross_entropy(flat, jnp.maximum(lab, 0),
                                 reduction="none")
        return jnp.sum(losses * m) / jnp.maximum(jnp.sum(m), 1.0)

    step = make_train_step(mlm, opt, mlm_loss, half_dtype=jnp.bfloat16,
                           loss_scale=1.0)
    ids = _ids(rng, b=4)
    labels = np.full((4, S), -100, np.int32)
    pick = np.random.default_rng(0).random((4, S)) < 0.3
    labels[pick] = np.random.default_rng(1).integers(0, V, int(pick.sum()))
    labels = jnp.asarray(labels)
    losses = [float(step(ids, labels)) for _ in range(8)]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_bert_pallas_vs_fallback_loss_parity(rng):
    """L1-style oracle on the transformer stack: the Pallas build
    (interpret) and the jnp fallback must produce matching MLM loss curves
    through the fused step (flash attention + fused LN under both)."""
    from apex_tpu.nn import functional as F
    from apex_tpu.ops.pallas import force_mode
    from apex_tpu.optimizers import FusedLAMB
    from apex_tpu.training import make_train_step

    def run(mode):
        mlm = _tiny_mlm()
        opt = FusedLAMB(list(mlm.parameters()), lr=1e-2)

        def mlm_loss(logits, labels):
            flat = logits.reshape((-1, V))
            lab = labels.reshape((-1,))
            m = (lab >= 0).astype(jnp.float32)
            losses = F.cross_entropy(flat, jnp.maximum(lab, 0),
                                     reduction="none")
            return jnp.sum(losses * m) / jnp.maximum(jnp.sum(m), 1.0)

        step = make_train_step(mlm, opt, mlm_loss, loss_scale=1.0)
        r = np.random.default_rng(7)
        ids = jnp.asarray(r.integers(0, V, (4, S)))
        labels = np.full((4, S), -100, np.int32)
        pick = r.random((4, S)) < 0.3
        labels[pick] = r.integers(0, V, int(pick.sum()))
        labels = jnp.asarray(labels)
        with force_mode(mode):
            return [float(step(ids, labels)) for _ in range(4)]

    pallas_build = run("interpret")
    python_build = run("off")
    np.testing.assert_allclose(pallas_build, python_build,
                               rtol=2e-3, atol=2e-4)


def test_remat_grads_match_with_padding_mask(rng):
    """remat=True matches the non-remat encoder exactly, through the
    multi-input checkpoint bridge (hidden states + key-padding mask)."""
    import jax

    ids = jnp.asarray(rng.integers(0, V, (2, S)))
    mask = np.ones((2, S), np.int32)
    mask[:, S - 5:] = 0                      # padded tail
    mask = jnp.asarray(mask)
    outs = {}
    for remat in (False, True):
        m = _tiny_bert(remat=remat)
        params = [p for p in m.parameters()]

        def loss_fn(vals):
            from apex_tpu.nn.modules import Ctx
            ctx = Ctx(env={id(p): v for p, v in zip(params, vals)},
                      stats_out={}, training=False)
            out = m.forward(ctx, ids, attention_mask=mask)
            return jnp.mean(out.astype(jnp.float32) ** 2)

        vals = [p.data for p in params]
        loss, grads = jax.jit(jax.value_and_grad(
            lambda v: loss_fn(v)))(vals)
        outs[remat] = (float(loss), [np.asarray(g) for g in grads])
    assert outs[False][0] == outs[True][0]
    for a, b in zip(outs[False][1], outs[True][1]):
        np.testing.assert_allclose(a, b, rtol=1e-6, atol=1e-7)


def test_sequence_parallel_bert_matches_unsharded(rng):
    """BertModel(sp_axis=...) under shard_map (Ulysses all-to-all, ids
    sharded on dim 1, GLOBAL padding mask replicated): outputs and
    parameter gradients match the unsharded encoder."""
    import jax
    from jax.sharding import Mesh, PartitionSpec as P
    from apex_tpu.nn.modules import Ctx

    S_G, HEADS8 = 32, 8   # heads must divide by the axis size

    def build(sp):
        nn.manual_seed(3)
        return BertModel(vocab_size=V, hidden=H, layers=2, heads=HEADS8,
                         intermediate=I, max_positions=S_G, dropout=0.0,
                         attn_dropout=0.0, sp_axis=sp)

    ids = jnp.asarray(rng.integers(0, V, (2, S_G)))
    mask = np.ones((2, S_G), np.int32)
    mask[:, S_G - 6:] = 0
    mask = jnp.asarray(mask)
    w = jnp.asarray(rng.standard_normal((2, S_G, H)), jnp.float32)

    m_ref = build(None)
    params_ref = list(m_ref.parameters())

    def ref_loss(vals):
        ctx = Ctx(env={id(p): v for p, v in zip(params_ref, vals)},
                  training=False)
        return jnp.sum(m_ref.forward(ctx, ids, attention_mask=mask) * w)

    vals = [p.data for p in params_ref]
    ref_out = np.asarray(m_ref(ids, None, mask).value)
    ref_grads = jax.grad(ref_loss)(vals)

    m_sp = build("sp")
    params_sp = list(m_sp.parameters())
    mesh = Mesh(np.array(jax.devices()), ("sp",))

    def sp_fwd(vals, ids_l, mask_g):
        ctx = Ctx(env={id(p): v for p, v in zip(params_sp, vals)},
                  training=False)
        return m_sp.forward(ctx, ids_l, attention_mask=mask_g)

    got = jax.jit(jax.shard_map(
        sp_fwd, mesh=mesh, in_specs=(P(), P(None, "sp"), P()),
        out_specs=P(None, "sp", None), check_vma=False))(vals, ids, mask)
    np.testing.assert_allclose(np.asarray(got), ref_out,
                               rtol=2e-4, atol=2e-4)

    def sp_loss(vals, ids, mask, w):
        def f(vals, ids_l, mask_g, w_l):
            out = sp_fwd(vals, ids_l, mask_g)
            return jax.lax.psum(jnp.sum(out * w_l), "sp")
        return jax.shard_map(
            f, mesh=mesh,
            in_specs=(P(), P(None, "sp"), P(), P(None, "sp", None)),
            out_specs=P(), check_vma=False)(vals, ids, mask, w)

    sp_grads = jax.jit(jax.grad(sp_loss))(vals, ids, mask, w)
    for a, b in zip(ref_grads, sp_grads):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-4, atol=3e-4)


def test_sp_mask_requires_ulysses():
    """The ring impl carries no mask operand — masked SP must name the
    ulysses requirement."""
    import pytest
    from apex_tpu.contrib.multihead_attn.attn_funcs import self_attn_func
    with pytest.raises(NotImplementedError, match="ulysses"):
        self_attn_func(False, False, 2, 1.0, jnp.zeros((4, 2, 8)),
                       jnp.zeros((24, 8)), jnp.zeros((8, 8)),
                       mask=jnp.zeros((2, 4), bool),
                       seq_parallel_axis="sp", seq_parallel_impl="ring")


def test_mlm_positions_gather_matches_full_head(rng):
    """mlm_positions (the reference masked_lm_positions convention):
    the per-position MLM head commutes with the gather, so gathered
    logits must equal the full forward's logits at those positions."""
    nn.manual_seed(9)
    m = bert_base(vocab_size=97, hidden=32, layers=2, heads=4,
                  intermediate=64, max_positions=32, dropout=0.0,
                  attn_dropout=0.0).eval()
    ids = jnp.asarray(rng.integers(0, 97, (2, 16)))
    pos = jnp.asarray(np.stack([np.sort(rng.choice(16, 4, replace=False))
                                for _ in range(2)]))
    from apex_tpu.nn.modules import Ctx
    params = list(m.parameters()) + list(m.buffers())
    ctx = Ctx(env={id(p): p.data for p in params}, stats_out={},
              training=False)
    full = m.forward(ctx, ids)
    gathered = m.forward(ctx, ids, mlm_positions=pos)
    ref = jnp.take_along_axis(full, pos[..., None], axis=1)
    np.testing.assert_allclose(np.asarray(gathered), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)
    # tuple-input spelling (the fused train step's convention)
    tup = m.forward(ctx, (ids, pos))
    np.testing.assert_allclose(np.asarray(tup), np.asarray(gathered),
                               rtol=1e-6)


def test_gathered_mlm_train_step_decreases_loss(rng):
    from apex_tpu.optimizers import FusedLAMB
    from apex_tpu.training import make_train_step
    from apex_tpu.nn import functional as F

    nn.manual_seed(3)
    m = bert_base(vocab_size=97, hidden=32, layers=2, heads=4,
                  intermediate=64, max_positions=32, dropout=0.0,
                  attn_dropout=0.0)
    opt = FusedLAMB(list(m.parameters()), lr=1e-3)

    def loss_fn(logits, labels_g):
        return F.cross_entropy(
            logits.reshape((-1, 97)), labels_g.reshape((-1,)))

    step = make_train_step(m, opt, loss_fn, half_dtype=jnp.bfloat16,
                           loss_scale=1.0)
    ids = jnp.asarray(rng.integers(0, 97, (4, 16)))
    pos = jnp.asarray(np.stack([np.sort(rng.choice(16, 4, replace=False))
                                for _ in range(4)]))
    labels = jnp.asarray(rng.integers(0, 97, (4, 4)))
    losses = [float(step((ids, pos), labels)) for _ in range(8)]
    assert losses[-1] < losses[0]
