"""Tape autograd: grads match jax.grad oracles, compiled-program caching,
accumulation, no_grad, detach."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import apex_tpu.nn as nn
from apex_tpu import autograd
from apex_tpu.nn import Parameter


def test_simple_op_grads_match_jax(rng):
    w = Parameter(jnp.asarray(rng.standard_normal((4, 4)), jnp.float32))
    x = jnp.asarray(rng.standard_normal((4,)), jnp.float32)
    t = autograd.lift(w)
    loss = ((t @ x) ** 2.0).sum()
    loss.backward()
    ref = jax.grad(lambda w: ((w @ x) ** 2.0).sum())(w.data)
    np.testing.assert_allclose(np.asarray(w.grad), np.asarray(ref), rtol=1e-5)


def test_module_grads_match_jax(rng):
    nn.manual_seed(3)
    lin = nn.Linear(5, 3)
    x = jnp.asarray(rng.standard_normal((7, 5)), jnp.float32)
    out = lin(x)
    loss = (out ** 2.0).mean()
    loss.backward()

    from apex_tpu.nn.modules import Ctx

    def f(w, b):
        env = {id(lin.weight): w, id(lin.bias): b}
        y = lin.forward(Ctx(env=env), x)
        return jnp.mean(y ** 2.0)

    gw, gb = jax.grad(f, argnums=(0, 1))(lin.weight.data, lin.bias.data)
    np.testing.assert_allclose(np.asarray(lin.weight.grad), np.asarray(gw),
                               rtol=1e-5)
    np.testing.assert_allclose(np.asarray(lin.bias.grad), np.asarray(gb),
                               rtol=1e-5)


def test_grad_accumulation(rng):
    nn.manual_seed(3)
    lin = nn.Linear(5, 3)
    x = jnp.asarray(rng.standard_normal((7, 5)), jnp.float32)
    (lin(x) ** 2.0).mean().backward()
    g1 = np.asarray(lin.weight.grad)
    (lin(x) ** 2.0).mean().backward()
    np.testing.assert_allclose(np.asarray(lin.weight.grad), 2 * g1, rtol=1e-5)


def test_program_cache_hit(rng):
    nn.manual_seed(3)
    lin = nn.Linear(5, 3)
    x = jnp.asarray(rng.standard_normal((7, 5)), jnp.float32)
    # the LRU may be at capacity from earlier suites, which would evict on
    # insert and break the +1 bookkeeping below
    autograd._compiled_cache.clear()
    before = len(autograd._compiled_cache)
    for _ in range(4):
        (lin(x) ** 2.0).mean().backward()
        lin.weight.grad = None
        lin.bias.grad = None
    assert len(autograd._compiled_cache) == before + 1


def test_no_grad_skips_recording(rng):
    nn.manual_seed(3)
    lin = nn.Linear(5, 3)
    x = jnp.asarray(rng.standard_normal((2, 5)), jnp.float32)
    with autograd.no_grad():
        out = lin(x)
    assert out.op == "const"
    loss = (out ** 2.0).sum()
    with pytest.raises(RuntimeError):
        loss.backward()


def test_detach_blocks_grad(rng):
    w = Parameter(jnp.ones((3,), jnp.float32))
    t = autograd.lift(w).detach()
    loss = (t * 2.0).sum()
    with pytest.raises(RuntimeError):
        loss.backward()


def test_backward_requires_scalar(rng):
    w = Parameter(jnp.ones((3,), jnp.float32))
    t = autograd.lift(w) * 2.0
    with pytest.raises(RuntimeError):
        t.backward()


def test_dropout_deterministic_between_fwd_and_bwd(rng):
    """The recorded dropout key must make backward's re-execution see the
    same mask (gradient exactly matches the eager forward's mask)."""
    nn.manual_seed(7)
    model = nn.Sequential(nn.Linear(16, 16), nn.Dropout(0.5))
    x = jnp.asarray(rng.standard_normal((4, 16)), jnp.float32)
    out = model(x)
    mask = np.asarray(out.value) != 0
    loss = out.sum()
    loss.backward()
    # grad of sum wrt bias: each bias column contributes (mask_count / keep)
    gb = np.asarray(model[0].bias.grad)
    expected = mask.sum(axis=0) / 0.5
    np.testing.assert_allclose(gb, expected, rtol=1e-5)


def test_dynamic_array_index(rng):
    """Array indices (gathers) are tape inputs, not static constants."""
    w = Parameter(jnp.asarray(rng.standard_normal((6, 4)), jnp.float32))
    idx = jnp.asarray([0, 2, 5])
    t = autograd.lift(w)[idx]
    loss = t.sum()
    loss.backward()
    g = np.asarray(w.grad)
    assert g[0].sum() == 4 and g[1].sum() == 0 and g[5].sum() == 4
    # advanced 2d index (row, col) pattern
    w.grad = None
    rows = jnp.asarray([0, 1])
    cols = jnp.asarray([1, 3])
    t2 = autograd.lift(w)[rows, cols]
    t2.sum().backward()
    g2 = np.asarray(w.grad)
    assert g2[0, 1] == 1 and g2[1, 3] == 1 and g2.sum() == 2


def test_tensor_numpy_surface(rng):
    w = Parameter(jnp.ones((2, 2), jnp.float32))
    t = autograd.lift(w) * 3.0
    assert t.shape == (2, 2)
    assert float(t.sum()) == 12.0
    assert t.numpy().shape == (2, 2)
    assert t.reshape(4).shape == (4,)
    assert t[0].shape == (2,)
