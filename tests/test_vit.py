"""Vision Transformer family (models/vit.py): shapes, CLS pooling,
remat parity, fused-step training, and input-size validation."""
import numpy as np
import pytest

import jax.numpy as jnp

import apex_tpu.nn as nn
from apex_tpu.models import VitModel, vit_small
from apex_tpu.nn import functional as F
from apex_tpu.optimizers import FusedAdam
from apex_tpu.training import make_train_step


def _tiny(**kw):
    nn.manual_seed(0)
    return VitModel(**{**dict(image_size=32, patch_size=8, hidden=64,
                              layers=2, heads=4, num_classes=10), **kw})


def test_forward_shape_and_param_count(rng):
    model = _tiny()
    x = jnp.asarray(rng.standard_normal((3, 3, 32, 32)), jnp.float32)
    out = model(x)
    assert out.value.shape == (3, 10)
    # 16 patches + cls -> 17 positions
    assert model.pos_emb.shape == (17, 64)
    # the real geometry helper exists
    vs = vit_small()
    n = sum(int(np.prod(p.shape)) for p in vs.parameters())
    assert 20e6 < n < 25e6, n


def test_remat_matches_no_remat(rng):
    x = jnp.asarray(rng.standard_normal((2, 3, 32, 32)), jnp.float32)
    a = _tiny(remat=False).eval()(x).value
    b = _tiny(remat=True).eval()(x).value
    np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                               rtol=1e-5, atol=1e-6)


def test_trains_through_fused_step(rng):
    model = _tiny()
    opt = FusedAdam(list(model.parameters()), lr=1e-3, adam_w_mode=True,
                    weight_decay=0.05)
    step = make_train_step(model, opt,
                           lambda out, y: F.cross_entropy(out, y),
                           half_dtype=jnp.bfloat16)
    x = jnp.asarray(rng.standard_normal((16, 3, 32, 32)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 10, (16,)))
    l0 = float(step(x, y))
    for _ in range(15):
        l = float(step(x, y))
    assert np.isfinite(l) and l < 0.8 * l0


def test_input_size_validation(rng):
    with pytest.raises(ValueError, match="divisible"):
        VitModel(image_size=30, patch_size=8)
    model = _tiny()
    bad = jnp.zeros((1, 3, 64, 64), jnp.float32)   # 64 patches, built 16
    with pytest.raises(ValueError, match="patches"):
        model(bad)
