"""Fused LM-head + cross-entropy kernel (EXPERIMENTAL,
ops/pallas/lm_head_xent.py) vs the jnp logits-then-loss oracle: fwd
losses and both gradients, across block boundaries and non-multiple
vocab sizes.  Not wired into any model; the on-chip A/B row decides."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.ops.pallas import force_mode
from apex_tpu.ops.pallas.lm_head_xent import fused_lm_head_xent


def _oracle(x, emb, labels):
    logits = jnp.matmul(x.astype(jnp.float32),
                        emb.astype(jnp.float32).T)
    logp = jax.nn.log_softmax(logits, axis=-1)
    return -jnp.take_along_axis(logp, labels[:, None], axis=1)[:, 0]


@pytest.mark.parametrize("n,v,e", [(16, 300, 32), (40, 1030, 64),
                                   (300, 257, 48)])
def test_fused_lm_head_matches_oracle(rng, n, v, e):
    x = jnp.asarray(rng.standard_normal((n, e)) * 0.3, jnp.float32)
    emb = jnp.asarray(rng.standard_normal((v, e)) * 0.1, jnp.float32)
    labels = jnp.asarray(rng.integers(0, v, (n,)))

    def loss_k(x, emb):
        return jnp.sum(fused_lm_head_xent(x, emb, labels) ** 2)

    def loss_r(x, emb):
        return jnp.sum(_oracle(x, emb, labels) ** 2)

    with force_mode("interpret"):
        per_k = fused_lm_head_xent(x, emb, labels)
        gx_k, ge_k = jax.grad(loss_k, argnums=(0, 1))(x, emb)
    per_r = _oracle(x, emb, labels)
    gx_r, ge_r = jax.grad(loss_r, argnums=(0, 1))(x, emb)
    np.testing.assert_allclose(np.asarray(per_k), np.asarray(per_r),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(gx_k), np.asarray(gx_r),
                               rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(ge_k), np.asarray(ge_r),
                               rtol=1e-4, atol=1e-5)


def test_fused_lm_head_bf16(rng):
    x = jnp.asarray(rng.standard_normal((24, 32)) * 0.3, jnp.bfloat16)
    emb = jnp.asarray(rng.standard_normal((150, 32)) * 0.1, jnp.bfloat16)
    labels = jnp.asarray(rng.integers(0, 150, (24,)))
    with force_mode("interpret"):
        per = fused_lm_head_xent(x, emb, labels)
        gx, ge = jax.grad(lambda a, b: jnp.sum(
            fused_lm_head_xent(a, b, labels)), argnums=(0, 1))(x, emb)
    ref = _oracle(x, emb, labels)
    assert per.dtype == jnp.float32
    assert gx.dtype == jnp.bfloat16 and ge.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(per), np.asarray(ref),
                               rtol=2e-2, atol=2e-2)
    assert np.isfinite(np.asarray(gx, np.float32)).all()
    assert np.isfinite(np.asarray(ge, np.float32)).all()
