"""MLP vs nn.Sequential(Linear, ReLU) reference — mirrors
tests/L0/run_mlp/test_mlp.py:16-53 (numeric fwd/bwd equality, ReLU after
every layer, constructor contract)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import apex_tpu.nn as nn
from apex_tpu.mlp import MLP
from apex_tpu.nn.modules import Ctx

mlp_sizes = [80, 96, 64, 1]


def _ref_forward(mlp, x):
    for w, b in zip(mlp.weights, mlp.biases):
        x = jnp.maximum(x @ w.data.T + b.data, 0)
    return x


def test_creation():
    MLP(mlp_sizes)
    with pytest.raises(TypeError):
        MLP(mlp_sizes, bias=False)
    with pytest.raises(TypeError):
        MLP(mlp_sizes, relu=False)


def test_numeric(rng):
    nn.manual_seed(0)
    mlp = MLP(mlp_sizes)
    x = jnp.asarray(rng.uniform(-1, 1, (64, mlp_sizes[0])), jnp.float32)
    out = mlp(x)
    ref = _ref_forward(mlp, x)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-7)


def test_gradients_match_reference(rng):
    nn.manual_seed(1)
    mlp = MLP(mlp_sizes)
    x = jnp.asarray(rng.uniform(-1, 1, (32, mlp_sizes[0])), jnp.float32)
    params = list(mlp.parameters())

    def fused_loss(vals, x):
        ctx = Ctx(env={id(p): v for p, v in zip(params, vals)})
        return jnp.mean(mlp.forward(ctx, x)) * 10.0

    def ref_loss(vals, x):
        n = len(vals) // 2
        for w, b in zip(vals[:n], vals[n:]):
            x = jnp.maximum(x @ w.T + b, 0)
        return jnp.mean(x) * 10.0

    vals = [p.data for p in params]
    # parameters() yields weight_0, bias_0, weight_1 ... ; regroup to
    # (all weights, all biases) for the reference closure
    ws = [p.data for p in mlp.weights]
    bs = [p.data for p in mlp.biases]
    gf = jax.grad(fused_loss)(vals, x)
    gr = jax.grad(ref_loss)(ws + bs, x)
    named = {id(p): g for p, g in zip(params, gf)}
    ordered = [named[id(p)] for p in mlp.weights] + \
              [named[id(p)] for p in mlp.biases]
    for a, r in zip(ordered, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                   rtol=1e-5, atol=1e-6)


def test_half_policy(rng):
    """Under the amp half policy the GEMMs run in bf16 — reference registers
    mlp_function via amp.half_function (apex/mlp/mlp.py:22)."""
    from apex_tpu.amp.policy import CastPolicy, autocast
    nn.manual_seed(2)
    mlp = MLP(mlp_sizes)
    x = jnp.asarray(rng.uniform(-1, 1, (16, mlp_sizes[0])), jnp.float32)
    with autocast(CastPolicy(half_dtype=jnp.bfloat16)):
        out = mlp(x)
    assert out.dtype == jnp.bfloat16
