"""Tier-1 gate: the shipped tree is lint-clean.

Runs the full apex_tpu.lint rule set over ``apex_tpu/`` and
``examples/`` and asserts zero unsuppressed, non-baselined findings —
the analyzer-backed generalization of test_compat.py's original source
greps.  A budget assertion keeps the gate honest about cost: the whole
analysis (parse + call graph + 7 rules over the tree) must stay under
10s on CPU so it can run on every tier-1 invocation.
"""
import os

import pytest

from apex_tpu import lint as tpu_lint

pytestmark = pytest.mark.lint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TARGETS = [os.path.join(REPO, "apex_tpu"), os.path.join(REPO, "examples")]


def _run():
    return tpu_lint.run(TARGETS, root=REPO)


def test_tree_is_lint_clean():
    res = _run()
    assert not res.active(), (
        "tpu-lint findings in the shipped tree (fix, or suppress with "
        "`# tpu-lint: disable=RULE-ID reason`, or baseline via "
        "`python -m apex_tpu.lint --write-baseline`):\n"
        + "\n".join(f.format() for f in res.active()))


def test_gate_covers_the_tree_and_all_rules():
    res = _run()
    rel = {os.path.relpath(p, REPO) for p in res.files}
    # the walk-coverage guarantee, at gate level
    assert os.path.join("apex_tpu", "parallel", "auto.py") in rel
    assert os.path.join("apex_tpu", "runtime", "step_cache.py") in rel
    assert os.path.join("examples", "imagenet", "main_amp.py") in rel
    assert any(p.startswith(os.path.join("examples", "simple"))
               for p in rel)
    assert len(res.rules) >= 7


def test_gate_runtime_budget():
    res = _run()
    assert res.elapsed_s < 10.0, (
        f"lint gate took {res.elapsed_s:.1f}s — over the 10s tier-1 "
        f"budget (profile the rules; with the mtime+size parse cache "
        f"warm, repeat runs are dominated by the rule passes alone)")


def test_repeat_run_hits_the_caches():
    """The parse/analysis caches (keyed on mtime+size) make the second
    gate run substantially cheaper: the ASTs are shared objects, so the
    callgraph and dataflow survive across runs too."""
    from apex_tpu.lint import engine

    first = _run()
    assert engine._PARSE_CACHE           # populated by the run above
    cached_trees = {path: payload[1][1]
                    for path, payload in engine._PARSE_CACHE.items()}
    second = _run()
    # identical verdicts, and the exact same AST objects were reused
    key = lambda f: (f.rule, f.path, f.line, f.col, f.message)  # noqa: E731
    assert sorted(map(key, first.findings)) == \
           sorted(map(key, second.findings))
    reused = [path for path in cached_trees
              if engine._PARSE_CACHE.get(path)
              and engine._PARSE_CACHE[path][1][1] is cached_trees[path]]
    assert len(reused) == len(cached_trees)


def test_suppressions_carry_reasons():
    """Every in-tree suppression must state WHY (the workflow the docs
    promise: a bare disable is a review smell)."""
    res = _run()
    bare = [f for f in res.findings
            if f.suppressed and not f.suppress_reason.strip()]
    assert not bare, "suppressions without a reason:\n" + "\n".join(
        f.format() for f in bare)
