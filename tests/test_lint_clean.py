"""Tier-1 gate: the shipped tree is lint-clean.

Runs the full apex_tpu.lint rule set over ``apex_tpu/`` and
``examples/`` and asserts zero unsuppressed, non-baselined findings —
the analyzer-backed generalization of test_compat.py's original source
greps.  A budget assertion keeps the gate honest about cost: the whole
analysis (parse + call graph + 7 rules over the tree) must stay under
10s on CPU so it can run on every tier-1 invocation.
"""
import os

import pytest

from apex_tpu import lint as tpu_lint

pytestmark = pytest.mark.lint

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
TARGETS = [os.path.join(REPO, "apex_tpu"), os.path.join(REPO, "examples")]


def _run():
    return tpu_lint.run(TARGETS, root=REPO)


def test_tree_is_lint_clean():
    res = _run()
    assert not res.active(), (
        "tpu-lint findings in the shipped tree (fix, or suppress with "
        "`# tpu-lint: disable=RULE-ID reason`, or baseline via "
        "`python -m apex_tpu.lint --write-baseline`):\n"
        + "\n".join(f.format() for f in res.active()))


def test_gate_covers_the_tree_and_all_rules():
    res = _run()
    rel = {os.path.relpath(p, REPO) for p in res.files}
    # the walk-coverage guarantee, at gate level
    assert os.path.join("apex_tpu", "parallel", "auto.py") in rel
    assert os.path.join("apex_tpu", "runtime", "step_cache.py") in rel
    assert os.path.join("examples", "imagenet", "main_amp.py") in rel
    assert any(p.startswith(os.path.join("examples", "simple"))
               for p in rel)
    assert len(res.rules) >= 7


def test_gate_runtime_budget():
    res = _run()
    assert res.elapsed_s < 10.0, (
        f"lint gate took {res.elapsed_s:.1f}s — over the 10s tier-1 "
        f"budget (profile the rules; the engine is pure-AST and this "
        f"tree is ~130 files)")


def test_suppressions_carry_reasons():
    """Every in-tree suppression must state WHY (the workflow the docs
    promise: a bare disable is a review smell)."""
    res = _run()
    bare = [f for f in res.findings
            if f.suppressed and not f.suppress_reason.strip()]
    assert not bare, "suppressions without a reason:\n" + "\n".join(
        f.format() for f in bare)
