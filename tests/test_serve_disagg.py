"""Serve v2: disaggregated prefill/decode + batched speculative
decoding.  The load-bearing claims, pinned on cpu: the streamed KV
handoff's continuation is BITWISE the unified engine's continuation
(fp32 and int8 pools alike), chaos mid-handoff either retries cleanly
(injected failure) or leaves manifest-less debris the loader rejects
(kill), forced decode-side preemption recomputes to the same tokens,
the self-draft speculative arm commits >= 2 tokens per sequence per
tick without leaving the bucket grid (recompile-free ragged
acceptance), and the phase-split planner sends HBM-bandwidth-rich
members to decode."""
import os

import numpy as np
import pytest

import jax.numpy as jnp

from apex_tpu import nn
from apex_tpu.inference import make_self_draft
from apex_tpu.inference.session import DecodeSession
from apex_tpu.models.gpt import GptModel
from apex_tpu.observe import registry as obs
from apex_tpu.parallel import plan_serve_phase_split
from apex_tpu.runtime import chaos
from apex_tpu.runtime import step_cache as sc
from apex_tpu.runtime.resilience import (CheckpointCorruptError,
                                         CheckpointReshardError,
                                         discard_kv_handoff,
                                         load_kv_handoff,
                                         stream_kv_handoff)
from apex_tpu.serve import (DisaggregatedEngine, Request, ServeEngine,
                            bucket)
from apex_tpu.serve.pool import init_pool_buffer

pytestmark = pytest.mark.serve

PROMPTS = [[5, 9, 11, 3], [7, 2], [1, 2, 3, 4, 5, 6, 7, 8, 9],
           [12, 30, 4]]
MAX_NEW = 6


@pytest.fixture(scope="module")
def model():
    nn.manual_seed(6)
    m = GptModel(vocab_size=73, hidden=32, layers=2, heads=4,
                 max_positions=96, dropout=0.0, attn_dropout=0.0)
    m.eval()
    return m


def _reqs():
    return [Request(f"r{i}", p, MAX_NEW) for i, p in enumerate(PROMPTS)]


def _unified_out(model, cache_dtype=None):
    eng = ServeEngine(model, num_blocks=64, block_size=8, max_batch=4,
                      prefill_chunk=4, cache_dtype=cache_dtype)
    out = eng.run(_reqs())
    eng.block_pool.check_no_leaks()
    return out


def _disagg(model, tmp_path, **kw):
    return DisaggregatedEngine(
        model, num_blocks=64, block_size=8, max_batch=4,
        prefill_chunk=4, handoff_dir=str(tmp_path), **kw)


def _check_disagg(eng):
    eng.prefill.block_pool.check_no_leaks()
    eng.decode.block_pool.check_no_leaks()
    assert not eng.pending


# ---------------------------------------------------------------------------
# handoff bitwise parity: prefill-on-A -> streamed KV -> decode-on-B
# ---------------------------------------------------------------------------


def test_disagg_parity_fp32(model, tmp_path):
    base = _unified_out(model)
    eng = _disagg(model, tmp_path)
    out = eng.run(_reqs())
    assert out == base                    # bitwise greedy parity
    m = eng.metrics()["handoff"]
    assert m["count"] == len(PROMPTS) and m["retries"] == 0
    # one fp32 block of the tiny GPT: 2 layers x K+V x 4 heads x 8 x 8
    assert 0 < m["bytes_peak_host"] <= 2 * 2 * 4 * 8 * 8 * 4
    _check_disagg(eng)


def test_disagg_parity_int8(model, tmp_path):
    base = _unified_out(model, cache_dtype="int8")
    eng = _disagg(model, tmp_path, cache_dtype="int8")
    out = eng.run(_reqs())
    assert out == base
    # int8 handoff streams q and scale as separate parts; the peak is
    # still one single-part block buffer, never a gathered pool
    assert 0 < eng.metrics()["handoff"]["bytes_peak_host"] \
        <= 2 * 2 * 4 * 8 * 8
    _check_disagg(eng)


def test_disagg_open_loop_arrivals_parity(model, tmp_path):
    base = _unified_out(model)
    eng = _disagg(model, tmp_path)
    out = eng.run(_reqs(), arrivals=[0, 2, 3, 7])
    assert out == base
    _check_disagg(eng)


# ---------------------------------------------------------------------------
# speculative decoding: ragged acceptance, recompile-free, >= 2 tok/tick
# ---------------------------------------------------------------------------


def test_unified_spec_parity_and_recompile_free(model):
    base = _unified_out(model)
    sc.reset_stats()
    sc.clear()
    eng = ServeEngine(model, num_blocks=128, block_size=8, max_batch=4,
                      prefill_chunk=4, draft=make_self_draft(model),
                      spec_k=3, spec_policy="on")
    out = eng.run(_reqs())
    assert out == base                    # spec is exact for ANY draft
    eng.block_pool.check_no_leaks()
    spec = eng.metrics()["spec"]
    assert spec["ticks"] > 0
    # SELF-draft: full acceptance up to finish-truncation (a sequence
    # that completes mid-tick forfeits the rest of its offered window,
    # so the rate lands at exactly 0.5 on this short trace) -> the
    # per-sequence committed-tokens floor the ISSUE pins
    assert spec["accept_rate"] >= 0.5
    seq_ticks = spec["offered"] / 3
    assert spec["committed_tokens"] / seq_ticks >= 2.0
    # ragged acceptance never reaches program identity: verify-step
    # compiles stay within batch x target-table x draft-table buckets
    stats = sc.kind_stats("spec_verify_step")
    bound = (len({bucket(b, 4) for b in range(1, 5)})
             * len({bucket(t) for t in range(1, 5)}) ** 2)
    assert 1 <= stats["compiles"] <= bound
    assert stats["dispatches"] >= stats["compiles"]


def test_disagg_spec_parity_int8_draft(model, tmp_path):
    base = _unified_out(model)
    eng = _disagg(model, tmp_path, draft=make_self_draft(model),
                  spec_k=3, decode_blocks=128,
                  draft_cache_dtype="int8")
    out = eng.run(_reqs())
    assert out == base
    spec = eng.decode.metrics()["spec"]
    assert spec["accept_rate"] >= 0.5
    _check_disagg(eng)


def test_spec_telemetry_names(model):
    reg = obs.get_registry()
    hist0 = reg.histogram("serve.spec.accepted_tokens").count
    eng = ServeEngine(model, num_blocks=128, block_size=8, max_batch=4,
                      prefill_chunk=4, draft=make_self_draft(model),
                      spec_k=2, spec_policy="on")
    eng.run(_reqs())
    assert reg.histogram("serve.spec.accepted_tokens").count > hist0
    rate = reg.gauge("serve.spec.accept_rate").value
    assert rate is not None and 0.0 <= rate <= 1.0
    eng.block_pool.check_no_leaks()


def test_divergent_draft_still_exact(model):
    """A draft that disagrees with the target (different init) can only
    slow decoding down — never change the emitted tokens."""
    base = _unified_out(model)
    nn.manual_seed(7)
    draft = GptModel(vocab_size=73, hidden=16, layers=1, heads=2,
                     max_positions=96, dropout=0.0, attn_dropout=0.0)
    draft.eval()
    eng = ServeEngine(model, num_blocks=128, block_size=8, max_batch=4,
                      prefill_chunk=4, draft=draft, spec_k=2,
                      spec_policy="on")
    out = eng.run(_reqs())
    assert out == base
    eng.block_pool.check_no_leaks()


# ---------------------------------------------------------------------------
# chaos mid-handoff + forced preemption
# ---------------------------------------------------------------------------


def test_chaos_mid_handoff_retries_then_parity(model, tmp_path):
    base = _unified_out(model)
    r0 = obs.counter("serve.handoff.retries").value
    with chaos.session(seed=0) as c:
        c.on("serve.kv_handoff", action="fail", at=1)
        eng = _disagg(model, tmp_path)
        out = eng.run(_reqs())
        assert [p for p, _, _ in c.log] == ["serve.kv_handoff"]
    assert out == base                    # retry re-streams, bitwise
    assert obs.counter("serve.handoff.retries").value == r0 + 1
    assert eng.metrics()["handoff"]["retries"] >= 1
    _check_disagg(eng)


def test_chaos_kill_mid_handoff_leaves_rejectable_debris(tmp_path):
    pool = init_pool_buffer(2, 4, 8, 8, 8)
    pool = pool.at[:, :, 1:4].set(1.5)
    d = str(tmp_path / "killed")
    with chaos.session(seed=0) as c:
        c.on("serve.kv_handoff", action="kill", at=2)
        with pytest.raises(chaos.ChaosKilled):
            stream_kv_handoff(d, pool, [1, 2, 3])
    # kill before the manifest commit: debris, no manifest — the
    # loader must refuse it as corrupt, never scatter partial blocks
    assert os.path.exists(d)
    assert "KV_MANIFEST.pkl" not in os.listdir(d)
    with pytest.raises(CheckpointCorruptError):
        load_kv_handoff(d, init_pool_buffer(2, 4, 8, 8, 8), [4, 5, 6])
    discard_kv_handoff(d)
    assert not os.path.exists(d)


def test_forced_preemption_on_decode_engine_parity(model, tmp_path):
    """A decode pool too small for the live set forces preemption after
    the handoff; recompute on the decode engine reproduces the exact
    greedy continuation."""
    reqs = [Request(f"p{i}", [3 + i, 5, 7], 8) for i in range(6)]
    p0 = obs.counter("serve.preemptions").value
    eng = DisaggregatedEngine(model, num_blocks=64, block_size=4,
                              max_batch=4, prefill_chunk=4,
                              decode_blocks=9,
                              handoff_dir=str(tmp_path))
    out = eng.run(reqs)
    assert sorted(out) == [f"p{i}" for i in range(6)]
    assert obs.counter("serve.preemptions").value > p0
    s = DecodeSession(model, batch=1)
    s.append(jnp.asarray([[3, 5, 7]], jnp.int32))
    assert out["p0"] == [int(t) for t in np.asarray(s.generate(8))[0]]
    _check_disagg(eng)


# ---------------------------------------------------------------------------
# load_kv_handoff error taxonomy
# ---------------------------------------------------------------------------


def _streamed(tmp_path, name="h"):
    pool = init_pool_buffer(2, 4, 8, 8, 8)
    pool = pool.at[:, :, 1:4].set(2.25)
    d = str(tmp_path / name)
    manifest, peak = stream_kv_handoff(d, pool, [1, 2, 3])
    return pool, d, manifest, peak


def test_kv_handoff_roundtrip_bitwise(tmp_path):
    pool, d, manifest, peak = _streamed(tmp_path)
    assert manifest["n_blocks"] == 3 and not manifest["quant"]
    assert peak == 2 * 2 * 4 * 8 * 8 * 4   # ONE block's bytes, fp32
    dst, peak2 = load_kv_handoff(
        d, init_pool_buffer(2, 4, 8, 8, 8), [5, 6, 7])
    assert peak2 == peak
    np.testing.assert_array_equal(np.asarray(pool[:, :, [1, 2, 3]]),
                                  np.asarray(dst[:, :, [5, 6, 7]]))
    assert not np.asarray(dst[:, :, [1, 2, 3]]).any()


def test_kv_handoff_int8_roundtrip_bitwise(tmp_path):
    pool = init_pool_buffer(2, 4, 8, 8, 8, dtype="int8")
    pool = type(pool)(pool.q.at[:, :, 1:3].set(7),
                      pool.scale.at[:, :, 1:3].set(0.125))
    d = str(tmp_path / "q")
    manifest, _ = stream_kv_handoff(d, pool, [1, 2])
    assert manifest["quant"]
    dst, _ = load_kv_handoff(
        d, init_pool_buffer(2, 4, 8, 8, 8, dtype="int8"), [3, 4])
    np.testing.assert_array_equal(np.asarray(pool.q[:, :, [1, 2]]),
                                  np.asarray(dst.q[:, :, [3, 4]]))
    np.testing.assert_array_equal(np.asarray(pool.scale[:, :, [1, 2]]),
                                  np.asarray(dst.scale[:, :, [3, 4]]))


def test_kv_handoff_crc_failure_is_corrupt(tmp_path):
    _, d, manifest, _ = _streamed(tmp_path)
    fname = manifest["blocks"][1]["kv"]["file"]
    path = os.path.join(d, fname)
    raw = bytearray(open(path, "rb").read())
    raw[0] ^= 0xFF
    with open(path, "wb") as f:
        f.write(raw)
    with pytest.raises(CheckpointCorruptError, match="checksum"):
        load_kv_handoff(d, init_pool_buffer(2, 4, 8, 8, 8), [4, 5, 6])


def test_kv_handoff_missing_block_is_corrupt(tmp_path):
    _, d, manifest, _ = _streamed(tmp_path)
    os.remove(os.path.join(d, manifest["blocks"][2]["kv"]["file"]))
    with pytest.raises(CheckpointCorruptError):
        load_kv_handoff(d, init_pool_buffer(2, 4, 8, 8, 8), [4, 5, 6])


def test_kv_handoff_geometry_and_count_mismatch_is_reshard(tmp_path):
    _, d, _, _ = _streamed(tmp_path)
    # quantization mismatch: fp32 handoff into an int8 pool
    with pytest.raises(CheckpointReshardError):
        load_kv_handoff(d, init_pool_buffer(2, 4, 8, 8, 8,
                                            dtype="int8"), [4, 5, 6])
    # per-block shape mismatch: different head_dim
    with pytest.raises(CheckpointReshardError):
        load_kv_handoff(d, init_pool_buffer(2, 4, 4, 8, 8), [4, 5, 6])
    # block-count mismatch: a grant that disagrees with the manifest
    with pytest.raises(CheckpointReshardError):
        load_kv_handoff(d, init_pool_buffer(2, 4, 8, 8, 8), [4, 5])


def test_kv_handoff_missing_manifest_is_corrupt(tmp_path):
    with pytest.raises(CheckpointCorruptError, match="manifest"):
        load_kv_handoff(str(tmp_path / "nope"),
                        init_pool_buffer(2, 4, 8, 8, 8), [1])


# ---------------------------------------------------------------------------
# phase-split planner + admission validation
# ---------------------------------------------------------------------------


def test_plan_serve_phase_split_colocates_single_device():
    sp = plan_serve_phase_split()
    assert sp.colocated and sp.prefill == (0,) and sp.decode == (0,)
    assert sp.name() == "colocated"


def test_plan_serve_phase_split_ranks_bandwidth_to_decode():
    # v4 has more HBM bandwidth per sustained FLOP than v5e, so in a
    # mixed fleet the v4 members (indices 2, 3) take decode
    sp = plan_serve_phase_split("v5e:2+v4:2")
    assert not sp.colocated
    assert sp.decode == (2, 3) and sp.prefill == (0, 1)
    assert sp.name() == "prefill:2+decode:2"
    # skewed demand: prefill-heavy traffic shrinks decode to its
    # 1-device floor, still the best-bandwidth member
    sp = plan_serve_phase_split("v5e:2+v4:2", prefill_weight=3.0,
                                decode_weight=1.0)
    assert len(sp.decode) == 1 and sp.decode[0] in (2, 3)
    assert len(sp.prefill) == 3


def test_disagg_submit_rejects_never_fit(model, tmp_path):
    eng = _disagg(model, tmp_path, draft=make_self_draft(model),
                  spec_k=4, decode_blocks=128)
    with pytest.raises(ValueError):
        eng.submit(Request("big", list(range(1, 90)), 10))
