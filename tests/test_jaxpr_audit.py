"""The jaxpr-level program verifier (apex_tpu.lint.jaxpr_audit): the
tier-1 gate (every real entry program passes every IR check), the
cross-checks grounding its verdicts in ``step_cache.kind_stats`` and
the lowered HLO, and the ``--jaxpr`` CLI surface."""
import subprocess
import sys

import pytest

from apex_tpu.lint import jaxpr_audit
from apex_tpu.runtime import step_cache as sc

pytestmark = pytest.mark.lint


@pytest.fixture(scope="module")
def audit():
    """One audited run for the whole module, with stats reset first so
    kind_stats cross-checks count exactly the audit's own workloads."""
    sc.reset_stats()
    return jaxpr_audit.run(force=True)


def _report(audit, name):
    (rep,) = [p for p in audit.programs if p.name == name]
    return rep


def _check(rep, name):
    (c,) = [c for c in rep.checks if c.name == name]
    return c


# ---------------------------------------------------------------------------
# the tier-1 gate
# ---------------------------------------------------------------------------


def test_every_audited_program_passes(audit):
    assert audit.programs, "audit collected no programs"
    assert audit.passed, "\n" + audit.format()


def test_audit_covers_the_entry_surfaces(audit):
    kinds = {p.kind for p in audit.programs}
    # train, eager optimizer, serve — the three executor surfaces
    assert "train_step" in kinds
    assert "fused_adam" in kinds
    assert "prefill_step" in kinds and "decode_step" in kinds
    # every registered kernel, both tiers
    from apex_tpu.kernels.dispatch import catalog
    for kname in catalog():
        assert f"kernel.{kname}.pallas" in kinds, kname
        assert f"kernel.{kname}.xla" in kinds, kname


def test_audit_counts_schema(audit):
    c = audit.counts()
    assert {"jaxpr_audit_ms", "programs_audited", "checks_run",
            "failures"} <= set(c)
    assert c["programs_audited"] == len(audit.programs) >= 12
    assert c["failures"] == 0


def test_telemetry_carry_delta_is_exact(audit):
    rep = _report(audit, "train_step[telemetry-delta]")
    assert rep.passed, rep.checks
    assert "+5 in / +5 out" in _check(rep, "telemetry-carry").detail


# ---------------------------------------------------------------------------
# cross-checks: the IR verdicts against runtime counters and lowered HLO
# ---------------------------------------------------------------------------


def test_train_verdict_matches_kind_stats(audit):
    """The two audited train_step programs (telemetry off/on) are the
    two compiles the runtime counted — the audit judged the programs
    that actually executed, not a parallel reconstruction."""
    assert audit.passed
    stats = sc.kind_stats("train_step")
    assert stats["compiles"] == 2
    assert stats["dispatches"] == 2
    # and the audited program really contains the 2-microbatch window:
    rep = _report(audit, "train_step")
    detail = _check(rep, "scan-carry-fp32").detail
    n_scans = int(detail.split(" ")[0])
    assert n_scans >= 1


def test_serve_verdict_matches_kind_stats(audit):
    """The serve programs the audit passed are the ones the engine
    dispatched: decode compiled at least once and re-dispatched per
    generated token without a callback in sight."""
    assert audit.passed
    decode = sc.kind_stats("decode_step")
    assert decode["compiles"] >= 1
    assert decode["dispatches"] >= decode["compiles"]
    assert sc.kind_stats("prefill_step")["compiles"] >= 1


def test_donation_census_matches_executor_hlo_bound(audit):
    """Generalization stays anchored to the original bound
    (test_executor.py::test_donation_alias_in_lowered_hlo): FusedAdam
    over 2 params donates params + exp_avg + exp_avg_sq per bucket plus
    the step counter — at least 7 aliased buffers in the HLO."""
    rep = _report(audit, "fused_adam")
    c = _check(rep, "donation-census")
    assert c.ok
    n = int(c.detail.split(" ")[0])
    assert n >= 3 * 2 + 1


# ---------------------------------------------------------------------------
# the CLI surface
# ---------------------------------------------------------------------------


def test_cli_jaxpr_exits_zero_on_shipped_tree(audit, capsys):
    """The acceptance-spelled invocation, in-process against the
    memoized audit (the subprocess spelling re-traces every program —
    ~40s of pure import/trace repeat — so it rides the slow tier)."""
    from apex_tpu.lint.__main__ import main as lint_main

    rc = lint_main(["--jaxpr"])
    out = capsys.readouterr().out
    assert rc == 0, out
    assert "0 failure(s)" in out


@pytest.mark.slow
def test_cli_jaxpr_subprocess_exits_zero():
    proc = subprocess.run(
        [sys.executable, "-m", "apex_tpu.lint", "--jaxpr"],
        capture_output=True, text=True, timeout=600)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    assert "0 failure(s)" in proc.stdout


# ---------------------------------------------------------------------------
# dynamic oracle: PRECISION-SINK's static verdict vs fp16 arithmetic
# ---------------------------------------------------------------------------


def test_precision_sink_dynamic_oracle():
    """The flagged fixture really overflows: an fp16-accumulated energy
    sum saturates to inf on values whose fp32 twin is ~66k, while the
    fp32-reduction fixture stays finite on the SAME input."""
    import importlib
    import os

    import jax.numpy as jnp
    import numpy as np

    from apex_tpu import lint as tpu_lint

    fixtures = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                            "lint_fixtures")
    bad_path = os.path.join(fixtures, "oracle_precision_bad.py")
    good_path = os.path.join(fixtures, "oracle_precision_good.py")

    bad_res = tpu_lint.run([bad_path], select=["PRECISION-SINK"],
                           baseline=None)
    good_res = tpu_lint.run([good_path], select=["PRECISION-SINK"],
                            baseline=None)
    assert len(bad_res.active()) == 1          # static verdict: flagged
    assert not good_res.active()               # static verdict: clean

    sys.path.insert(0, fixtures)
    try:
        bad = importlib.import_module("oracle_precision_bad")
        good = importlib.import_module("oracle_precision_good")
    finally:
        sys.path.pop(0)
    xs = jnp.full((4096,), 4.0, jnp.float32)   # energy = 16 * 4096 = 65536
    assert np.isinf(np.asarray(bad.window_energy(xs)))       # > fp16 max
    assert np.isfinite(np.asarray(good.window_energy(xs)))
    np.testing.assert_allclose(np.asarray(good.window_energy(xs)),
                               65536.0, rtol=1e-3)
