"""Regression: mixed fp16+bf16 promote to fp32 (not an arbitrary half type)."""
import jax.numpy as jnp

from apex_tpu.amp.policy import CastPolicy, apply_op_policy, autocast


def test_mixed_half_types_promote_to_fp32():
    a = jnp.ones((4,), jnp.float16)
    b = jnp.ones((4,), jnp.bfloat16)
    with autocast(CastPolicy()):
        for order in [(a, b), (b, a)]:
            args, _ = apply_op_policy("add", order)
            assert args[0].dtype == jnp.float32
            assert args[1].dtype == jnp.float32


def test_half_and_fp64_promotes_to_fp64():
    a = jnp.ones((4,), jnp.float16)
    b = jnp.ones((4,), jnp.float64)
    with autocast(CastPolicy()):
        args, _ = apply_op_policy("add", (a, b))
    # CPU x64 is disabled by default so the widest representable is fine as
    # long as it is not a half type
    assert args[0].dtype not in (jnp.float16, jnp.bfloat16)
