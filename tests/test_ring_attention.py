"""Sequence-parallel attention (ring + Ulysses) vs single-device oracle.

Oracle: attention_reference (full-softmax jnp attention) on the gathered
sequence.  The ring/Ulysses paths run under shard_map on the 8-device CPU
mesh with the sequence axis sharded — the same pattern the TPU deployment
uses over ICI.  Gradients are checked through jax.grad to exercise the
custom ring backward (rotating dk/dv accumulators).
"""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from apex_tpu.contrib.multihead_attn.attn_funcs import attention_reference
from apex_tpu.parallel import ring_attention, ulysses_attention

B, H, S, D = 2, 4, 64, 16


def _mesh(n):
    return Mesh(np.array(jax.devices()[:n]), ("sp",))


def _inputs(rng, dtype=jnp.float32):
    q, k, v = (jnp.asarray(rng.standard_normal((B, H, S, D)), dtype)
               for _ in range(3))
    return q, k, v


def _run_sharded(fn, mesh, q, k, v):
    shard = jax.shard_map(fn, mesh=mesh, in_specs=P(None, None, "sp", None),
                          out_specs=P(None, None, "sp", None),
                          check_vma=False)
    return jax.jit(shard)(q, k, v)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("n", [4, 8])
def test_ring_forward_matches_reference(rng, causal, n):
    mesh = _mesh(n)
    q, k, v = _inputs(rng)
    scale = 1.0 / np.sqrt(D)
    ref = attention_reference(q, k, v, None, causal, scale)
    out = _run_sharded(
        functools.partial(ring_attention, axis_name="sp", causal=causal),
        mesh, q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_grads_match_reference(rng, causal):
    mesh = _mesh(4)
    q, k, v = _inputs(rng)
    w = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    scale = 1.0 / np.sqrt(D)

    def ref_loss(q, k, v):
        return jnp.sum(attention_reference(q, k, v, None, causal, scale) * w)

    def ring_loss(q, k, v):
        fn = functools.partial(ring_attention, axis_name="sp", causal=causal)
        shard = jax.shard_map(fn, mesh=mesh,
                              in_specs=P(None, None, "sp", None),
                              out_specs=P(None, None, "sp", None),
                              check_vma=False)
        return jnp.sum(shard(q, k, v) * w)

    g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.jit(jax.grad(ring_loss, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-4, atol=3e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ulysses_matches_reference(rng, causal):
    mesh = _mesh(4)  # H=4 heads divisible by 4
    q, k, v = _inputs(rng)
    scale = 1.0 / np.sqrt(D)
    ref = attention_reference(q, k, v, None, causal, scale)
    out = _run_sharded(
        functools.partial(ulysses_attention, axis_name="sp", causal=causal),
        mesh, q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ulysses_grads(rng):
    mesh = _mesh(4)
    q, k, v = _inputs(rng)
    w = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    scale = 1.0 / np.sqrt(D)

    def ref_loss(q, k, v):
        return jnp.sum(attention_reference(q, k, v, None, True, scale) * w)

    def uly_loss(q, k, v):
        fn = functools.partial(ulysses_attention, axis_name="sp",
                               causal=True)
        shard = jax.shard_map(fn, mesh=mesh,
                              in_specs=P(None, None, "sp", None),
                              out_specs=P(None, None, "sp", None),
                              check_vma=False)
        return jnp.sum(shard(q, k, v) * w)

    g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    g_uly = jax.jit(jax.grad(uly_loss, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g_uly, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-4, atol=3e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_pallas_interpret_chunks(rng, causal):
    """Ring with the actual Pallas flash kernels (interpreted) per chunk."""
    from apex_tpu.ops.pallas import force_mode
    mesh = _mesh(4)
    q, k, v = _inputs(rng)
    scale = 1.0 / np.sqrt(D)
    ref = attention_reference(q, k, v, None, causal, scale)
    with force_mode("interpret"):
        out = _run_sharded(
            functools.partial(ring_attention, axis_name="sp", causal=causal),
            mesh, q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_cross_attention_uneven_sq_sk(rng, causal):
    """Sq_local != Sk_local (cross attention): offset math idx*sq vs src*sk."""
    mesh = _mesh(4)
    sq, sk = 32, 64
    q = jnp.asarray(rng.standard_normal((B, H, sq, D)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((B, H, sk, D)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((B, H, sk, D)), jnp.float32)
    scale = 1.0 / np.sqrt(D)
    ref = attention_reference(q, k, v, None, causal, scale)
    out = _run_sharded(
        functools.partial(ring_attention, axis_name="sp", causal=causal),
        mesh, q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_ring_bf16_tolerance(rng):
    """Ring attention with bf16 inputs stays close to the f32 oracle."""
    mesh = _mesh(8)
    q, k, v = _inputs(rng, jnp.bfloat16)
    scale = 1.0 / np.sqrt(D)
    ref = attention_reference(q.astype(jnp.float32), k.astype(jnp.float32),
                              v.astype(jnp.float32), None, True, scale)
    out = _run_sharded(
        functools.partial(ring_attention, axis_name="sp", causal=True),
        mesh, q, k, v)
    assert out.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(out, np.float32), np.asarray(ref),
                               rtol=0.05, atol=0.05)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_pallas_interpret_grads(rng, causal):
    """Gradients with the Pallas flash kernels (interpreted) per chunk:
    covers the _ring_vjp_bwd -> flash_attention_bwd path (global lse/out,
    rotating dk/dv accumulators) that the jnp fallback tests miss."""
    from apex_tpu.ops.pallas import force_mode
    mesh = _mesh(4)
    q, k, v = _inputs(rng)
    w = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    scale = 1.0 / np.sqrt(D)

    def ref_loss(q, k, v):
        return jnp.sum(attention_reference(q, k, v, None, causal, scale) * w)

    def ring_loss(q, k, v):
        fn = functools.partial(ring_attention, axis_name="sp", causal=causal)
        shard = jax.shard_map(fn, mesh=mesh,
                              in_specs=P(None, None, "sp", None),
                              out_specs=P(None, None, "sp", None),
                              check_vma=False)
        return jnp.sum(shard(q, k, v) * w)

    g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    with force_mode("interpret"):
        g_ring = jax.jit(jax.grad(ring_loss, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-4, atol=3e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_fori_loop_path(rng, causal, monkeypatch):
    """Large-ring fallback: with UNROLL_LIMIT forced to 0 the fwd and bwd
    ring loops run as lax.fori_loop (O(1) HLO per pass) and must match the
    reference exactly like the unrolled path does.

    causal=False (+ no dropout) exercises ``_must_unroll``: on jaxlib
    0.4.x the SPMD partitioner rejects the PartitionId instruction the
    fori lowering leaves in the ring body when causal masking (the only
    live axis-index consumer) is off, so production routes those cases
    to the unrolled path — identical math, and this parametrization
    proves the routing keeps the case working rather than xfailing."""
    import importlib
    ra_mod = importlib.import_module("apex_tpu.parallel.ring_attention")
    monkeypatch.setattr(ra_mod, "UNROLL_LIMIT", 0)
    mesh = _mesh(8)
    q, k, v = _inputs(rng)
    w = jnp.asarray(rng.standard_normal((B, H, S, D)), jnp.float32)
    scale = 1.0 / np.sqrt(D)
    ref = attention_reference(q, k, v, None, causal, scale)
    out = _run_sharded(
        functools.partial(ring_attention, axis_name="sp", causal=causal),
        mesh, q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)

    def ref_loss(q, k, v):
        return jnp.sum(attention_reference(q, k, v, None, causal, scale) * w)

    def ring_loss(q, k, v):
        fn = functools.partial(ring_attention, axis_name="sp", causal=causal)
        shard = jax.shard_map(fn, mesh=mesh,
                              in_specs=P(None, None, "sp", None),
                              out_specs=P(None, None, "sp", None),
                              check_vma=False)
        return jnp.sum(shard(q, k, v) * w)

    g_ref = jax.grad(ref_loss, argnums=(0, 1, 2))(q, k, v)
    g_ring = jax.jit(jax.grad(ring_loss, argnums=(0, 1, 2)))(q, k, v)
    for a, b in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-4, atol=3e-5)


def test_ring_gqa_matches_expanded(rng):
    """KVH-wide ring (GQA: chunks rotate un-expanded, H/KVH x fewer ICI
    bytes) equals the ring over pre-repeated K/V — values and gradients."""
    import functools
    from jax.sharding import Mesh, PartitionSpec as P

    b, h, kvh, s, d = 2, 8, 2, 32, 16
    mesh = Mesh(np.array(jax.devices()), ("sp",))
    q = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)
    k = jnp.asarray(rng.standard_normal((b, kvh, s, d)), jnp.float32)
    v = jnp.asarray(rng.standard_normal((b, kvh, s, d)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((b, h, s, d)), jnp.float32)

    def run(q, k, v, w, expand_first):
        def f(q_l, k_l, v_l, w_l):
            kk, vv = k_l, v_l
            if expand_first:
                kk = jnp.repeat(k_l, h // kvh, axis=1)
                vv = jnp.repeat(v_l, h // kvh, axis=1)
            out = ring_attention(q_l, kk, vv, "sp", causal=True)
            return jax.lax.psum(jnp.sum(out * w_l), "sp")
        shard = jax.shard_map(
            f, mesh=mesh,
            in_specs=(P(None, None, "sp"), P(None, None, "sp"),
                      P(None, None, "sp"), P(None, None, "sp")),
            out_specs=P(), check_vma=False)
        loss, grads = jax.value_and_grad(
            lambda q, k, v: shard(q, k, v, w), argnums=(0, 1, 2))(q, k, v)
        return loss, grads

    l_g, g_g = jax.jit(functools.partial(run, expand_first=False))(
        q, k, v, w)
    l_e, g_e = jax.jit(functools.partial(run, expand_first=True))(
        q, k, v, w)
    np.testing.assert_allclose(float(l_g), float(l_e), rtol=1e-5)
    for a, bb in zip(g_g, g_e):
        np.testing.assert_allclose(np.asarray(a), np.asarray(bb),
                                   rtol=2e-5, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
def test_ring_dropout_bit_consistent_with_single_device(rng, causal):
    """Ring attention with dropout: the hash mask is a function of GLOBAL
    (seed, head, row, col), so the 4-way sequence-sharded result equals
    the single-device dropped attention under the same seed — sequence
    parallelism does not change which probabilities drop.  Gradients
    exercise the dropped ring backward (dk/dv accumulators rotating
    through dropped chunks)."""
    mesh = _mesh(4)
    q, k, v = _inputs(rng)
    scale = 1.0 / np.sqrt(D)
    seed = jnp.int32(90210)
    p = 0.3

    ref = attention_reference(q, k, v, None, causal, scale,
                              dropout_p=p, dropout_seed=seed)
    out = _run_sharded(
        functools.partial(ring_attention, axis_name="sp", causal=causal,
                          dropout_p=p, dropout_seed=seed),
        mesh, q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)

    def loss_ring(q, k, v):
        shard = jax.shard_map(
            functools.partial(ring_attention, axis_name="sp",
                              causal=causal, dropout_p=p,
                              dropout_seed=seed),
            mesh=mesh, in_specs=P(None, None, "sp", None),
            out_specs=P(None, None, "sp", None), check_vma=False)
        return jnp.sum(shard(q, k, v).astype(jnp.float32) ** 2)

    def loss_ref(q, k, v):
        return jnp.sum(attention_reference(
            q, k, v, None, causal, scale, dropout_p=p,
            dropout_seed=seed).astype(jnp.float32) ** 2)

    g_ring = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
    g_ref = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
    for a, r in zip(g_ring, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                   rtol=2e-4, atol=2e-4)


def test_ulysses_dropout_runs_and_decorrelates(rng):
    """Ulysses dropout: per-head-shard streams — runs, is finite, differs
    from the dropout-free output, and is deterministic per seed."""
    mesh = _mesh(4)
    q, k, v = _inputs(rng)
    seed = jnp.int32(7)

    def run(p, s):
        return _run_sharded(
            functools.partial(ulysses_attention, axis_name="sp",
                              causal=False, dropout_p=p, dropout_seed=s),
            mesh, q, k, v)

    clean = run(0.0, None)
    a = run(0.4, seed)
    b = run(0.4, seed)
    c = run(0.4, jnp.int32(8))
    assert np.isfinite(np.asarray(a)).all()
    assert not np.allclose(np.asarray(a), np.asarray(clean))
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    assert not (np.asarray(a) == np.asarray(c)).all()


def test_ring_dropout_requires_seed():
    q = jnp.zeros((1, 1, 8, 8), jnp.float32)
    with pytest.raises(ValueError, match="dropout_seed"):
        ring_attention(q, q, q, axis_name="sp", dropout_p=0.1)


def test_sp_seed_fold_not_symmetric_with_tp_fold():
    """Round-4 advisor finding: the SP fold must not be the TP fold's
    linear xor with the same constant — a shard-replicated base seed on
    a TP x SP mesh would then give devices with swapped (tp, sp)
    indices identical dropout streams (seed ^ a*C ^ b*C is symmetric).
    The SP fold is multiply-then-avalanche; assert no swap collision
    and no collision with the TP fold itself over a realistic range."""
    from apex_tpu.parallel.ring_attention import _sp_seed_fold

    def tp_fold(seed, idx):   # mirrors attn_funcs._dropout_seed's fold
        return int(jnp.asarray(
            (jnp.uint32(seed) ^ (jnp.uint32(idx)
                                 * jnp.uint32(0x9E3779B1)))
            .astype(jnp.int32)))

    base = 0x12345678
    n = 8
    seen = {}
    for tp in range(n):
        for sp in range(n):
            s = int(_sp_seed_fold(jnp.int32(tp_fold(base, tp)),
                                  jnp.uint32(sp)))
            assert (tp, sp) not in seen
            for (otp, osp), os in seen.items():
                assert s != os, (
                    f"seed collision between (tp={tp},sp={sp}) and "
                    f"(tp={otp},sp={osp})")
            seen[(tp, sp)] = s
