"""Native runtime (csrc/runtime.cpp via ctypes) vs numpy oracles.

The native path must agree exactly with the numpy fallback — same oracle
style as the reference's flatten/unflatten usage in DDP and the prefetcher
normalize math (main_amp.py:287-301).
"""
import numpy as np
import pytest

from apex_tpu import runtime


def test_native_lib_builds():
    # toolchain is baked into the image; if this fails the fallback paths
    # still work but the native mandate is unmet — fail loudly.
    assert runtime.available()


def test_flatten_unflatten_roundtrip(rng):
    arrays = [rng.standard_normal(s).astype(np.float32)
              for s in [(3, 4), (7,), (2, 5, 6), (1,)]]
    flat = runtime.flatten(arrays)
    ref = np.concatenate([a.ravel() for a in arrays])
    np.testing.assert_array_equal(flat, ref)
    back = runtime.unflatten(flat, arrays)
    for a, b in zip(back, arrays):
        np.testing.assert_array_equal(a, b)


def test_flatten_dtype_mismatch_raises(rng):
    with pytest.raises(TypeError):
        runtime.flatten([np.zeros(3, np.float32), np.zeros(3, np.float16)])


def test_unflatten_size_mismatch_raises():
    with pytest.raises(ValueError):
        runtime.unflatten(np.zeros(5, np.float32), [np.zeros((2, 2))])


def test_flatten_matches_python_fallback(rng, monkeypatch):
    arrays = [rng.standard_normal((64, 64)).astype(np.float16)
              for _ in range(5)]
    native = runtime.flatten(arrays)
    monkeypatch.setattr(runtime, "_lib", False)  # force fallback
    fallback = runtime.flatten(arrays)
    np.testing.assert_array_equal(native, fallback)


def test_normalize_u8(rng):
    batch = rng.integers(0, 256, (4, 10, 12, 3), dtype=np.uint8)
    mean = np.array([0.485, 0.456, 0.406], np.float32)
    std = np.array([0.229, 0.224, 0.225], np.float32)
    out = runtime.normalize_u8_nhwc_to_f32_nchw(batch, mean, std)
    ref = (batch.astype(np.float32) / 255.0 - mean) / std
    ref = ref.transpose(0, 3, 1, 2)
    assert out.shape == (4, 3, 10, 12)
    np.testing.assert_allclose(out, ref, rtol=1e-6, atol=1e-6)


def test_normalize_u8_channels_last(rng):
    """The layout-preserving variant: same arithmetic, NHWC out, and
    equal to the NCHW variant up to the transpose."""
    batch = rng.integers(0, 256, (4, 10, 12, 3), dtype=np.uint8)
    mean = np.array([0.485, 0.456, 0.406], np.float32)
    std = np.array([0.229, 0.224, 0.225], np.float32)
    out = runtime.normalize_u8_nhwc_to_f32_nhwc(batch, mean, std)
    ref = (batch.astype(np.float32) / 255.0 - mean) / std
    assert out.shape == (4, 10, 12, 3)
    np.testing.assert_allclose(out, ref, rtol=1e-6, atol=1e-6)
    nchw = runtime.normalize_u8_nhwc_to_f32_nchw(batch, mean, std)
    np.testing.assert_allclose(out.transpose(0, 3, 1, 2), nchw,
                               rtol=1e-6, atol=1e-6)


def test_data_prefetcher_channels_last(rng):
    batches = [(rng.integers(0, 256, (2, 4, 4, 3), dtype=np.uint8),
                np.zeros(2))]
    pf = runtime.DataPrefetcher(batches, channels_last=True)
    inp, _ = pf.next()
    assert inp.shape == (2, 4, 4, 3)    # NHWC preserved
    ref = runtime.normalize_u8_nhwc_to_f32_nhwc(
        batches[0][0], pf.mean, pf.std)
    np.testing.assert_allclose(np.asarray(inp), ref, rtol=1e-6)


def test_f32_to_bf16_rne(rng):
    import ml_dtypes
    x = rng.standard_normal(10000).astype(np.float32)
    # include RNE tie cases and specials
    x = np.concatenate([x, np.array([1.0, -1.0, 0.0, np.inf, -np.inf,
                                     np.nan, 3.402823e38, 1e-40],
                                    np.float32)])
    out = runtime.f32_to_bf16(x)
    ref = x.astype(ml_dtypes.bfloat16)
    np.testing.assert_array_equal(out.view(np.uint16) & 0x7FFF != 0x7FC0,
                                  ref.view(np.uint16) & 0x7FFF != 0x7FC0)
    finite = np.isfinite(x)
    np.testing.assert_array_equal(out[finite], ref[finite])


def test_data_prefetcher_order_and_values(rng):
    batches = [(rng.integers(0, 256, (2, 4, 4, 3), dtype=np.uint8),
                np.array([i, i + 1])) for i in range(5)]
    pf = runtime.DataPrefetcher(batches, depth=2)
    seen = list(pf)
    assert len(seen) == 5
    for i, (inp, tgt) in enumerate(seen):
        assert inp.shape == (2, 3, 4, 4)
        np.testing.assert_array_equal(np.asarray(tgt), [i, i + 1])
        ref = runtime.normalize_u8_nhwc_to_f32_nchw(
            batches[i][0], pf.mean, pf.std)
        np.testing.assert_allclose(np.asarray(inp), ref, rtol=1e-6)


def test_data_prefetcher_propagates_errors():
    def bad():
        yield np.zeros((1, 2, 2, 3), np.uint8), np.zeros(1)
        raise RuntimeError("loader died")
    pf = runtime.DataPrefetcher(bad())
    pf.next()
    with pytest.raises(RuntimeError, match="loader died"):
        pf.next()


def test_data_prefetcher_bf16(rng):
    import jax.numpy as jnp
    batches = [(rng.integers(0, 256, (2, 4, 4, 3), dtype=np.uint8),
                np.zeros(2))]
    pf = runtime.DataPrefetcher(batches, half_dtype=jnp.bfloat16)
    inp, _ = pf.next()
    assert jnp.asarray(inp).dtype == jnp.bfloat16


def test_data_prefetcher_exhausted_stays_exhausted(rng):
    batches = [(rng.integers(0, 256, (1, 2, 2, 3), dtype=np.uint8),
                np.zeros(1))]
    pf = runtime.DataPrefetcher(batches)
    assert pf.next()[0] is not None
    assert pf.next() == (None, None)
    assert pf.next() == (None, None)  # no deadlock on repeat


def test_data_prefetcher_close_releases_worker(rng):
    batches = [(rng.integers(0, 256, (1, 2, 2, 3), dtype=np.uint8),
                np.zeros(1)) for _ in range(10)]
    pf = runtime.DataPrefetcher(batches, depth=1)
    pf.next()  # consume one, abandon the rest
    pf.close()
    assert not pf._worker.is_alive()
    assert pf.next() == (None, None)


def test_flatten_noncontiguous_out_raises(rng):
    buf = np.zeros(8, np.float32)
    with pytest.raises(ValueError, match="contiguous"):
        runtime.flatten([np.ones(4, np.float32)], out=buf[::2])
