"""apex_tpu.observe: metrics registry / JSONL schema round-trip, the
zero-dispatch on-device telemetry carry (bitwise grad-norm parity with an
eager recompute, 1-compile/1-dispatch pin under accumulation), trace
spans, and the stall watchdog (fires under an injected chaos stall, stays
silent on a clean run)."""
import json
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import apex_tpu.nn as nn
from apex_tpu import observe
from apex_tpu.nn import functional as F
from apex_tpu.nn.modules import Ctx
from apex_tpu.observe import (MetricsRegistry, SCHEMA_VERSION, StallWatchdog,
                              get_registry, heartbeat, last_span, span)
from apex_tpu.optimizers import FusedSGD
from apex_tpu.runtime import chaos, step_cache
from apex_tpu.training import make_train_step

pytestmark = pytest.mark.observe


def _mlp(seed=0, din=8, hidden=16, dout=4):
    nn.manual_seed(seed)
    return nn.Sequential(nn.Linear(din, hidden), nn.ReLU(),
                         nn.Linear(hidden, dout))


def _data(n=4, din=8, dout=4, seed=0):
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.standard_normal((n, din)), jnp.float32)
    y = jnp.asarray(rng.integers(0, dout, (n,)))
    return x, y


# ---------------------------------------------------------------------------
# registry + event log
# ---------------------------------------------------------------------------


def test_registry_jsonl_schema_roundtrip(tmp_path):
    reg = MetricsRegistry()
    path = str(tmp_path / "events.jsonl")
    reg.add_jsonl_sink(path)
    reg.counter("c").inc(3)
    reg.gauge("g").set(2.5)
    reg.histogram("h").observe(1.0)
    reg.histogram("h").observe(3.0)
    reg.event("alpha", k=1)
    reg.event("beta", arr=jnp.zeros(2))     # non-JSON value -> default=str
    reg.remove_jsonl_sink(path)

    lines = [json.loads(line) for line in open(path)]
    assert [ln["event"] for ln in lines] == ["alpha", "beta"]
    for ln in lines:
        assert ln["schema"] == SCHEMA_VERSION
        assert isinstance(ln["ts_ms"], float)
    assert lines[0]["k"] == 1
    assert isinstance(lines[1]["arr"], str)
    # monotonic timestamps order the stream
    assert lines[1]["ts_ms"] >= lines[0]["ts_ms"]
    # the in-memory buffer carries the same records
    assert reg.events("alpha")[0]["k"] == 1
    snap = reg.snapshot()
    assert snap["schema"] == SCHEMA_VERSION
    assert snap["counters"]["c"] == 3
    assert snap["gauges"]["g"] == 2.5
    h = snap["histograms"]["h"]
    assert h["count"] == 2 and h["min"] == 1.0 and h["max"] == 3.0 \
        and h["mean"] == 2.0
    # prefix removal resets one subsystem's slice only
    reg.remove("c")
    snap = reg.snapshot()
    assert "c" not in snap["counters"] and "g" in snap["gauges"]


def test_span_emits_event_histogram_and_last_span():
    reg = get_registry()
    reg.clear_events()
    with span("test.region", phase="fwd"):
        pass
    (ev,) = [e for e in reg.events("span") if e["span"] == "test.region"]
    assert ev["phase"] == "fwd" and ev["dur_ms"] >= 0
    assert ev["schema"] == SCHEMA_VERSION
    assert last_span()["span"] == "test.region"
    assert reg.histogram("span.test.region_ms").count >= 1


# ---------------------------------------------------------------------------
# the on-device telemetry carry
# ---------------------------------------------------------------------------


def test_drained_grad_norm_bitwise_matches_eager_recompute():
    """At loss_scale=1.0 (static) the master grads are the raw f32 grads,
    so the carry's on-device sqrt(sum(g*g)) must be bitwise-identical to
    an eager jax.grad recompute over the same forward/env/key."""
    get_registry().clear_events()
    model = _mlp()
    params = [p for p in model.parameters()]
    opt = FusedSGD(params, lr=0.1, momentum=0.9)
    step = make_train_step(model, opt, lambda o, t: F.cross_entropy(o, t),
                           half_dtype=None, loss_scale=1.0,
                           telemetry=True, drain_every=1)
    x, y = _data()

    # eager reference from the PRE-step masters, replicating step_fn's
    # forward exactly: same env substitution, same step-derived RNG key,
    # same f32 cast + loss-scale multiply
    masters = [jnp.asarray(m) for m in step.state.master_params]
    step_ctr = step.state.step

    def scaled_loss(vals):
        env = {id(p): v for p, v in zip(params, vals)}
        key = jax.random.fold_in(jax.random.PRNGKey(0), step_ctr)
        ctx = Ctx(env=env, stats_out={}, training=True, key=key)
        out = model.forward(ctx, x)
        return F.cross_entropy(out, y).astype(jnp.float32) * \
            jnp.asarray(1.0, jnp.float32)

    grads = jax.grad(scaled_loss)(masters)
    gsq = jnp.zeros((), jnp.float32)
    for g in grads:
        gsq = gsq + jnp.sum(g * g)
    ref_norm = float(jnp.sqrt(gsq))

    loss = float(step(x, y))            # drain_every=1: drains immediately
    assert np.isfinite(loss)
    (rec,) = get_registry().events("train.telemetry")
    assert rec["windows"] == 1
    assert rec["grad_norm"] == ref_norm          # bitwise, not allclose
    assert rec["loss_scale"] == 1.0
    assert rec["overflow_count"] == 0


def test_telemetry_keeps_one_compile_one_dispatch_per_window():
    """The tentpole pin: with telemetry ON and a K-microbatch window, the
    step stays one executable and one dispatch per window; the drain
    happens outside jit and keys no new program."""
    get_registry().clear_events()
    model = _mlp(din=8)
    opt = FusedSGD(list(model.parameters()), lr=0.1, momentum=0.9)
    step = make_train_step(model, opt, lambda o, t: F.cross_entropy(o, t),
                           half_dtype=jnp.bfloat16, loss_scale="dynamic",
                           accum_steps=4, accum_stacked=True,
                           telemetry=True, drain_every=2)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((4, 4, 8)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 4, (4, 4)))

    step_cache.reset_stats()
    windows = 6
    for _ in range(windows):
        step(x, y)
    st = step_cache.stats()["by_kind"]["train_step"]
    assert st["compiles"] == 1
    assert st["dispatches"] == windows
    assert st["cache_hits"] == windows - 1

    recs = get_registry().events("train.telemetry")
    assert [r["step"] for r in recs] == [2, 4, 6]    # drain_every=2
    for r in recs:
        assert r["windows"] == 2
        assert np.isfinite(r["loss_mean"]) and np.isfinite(r["grad_norm"])
    # drained gauges track the last drain
    assert get_registry().gauge("train.grad_norm").value == \
        recs[-1]["grad_norm"]


def test_telemetry_off_leaves_state_signature_unchanged():
    """telemetry=False (the default) keeps StepState.telem=None — an
    empty pytree subtree, so signatures and checkpoints are identical to
    pre-observe builds."""
    model = _mlp()
    opt = FusedSGD(list(model.parameters()), lr=0.1)
    step = make_train_step(model, opt, lambda o, t: F.cross_entropy(o, t))
    assert step.state.telem is None
    assert step.drain_telemetry() is None
    x, y = _data()
    step(x, y)
    assert step.state.telem is None


# ---------------------------------------------------------------------------
# stall watchdog
# ---------------------------------------------------------------------------


@pytest.mark.chaos
def test_watchdog_fires_on_injected_stall():
    """A chaos train.step delay wedges the dispatch loop; the watchdog
    must emit exactly one typed diagnostic carrying the last step, the
    last span, the backend, and the stale-tunnel remediation hint."""
    get_registry().clear_events()
    model = _mlp()
    opt = FusedSGD(list(model.parameters()), lr=0.1)
    step = make_train_step(model, opt, lambda o, t: F.cross_entropy(o, t))
    x, y = _data()
    step(x, y)                          # compile outside the timed window

    heartbeat()                         # fresh anchor for THIS test
    wd = StallWatchdog(deadline_s=0.12, poll_s=0.03)
    with wd:
        with chaos.session(seed=0) as c:
            c.on("train.step", action="delay", delay_s=0.6, at=1)
            step(x, y)                  # call 1 (fast), beats
            step(x, y)                  # call 2: delayed 0.6s -> stall
    assert len(wd.stalls) == 1          # one diagnostic per stall, not per poll
    diag = wd.stalls[0]
    assert diag["deadline_s"] == 0.12
    assert diag["since_last_step_s"] >= 0.12
    assert diag["last_step"] == 2       # heartbeats carry the call count
    assert diag["backend"] == "cpu"
    assert diag["last_span"] is not None and "span" in diag["last_span"]
    assert "stale axon tunnel claim" in diag["hint"]
    (ev,) = get_registry().events("watchdog.stall")
    assert ev["hint"] == diag["hint"]


def test_watchdog_silent_on_clean_run():
    model = _mlp()
    opt = FusedSGD(list(model.parameters()), lr=0.1)
    step = make_train_step(model, opt, lambda o, t: F.cross_entropy(o, t))
    x, y = _data()
    step(x, y)                          # compile outside the timed window

    heartbeat()
    wd = StallWatchdog(deadline_s=0.6, poll_s=0.05)
    with wd:
        t0 = time.monotonic()
        while time.monotonic() - t0 < 1.0:   # longer than the deadline
            step(x, y)                  # each dispatch beats
            time.sleep(0.05)
    assert wd.stalls == []


def test_watchdog_rejects_nonpositive_deadline():
    with pytest.raises(ValueError):
        StallWatchdog(deadline_s=0.0)


def test_observe_exports():
    """The public surface other subsystems wire against."""
    for name in ("span", "last_span", "counter", "gauge", "histogram",
                 "event", "events", "get_registry", "MetricsRegistry",
                 "StallWatchdog", "heartbeat", "last_heartbeat",
                 "StepTelemetry", "init_telemetry", "accumulate",
                 "SCHEMA_VERSION", "STALL_HINT"):
        assert hasattr(observe, name), name
