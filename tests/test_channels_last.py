"""Channels-last (NHWC) execution (nn.to_channels_last + the
channel-axis paths in nn/functional.py): the layout flip must be
numerically invisible — same logits, same parameter gradients — with
weights stored identically (OIHW) in both layouts.

Reference analogue: the channel-last kernel variants in
apex/contrib/groupbn and apex/parallel/optimized_sync_batchnorm.py:58;
oracle style follows SURVEY.md §4 (fused/alternate path == reference
path numerics)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import apex_tpu.nn as nn
import apex_tpu.nn.functional as F
from apex_tpu.models.resnet import resnet18
from apex_tpu.nn.modules import Ctx


def _nhwc(x):
    return jnp.transpose(x, (0, 2, 3, 1))


def test_conv2d_channels_last_matches(rng):
    x = jnp.asarray(rng.standard_normal((2, 5, 12, 12)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((7, 5, 3, 3)), jnp.float32)
    b = jnp.asarray(rng.standard_normal((7,)), jnp.float32)
    want = F.conv2d(x, w, b, stride=2, padding=1)
    got = F.conv2d(_nhwc(x), w, b, stride=2, padding=1,
                   channels_last=True)
    np.testing.assert_allclose(np.asarray(got),
                               np.asarray(_nhwc(want)),
                               rtol=1e-5, atol=1e-5)


def test_grouped_conv2d_channels_last_matches(rng):
    x = jnp.asarray(rng.standard_normal((2, 6, 8, 8)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((4, 3, 3, 3)), jnp.float32)
    want = F.conv2d(x, w, None, padding=1, groups=2)
    got = F.conv2d(_nhwc(x), w, None, padding=1, groups=2,
                   channels_last=True)
    np.testing.assert_allclose(np.asarray(got), np.asarray(_nhwc(want)),
                               rtol=1e-5, atol=1e-5)


def test_pools_channels_last_match(rng):
    x = jnp.asarray(rng.standard_normal((2, 4, 11, 11)), jnp.float32)
    for f, kw in ((F.max_pool2d, dict(kernel_size=3, stride=2, padding=1)),
                  (F.avg_pool2d, dict(kernel_size=2)),
                  (F.adaptive_avg_pool2d, dict(output_size=(1, 1))),
                  (F.adaptive_avg_pool2d, dict(output_size=(3, 5)))):
        want = f(x, **kw)
        got = f(_nhwc(x), channels_last=True, **kw)
        np.testing.assert_allclose(np.asarray(got),
                                   np.asarray(_nhwc(want)),
                                   rtol=1e-5, atol=1e-5, err_msg=str(kw))


def test_batch_norm_channel_axis_matches(rng):
    x = jnp.asarray(rng.standard_normal((3, 5, 6, 6)), jnp.float32) + 2.0
    rm = jnp.zeros((5,))
    rv = jnp.ones((5,))
    w = jnp.asarray(rng.standard_normal((5,)), jnp.float32)
    b = jnp.asarray(rng.standard_normal((5,)), jnp.float32)
    want, wrm, wrv = F.batch_norm(x, rm, rv, w, b, training=True)
    got, grm, grv = F.batch_norm(_nhwc(x), rm, rv, w, b, training=True,
                                 channel_axis=-1)
    np.testing.assert_allclose(np.asarray(got), np.asarray(_nhwc(want)),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(grm), np.asarray(wrm), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(grv), np.asarray(wrv), rtol=1e-6)


def test_resnet_channels_last_forward_and_grads_match(rng):
    """The MFU-lever flow: the same ResNet weights run NCHW and NHWC;
    logits and every parameter gradient agree (layout is numerically
    invisible, OIHW weights shared)."""
    nn.manual_seed(0)
    model = resnet18(num_classes=7, small_input=True)
    nn.manual_seed(0)
    model_cl = nn.to_channels_last(resnet18(num_classes=7,
                                            small_input=True))
    for a, b in zip(model.parameters(), model_cl.parameters()):
        b.data = a.data

    x = jnp.asarray(rng.standard_normal((2, 3, 16, 16)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 7, (2,)))

    def loss_of(m, params, xin):
        vals = list(params)
        ps = list(m.parameters())
        ctx = Ctx(env={id(p): v for p, v in zip(ps, vals)},
                  stats_out={}, training=True)
        logits = m.forward(ctx, xin)
        return F.cross_entropy(logits, y), logits

    p0 = [p.data for p in model.parameters()]
    (want_l, want_logits), want_g = jax.value_and_grad(
        lambda ps: loss_of(model, ps, x), has_aux=True)(p0)
    (got_l, got_logits), got_g = jax.value_and_grad(
        lambda ps: loss_of(model_cl, ps, _nhwc(x)), has_aux=True)(p0)

    np.testing.assert_allclose(np.asarray(got_logits),
                               np.asarray(want_logits),
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(float(got_l), float(want_l), rtol=1e-5)
    for ga, gb in zip(want_g, got_g):
        np.testing.assert_allclose(np.asarray(gb), np.asarray(ga),
                                   rtol=2e-3, atol=2e-4)


def test_resnet_channels_last_eval_uses_running_stats(rng):
    nn.manual_seed(1)
    model = resnet18(num_classes=5, small_input=True)
    model.eval()
    nn.manual_seed(1)
    model_cl = nn.to_channels_last(resnet18(num_classes=5,
                                            small_input=True))
    model_cl.eval()
    for a, b in zip(model.parameters(), model_cl.parameters()):
        b.data = a.data
    x = jnp.asarray(rng.standard_normal((2, 3, 16, 16)), jnp.float32)
    ctx = Ctx(training=False)
    want = model.forward(ctx, x)
    got = model_cl.forward(Ctx(training=False), _nhwc(x))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-4, atol=1e-5)


def test_to_channels_last_refuses_conv_transpose():
    nn.manual_seed(2)
    gen = nn.Sequential(nn.ConvTranspose2d(4, 8, 4, stride=2),
                        nn.ReLU())
    with pytest.raises(ValueError, match="ConvTranspose2d"):
        nn.to_channels_last(gen)


def test_to_channels_last_refuses_axis1_norms():
    """Norms whose channel axis stays hard-coded at 1 refuse instead of
    silently normalizing the wrong axis under NHWC."""
    nn.manual_seed(2)
    for bad in (nn.GroupNorm(2, 4), nn.InstanceNorm2d(4),
                nn.BatchNorm1d(4), nn.BatchNorm3d(4)):
        tree = nn.Sequential(nn.Conv2d(3, 4, 3), bad)
        with pytest.raises(ValueError, match="channels-last path"):
            nn.to_channels_last(tree)


def test_sync_batchnorm_channel_last_native_axis(rng):
    """SyncBatchNorm(channel_last=True) normalizes NHWC natively (no
    transpose sandwich) and matches the NCHW module's numbers."""
    from apex_tpu.parallel import SyncBatchNorm

    bn = SyncBatchNorm(6, axis_name="data")
    bn_cl = SyncBatchNorm(6, channel_last=True, axis_name="data")
    for a, b in zip(bn.parameters(), bn_cl.parameters()):
        b.data = a.data
    x = jnp.asarray(rng.standard_normal((2, 6, 4, 4)), jnp.float32)
    # outside shard_map the axis is unbound -> local stats (warned path)
    want = bn.forward(Ctx(training=True, stats_out={}), x)
    got = bn_cl.forward(Ctx(training=True, stats_out={}), _nhwc(x))
    np.testing.assert_allclose(np.asarray(got), np.asarray(_nhwc(want)),
                               rtol=1e-5, atol=1e-5)
    assert bn_cl.channel_last is True    # reference-API spelling intact


def test_resnet_channels_last_bf16_step_parity(rng):
    """The queued bench arm's exact regime (half_dtype=bf16 fused step,
    fp32-stat BN): NHWC and NCHW runs of the same weights stay together
    over several steps — de-risks `bench.py --nhwc` numerics."""
    import jax.numpy as jnp
    from apex_tpu.optimizers import FusedSGD
    from apex_tpu.training import make_train_step

    def build(cl):
        nn.manual_seed(4)
        m = resnet18(num_classes=7, small_input=True)
        if cl:
            nn.to_channels_last(m)
        opt = FusedSGD(list(m.parameters()), lr=0.05, momentum=0.9)
        step = make_train_step(m, opt,
                               lambda o, y: F.cross_entropy(o, y),
                               half_dtype=jnp.bfloat16, loss_scale=1.0)
        return m, step

    m_a, step_a = build(False)
    m_b, step_b = build(True)
    for a, b in zip(m_a.parameters(), m_b.parameters()):
        b.data = a.data

    x = jnp.asarray(rng.standard_normal((4, 3, 16, 16)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 7, (4,)))
    la = [float(step_a(x, y)) for _ in range(4)]
    lb = [float(step_b(jnp.transpose(x, (0, 2, 3, 1)), y))
          for _ in range(4)]
    # bf16 activations round differently across layouts (conv
    # reassociation), so trajectories drift — bound it per step
    for a, b in zip(la, lb):
        assert abs(a - b) / max(abs(a), 1e-6) < 0.05, (la, lb)
    assert lb[-1] < lb[0]          # and it actually learns
