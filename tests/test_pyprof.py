"""pyprof analogue: annotate → parse → prof pipeline (reference test model:
tests/L0/run_pyprof_nvtx + run_pyprof_data — patching coverage and analysis
correctness on known ops)."""
import json
import subprocess
import sys

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import apex_tpu.nn as nn
from apex_tpu import pyprof
from apex_tpu.nn import functional as F
from apex_tpu.pyprof.parse.parse import enrich
from apex_tpu.pyprof.prof.models import model_row
from apex_tpu.pyprof.prof.prof import analyze_rows


@pytest.fixture(autouse=True)
def _disable_after():
    yield
    pyprof.annotate.set_enabled(False)


def test_capture_records_functional_ops(rng):
    x = jnp.asarray(rng.standard_normal((4, 8)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((3, 8)), jnp.float32)
    with pyprof.capture() as ev:
        y = F.linear(x, w)
        F.relu(y)
    ops = [e["op"] for e in ev]
    assert ops == ["linear", "relu"]
    assert ev[0]["shapes"][0] == [4, 8] and ev[0]["shapes"][1] == [3, 8]
    assert ev[0]["dtypes"][0] == "float32"


def test_capture_inside_jit_records_once(rng):
    x = jnp.asarray(rng.standard_normal((4, 8)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((8, 8)), jnp.float32)

    @jax.jit
    def f(x, w):
        return F.relu(F.linear(x, w))

    with pyprof.capture() as ev:
        f(x, w)
        f(x, w)  # cached trace: no re-record
    assert [e["op"] for e in ev] == ["linear", "relu"]


def test_module_scope_and_conv_staticmethod_rebind(rng):
    nn.manual_seed(0)
    model = nn.Sequential(nn.Conv2d(3, 4, 3, padding=1), nn.ReLU())
    x = jnp.asarray(rng.standard_normal((2, 3, 8, 8)), jnp.float32)
    with pyprof.capture() as ev:
        model(x)
    convs = [e for e in ev if e["op"] == "conv2d"]
    assert len(convs) == 1, [e["op"] for e in ev]
    assert "Conv2d" in convs[0]["scope"]


def test_optimizer_step_annotated(rng):
    from apex_tpu.optimizers import FusedSGD
    nn.manual_seed(0)
    lin = nn.Linear(4, 4)
    opt = FusedSGD(list(lin.parameters()), lr=0.1)
    for p in lin.parameters():
        p.grad = jnp.zeros_like(p.data)
    with pyprof.capture() as ev:
        opt.step()
    assert any(e["op"] == "optimizer.FusedSGD.step" for e in ev)
    numel = sum(int(np.prod(p.data.shape)) for p in lin.parameters())
    step_ev = next(e for e in ev if e["op"].endswith("step"))
    assert step_ev["shapes"][0] == [numel]


def test_parse_synthesizes_backward():
    ev = [{"seq": 0, "op": "linear", "dir": "fwd", "scope": "",
           "shapes": [[4, 8], [3, 8]], "dtypes": ["float32"], "tensors": {},
           "params": {}, "callsite": None},
          {"seq": 1, "op": "relu", "dir": "fwd", "scope": "",
           "shapes": [[4, 3]], "dtypes": ["float32"], "tensors": {},
           "params": {}, "callsite": None}]
    rows = enrich(ev)
    assert [(r["op"], r["dir"]) for r in rows] == [
        ("linear", "fwd"), ("relu", "fwd"), ("relu", "bwd"),
        ("linear", "bwd")]
    assert rows[3]["corr"] == 0  # bwd linked to its fwd


def test_flop_models_known_values():
    linear = {"op": "linear", "dir": "fwd", "shapes": [[32, 64], [16, 64]],
              "dtypes": ["bfloat16"], "params": {}}
    f, b, mxu = model_row(linear)
    assert f == 2 * 32 * 64 * 16
    assert mxu["eligible"] is True
    bwd = dict(linear, dir="bwd")
    assert model_row(bwd)[0] == 2 * f

    conv = {"op": "conv2d", "dir": "fwd",
            "shapes": [[2, 3, 8, 8], [4, 3, 3, 3]], "dtypes": ["float32"],
            "params": {"stride": 1, "padding": 1, "dilation": 1,
                       "groups": 1}}
    f, b, mxu = model_row(conv)
    assert f == 2 * 2 * 4 * 8 * 8 * 3 * 3 * 3   # 2·N·Cout·H'·W'·Cin·Kh·Kw
    assert mxu["eligible"] is False  # f32

    # perfectly-tiled matmul → util 1.0
    mm = {"op": "matmul", "dir": "fwd", "shapes": [[128, 256], [256, 128]],
          "dtypes": ["bfloat16"], "params": {}}
    assert model_row(mm)[2]["util"] == 1.0


def test_analyze_roofline_bounds():
    rows = enrich([
        {"seq": 0, "op": "linear", "dir": "fwd",
         "shapes": [[1024, 1024], [1024, 1024]], "dtypes": ["bfloat16"],
         "tensors": {}, "params": {}, "callsite": None, "scope": ""},
        {"seq": 1, "op": "relu", "dir": "fwd", "shapes": [[1024, 1024]],
         "dtypes": ["bfloat16"], "tensors": {}, "params": {},
         "callsite": None, "scope": ""}], with_backward=False)
    out = analyze_rows(rows)
    assert out[0]["bound"] == "compute"   # big matmul
    assert out[1]["bound"] == "memory"    # pointwise
    assert out[0]["est_us"] > 0


def test_cli_pipeline(tmp_path, rng):
    x = jnp.asarray(rng.standard_normal((4, 8)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((3, 8)), jnp.float32)
    with pyprof.capture() as ev:
        F.relu(F.linear(x, w))
    import os
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    raw = tmp_path / "run.jsonl"
    pyprof.save(str(raw), ev)
    parsed = subprocess.run(
        [sys.executable, "-m", "apex_tpu.pyprof.parse", str(raw)],
        capture_output=True, text=True, check=True, cwd=repo)
    dict_file = tmp_path / "net.dict"
    dict_file.write_text(parsed.stdout)
    rows = [json.loads(l) for l in parsed.stdout.splitlines()]
    assert len(rows) == 4  # 2 fwd + 2 bwd
    prof = subprocess.run(
        [sys.executable, "-m", "apex_tpu.pyprof.prof", str(dict_file),
         "--csv"],
        capture_output=True, text=True, check=True, cwd=repo)
    assert "linear" in prof.stdout and "est_us" in prof.stdout


def test_conv_params_captured_positionally_and_as_tuples(rng):
    x = jnp.asarray(rng.standard_normal((1, 3, 8, 8)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((4, 3, 3, 3)), jnp.float32)
    with pyprof.capture() as ev:
        F.conv2d(x, w, None, (2, 2), (1, 1))   # positional tuple args
        F.max_pool2d(x, 3)                     # positional int kernel
    conv, pool = ev
    assert conv["params"]["stride"] == [2, 2]
    assert conv["params"]["padding"] == [1, 1]
    assert pool["params"]["kernel_size"] == 3
    rows = pyprof.analyze(ev, with_backward=False)
    # stride-2/pad-1: out 4x4 -> 2*1*4*4*4*3*3*3 flops
    assert rows[0]["flops"] == 2 * 1 * 4 * 4 * 4 * 3 * 3 * 3
    # 3x3 pool costed as 9 flops/elem, not the default 2x2
    assert rows[1]["flops"] == 9 * 3 * 8 * 8


def test_amp_policy_effective_dtype_recorded(rng):
    from apex_tpu.amp.policy import CastPolicy, autocast
    x = jnp.asarray(rng.standard_normal((4, 8)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((3, 8)), jnp.float32)
    with pyprof.capture() as ev:
        with autocast(CastPolicy(half_dtype=jnp.bfloat16)):
            F.linear(x, w)      # half list -> bf16 on the MXU
            F.softmax(x)        # float list -> stays f32
    assert ev[0]["dtypes"][0] == "bfloat16"
    assert ev[1]["dtypes"][0] == "float32"
    rows = pyprof.analyze(ev, with_backward=False)
    assert rows[0]["mxu"]["eligible"] is True


def test_analyze_in_process(rng):
    x = jnp.asarray(rng.standard_normal((4, 8)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((3, 8)), jnp.float32)
    with pyprof.capture() as ev:
        F.relu(F.linear(x, w))
    rows = pyprof.analyze(ev)
    assert len(rows) == 4
    assert all("flops" in r and "est_us" in r for r in rows)


@pytest.mark.skipif(
    not pyprof.thunk_events_available(),
    reason="backend capability: jax.profiler on this backend emits no "
           "XLA thunk-duration events (pyprof.thunk_events_available() "
           "probed false — CPU jaxlib 0.4.x), so the trace<->HLO join "
           "has nothing to measure; runs on real TPU")
def test_profile_step_measured_durations(rng, tmp_path):
    """The measured pipeline (VERDICT round 1 #5): profile a tiny jitted
    step, join jax.profiler thunk events to annotate ops through the HLO
    metadata, and get per-op rows with measured durations — the TPU-native
    analogue of the reference's nvprof-SQL kernel<->marker correlation
    (apex/pyprof/parse/nvvp.py:91-199)."""
    x = jnp.asarray(rng.standard_normal((64, 128)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((256, 128)), jnp.float32)
    y = jnp.asarray(rng.standard_normal((64, 256)), jnp.float32)

    def step(x, w, y):
        def loss_fn(w):
            h = F.relu(F.linear(x, w))
            return F.mse_loss(h, y)
        import jax
        return jax.value_and_grad(loss_fn)(w)

    rows, report = pyprof.profile_step(
        step, x, w, y, trace_dir=str(tmp_path), executions=3)

    assert report["matched_seqs"] >= 1
    assert report["matched_us"] > 0
    measured = [r for r in rows if r.get("meas_us")]
    assert measured, f"no measured rows; report={report}"
    # the linear op must have a measured fwd duration and analytic columns
    lin_fwd = [r for r in rows if r["op"] == "linear" and r["dir"] == "fwd"]
    assert lin_fwd and lin_fwd[0]["meas_us"] and lin_fwd[0]["meas_us"] > 0
    assert lin_fwd[0]["flops"] > 0 and lin_fwd[0]["tflops"] is not None
    # backward rows replace the analytic synthesis with measurements when
    # the transpose thunks matched
    lin_bwd = [r for r in rows if r["op"] == "linear" and r["dir"] == "bwd"]
    assert lin_bwd
    # the unmatched bucket is named by thunk category, and its categories
    # sum to the unattributed total (same trace, same scale)
    by = report["unattributed_by"]
    assert abs(sum(by.values()) - report["unattributed_us"]) < 1.0


def test_correlate_unattributed_breakdown():
    """Unmatched thunk time buckets by instruction-name stem (no metadata)
    or scope-less op_name tail — the split that tells layout transposes
    from unannotated compute in a profile."""
    from apex_tpu.pyprof.parse.trace import correlate

    thunks = [
        {"name": "pp0lin", "dur_us": 5.0, "ts_us": 0.0},       # matched
        {"name": "transpose.7", "dur_us": 3.0, "ts_us": 1.0},  # no metadata
        {"name": "transpose.9", "dur_us": 2.0, "ts_us": 2.0},
        {"name": "copy.1", "dur_us": 4.0, "ts_us": 3.0},
        {"name": "fusion.2", "dur_us": 1.5, "ts_us": 4.0},     # scope-less
    ]
    smap = {"pp0lin": "jit(f)/pp0_linear/dot_general",
            "fusion.2": "jit(f)/convert_element_type"}
    per_seq, unattributed, by = correlate(thunks, smap)
    assert per_seq[0]["fwd_us"] == 5.0
    assert unattributed == 10.5
    assert by == {"transpose": 5.0, "copy": 4.0,
                  "op:convert_element_type": 1.5}


@pytest.mark.skipif(
    not pyprof.thunk_events_available(),
    reason="same capability probe as test_profile_step_measured_durations:"
           " no thunk-duration events from jax.profiler on this backend, "
           "so the CLI's dur_us column is empty")
def test_parse_cli_with_trace(tmp_path, rng):
    """CLI join path: parse --trace --hlo produces dur_us columns."""
    import io
    import json as _json
    import sys

    import jax

    from apex_tpu.pyprof.parse import parse as parse_mod

    x = jnp.asarray(rng.standard_normal((32, 64)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((16, 64)), jnp.float32)

    def fwd(x, w):
        return F.relu(F.linear(x, w)).sum()

    with pyprof.capture() as ev:
        jitted = jax.jit(fwd)
        lowered = jitted.lower(x, w)
    events_file = tmp_path / "events.jsonl"
    pyprof.save(str(events_file), ev)

    compiled = lowered.compile()
    hlo_file = tmp_path / "hlo.txt"
    hlo_file.write_text(compiled.as_text())
    trace_dir = tmp_path / "trace"
    with jax.profiler.trace(str(trace_dir)):
        for _ in range(2):
            out = compiled(x, w)
        float(out)

    old = sys.stdout
    sys.stdout = io.StringIO()
    try:
        parse_mod.main([str(events_file), "--trace", str(trace_dir),
                        "--hlo", str(hlo_file), "--executions", "2",
                        "--no-backward"])
        lines = sys.stdout.getvalue().strip().splitlines()
    finally:
        sys.stdout = old
    rows = [_json.loads(ln) for ln in lines]
    assert any(r.get("dur_us") for r in rows)


def test_tensor_method_ops_captured(rng):
    """Tape-level Tensor ops (add/mul/mean/log...) are recorded through the
    record_op hook — the analogue of the reference wrapping torch.Tensor
    methods via tensor_overrides (nvmarker.py)."""
    import apex_tpu.nn as nn
    from apex_tpu import pyprof

    nn.manual_seed(0)
    model = nn.Linear(8, 4)
    x = jnp.asarray(rng.standard_normal((2, 8)), jnp.float32)
    with pyprof.capture() as events:
        out = model(x)
        y = ((out * 2.0 + 1.0).abs() + 1e-3).log().mean()
        float(y)
    ops = [e["op"] for e in events]
    assert "linear" in ops
    for expected in ("mul", "add", "abs", "log", "mean"):
        assert expected in ops, f"{expected} not captured: {ops}"
    add_ev = next(e for e in events if e["op"] == "mul")
    assert add_ev["shapes"][0] == [2, 4]


def test_tape_op_flop_models():
    from apex_tpu.pyprof.prof.models import model_row

    row = {"op": "add", "dir": "fwd", "shapes": [[4, 8], [4, 8]],
           "dtypes": ["float32", "float32"], "params": {}}
    f, b, m = model_row(row)
    assert f == 32 and b == 3 * 32 * 4 and m is None

    # broadcasting: work follows the larger operand, not shapes[0]
    row = {"op": "mul", "dir": "fwd", "shapes": [[1, 8], [4096, 8]],
           "dtypes": ["float32", "float32"], "params": {}}
    f, b, _ = model_row(row)
    assert f == 4096 * 8

    row = {"op": "mean", "dir": "fwd", "shapes": [[4, 8]],
           "dtypes": ["float32"], "params": {}}
    f, b, _ = model_row(row)
    assert f == 32 and b == 32 * 4

    row = {"op": "reshape", "dir": "fwd", "shapes": [[4, 8]],
           "dtypes": ["float32"], "params": {}}
    assert model_row(row)[:2] == (0, 0)  # XLA view: free

    # movement sized by the output: one row out of a big tensor
    row = {"op": "getitem", "dir": "fwd", "shapes": [[1024, 1024]],
           "dtypes": ["float32"], "params": {}, "out_shape": [1024]}
    f, b, _ = model_row(row)
    assert f == 0 and b == 2 * 1024 * 4

    # cast bytes use both dtypes
    row = {"op": "astype", "dir": "fwd", "shapes": [[4, 8]],
           "dtypes": ["bfloat16"], "params": {"dtype": "float32"},
           "out_shape": [4, 8]}
    f, b, _ = model_row(row)
    assert f == 0 and b == 32 * (2 + 4)

    # matmul rank promotion: vector dot and matvec must not crash
    row = {"op": "matmul", "dir": "fwd", "shapes": [[8], [8]],
           "dtypes": ["float32", "float32"], "params": {}}
    f, b, _ = model_row(row)
    assert f == 2 * 8
    row = {"op": "matmul", "dir": "fwd", "shapes": [[4, 8], [8]],
           "dtypes": ["float32", "float32"], "params": {}}
    f, b, _ = model_row(row)
    assert f == 2 * 4 * 8


def test_fused_ops_annotated(rng):
    """Flash attention, FusedLayerNorm and contrib xentropy live outside
    nn.functional; init() wraps their defining-module bindings so module
    classes that call them produce profile rows."""
    from apex_tpu.contrib.multihead_attn import SelfMultiheadAttn
    from apex_tpu.contrib.xentropy import SoftmaxCrossEntropyLoss
    from apex_tpu.normalization import FusedLayerNorm

    nn.manual_seed(0)
    attn = SelfMultiheadAttn(16, 2, dropout=0.0, impl="fast", causal=True)
    ln = FusedLayerNorm(16)
    x = jnp.asarray(rng.standard_normal((8, 2, 16)), jnp.float32)
    logits = jnp.asarray(rng.standard_normal((4, 11)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 11, (4,)))
    with pyprof.capture() as ev:
        out, _ = attn(x)
        ln(out)
        SoftmaxCrossEntropyLoss.apply(logits, labels)
    ops = [e["op"] for e in ev]
    assert "flash_attention" in ops
    assert "fused_layer_norm_affine" in ops
    assert "softmax_cross_entropy_loss" in ops
    fa = ev[ops.index("flash_attention")]
    assert fa["params"].get("causal") is True
    assert len(fa["shapes"][0]) == 4  # (B, H, S, D)


def test_fused_op_flop_models():
    """Known-value cost models for the fused families, incl. the causal
    halving, the flash bytes model (no S^2 traffic) and bwd factors."""
    row = {"op": "flash_attention", "dir": "fwd",
           "shapes": [[2, 4, 64, 32], [2, 4, 64, 32], [2, 4, 64, 32]],
           "dtypes": ["bfloat16"], "params": {"causal": False}}
    f, b, m = model_row(row)
    area = 2 * 4 * 64 * 64
    assert f == 2 * 2 * area * 32 + 5 * area
    assert b == 2 * 4 * (2 * 64 + 2 * 64) * 32 * 2  # qkvo only, bf16
    assert m["eligible"]
    f_causal, _, _ = model_row({**row, "params": {"causal": True}})
    assert f_causal == f / 2
    f_bwd, _, _ = model_row({**row, "dir": "bwd"})
    assert f_bwd == 2.5 * f

    row = {"op": "fused_layer_norm_affine", "dir": "fwd",
           "shapes": [[8, 16], [16], [16]], "dtypes": ["float32"],
           "params": {"normalized_shape": [16]}}
    f, b, _ = model_row(row)
    assert f == 8 * 8 * 16 and b == 3 * 8 * 16 * 4

    row = {"op": "softmax_cross_entropy_loss", "dir": "fwd",
           "shapes": [[4, 11], [4]], "dtypes": ["float32"], "params": {}}
    f, b, _ = model_row(row)
    assert f == 7 * 4 * 11 and b == 2 * 4 * 11 * 4


def test_fused_ops_grads_flow_after_annotation(rng):
    """Wrapping must not break the custom-vjp gradient paths."""
    from apex_tpu import normalization
    pyprof.annotate.init()
    pyprof.annotate.set_enabled(False)
    x = jnp.asarray(rng.standard_normal((8, 16)), jnp.float32)
    w = jnp.ones((16,), jnp.float32)
    bias = jnp.zeros((16,), jnp.float32)

    def loss(x, w, bias):
        return jnp.sum(normalization.fused_layer_norm_affine(
            x, w, bias, (16,)) ** 2)

    g = jax.grad(loss, argnums=(0, 1, 2))(x, w, bias)
    assert all(np.isfinite(np.asarray(gi)).all() for gi in g)
    assert float(jnp.abs(g[0]).max()) > 0


def test_flash_attention_package_reexport_annotated(rng):
    """The multihead_attn package re-export must be wrapped too, not just
    the defining module."""
    from apex_tpu.contrib import multihead_attn as pkg
    q = jnp.asarray(rng.standard_normal((1, 2, 8, 4)), jnp.float32)
    with pyprof.capture() as ev:
        pkg.flash_attention(q, q, q, causal=True)
    assert [e["op"] for e in ev] == ["flash_attention"]


def test_rms_norm_annotated_and_modeled(rng):
    """The Llama-family norm rows get the norm cost model (not the
    generic 1-flop fallback) and FusedRMSNorm calls produce rows."""
    from apex_tpu.normalization import FusedRMSNorm

    nn.manual_seed(0)
    rn = FusedRMSNorm(16)
    x = jnp.asarray(rng.standard_normal((8, 16)), jnp.float32)
    with pyprof.capture() as ev:
        rn(x)
    ops = [e["op"] for e in ev]
    assert "fused_rms_norm_affine" in ops

    row = {"op": "fused_rms_norm_affine", "dir": "fwd",
           "shapes": [[8, 16], [16]], "dtypes": ["float32"],
           "params": {"normalized_shape": [16]}}
    f, b, _ = model_row(row)
    assert f == 6 * 8 * 16 and b == 3 * 8 * 16 * 4


def test_nvtx_annotate_delegates_to_observe_span():
    """The replacement for the dead thunk-event path on thunk-less
    backends: nvtx.annotate is observe.span, so pyprof range markers land
    in the observe event stream (and TraceAnnotation) with durations
    measured on the host — available on EVERY backend."""
    from apex_tpu import observe
    from apex_tpu.pyprof import nvtx

    before = len(observe.events("span"))
    with nvtx.annotate("pyprof.region", phase="fwd"):
        jnp.ones((4, 4)).sum().block_until_ready()
    spans = observe.events("span")[before:]
    ours = [e for e in spans if e["span"] == "pyprof.region"]
    assert len(ours) == 1
    rec = ours[0]
    assert rec["schema"] == observe.SCHEMA_VERSION
    assert rec["dur_ms"] >= 0
    assert rec["phase"] == "fwd"
    # the open span was recorded for the stall watchdog's diagnostics
    last = observe.last_span()
    assert last is not None and "span" in last


def test_thunk_capability_probe_is_cached_and_boolean():
    """The capability gate the two measured-pipeline tests now key on:
    a plain bool, probed once per process (second call hits the cache)."""
    r1 = pyprof.thunk_events_available()
    r2 = pyprof.thunk_events_available()
    assert isinstance(r1, bool) and r1 is r2
    # on the CPU-forced test image the probe must come back False —
    # exactly the condition that skips the measured-duration tests
    assert r1 is False
