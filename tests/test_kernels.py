"""The measured kernel tier (apex_tpu.kernels): interpret-mode parity
pins for all three kernels (flash attention incl. causal/window masks
and the ring sp composition, fused multi-tensor updates vs the
per-bucket stacks, the fused vocab chain vs the chunked XLA chain),
calibration-ledger round-trips and corrupt-entry recovery, and the
dispatch policy itself — a below-threshold ledger entry must route to
XLA and the deciding entry must land in the observe event log.

Parity regime: fp32 comparisons are BITWISE but always jit-vs-jit —
XLA CPU contracts mul+add into FMA under jit but not eagerly, so an
eager arm differs from any jitted arm by ~1 ulp while two jitted arms
(the only configuration production runs) agree exactly.
"""
import functools
import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.contrib.multihead_attn.attn_funcs import (
    attention_reference, flash_attention)
from apex_tpu.contrib.xentropy.chunked import chunked_lm_head_loss
from apex_tpu.kernels import dispatch, ledger
from apex_tpu.kernels.dispatch import force_mode
from apex_tpu.kernels.multi_tensor import fused_adam, fused_sgd, group_fp
from apex_tpu.kernels.vocab_chain import vocab_chain_loss
from apex_tpu.ops import multi_tensor as ops_mt
from apex_tpu.parallel import ring_attention
from apex_tpu.runtime import step_cache

pytestmark = pytest.mark.kernels


@pytest.fixture
def tmp_ledger(tmp_path):
    """A fresh ledger file + cleared decision cache, restored after."""
    led = ledger.set_path(str(tmp_path / "ledger.json"))
    dispatch.reset_decisions()
    yield led
    ledger.set_path(None)
    dispatch.reset_decisions()


def _tensors(rng, shapes, dtype=jnp.float32):
    return [jnp.asarray(rng.standard_normal(s), dtype) for s in shapes]


SHAPES = [(33, 7), (128,), (5, 3, 11), (257,)]


# ---------------------------------------------------------------------------
# fused multi-tensor vs per-bucket: bitwise, jit-vs-jit
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("momentum,nesterov,wd,wd_after", [
    (0.9, False, 0.01, False),
    (0.9, True, 0.01, True),
    (0.0, False, 0.0, False),
])
def test_fused_sgd_bitwise_vs_per_bucket(rng, dtype, momentum, nesterov,
                                         wd, wd_after):
    gs = _tensors(rng, SHAPES, dtype)
    ps = _tensors(rng, SHAPES, dtype)
    ms = _tensors(rng, SHAPES, jnp.float32)
    flag = jnp.zeros((), jnp.int32)
    args = (wd, momentum, 0.0, 0.1, nesterov, False, wd_after, 2.0)
    with force_mode("interpret"):
        ref = jax.jit(lambda f, t: ops_mt.sgd_unfused(f, t, *args))(
            flag, [gs, ps, ms])
        got = jax.jit(lambda f, t: fused_sgd(f, t, *args))(
            flag, [gs, ps, ms])
    for r, g in zip(ref[1] + ref[2], got[1] + got[2]):
        np.testing.assert_array_equal(np.asarray(r, np.float32),
                                      np.asarray(g, np.float32))


def test_fused_sgd_depth4_model_copy_bitwise(rng):
    gs = _tensors(rng, SHAPES)
    ps = _tensors(rng, SHAPES)          # fp32 masters
    ms = _tensors(rng, SHAPES)
    model = [p.astype(jnp.bfloat16) for p in ps]
    flag = jnp.zeros((), jnp.int32)
    args = (0.01, 0.9, 0.1, 0.05, False, True, False, 1.0)
    with force_mode("interpret"):
        ref = jax.jit(lambda f, t: ops_mt.sgd_unfused(f, t, *args))(
            flag, [gs, ps, ms, model])
        got = jax.jit(lambda f, t: fused_sgd(f, t, *args))(
            flag, [gs, ps, ms, model])
    assert len(ref) == len(got) == 4
    for lr, lg in zip(ref[1:], got[1:]):
        for r, g in zip(lr, lg):
            assert r.dtype == g.dtype
            np.testing.assert_array_equal(np.asarray(r, np.float32),
                                          np.asarray(g, np.float32))


def test_fused_sgd_noop_flag_skips(rng):
    gs, ps, ms = (_tensors(rng, SHAPES) for _ in range(3))
    flag = jnp.ones((), jnp.int32)
    with force_mode("interpret"):
        got = jax.jit(lambda f, t: fused_sgd(
            f, t, 0.0, 0.9, 0.0, 0.1, False, False, False))(
            flag, [gs, ps, ms])
    for p, np_ in zip(ps, got[1]):
        np.testing.assert_array_equal(np.asarray(p), np.asarray(np_))


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16])
@pytest.mark.parametrize("mode,bias_correction,wd", [
    (0, True, 0.01),        # ADAM_MODE_L2
    (1, True, 0.01),        # decoupled (AdamW)
    (0, False, 0.0),
])
def test_fused_adam_bitwise_vs_per_bucket(rng, dtype, mode,
                                          bias_correction, wd):
    gs = _tensors(rng, SHAPES, dtype)
    ps = _tensors(rng, SHAPES, dtype)
    ms = _tensors(rng, SHAPES, jnp.float32)
    vs = [jnp.abs(t) for t in _tensors(rng, SHAPES, jnp.float32)]
    flag = jnp.zeros((), jnp.int32)
    args = (1e-3, 0.9, 0.999, 1e-8, 7, mode, bias_correction, wd)
    with force_mode("interpret"):
        ref = jax.jit(lambda f, t: ops_mt.adam_unfused(f, t, *args))(
            flag, [gs, ps, ms, vs])
        got = jax.jit(lambda f, t: fused_adam(f, t, *args))(
            flag, [gs, ps, ms, vs])
    for lr, lg in zip(ref[1:], got[1:]):
        for r, g in zip(lr, lg):
            np.testing.assert_array_equal(np.asarray(r, np.float32),
                                          np.asarray(g, np.float32))


# ---------------------------------------------------------------------------
# flash attention parity (incl. masks and the ring sp composition)
# ---------------------------------------------------------------------------

B, H, S, D = 2, 4, 64, 16


def _qkv(rng, dtype=jnp.float32):
    return tuple(jnp.asarray(rng.standard_normal((B, H, S, D)), dtype)
                 for _ in range(3))


@pytest.mark.parametrize("causal,window", [(False, None), (True, None),
                                           (True, 24)])
def test_flash_interpret_parity_masks(rng, tmp_ledger, causal, window):
    q, k, v = _qkv(rng)
    scale = 1.0 / np.sqrt(D)
    ref = attention_reference(q, k, v, None, causal, scale, window=window)
    with force_mode("interpret"):
        out = flash_attention(q, k, v, causal=causal,
                              sliding_window=window)
        g = jax.grad(lambda q, k, v: jnp.sum(jnp.sin(flash_attention(
            q, k, v, causal=causal, sliding_window=window))))(q, k, v)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)
    g_ref = jax.grad(lambda q, k, v: jnp.sum(jnp.sin(attention_reference(
        q, k, v, None, causal, scale, window=window))))(q, k, v)
    np.testing.assert_allclose(np.asarray(g), np.asarray(g_ref),
                               rtol=3e-4, atol=3e-5)


def test_ring_sp_composition_honors_ledger_fallback(rng, tmp_ledger):
    """The sp plan's ring step consults the same dispatch policy: a
    losing ledger entry for the chunk shape routes every ring chunk to
    the XLA fallback (numerics unchanged), a winning one keeps the
    Pallas kernel — both match the gathered-sequence oracle."""
    n = 4
    mesh = Mesh(np.array(jax.devices()[:n]), ("sp",))
    q, k, v = _qkv(rng)
    scale = 1.0 / np.sqrt(D)
    ref = attention_reference(q, k, v, None, True, scale)
    chunk_fp = dispatch.attention_fp(B, H, S // n, S // n, D,
                                     "float32", True)
    chip = ledger.chip_name()

    def run_ring():
        fn = functools.partial(ring_attention, axis_name="sp",
                               causal=True)
        shard = jax.shard_map(fn, mesh=mesh,
                              in_specs=P(None, None, "sp", None),
                              out_specs=P(None, None, "sp", None),
                              check_vma=False)
        return jax.jit(shard)(q, k, v)

    for pallas_us, xla_us, want_tier in ((100.0, 50.0, "xla"),
                                         (50.0, 100.0, "pallas")):
        tmp_ledger.record_kernel(chip, "flash_attention", chunk_fp,
                                 pallas_us=pallas_us, xla_us=xla_us)
        dispatch.reset_decisions()
        with force_mode("interpret"):
            out = run_ring()
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-5, atol=2e-5)
        decided = {(d["kernel"], d["tier"], d["source"])
                   for d in dispatch.decisions()}
        assert ("flash_attention", want_tier, "ledger") in decided


# ---------------------------------------------------------------------------
# vocab chain: fused kernel vs chunked XLA chain, fwd + bwd
# ---------------------------------------------------------------------------


def test_vocab_chain_fwd_bwd_bitwise(rng, tmp_ledger):
    n, v, e = 24, 384, 64
    hidden = jnp.asarray(rng.standard_normal((n, e)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((v, e)) * 0.02, jnp.float32)
    labels = jnp.asarray(rng.integers(0, v, (n,)), jnp.int32)
    labels = labels.at[3].set(-100)      # padding row

    def chunked_mean(h, w):
        per = chunked_lm_head_loss(h, w, labels)
        return per.sum() / jnp.maximum((labels != -100).sum(), 1)

    def fused_mean(h, w):
        per = vocab_chain_loss(h, w, labels)
        return per.sum() / jnp.maximum((labels != -100).sum(), 1)

    with force_mode("interpret"):
        ref = jax.jit(chunked_mean)(hidden, w)
        got = jax.jit(fused_mean)(hidden, w)
        g_ref = jax.jit(jax.grad(chunked_mean, argnums=(0, 1)))(hidden, w)
        g_got = jax.jit(jax.grad(fused_mean, argnums=(0, 1)))(hidden, w)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))
    for r, g in zip(g_ref, g_got):
        np.testing.assert_allclose(np.asarray(r), np.asarray(g),
                                   rtol=1e-6, atol=1e-7)


def test_vocab_chain_smoothing_takes_chunked_path(rng, tmp_ledger):
    """Smoothing is outside the kernel's contract — the dispatch-gated
    entry must produce the chunked chain's exact result."""
    n, v, e = 16, 256, 32
    hidden = jnp.asarray(rng.standard_normal((2, n // 2, e)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((v, e)) * 0.02, jnp.float32)
    labels = jnp.asarray(rng.integers(0, v, (2, n // 2)), jnp.int32)
    with force_mode("interpret"):
        ref = chunked_lm_head_loss(hidden, w, labels, smoothing=0.1)
        got = vocab_chain_loss(hidden, w, labels, smoothing=0.1)
    np.testing.assert_array_equal(np.asarray(ref), np.asarray(got))
    assert ref.shape == hidden.shape[:-1]


# ---------------------------------------------------------------------------
# ledger round-trip + corrupt-entry recovery
# ---------------------------------------------------------------------------


def test_ledger_round_trip(tmp_path):
    led = ledger.Ledger(str(tmp_path / "l.json"))
    rec = led.record_kernel("cpu", "flash_attention", "sk=512",
                            pallas_us=50.0, xla_us=100.0, threshold=512)
    assert rec["win"] == pytest.approx(2.0)
    # a second process sees the same entry from disk
    led2 = ledger.Ledger(str(tmp_path / "l.json"))
    hit = led2.lookup_kernel("cpu", "flash_attention", "sk=512")
    assert hit["win"] == pytest.approx(2.0)
    assert hit["chip"] == "cpu" and hit["shape_fp"] == "sk=512"
    assert led2.lookup_kernel("cpu", "flash_attention", "sk=64") is None
    assert led2.lookup_kernel("tpu v5", "flash_attention", "sk=512") is None
    # runs accumulate on refresh
    assert led.record_kernel("cpu", "flash_attention", "sk=512",
                             pallas_us=55.0, xla_us=95.0)["runs"] == 2


def test_ledger_plan_round_trip_preserves_measured(tmp_path):
    led = ledger.Ledger(str(tmp_path / "l.json"))
    key = (2, 1, 1, 3, 1, False)
    led.record_plan("cpu", "params=10", key, measured_ms=1.5,
                    predicted_ms=2.0)
    # a later decision with no measurement must not erase the data
    led.record_plan("cpu", "params=10", key, measured_ms=None,
                    predicted_ms=2.1, source="decision")
    meas = led.plan_measurements("cpu", "params=10")
    assert meas["2/1/1/3/1/0"]["measured_ms"] == 1.5


def test_ledger_corrupt_file_and_entries_recover(tmp_path):
    p = tmp_path / "l.json"
    p.write_text("{ not json")
    led = ledger.Ledger(str(p))
    assert led.lookup_kernel("cpu", "k", "fp") is None      # not fatal
    led.record_kernel("cpu", "k", "fp", pallas_us=1.0, xla_us=2.0)
    assert led.lookup_kernel("cpu", "k", "fp")["win"] == 2.0
    # corrupt ENTRIES inside a valid document are dropped, good ones kept
    doc = json.loads(p.read_text())
    doc["kernels"]["cpu"]["bad"] = "not-a-dict"
    doc["kernels"]["weird"] = 7
    doc["plans"] = {"cpu": {"mfp": {"1/1/1/0/1/0": {"measured_ms": 3.0}}}}
    p.write_text(json.dumps(doc))
    led2 = ledger.Ledger(str(p))
    assert led2.lookup_kernel("cpu", "k", "fp")["win"] == 2.0
    assert led2.plan_measurements("cpu", "mfp")
    # an entry without a usable win ratio cannot decide dispatch
    led2.record_kernel("cpu", "half", "fp", pallas_us=5.0, xla_us=None)
    assert led2.lookup_kernel("cpu", "half", "fp") is None


def test_ledger_ingest_events(tmp_path):
    led = ledger.Ledger(str(tmp_path / "l.json"))
    n = led.ingest_events([
        {"event": "bench.kernel_probe", "kernel": "flash_attention",
         "shape_fp": "sk=512", "chip": "cpu", "pallas_us": 40.0,
         "xla_us": 80.0, "threshold": 512},
        {"event": "plan.auto_tune", "chip": "cpu", "model_fp": "m",
         "plan_key": [2, 1, 1, 0, 1, 0], "measured_ms": 4.2,
         "predicted_ms": 5.0, "plan": "dp2"},
        {"event": "plan.auto_tune", "plan_key": [1, 1, 1, 0, 1, 0],
         "measured_ms": 9.9},                   # no chip/model_fp: skipped
        {"event": "unrelated", "kernel": "x"},
        "not-a-dict",
    ])
    assert n == 2
    assert led.lookup_kernel("cpu", "flash_attention", "sk=512")["win"] == 2.0
    assert led.plan_measurements("cpu", "m")["2/1/1/0/1/0"][
        "measured_ms"] == 4.2


# ---------------------------------------------------------------------------
# dispatch policy: ledger verdicts route tiers, observably
# ---------------------------------------------------------------------------


def _sgd_lists(rng):
    gs, ps, ms = (_tensors(rng, [(16, 8), (40,)]) for _ in range(3))
    return [gs, ps, ms]


@pytest.mark.parametrize("pallas_us,xla_us,tier", [
    (100.0, 50.0, "xla"),           # below the win region -> XLA
    (50.0, 100.0, "pallas"),        # measured win -> the kernel
])
def test_dispatch_tier_pinned_via_kind_stats(rng, tmp_ledger, pallas_us,
                                             xla_us, tier):
    from apex_tpu.kernels.multi_tensor import multi_tensor_sgd
    from apex_tpu.observe import registry as obs

    lists = _sgd_lists(rng)
    fp = group_fp("sgd", lists[0])
    chip = ledger.chip_name()
    tmp_ledger.record_kernel(chip, "multi_tensor_sgd", fp,
                             pallas_us=pallas_us, xla_us=xla_us)
    dispatch.reset_decisions()
    kind = f"kernel.multi_tensor_sgd.{tier}"
    other = f"kernel.multi_tensor_sgd.{'pallas' if tier == 'xla' else 'xla'}"
    before = step_cache.kind_stats(kind)["dispatches"]
    before_other = step_cache.kind_stats(other)["dispatches"]
    with force_mode("interpret"):
        out = multi_tensor_sgd(jnp.zeros((), jnp.int32), lists,
                               0.0, 0.9, 0.0, 0.1, False, True, False)
    assert len(out) == 3
    assert step_cache.kind_stats(kind)["dispatches"] == before + 1
    assert step_cache.kind_stats(other)["dispatches"] == before_other
    # the deciding ledger entry is in the observe event log
    evs = [e for e in obs.events("kernels.dispatch")
           if e.get("kernel") == "multi_tensor_sgd"
           and e.get("shape_fp") == fp and e.get("tier") == tier]
    assert evs, "no kernels.dispatch event for the decision"
    assert evs[-1]["source"] == "ledger"
    assert evs[-1]["ledger_entry"]["pallas_us"] == pallas_us


def test_dispatch_defaults_no_mode_is_xla(rng, tmp_ledger):
    """CPU default (no forced mode): every kernel routes to XLA and the
    per-bucket paths run unchanged — the tier-1 invariance guarantee."""
    d = dispatch.decide("multi_tensor_sgd", "op=sgd,n=1,t=1,dtype=float32")
    assert d.tier == "xla" and d.source == "mode"


def test_dispatch_probe_decides_compiled_unmeasured(tmp_ledger):
    """Compiled mode with an empty ledger: the registered threshold
    probe decides (flash: sk below the 512-key prior -> XLA, above ->
    Pallas)."""
    with force_mode("compiled"):
        lo = dispatch.decide(
            "flash_attention",
            dispatch.attention_fp(2, 4, 64, 64, 16, "float32", True))
        hi = dispatch.decide(
            "flash_attention",
            dispatch.attention_fp(2, 4, 1024, 1024, 16, "float32", True))
    assert (lo.tier, lo.source) == ("xla", "probe")
    assert lo.threshold == 512
    assert (hi.tier, hi.source) == ("pallas", "probe")


def test_flash_min_sk_reads_measured_threshold(tmp_ledger, monkeypatch):
    from apex_tpu.kernels import attention as ka
    assert ka.flash_min_sk() == 512                  # frozen prior
    tmp_ledger.record_kernel(
        ledger.chip_name(), "flash_attention",
        dispatch.attention_fp(8, 8, 256, 256, 64, "bfloat16", True),
        pallas_us=40.0, xla_us=60.0)
    assert ka.flash_min_sk() == 256                  # measured win at 256
    monkeypatch.setenv("APEX_TPU_FLASH_MIN_SK", "128")
    assert ka.flash_min_sk() == 128                  # env beats both


def test_kernel_catalog_declares_fallbacks():
    cat = dispatch.catalog()
    for name in ("flash_attention", "multi_tensor_sgd",
                 "multi_tensor_adam", "vocab_chain_loss"):
        assert name in cat, f"{name} not registered"
        assert cat[name].xla_fallback
        assert callable(cat[name].threshold_probe)
    with pytest.raises(ValueError):
        dispatch.register_kernel("bad", xla_fallback="",
                                 threshold_probe=lambda d: (None, False))


# ---------------------------------------------------------------------------
# planner: warm ledger re-prices terms and re-ranks plans
# ---------------------------------------------------------------------------


def _planner_setup(rng):
    import dataclasses as dc

    import apex_tpu.nn as nn
    from apex_tpu.nn import functional as F
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.parallel import auto

    nn.manual_seed(0)
    model = nn.Sequential(nn.Linear(64, 128), nn.ReLU(), nn.Linear(128, 8))
    opt = FusedAdam(list(model.parameters()), lr=1e-2)
    loss = lambda o, t: F.cross_entropy(o, t)
    x = jnp.asarray(rng.standard_normal((64, 64)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 8, (64,)))
    prof = auto.profile_model(model, opt, loss, (x, y))
    # stamp transformer geometry so the attention term prices too
    prof = dc.replace(prof, layers=2, heads=4, hidden=64, seq_len=128)
    return auto, model, opt, loss, (x, y), prof


def test_planner_warm_ledger_cites_measured_terms(rng, tmp_ledger):
    auto, model, opt, loss, batch, prof = _planner_setup(rng)
    chip = ledger.chip_name()
    tmp_ledger.record_kernel(
        chip, "multi_tensor_adam",
        dispatch.multi_tensor_fp("adam", prof.n_params,
                                 len(prof.param_shapes)),
        pallas_us=40.0, xla_us=60.0)
    tmp_ledger.record_kernel(
        chip, "flash_attention",
        dispatch.attention_fp(64, 4, 128, 128, 16, "float32", True),
        pallas_us=85.0, xla_us=136.0)
    rep = auto.plan_training(model, opt, loss, batch, profile=prof)
    text = rep.describe()
    assert "ledger-measured" in text
    assert "flash_attention" in text and "multi_tensor_adam" in text
    assert rep.best.ledger_terms
    # the citation covers both required terms
    joined = " ".join(rep.best.ledger_terms)
    assert joined.startswith("attention")
    assert "optimizer" in joined


def test_planner_cold_ledger_unchanged(rng, tmp_ledger):
    auto, model, opt, loss, batch, prof = _planner_setup(rng)
    rep = auto.plan_training(model, opt, loss, batch, profile=prof)
    assert all(not p.ledger_terms for p in rep.ranked)
    assert all(p.measured_ms is None for p in rep.ranked)


def test_planner_reranks_from_recorded_plan_measurement(rng, tmp_ledger):
    auto, model, opt, loss, batch, prof = _planner_setup(rng)
    rep = auto.plan_training(model, opt, loss, batch, profile=prof)
    assert len(rep.ranked) > 1
    other = rep.ranked[1]
    tmp_ledger.record_plan(
        ledger.chip_name(), auto.model_fp(prof, 64), other.key(),
        measured_ms=1e-3, predicted_ms=other.predicted_ms,
        plan=other.name())
    rep2 = auto.plan_training(model, opt, loss, batch, profile=prof)
    assert rep2.best.key() == other.key()
    assert rep2.best.measured_ms == 1e-3
    assert "measured" in rep2.best.describe()


def test_plan_decision_event_carries_ledger_keys(rng, tmp_ledger):
    from apex_tpu.observe import registry as obs
    from apex_tpu.training import make_train_step

    auto, model, opt, loss, batch, prof = _planner_setup(rng)
    step = make_train_step(model, opt, loss, parallel="auto",
                           example_batch=batch,
                           plan_options={"profile": prof})
    evs = [e for e in obs.events("plan.decision") if e.get("model_fp")]
    assert evs, "plan.decision missing ledger keys"
    ev = evs[-1]
    assert ev["chip"] == ledger.chip_name()
    assert ev["model_fp"] == auto.model_fp(prof, 64)
    # the decision write-through is in the ledger (predicted only)
    assert tmp_ledger.plan_measurements(ev["chip"], ev["model_fp"]) == {}
    doc = json.loads(open(tmp_ledger.path).read())
    assert ev["model_fp"] in doc["plans"][ev["chip"]]
    assert step.plan_report is not None
