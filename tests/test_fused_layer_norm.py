"""FusedLayerNorm vs nn.LayerNorm reference — mirrors
tests/L0/run_fused_layer_norm/test_fused_layer_norm.py (fused == unfused
numerics, fwd and bwd, affine and plain, half inputs), plus the
pallas-interpret vs jnp-fallback cross-build oracle (tests/L1 analogue).
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import apex_tpu.nn as nn
from apex_tpu.nn import functional as F
from apex_tpu.normalization import (FusedLayerNorm, fused_layer_norm,
                                    fused_layer_norm_affine)
from apex_tpu.ops.pallas import force_mode


def _ref_ln(x, shape, w=None, b=None, eps=1e-5):
    return F.layer_norm(x, shape, w, b, eps)


@pytest.mark.parametrize("shape,norm_shape", [
    ((8, 16, 32), (32,)),
    ((4, 6, 8, 10), (8, 10)),
    ((64, 96), (96,)),
])
def test_forward_matches_reference(rng, shape, norm_shape):
    x = jnp.asarray(rng.standard_normal(shape), jnp.float32)
    n = int(np.prod(norm_shape))
    w = jnp.asarray(rng.standard_normal(norm_shape), jnp.float32)
    b = jnp.asarray(rng.standard_normal(norm_shape), jnp.float32)
    y = fused_layer_norm_affine(x, w, b, norm_shape, 1e-5)
    y_ref = _ref_ln(x, norm_shape, w, b)
    np.testing.assert_allclose(np.asarray(y), np.asarray(y_ref),
                               rtol=1e-5, atol=1e-5)
    y2 = fused_layer_norm(x, norm_shape, 1e-5)
    np.testing.assert_allclose(np.asarray(y2),
                               np.asarray(_ref_ln(x, norm_shape)),
                               rtol=1e-5, atol=1e-5)
    assert n == w.size


def test_backward_matches_autodiff_of_reference(rng):
    x = jnp.asarray(rng.standard_normal((32, 48)), jnp.float32)
    w = jnp.asarray(1 + 0.1 * rng.standard_normal((48,)), jnp.float32)
    b = jnp.asarray(0.1 * rng.standard_normal((48,)), jnp.float32)

    def fused_loss(x, w, b):
        return jnp.sum(fused_layer_norm_affine(x, w, b, (48,), 1e-5) ** 2)

    def ref_loss(x, w, b):
        return jnp.sum(_ref_ln(x, (48,), w, b) ** 2)

    gf = jax.grad(fused_loss, argnums=(0, 1, 2))(x, w, b)
    gr = jax.grad(ref_loss, argnums=(0, 1, 2))(x, w, b)
    for a, r in zip(gf, gr):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                   rtol=1e-4, atol=1e-4)


def test_half_input_fp32_stats(rng):
    # fp32 statistics for half inputs (csrc/layer_norm_cuda.cpp:133,155)
    x = jnp.asarray(rng.standard_normal((16, 64)), jnp.bfloat16)
    w = jnp.ones((64,), jnp.float32)
    b = jnp.zeros((64,), jnp.float32)
    y = fused_layer_norm_affine(x, w, b, (64,), 1e-5)
    assert y.dtype == jnp.bfloat16
    y_ref = _ref_ln(x, (64,), w, b)
    np.testing.assert_allclose(np.asarray(y, np.float32),
                               np.asarray(y_ref, np.float32),
                               rtol=2e-2, atol=2e-2)


def test_pallas_interpret_matches_fallback(rng):
    """Kernel logic vs jnp fallback — the 'extension build vs python build'
    oracle of tests/L1/common/compare.py:34-40."""
    x = jnp.asarray(rng.standard_normal((40, 136)), jnp.float32)  # row pad +
    w = jnp.asarray(1 + 0.1 * rng.standard_normal((136,)), jnp.float32)
    b = jnp.asarray(0.1 * rng.standard_normal((136,)), jnp.float32)

    def loss(x, w, b):
        return jnp.sum(jnp.sin(fused_layer_norm_affine(x, w, b, (136,))))

    with force_mode("off"):
        y0 = fused_layer_norm_affine(x, w, b, (136,))
        g0 = jax.grad(loss, argnums=(0, 1, 2))(x, w, b)
    with force_mode("interpret"):
        y1 = fused_layer_norm_affine(x, w, b, (136,))
        g1 = jax.grad(loss, argnums=(0, 1, 2))(x, w, b)
    np.testing.assert_allclose(np.asarray(y0), np.asarray(y1),
                               rtol=1e-5, atol=1e-6)
    for a, r in zip(g1, g0):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                   rtol=1e-4, atol=1e-5)


def test_module_trains(rng):
    nn.manual_seed(0)
    m = FusedLayerNorm(24)
    x = jnp.asarray(rng.standard_normal((8, 24)), jnp.float32)
    y = m(x).value
    assert y.shape == (8, 24)
    # normalized output: ~zero mean, ~unit variance per row
    np.testing.assert_allclose(np.asarray(jnp.mean(y, axis=1)), 0, atol=1e-5)
    np.testing.assert_allclose(np.asarray(jnp.var(y, axis=1)), 1, atol=1e-3)
