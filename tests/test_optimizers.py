"""Fused optimizer tests vs torch.optim CPU oracles — the reference's own
strategy (tests/L0/run_optimizers/test_adam.py compares FusedAdam vs
torch.optim.Adam).  LAMB/NovoGrad use independent numpy oracles since torch
has no reference implementation."""
import jax.numpy as jnp
import numpy as np
import pytest
import torch

from apex_tpu.nn import Parameter
from apex_tpu.optimizers import FusedAdam, FusedLAMB, FusedNovoGrad, FusedSGD
from apex_tpu.parallel import LARC

SHAPES = [(7,), (31, 13), (2, 3, 5)]


def _make_pair(rng, shapes=SHAPES):
    """Matched (apex_tpu params, torch params) with identical data+grads."""
    ours, theirs = [], []
    for s in shapes:
        w = rng.standard_normal(s).astype(np.float32)
        g = rng.standard_normal(s).astype(np.float32)
        p = Parameter(jnp.asarray(w))
        p.grad = jnp.asarray(g)
        ours.append(p)
        tp = torch.nn.Parameter(torch.tensor(w))
        tp.grad = torch.tensor(g)
        theirs.append(tp)
    return ours, theirs


def _step_both(opt, topt, ours, theirs, rng, n=5):
    for _ in range(n):
        opt.step()
        topt.step()
        for p, tp in zip(ours, theirs):
            g = rng.standard_normal(p.shape).astype(np.float32)
            p.grad = jnp.asarray(g)
            tp.grad = torch.tensor(g)


def _assert_close(ours, theirs, rtol=2e-5, atol=2e-6):
    for p, tp in zip(ours, theirs):
        np.testing.assert_allclose(np.asarray(p.data),
                                   tp.detach().numpy(), rtol=rtol, atol=atol)


@pytest.mark.parametrize("wd", [0.0, 0.1])
def test_fused_adam_matches_torch_adamw(rng, wd):
    ours, theirs = _make_pair(rng)
    opt = FusedAdam(ours, lr=1e-2, weight_decay=wd, adam_w_mode=True)
    topt = torch.optim.AdamW(theirs, lr=1e-2, weight_decay=wd)
    _step_both(opt, topt, ours, theirs, rng)
    _assert_close(ours, theirs)


@pytest.mark.parametrize("wd", [0.0, 0.1])
def test_fused_adam_l2_matches_torch_adam(rng, wd):
    ours, theirs = _make_pair(rng)
    opt = FusedAdam(ours, lr=1e-2, weight_decay=wd, adam_w_mode=False)
    topt = torch.optim.Adam(theirs, lr=1e-2, weight_decay=wd)
    _step_both(opt, topt, ours, theirs, rng)
    _assert_close(ours, theirs)


@pytest.mark.parametrize("momentum,nesterov,wd", [
    (0.0, False, 0.0), (0.9, False, 0.0), (0.9, True, 0.0), (0.9, False, 1e-4)])
def test_fused_sgd_matches_torch(rng, momentum, nesterov, wd):
    ours, theirs = _make_pair(rng)
    opt = FusedSGD(ours, lr=0.1, momentum=momentum, nesterov=nesterov,
                   weight_decay=wd)
    topt = torch.optim.SGD(theirs, lr=0.1, momentum=momentum,
                           nesterov=nesterov, weight_decay=wd)
    _step_both(opt, topt, ours, theirs, rng)
    _assert_close(ours, theirs)


def _numpy_lamb_reference(ws, gs, n_steps, rng, lr=1e-2, b1=0.9, b2=0.999,
                          eps=1e-6, wd=0.01, max_grad_norm=1.0):
    """Independent LAMB oracle following the published algorithm + the
    reference's clipping/trust-ratio conventions."""
    ms = [np.zeros_like(w) for w in ws]
    vs = [np.zeros_like(w) for w in ws]
    ws = [w.copy() for w in ws]
    gs = [g.copy() for g in gs]
    rngs = np.random.default_rng(999)
    for step in range(1, n_steps + 1):
        gnorm = np.sqrt(sum((g ** 2).sum() for g in gs))
        clip = gnorm / max_grad_norm if gnorm > max_grad_norm else 1.0
        bc1 = 1 - b1 ** step
        bc2 = 1 - b2 ** step
        for i in range(len(ws)):
            g = gs[i] / clip
            ms[i] = b1 * ms[i] + (1 - b1) * g
            vs[i] = b2 * vs[i] + (1 - b2) * g * g
            u = (ms[i] / bc1) / (np.sqrt(vs[i] / bc2) + eps) + wd * ws[i]
            pn = np.linalg.norm(ws[i].ravel())
            un = np.linalg.norm(u.ravel())
            ratio = lr * pn / un if (pn != 0 and un != 0) else lr
            ws[i] = ws[i] - ratio * u
        gs = [rngs.standard_normal(w.shape).astype(np.float32) for w in ws]
    return ws


def test_fused_lamb_matches_numpy_oracle(rng):
    ws = [rng.standard_normal(s).astype(np.float32) for s in SHAPES]
    gs = [rng.standard_normal(s).astype(np.float32) for s in SHAPES]
    params = []
    for w, g in zip(ws, gs):
        p = Parameter(jnp.asarray(w))
        p.grad = jnp.asarray(g)
        params.append(p)
    opt = FusedLAMB(params, lr=1e-2, weight_decay=0.01, max_grad_norm=1.0)
    rngs = np.random.default_rng(999)
    n = 4
    for _ in range(n):
        opt.step()
        for p in params:
            p.grad = jnp.asarray(
                rngs.standard_normal(p.shape).astype(np.float32))
    ref = _numpy_lamb_reference(ws, gs, n, rng)
    for p, w in zip(params, ref):
        np.testing.assert_allclose(np.asarray(p.data), w, rtol=1e-4, atol=1e-5)


def _numpy_novograd_reference(ws, gs, n_steps, lr=1e-2, b1=0.95, b2=0.98,
                              eps=1e-8, wd=0.01, moment_mode=1):
    ms = [np.zeros_like(w) for w in ws]
    gns = [np.sqrt((g.astype(np.float64) ** 2).sum()) for g in gs]  # init
    ws = [w.copy() for w in ws]
    gs = [g.copy() for g in gs]
    rngs = np.random.default_rng(999)
    for step in range(1, n_steps + 1):
        bc1 = 1 - b1 ** step
        bc2 = np.sqrt(1 - b2 ** step)
        for i in range(len(ws)):
            g = gs[i]
            gns[i] = np.sqrt(b2 * gns[i] ** 2 + (1 - b2) * (g ** 2).sum())
            denom = gns[i] / bc2 + eps
            if moment_mode == 0:
                gp = g / denom + wd * ws[i]
                ms[i] = b1 * ms[i] + (1 - b1) * gp
                ws[i] = ws[i] - lr * (ms[i] / bc1)
            else:
                ms[i] = b1 * ms[i] + (1 - b1) * g
                ws[i] = ws[i] - lr * ((ms[i] / bc1) / denom + wd * ws[i])
        gs = [rngs.standard_normal(w.shape).astype(np.float32) for w in ws]
    return ws


@pytest.mark.parametrize("reg_inside_moment", [False, True])
def test_fused_novograd_matches_numpy_oracle(rng, reg_inside_moment):
    ws = [rng.standard_normal(s).astype(np.float32) for s in SHAPES]
    gs = [rng.standard_normal(s).astype(np.float32) for s in SHAPES]
    params = []
    for w, g in zip(ws, gs):
        p = Parameter(jnp.asarray(w))
        p.grad = jnp.asarray(g)
        params.append(p)
    opt = FusedNovoGrad(params, lr=1e-2, weight_decay=0.01,
                        reg_inside_moment=reg_inside_moment)
    rngs = np.random.default_rng(999)
    n = 4
    for _ in range(n):
        opt.step()
        for p in params:
            p.grad = jnp.asarray(
                rngs.standard_normal(p.shape).astype(np.float32))
    ref = _numpy_novograd_reference(
        ws, gs, n, moment_mode=0 if reg_inside_moment else 1)
    for p, w in zip(params, ref):
        np.testing.assert_allclose(np.asarray(p.data), w, rtol=1e-4, atol=1e-5)


def test_mixed_dtype_buckets(rng):
    p32 = Parameter(jnp.asarray(rng.standard_normal((8,)), jnp.float32))
    p16 = Parameter(jnp.asarray(rng.standard_normal((8,)), jnp.bfloat16))
    p32.grad = jnp.asarray(rng.standard_normal((8,)), jnp.float32)
    p16.grad = jnp.asarray(rng.standard_normal((8,)), jnp.bfloat16)
    opt = FusedAdam([p32, p16], lr=1e-2)
    opt.step()
    assert p32.dtype == jnp.float32 and p16.dtype == jnp.bfloat16


def test_zero_grad_set_to_none(rng):
    ours, _ = _make_pair(rng)
    opt = FusedAdam(ours, lr=1e-2)  # set_grad_none default True
    opt.zero_grad()
    assert all(p.grad is None for p in ours)


def test_state_dict_roundtrip(rng):
    ours, _ = _make_pair(rng)
    opt = FusedAdam(ours, lr=1e-2)
    opt.step()
    sd = opt.state_dict()

    ours2 = [Parameter(p.data) for p in ours]
    opt2 = FusedAdam(ours2, lr=5e-4)
    opt2.load_state_dict(sd)
    assert opt2.param_groups[0]["lr"] == 1e-2
    for p, p2 in zip(ours, ours2):
        np.testing.assert_allclose(
            np.asarray(opt.state[p]["exp_avg"]),
            np.asarray(opt2.state[p2]["exp_avg"]))


def test_duplicate_param_rejected(rng):
    ours, _ = _make_pair(rng)
    opt = FusedAdam(ours, lr=1e-2)
    with pytest.raises(ValueError):
        opt.add_param_group({"params": [ours[0]]})


def test_larc_clips_effective_lr(rng):
    # huge grads -> adaptive_lr tiny -> update much smaller than plain SGD
    w = np.ones((16,), np.float32)
    p = Parameter(jnp.asarray(w))
    p.grad = jnp.asarray(1000.0 * np.ones((16,), np.float32))
    base = FusedSGD([p], lr=0.1)
    opt = LARC(base, trust_coefficient=0.001, clip=True)
    opt.step()
    delta = np.abs(np.asarray(p.data) - w).max()
    assert delta < 0.1 * 1000.0  # plain SGD would move 100.0
    assert delta > 0


def test_larc_delegates_api(rng):
    ours, _ = _make_pair(rng)
    base = FusedSGD(ours, lr=0.1, weight_decay=0.01)
    opt = LARC(base)
    assert opt.param_groups is base.param_groups
    opt.zero_grad(set_to_none=True)
    assert all(p.grad is None for p in ours)
    # weight decay restored after step
    for p in ours:
        p.grad = jnp.zeros(p.shape, jnp.float32)
    opt.step()
    assert base.param_groups[0]["weight_decay"] == 0.01
