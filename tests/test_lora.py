"""LoRA fine-tuning (reparameterization/lora.py): exact base-model
start (B=0), gradient flow to the factors only, frozen w0 through the
fused step, merge-for-inference parity, conv adaptation, and the HF
fine-tune flow on a converted checkpoint."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import apex_tpu.nn as nn
from apex_tpu.nn.modules import Ctx
from apex_tpu.optimizers import FusedAdam
from apex_tpu.reparameterization import (LoRA, apply_lora,
                                         lora_parameters,
                                         remove_reparameterization)
from apex_tpu.training import make_train_step


def _mlp(seed=0):
    nn.manual_seed(seed)
    return nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 8))


def test_lora_starts_at_base_model(rng):
    m = _mlp()
    x = jnp.asarray(rng.standard_normal((4, 16)), jnp.float32)
    base = np.asarray(m(x).value)
    apply_lora(m, r=4)
    got = np.asarray(m(x).value)
    np.testing.assert_allclose(got, base, rtol=1e-6, atol=1e-6)
    # factors exist for every >1-d weight; biases untouched
    names = [n for n, _ in m.named_parameters()]
    assert any(n.endswith("weight_lora_a") for n in names)
    assert any(n.endswith("weight_w0") for n in names)
    assert not any(n.endswith("bias_lora_a") for n in names)


def test_lora_trains_factors_only_through_fused_step(rng):
    m = _mlp(seed=1)
    apply_lora(m, r=4)
    w0_before = {
        n: np.asarray(p.data) for n, p in m.named_parameters()
        if n.endswith("_w0")}
    opt = FusedAdam(lora_parameters(m), lr=5e-2)
    step = make_train_step(
        m, opt, lambda out, y: jnp.mean((out - y) ** 2),
        half_dtype=None, loss_scale=1.0)
    x = jnp.asarray(rng.standard_normal((32, 16)), jnp.float32)
    y = jnp.asarray(rng.standard_normal((32, 8)), jnp.float32)
    l0 = float(step(x, y))
    for _ in range(25):
        l = float(step(x, y))
    assert np.isfinite(l) and l < 0.7 * l0
    step.sync_to_objects()
    for n, p in m.named_parameters():
        if n.endswith("_w0"):
            np.testing.assert_array_equal(np.asarray(p.data),
                                          w0_before[n]), n
        if n.endswith("_lora_b"):
            assert float(jnp.sum(jnp.abs(p.data))) > 0, \
                f"{n} never trained"


def test_lora_merge_matches_adapted_forward(rng):
    m = _mlp(seed=2)
    apply_lora(m, "0.weight", r=2)
    # give the factors nonzero values so the merge is nontrivial
    for n, p in m.named_parameters():
        if n.endswith("_lora_b"):
            p.data = jnp.ones_like(p.data) * 0.1
    x = jnp.asarray(rng.standard_normal((4, 16)), jnp.float32)
    adapted = np.asarray(m(x).value)
    remove_reparameterization(m, LoRA, remove_all=True)
    merged = np.asarray(m(x).value)
    np.testing.assert_allclose(merged, adapted, rtol=1e-5, atol=1e-6)
    names = [n for n, _ in m.named_parameters()]
    assert not any("lora" in n or n.endswith("_w0") for n in names)


def test_lora_on_conv(rng):
    nn.manual_seed(3)
    conv = nn.Conv2d(3, 8, 3, padding=1)
    x = jnp.asarray(rng.standard_normal((2, 3, 8, 8)), jnp.float32)
    base = np.asarray(conv(x).value)
    apply_lora(conv, "weight", r=2)
    np.testing.assert_allclose(np.asarray(conv(x).value), base,
                               rtol=1e-6, atol=1e-6)
    # factor shapes: B (out, r), A (r, in*k*k)
    assert conv.weight_lora_b.shape == (8, 2)
    assert conv.weight_lora_a.shape == (2, 3 * 3 * 3)


def test_lora_rank_validation():
    m = _mlp(seed=4)
    with pytest.raises(ValueError, match="rank"):
        apply_lora(m, "0.weight", r=0)
    with pytest.raises(ValueError, match="exceeds"):
        apply_lora(m, "2.weight", r=64)   # Linear(32, 8): min dim 8
    # a rejected apply must leave the model INTACT (the registry is
    # only mutated after reparameterize succeeds)
    names = [n for n, _ in m.named_parameters()]
    assert "2.weight" in names and not any("lora" in n for n in names)
    np.isfinite(np.asarray(m(jnp.ones((1, 16))).value)).all()


def test_lora_bulk_sweep_skips_small_weights(rng):
    """The '' (everything) sweep skips weights too small for the rank
    instead of aborting half-adapted — the strict=False contract."""
    nn.manual_seed(5)
    m = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 2))
    apply_lora(m, r=8)                    # Linear(32, 2): min dim 2 < 8
    names = [n for n, _ in m.named_parameters()]
    assert any(n.startswith("0.weight_lora") for n in names)
    assert "2.weight" in names            # skipped, intact
    assert not any(n.startswith("2.weight_lora") for n in names)
    x = jnp.asarray(rng.standard_normal((3, 16)), jnp.float32)
    assert np.isfinite(np.asarray(m(x).value)).all()


def test_lora_fine_tunes_hf_gpt2(rng):
    """The migration flow: convert an HF GPT-2 checkpoint, LoRA the
    attention projections, fine-tune — base weights bit-frozen, loss
    decreases, and the merged model serves without LoRA machinery."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    from apex_tpu.models import gpt2_from_hf
    from apex_tpu.nn import functional as F

    cfg = transformers.GPT2Config(
        vocab_size=97, n_embd=32, n_layer=2, n_head=4, n_positions=32,
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0)
    torch.manual_seed(0)
    model = gpt2_from_hf(transformers.GPT2LMHeadModel(cfg))
    model.train()
    for blk in model.blocks:
        apply_lora(blk, "attn.in_proj_weight", r=4)
    opt = FusedAdam(lora_parameters(model), lr=1e-2)

    def lm_loss(logits, ids):
        return jnp.mean(F.cross_entropy(
            logits[:, :-1].reshape((-1, 97)), ids[:, 1:].reshape((-1,))))

    step = make_train_step(model, opt, lm_loss, half_dtype=None,
                           loss_scale=1.0)
    ids = jnp.asarray(rng.integers(0, 97, (4, 16)))
    l0 = float(step(ids, ids))
    for _ in range(20):
        l = float(step(ids, ids))
    assert np.isfinite(l) and l < l0
    step.sync_to_objects()
    remove_reparameterization(model, LoRA, remove_all=True)
    out = model(ids)
    assert np.isfinite(np.asarray(out.value)).all()


def test_lora_train_sync_generate_flow(rng):
    """Regression: generate() before AND after a LoRA merge.  The jit
    cache used to key only on shapes/config, so the post-merge call hit
    the pre-merge compiled run, whose env zipped the OLD parameter list
    against the new values — reading the wrong weights (a trace-time
    shape error here; silently wrong logits in same-shape cases).  The
    cache now keys on the parameter-object tuple."""
    from apex_tpu.models import generate
    from apex_tpu.models.llama import llama_tiny
    from apex_tpu.nn import functional as F

    nn.manual_seed(0)
    model = llama_tiny()
    for blk in model.blocks:
        apply_lora(blk, "q_proj.weight", r=4)
        apply_lora(blk, "v_proj.weight", r=4)
    opt = FusedAdam(lora_parameters(model), lr=5e-3)
    step = make_train_step(
        model, opt,
        lambda lg, t: jnp.mean(F.cross_entropy(
            lg[:, :-1].reshape((-1, 1000)), t[:, 1:].reshape((-1,)))),
        half_dtype=jnp.bfloat16)
    ids = jnp.asarray(rng.integers(0, 1000, (8, 24)))
    l0 = float(step(ids, ids))
    for _ in range(10):
        l = float(step(ids, ids))
    assert np.isfinite(l) and l < l0
    step.sync_to_objects()
    model.eval()
    pre = generate(model, ids[:1, :8], 6)
    remove_reparameterization(model, LoRA, remove_all=True)
    post = generate(model, ids[:1, :8], 6)
    np.testing.assert_array_equal(np.asarray(pre), np.asarray(post))


def test_lora_refuses_quantized_weight(rng):
    """Adapting an int8 weight would train factors against rounding
    noise and break compute_weight's dtype math — refuse loudly, and
    (non-strict sweep) leave the model intact."""
    from apex_tpu.inference import quantize_int8
    from apex_tpu.models.llama import llama_tiny

    nn.manual_seed(0)
    model = llama_tiny()
    quantize_int8(model, min_size=1)
    with pytest.raises(ValueError, match="quantized"):
        apply_lora(model.blocks[0], "q_proj.weight", r=4)
    # bulk sweep: every matrix is quantized -> everything skipped
    apply_lora(model, r=4)
    assert not any("lora" in n for n, _ in model.named_parameters())
    # the guard is generic (shared eligibility): WeightNorm refuses too
    from apex_tpu.reparameterization import apply_weight_norm
    with pytest.raises(ValueError, match="quantized"):
        apply_weight_norm(model.blocks[0], "q_proj.weight")
