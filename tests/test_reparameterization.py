"""WeightNorm / Reparameterization vs torch.nn.utils.weight_norm (the
reference has no tests for this package; torch's implementation is the
behavioral contract both share)."""
import jax.numpy as jnp
import numpy as np
import torch

import apex_tpu.nn as nn
from apex_tpu.reparameterization import (
    Reparameterization, WeightNorm, apply_weight_norm, remove_weight_norm)


def _torch_twin(lin):
    t = torch.nn.Linear(lin.in_features, lin.out_features)
    with torch.no_grad():
        t.weight.copy_(torch.from_numpy(np.asarray(lin.weight.data)))
        t.bias.copy_(torch.from_numpy(np.asarray(lin.bias.data)))
    return t


def test_weight_norm_matches_torch(rng):
    lin = nn.Linear(6, 4)
    t_lin = _torch_twin(lin)
    apply_weight_norm(lin, name="weight", dim=0)
    t_lin = torch.nn.utils.weight_norm(t_lin, name="weight", dim=0)

    assert lin.weight_g.shape == tuple(t_lin.weight_g.shape)
    assert lin.weight_v.shape == tuple(t_lin.weight_v.shape)
    np.testing.assert_allclose(np.asarray(lin.weight_g.data),
                               t_lin.weight_g.detach().numpy(), atol=1e-6)

    x = rng.standard_normal((3, 6)).astype(np.float32)
    out = lin(jnp.asarray(x))
    t_out = t_lin(torch.from_numpy(x))
    np.testing.assert_allclose(np.asarray(out.value),
                               t_out.detach().numpy(), atol=1e-5)


def test_weight_norm_grads_match_torch(rng):
    lin = nn.Linear(5, 3)
    t_lin = _torch_twin(lin)
    apply_weight_norm(lin, name="weight", dim=0)
    t_lin = torch.nn.utils.weight_norm(t_lin, name="weight", dim=0)

    x = rng.standard_normal((4, 5)).astype(np.float32)
    out = lin(jnp.asarray(x))
    loss = (out * out).mean()
    loss.backward()

    t_out = t_lin(torch.from_numpy(x))
    t_loss = (t_out * t_out).mean()
    t_loss.backward()

    np.testing.assert_allclose(np.asarray(lin.weight_g.grad),
                               t_lin.weight_g.grad.numpy(), atol=1e-5)
    np.testing.assert_allclose(np.asarray(lin.weight_v.grad),
                               t_lin.weight_v.grad.numpy(), atol=1e-5)
    # the replaced weight itself is out of the parameter list
    names = [n for n, _ in lin.named_parameters()]
    assert "weight" not in names
    assert set(names) == {"weight_g", "weight_v", "bias"}


def test_weight_norm_training_updates_weight(rng):
    lin = nn.Linear(4, 4)
    apply_weight_norm(lin, name="weight", dim=0)
    from apex_tpu.optimizers import FusedSGD
    opt = FusedSGD(list(lin.parameters()), lr=0.5)
    x = jnp.asarray(rng.standard_normal((8, 4)).astype(np.float32))
    out0 = np.asarray(lin(x).value)
    loss = (lin(x) * lin(x)).mean()
    loss.backward()
    opt.step()
    out1 = np.asarray(lin(x).value)
    assert not np.allclose(out0, out1)


def test_remove_weight_norm_bakes_weight(rng):
    lin = nn.Linear(6, 4)
    apply_weight_norm(lin, name="weight", dim=0)
    x = jnp.asarray(rng.standard_normal((3, 6)).astype(np.float32))
    before = np.asarray(lin(x).value)
    remove_weight_norm(lin, name="weight")
    names = [n for n, _ in lin.named_parameters()]
    assert "weight" in names and "weight_g" not in names
    after = np.asarray(lin(x).value)
    np.testing.assert_allclose(before, after, atol=1e-6)


def test_apply_to_whole_model_skips_1d_and_embeddings(rng):
    model = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    emb = nn.Embedding(10, 4)
    root = nn.Sequential(emb, model)
    apply_weight_norm(root)
    names = [n for n, _ in root.named_parameters()]
    # embedding weight untouched, linear weights reparameterized, biases kept
    assert any(n.endswith("weight_g") for n in names)
    assert not any("0.weight_g" == n for n in names)  # embedding is '0'
    assert "0.weight" in names
    assert all(not n.endswith("bias_g") for n in names)


def test_dim_none_whole_tensor_norm(rng):
    lin = nn.Linear(6, 4)
    t_lin = _torch_twin(lin)
    apply_weight_norm(lin, name="weight", dim=None)
    t_lin = torch.nn.utils.weight_norm(t_lin, name="weight", dim=None)
    x = rng.standard_normal((3, 6)).astype(np.float32)
    np.testing.assert_allclose(np.asarray(lin(jnp.asarray(x)).value),
                               t_lin(torch.from_numpy(x)).detach().numpy(),
                               atol=1e-5)


def test_remove_with_hook_child_false_dotted_name(rng):
    from apex_tpu.reparameterization import (
        apply_reparameterization, remove_reparameterization)
    model = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    apply_reparameterization(model, WeightNorm, name="2.weight", dim=0,
                             hook_child=False)
    x = jnp.asarray(rng.standard_normal((3, 4)).astype(np.float32))
    before = np.asarray(model(x).value)
    remove_reparameterization(model, WeightNorm, remove_all=True)
    names = [n for n, _ in model.named_parameters()]
    assert "2.weight" in names and "2.weight_g" not in names
    np.testing.assert_allclose(before, np.asarray(model(x).value), atol=1e-6)


def test_tensor_row_unpacking_still_works(rng):
    # regression: defining Tensor.__iter__ must not break row iteration
    lin = nn.Linear(3, 3)
    a, b = lin(jnp.ones((2, 3)))
    assert a.shape == (3,) and b.shape == (3,)
    loss = (a * a).sum() + (b * b).sum()
    loss.backward()
    assert lin.weight.grad is not None


def test_dotted_name_application(rng):
    model = nn.Sequential(nn.Linear(4, 8), nn.ReLU(), nn.Linear(8, 2))
    apply_weight_norm(model, name="2.weight", dim=0)
    names = [n for n, _ in model.named_parameters()]
    assert "2.weight_g" in names and "0.weight" in names


def test_explicit_bad_name_raises():
    import pytest
    from apex_tpu.reparameterization import apply_weight_norm
    import apex_tpu.nn as nn
    nn.manual_seed(0)
    lin = nn.Linear(4, 4)
    with pytest.raises(AttributeError):
        apply_weight_norm(lin, name="wieght")
    apply_weight_norm(lin, name="weight")
    with pytest.raises(ValueError):
        apply_weight_norm(lin, name="weight")  # already reparameterized
    with pytest.raises(ValueError):
        apply_weight_norm(lin, name="bias")  # 1-d


def test_weight_norm_through_fused_step(rng):
    """A weight-normed model trains through make_train_step: the derived
    weight recomputes from (g, v) inside the compiled step and the
    normalization invariant holds after updates."""
    import jax.numpy as jnp
    from apex_tpu.nn import functional as F
    from apex_tpu.optimizers import FusedSGD
    from apex_tpu.reparameterization import apply_weight_norm
    from apex_tpu.training import make_train_step

    nn.manual_seed(0)
    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    apply_weight_norm(model[0], "weight")
    opt = FusedSGD(list(model.parameters()), lr=0.1, momentum=0.9)
    step = make_train_step(model, opt,
                           lambda o, t: F.cross_entropy(o, t),
                           half_dtype=None, loss_scale=1.0)
    x = jnp.asarray(rng.standard_normal((8, 8)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 4, (8,)))
    l0 = float(step(x, y))
    for _ in range(10):
        l = float(step(x, y))
    assert np.isfinite(l) and l < l0
    step.sync_to_objects()
    # derived weight == g * v / ||v|| row-wise after training
    import numpy as np_
    g = np_.asarray(model[0].weight_g.data)
    v = np_.asarray(model[0].weight_v.data)
    w = model[0].weight
    from apex_tpu.nn.modules import Ctx
    w_val = np_.asarray(Ctx().value(w))
    norm = np_.linalg.norm(v.reshape(v.shape[0], -1), axis=1,
                           keepdims=True)
    want = (g.reshape(v.shape[0], -1) / norm) * v.reshape(v.shape[0], -1)
    np_.testing.assert_allclose(w_val.reshape(v.shape[0], -1), want,
                                rtol=1e-5, atol=1e-6)
