"""Legacy fp16_utils surface — mirrors tests/L0/run_fp16util (network
conversion, master/model param list round-trips) and the FP16_Optimizer
manual loop."""
import jax.numpy as jnp
import numpy as np

import apex_tpu.nn as nn
from apex_tpu.fp16_utils import (
    FP16_Optimizer, DynamicLossScaler, convert_network,
    master_params_to_model_params, model_grads_to_master_grads,
    network_to_half, prep_param_lists)
from apex_tpu.optimizers import FusedSGD


def _model():
    nn.manual_seed(0)
    return nn.Sequential(
        nn.Linear(8, 16), nn.BatchNorm1d(16), nn.ReLU(), nn.Linear(16, 4))


def test_network_to_half_keeps_bn_fp32():
    m = network_to_half(_model())
    dtypes = {name: p.dtype for name, p in m.named_parameters()}
    assert dtypes["0.weight"] == jnp.bfloat16
    assert dtypes["1.weight"] == jnp.float32  # batchnorm stays fp32
    assert dtypes["3.weight"] == jnp.bfloat16
    # running stats stay fp32 too
    assert m[1].running_mean.dtype == jnp.float32


def test_convert_network_dtype():
    m = convert_network(_model(), jnp.float16)
    assert m[0].weight.dtype == jnp.float16
    assert m[1].weight.dtype == jnp.float32


def test_prep_param_lists_roundtrip(rng):
    m = network_to_half(_model())
    model_params, master_params = prep_param_lists(m)
    assert all(mp.dtype == jnp.float32 for mp in master_params)
    for p in model_params:
        p.grad = jnp.ones(p.shape, p.dtype)
    model_grads_to_master_grads(model_params, master_params)
    assert all(mp.grad.dtype == jnp.float32 for mp in master_params)
    for mp in master_params:
        mp.data = mp.data * 0.5
    master_params_to_model_params(model_params, master_params)
    for p, mp in zip(model_params, master_params):
        np.testing.assert_allclose(
            np.asarray(p.data, np.float32),
            np.asarray(mp.data.astype(p.dtype), np.float32))


def test_prep_param_lists_flat_master(rng):
    m = network_to_half(_model())
    model_params, master = prep_param_lists(m, flat_master=True)
    assert len(master) == 1
    total = sum(p.numel() for p in model_params)
    assert master[0].numel() == total
    master[0].data = master[0].data + 1.0
    master_params_to_model_params(model_params, master, flat_master=True)


def test_fp16_optimizer_step_and_overflow():
    m = network_to_half(_model())
    opt = FP16_Optimizer(FusedSGD(list(m.parameters()), lr=0.1),
                         dynamic_loss_scale=True,
                         dynamic_loss_args={"init_scale": 2 ** 8},
                         verbose=False)
    params = list(m.parameters())
    before = [np.asarray(p.data, np.float32).copy() for p in params]
    # healthy grads → step moves params
    for p in params:
        p.grad = jnp.ones(p.shape, p.dtype) * float(opt.loss_scale)
    opt.update_master_grads()
    assert not opt.overflow
    norm = opt.clip_master_grads(1e9)
    assert norm > 0
    opt.step()
    after = [np.asarray(p.data, np.float32) for p in params]
    assert any(not np.allclose(b, a) for b, a in zip(before, after))
    # inf grads → overflow, skip, scale halves
    scale0 = opt.loss_scale
    for p in params:
        p.grad = jnp.full(p.shape, jnp.inf, p.dtype)
    opt.update_master_grads()
    assert opt.overflow
    assert opt.clip_master_grads(1.0) == -1
    snap = [np.asarray(p.data, np.float32).copy() for p in params]
    opt.step()  # skipped
    for s, p in zip(snap, params):
        np.testing.assert_array_equal(s, np.asarray(p.data, np.float32))
    assert opt.loss_scale == scale0 / 2


def test_dynamic_scaler_growth():
    s = DynamicLossScaler(init_scale=4.0, scale_window=2)
    s.update_scale(False)
    s.update_scale(False)  # window hit → doubles
    assert s.loss_scale >= 8.0
    s.update_scale(True)
    assert s.loss_scale == 4.0


def test_fp16model_wraps_batchnorm_safely(rng):
    from apex_tpu.fp16_utils import FP16Model

    nn.manual_seed(2)
    net = nn.Sequential(nn.Conv2d(3, 8, 3, padding=1), nn.BatchNorm2d(8),
                        nn.ReLU(), nn.Flatten(), nn.Linear(8 * 16, 4))
    wrapped = FP16Model(net)
    # conv/linear half, BN stays fp32 (reference fp16util.py:73-84)
    assert net[0].weight.dtype == jnp.bfloat16
    assert net[4].weight.dtype == jnp.bfloat16
    assert net[1].weight.dtype == jnp.float32
    x = jnp.asarray(rng.standard_normal((2, 3, 4, 4)), jnp.float32)
    out = wrapped(x)
    assert out.dtype == jnp.bfloat16
    assert np.isfinite(np.asarray(out, np.float32)).all()
