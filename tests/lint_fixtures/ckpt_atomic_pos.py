"""CKPT-ATOMIC positive fixture: raw checkpoint writes that bypass the
atomic tmp+fsync+rename path (every call below must be flagged)."""
import pickle


def save_model_raw(state, path="model_ckpt.pkl"):
    with open(path, "wb") as f:                 # flagged: ckpt path, "wb"
        pickle.dump(state, f)                   # flagged: raw pickle.dump


def save_with_imported_dump(state, step):
    from pickle import dump
    with open(f"/tmp/run/checkpoint_{step:08d}.bin", "wb") as f:  # flagged
        dump(state, f)                          # flagged: aliased dump


def save_mode_kwarg(state):
    f = open("latest.ckpt.pkl", mode="w+b")     # flagged: mode= spelling
    try:
        pickle.dump(state, f)                   # flagged
    finally:
        f.close()
