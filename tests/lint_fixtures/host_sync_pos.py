"""HOST-SYNC positive: device round-trips inside jitted code."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def bad_norm_step(params, grads):
    # BAD: .item() blocks on a device fetch every step
    gnorm = jnp.sqrt(sum((g * g).sum() for g in grads)).item()
    return [p - 0.1 * g / gnorm for p, g in zip(params, grads)]


def bad_overflow_step(params, grads, flag):
    # BAD: Python branching on a traced value
    if flag:
        return params
    return [p - 0.1 * g for p, g in zip(params, grads)]


def bad_fetch_step(state, batch):
    # BAD: np.asarray of a traced value materializes on host
    host = np.asarray(batch)
    # BAD: device_get inside the compiled step
    stats = jax.device_get(state)
    return state, host, stats


def bad_scale_step(params, scale):
    # BAD: float() of a traced scalar is a host sync
    s = float(scale)
    return [p * s for p in params]


train = jax.jit(bad_overflow_step)
fetch = jax.jit(bad_fetch_step)
scaled = jax.jit(bad_scale_step)


def _decide(x):
    # BAD (interprocedural): x arrives traced from the jitted caller —
    # the branch is a device fetch even though this helper never
    # mentions jax
    if x > 0:
        return x
    return -x


@jax.jit
def routed_step(v):
    return _decide(v * 2.0)
