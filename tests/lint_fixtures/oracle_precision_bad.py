"""Dynamic-oracle fixture: PRECISION-SINK flags this module statically,
and running it proves the hazard is real — the fp16 reduction saturates
(inf) on values every element of which is comfortably representable."""
import jax
import jax.numpy as jnp


@jax.jit
def window_energy(xs):
    # BAD: the squared-activation energy is summed IN fp16 — the
    # accumulator overflows fp16's 65504 max long before any single
    # element does
    h = xs.astype(jnp.float16)
    return jnp.sum(h * h)
