"""OBS-IN-JIT positive: host-side observe calls inside traced code."""
import jax

from apex_tpu.observe import span, counter, event
from apex_tpu.observe import registry as obs_registry


@jax.jit
def bad_span_step(params, grads):
    # BAD: span reads wall clocks and writes JSONL — traced, it times
    # the trace, not the execution
    with span("update"):
        out = [p - 0.1 * g for p, g in zip(params, grads)]
    return out


def bad_counter_step(params, grads):
    # BAD: registry counters take a lock and mutate host state; traced,
    # the count sticks at its trace-time value
    counter("train.steps").inc()
    event("step", n=len(params))
    return [p - 0.1 * g for p, g in zip(params, grads)]


def bad_registry_step(state, batch):
    # BAD: module-alias spelling of the same hazard
    obs_registry.event("batch", size=batch.shape[0])
    return state


def bad_drain_step(train_step, state):
    # BAD: the drain IS the host fetch the telemetry carry defers
    train_step.drain_telemetry()
    return state


train = jax.jit(bad_counter_step)
stepped = jax.jit(bad_registry_step)
drained = jax.jit(bad_drain_step)
