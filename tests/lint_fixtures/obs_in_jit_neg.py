"""OBS-IN-JIT negative: on-device telemetry accumulates in the carry
inside jit; spans, counters, events and drains live in the eager driver."""
import jax
import jax.numpy as jnp

from apex_tpu.observe import span, counter, event
from apex_tpu.observe import telemetry as obs_telemetry
from apex_tpu.observe.telemetry import init_telemetry


@jax.jit
def clean_step(telem, grads, loss):
    # fine: the telemetry surface is jit-safe by construction — pure jnp
    # accumulation into the donated carry, drained outside the step
    telem = telem if telem is not None else init_telemetry()
    return obs_telemetry.accumulate(
        telem, loss=loss, master_grads=grads,
        flag=jnp.zeros((), jnp.bool_),
        loss_scale=jnp.ones((), jnp.float32))


def eager_train_loop(step, state, batches):
    """Eager driver — spans and counters belong exactly here, outside
    the compiled step."""
    loss = None
    for batch in batches:
        with span("dispatch"):
            state, loss = step(state, batch)
        counter("train.steps").inc()
        step.drain_telemetry()
    event("epoch.done", loss=float(loss))
    return state
