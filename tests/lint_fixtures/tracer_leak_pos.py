"""TRACER-LEAK positive: traced values parked in state that outlives
the trace (module globals, long-lived containers, instance state)."""
import jax

_CACHE = {}
_LAST = []


@jax.jit
def bad_probe_step(params, grads):
    g = grads[0]
    # BAD: traced value keyed into a module-level dict
    _CACHE["last_grad"] = g
    # BAD: traced value appended to a module-level list
    _LAST.append(g * 2.0)
    return [p - 0.1 * gi for p, gi in zip(params, grads)]


_PEAK = None


@jax.jit
def bad_global_step(params):
    global _PEAK
    # BAD: traced value rebinds a module global
    _PEAK = params[0] * params[0]
    return params
