"""SCAN-COLLECTIVE positive: gradient exchange inside the accumulation
scan body — K collectives per window instead of one."""
import jax
from jax import lax


def accum_window(grad_fn, params, micro, axis_name):
    def body(carry, mb):
        g = grad_fn(params, mb)
        # BAD: per-microbatch exchange
        g = lax.psum(g, axis_name)
        return [c + gi for c, gi in zip(carry, g)], None

    acc0 = [0.0 * p for p in params]
    acc, _ = lax.scan(body, acc0, micro)
    return acc


def mean_window(vals, xs, axis_name):
    # BAD: lambda body with a per-step pmean
    out, _ = jax.lax.scan(
        lambda c, x: (c + lax.pmean(x, axis_name), None), vals, xs)
    return out
