"""SERVE-SHAPE positive: raw per-request extents keying / steering
serving programs — every distinct request length compiles a fresh
executable, so recompilation scales with traffic."""
from apex_tpu.runtime import executor as _executor


def make_decode_program(tokens, tables, build_fn):
    # BAD: operand extents straight into the static key — one program
    # per occupancy x table length, unbounded over a request stream
    key = (tokens.shape[0], len(tables[0]))
    return _executor.Program("decode_step", key, build_fn)


def make_prefill_program(prompt, build_fn):
    # BAD: prompt length steers which program gets built — the same
    # recompile surface as keying on it
    if len(prompt) > 32:
        key = ("long", len(prompt))
    else:
        key = ("short", len(prompt))
    return _executor.Program("prefill_step", key, build_fn)


def make_spec_program(n_acc, build_fn):
    # BAD: a speculative tick's ragged acceptance count in the static
    # key — acceptance varies 1..k+1 per sequence per tick, so the
    # engine recompiles mid-stream the first time a new pattern shows
    key = ("spec", int(n_acc))
    return _executor.Program("spec_verify_step", key, build_fn)


def pick_verify_program(accepted_len, wide_fn, narrow_fn):
    # BAD: acceptance steering which program gets built — the same
    # recompile surface as keying on it
    if accepted_len > 2:
        return _executor.Program("spec_verify_step", ("wide",), wide_fn)
    return _executor.Program("draft_prefill_step", ("narrow",), narrow_fn)
