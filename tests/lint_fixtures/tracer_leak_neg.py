"""TRACER-LEAK negative: traced values leave through the return value;
host-side bookkeeping happens outside the trace; locals may hold
tracers freely (they die with the trace)."""
import jax
import jax.numpy as jnp

_STATS = {}


@jax.jit
def clean_step(params, grads):
    g = grads[0]
    # fine: a LOCAL container dies with the trace
    scratch = {}
    scratch["g2"] = g * g
    out = [p - 0.1 * gi for p, gi in zip(params, grads)]
    # traced values exit through the outputs, as they should
    return out, jnp.sqrt(jnp.sum(scratch["g2"]))


def record(kind, ms):
    # fine: eager bookkeeping with host floats, outside any trace
    _STATS[kind] = ms
    _STATS.setdefault("count", 0)
