"""UNBOUNDED-COLLECTIVE negative: process-wide calls through PR 2's
bounded wrapper (deadline + CollectiveTimeoutError naming absent
ranks)."""
from apex_tpu.parallel import timed_flat_dist_call


def distributed_init(tensors, collective):
    return timed_flat_dist_call(tensors, collective, timeout_s=60.0)
