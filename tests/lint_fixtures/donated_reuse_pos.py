"""DONATED-REUSE positive: reading a buffer after the step donated it."""
import jax


def train_loop(update, state, batches, log):
    step = jax.jit(update, donate_argnums=(0,))
    for batch in batches:
        new_state = step(state, batch)
        # BAD: `state` was donated to step() — this reads freed memory
        log(state)
        state = new_state
    return state


def one_shot(update, params, grads):
    # BAD: inline donating call, then the stale reference
    out = jax.jit(update, donate_argnums=(0,))(params, grads)
    return out, params
