"""KERNEL-FALLBACK positive fixture: raw pallas_call outside
apex_tpu/kernels/ (two import spellings), and registrations missing the
declared fallback / probe."""
import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import pallas_call          # flagged import

from apex_tpu.kernels.dispatch import register_kernel


def _double_kernel(x_ref, o_ref):
    o_ref[...] = x_ref[...] * 2.0


def model_path_kernel(x):
    # flagged: pallas_call wired straight into model code — no XLA
    # fallback seam, no probe record
    return pl.pallas_call(
        _double_kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype))(x)


def aliased_spelling(x):
    return pallas_call(                                   # flagged call
        _double_kernel,
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype))(x)


def _probe(dims):
    return None, False


# flagged: no xla_fallback declared
register_kernel("orphan_kernel", threshold_probe=_probe)

# flagged: no threshold_probe declared
register_kernel("blind_kernel", xla_fallback="apex_tpu.ops.some_op")

# flagged: fallback declared but empty
register_kernel("hollow_kernel", xla_fallback="", threshold_probe=_probe)
