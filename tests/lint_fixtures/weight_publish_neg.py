"""WEIGHT-PUBLISH negative: weight movement through the measured
surfaces, and raw placement of things that are NOT weights (batches)."""
import jax

from apex_tpu.rollout import WeightPublisher, master_leaves
from apex_tpu.runtime.resilience import reshard_state


def publish(step, engine):
    # the sanctioned path: cast-once, zero-copy where layouts match,
    # versioned, telemetered
    WeightPublisher(engine, which="target").publish(master_leaves(step))


def restore(host_state, train_step):
    # validated reshard — per-leaf stats available via stats_out
    stats = {}
    return reshard_state(host_state, train_step.state, stats_out=stats)


def stage_batch(images, labels, device):
    # batch data is not a weight pytree — raw placement is fine
    return (jax.device_put(images, device),
            jax.device_put(labels, device))
