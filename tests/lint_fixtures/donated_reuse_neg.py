"""DONATED-REUSE negative: the sanctioned rebind pattern — every output
rebound, no reads of the donated reference."""
import jax
import jax.numpy as jnp


def train_loop(update, state, batches, log):
    step = jax.jit(update, donate_argnums=(0,))
    for batch in batches:
        state = step(state, batch)      # consumed and rebound
        log(batch)
    return state


def with_copy(update, params, grads):
    before = jnp.stack([jnp.copy(p) for p in params])
    new_params = jax.jit(update, donate_argnums=(0,))(params, grads)
    return new_params, before           # the copy, not the donated ref
