"""COMPAT-SHIM positive (scoped: this file sits under a directory named
apex_tpu, so the rule treats it as package code)."""
import jax
from jax.experimental.shard_map import shard_map as legacy_sm   # BAD
from jax.sharding import PartitionSpec as P


def wrap(f, mesh):
    # BAD: jax.shard_map is an AttributeError on jax 0.4.x
    return jax.shard_map(f, mesh=mesh, in_specs=P("dp"),
                         out_specs=P("dp"))


def world(axis):
    # BAD: jax.lax.axis_size does not exist on jax 0.4.x
    return jax.lax.axis_size(axis)


del legacy_sm
