"""COMPAT-SHIM negative: everything through the version shim."""
from jax.sharding import PartitionSpec as P

from apex_tpu import compat


def wrap(f, mesh):
    return compat.shard_map(f, mesh=mesh, in_specs=P("dp"),
                            out_specs=P("dp"), check_vma=False)


def world(axis):
    return compat.axis_size(axis)
