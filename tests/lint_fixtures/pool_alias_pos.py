"""POOL-ALIAS positive: pool blocks mutated outside the refcount API —
private bookkeeping reached directly, and an in-place scatter into a
(possibly shared) pool block."""


def rogue_free(engine, session):
    # bypasses refcounting: a shared block lands on the free list while
    # other tables still reference it
    engine.block_pool._free.append(session.table[0])
    engine.block_pool._refs.pop(session.table[0], None)


def rogue_index_drop(pool, key):
    del pool._hash_index[key]


def rogue_scatter(engine, blk, row):
    # in-place write into the KV pool outside the kernel bodies — if
    # blk is shared this corrupts every session holding the prefix
    engine.pool = engine.pool.at[:, :, blk, :, row].set(0.0)


def rogue_quant_scatter(kv_pool, blk, payload):
    return kv_pool.q.at[:, :, blk].add(payload)
