"""SCAN-COLLECTIVE negative: boundary-only exchange (PR 3's invariant)
— accumulate in the carry, one psum after the scan."""
import jax
from jax import lax


def accum_window(grad_fn, params, micro, axis_name):
    def body(carry, mb):
        # the axis-size idiom: psum of the literal 1 constant-folds,
        # no collective is emitted
        n = lax.psum(1, axis_name)
        g = grad_fn(params, mb)
        return [c + gi / n for c, gi in zip(carry, g)], None

    acc0 = [0.0 * p for p in params]
    acc, _ = lax.scan(body, acc0, micro)
    # ONE exchange at the window boundary
    return [lax.psum(a, axis_name) for a in acc]
