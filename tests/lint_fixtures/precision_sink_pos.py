"""PRECISION-SINK positive: half-precision values reaching reductions
with no fp32 accumulator anywhere on the path."""
import jax
import jax.numpy as jnp


@jax.jit
def bad_loss(h):
    hh = h.astype(jnp.float16)
    # BAD: jnp.sum of an fp16 array accumulates IN fp16 (caps at 65504)
    total = jnp.sum(hh)
    # BAD: fp16 @ fp16 keeps the fp16 accumulator too
    gram = hh @ hh
    return total, gram


@jax.jit
def bad_running(h):
    hh = h.astype(jnp.bfloat16)
    acc = hh * 0.0
    for _ in range(4):
        # BAD: python-loop accumulation in bf16 drops mantissa bits
        acc = acc + hh
    return acc
