"""SHAPE-BRANCH negative: shape decisions routed through a bucket
quantizer, and raise-only validation guards."""
import jax


def bucket_len(n, cap=256):
    # fine: this IS the sanctioned quantizer — O(log) programs by
    # construction
    m = 8
    while m < n and m < cap:
        m *= 2
    return min(m, cap)


@jax.jit
def clean_bucketed(x):
    n = bucket_len(x.shape[0])
    # fine: branches on the BUCKET, not the raw extent
    if n > 128:
        return x.sum() / n
    return x.sum()


@jax.jit
def clean_guard(x, y):
    # fine: a validation guard raises — it never forks program identity
    if x.shape != y.shape:
        raise ValueError("shape mismatch")
    return x + y
