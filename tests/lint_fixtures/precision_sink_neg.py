"""PRECISION-SINK negative: every reduction of a half value routes
through an fp32 accumulator — the amp-O2 master-weight discipline."""
import jax
import jax.numpy as jnp


@jax.jit
def clean_loss(h):
    hh = h.astype(jnp.float16)
    # upcast BEFORE the reduction
    total = jnp.sum(hh.astype(jnp.float32))
    # or tell the reduction to accumulate in fp32
    total2 = jnp.sum(hh, dtype=jnp.float32)
    # or give the contraction an fp32 accumulator explicitly
    gram = jnp.matmul(hh, hh, preferred_element_type=jnp.float32)
    return total + total2, gram


@jax.jit
def clean_master(h):
    hh = h.astype(jnp.bfloat16)
    acc = jnp.zeros_like(h, dtype=jnp.float32)
    for _ in range(4):
        # fp32 running sum over half-precision increments
        acc = acc + hh.astype(jnp.float32)
    return acc
