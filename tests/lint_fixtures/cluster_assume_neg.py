"""CLUSTER-ASSUME negative: topology questions through the sanctioned
seam — parallel.distributed helpers and cluster membership views."""
import os

from apex_tpu.cluster import current_view, default_kv
from apex_tpu.parallel import init_distributed
from apex_tpu.parallel.distributed import num_processes, rank


def should_log():
    return num_processes() > 1 and rank() != 0


def setup(addr):
    # bounded retry loop, launcher env consumed inside the seam
    init_distributed(coordinator_address=addr)
    view = current_view(default_kv())
    # epoch-keyed, not rank-keyed: immutable per membership epoch
    port = int(os.environ.get("APEX_TPU_COORD_PORT", "12355"))
    return view.epoch if view is not None else 0, port
