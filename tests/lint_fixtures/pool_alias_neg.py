"""POOL-ALIAS negative: the refcount API used as intended, and
``.at[...]`` scatters on non-pool arrays (plain jnp updates are not the
rule's business)."""


def good_lifecycle(engine, keys):
    pool = engine.block_pool
    shared = pool.acquire_prefix(keys)
    ids = pool.alloc(2)
    for bid, key in zip(ids, keys[len(shared):]):
        pool.commit(bid, key)
    pool.free(ids)
    pool.free(shared)
    pool.check_no_leaks()


def good_scatter(grads, idx, val):
    # .at writes on ordinary arrays are fine — the rule audits POOL
    # buffers, not the update syntax
    return grads.at[idx].set(val)


def good_read(engine, blk):
    # reading pool contents is not a write hazard
    return engine.pool[:, :, blk]


def good_gauges(engine):
    return (engine.block_pool.free_count, engine.block_pool.cached_count,
            engine.block_pool.in_use)
