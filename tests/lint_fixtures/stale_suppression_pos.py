"""STALE-SUPPRESSION positive: the directive outlived its finding —
RETRACE-STATIC never fires on shape knobs, so the disable below masks
nothing (except future regressions on this line)."""
import jax


def make(update):
    # tpu-lint: disable=RETRACE-STATIC shape knobs are static here
    return jax.jit(update, static_argnames=("accum_steps",))
