"""CLUSTER-ASSUME positive: raw process-topology queries that go stale
the moment cluster membership changes epoch."""
import os

import jax


def should_log():
    # BAD: rank gate on the fleet the job STARTED with
    return jax.process_count() > 1 and jax.process_index() != 0


def setup(addr):
    # BAD: bare initialize — blocks forever, no retry/backoff
    jax.distributed.initialize(coordinator_address=addr)
    # BAD: hardcoded process-count arithmetic from the launcher env
    n = int(os.environ["APEX_TPU_NUM_PROCESSES"])
    me = int(os.environ.get("APEX_TPU_PROCESS_ID", "0"))
    return me * 100 // n
