"""EXEC-BYPASS positive: step programs compiled/dispatched around the
one-runtime executor — no dispatch count, no span, no heartbeat."""
import jax


def cached_dispatch(step_cache, key, args, build):
    # BAD: direct compile-or-hit against the step cache
    fn = step_cache.program("train_step", key, args, build)
    # BAD: hand-rolled dispatch counting
    step_cache._bump("dispatches", "train_step")
    return fn(*args)


def make_step(step_fn, donate):
    # BAD: jitting a train step directly — bypasses the program cache,
    # the donation policy and the dispatch observability
    jit_step = jax.jit(step_fn, donate_argnums=(0,) if donate else ())
    return jit_step


def wrap_raw(wrapper):
    # BAD: same bypass through an attribute spelling
    return jax.jit(wrapper._raw_step_fn, donate_argnums=(0,))
