"""STALE-SUPPRESSION negative: the directive still earns its keep —
RETRACE-STATIC fires on this line and the suppression consumes it."""
import jax


def make(update):
    # tpu-lint: disable=RETRACE-STATIC fixture: lr deliberately static
    return jax.jit(update, static_argnames=("lr",))
