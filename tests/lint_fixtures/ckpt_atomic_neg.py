"""CKPT-ATOMIC negative fixture: the sanctioned write paths, in-memory
pickling, non-checkpoint binary IO, and checkpoint READS all stay
clean."""
import pickle

from apex_tpu.runtime import CheckpointManager
from apex_tpu.runtime.resilience import write_checkpoint_file


def save_model(state, path):
    # the one write path: atomic rename + CRC32 manifest + layout
    write_checkpoint_file(path, {"model": state})


def save_rolling(state, directory):
    CheckpointManager(directory, keep_n=3).save(0, model=state)


def serialize_in_memory(state):
    return pickle.dumps(state)      # bytes in memory, not a file write


def write_plot(png_bytes):
    with open("training_curve.png", "wb") as f:   # binary, not a ckpt
        f.write(png_bytes)


def read_checkpoint(path="ckpt_00000001.pkl"):
    with open(path, "rb") as f:     # read mode: no durability hazard
        return pickle.load(f)
