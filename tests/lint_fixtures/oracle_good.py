"""Dynamic-oracle GOOD optimizer: lr enters as a traced device scalar.

The static key carries program shape only, so a whole lr schedule runs
against ONE compiled executable — ``step_cache.stats()`` pins 1 compile
however many steps run, and RETRACE-STATIC stays silent.
"""
import jax
import jax.numpy as jnp

from apex_tpu.runtime import step_cache


def sgd_step(params, grads, lr):
    def build():
        def run(params, grads, lr):
            return [p - lr * g for p, g in zip(params, grads)]
        return jax.jit(run)

    args = (params, grads, jnp.asarray(lr, jnp.float32))
    fn = step_cache.step_cache.program("oracle_good", ("sgd",),
                                       args, build)
    return fn(*args)


def train(steps=4, lr0=0.1):
    params = [jnp.ones((4,), jnp.float32)]
    grads = [jnp.full((4,), 0.5, jnp.float32)]
    for i in range(steps):
        params = sgd_step(params, grads, lr0 * (0.5 ** i))
    return params
