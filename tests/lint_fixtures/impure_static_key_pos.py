"""IMPURE-STATIC-KEY positive: wall-clock / RNG / object identity in
program-cache keys — every call becomes a distinct executable."""
import random
import time


def timed_step(step_cache, params, grads, build):
    args = (params, grads)
    # BAD: time.time() keys a fresh program every call
    fn = step_cache.program("sgd", ("cfg", time.time()), args, build)
    return fn(*args)


def jittered_step(step_cache, params, grads, build):
    args = (params, grads)
    # BAD: random jitter in the key — unbounded recompilation
    fn = step_cache.program("sgd", ("cfg", random.random()), args, build)
    return fn(*args)


def identity_step(step_cache, optimizer, params, grads, build):
    args = (params, grads)
    # BAD: id() is not stable across restarts — resumed runs recompile
    fn = step_cache.program("sgd", (id(optimizer),), args, build)
    return fn(*args)
