"""Dynamic-oracle fixture: the fp32-accumulated twin of
oracle_precision_bad — clean statically, finite dynamically on the
same input."""
import jax
import jax.numpy as jnp


@jax.jit
def window_energy(xs):
    # products form in fp16 (that is the storage dtype), but the
    # REDUCTION runs in fp32 — the accumulator cannot saturate
    h = xs.astype(jnp.float16)
    return jnp.sum((h * h).astype(jnp.float32))
