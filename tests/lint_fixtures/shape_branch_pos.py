"""SHAPE-BRANCH positive: python control flow forking on a traced
value's shape — every distinct input shape compiles its own program."""
import jax


@jax.jit
def bad_pick_program(x):
    # BAD: each arriving length takes its own branch (and its own XLA
    # executable) — the unbucketed-serve pathology
    if x.shape[0] > 128:
        return x[:128] * 2.0
    return x * 2.0


def _route(n):
    # BAD (interprocedural): n derives from a traced shape two frames up
    while n > 1:
        n = n // 2
    return n


@jax.jit
def bad_halving(x):
    return x * _route(x.shape[0])
