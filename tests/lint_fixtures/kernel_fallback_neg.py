"""KERNEL-FALLBACK negative fixture: model code consumes the kernels
tier through its dispatch surface, and registrations declare both the
XLA fallback and the threshold probe."""
import jax.numpy as jnp

from apex_tpu.kernels import attention as _k
from apex_tpu.kernels.dispatch import register_kernel


def model_path(q, k, v):
    # the sanctioned route: the kernels tier decides pallas-vs-XLA from
    # the calibration ledger; no raw pallas_call in model code
    return _k.flash_attention_fwd(q, k, v, None, 1.0, True)


def _probe(dims):
    # measured win region as data: below 512 keys XLA wins (round 5)
    return 512, dims.get("sk", 0) >= 512


register_kernel(
    "well_declared_kernel",
    xla_fallback="apex_tpu.contrib.multihead_attn.attn_funcs."
                 "attention_reference",
    threshold_probe=_probe,
    doc="fixture: compliant registration")
