"""SERVE-SHAPE negative: serving programs keyed on config only, with
every request-dependent extent rounded through the bucket table before
it reaches program identity; operand signatures complete the cache key."""
import itertools

from apex_tpu.runtime import executor as _executor
from apex_tpu.serve.scheduler import bucket

_TOKENS = itertools.count()


def make_programs(block_size, dtype_name, window, build_decode,
                  build_prefill):
    # GOOD: static key is pure config + a monotonic builder token —
    # bucketed operand shapes complete the key via the signature
    key = (next(_TOKENS), block_size, dtype_name, window)
    decode = _executor.Program("decode_step", key, build_decode)
    prefill = _executor.Program("prefill_step", key, build_prefill)
    return prefill, decode


def pack_batch(sessions, max_batch):
    # GOOD: len() rounded through the bucket table before it can
    # influence any program shape — O(log) distinct values
    b = bucket(len(sessions), max_batch)
    nb = bucket(max(len(s.table) for s in sessions))
    return b, nb


def train_key(batch):
    # GOOD: non-serve kinds are out of scope for this rule
    return _executor.Program("train_step", (len(batch),), lambda x: x)


def make_spec_programs(block_size, dtype_name, spec_k, build_draft,
                       build_verify):
    # GOOD: the speculative kinds key on config (spec_k is a config
    # constant, not a per-tick acceptance) + the builder token
    key = (next(_TOKENS), block_size, dtype_name, spec_k)
    draft = _executor.Program("draft_prefill_step", key, build_draft)
    verify = _executor.Program("spec_verify_step", key, build_verify)
    return draft, verify


def commit_accepted(sessions, emitted, n_acc):
    # GOOD: ragged acceptance consumed as operand VALUES in the host
    # commit loop — it never reaches program identity
    for i, s in enumerate(sessions):
        for j in range(int(n_acc[i])):
            s.out.append(int(emitted[i, j]))
    return sessions


def spec_batch_key(sessions, max_batch):
    # GOOD: acceptance-adjacent extents rounded through the bucket
    # table before they can influence any program shape
    b = bucket(len(sessions), max_batch)
    nbd = bucket(max(len(s.draft_table) for s in sessions))
    return b, nbd
