"""SERVE-SHAPE negative: serving programs keyed on config only, with
every request-dependent extent rounded through the bucket table before
it reaches program identity; operand signatures complete the cache key."""
import itertools

from apex_tpu.runtime import executor as _executor
from apex_tpu.serve.scheduler import bucket

_TOKENS = itertools.count()


def make_programs(block_size, dtype_name, window, build_decode,
                  build_prefill):
    # GOOD: static key is pure config + a monotonic builder token —
    # bucketed operand shapes complete the key via the signature
    key = (next(_TOKENS), block_size, dtype_name, window)
    decode = _executor.Program("decode_step", key, build_decode)
    prefill = _executor.Program("prefill_step", key, build_prefill)
    return prefill, decode


def pack_batch(sessions, max_batch):
    # GOOD: len() rounded through the bucket table before it can
    # influence any program shape — O(log) distinct values
    b = bucket(len(sessions), max_batch)
    nb = bucket(max(len(s.table) for s in sessions))
    return b, nb


def train_key(batch):
    # GOOD: non-serve kinds are out of scope for this rule
    return _executor.Program("train_step", (len(batch),), lambda x: x)
