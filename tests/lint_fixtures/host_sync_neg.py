"""HOST-SYNC negative: syncs in eager code are fine; traced code keeps
values on device; static_argnames config may branch."""
import jax
import jax.numpy as jnp
import numpy as np


@jax.jit
def clean_step(params, grads, flag):
    # on-device conditional: no round-trip
    keep = flag > 0
    return [jnp.where(keep, p, p - 0.1 * g)
            for p, g in zip(params, grads)]


def branchy_step(params, grads, mode):
    if mode == "sgd":           # fine: mode is static at the jit site
        return [p - 0.1 * g for p, g in zip(params, grads)]
    return params


jitted = jax.jit(branchy_step, static_argnames=("mode",))


@jax.jit
def host_const_step(params):
    # fine: .item() on a numpy scalar — dataflow proves it lives on
    # host, so there is no device round-trip to flag
    cap = np.float32(8.0).item()
    return [p * cap for p in params]


def eager_train_loop(step, params, batches):
    """Eager driver — host syncs for logging are exactly where they
    belong, OUTSIDE the compiled step."""
    for batch in batches:
        params, loss = step(params, batch)
        print("loss:", float(loss), np.asarray(loss).shape)
    return params, loss.item()
