"""RETRACE-STATIC negative: hyperparameters traced, static keys carry
program shape only."""
import jax
import jax.numpy as jnp


def make_update(update):
    # shape knobs may be static; hyperparams enter as traced args
    return jax.jit(update, static_argnames=("accum_steps", "donate"))


def cached_step(step_cache, params, grads, lr, build):
    # lr rides in the traced argument tuple, not the key
    args = (params, grads, jnp.asarray(lr, jnp.float32))
    fn = step_cache.program("sgd", ("cfg", True), args, build)
    return fn(*args)


def _accum(params, grads, accum_steps):
    del accum_steps
    return [p - 0.1 * g for p, g in zip(params, grads)]


WINDOWED = jax.jit(_accum, static_argnames=("accum_steps",))


def windowed_step(params, grads, cfg):
    # fine: the static knob is host config, never a tracer
    k = cfg.get("accum_steps", 1)
    return WINDOWED(params, grads, accum_steps=k)
