"""RETRACE-STATIC negative: hyperparameters traced, static keys carry
program shape only."""
import jax
import jax.numpy as jnp


def make_update(update):
    # shape knobs may be static; hyperparams enter as traced args
    return jax.jit(update, static_argnames=("accum_steps", "donate"))


def cached_step(step_cache, params, grads, lr, build):
    # lr rides in the traced argument tuple, not the key
    args = (params, grads, jnp.asarray(lr, jnp.float32))
    fn = step_cache.program("sgd", ("cfg", True), args, build)
    return fn(*args)
