"""IMPURE-STATIC-KEY negative: keys built from stable program shape —
config tuples and monotonic builder tokens (training/step.py's
_STEP_TOKENS pattern)."""
import itertools

_TOKENS = itertools.count()


def make_step(step_cache, accum_steps, donate, build):
    token = next(_TOKENS)

    def step(params, grads):
        args = (params, grads)
        fn = step_cache.program(
            "train_step", (token, accum_steps, bool(donate)), args, build)
        return fn(*args)

    return step
