"""Dynamic-oracle BAD optimizer: the PR 1 retrace pathology, distilled.

``lr`` lands in the hashable step-cache key, so every schedule tick
compiles a fresh XLA executable.  ``tests/test_lint.py`` both lints
this file (RETRACE-STATIC must fire) and RUNS it (``step_cache.stats()``
must show one compile per distinct lr) — proving the static verdict
matches runtime behavior.
"""
import jax
import jax.numpy as jnp

from apex_tpu.runtime import step_cache


def sgd_step(params, grads, lr):
    def build():
        def run(params, grads):
            return [p - lr * g for p, g in zip(params, grads)]
        return jax.jit(run)

    args = (params, grads)
    # BAD: lr in the static key — one executable per lr value
    fn = step_cache.step_cache.program("oracle_bad", ("sgd", lr),
                                       args, build)
    return fn(*args)


def train(steps=4, lr0=0.1):
    params = [jnp.ones((4,), jnp.float32)]
    grads = [jnp.full((4,), 0.5, jnp.float32)]
    for i in range(steps):
        params = sgd_step(params, grads, lr0 * (0.5 ** i))
    return params
