"""EXEC-BYPASS negative: steps described as Program descriptors and
submitted through the runtime executor; non-step jits stay legal."""
import itertools

import jax

from apex_tpu.runtime import executor as _executor

_TOKENS = itertools.count()


def make_step(step_fn, donate):
    # GOOD: describe the program, let the executor compile/count/span
    program = _executor.Program(
        "train_step", (next(_TOKENS), bool(donate)), step_fn,
        donate_argnums=(0,) if donate else ())
    dispatch_no = itertools.count(1)

    def jit_step(state, *batch):
        return _executor.executor.submit(
            program, (state,) + batch, step=next(dispatch_no))

    return jit_step


def decode_fn(logits_fn):
    # GOOD: jit of a non-step function (inference helper) is not a
    # dispatch bypass
    def run(tokens):
        return logits_fn(tokens)
    return jax.jit(run)
