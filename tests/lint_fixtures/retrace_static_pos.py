"""RETRACE-STATIC positive: hyperparameters in static jit keys — both
spellings (static_argnames and a hashable step-cache key)."""
import functools

import jax


def make_update(update):
    # BAD: lr/weight_decay static — every schedule tick recompiles
    return jax.jit(update, static_argnames=("lr", "weight_decay"))


def make_update_partial(update):
    # BAD: the functools.partial spelling of the same bug
    return functools.partial(jax.jit, static_argnames=["lr"])(update)


def cached_step(step_cache, params, grads, lr, build):
    args = (params, grads)
    # BAD: lr in the hashable program key — one executable per lr value
    fn = step_cache.program("sgd", ("cfg", lr), args, build)
    return fn(*args)


def _sgd(params, grads, lr):
    return [p - lr * g for p, g in zip(params, grads)]


UPDATE = jax.jit(_sgd, static_argnames=("lr",))


def _decay(x):
    return x * 0.99


def _anneal(base):
    return _decay(base)


def schedule_step(params, grads):
    import jax.numpy as jnp
    lr = _anneal(jnp.asarray(0.1))
    # BAD: the traced lr schedule reaches the static argname through
    # two helper frames — dataflow catches what the AST cannot
    return UPDATE(params, grads, lr=lr)
