"""UNBOUNDED-COLLECTIVE positive: raw multihost_utils — blocks forever
if one process never arrives, with no diagnosis."""
from jax.experimental import multihost_utils                     # BAD
from jax.experimental.multihost_utils import broadcast_one_to_all  # BAD


def distributed_init(seed):
    # BAD: no deadline, no missing-rank report
    multihost_utils.sync_global_devices("init_barrier")
    return broadcast_one_to_all(seed)
