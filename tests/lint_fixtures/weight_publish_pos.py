"""WEIGHT-PUBLISH positive: raw placement of parameter/state pytrees —
weight movement the sync accounting never sees."""
import jax


def hand_rolled_publish(step, engine_params, device):
    # BAD: gather ALL masters to host every epoch ...
    masters = jax.device_get(step.state.master_params)
    # BAD: ... then re-place them raw — no validation, no zero-copy
    # fast path, no per-leaf stats, no weight epoch
    placed = jax.device_put(masters, device)
    for p, v in zip(engine_params, placed):
        p.data = v


def reload_weights(host_weights, sharding):
    # BAD: raw placement of a weight pytree outside the reshard surface
    return jax.device_put(host_weights, sharding)
