"""Tensor-parallel linears (parallel/tensor_parallel.py) vs the unsharded
nn.Linear oracle on the 8-device CPU mesh: forward equality, gradient
equality through the column->row MLP pattern, and the one-collective-per-
pair property is exercised implicitly by running under shard_map."""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import apex_tpu.nn as nn
from apex_tpu.nn import functional as F
from apex_tpu.parallel import ColumnParallelLinear, RowParallelLinear

IN, HID, OUT, B = 16, 64, 24, 8


def _mesh(n=8):
    return Mesh(np.array(jax.devices()[:n]), ("tp",))


def _oracle_and_tp():
    nn.manual_seed(31)
    col = ColumnParallelLinear(IN, HID, "tp")
    row = RowParallelLinear(HID, OUT, "tp")
    # same seed stream → identical full-size weights for the oracle
    nn.manual_seed(31)
    lin1 = nn.Linear(IN, HID)
    lin2 = nn.Linear(HID, OUT)
    return (col, row), (lin1, lin2)


def _tp_forward(col, row, mesh, x):
    def f(x):
        from apex_tpu.nn.modules import Ctx
        ctx = Ctx()
        h = F.relu(col.forward(ctx, x))
        return row.forward(ctx, h)

    shard = jax.shard_map(f, mesh=mesh, in_specs=P(), out_specs=P(),
                          check_vma=False)
    return jax.jit(shard)(x)


def test_tp_mlp_matches_unsharded(rng):
    mesh = _mesh()
    (col, row), (lin1, lin2) = _oracle_and_tp()
    x = jnp.asarray(rng.standard_normal((B, IN)), jnp.float32)
    got = _tp_forward(col, row, mesh, x)
    want = lin2(nn.ReLU()(lin1(x)))
    np.testing.assert_allclose(np.asarray(got), np.asarray(want.value),
                               rtol=2e-5, atol=2e-5)


def test_tp_grads_match_unsharded(rng):
    """Gradients computed INSIDE shard_map (the fused train step's
    convention, training/step.py): the f/g operator pair makes the
    column weight/bias and row weight grads disjoint per-device blocks
    that psum-assemble to the unsharded oracle, while the row bias (added
    after the reduction) gets the full replicated grad on every device."""
    mesh = _mesh()
    (col, row), (lin1, lin2) = _oracle_and_tp()
    x = jnp.asarray(rng.standard_normal((B, IN)), jnp.float32)
    w_out = jnp.asarray(rng.standard_normal((B, OUT)), jnp.float32)

    def f(cw, cb, rw, rb, x):
        from apex_tpu.nn.modules import Ctx
        from apex_tpu.parallel.tensor_parallel import copy_to_tp_region

        def loss(cw, cb, rw, rb):
            ctx = Ctx(env={id(col.weight): cw, id(col.bias): cb,
                           id(row.weight): rw, id(row.bias): rb})
            h = F.relu(col.forward(ctx, copy_to_tp_region(x, "tp")))
            return jnp.sum(row.forward(ctx, h) * w_out)

        gcw, gcb, grw, grb = jax.grad(loss, argnums=(0, 1, 2, 3))(
            cw, cb, rw, rb)
        # sharded-param grads are disjoint blocks: assemble by psum (what
        # make_train_step(tp_axis=...) does); row bias is already full
        return (jax.lax.psum(gcw, "tp"), jax.lax.psum(gcb, "tp"),
                jax.lax.psum(grw, "tp"), grb)

    g_tp = jax.jit(jax.shard_map(
        f, mesh=mesh, in_specs=(P(),) * 5, out_specs=P(),
        check_vma=False))(
        col.weight.data, col.bias.data, row.weight.data, row.bias.data, x)

    # oracle grads through the tape
    loss = (lin2(nn.ReLU()(lin1(x))) * w_out).sum()
    loss.backward()
    g_ref = [lin1.weight.grad, lin1.bias.grad,
             lin2.weight.grad, lin2.bias.grad]
    for a, b in zip(g_tp, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-5, atol=3e-5)


def test_column_gather_output(rng):
    mesh = _mesh()
    nn.manual_seed(3)
    col = ColumnParallelLinear(IN, HID, "tp", gather_output=True)
    nn.manual_seed(3)
    lin = nn.Linear(IN, HID)
    x = jnp.asarray(rng.standard_normal((B, IN)), jnp.float32)

    def f(x):
        from apex_tpu.nn.modules import Ctx
        return col.forward(Ctx(), x)

    got = jax.jit(jax.shard_map(f, mesh=mesh, in_specs=P(), out_specs=P(),
                                check_vma=False))(x)
    np.testing.assert_allclose(np.asarray(got), np.asarray(lin(x).value),
                               rtol=2e-5, atol=2e-5)


def test_functional_forms_reject_bad_shapes(rng):
    """Sanity: shard helpers assume divisibility; a non-divisible feature
    dim surfaces as a shape error under shard_map rather than silence."""
    mesh = _mesh()
    nn.manual_seed(1)
    col = ColumnParallelLinear(IN, 60, "tp")  # 60 % 8 != 0

    def f(x):
        from apex_tpu.nn.modules import Ctx
        return col.forward(Ctx(), x)

    x = jnp.asarray(rng.standard_normal((B, IN)), jnp.float32)
    with pytest.raises(Exception):
        jax.jit(jax.shard_map(f, mesh=mesh, in_specs=P(), out_specs=P(),
                              check_vma=False))(x)


def test_functional_forms_with_explicit_shards(rng):
    """column_parallel_linear / row_parallel_linear with hand-sliced weight
    shards (the 'fully manual layouts' API) vs the dense computation."""
    from apex_tpu.parallel import (column_parallel_linear,
                                   row_parallel_linear)
    mesh = _mesh(4)
    w1 = jnp.asarray(rng.standard_normal((HID, IN)), jnp.float32)
    b1 = jnp.asarray(rng.standard_normal((HID,)), jnp.float32)
    w2 = jnp.asarray(rng.standard_normal((OUT, HID)), jnp.float32)
    b2 = jnp.asarray(rng.standard_normal((OUT,)), jnp.float32)
    x = jnp.asarray(rng.standard_normal((B, IN)), jnp.float32)

    def f(x, w1, b1, w2, b2):
        h = column_parallel_linear(x, w1, b1, "tp")          # (B, HID/4)
        h = jnp.maximum(h, 0)
        return row_parallel_linear(h, w2, None, "tp") + b2   # (B, OUT)

    got = jax.jit(jax.shard_map(
        f, mesh=mesh,
        in_specs=(P(), P("tp"), P("tp"), P(None, "tp"), P()),
        out_specs=P(), check_vma=False))(x, w1, b1, w2, b2)
    want = jnp.maximum(x @ w1.T + b1, 0) @ w2.T + b2
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)

    # gather_output returns the full feature dim in shard order
    def g(x, w1, b1):
        return column_parallel_linear(x, w1, b1, "tp", gather_output=True)

    full = jax.jit(jax.shard_map(
        g, mesh=mesh, in_specs=(P(), P("tp"), P("tp")),
        out_specs=P(), check_vma=False))(x, w1, b1)
    np.testing.assert_allclose(np.asarray(full), np.asarray(x @ w1.T + b1),
                               rtol=2e-5, atol=2e-5)
