"""Legacy old-API handle + OptimWrapper (reference apex/amp/opt.py:9-103,
handle.py:170-281): amp.init() -> handle.wrap_optimizer(opt, num_loss=N),
per-loss dynamic scalers, grad caching across multiple losses, any-loss
overflow skipping the shared step."""
import jax.numpy as jnp
import numpy as np
import pytest

import apex_tpu.nn as nn
from apex_tpu import amp
from apex_tpu.amp.opt import OptimWrapper
from apex_tpu.optimizers import FusedSGD


@pytest.fixture(autouse=True)
def _fresh_amp_state():
    from apex_tpu.amp._amp_state import reset
    reset()
    yield
    reset()


def _model():
    nn.manual_seed(7)
    return nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))


def _data(seed=0):
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.standard_normal((8, 16)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 4, (8,)))
    return x, y


def test_wrap_optimizer_trains():
    handle = amp.init(verbose=False)
    model = _model()
    opt = handle.wrap_optimizer(FusedSGD(list(model.parameters()), lr=0.1))
    assert isinstance(opt, OptimWrapper)
    crit = nn.CrossEntropyLoss()
    x, y = _data()
    losses = []
    for _ in range(5):
        out = model(x)
        loss = crit(out, y)
        with opt.scale_loss(loss) as scaled:
            scaled.backward()
        opt.step()
        opt.zero_grad()
        losses.append(float(loss))
    handle._deactivate()
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_scale_loss_scales_by_scaler():
    handle = amp.init()
    model = _model()
    opt = handle.wrap_optimizer(FusedSGD(list(model.parameters()), lr=0.1))
    crit = nn.CrossEntropyLoss()
    x, y = _data()
    loss = crit(model(x), y)
    with opt.scale_loss(loss) as scaled:
        np.testing.assert_allclose(float(scaled), float(loss) * 2.0 ** 16,
                                   rtol=1e-6)
        scaled.backward()
    handle._deactivate()


def test_multi_loss_grads_accumulate():
    """Two losses through num_loss=2 must equal the grads of (loss1+loss2)
    computed without amp — the cache/restore path of opt.py:24-53."""
    handle = amp.init()
    model = _model()
    params = list(model.parameters())
    opt = handle.wrap_optimizer(FusedSGD(params, lr=0.1), num_loss=2)
    crit = nn.CrossEntropyLoss()
    x1, y1 = _data(1)
    x2, y2 = _data(2)

    with opt.scale_loss(crit(model(x1), y1)) as scaled:
        scaled.backward()
    with opt.scale_loss(crit(model(x2), y2)) as scaled:
        scaled.backward()
    amp_grads = [p.grad for p in params]
    opt.zero_grad()
    handle._deactivate()

    # reference grads, no amp in the picture
    model2 = _model()
    params2 = list(model2.parameters())
    loss = nn.CrossEntropyLoss()(model2(x1), y1) \
        + nn.CrossEntropyLoss()(model2(x2), y2)
    loss.backward()
    # the amp path runs the model in fp16 under the ambient policy; the
    # oracle is fp32, so tolerances are fp16-sized
    for a, b in zip(amp_grads, [p.grad for p in params2]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-2, atol=3e-4)


def test_overflow_skips_step_and_halves_scale():
    handle = amp.init()
    model = _model()
    params = list(model.parameters())
    opt = handle.wrap_optimizer(FusedSGD(params, lr=0.1))
    crit = nn.CrossEntropyLoss()
    x, y = _data()
    before = [np.asarray(p.data) for p in params]
    scale0 = opt._loss_scaler[0].loss_scale()

    loss = crit(model(x), y) * 1.0e38  # scaled grads overflow to inf
    with opt.scale_loss(loss) as scaled:
        scaled.backward()
    assert opt._skip_next[0] is True
    opt.step()          # must be skipped
    opt.zero_grad()
    handle._deactivate()

    for p, b in zip(params, before):
        np.testing.assert_array_equal(np.asarray(p.data), b)
    assert opt._loss_scaler[0].loss_scale() == scale0 / 2.0
    assert opt._skip_next[0] is False  # reset by step()


def test_overflow_streak_halves_scale_each_skip():
    """Scaler edge dynamics BadStepGuard layers on: a STREAK of overflows
    halves the scale once per skipped step (2^16 → 2^13 after three) and
    never touches the params; the first clean step then applies."""
    handle = amp.init()
    model = _model()
    params = list(model.parameters())
    opt = handle.wrap_optimizer(FusedSGD(params, lr=0.1))
    crit = nn.CrossEntropyLoss()
    x, y = _data()
    before = [np.asarray(p.data) for p in params]

    for k in range(1, 4):
        loss = crit(model(x), y) * 1.0e38
        with opt.scale_loss(loss) as scaled:
            scaled.backward()
        opt.step()
        opt.zero_grad()
        assert opt._loss_scaler[0].loss_scale() == 2.0 ** (16 - k)
        for p, b in zip(params, before):
            np.testing.assert_array_equal(np.asarray(p.data), b)

    loss = crit(model(x), y)
    with opt.scale_loss(loss) as scaled:
        scaled.backward()
    opt.step()
    handle._deactivate()
    assert opt._loss_scaler[0].loss_scale() == 2.0 ** 13  # unchanged: clean
    assert any(not np.array_equal(np.asarray(p.data), b)
               for p, b in zip(params, before))           # step applied


def test_guard_observes_reference_exact_skip_patching():
    """BadStepGuard on the NON-deferred eager surface: the skip decision
    is host-known (the one-shot step patch), so the guard sees the streak
    without any device flag in the picture."""
    from apex_tpu.runtime.resilience import (BadStepGuard,
                                             TrainingDivergedError)

    from apex_tpu import amp as amp_mod
    from apex_tpu.amp._amp_state import reset
    from apex_tpu.optimizers import FusedAdam

    reset()
    model = _model()
    opt = FusedAdam(list(model.parameters()), lr=1e-3)
    model, opt = amp_mod.initialize(model, opt, opt_level="O2", verbosity=0)
    guard = BadStepGuard(patience=2, policy="raise")
    guard.attach_optimizer(opt)
    crit = nn.CrossEntropyLoss()
    x, y = _data()

    with pytest.raises(TrainingDivergedError):
        for _ in range(4):
            loss = crit(model(x), y) * 1.0e38
            with amp_mod.scale_loss(loss, opt) as scaled:
                scaled.backward()
            opt.step()
            opt.zero_grad()
        guard.flush()
    assert guard.stats["skipped"] >= 2
    reset()


def test_disabled_handle_is_passthrough():
    handle = amp.init(enabled=False)
    assert not handle.is_active()
    model = _model()
    opt = handle.wrap_optimizer(FusedSGD(list(model.parameters()), lr=0.1))
    crit = nn.CrossEntropyLoss()
    x, y = _data()
    loss = crit(model(x), y)
    with opt.scale_loss(loss) as scaled:
        assert scaled is loss
        scaled.backward()
    opt.step()


def test_attribute_forwarding():
    handle = amp.init(enabled=False)
    inner = FusedSGD([nn.Parameter(jnp.zeros((2, 2)))], lr=0.25)
    opt = handle.wrap_optimizer(inner)
    assert opt.param_groups is inner.param_groups
    assert opt.param_groups[0]["lr"] == 0.25


def test_closure_rejected():
    handle = amp.init()
    opt = handle.wrap_optimizer(
        FusedSGD([nn.Parameter(jnp.zeros((2, 2)))], lr=0.1))
    with pytest.raises(NotImplementedError):
        opt.step(closure=lambda: None)
    handle._deactivate()


def test_disable_casts_suppresses_ambient_policy():
    """Inside handle._disable_casts (and the free amp.disable_casts) module
    forwards must NOT be cast by the ambient O1 policy."""
    handle = amp.init()
    model = _model()
    x, _ = _data()
    out = model(x)
    assert out.dtype == jnp.float16  # ambient policy casts the linears
    with handle._disable_casts():
        out_fp32 = model(x)
    assert out_fp32.dtype == jnp.float32
    with amp.disable_casts():
        out_fp32 = model(x)
    assert out_fp32.dtype == jnp.float32
    out = model(x)
    assert out.dtype == jnp.float16  # restored after the scopes
    handle._deactivate()


def test_disable_casts_exception_safe():
    handle = amp.init()
    with pytest.raises(ValueError):
        with handle._disable_casts():
            raise ValueError("boom")
    assert handle.is_active()
    handle._deactivate()


def test_static_loss_scale_threads_through():
    handle = amp.init(loss_scale=128.0)
    model = _model()
    opt = handle.wrap_optimizer(FusedSGD(list(model.parameters()), lr=0.1))
    assert opt._loss_scaler[0].dynamic is False
    assert opt._loss_scaler[0].loss_scale() == 128.0
    crit = nn.CrossEntropyLoss()
    x, y = _data()
    loss = crit(model(x), y)
    with opt.scale_loss(loss) as scaled:
        np.testing.assert_allclose(float(scaled), float(loss) * 128.0,
                                   rtol=1e-6)
        scaled.backward()
    opt.step()
    handle._deactivate()
