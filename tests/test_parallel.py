"""Distributed layer tests on the 8-device CPU mesh — the analogue of the
reference's tests/distributed/ suite (synced_batchnorm unit tests, DDP
validation, amp_master_params cross-replica equality)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import apex_tpu.nn as nn
from apex_tpu import parallel
from apex_tpu.nn import functional as F
from apex_tpu.optimizers import FusedSGD
from apex_tpu.parallel import (DistributedDataParallel, Reducer,
                               SyncBatchNorm, convert_syncbn_model,
                               create_syncbn_process_group)
from apex_tpu.training import make_train_step


def _mesh():
    return Mesh(np.array(jax.devices()), ("data",))


# ---------------------------------------------------------------------------
# SyncBatchNorm (reference tests/distributed/synced_batchnorm/)
# ---------------------------------------------------------------------------

def test_syncbn_matches_fullbatch_bn_under_shard_map():
    """8 shards x batch 2 with SyncBN must equal single-device batch-16 BN
    (the reference's two_gpu_unit_test.py oracle)."""
    def make(sync):
        nn.manual_seed(42)
        bn = SyncBatchNorm(4) if sync else nn.BatchNorm2d(4)
        return nn.Sequential(nn.Conv2d(3, 4, 3, padding=1), bn, nn.ReLU(),
                             nn.Flatten(), nn.Linear(4 * 8 * 8, 5))

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((16, 3, 8, 8)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 5, (16,)))

    # single device full batch (plain BN == global stats)
    model_a = make(sync=False)
    opt_a = FusedSGD(list(model_a.parameters()), lr=0.1, momentum=0.9)
    single = make_train_step(model_a, opt_a,
                             lambda o, yy: F.cross_entropy(o, yy))
    for _ in range(3):
        single(x, y)

    # 8-way sharded with SyncBN
    model_b = make(sync=True)
    opt_b = FusedSGD(list(model_b.parameters()), lr=0.1, momentum=0.9)
    ddp = make_train_step(model_b, opt_b,
                          lambda o, yy: F.cross_entropy(o, yy),
                          axis_name="data")
    sharded = jax.jit(jax.shard_map(
        ddp._step_fn, mesh=_mesh(),
        in_specs=(P(), P("data"), P("data")), out_specs=(P(), P()),
        check_vma=False))
    state = ddp.state
    for _ in range(3):
        state, _ = sharded(state, x, y)

    for a, b in zip(single.state.master_params, state.master_params):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-4, atol=1e-5)
    # running stats must match the full-batch run too
    rm_a = [s for s in single.state.stats]
    rm_b = [s for s in state.stats]
    for a, b in zip(rm_a, rm_b):
        np.testing.assert_allclose(np.asarray(a, np.float32),
                                   np.asarray(b, np.float32),
                                   rtol=2e-4, atol=1e-5)


def test_syncbn_group_stats_stay_local():
    """With process groups of size 4, stats sync only within each group
    (reference test_groups.py)."""
    groups = create_syncbn_process_group(4)
    assert groups == [[0, 1, 2, 3], [4, 5, 6, 7]]

    bn = SyncBatchNorm(2, process_group=groups)
    # shard-dependent input: group 0 shards see 1.0, group 1 shards see 3.0
    x = jnp.concatenate([jnp.full((8, 2, 2, 2), 1.0),
                         jnp.full((8, 2, 2, 2), 3.0)])

    from apex_tpu.nn.modules import Ctx

    def fwd(xs):
        ctx = Ctx(env={}, stats_out={}, training=True)
        return bn.forward(ctx, xs)

    out = jax.jit(jax.shard_map(
        fwd, mesh=_mesh(), in_specs=P("data"), out_specs=P("data"),
        check_vma=False))(x)
    # within each group input is constant -> normalized output ~ 0
    np.testing.assert_allclose(np.asarray(out), 0.0, atol=1e-3)


def test_create_syncbn_process_group_validation():
    with pytest.raises(ValueError):
        create_syncbn_process_group(3)  # 8 % 3 != 0
    with pytest.raises(ValueError):
        create_syncbn_process_group(16)
    assert create_syncbn_process_group(0) is None
    assert create_syncbn_process_group(8) is None
    assert create_syncbn_process_group(2) == [[0, 1], [2, 3], [4, 5], [6, 7]]


def test_convert_syncbn_model_preserves_state():
    nn.manual_seed(1)
    model = nn.Sequential(nn.Conv2d(3, 4, 3), nn.BatchNorm2d(4), nn.ReLU(),
                          nn.Sequential(nn.BatchNorm1d(7)))
    model[1].running_mean.data = jnp.full((4,), 2.5)
    w = np.asarray(model[1].weight.data)
    converted = convert_syncbn_model(model)
    assert isinstance(converted[1], SyncBatchNorm)
    assert isinstance(converted[3][0], SyncBatchNorm)
    np.testing.assert_array_equal(np.asarray(converted[1].running_mean.data),
                                  2.5)
    np.testing.assert_array_equal(np.asarray(converted[1].weight.data), w)
    # non-BN modules untouched
    assert isinstance(converted[0], nn.Conv2d)


# ---------------------------------------------------------------------------
# DistributedDataParallel facade
# ---------------------------------------------------------------------------

def test_ddp_option_validation():
    nn.manual_seed(0)
    m = nn.Sequential(nn.Linear(4, 4))
    with pytest.raises(ValueError):
        DistributedDataParallel(m, shared_param=True)
    with pytest.raises(ValueError):
        DistributedDataParallel(m, delay_allreduce=True,
                                num_allreduce_streams=2)
    with pytest.raises(ValueError):
        DistributedDataParallel(
            m, delay_allreduce=True,
            allreduce_trigger_params=[list(m.parameters())[0]])


def test_ddp_imperative_training_with_sharded_batch():
    """DDP wrapper: replicated params, sharded batch; imperative tape
    training works and grads/params stay replicated across devices."""
    nn.manual_seed(3)
    model = nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))
    opt = FusedSGD(list(model.parameters()), lr=0.1, momentum=0.9)
    ddp = DistributedDataParallel(model, mesh=_mesh())
    crit = nn.CrossEntropyLoss()
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((16, 8)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 4, (16,)))
    losses = []
    for _ in range(5):
        out = ddp(x)
        loss = crit(out, y)
        loss.backward()
        opt.step()
        opt.zero_grad(set_to_none=True)
        losses.append(float(loss))
    assert losses[-1] < losses[0]
    for p in model.parameters():
        assert p.data.sharding.is_fully_replicated


def test_ddp_matches_single_device_run():
    nn.manual_seed(3)

    def build():
        nn.manual_seed(7)
        return nn.Sequential(nn.Linear(8, 16), nn.ReLU(), nn.Linear(16, 4))

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((16, 8)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 4, (16,)))
    crit = nn.CrossEntropyLoss()

    def train(model):
        opt = FusedSGD(list(model.parameters()), lr=0.1, momentum=0.9)
        out_losses = []
        for _ in range(4):
            out = model(x)
            loss = crit(out, y)
            loss.backward()
            opt.step()
            opt.zero_grad(set_to_none=True)
            out_losses.append(float(loss))
        return out_losses

    base = train(build())
    ddp_losses = train(DistributedDataParallel(build(), mesh=_mesh()))
    np.testing.assert_allclose(ddp_losses, base, rtol=1e-5)


def test_ddp_after_amp_applies_casts():
    """Regression: DDP wrapped around an amp-O2 model must still apply the
    input cast (the tags live on the inner module; the wrapper mirrors
    them)."""
    from apex_tpu import amp
    from apex_tpu.amp._amp_state import _amp_state
    _amp_state.opt_properties = None
    _amp_state.ambient_policy = None

    nn.manual_seed(0)
    model = nn.Sequential(nn.Conv2d(3, 4, 3, padding=1), nn.Flatten(),
                          nn.Linear(4 * 8 * 8, 5))
    opt = FusedSGD(list(model.parameters()), lr=0.05)
    model, opt = amp.initialize(model, opt, opt_level="O2",
                                cast_model_type="bfloat16", verbosity=0)
    ddp = DistributedDataParallel(model, mesh=_mesh())
    x = jnp.ones((8, 3, 8, 8), jnp.float32)
    out = ddp(x)  # crashes with a dtype mismatch if the cast tag is lost
    assert out.dtype == jnp.float32  # output cast back to fp32


def test_reducer_identity_on_replicated():
    nn.manual_seed(0)
    model = nn.Sequential(nn.Linear(4, 4))
    p = list(model.parameters())[0]
    p.grad = jnp.ones(p.shape, jnp.float32)
    red = Reducer(model, mesh=_mesh())
    red.reduce()
    np.testing.assert_array_equal(np.asarray(p.grad), 1.0)


def test_all_reduce_predivide_keeps_fp16_finite():
    """The predivide knob must observably change the collective's scaling
    order: near-max fp16 grads summed over 8 replicas overflow without it,
    and stay finite with predivide_factor=world_size (the knob's purpose,
    reference distributed.py:445-454)."""
    mesh = _mesh()
    big = jnp.full((8, 4), 60000.0, jnp.float16)  # fp16 max is 65504
    sharded = jax.device_put(big, jax.sharding.NamedSharding(mesh, P("data")))

    (plain,) = parallel.all_reduce_mean([sharded], mesh)
    assert not np.all(np.isfinite(np.asarray(plain, np.float32)))

    (pre,) = parallel.all_reduce_mean([sharded], mesh, predivide_factor=8.0)
    np.testing.assert_allclose(np.asarray(pre, np.float32), 60000.0,
                               rtol=1e-3)


def test_all_reduce_always_fp32_changes_collective_dtype():
    """allreduce_always_fp32 must change the collective's dtype observably:
    an fp16 psum whose sum exceeds fp16 max goes non-finite, while the fp32
    collective (sum 480000 in fp32, mean 60000 cast back) stays finite."""
    mesh = _mesh()
    sharded = jax.device_put(jnp.full((8, 4), 60000.0, jnp.float16),
                             jax.sharding.NamedSharding(mesh, P("data")))

    (fp16,) = parallel.all_reduce_mean([sharded], mesh)
    (fp32,) = parallel.all_reduce_mean([sharded], mesh, always_fp32=True)
    assert fp32.dtype == jnp.float16  # cast back after the collective
    assert not np.all(np.isfinite(np.asarray(fp16, np.float32)))
    np.testing.assert_allclose(np.asarray(fp32, np.float32), 60000.0)


def test_ddp_allreduce_gradients_honors_knobs():
    """The DDP wrapper's recorded knobs must route into its explicit
    gradient exchange (round 1: knobs were recorded, never exercised)."""
    mesh = _mesh()
    nn.manual_seed(0)
    model = nn.Sequential(nn.Linear(4, 4, bias=False))
    ddp = DistributedDataParallel(model, mesh=mesh,
                                  gradient_predivide_factor=8.0,
                                  allreduce_always_fp32=True)
    p = list(model.parameters())[0]
    per_replica = jnp.full((8, 4), 60000.0, jnp.float16)
    p.grad = jax.device_put(per_replica,
                            jax.sharding.NamedSharding(mesh, P("data")))
    ddp.allreduce_gradients()
    np.testing.assert_allclose(np.asarray(p.grad, np.float32), 60000.0,
                               rtol=1e-3)

    # and gradient_average=False → pure psum (sum, not mean)
    model2 = nn.Sequential(nn.Linear(4, 4, bias=False))
    ddp2 = DistributedDataParallel(model2, mesh=mesh, gradient_average=False)
    p2 = list(model2.parameters())[0]
    p2.grad = jax.device_put(jnp.ones((8, 4), jnp.float32),
                             jax.sharding.NamedSharding(mesh, P("data")))
    ddp2.allreduce_gradients()
    np.testing.assert_allclose(np.asarray(p2.grad), 8.0)


def test_reducer_honors_knobs():
    mesh = _mesh()
    grads = [jax.device_put(jnp.full((8, 2), 60000.0, jnp.float16),
                            jax.sharding.NamedSharding(mesh, P("data")))]
    red = Reducer(grads, mesh=mesh, gradient_predivide_factor=8.0)
    red.reduce()
    np.testing.assert_allclose(np.asarray(red.grads[0], np.float32), 60000.0,
                               rtol=1e-3)


def test_all_reduce_mean_sharded():
    mesh = _mesh()
    vals = jnp.arange(8.0).reshape(8, 1)
    sharded = jax.device_put(
        vals, jax.sharding.NamedSharding(mesh, P("data")))
    (out,) = parallel.all_reduce_mean([sharded], mesh)
    np.testing.assert_allclose(np.asarray(out), 3.5)


def test_world_size_rank():
    assert parallel.world_size() == 8
    assert parallel.rank() == 0
