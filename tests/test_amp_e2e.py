"""End-to-end amp.initialize + scale_loss training across opt levels —
the analogue of the reference's L1 cross-product tests (tests/L1/common/):
loss curves must be finite and close across O0/O1/O2/O3, O2 must keep fp32
masters + fp16 model, and the overflow path must skip steps."""
import jax.numpy as jnp
import numpy as np
import pytest

import apex_tpu.nn as nn
from apex_tpu import amp
from apex_tpu.amp._amp_state import _amp_state
from apex_tpu.optimizers import FusedAdam, FusedSGD


def _reset_amp():
    from apex_tpu.amp._amp_state import reset as _r
    _r()


def _small_model():
    nn.manual_seed(42)
    return nn.Sequential(
        nn.Conv2d(3, 8, 3, padding=1), nn.BatchNorm2d(8), nn.ReLU(),
        nn.MaxPool2d(2), nn.Flatten(), nn.Linear(8 * 8 * 8, 10))


def _data(seed=0):
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.standard_normal((8, 3, 16, 16)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 10, (8,)))
    return x, y


def _train(opt_level, steps=6, make_opt=None, **init_kw):
    _reset_amp()
    model = _small_model()
    make_opt = make_opt or (lambda ps: FusedSGD(ps, lr=0.05, momentum=0.9))
    opt = make_opt(list(model.parameters()))
    model, opt = amp.initialize(model, opt, opt_level=opt_level, verbosity=0,
                                **init_kw)
    crit = nn.CrossEntropyLoss()
    x, y = _data()
    losses = []
    for _ in range(steps):
        out = model(x)
        loss = crit(out, y)
        with amp.scale_loss(loss, opt) as scaled_loss:
            scaled_loss.backward()
        opt.step()
        opt.zero_grad()
        losses.append(float(loss))
    return model, opt, losses


@pytest.mark.parametrize("opt_level", ["O0", "O1", "O2", "O3"])
def test_loss_decreases(opt_level):
    _, _, losses = _train(opt_level)
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]


def test_opt_levels_agree_with_O0():
    _, _, base = _train("O0")
    for level in ["O1", "O2"]:
        _, _, other = _train(level)
        # half precision diverges slowly; first few steps should track O0
        np.testing.assert_allclose(other[:3], base[:3], rtol=0.05)


def test_O2_structure():
    model, opt, _ = _train("O2")
    # model params half (conv idx 0, linear idx 5), BN (idx 1) fp32
    assert model[0].weight.dtype == jnp.float16
    assert model[5].weight.dtype == jnp.float16
    assert model[1].weight.dtype == jnp.float32
    masters = opt.param_groups[0]["params"]
    assert all(p.dtype == jnp.float32 for p in masters)
    # model.state_dict() reports fp32 (O2StateDictHook analogue)
    assert all(v.dtype == jnp.float32
               for v in model.state_dict().values()
               if jnp.issubdtype(v.dtype, jnp.floating))


def test_O2_keeps_batchnorm_fp32():
    _reset_amp()
    model = _small_model()
    opt = FusedSGD(list(model.parameters()), lr=0.05)
    model, opt = amp.initialize(model, opt, opt_level="O2", verbosity=0)
    bn = model[1]
    assert bn.weight.dtype == jnp.float32
    assert model[0].weight.dtype == jnp.float16


def test_O3_casts_everything():
    _reset_amp()
    model = _small_model()
    opt = FusedSGD(list(model.parameters()), lr=0.05)
    model, opt = amp.initialize(model, opt, opt_level="O3", verbosity=0)
    assert model[1].weight.dtype == jnp.float16


def test_bfloat16_via_cast_model_type():
    _reset_amp()
    model = _small_model()
    opt = FusedSGD(list(model.parameters()), lr=0.05)
    model, opt = amp.initialize(model, opt, opt_level="O2",
                                cast_model_type="bfloat16", verbosity=0)
    assert model[0].weight.dtype == jnp.bfloat16
    x, y = _data()
    out = model(x)
    loss = nn.CrossEntropyLoss()(out, y)
    with amp.scale_loss(loss, opt) as scaled_loss:
        scaled_loss.backward()
    opt.step()
    assert np.isfinite(float(loss))


def test_overflow_skips_step_and_halves_scale():
    _reset_amp()
    model = _small_model()
    opt = FusedSGD(list(model.parameters()), lr=0.05)
    model, opt = amp.initialize(model, opt, opt_level="O2", verbosity=0)
    x, y = _data()
    out = model(x)
    loss = nn.CrossEntropyLoss()(out, y)
    with amp.scale_loss(loss, opt) as scaled_loss:
        scaled_loss.backward()
        # sabotage: plant inf in a model grad before unscale
        p16 = opt._amp_stash.all_fp16_params[0]
        p16.grad = p16.grad.at[(0,) * p16.grad.ndim].set(np.inf)
    masters_before = [np.asarray(p.data)
                      for p in opt.param_groups[0]["params"]]
    opt.step()   # patched to skip
    for p, before in zip(opt.param_groups[0]["params"], masters_before):
        np.testing.assert_array_equal(np.asarray(p.data), before)
    assert _amp_state.loss_scalers[0].loss_scale() == 2.0 ** 15
    # next step proceeds normally (one-shot patch restored)
    out = model(x)
    loss = nn.CrossEntropyLoss()(out, y)
    with amp.scale_loss(loss, opt) as scaled_loss:
        scaled_loss.backward()
    opt.step()
    changed = any(
        not np.array_equal(np.asarray(p.data), b)
        for p, b in zip(opt.param_groups[0]["params"], masters_before))
    assert changed


def test_O1_banned_bce_raises():
    _reset_amp()
    nn.manual_seed(0)
    model = nn.Sequential(nn.Linear(4, 1), nn.Sigmoid())
    opt = FusedSGD(list(model.parameters()), lr=0.1)
    model, opt = amp.initialize(model, opt, opt_level="O1", verbosity=0)
    x = jnp.ones((4, 4), jnp.float32)
    t = jnp.ones((4, 1), jnp.float32)
    out = model(x)
    # the criterion is NOT tagged: the ambient O1 policy must cover it,
    # as global torch patching does in the reference
    crit = nn.BCELoss()
    with pytest.raises(NotImplementedError):
        crit(out, t)


def test_multiple_losses_per_loss_scalers():
    _reset_amp()
    model = _small_model()
    opt = FusedSGD(list(model.parameters()), lr=0.05)
    model, opt = amp.initialize(model, opt, opt_level="O2", num_losses=3,
                                verbosity=0)
    assert len(_amp_state.loss_scalers) == 3
    x, y = _data()
    for loss_id in range(3):
        out = model(x)
        loss = nn.CrossEntropyLoss()(out, y)
        with amp.scale_loss(loss, opt, loss_id=loss_id) as scaled_loss:
            scaled_loss.backward()
        opt.step()
        opt.zero_grad()
    sd = amp.state_dict()
    assert set(sd) == {"loss_scaler0", "loss_scaler1", "loss_scaler2"}


def test_initialize_twice_rejected():
    _reset_amp()
    model = _small_model()
    opt = FusedSGD(list(model.parameters()), lr=0.05)
    model, opt = amp.initialize(model, opt, opt_level="O1", verbosity=0)
    with pytest.raises(RuntimeError):
        amp.initialize(model, opt, opt_level="O1", verbosity=0)


def test_enabled_false_passthrough():
    _reset_amp()
    model = _small_model()
    opt = FusedSGD(list(model.parameters()), lr=0.05)
    m2, o2 = amp.initialize(model, opt, enabled=False)
    assert m2 is model and o2 is opt


def test_fused_adam_O2():
    _, _, losses = _train(
        "O2", make_opt=lambda ps: FusedAdam(ps, lr=1e-3))
    assert losses[-1] < losses[0]


def test_transformer_through_imperative_amp_O2():
    """The imperative path (amp.initialize O2 + scale_loss + FusedLAMB)
    trains a transformer — flash attention and FusedLayerNorm under the
    tape, fp32 masters behind bf16 model params."""
    _reset_amp()
    from apex_tpu.models import BertModel

    nn.manual_seed(7)
    V = 67
    bert = BertModel(vocab_size=V, hidden=32, layers=2, heads=4,
                     intermediate=64, max_positions=16, dropout=0.0,
                     attn_dropout=0.0)
    head = nn.Linear(32, V)

    class WithHead(nn.Module):
        def __init__(self):
            super().__init__()
            self.bert = bert
            self.head = head

        def forward(self, ctx, ids):
            return self.head.forward(ctx, self.bert.forward(ctx, ids))

    model = WithHead()
    from apex_tpu.optimizers import FusedLAMB
    opt = FusedLAMB(list(model.parameters()), lr=5e-3)
    model, opt = amp.initialize(model, opt, opt_level="O2", verbosity=0,
                                cast_model_type=jnp.bfloat16,
                                loss_scale=1.0)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, V, (4, 16)))
    crit = nn.CrossEntropyLoss()
    losses = []
    for _ in range(10):
        out = model(ids)
        loss = crit(out.reshape((-1, V)), ids.reshape((-1,)))
        with amp.scale_loss(loss, opt) as scaled:
            scaled.backward()
        opt.step()
        opt.zero_grad()
        losses.append(float(loss))
    assert all(np.isfinite(l) for l in losses)
    assert losses[-1] < losses[0]
