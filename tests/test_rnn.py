"""apex_tpu.RNN vs torch.nn reference numerics (the reference has no RNN
tests; we hold ourselves to the L0 standard anyway — fused/scan
implementation vs unfused reference math, SURVEY.md §4.1)."""
import jax
import numpy as np
import jax.numpy as jnp
import pytest
import torch

import apex_tpu.RNN as RNN
from apex_tpu import nn


def _copy_lstm_weights(cell, t_rnn, layer):
    """Write torch layer-l LSTM/GRU weights into our RNNCell."""
    cell.w_ih.data = jnp.asarray(
        getattr(t_rnn, f"weight_ih_l{layer}").detach().numpy())
    cell.w_hh.data = jnp.asarray(
        getattr(t_rnn, f"weight_hh_l{layer}").detach().numpy())
    if cell.bias:
        cell.b_ih.data = jnp.asarray(
            getattr(t_rnn, f"bias_ih_l{layer}").detach().numpy())
        cell.b_hh.data = jnp.asarray(
            getattr(t_rnn, f"bias_hh_l{layer}").detach().numpy())


@pytest.mark.parametrize("num_layers", [1, 2])
def test_lstm_matches_torch(rng, num_layers):
    T, B, I, H = 5, 3, 4, 6
    model = RNN.LSTM(I, H, num_layers, bias=True)
    t_rnn = torch.nn.LSTM(I, H, num_layers, bias=True)
    for layer in range(num_layers):
        _copy_lstm_weights(model.rnns[layer], t_rnn, layer)

    x = rng.standard_normal((T, B, I)).astype(np.float32)
    out, (h, c) = model(jnp.asarray(x))
    t_out, (t_h, t_c) = t_rnn(torch.from_numpy(x))

    np.testing.assert_allclose(np.asarray(out.value),
                               t_out.detach().numpy(), atol=1e-5)
    np.testing.assert_allclose(np.asarray(h.value),
                               t_h.detach().numpy(), atol=1e-5)
    np.testing.assert_allclose(np.asarray(c.value),
                               t_c.detach().numpy(), atol=1e-5)


def test_gru_matches_torch(rng):
    T, B, I, H = 4, 2, 3, 5
    model = RNN.GRU(I, H, 2, bias=True)
    t_rnn = torch.nn.GRU(I, H, 2, bias=True)
    for layer in range(2):
        _copy_lstm_weights(model.rnns[layer], t_rnn, layer)

    x = rng.standard_normal((T, B, I)).astype(np.float32)
    out, (h,) = model(jnp.asarray(x))
    t_out, t_h = t_rnn(torch.from_numpy(x))
    np.testing.assert_allclose(np.asarray(out.value),
                               t_out.detach().numpy(), atol=1e-5)
    np.testing.assert_allclose(np.asarray(h.value),
                               t_h.detach().numpy(), atol=1e-5)


def test_bidirectional_lstm_output_matches_torch(rng):
    T, B, I, H = 5, 3, 4, 6
    model = RNN.LSTM(I, H, 1, bias=True, bidirectional=True)
    t_rnn = torch.nn.LSTM(I, H, 1, bias=True, bidirectional=True)
    _copy_lstm_weights(model.fwd.rnns[0], t_rnn, 0)
    model.bckwrd.rnns[0].w_ih.data = jnp.asarray(
        t_rnn.weight_ih_l0_reverse.detach().numpy())
    model.bckwrd.rnns[0].w_hh.data = jnp.asarray(
        t_rnn.weight_hh_l0_reverse.detach().numpy())
    model.bckwrd.rnns[0].b_ih.data = jnp.asarray(
        t_rnn.bias_ih_l0_reverse.detach().numpy())
    model.bckwrd.rnns[0].b_hh.data = jnp.asarray(
        t_rnn.bias_hh_l0_reverse.detach().numpy())

    x = rng.standard_normal((T, B, I)).astype(np.float32)
    out, _ = model(jnp.asarray(x))
    t_out, _ = t_rnn(torch.from_numpy(x))
    np.testing.assert_allclose(np.asarray(out.value),
                               t_out.detach().numpy(), atol=1e-5)


def test_mlstm_matches_reference_math(rng):
    """mLSTM against a hand-rolled numpy step loop (reference cell math,
    apex/RNN/cells.py:55-84)."""
    T, B, I, H = 4, 2, 3, 5
    model = RNN.mLSTM(I, H, 1, bias=True)
    cell = model.rnns[0]
    x = rng.standard_normal((T, B, I)).astype(np.float32)
    out, (h_fin, c_fin) = model(jnp.asarray(x))

    def sig(v):
        return 1.0 / (1.0 + np.exp(-v))

    w_ih = np.asarray(cell.w_ih.data)
    w_hh = np.asarray(cell.w_hh.data)
    w_mih = np.asarray(cell.w_mih.data)
    w_mhh = np.asarray(cell.w_mhh.data)
    b_ih = np.asarray(cell.b_ih.data)
    b_hh = np.asarray(cell.b_hh.data)
    h = np.zeros((B, H), np.float32)
    c = np.zeros((B, H), np.float32)
    outs = []
    for t in range(T):
        m = (x[t] @ w_mih.T) * (h @ w_mhh.T)
        gates = x[t] @ w_ih.T + b_ih + m @ w_hh.T + b_hh
        i, f, g, o = np.split(gates, 4, axis=-1)
        c = sig(f) * c + sig(i) * np.tanh(g)
        h = sig(o) * np.tanh(c)
        outs.append(h)
    np.testing.assert_allclose(np.asarray(out.value), np.stack(outs),
                               atol=1e-5)
    np.testing.assert_allclose(np.asarray(h_fin.value)[0], h, atol=1e-5)


def test_hidden_state_persists_and_resets(rng):
    T, B, I, H = 3, 2, 4, 4
    model = RNN.LSTM(I, H, 1)
    x = jnp.asarray(rng.standard_normal((T, B, I)).astype(np.float32))
    out1, _ = model(x)
    h_after = model.rnns[0].hidden[0]
    assert float(jnp.abs(h_after).sum()) > 0
    out2, _ = model(x)  # different because carry persisted
    assert not np.allclose(np.asarray(out1.value), np.asarray(out2.value))
    model.reset_hidden(B)
    out3, _ = model(x)
    np.testing.assert_allclose(np.asarray(out1.value),
                               np.asarray(out3.value), atol=1e-6)


def test_collect_hidden_shapes(rng):
    T, B, I, H, L = 4, 2, 3, 5, 2
    model = RNN.LSTM(I, H, L)
    x = jnp.asarray(rng.standard_normal((T, B, I)).astype(np.float32))
    out, hiddens = model(x, collect_hidden=True)
    h_states, c_states = hiddens
    assert len(h_states.value) == T
    assert h_states[0].shape == (L, B, H)
    assert c_states[T - 1].shape == (L, B, H)


def test_rnn_backward_fills_grads(rng):
    T, B, I, H = 4, 2, 3, 5
    model = RNN.GRU(I, H, 2, bias=True)
    x = jnp.asarray(rng.standard_normal((T, B, I)).astype(np.float32))
    out, _ = model(x)
    loss = (out * out).mean()
    loss.backward()
    for p in model.parameters():
        assert p.grad is not None
        assert float(jnp.abs(p.grad).sum()) > 0


def test_backward_uses_the_h0_of_its_own_forward(rng):
    """Regression: forward mutates the stored hidden state; backward's
    re-execution must see the PRE-forward h0 (threaded as tape inputs), not
    the mutated finals — checked against jax.grad on the pure scan."""
    T, B, I, H = 4, 2, 3, 5
    model = RNN.LSTM(I, H, 1, bias=True)
    cell = model.rnns[0]
    x = jnp.asarray(rng.standard_normal((T, B, I)).astype(np.float32))

    out1, _ = model(x)            # from zero state; mutates cell.hidden
    out2, _ = model(x)            # from persisted state
    h0 = [jnp.asarray(h) for h in cell.hidden]  # pre-third-call state
    out3, _ = model(x)
    loss = (out3 * out3).mean()
    eager_loss = float(loss.value)
    loss.backward()
    got = np.asarray(cell.w_ih.grad)

    def pure_loss(w_ih):
        def body(carry, x_t):
            hx, cx = carry
            gates = x_t @ w_ih.T + cell.b_ih.data + \
                hx @ cell.w_hh.data.T + cell.b_hh.data
            i, f, g, o = jnp.split(gates, 4, axis=-1)
            cy = jax.nn.sigmoid(f) * cx + jax.nn.sigmoid(i) * jnp.tanh(g)
            hy = jax.nn.sigmoid(o) * jnp.tanh(cy)
            return (hy, cy), hy
        _, ys = jax.lax.scan(body, (h0[0], h0[1]), x)
        return (ys * ys).mean()

    want_loss = float(pure_loss(cell.w_ih.data))
    want = np.asarray(jax.grad(pure_loss)(cell.w_ih.data))
    assert abs(eager_loss - want_loss) < 1e-6
    np.testing.assert_allclose(got, want, atol=1e-5)


def test_rnn_training_converges(rng):
    """Tiny seq task: predict next value of a noiseless sine — loss must
    drop (end-to-end through scan + tape + optimizer)."""
    from apex_tpu.optimizers import FusedAdam
    T, B, H = 16, 8, 16
    model = RNN.LSTM(1, H, 1, bias=True, output_size=1)
    opt = FusedAdam(list(model.parameters()), lr=1e-2)
    t = np.linspace(0, 2 * np.pi, T + 1)[:, None]
    phases = rng.uniform(0, 2 * np.pi, (1, B))
    sig = np.sin(t + phases).astype(np.float32)[:, :, None]
    x, y = jnp.asarray(sig[:-1]), jnp.asarray(sig[1:])
    losses = []
    for i in range(30):
        model.reset_hidden(B)
        out, _ = model(x)
        loss = ((out - y) * (out - y)).mean()
        loss.backward()
        opt.step()
        opt.zero_grad()
        losses.append(float(loss.value))
    assert losses[-1] < 0.5 * losses[0]
