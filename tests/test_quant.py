"""Weight-only int8 quantization (apex_tpu/inference/quant.py): per-row
absmax round-trip error bounds, quantized-forward closeness on the
GPT/Llama families, the KV-cache decode path over int8 weights, int8
device residency, and the train-step rejection of quantized models."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

from apex_tpu.inference import (QuantTensor, quantize_int8,
                                quantize_tensor_int8)


def test_roundtrip_error_bounded(rng):
    x = jnp.asarray(rng.standard_normal((64, 128)), jnp.float32)
    qt = quantize_tensor_int8(x)
    assert qt.q.dtype == jnp.int8
    assert qt.scale.shape == (64, 1)
    err = np.abs(np.asarray(qt.dequant()) - np.asarray(x))
    # symmetric absmax: per-row max error <= scale/2 = absmax/254
    bound = np.abs(np.asarray(x)).max(axis=1, keepdims=True) / 254 + 1e-7
    assert (err <= bound).all()


def test_roundtrip_bound_holds_for_bf16(rng):
    """bf16 checkpoints: the quantizer rounds against the STORED
    (bf16-cast) scale, so the absmax/254 bound survives the cast (plus
    bf16 resolution on the product)."""
    x = jnp.asarray(rng.standard_normal((32, 256)), jnp.bfloat16)
    qt = quantize_tensor_int8(x)
    assert qt.scale.dtype == jnp.bfloat16
    xf = np.asarray(x, np.float32)
    err = np.abs(np.asarray(qt.dequant(), np.float32) - xf)
    # quantization error (absmax/254) + bf16 rounding of the dequant
    # product (~2^-8 relative)
    bound = np.abs(xf).max(axis=1, keepdims=True) * (1 / 254 + 1 / 256) \
        + 1e-6
    assert (err <= bound).all()


def test_extreme_rows_keep_precision(rng):
    """Per-ROW scales: a huge row does not destroy a small row's
    resolution (the reason scales are not per-tensor)."""
    x = np.ones((2, 256), np.float32)
    x[0] *= 1e4
    x[1] *= 1e-4
    qt = quantize_tensor_int8(jnp.asarray(x))
    back = np.asarray(qt.dequant())
    np.testing.assert_allclose(back, x, rtol=1e-2)


def test_rejects_1d():
    with pytest.raises(ValueError, match="1-D"):
        quantize_tensor_int8(jnp.ones((128,)))


def test_quantize_model_selects_matrices(rng):
    from apex_tpu.models.llama import llama_tiny

    model = llama_tiny()
    norm_shapes = {id(blk.ln1.weight) for blk in model.blocks}
    quantize_int8(model, min_size=1)
    for p in model.parameters():
        if p.ndim >= 2:
            assert isinstance(p.data, QuantTensor), "matrix not quantized"
            assert p.data.q.dtype == jnp.int8
        else:
            assert not isinstance(p.data, QuantTensor), "1-D quantized"
    assert not model.training
    # idempotent: re-quantizing quantized weights is a no-op, and with
    # every matrix already converted there is nothing left -> loud error
    with pytest.raises(ValueError, match="nothing was quantized"):
        quantize_int8(model, min_size=1)


@pytest.mark.parametrize("family", ["gpt", "llama"])
def test_quantized_forward_close(rng, family):
    """Quantized logits track full-precision logits closely enough to
    keep next-token argmax mostly unchanged (tiny models; real models
    tolerate w8 better, not worse)."""
    if family == "gpt":
        from apex_tpu.models.gpt import GptModel
        import apex_tpu.nn as nn
        nn.manual_seed(0)
        model = GptModel(vocab_size=211, hidden=64, layers=2, heads=4,
                         max_positions=32, dropout=0.0)
        ids = jnp.asarray(rng.integers(0, 211, (2, 16)))
    else:
        from apex_tpu.models.llama import llama_tiny
        import apex_tpu.nn as nn
        nn.manual_seed(0)
        model = llama_tiny()
        ids = jnp.asarray(rng.integers(0, 1000, (2, 16)))
    model.eval()
    want = np.asarray(model(ids).value)
    quantize_int8(model, min_size=1)
    got = np.asarray(model(ids).value)
    # relative closeness of the logit vectors, and argmax agreement on
    # most positions
    rel = np.abs(got - want).max() / (np.abs(want).max() + 1e-9)
    assert rel < 0.12, f"quantized logits off by {rel:.3f}"
    agree = (got.argmax(-1) == want.argmax(-1)).mean()
    assert agree > 0.9, f"argmax agreement {agree:.2f}"


def test_quantized_decode_matches_quantized_forward(rng):
    """generate() over int8 weights: the KV-cache decode reproduces the
    quantized model's own full-forward argmax continuation."""
    from apex_tpu.models.llama import llama_tiny
    from apex_tpu.models.gpt import generate
    import apex_tpu.nn as nn

    nn.manual_seed(0)
    model = llama_tiny()
    quantize_int8(model, min_size=1)
    prompt = jnp.asarray(rng.integers(0, 1000, (2, 5)))
    out = generate(model, prompt, max_new_tokens=4)
    cur = prompt
    for _ in range(4):
        logits = model(cur).value
        cur = jnp.concatenate(
            [cur, jnp.argmax(logits[:, -1], axis=-1)[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(cur))


def test_quantized_weights_are_int8_resident(rng):
    """The memory claim: quantized parameters store int8 on device (plus
    one fp scale per row), not a dequantized copy."""
    from apex_tpu.models.llama import llama_tiny

    model = llama_tiny()
    full_bytes = sum(p.data.nbytes for p in model.parameters())
    quantize_int8(model, min_size=1)
    q_bytes = 0
    for p in model.parameters():
        if isinstance(p.data, QuantTensor):
            assert p.data.q.dtype == jnp.int8
            q_bytes += p.data.q.nbytes + p.data.scale.nbytes
        else:
            q_bytes += p.data.nbytes
    # f32 -> int8 (+scales): at least 3.5x smaller overall
    assert q_bytes < full_bytes / 3.5


def test_train_step_rejects_quantized_model(rng):
    from apex_tpu.models.llama import llama_tiny
    from apex_tpu.nn import functional as F
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.training import make_train_step

    model = llama_tiny()
    quantize_int8(model, min_size=1)
    opt = FusedAdam(list(model.parameters()), lr=1e-4)
    with pytest.raises(ValueError, match="inference-only"):
        make_train_step(
            model, opt,
            lambda logits, ids: jnp.mean(F.cross_entropy(
                logits[:, :-1].reshape(-1, 1000),
                ids[:, 1:].reshape(-1))))


def test_gather_rows_matches_dequant_gather(rng):
    """The int8-aware embedding gather equals dequantize-then-gather,
    and passes through untouched for unquantized params."""
    from apex_tpu.inference import gather_rows
    from apex_tpu.nn.modules import Ctx
    from apex_tpu.nn.parameter import Parameter

    table = jnp.asarray(rng.standard_normal((64, 16)), jnp.float32)
    p = Parameter(table)
    ids = jnp.asarray(rng.integers(0, 64, (3, 5)))
    ctx = Ctx(env={id(p): p.data}, training=False)
    np.testing.assert_array_equal(
        np.asarray(gather_rows(ctx, p, ids)), np.asarray(table[ids]))

    p.data = quantize_tensor_int8(table)
    ctx = Ctx(env={id(p): p.data}, training=False)
    want = np.asarray(p.data.dequant())[np.asarray(ids)]
    got = np.asarray(gather_rows(ctx, p, ids))
    np.testing.assert_allclose(got, want, rtol=1e-6, atol=1e-7)
    # env-miss (eager) branch: resolution falls to p.data and still
    # takes the int8 gather
    got_eager = np.asarray(gather_rows(Ctx(training=False), p, ids))
    np.testing.assert_allclose(got_eager, want, rtol=1e-6, atol=1e-7)


def test_quantize_skips_lora_sources(rng):
    """LoRA factors / frozen bases are reparameterization SOURCES — they
    must stay full precision under quantize_int8 (the derived-weight
    closure reads them; quantizing a trainable rank factor is never
    intended).  Merging first quantizes the composed weight as usual."""
    from apex_tpu.models.llama import llama_tiny
    from apex_tpu.reparameterization import (LoRA, apply_lora,
                                             remove_reparameterization)

    model = llama_tiny()
    apply_lora(model, r=2)
    quantize_int8(model, min_size=1)
    for name, p in model.named_parameters():
        if name.endswith(("_w0", "_lora_a", "_lora_b")):
            assert not isinstance(p.data, QuantTensor), name
    # non-reparameterized matrices (embedding) still quantized
    assert isinstance(model.tok_emb.weight.data, QuantTensor)

    # the documented flow: merge, then quantize the composed weight
    model2 = llama_tiny()
    apply_lora(model2, r=2)
    remove_reparameterization(model2, LoRA, remove_all=True)
    quantize_int8(model2, min_size=1)
    assert all(isinstance(p.data, QuantTensor)
               for p in model2.parameters() if p.ndim >= 2)
