"""BatchNorm2d_NHWC — mirrors the reference's groupbn tests (NHWC BN vs
NCHW reference numerics; group stats over mesh sub-groups)."""
import jax
import jax.numpy as jnp
import numpy as np

import apex_tpu.nn as nn
from apex_tpu.contrib.groupbn import BatchNorm2d_NHWC
from apex_tpu.nn.modules import Ctx


def test_matches_nchw_batchnorm(rng):
    nn.manual_seed(0)
    c = 8
    bn_ref = nn.BatchNorm2d(c)
    bn_nhwc = BatchNorm2d_NHWC(c)
    x = jnp.asarray(rng.standard_normal((4, 5, 6, c)), jnp.float32)  # NHWC
    x_nchw = jnp.moveaxis(x, -1, 1)
    ctx1, ctx2 = Ctx(training=True), Ctx(training=True)
    y_ref = bn_ref.forward(ctx1, x_nchw)
    y = bn_nhwc.forward(ctx2, x)
    np.testing.assert_allclose(np.asarray(y),
                               np.asarray(jnp.moveaxis(y_ref, 1, -1)),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(bn_nhwc.running_mean.data),
                               np.asarray(bn_ref.running_mean.data),
                               rtol=1e-5, atol=1e-6)


def test_fuse_relu_and_add(rng):
    nn.manual_seed(0)
    c = 4
    bn = BatchNorm2d_NHWC(c, fuse_relu=True)
    x = jnp.asarray(rng.standard_normal((2, 3, 3, c)), jnp.float32)
    z = jnp.asarray(rng.standard_normal((2, 3, 3, c)), jnp.float32)
    y = bn.forward(Ctx(training=True), x, z)
    assert np.all(np.asarray(y) >= 0)  # relu applied after residual add


def test_group_stats_sync_on_mesh(rng):
    """bn_group=2 over an 8-device axis: stats shared within pairs only."""
    from jax.sharding import Mesh, PartitionSpec as P

    c = 4
    bn = BatchNorm2d_NHWC(c, bn_group=2, group_world_size=8)
    x = jnp.asarray(rng.standard_normal((16, 2, 2, c)), jnp.float32)

    devices = np.array(jax.devices()[:8])
    mesh = Mesh(devices, ("data",))

    def fwd(x):
        stats = {}
        ctx = Ctx(env={}, stats_out=stats, training=True)
        y = bn.forward(ctx, x)
        return y, stats[id(bn.running_mean)]

    y, rm = jax.jit(jax.shard_map(
        fwd, mesh=mesh, in_specs=P("data"),
        out_specs=(P("data"), P("data")), check_vma=False))(x)
    assert y.shape == x.shape
    rm = np.asarray(rm).reshape(8, c)
    # running means agree within each pair of devices, differ across pairs
    for g in range(4):
        np.testing.assert_allclose(rm[2 * g], rm[2 * g + 1], rtol=1e-5)
    assert not np.allclose(rm[0], rm[2])
