"""Multi-turn decode sessions (inference/session.py): persistent KV
caches across append/generate calls must reproduce the one-shot decode
of the concatenated history.

Oracle: ``generate(model, full_history, n)`` (the cache protocol's
one-shot driver).  Reference analogue: none (training-side library,
SURVEY.md §2)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import apex_tpu.nn as nn
from apex_tpu.inference import DecodeSession
from apex_tpu.models import GptModel
from apex_tpu.models.gpt import generate
from apex_tpu.models.llama import LlamaModel

V = 73


def _gpt(**kw):
    nn.manual_seed(6)
    return GptModel(vocab_size=V, hidden=32, layers=2, heads=4,
                    max_positions=96, dropout=0.0, attn_dropout=0.0, **kw)


def test_single_turn_matches_one_shot(rng):
    m = _gpt()
    m.eval()
    prompt = jnp.asarray(rng.integers(0, V, (1, 6)))
    want = np.asarray(generate(m, prompt, 8))[:, 6:]
    s = DecodeSession(m)
    s.append(prompt)
    got = np.asarray(s.generate(8))
    np.testing.assert_array_equal(got, want)
    assert s.position == 14


def test_multi_turn_matches_one_shot_of_history(rng):
    """The chat pattern: prompt -> model turn -> user turn -> model
    turn, never re-prefilling; equals one-shot decode of the full
    history."""
    m = _gpt()
    m.eval()
    p1 = jnp.asarray(rng.integers(0, V, (1, 5)))
    u2 = jnp.asarray(rng.integers(0, V, (1, 4)))

    s = DecodeSession(m)
    s.append(p1)
    g1 = s.generate(6)
    s.append(u2)
    g2 = np.asarray(s.generate(6))

    history = jnp.concatenate([p1, g1, u2], axis=1)
    want = np.asarray(generate(m, history, 6))[:, history.shape[1]:]
    np.testing.assert_array_equal(g2, want)


def test_back_to_back_generate_continues(rng):
    m = _gpt()
    m.eval()
    prompt = jnp.asarray(rng.integers(0, V, (1, 5)))
    s = DecodeSession(m)
    s.append(prompt)
    g = np.concatenate([np.asarray(s.generate(3)),
                        np.asarray(s.generate(3))], axis=1)
    want = np.asarray(generate(m, prompt, 6))[:, 5:]
    np.testing.assert_array_equal(g, want)


def test_session_append_logits_match_forward(rng):
    from apex_tpu.nn.modules import Ctx

    m = _gpt()
    m.eval()
    prompt = jnp.asarray(rng.integers(0, V, (2, 7)))
    s = DecodeSession(m, batch=2)
    logits = s.append(prompt)
    want = m.forward(Ctx(training=False), prompt)
    np.testing.assert_allclose(np.asarray(logits), np.asarray(want),
                               rtol=2e-4, atol=2e-4)


def test_session_rolling_window_model(rng):
    """Windowed (rolling-cache) session: a multi-turn history well past
    the window, with every generated position verified against the
    exact banded-flash forward re-score of the full stream."""
    from apex_tpu.nn.modules import Ctx

    nn.manual_seed(6)
    m = LlamaModel(vocab_size=V, hidden=32, layers=2, heads=4,
                   kv_heads=2, max_positions=96, sliding_window=8)
    m.eval()
    s = DecodeSession(m)
    p1 = jnp.asarray(rng.integers(0, V, (1, 12)))
    u2 = jnp.asarray(rng.integers(0, V, (1, 6)))
    s.append(p1)
    g1 = s.generate(10)
    s.append(u2)
    g2 = s.generate(10)
    assert s.position == 12 + 10 + 6 + 10

    hist = np.asarray(jnp.concatenate([p1, g1, u2, g2], axis=1))
    logits = m.forward(Ctx(training=False), jnp.asarray(hist))
    redo = np.asarray(jnp.argmax(logits, axis=-1))
    # every greedily generated token equals the forward argmax of its
    # preceding position
    np.testing.assert_array_equal(hist[0, 12:22], redo[0, 11:21])
    np.testing.assert_array_equal(hist[0, 28:38], redo[0, 27:37])


def test_session_int8_cache_and_sampling(rng):
    m = _gpt()
    m.eval()
    s = DecodeSession(m, cache_dtype="int8")
    s.append(jnp.asarray(rng.integers(0, V, (1, 5))))
    g = s.generate(6, temperature=0.8, top_k=10, top_p=0.9,
                   key=jax.random.PRNGKey(0))
    assert g.shape == (1, 6)
    assert s.position == 11


def test_session_validation(rng):
    m = _gpt()
    m.eval()
    s = DecodeSession(m, capacity=10)
    with pytest.raises(ValueError, match="append a prompt"):
        s.generate(2)
    s.append(jnp.zeros((1, 8), jnp.int32))
    with pytest.raises(ValueError, match="capacity"):
        s.generate(5)
    s.reset()
    assert s.position == 0

    sp = _gpt(sp_axis="sp")
    sp.eval()
    with pytest.raises(NotImplementedError, match="single-shard"):
        DecodeSession(sp)


def test_session_lora_swap_recompiles(rng):
    """Parameter-identity invariant: applying LoRA mid-lifecycle must
    MISS the session's compiled cache and decode with the new
    weights (utils/jit_cache.py contract)."""
    from apex_tpu.reparameterization import apply_lora

    m = _gpt()
    m.eval()
    prompt = jnp.asarray(rng.integers(0, V, (1, 5)))
    s = DecodeSession(m)
    s.append(prompt)
    before = np.asarray(s.generate(4))

    apply_lora(m, r=2)
    # fresh decode state under the new parameter set
    s.reset()
    s.append(prompt)
    after = np.asarray(s.generate(4))
    want = np.asarray(generate(m, prompt, 4))[:, 5:]
    np.testing.assert_array_equal(after, want)
    assert s.position == 9
    _ = before  # decoded under the pre-LoRA weights


def test_session_capacity_validation():
    m = _gpt()
    m.eval()
    with pytest.raises(ValueError, match="capacity"):
        DecodeSession(m, capacity=0)
    with pytest.raises(ValueError, match="capacity"):
        DecodeSession(m, capacity=-3)
    with pytest.raises(ValueError, match="capacity"):
        DecodeSession(m, capacity=1000)


def test_session_sampler_validation(rng):
    m = _gpt()
    m.eval()
    s = DecodeSession(m)
    s.append(jnp.zeros((1, 3), jnp.int32))
    with pytest.raises(ValueError, match="top_p"):
        s.generate(2, temperature=0.7, top_p=0.0,
                   key=jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="top_k"):
        s.generate(2, temperature=0.7, top_k=0,
                   key=jax.random.PRNGKey(0))
