"""L0-style fused-vs-reference tests for the multi_tensor suite.

Mirrors tests/L0/run_amp/test_multi_tensor_scale.py / _axpby / _l2norm in the
reference: dtype cross-products, numerics vs an unfused numpy oracle, and
overflow-flag behavior (inf/nan anywhere sets the flag).
"""
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.multi_tensor_apply import multi_tensor_applier
from apex_tpu import ops

DTYPES = [jnp.float16, jnp.bfloat16, jnp.float32]


def _lists(rng, dtype, shapes=((17,), (64, 33), (5, 7, 9))):
    return [jnp.asarray(rng.standard_normal(s), dtype) for s in shapes]


@pytest.mark.parametrize("in_dtype", DTYPES)
@pytest.mark.parametrize("out_dtype", DTYPES)
def test_scale_cross_product(rng, in_dtype, out_dtype):
    ins = _lists(rng, in_dtype)
    outs = [jnp.zeros_like(x, dtype=out_dtype) for x in ins]
    flag, got = multi_tensor_applier(
        ops.multi_tensor_scale, ops.zero_flag(), [ins, outs], 0.5)
    assert int(flag) == 0
    for x, y in zip(ins, got):
        assert y.dtype == out_dtype
        np.testing.assert_allclose(
            np.asarray(y, np.float32),
            np.asarray(x, np.float32) * 0.5, rtol=1e-2, atol=1e-2)


@pytest.mark.parametrize("bad", [np.inf, -np.inf, np.nan])
@pytest.mark.parametrize("which_tensor", [0, 2])
def test_scale_overflow_flag(rng, bad, which_tensor):
    ins = _lists(rng, jnp.float32)
    ins[which_tensor] = ins[which_tensor].at[0].set(bad)
    outs = [jnp.zeros_like(x) for x in ins]
    flag, _ = ops.multi_tensor_scale(ops.zero_flag(), [ins, outs], 1.0)
    assert int(flag) == 1


def test_axpby(rng):
    xs = _lists(rng, jnp.float32)
    ys = _lists(rng, jnp.float32)
    outs = [jnp.zeros_like(x) for x in xs]
    flag, got = ops.multi_tensor_axpby(ops.zero_flag(), [xs, ys, outs], 2.0, -3.0)
    assert int(flag) == 0
    for x, y, o in zip(xs, ys, got):
        np.testing.assert_allclose(
            np.asarray(o), 2.0 * np.asarray(x) - 3.0 * np.asarray(y), rtol=1e-6)


@pytest.mark.parametrize("arg_to_check,expect", [(0, 1), (1, 0), (-1, 1)])
def test_axpby_checks_selected_arg(rng, arg_to_check, expect):
    # inf planted in x only; flag fires iff x is checked
    xs = _lists(rng, jnp.float32)
    xs[0] = xs[0].at[3].set(np.inf)
    ys = _lists(rng, jnp.float32)
    outs = [jnp.zeros_like(x) for x in xs]
    flag, _ = ops.multi_tensor_axpby(
        ops.zero_flag(), [xs, ys, outs], 1.0, 1.0, arg_to_check)
    assert int(flag) == expect


def test_l2norm(rng):
    xs = _lists(rng, jnp.float32)
    _, total, per = ops.multi_tensor_l2norm(ops.zero_flag(), [xs], per_tensor=True)
    ref_per = np.array([np.linalg.norm(np.asarray(x).ravel()) for x in xs])
    ref_total = np.sqrt((ref_per ** 2).sum())
    np.testing.assert_allclose(float(total), ref_total, rtol=1e-5)
    np.testing.assert_allclose(np.asarray(per), ref_per, rtol=1e-5)


def test_l2norm_fp16_storage_fp32_math(rng):
    xs = _lists(rng, jnp.float16)
    _, total, _ = ops.multi_tensor_l2norm(ops.zero_flag(), [xs])
    assert total.dtype == jnp.float32


def test_maxnorm(rng):
    xs = _lists(rng, jnp.float32)
    _, total, per = ops.multi_tensor_maxnorm(ops.zero_flag(), [xs], per_tensor=True)
    ref = [np.abs(np.asarray(x)).max() for x in xs]
    np.testing.assert_allclose(float(total), max(ref), rtol=1e-6)
    np.testing.assert_allclose(np.asarray(per), ref, rtol=1e-6)


def test_maxnorm_empty_list():
    _, total, per = ops.multi_tensor_maxnorm(ops.zero_flag(), [[]], per_tensor=True)
    assert float(total) == 0.0 and per.shape == (0,)


def test_sgd_skips_when_flag_set(rng):
    """multi_tensor_sgd honors an already-set noop flag: params untouched
    (reference early-exit, multi_tensor_sgd_kernel.cu:46)."""
    gs = _lists(rng, jnp.float32)
    ps = _lists(rng, jnp.float32)
    ms = [jnp.zeros_like(p) for p in ps]
    set_flag = jnp.ones((), jnp.int32)
    _, new_ps, new_ms = ops.multi_tensor_sgd(
        set_flag, [gs, ps, ms], 0.0, 0.9, 0.0, 0.1, False, True, False)
    for p, np_ in zip(ps, new_ps):
        np.testing.assert_array_equal(np.asarray(p), np.asarray(np_))
    # and runs normally with a clean flag
    _, new_ps2, _ = ops.multi_tensor_sgd(
        ops.zero_flag(), [gs, ps, ms], 0.0, 0.9, 0.0, 0.1, False, True, False)
    assert any(np.any(np.asarray(a) != np.asarray(b))
               for a, b in zip(ps, new_ps2))


def test_optimizer_ops_propagate_nonfinite(rng):
    """Adam must NOT write the flag on bad grads (reference propagates,
    multi_tensor_adam.cu:40-41)."""
    gs = _lists(rng, jnp.float32)
    gs[0] = gs[0].at[0].set(np.nan)
    ps = _lists(rng, jnp.float32)
    ms = [jnp.zeros_like(p) for p in ps]
    vs = [jnp.zeros_like(p) for p in ps]
    flag, new_ps, _, _ = ops.multi_tensor_adam(
        ops.zero_flag(), [gs, ps, ms, vs], 1e-3, 0.9, 0.999, 1e-8, 1,
        ops.ADAM_MODE_L2, True, 0.0)
    assert int(flag) == 0
    assert np.isnan(np.asarray(new_ps[0])).any()


def test_flag_accumulates_across_calls(rng):
    ins = _lists(rng, jnp.float32)
    outs = [jnp.zeros_like(x) for x in ins]
    flag = ops.zero_flag()
    flag, _ = ops.multi_tensor_scale(flag, [ins, outs], 1.0)
    bad = [x.at[0].set(np.nan) for x in ins]
    flag, _ = ops.multi_tensor_scale(flag, [bad, outs], 1.0)
    flag, _ = ops.multi_tensor_scale(flag, [ins, outs], 1.0)  # clean call keeps it set
    assert int(flag) == 1
