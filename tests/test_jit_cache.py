"""The shared compiled-run cache (utils/jit_cache.py): parameter-
identity keying, LRU eviction, and pinned refs — the invariants the
three decode drivers rely on (a stale hit would zip old closure params
against new values and silently read wrong weights)."""
from apex_tpu.utils.jit_cache import compiled_run_cache


class _Obj:
    pass


def test_hit_and_param_identity_miss():
    m = _Obj()
    p1, p2 = object(), object()
    builds = []

    def build():
        builds.append(1)
        return lambda: len(builds)

    f1 = compiled_run_cache(m, "_c", ("cfg",), [p1, p2], build)
    f2 = compiled_run_cache(m, "_c", ("cfg",), [p1, p2], build)
    assert f1 is f2 and len(builds) == 1           # hit
    f3 = compiled_run_cache(m, "_c", ("cfg",), [p1, object()], build)
    assert f3 is not f1 and len(builds) == 2       # param swap missed
    f4 = compiled_run_cache(m, "_c", ("other",), [p1, p2], build)
    assert f4 is not f1 and len(builds) == 3       # cfg change missed


def test_lru_eviction_and_refresh():
    m = _Obj()
    p = object()

    def build():
        return object()

    entries = [compiled_run_cache(m, "_c", (i,), [p], build, cap=3)
               for i in range(3)]
    # refresh entry 0 (pop + reinsert), then insert a 4th: entry 1 is
    # now the oldest and must be the one evicted
    assert compiled_run_cache(m, "_c", (0,), [p], build, cap=3) \
        is entries[0]
    compiled_run_cache(m, "_c", (99,), [p], build, cap=3)
    assert compiled_run_cache(m, "_c", (0,), [p], build, cap=3) \
        is entries[0]                               # survived
    assert compiled_run_cache(m, "_c", (1,), [p], build, cap=3) \
        is not entries[1]                           # evicted, rebuilt


def test_entry_pins_param_refs():
    """The entry must hold the parameter objects it keyed on — without
    the pin, a garbage-collected param's id could be recycled by a new
    object and FALSELY hit the stale entry."""
    m = _Obj()
    p = object()
    compiled_run_cache(m, "_c", ("k",), [p], lambda: object())
    (pinned, _), = list(m._c.values())
    assert pinned[0] is p
