"""Switch-MoE integration: top-2 routing vs a dense oracle, the
load-balancing aux loss keeping expert occupancy balanced on a toy
mixture task (VERDICT r2: a top-1 router with no balance term collapses),
and the MoE GPT family training through the fused step with the aux loss
routed via Ctx.add_aux_loss — including across the remat boundary."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import apex_tpu.nn as nn
from apex_tpu.nn import functional as F
from apex_tpu.models import GptModel
from apex_tpu.optimizers import FusedAdam
from apex_tpu.parallel import switch_moe
from apex_tpu.training import make_train_step

V, H, HEADS, S = 97, 32, 4, 16
D, DFF, TLOC = 8, 16, 12


def _mesh(n, name="ep"):
    return Mesh(np.array(jax.devices()[:n]), (name,))


def _expert_fn(params, x):
    w1, w2 = params
    return jnp.maximum(x @ w1[0], 0) @ w2[0]


def test_top2_matches_dense_oracle(rng):
    """top_k=2 with generous capacity: y = g1*E1(x) + g2*E2(x), gates
    normalized over the selected pair (GShard)."""
    n = 4
    router = jnp.asarray(rng.standard_normal((D, n)), jnp.float32)
    w1 = jnp.asarray(rng.standard_normal((n, D, DFF)) * 0.3, jnp.float32)
    w2 = jnp.asarray(rng.standard_normal((n, DFF, D)) * 0.3, jnp.float32)
    x = jnp.asarray(rng.standard_normal((n * TLOC, D)), jnp.float32)

    def f(x, router, w1, w2):
        y, aux = switch_moe(x, router, (w1, w2), _expert_fn, "ep",
                            capacity_factor=8.0, top_k=2)
        return y, aux

    got, aux = jax.jit(jax.shard_map(
        f, mesh=_mesh(n), in_specs=(P("ep"), P(), P("ep"), P("ep")),
        out_specs=(P("ep"), P()), check_vma=False))(x, router, w1, w2)

    probs = np.asarray(jax.nn.softmax(x @ router, axis=-1))
    order = np.argsort(-probs, axis=-1)
    want = np.zeros((n * TLOC, D), np.float32)
    for t in range(n * TLOC):
        e1, e2 = int(order[t, 0]), int(order[t, 1])
        g1, g2 = probs[t, e1], probs[t, e2]
        zn = g1 + g2
        xt = np.asarray(x[t])
        h1 = np.maximum(xt @ np.asarray(w1[e1]), 0) @ np.asarray(w2[e1])
        h2 = np.maximum(xt @ np.asarray(w1[e2]), 0) @ np.asarray(w2[e2])
        want[t] = (g1 / zn) * h1 + (g2 / zn) * h2
    np.testing.assert_allclose(np.asarray(got), want, rtol=3e-5, atol=3e-5)
    assert np.isfinite(float(aux)) and float(aux) >= 1.0 - 1e-5


def test_aux_loss_uniform_is_one(rng):
    """With a zero router every expert is equally probable and f_e is
    whatever argmax ties give — but P_e is uniform, so aux = E * sum(f_e
    / E) = 1 exactly: the minimum of the Switch balance loss."""
    n = 4
    router = jnp.zeros((D, n), jnp.float32)
    w1 = jnp.asarray(rng.standard_normal((n, D, DFF)) * 0.3, jnp.float32)
    w2 = jnp.asarray(rng.standard_normal((n, DFF, D)) * 0.3, jnp.float32)
    x = jnp.asarray(rng.standard_normal((n * TLOC, D)), jnp.float32)

    def f(x, router, w1, w2):
        return switch_moe(x, router, (w1, w2), _expert_fn, "ep")[1]

    aux = jax.jit(jax.shard_map(
        f, mesh=_mesh(n), in_specs=(P("ep"), P(), P("ep"), P("ep")),
        out_specs=P(), check_vma=False))(x, router, w1, w2)
    np.testing.assert_allclose(float(aux), 1.0, rtol=1e-6)


def test_aux_loss_keeps_experts_balanced_on_mixture_task(rng):
    """Train router + experts on a 4-cluster mixture regression with the
    aux loss: after training, every expert keeps a meaningful share of
    the tokens (no collapse) while the task loss drops."""
    n = 4
    mesh = _mesh(n)
    centers = rng.standard_normal((n, D)).astype(np.float32) * 3.0
    xs = np.concatenate([
        centers[i] + 0.3 * rng.standard_normal((TLOC * 2, D))
        for i in range(n)]).astype(np.float32)
    perm = rng.permutation(len(xs))
    xs = xs[perm]
    ys = np.tanh(xs @ rng.standard_normal((D, D)).astype(np.float32))
    x, y = jnp.asarray(xs), jnp.asarray(ys)

    router = jnp.asarray(rng.standard_normal((D, n)) * 0.01, jnp.float32)
    w1 = jnp.asarray(rng.standard_normal((n, D, DFF)) * 0.3, jnp.float32)
    w2 = jnp.asarray(rng.standard_normal((n, DFF, D)) * 0.3, jnp.float32)

    def step(router, w1, w2, x, y):
        def local(router, w1, w2, x, y):
            def loss_fn(router, w1, w2):
                out, aux = switch_moe(x, router, (w1, w2), _expert_fn,
                                      "ep", capacity_factor=2.0)
                task = jnp.mean((out - y) ** 2)
                # aux weight 0.5: the toy run is ~300 steps, so the
                # balance term needs more pressure than Switch's 0.01
                # (which acts over hundreds of thousands of steps) to
                # un-stick a cluster->expert assignment that starves one
                # expert
                return jax.lax.pmean(task, "ep") + 0.5 * aux
            l, g = jax.value_and_grad(loss_fn, argnums=(0, 1, 2))(
                router, w1, w2)
            # replicated router: grads identical-ish per device (token
            # shards differ) -> pmean; expert blocks: psum/n = true mean
            gr = jax.lax.pmean(g[0], "ep")
            g1 = jax.lax.psum(g[1], "ep") / n
            g2 = jax.lax.psum(g[2], "ep") / n
            return l, gr, g1, g2
        return jax.shard_map(
            local, mesh=mesh,
            in_specs=(P(), P("ep"), P("ep"), P("ep"), P("ep")),
            out_specs=(P(), P(), P("ep"), P("ep")), check_vma=False)(
            router, w1, w2, x, y)

    jstep = jax.jit(step)
    l0 = None
    for i in range(300):
        l, gr, g1, g2 = jstep(router, w1, w2, x, y)
        if l0 is None:
            l0 = float(l)
        router = router - 0.05 * gr
        w1 = w1 - 0.05 * g1
        w2 = w2 - 0.05 * g2
    assert float(l) < l0

    probs = np.asarray(jax.nn.softmax(x @ router, axis=-1))
    occupancy = np.bincount(probs.argmax(-1), minlength=n) / len(xs)
    # balanced: no expert starves, none dominates
    assert occupancy.max() < 0.6, occupancy
    assert occupancy.min() > 0.05, occupancy
    # router entropy has not collapsed to a point mass
    ent = -(probs * np.log(probs + 1e-9)).sum(-1).mean()
    assert ent > 0.1, ent


def _moe_gpt(dropout=0.0, **kw):
    nn.manual_seed(5)
    return GptModel(vocab_size=V, hidden=H, layers=2, heads=HEADS,
                    max_positions=32, dropout=dropout, attn_dropout=0.0,
                    moe_axis="data", moe_num_experts=4, **kw)


def _run_moe_step(model, n_steps=15, half_dtype=None, loss_scale=1.0):
    opt = FusedAdam(list(model.parameters()), lr=1e-2)

    def lm_loss(logits, tgt):
        return F.cross_entropy(logits.reshape((-1, V)),
                               tgt.reshape((-1,)))

    step = make_train_step(model, opt, lm_loss, half_dtype=half_dtype,
                           loss_scale=loss_scale, axis_name="data")
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, V, (8, S)))
    tgt = jnp.asarray(np.roll(np.asarray(ids), -1, axis=1))
    mesh = _mesh(4, "data")
    sharded = jax.jit(jax.shard_map(
        step._step_fn, mesh=mesh,
        in_specs=(P(), P("data"), P("data")),
        out_specs=(P(), P()), check_vma=False))
    state, l0 = sharded(step.state, ids, tgt)
    for _ in range(n_steps):
        state, l = sharded(state, ids, tgt)
    return float(l0), float(l)


def test_moe_gpt_trains_through_fused_step():
    """GptModel(moe_axis="data"): every second block routes its FFN over
    4 experts on the data axis; the fused step folds the aux loss in and
    the loss decreases."""
    l0, l = _run_moe_step(_moe_gpt())
    assert np.isfinite(l) and l < l0


def test_moe_gpt_trains_with_remat():
    """The aux loss crosses the jax.checkpoint boundary as an explicit
    output (nn.checkpoint_forward), so MoE composes with remat."""
    l0, l = _run_moe_step(_moe_gpt(remat=True))
    assert np.isfinite(l) and l < l0


def test_moe_bf16_dynamic_scale_remat_dropout():
    """The harshest MoE composition: bf16 half copies + dynamic loss
    scaling + remat boundaries (aux crossing them) + residual dropout +
    top-2 routing, through the DP fused step — trains and converges."""
    l0, l = _run_moe_step(
        _moe_gpt(dropout=0.1, moe_top_k=2, remat=True), n_steps=12,
        half_dtype=jnp.bfloat16, loss_scale="dynamic")
    assert np.isfinite(l) and l < l0


def test_moe_config_validation():
    with pytest.raises(ValueError, match="moe_num_experts"):
        GptModel(vocab_size=V, hidden=H, layers=2, heads=HEADS,
                 attn_dropout=0.0, moe_axis="data")
    with pytest.raises(ValueError, match="mutually exclusive"):
        GptModel(vocab_size=V, hidden=H, layers=2, heads=HEADS,
                 attn_dropout=0.0, moe_axis="data", moe_num_experts=4,
                 tp_axis="tp")
    with pytest.raises(ValueError, match="top_k"):
        from apex_tpu.parallel.expert_parallel import switch_moe as sm
        sm(jnp.zeros((4, D)), jnp.zeros((D, 2)), None, _expert_fn,
           "ep", top_k=3)
