"""bench.py analytic helpers: the flash-attention FLOP complement that
keeps MFU honest when Pallas custom calls hide attention matmuls from XLA
cost analysis (VERDICT round 2, missing #2), and its coupling to the
shape-aware flash dispatch (below APEX_TPU_FLASH_MIN_SK the XLA path
carries attention and cost analysis already counts it)."""
import pytest

import bench


@pytest.fixture
def count_all(monkeypatch):
    """Pin the dispatch threshold open so the closed-form math is
    testable at small shapes."""
    monkeypatch.setenv("APEX_TPU_FLASH_MIN_SK", "0")


def test_flash_attn_flops_closed_form(count_all):
    # one layer, b=2, h=4, s=8, d=16, non-causal:
    # area = 2*4*8*8 = 512; fwd+bwd = 12 * area * d
    assert bench.flash_attn_step_flops([(1, 2, 4, 8, 8, 16, False)]) \
        == 12.0 * 512 * 16


def test_causal_halves_flops(count_all):
    full = bench.flash_attn_step_flops([(3, 2, 4, 64, 64, 16, False)])
    causal = bench.flash_attn_step_flops([(3, 2, 4, 64, 64, 16, True)])
    assert causal == full / 2


def test_flops_scale_quadratically_in_seq(count_all):
    s1 = bench.flash_attn_step_flops([(1, 1, 1, 128, 128, 64, False)])
    s2 = bench.flash_attn_step_flops([(1, 1, 1, 256, 256, 64, False)])
    assert s2 == 4 * s1


def test_multiple_entries_sum(count_all):
    a = [(6, 4, 8, 128, 128, 64, False)]
    b = [(6, 4, 8, 128, 128, 64, True)]
    assert bench.flash_attn_step_flops(a + b) == \
        bench.flash_attn_step_flops(a) + bench.flash_attn_step_flops(b)


def test_gpt2_small_magnitude(count_all):
    """The complement for GPT-2-small B=16 S=1024 (the BENCH_HISTORY
    long-sequence config) is ~8% of the 6ND param FLOPs — the scale at
    which the round-2 MFU floor was understated; at S=128 it is ~1%."""
    attn = bench.flash_attn_step_flops([(12, 16, 12, 1024, 1024, 64, True)])
    param = 6.0 * 124e6 * 16 * 1024
    assert 0.05 < attn / param < 0.12
    short = bench.flash_attn_step_flops([(12, 64, 12, 128, 128, 64, True)])
    assert 0.005 < short / (6.0 * 124e6 * 64 * 128) < 0.02


def test_sub_threshold_shapes_not_counted(monkeypatch):
    """Under the default dispatch threshold, attention at sk < 512 runs
    on the XLA path — its matmuls are in cost analysis, so the
    complement must NOT count them (it would double-count), while
    >= 512 shapes (flash) still are."""
    monkeypatch.delenv("APEX_TPU_FLASH_MIN_SK", raising=False)
    short = [(12, 64, 12, 128, 128, 64, True)]
    long = [(12, 16, 12, 1024, 1024, 64, True)]
    assert bench.flash_attn_step_flops(short) == 0.0
    assert bench.flash_attn_step_flops(long) > 0.0
    assert bench.flash_attn_step_flops(short + long) == \
        bench.flash_attn_step_flops(long)


def test_dispatch_threshold_env_override(monkeypatch):
    from apex_tpu.contrib.multihead_attn.attn_funcs import _flash_min_sk

    monkeypatch.delenv("APEX_TPU_FLASH_MIN_SK", raising=False)
    assert _flash_min_sk() == 512
    monkeypatch.setenv("APEX_TPU_FLASH_MIN_SK", "256")
    assert _flash_min_sk() == 256


def test_markov_ids_deterministic_chains():
    import numpy as np
    rng = np.random.default_rng(0)
    nxt = rng.permutation(64)
    ids = bench._markov_ids(nxt, 8, 16, rng, active=64)
    assert ids.shape == (8, 16)
    # every transition follows the successor map
    for t in range(1, 16):
        assert (ids[:, t] == nxt[ids[:, t - 1]]).all()


def test_trained_draft_raises_spec_acceptance():
    """The round-5 spec-decode fix in miniature: training target AND
    draft on the successor task must lift draft acceptance far above
    the random-weights floor (the round-4 bench measured acceptance
    0.0 and an 0.17x 'speedup' because the draft was random)."""
    import jax.numpy as jnp
    import numpy as np

    import apex_tpu.nn as nn
    from apex_tpu.inference import speculative_generate
    from apex_tpu.models import LlamaModel

    def mk(seed, hidden, layers):
        nn.manual_seed(seed)
        return LlamaModel(vocab_size=64, hidden=hidden, layers=layers,
                          heads=4, kv_heads=2, intermediate=64,
                          max_positions=64).eval()

    rng = np.random.default_rng(0)
    nxt = rng.permutation(64)
    target = mk(0, 32, 2)
    draft = mk(1, 16, 1)
    prompt = jnp.asarray(bench._markov_ids(nxt, 2, 8, rng, 64))

    _, stats0 = speculative_generate(target, draft, prompt, 16, k=4,
                                     return_stats=True)
    acc_random = stats0["draft_acceptance"]

    bench._train_on_markov(target, nxt, 64, 120, 16, 16, rng, lr=3e-3)
    bench._train_on_markov(draft, nxt, 64, 120, 16, 16, rng, lr=3e-3)
    _, stats1 = speculative_generate(target, draft, prompt, 16, k=4,
                                     return_stats=True)
    acc_trained = stats1["draft_acceptance"]
    assert acc_trained > max(0.5, acc_random + 0.3), \
        (acc_random, acc_trained)


def test_opt_microbench_records_schema():
    """--opt-microbench stage: runs on the cpu backend and emits the
    step_cache / per_bucket / schedule-retrace arms plus a speedup line."""
    recs = bench.opt_microbench_records(sizes=(4096,), n_tensors=4,
                                        warmup=1, timed_steps=2)
    modes = {r["mode"] for r in recs if r["metric"] == "opt_step_us"}
    assert modes == {"step_cache", "per_bucket",
                     "per_bucket_wd_schedule_retrace"}
    assert all(r["opt_step_us"] > 0 for r in recs
               if r["metric"] == "opt_step_us")
    (speedup,) = [r for r in recs if r["metric"] == "opt_step_us_speedup"]
    assert speedup["value"] > 0
    assert speedup["step_cache_stats"]["compiles"] >= 1


def test_run_with_timeout_bounded_retry():
    """backend_init hardening (BENCH_r05 backend_wedged): a call that
    wedges once and recovers must survive via the one bounded retry
    instead of hard-exiting on the first 75s window."""
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] == 1:
            import time
            time.sleep(5)        # first attempt: slower than the window
        return "ok"

    assert bench._run_with_timeout(flaky, 0.2, "backend_wedged: test",
                                   retries=1) == "ok"
    assert calls["n"] == 2


def test_run_with_timeout_emits_hint_json(monkeypatch, capsys):
    """A persistent wedge still exits 4, but the emitted JSON error line
    now carries the remediation hint (stale tunnel claim) so the bench
    ledger stays parseable and self-diagnosing."""
    import json

    def die(code):
        raise SystemExit(code)

    monkeypatch.setattr(bench.os, "_exit", die)
    with pytest.raises(SystemExit) as e:
        bench._run_with_timeout(lambda: __import__("time").sleep(5),
                                0.1, "backend_wedged: test wedge",
                                retries=1)
    assert e.value.code == 4
    line = capsys.readouterr().out.strip().splitlines()[-1]
    rec = json.loads(line)
    assert rec["error"].startswith("backend_wedged")
    assert "stale axon tunnel claim" in rec["hint"]


def test_plan_bench_records_schema():
    """--plan stage: predicted-vs-measured per plan plus the report
    summary, on a tiny GPT so the test stays quick."""
    recs = bench.plan_bench_records(vocab=256, hidden=32, layers=1,
                                    heads=2, seq=16, batch=8, topk=2,
                                    timed_steps=1)
    plans = [r for r in recs if r["metric"] == "plan_predicted_vs_measured_ms"]
    assert len(plans) == 2
    for r in plans:
        assert r["predicted_ms"] > 0 and r["predicted_hbm_mb"] > 0
        assert r["measured_ms"] is not None and r["measured_ms"] > 0
        assert r["rel_err"] is not None
    (report,) = [r for r in recs if r["metric"] == "plan_report"]
    assert report["chosen"] == plans[0]["plan"]
    assert report["feasible"] > 0 and report["rejected"] > 0
    assert report["rejected_reasons"]        # no silent pruning
    # joint-search telemetry for BOTH profiles (satellite of ISSUE 19)
    searches = {r["profile"]: r for r in recs
                if r["metric"] == "plan_search"}
    assert set(searches) == {"gpt", "switch_moe"}
    for name, s in searches.items():
        assert "error" not in s, s
        assert s["plans_explored"] > 0
        assert s["plans_pruned_oom"] >= 0
        assert s["search_ms"] > 0
        assert s["chosen"] and s["top"]
        assert s["top"][0]["plan"] == s["chosen"]
        assert s["top"][0]["vs_chosen_ms"] == 0.0
        assert all(t["vs_chosen_ms"] >= 0 for t in s["top"])
    # the MoE search had the expert axis in its space
    moe_top = [t["plan"] for t in searches["switch_moe"]["top"]]
    assert any("ep" in p for p in moe_top) or \
        searches["switch_moe"]["plans_explored"] > 0


def test_ckpt_microbench_records_schema(tmp_path):
    """--ckpt-microbench stage: sync / async_submit / async_drain arms
    plus the overlap factor, all on a small state so the test is quick."""
    recs = bench.ckpt_microbench_records(total_mb=2, n_tensors=4,
                                         repeats=2,
                                         directory=str(tmp_path))
    modes = {r["mode"] for r in recs if r["metric"] == "ckpt_save_ms"}
    assert modes == {"sync", "async_submit", "async_drain"}
    assert all(r["value"] >= 0 for r in recs)
    (overlap,) = [r for r in recs if r["metric"] == "ckpt_save_overlap_x"]
    assert overlap["value"] > 0


def test_elastic_bench_records_schema(tmp_path):
    """--elastic stage: one record per topology transition (shrink,
    regrow) carrying the recovery-latency fields {replan_ms, reshard_ms,
    resume_gap_steps} plus the plan the checkpoint was saved under."""
    recs = bench.elastic_bench_records(dim=16, batch=8, pre_steps=2,
                                       lost_steps=1,
                                       directory=str(tmp_path))
    assert {r["event"] for r in recs} == {"shrink", "regrow"}
    for r in recs:
        assert r["metric"] == "elastic_recovery"
        assert r["platform"] == "cpu"
        assert r["replan_ms"] > 0
        assert r["reshard_ms"] > 0
        assert r["resume_gap_steps"] >= 0
        assert r["to_devices"] >= 1 and r["from_devices"] >= 1
        assert r["ckpt_plan"]       # schema-2 manifest carried the plan
    (shrink,) = [r for r in recs if r["event"] == "shrink"]
    assert shrink["to_devices"] < shrink["from_devices"]
    # exactly the un-checkpointed steps are replayed after the preempt
    assert shrink["resume_gap_steps"] == 1


def test_cluster_bench_records_schema(tmp_path):
    """--cluster stage: one cluster_recovery record carrying the full
    cycle's latency split and the streaming-shard-IO claim — the
    streamed restore's host high-water mark stays strictly below the
    gathered full-state size.  (The real-OS-process FileKV arm is
    covered by tests/test_cluster.py; skipped here to keep this quick.)"""
    recs = bench.cluster_bench_records(dim=16, batch=24, pre_steps=2,
                                       directory=str(tmp_path),
                                       spawn_processes=False)
    (r,) = recs
    assert r["metric"] == "cluster_recovery"
    assert r["platform"] == "cpu"
    assert r["membership_epochs"] >= 2       # join epoch + the host loss
    assert r["surviving_devices"] >= 1
    assert r["detect_ms"] >= 0
    assert r["replan_ms"] > 0
    assert r["stream_restore_ms"] > 0
    assert r["gathered_restore_ms"] > 0
    assert r["restore_mode"] == "streamed"
    assert 0 < r["shard_bytes_peak_host"] < r["gathered_state_bytes"]
    assert r["shard_bytes_peak_save"] > 0


def test_observe_microbench_records_schema():
    """--observe-microbench stage: the fused step with the on-device
    telemetry carry vs telemetry off, and the observe claim — at
    drain_every >= 16 the telemetry costs under 2% of step time.

    The measurement interleaves base/telemetry arms per repeat and
    takes the median of the paired per-repeat differences, so a load
    spike hits both arms of its repeat instead of whichever arm ran
    last.  The bound is contention-aware on top of that: each record
    carries ``base_spread_pct`` — how far the base arm's repeats
    disagree with each other — and when the box is visibly contended
    (spread past 5%) the bound widens by the excess, because no
    difference of timings can resolve finer than the noise floor the
    identical arm measured on itself."""
    for attempt in range(3):
        recs = bench.observe_microbench_records(timed_steps=5,
                                                repeats=3 + attempt)
        assert {r["drain_every"] for r in recs} == {1, 16}
        for r in recs:
            assert r["metric"] == "telemetry_overhead_us"
            assert r["platform"] == "cpu"
            assert r["step_us_base"] > 0 and r["step_us_telemetry"] > 0
            assert r["telemetry_overhead_us"] == \
                round(r["step_us_telemetry"] - r["step_us_base"], 1)
            assert r["base_spread_pct"] >= 0.0
        (d16,) = [r for r in recs if r["drain_every"] >= 16]
        allowed = 2.0 + max(0.0, d16["base_spread_pct"] - 5.0)
        if d16["overhead_pct"] < allowed:
            break
    assert d16["overhead_pct"] < allowed, d16


def test_serve_elastic_bench_records_schema(tmp_path):
    """--serve-elastic stage: one serve_elastic_recovery record for a
    full detect→shed→migrate→resume cycle — every request completes
    across the shrink, the epoch advanced past the host loss, and the
    recovery split (migrated / shed-requeued / recomputed) accounts
    for at least one session actually re-homed."""
    recs = bench.serve_elastic_bench_records(n_requests=12)
    (r,) = recs
    assert r["metric"] == "serve_elastic_recovery"
    assert r["platform"] == "cpu"
    assert r["engines"] >= 2
    assert r["completed"] == r["requests"] == 12
    assert r["epoch"] >= 2                   # join epoch + the host loss
    assert r["detect_ms"] >= 0.0
    assert r["migrate_ms"] >= 0.0
    assert r["sessions_migrated"] + r["sessions_shed_requeued"] + \
        r["sessions_recomputed"] >= 1        # someone was re-homed
    assert r["sessions_migrated"] >= 0
    assert r["snapshot_bytes_peak_host"] > 0


def test_serve_bench_records_schema():
    """--serve stage: the serving engine under a Poisson open-loop
    trace, one record per arm (unified / disaggregated / speculative).
    Schema plus the serving claims: every arm's decode-path compile
    count after the whole trace stays within its bucket grid
    (recompile-free decode past warmup, ragged acceptance included);
    the disaggregated arms hand KV off one block buffer at a time
    (``handoff_bytes_peak_host`` bounded by a single block's bytes);
    the speculative arm commits >= 2 tokens per sequence per tick on
    the self-draft trace."""
    recs = bench.serve_bench_records(n_requests=40, arrival_rate=1.0)
    assert [r["arm"] for r in recs] == \
        ["unified", "disaggregated", "speculative"]
    for r in recs:
        assert r["metric"] == "serve_throughput"
        assert r["platform"] == "cpu"
        assert r["requests"] == 40 and r["ticks"] > 0
        assert r["tokens_per_s_per_chip"] > 0
        assert r["p50_ms"] > 0 and r["p99_ms"] >= r["p50_ms"]
        assert r["ttft_p50_ms"] > 0
        assert 0.0 < r["pool_occupancy"] <= 1.0
        assert r["preemptions"] >= 0
        assert 1 <= r["decode_compiles"] <= r["bucket_bound"]
        assert r["accept_rate"] >= 0.0
        assert r["handoff_bytes_peak_host"] >= 0
    uni, dis, spec = recs
    assert uni["handoff_bytes_peak_host"] == 0
    # one fp32 KV block for the tiny GPT: 2 layers x K+V x 4 heads x
    # block_size 8 x head_dim 8 x 4 bytes — the streamed handoff never
    # holds more than one block buffer on the host
    block_bytes = 2 * 2 * 4 * 8 * 8 * 4
    for r in (dis, spec):
        assert r["handoffs"] == 40
        assert 0 < r["handoff_bytes_peak_host"] <= block_bytes
    # self-draft: full acceptance, and the committed-tokens floor the
    # ISSUE pins — >= 2 tokens per sequence per speculative tick
    assert spec["accept_rate"] > 0.5
    assert spec["spec_tokens_per_tick"] >= 2.0


def test_serve_prefix_bench_records_schema():
    """--serve shared-prefix arm: the prefix cache under a Poisson
    trace of requests sharing an 80-token block-aligned scaffold,
    cache off vs on over the SAME trace.  Schema plus the ISSUE's
    acceptance floors: warm hit rate >= 0.9 (only the first request
    pays the scaffold cold), TTFT p50 strictly better cache-on, at
    least one copy-on-write fork (every 4th request is exactly the
    shared prompt — the full-chain-hit path), and decode stays
    recompile-free in both arms."""
    recs = bench.serve_prefix_bench_records()
    assert [r["arm"] for r in recs] == ["cache_off", "cache_on"]
    for r in recs:
        assert r["metric"] == "serve_prefix_cache"
        assert r["platform"] == "cpu"
        assert r["requests"] == 24 and r["ticks"] > 0
        assert r["ttft_p50_ms"] > 0
        assert r["prefill_tokens_saved"] >= 0
        assert r["cow_forks"] >= 0 and r["cache_evictions"] >= 0
        assert 1 <= r["decode_compiles"] <= 8
    off, on = recs
    assert off["prefix_hit_rate"] == 0.0
    assert off["prefill_tokens_saved"] == 0
    assert off["cow_forks"] == 0 and off["cached_blocks"] == 0
    assert on["prefix_hit_rate"] >= 0.9
    assert on["prefill_tokens_saved"] > 1000   # ~23 x 80 scaffold tokens
    assert on["cow_forks"] >= 1                # full-chain hits forked
    assert on["cached_blocks"] > 0             # warm tier survives drain
    assert on["ttft_p50_ms"] < off["ttft_p50_ms"]


def test_stage_ledger_resumable(tmp_path, capsys):
    """--ledger: done stages are skipped on re-run, failed/wedged ones
    are not — a stage that raises is recorded ``failed`` (and a
    hard-exit mid-stage leaves ``running``), neither of which counts as
    done, so exactly the broken stage re-runs."""
    import json

    path = str(tmp_path / "ledger.json")
    led = bench.StageLedger(path)
    calls = {"a": 0, "b": 0}

    def ok():
        calls["a"] += 1
        return 0

    def boom():
        calls["b"] += 1
        raise RuntimeError("wedged")

    assert led.run("a", ok) == 0
    with pytest.raises(RuntimeError):
        led.run("b", boom)
    on_disk = json.load(open(path))["stages"]
    assert on_disk["a"]["status"] == "done"
    assert on_disk["b"]["status"] == "failed"
    assert "wedged" in on_disk["b"]["error"]

    # a fresh process over the same ledger: done skips, failed re-runs
    led2 = bench.StageLedger(path)
    assert led2.run("a", ok) == 0
    assert calls["a"] == 1                      # skipped, not re-run
    with pytest.raises(RuntimeError):
        led2.run("b", boom)
    assert calls["b"] == 2                      # failed stage re-ran

    # nonzero rc is failed too; a later green run flips it to done
    led2.run("c", lambda: 1)
    assert led2.status("c") == "failed"
    led2.run("c", lambda: 0)
    assert led2.is_done("c")

    # mid-stage hard-exit simulation: 'running' never reads as done
    led2.mark("d", "running")
    assert bench.StageLedger(path).is_done("d") is False

    # corrupt ledger file: start fresh instead of crashing the round
    with open(path, "w") as f:
        f.write("{not json")
    led3 = bench.StageLedger(path)
    assert led3.stages == {}


def test_rollout_bench_records_schema():
    """--rollout stage: one rollout_loop record for the generate-then-
    train runtime — both sides of the loop made progress (tokens
    generated, fused steps run), every weight sync was measured, the
    cpu publish path is fully zero-copy (layout-identical leaves,
    donation off), the per-round staleness medians respect the default
    bound, and the distiller logged an acceptance trend."""
    recs = bench.rollout_bench_records(rounds=4)
    (r,) = recs
    assert r["metric"] == "rollout_loop"
    assert r["platform"] == "cpu"
    assert r["rounds"] == 4
    assert r["rollout_tokens_per_s"] > 0
    assert r["train_steps_per_s"] > 0
    assert r["weight_sync_ms"] > 0.0
    assert r["zero_copy_frac"] == 1.0
    assert isinstance(r["accept_rate_trend"], list)
    assert len(r["accept_rate_trend"]) >= 1
    assert all(0.0 <= a <= 1.0 for a in r["accept_rate_trend"])
    # default max_staleness=2: the observed median age never exceeds it
    assert 0.0 <= r["buffer_staleness_p50"] <= 2.0
    # publish_every=1 with a warmup round: epoch == publishes == rounds+1
    assert r["weight_epoch"] == r["publishes"] == 5
    assert r["loss_last"] < r["loss_first"]


def test_overlap_microbench_records_schema():
    """--overlap-microbench stage: the executor overlap knobs (ZeRO
    all-gather prefetch, async H2D double-buffering) off vs on per K.
    Both arms compile the same math DAG — the bitwise parity is pinned
    in tests/test_executor.py — so on cpu this asserts the record
    schema and that the factors are sane ratios, not a perf win (that
    claim belongs to the multichip rounds)."""
    recs = bench.overlap_microbench_records(ks=(1, 4), timed_windows=2,
                                            warmup=1)
    assert {r["accum_steps"] for r in recs} == {1, 4}
    for r in recs:
        assert r["metric"] == "window_step_us"
        assert r["platform"] == "cpu"
        assert r["window_step_us"] > 0
        for knob in ("gather", "h2d"):
            assert r[f"{knob}_window_us_off"] > 0
            assert r[f"{knob}_window_us_on"] > 0
            # same DAG both arms: a ratio far from 1 on cpu means an
            # arm compiled something else entirely
            assert 0.2 < r[f"{knob}_overlap_factor"] < 5.0


def test_kernel_probe_records_schema(tmp_path):
    """--kernels calibration stage: one ledger-shaped record per
    registered kernel/shape.  The schema is the TPU contract — off-TPU
    the pallas arm is interpret-mode emulation, so the test asserts the
    plumbing, not the win: every record carries the ingest_events
    fields, mirrors as a ``bench.kernel_probe`` observe event, and a
    ledger fed those events serves dispatch lookups."""
    from apex_tpu import observe
    from apex_tpu.kernels import dispatch as kdispatch
    from apex_tpu.kernels.ledger import Ledger

    recs = bench.kernel_probe_records(iters=1, reps=1)
    by_kernel = {}
    for r in recs:
        assert r["metric"] == "kernel_probe"
        assert {"kernel", "shape_fp", "pallas_us", "xla_us", "win",
                "threshold"} <= set(r)
        assert "error" not in r, r
        assert r["pallas_us"] > 0 and r["xla_us"] > 0 and r["win"] > 0
        assert kdispatch.parse_fp(r["shape_fp"])     # round-trippable key
        by_kernel.setdefault(r["kernel"], []).append(r)
    # every registered dispatch-tier kernel got probed
    assert set(by_kernel) == set(kdispatch.catalog())
    # the flash rows carry the production threshold, not the probe pin
    assert all(r["threshold"] == 512
               for r in by_kernel["flash_attention"])
    # off-TPU: interpret-mode arms are emitted but never persisted into
    # the calibration ledger (emulation timings must not steer dispatch)
    assert all(r["mode"] == "interpret" and not r["ledger_write"]
               for r in recs)
    # the register_record mirror IS the ledger ingest contract
    fps = {(r["kernel"], r["shape_fp"]) for r in recs}
    evs = [e for e in observe.events("bench.kernel_probe")
           if (e.get("kernel"), e.get("shape_fp")) in fps]
    assert len(evs) >= len(recs)
    led = Ledger(str(tmp_path / "ledger.json"))
    assert led.ingest_events(evs) >= len(recs)
    for r in recs:
        entry = led.lookup_kernel(r["chip"], r["kernel"], r["shape_fp"])
        assert entry is not None and entry["win"] == pytest.approx(
            r["win"], rel=1e-3)


def test_lint_records_schema():
    """--lint stage: one lint_findings record with the analyzer-health
    fields (the r06 multichip rerun records hazard-cleanliness next to
    perf), and a clean shipped tree."""
    (rec,) = bench.lint_records()
    assert rec["metric"] == "lint_findings"
    assert rec["value"] == rec["lint_findings"] == 0   # tree ships clean
    assert rec["lint_ms"] > 0
    assert len(rec["rules_run"]) >= 16
    assert rec["files_scanned"] > 100      # apex_tpu + examples
    # lint v2 analyzer-health fields: the dataflow pass ran, the tree
    # carries no dead suppressions, and the jaxpr audit covered the
    # entry programs without a failure
    assert rec["dataflow_ms"] > 0
    assert rec["stale_suppressions"] == 0
    assert rec["jaxpr_audit_ms"] > 0
    assert rec["programs_audited"] >= 12
    assert rec["jaxpr_failures"] == 0
