"""The apex_tpu.lint analyzer: every rule against its paired fixtures,
engine machinery (suppressions, baseline, reporters, CLI exit codes),
and the dynamic oracle proving RETRACE-STATIC's static verdict matches
``step_cache.stats()`` compile counts at runtime."""
import importlib
import json
import os
import subprocess
import sys

import pytest

from apex_tpu import lint as tpu_lint
from apex_tpu.lint import engine, report, rules
from apex_tpu.lint.__main__ import main as lint_main

HERE = os.path.dirname(os.path.abspath(__file__))
FIXTURES = os.path.join(HERE, "lint_fixtures")
REPO = os.path.dirname(HERE)

pytestmark = pytest.mark.lint

#: rule id -> fixture stem (pos/neg pair)
RULE_FIXTURES = {
    "RETRACE-STATIC": "retrace_static",
    "HOST-SYNC": "host_sync",
    "SCAN-COLLECTIVE": "scan_collective",
    "DONATED-REUSE": "donated_reuse",
    "COMPAT-SHIM": os.path.join("apex_tpu", "compat_shim"),
    "UNBOUNDED-COLLECTIVE": "unbounded_collective",
    "IMPURE-STATIC-KEY": "impure_static_key",
    "CKPT-ATOMIC": "ckpt_atomic",
    "OBS-IN-JIT": "obs_in_jit",
    "EXEC-BYPASS": "exec_bypass",
    "SERVE-SHAPE": "serve_shape",
    "KERNEL-FALLBACK": "kernel_fallback",
    "PRECISION-SINK": "precision_sink",
    "TRACER-LEAK": "tracer_leak",
    "SHAPE-BRANCH": "shape_branch",
    "STALE-SUPPRESSION": "stale_suppression",
    "CLUSTER-ASSUME": "cluster_assume",
    "WEIGHT-PUBLISH": "weight_publish",
    "POOL-ALIAS": "pool_alias",
}


def _fixture(stem, kind):
    return os.path.join(FIXTURES, f"{stem}_{kind}.py")


def _run(paths, **kw):
    kw.setdefault("baseline", None)
    return tpu_lint.run(paths, **kw)


# ---------------------------------------------------------------------------
# per-rule fixtures
# ---------------------------------------------------------------------------


def test_registry_covers_required_rules():
    assert set(RULE_FIXTURES) <= set(rules.rule_ids())
    assert len(rules.rule_ids()) >= 19


@pytest.mark.parametrize("rule_id", sorted(RULE_FIXTURES))
def test_positive_fixture_flags(rule_id):
    res = _run([_fixture(RULE_FIXTURES[rule_id], "pos")],
               select=[rule_id])
    assert res.active(), f"{rule_id}: positive fixture produced no finding"
    assert all(f.rule == rule_id for f in res.active())


@pytest.mark.parametrize("rule_id", sorted(RULE_FIXTURES))
def test_negative_fixture_clean(rule_id):
    res = _run([_fixture(RULE_FIXTURES[rule_id], "neg")],
               select=[rule_id])
    assert not res.active(), (
        f"{rule_id}: negative fixture flagged:\n"
        + "\n".join(f.format() for f in res.active()))


@pytest.mark.parametrize("rule_id", sorted(RULE_FIXTURES))
def test_cli_exits_nonzero_on_positive_fixture(rule_id, capsys):
    rc = lint_main([_fixture(RULE_FIXTURES[rule_id], "pos"),
                    "--select", rule_id, "--no-baseline"])
    assert rc == 1
    assert rule_id in capsys.readouterr().out


def test_cli_module_entry_runs_positive_fixture():
    """The acceptance-spelled invocation: ``python -m apex_tpu.lint``
    exits non-zero on a positive fixture (one subprocess smoke test;
    per-rule coverage runs in-process above)."""
    proc = subprocess.run(
        [sys.executable, "-m", "apex_tpu.lint",
         _fixture("retrace_static", "pos"), "--no-baseline"],
        cwd=REPO, capture_output=True, text=True, timeout=120)
    assert proc.returncode == 1, proc.stdout + proc.stderr
    assert "RETRACE-STATIC" in proc.stdout


def test_finding_locations_are_exact():
    res = _run([_fixture("retrace_static", "pos")],
               select=["RETRACE-STATIC"])
    lines = {f.line for f in res.active()}
    src = open(_fixture("retrace_static", "pos")).read().splitlines()
    for ln in lines:
        assert "lr" in src[ln - 1]


# ---------------------------------------------------------------------------
# engine machinery
# ---------------------------------------------------------------------------


def test_suppression_on_line_and_comment_block(tmp_path):
    f = tmp_path / "m.py"
    f.write_text(
        "import jax\n"
        "def mk(u):\n"
        "    a = jax.jit(u, static_argnames=('lr',))"
        "  # tpu-lint: disable=RETRACE-STATIC fixture reason\n"
        "    # tpu-lint: disable=RETRACE-STATIC block reason\n"
        "    # (wrapped continuation of the reason)\n"
        "    b = jax.jit(u, static_argnames=('lr',))\n"
        "    c = jax.jit(u, static_argnames=('lr',))\n"
        "    return a, b, c\n")
    res = _run([str(f)], select=["RETRACE-STATIC"])
    assert len(res.findings) == 3
    live = res.active()
    assert len(live) == 1 and live[0].line == 7   # c: no directive
    sup = [x for x in res.findings if x.suppressed]
    assert {s.suppress_reason for s in sup} == {"fixture reason",
                                                "block reason"}


def test_file_wide_suppression(tmp_path):
    f = tmp_path / "m.py"
    f.write_text(
        "# tpu-lint: disable-file=RETRACE-STATIC generated file\n"
        "import jax\n"
        "def mk(u):\n"
        "    return jax.jit(u, static_argnames=('lr',))\n")
    res = _run([str(f)], select=["RETRACE-STATIC"])
    assert not res.active()
    assert any(x.suppressed for x in res.findings)


def test_parse_error_is_a_finding(tmp_path):
    f = tmp_path / "broken.py"
    f.write_text("def oops(:\n")
    res = _run([str(f)])
    assert any(x.rule == "PARSE-ERROR" for x in res.active())


def test_baseline_roundtrip(tmp_path):
    src = tmp_path / "m.py"
    src.write_text(
        "import jax\n"
        "def mk(u):\n"
        "    return jax.jit(u, static_argnames=('lr',))\n")
    bl = tmp_path / "baseline.json"
    res = _run([str(src)], select=["RETRACE-STATIC"])
    assert len(res.active()) == 1
    n = engine.write_baseline(str(bl), res, res._modules_by_rel)
    assert n == 1
    res2 = tpu_lint.run([str(src)], select=["RETRACE-STATIC"],
                        baseline=str(bl))
    assert not res2.active()
    assert any(f.baselined for f in res2.findings)
    # a NEW finding is not grandfathered
    src.write_text(src.read_text()
                   + "def mk2(u):\n"
                   "    return jax.jit(u, static_argnames=('wd',))\n")
    res3 = tpu_lint.run([str(src)], select=["RETRACE-STATIC"],
                        baseline=str(bl))
    assert len(res3.active()) == 1 and res3.active()[0].line == 5


def test_unknown_rule_id_is_usage_error(capsys):
    assert lint_main(["--select", "NOT-A-RULE", FIXTURES]) == 2


def test_json_reporter_schema():
    res = _run([_fixture("scan_collective", "pos")],
               select=["SCAN-COLLECTIVE"])
    data = json.loads(report.as_json(res))
    assert data["findings"] == len(res.active()) > 0
    row = data["findings_list"][0]
    assert {"rule", "path", "line", "col", "message",
            "hint"} <= set(row)
    assert data["rules_run"] == ["SCAN-COLLECTIVE"]


def test_sarif_reporter_schema():
    res = _run([_fixture("retrace_static", "pos")],
               select=["RETRACE-STATIC"])
    doc = json.loads(report.as_sarif(res))
    assert doc["version"] == "2.1.0"
    drv = doc["runs"][0]["tool"]["driver"]
    assert drv["name"] == "apex-tpu-lint"
    assert {"RETRACE-STATIC", "HOST-SYNC"} <= {r["id"] for r in
                                               drv["rules"]}
    results = doc["runs"][0]["results"]
    assert len(results) == len(res.active()) > 0
    r0 = results[0]
    assert r0["ruleId"] == "RETRACE-STATIC"
    loc = r0["locations"][0]["physicalLocation"]
    assert loc["artifactLocation"]["uri"].endswith("retrace_static_pos.py")
    assert loc["region"]["startLine"] >= 1
    assert loc["region"]["startColumn"] >= 1      # sarif is 1-based


def test_cli_sarif_format(capsys):
    rc = lint_main([_fixture("retrace_static", "pos"),
                    "--select", "RETRACE-STATIC", "--no-baseline",
                    "--format", "sarif"])
    assert rc == 1
    doc = json.loads(capsys.readouterr().out)
    assert doc["runs"][0]["results"]


def _git(tmp_path, *argv):
    subprocess.run(["git", "-C", str(tmp_path), *argv],
                   check=True, capture_output=True)


def test_cli_changed_scope(tmp_path, monkeypatch, capsys):
    """--changed lints exactly the files touched vs the git base: an
    unchanged committed file stays out of scope even when it carries a
    finding; untracked and modified files are in."""
    _git(tmp_path, "init", "-q")
    _git(tmp_path, "config", "user.email", "lint@test")
    _git(tmp_path, "config", "user.name", "lint test")
    committed = tmp_path / "committed.py"
    committed.write_text(
        "import jax\n"
        "def mk(u):\n"
        "    return jax.jit(u, static_argnames=('lr',))\n")
    _git(tmp_path, "add", "committed.py")
    _git(tmp_path, "commit", "-q", "-m", "seed")

    fresh = tmp_path / "fresh.py"
    fresh.write_text(
        "import jax\n"
        "def mk2(u):\n"
        "    return jax.jit(u, static_argnames=('wd',))\n")
    monkeypatch.chdir(tmp_path)
    rc = lint_main(["--changed", "--select", "RETRACE-STATIC",
                    "--no-baseline"])
    out = capsys.readouterr().out
    assert rc == 1
    assert "fresh.py" in out and "committed.py" not in out

    _git(tmp_path, "add", "fresh.py")
    _git(tmp_path, "commit", "-q", "-m", "add fresh")
    rc = lint_main(["--changed", "--no-baseline"])
    out = capsys.readouterr().out
    assert rc == 0 and "no changed python files" in out
    # an explicit base ref widens the scope back to both files
    rc = lint_main(["--changed", "HEAD~1", "--select", "RETRACE-STATIC",
                    "--no-baseline"])
    out = capsys.readouterr().out
    assert rc == 1 and "fresh.py" in out


def test_list_rules_cli(capsys):
    assert lint_main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for rid in RULE_FIXTURES:
        assert rid in out


def test_engine_scans_nested_package_dirs():
    """Walk-coverage: pointing the engine at apex_tpu/ provably visits
    the planner and the step cache (the guarantee test_compat.py's
    wrappers rely on)."""
    res = _run([os.path.join(REPO, "apex_tpu")], select=["COMPAT-SHIM"])
    rel = {os.path.relpath(p, REPO) for p in res.files}
    assert os.path.join("apex_tpu", "parallel", "auto.py") in rel
    assert os.path.join("apex_tpu", "runtime", "step_cache.py") in rel
    assert os.path.join("apex_tpu", "lint", "rules.py") in rel


# ---------------------------------------------------------------------------
# the dynamic oracle
# ---------------------------------------------------------------------------


def _import_fixture(name):
    sys.path.insert(0, FIXTURES)
    try:
        mod = importlib.import_module(name)
    finally:
        sys.path.pop(0)
    return mod


def test_retrace_static_dynamic_oracle():
    """The static verdict matches runtime behavior: the fixture optimizer
    RETRACE-STATIC flags recompiles on every schedule tick; the clean
    one pins 1 compile over the same schedule."""
    from apex_tpu.runtime import step_cache

    bad_res = _run([os.path.join(FIXTURES, "oracle_bad.py")],
                   select=["RETRACE-STATIC"])
    good_res = _run([os.path.join(FIXTURES, "oracle_good.py")],
                    select=["RETRACE-STATIC"])
    assert len(bad_res.active()) == 1     # static verdict: bad
    assert not good_res.active()          # static verdict: clean

    bad = _import_fixture("oracle_bad")
    good = _import_fixture("oracle_good")
    steps = 4
    step_cache.reset_stats()
    bad.train(steps=steps)
    good.train(steps=steps)
    by_kind = step_cache.stats()["by_kind"]
    # the flagged optimizer compiled once PER STEP (distinct lr values
    # key distinct programs — the PR 1 pathology)
    assert by_kind["oracle_bad"]["compiles"] == steps
    assert by_kind["oracle_bad"]["cache_hits"] == 0
    # the clean one compiled once and then hit the cache every step
    assert by_kind["oracle_good"]["compiles"] == 1
    assert by_kind["oracle_good"]["cache_hits"] == steps - 1
