"""apex_tpu.rollout — the generate-then-train loop (tier-1, CPU).

Pins the ISSUE-18 acceptance criteria: bitwise weight sync at every
publish epoch, draft accept-rate strictly improving over >= 3
distillation publishes, and chaos-kill resume matching the
uninterrupted loss trajectory — plus the buffer's staleness/
backpressure/replay contracts, the reshard per-leaf stats satellite,
and zero leaked pool blocks.
"""
import numpy as np
import pytest

import jax.numpy as jnp

import apex_tpu.nn as nn
import apex_tpu.nn.functional as F
from apex_tpu.inference.draft import make_self_draft
from apex_tpu.models.gpt import GptModel
from apex_tpu.observe import registry as obs
from apex_tpu.observe.catalog import CATALOG
from apex_tpu.optimizers.fused_adam import FusedAdam
from apex_tpu.rollout import (OnlineDistiller, RolloutBuffer,
                              RolloutRuntime, RolloutSample,
                              WeightPublisher, master_leaves)
from apex_tpu.runtime import chaos
from apex_tpu.runtime import step_cache as sc
from apex_tpu.runtime.resilience import CheckpointManager, reshard_state
from apex_tpu.serve.engine import ServeEngine
from apex_tpu.serve.scheduler import Request
from apex_tpu.training.step import make_train_step

pytestmark = pytest.mark.rollout

V = 73


def _gpt(seed):
    nn.manual_seed(seed)
    return GptModel(vocab_size=V, hidden=32, layers=2, heads=4,
                    max_positions=96, dropout=0.0, attn_dropout=0.0)


def _lm_loss(logits, ids):
    flat = logits[:, :-1].reshape((-1, V))
    tgt = ids[:, 1:].reshape((-1,))
    return F.cross_entropy(flat, tgt)


def _train_step(model, lr=1e-3):
    opt = FusedAdam(list(model.parameters()), lr=lr)
    return make_train_step(model, opt, _lm_loss, loss_scale=1.0)


def _loop(*, distill=False, capacity=16, max_staleness=2,
          rollouts_per_round=4, train_batch=4, train_steps_per_round=2,
          publish_every=1, seed=0, num_blocks=64, **kw):
    """Fresh, fully seeded loop: train model, serve copy, engine,
    fused step, optional online distiller, runtime."""
    train_m = _gpt(6)
    serve_m = make_self_draft(train_m)
    draft = None
    if distill:
        draft_master = _gpt(99)
        draft = make_self_draft(draft_master)
    eng = ServeEngine(serve_m, num_blocks=num_blocks, block_size=8,
                      max_batch=4, prefill_chunk=4, draft=draft,
                      spec_k=4, spec_policy="on")
    step = _train_step(train_m)
    dist = OnlineDistiller(eng, draft_master, lr=1e-3) if distill \
        else None
    rt = RolloutRuntime(eng, step, capacity=capacity,
                        max_staleness=max_staleness,
                        rollouts_per_round=rollouts_per_round,
                        train_batch=train_batch,
                        train_steps_per_round=train_steps_per_round,
                        publish_every=publish_every,
                        prompt_len=6, max_new_tokens=6, seq_len=16,
                        distiller=dist, seed=seed, **kw)
    return eng, step, rt


# ---------------------------------------------------------------------------
# buffer: staleness, backpressure, seeded replay
# ---------------------------------------------------------------------------


def _sample(rid, epoch, n=12):
    toks = np.arange(n, dtype=np.int32) % V
    return RolloutSample(rid=rid, tokens=toks, prompt_len=4,
                         weight_epoch=epoch)


def test_buffer_staleness_eviction():
    buf = RolloutBuffer(8, max_staleness=2, seed=0)
    for i, ep in enumerate([0, 0, 1, 3, 4]):
        assert buf.push(_sample(f"s{i}", ep))
    # at epoch 4: ages are 4,4,3,1,0 -> the three older than bound leave
    assert buf.evict_stale(4) == 3
    assert len(buf) == 2
    assert buf.evicted == 3
    assert max(buf.ages(4)) <= 2
    # downweight policy never evicts; it weights instead
    dbuf = RolloutBuffer(8, max_staleness=1, staleness_policy="downweight",
                         downweight=0.5, seed=0)
    for i, ep in enumerate([0, 3]):
        dbuf.push(_sample(f"d{i}", ep))
    assert dbuf.evict_stale(3) == 0
    xs, w, ages = dbuf.sample_batch(8, 8, current_epoch=3)
    for wi, ai in zip(w, ages):
        assert wi == pytest.approx(0.5 ** max(ai - 1, 0))


def test_buffer_full_refuses_and_counts():
    buf = RolloutBuffer(2, seed=0)
    assert buf.push(_sample("a", 0)) and buf.push(_sample("b", 0))
    assert buf.free_slots == 0
    assert not buf.push(_sample("c", 0))
    assert buf.rejects == 1
    assert len(buf) == 2


def test_buffer_seeded_replay_and_checkpoint_roundtrip():
    def fill(buf):
        for i in range(6):
            buf.push(_sample(f"s{i}", i % 3, n=10 + i))
        return buf
    a = fill(RolloutBuffer(8, seed=7))
    b = fill(RolloutBuffer(8, seed=7))
    for _ in range(3):
        xa, _, _ = a.sample_batch(4, 8, current_epoch=3)
        xb, _, _ = b.sample_batch(4, 8, current_epoch=3)
        np.testing.assert_array_equal(xa, xb)
    # checkpoint mid-sequence: the restored buffer replays the exact
    # continuation the original produces
    sd = a.state_dict()
    cont_a = [a.sample_batch(4, 8, current_epoch=3)[0] for _ in range(3)]
    c = RolloutBuffer(8, seed=0).load_state_dict(sd)
    cont_c = [c.sample_batch(4, 8, current_epoch=3)[0] for _ in range(3)]
    for xa, xc in zip(cont_a, cont_c):
        np.testing.assert_array_equal(xa, xc)
    with pytest.raises(ValueError):
        RolloutBuffer(4, seed=0).load_state_dict(sd)  # capacity mismatch


# ---------------------------------------------------------------------------
# satellite: reshard_state per-leaf hit stats
# ---------------------------------------------------------------------------


def test_reshard_state_reports_per_leaf_stats():
    live = [jnp.arange(8, dtype=jnp.float32),
            jnp.ones((4, 4), jnp.float32)]
    tgt = [jnp.zeros(8, jnp.float32), jnp.zeros((4, 4), jnp.float32)]
    stats = {}
    out = reshard_state(live, tgt, stats_out=stats)
    # layout-identical live arrays ride the zero-copy fast path
    assert stats["leaves"] == 2 and stats["zero_copy"] == 2
    assert stats["copied"] == 0 and stats["bytes_moved"] == 0
    assert all(mode == "zero_copy" for _, mode in stats["per_leaf"])
    assert out[0] is live[0]
    # host sources pay the copy, and the bytes are priced
    host = [np.arange(8, dtype=np.float32), np.ones((4, 4), np.float32)]
    stats2 = {}
    reshard_state(host, tgt, stats_out=stats2)
    assert stats2["zero_copy"] == 0 and stats2["copied"] == 2
    assert stats2["bytes_moved"] == 8 * 4 + 16 * 4


def test_gathered_restore_surfaces_reshard_stats(tmp_path):
    m = _gpt(3)
    step = _train_step(m)
    step(jnp.zeros((2, 8), jnp.int32), jnp.zeros((2, 8), jnp.int32))
    mgr = CheckpointManager(str(tmp_path))
    mgr.save(0, state=step.state)
    m2 = _gpt(3)
    step2 = _train_step(m2)
    with pytest.warns(UserWarning):
        mgr.restore_resharded(step2, step=0)
    stats = mgr.last_restore_stats
    assert stats["mode"] == "gathered"
    assert stats["copied_leaves"] > 0 and stats["zero_copy_leaves"] == 0
    assert stats["reshard_bytes_moved"] > 0


# ---------------------------------------------------------------------------
# weight publish: bitwise, versioned, recompile-free
# ---------------------------------------------------------------------------


def test_publish_bitwise_no_recompile_and_epoch_attribution():
    eng, step, rt = _loop()
    reqs = [Request(rid=f"w{i}", prompt=[1 + i, 2, 3, 4],
                    max_new_tokens=4) for i in range(3)]
    eng.run(reqs)                      # warm the bucketed programs
    compiles0 = sc.kind_stats("decode_step")["compiles"]
    for k in range(3):                 # three publish epochs, each pinned
        step(jnp.ones((2, 8), jnp.int32), jnp.ones((2, 8), jnp.int32))
        stats = rt.publisher.publish(master_leaves(step))
        assert stats["epoch"] == k + 1
        assert stats["zero_copy"] == stats["leaves"] > 0
        assert stats["bytes_moved"] == 0 and not stats["cast_dispatch"]
        for p, mv in zip(eng.model.parameters(), master_leaves(step)):
            np.testing.assert_array_equal(np.asarray(p.data),
                                          np.asarray(mv))
        # generation proceeds under the new weights without recompiling
        eng.run([Request(rid=f"w{k}b", prompt=[5, 6, 7],
                         max_new_tokens=4)])
        assert eng.result_meta[f"w{k}b"]["weight_epoch"] == k + 1
    assert sc.kind_stats("decode_step")["compiles"] == compiles0
    ev = obs.events("rollout.weight_sync")
    assert len(ev) >= 3 and ev[-1]["zero_copy_frac"] == 1.0
    eng.close()


def test_publish_casts_once_through_executor():
    train_m = _gpt(6)
    serve_m = make_self_draft(train_m)
    for p in serve_m.parameters():
        p.data = p.data.astype(jnp.bfloat16)
    eng = ServeEngine(serve_m, num_blocks=16, block_size=8)
    step = _train_step(train_m)
    step(jnp.ones((2, 8), jnp.int32), jnp.ones((2, 8), jnp.int32))
    d0 = sc.kind_stats("weight_publish")["dispatches"]
    pub = WeightPublisher(eng, which="target")
    stats = pub.publish(master_leaves(step))
    # one fused cast dispatch; published leaves == masters cast ONCE
    assert stats["cast_dispatch"]
    assert sc.kind_stats("weight_publish")["dispatches"] == d0 + 1
    for p, mv in zip(serve_m.parameters(), master_leaves(step)):
        np.testing.assert_array_equal(
            np.asarray(p.data), np.asarray(jnp.asarray(mv, jnp.bfloat16)))
    # dtype mismatch is rejected at the engine seam (cast is the
    # publisher's job, exactly once)
    with pytest.raises(ValueError):
        eng.publish_weights(master_leaves(step))
    eng.close()


# ---------------------------------------------------------------------------
# the loop: determinism, staleness, backpressure, leaks
# ---------------------------------------------------------------------------


def test_roundtrip_loss_trajectory_pinned():
    eng1, _, rt1 = _loop(seed=11)
    recs1 = rt1.run(4)
    eng1.close()
    eng2, _, rt2 = _loop(seed=11)
    rt2.run(4)
    eng2.close()
    # seeded end-to-end: two fresh loops replay the exact trajectory
    assert rt1.losses == rt2.losses
    assert len(rt1.losses) == 8 and all(np.isfinite(rt1.losses))
    assert rt1.losses[-1] < rt1.losses[0]          # it actually learns
    assert [r["weight_epoch"] for r in recs1] == [1, 2, 3, 4]


def test_staleness_bound_evicts_in_loop():
    eng, _, rt = _loop(max_staleness=0, capacity=32)
    recs = rt.run(4)
    eng.close()
    # publish bumps the epoch every round; epoch-0 samples must leave
    assert sum(r["evicted"] for r in recs) > 0
    assert rt.buffer.evicted > 0
    # the bound is enforced at round start: one more evict pass leaves
    # nothing over the bound (the final publish aged the tail samples
    # after the last round's evict already ran)
    ep = eng.weight_epochs["target"]
    rt.buffer.evict_stale(ep)
    assert all(a <= rt.buffer.max_staleness for a in rt.buffer.ages(ep))


def test_backpressure_throttles_generation_not_samples():
    # publishes never happen (no epoch growth -> no eviction), so the
    # buffer fills and the serve side must throttle
    eng, _, rt = _loop(capacity=6, publish_every=100,
                       rollouts_per_round=4)
    recs = rt.run(4)
    eng.close()
    assert rt.backpressure_rounds > 0
    assert any(r["submitted"] < rt.rollouts_per_round for r in recs)
    assert rt.buffer.rejects == 0      # reservation: never drop a rollout
    assert len(rt.buffer) <= rt.buffer.capacity


def test_zero_leaked_pool_blocks_after_loop():
    eng, _, rt = _loop(distill=True)
    rt.run(3)
    assert eng.block_pool.occupancy == 0
    eng.close()                         # asserts check_no_leaks


def test_rollout_metrics_are_cataloged():
    eng, _, rt = _loop(distill=True)
    rt.run(3)
    eng.close()
    snap = obs.get_registry().snapshot()
    seen = set()
    for kind in ("counters", "gauges", "histograms"):
        seen |= {n for n in snap[kind] if n.startswith("rollout.")}
    seen |= {e["event"] for e in obs.events()
             if e["event"].startswith("rollout.")}
    missing = {n for n in seen if n not in CATALOG}
    assert not missing, f"uncataloged rollout metrics: {missing}"


# ---------------------------------------------------------------------------
# acceptance pin 2: accept rate strictly improves across publishes
# ---------------------------------------------------------------------------


def test_accept_rate_strictly_improves_over_distill_publishes():
    train_m = _gpt(6)
    serve_m = make_self_draft(train_m)
    draft_master = _gpt(99)            # random-init draft: near-0 accept
    eng = ServeEngine(serve_m, num_blocks=64, block_size=8, max_batch=4,
                      prefill_chunk=4, draft=make_self_draft(draft_master),
                      spec_k=4, spec_policy="on")
    dist = OnlineDistiller(eng, draft_master, lr=1e-3)
    rng = np.random.default_rng(0)
    trace = [[int(t) for t in rng.integers(0, V, size=6)]
             for _ in range(6)]

    def accept_on_trace(tag):
        m0 = eng.metrics()["spec"]
        res = eng.run([Request(rid=f"{tag}.{i}", prompt=p,
                               max_new_tokens=10)
                       for i, p in enumerate(trace)])
        m1 = eng.metrics()["spec"]
        d_off = m1["offered"] - m0["offered"]
        assert d_off > 0
        rate = (m1["accepted"] - m0["accepted"]) / d_off
        # full sequences (prompt + generated continuation) are the
        # on-policy distillation data: the draft must learn the
        # target's behaviour where acceptance is actually measured —
        # off-policy random tokens converge to the target's (weakly
        # input-dependent) modal prediction in a handful of steps and
        # then plateau, so gains would not spread across publishes
        seqs = [np.asarray(p + list(res[f"{tag}.{i}"]), np.int32)
                for i, p in enumerate(trace)]
        return rate, np.stack([np.resize(s, 16) for s in seqs])

    rate0, xs = accept_on_trace("base")
    rates = [rate0]
    for k in range(3):                 # >= 3 distillation publishes
        for _ in range(10):
            dist.train_on(xs)
        dist.publish(accept_rate=rates[-1])
        rate, xs = accept_on_trace(f"pub{k}")
        rates.append(rate)
    assert all(b > a for a, b in zip(rates, rates[1:])), rates
    assert len(dist.publish_log) == 3
    assert [r["epoch"] for r in dist.publish_log] == [1, 2, 3]
    eng.close()


# ---------------------------------------------------------------------------
# acceptance pin 3: chaos resume == uninterrupted trajectory
# ---------------------------------------------------------------------------


def test_resume_equals_uninterrupted_under_train_kill(tmp_path):
    rounds = 6
    eng_u, _, rt_u = _loop(distill=True, seed=5)
    rt_u.run(rounds)
    eng_u.close()
    ref = rt_u.losses
    assert len(ref) == rounds * 2

    mgr = CheckpointManager(str(tmp_path / "ck"))
    eng_i, _, rt_i = _loop(distill=True, seed=5)
    with chaos.session(seed=0) as c:
        # the train.step hook fires for target AND distill steps (3 per
        # round); index 9 is round 3's first target step — mid-round,
        # after three checkpointed round boundaries
        c.on("train.step", action="kill", at=(9,))
        with pytest.raises(chaos.ChaosKilled):
            rt_i.run(rounds, manager=mgr, save_every=1)
    eng_i.close()
    assert mgr.latest_step() == 3

    eng_r, _, rt_r = _loop(distill=True, seed=5)
    resumed_at = rt_r.restore(mgr)
    assert resumed_at == 3 and rt_r.round == 3
    assert rt_r.losses == ref[:6]      # the checkpointed prefix matches
    rt_r.run(rounds - rt_r.round)
    eng_r.close()
    # the FULL trajectory is bitwise the uninterrupted one
    assert rt_r.losses == ref
    assert rt_r.engine.weight_epochs == rt_u.engine.weight_epochs
