"""Transformer encoder-decoder (models/seq2seq.py): shapes, decoder
causality, cross-attention dependence, padding-mask semantics, and a
copy-task convergence check through the fused step."""
import jax.numpy as jnp
import numpy as np

import apex_tpu.nn as nn
from apex_tpu.models import TransformerSeq2Seq

V, H, HEADS = 89, 32, 4


def _tiny(**kw):
    nn.manual_seed(4)
    return TransformerSeq2Seq(vocab_size=V, hidden=H, enc_layers=2,
                              dec_layers=2, heads=HEADS, intermediate=64,
                              max_positions=32, dropout=0.0,
                              attn_dropout=0.0, **kw)


def test_shapes(rng):
    m = _tiny()
    src = jnp.asarray(rng.integers(0, V, (2, 12)))
    tgt = jnp.asarray(rng.integers(0, V, (2, 9)))
    out = m(src, tgt)
    assert out.value.shape == (2, 9, V)


def test_decoder_causality(rng):
    """Target logits at position i must not see target tokens > i (but
    DO see the whole source)."""
    m = _tiny()
    m.eval()
    src = jnp.asarray(rng.integers(0, V, (2, 12)))
    tgt = np.asarray(rng.integers(0, V, (2, 10)))
    out1 = np.asarray(m(src, jnp.asarray(tgt)).value)
    tgt2 = tgt.copy()
    tgt2[:, 6:] = (tgt2[:, 6:] + 7) % V
    out2 = np.asarray(m(src, jnp.asarray(tgt2)).value)
    np.testing.assert_allclose(out1[:, :6], out2[:, :6],
                               rtol=1e-5, atol=1e-5)
    assert np.abs(out1[:, 6:] - out2[:, 6:]).max() > 1e-3


def test_cross_attention_sees_source(rng):
    m = _tiny()
    m.eval()
    src = np.asarray(rng.integers(0, V, (2, 12)))
    tgt = jnp.asarray(rng.integers(0, V, (2, 8)))
    out1 = np.asarray(m(jnp.asarray(src), tgt).value)
    src2 = (src + 11) % V
    out2 = np.asarray(m(jnp.asarray(src2), tgt).value)
    assert np.abs(out1 - out2).max() > 1e-3


def test_source_padding_masked_everywhere(rng):
    """Padded source positions must not influence the output — through
    encoder self-attention AND decoder cross-attention."""
    m = _tiny()
    m.eval()
    src = np.asarray(rng.integers(0, V, (2, 12)))
    mask = np.ones((2, 12), np.int32)
    mask[:, 8:] = 0
    tgt = jnp.asarray(rng.integers(0, V, (2, 8)))
    out1 = np.asarray(m(jnp.asarray(src), tgt,
                        jnp.asarray(mask)).value)
    src2 = src.copy()
    src2[:, 8:] = (src2[:, 8:] + 31) % V     # perturb only padded slots
    out2 = np.asarray(m(jnp.asarray(src2), tgt,
                        jnp.asarray(mask)).value)
    np.testing.assert_allclose(out1, out2, rtol=1e-5, atol=1e-5)


def test_copy_task_converges_through_fused_step(rng):
    """Seq2seq trains end-to-end on a copy task with the fused bf16 step
    (exercises EncdecMultiheadAttn's flash path under jit + grad)."""
    from apex_tpu.nn import functional as F
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.training import make_train_step

    m = _tiny()
    opt = FusedAdam(list(m.parameters()), lr=3e-3)

    def loss_fn(logits, tgt_out):
        return F.cross_entropy(logits.reshape((-1, V)),
                               tgt_out.reshape((-1,)))

    # the packed forward form feeds both streams as batch[0]
    step = make_train_step(m, opt, loss_fn, half_dtype=jnp.bfloat16,
                           loss_scale=1.0)
    src = jnp.asarray(rng.integers(1, V, (8, 10)))
    # teacher forcing: decoder input is the shifted target (BOS=0)
    tgt_in = jnp.concatenate(
        [jnp.zeros((8, 1), src.dtype), src[:, :-1]], axis=1)
    l0 = float(step((src, tgt_in), src))
    for _ in range(40):
        l = float(step((src, tgt_in), src))
    assert np.isfinite(l) and l < l0 - 1.0


def test_packed_input_with_grad_accum(rng):
    """The tuple-packed batch[0] microbatches correctly under
    grad_accum_steps (every leaf splits on the shared batch dim)."""
    from apex_tpu.nn import functional as F
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.training import make_train_step

    m = _tiny()
    opt = FusedAdam(list(m.parameters()), lr=1e-3)

    def loss_fn(logits, tgt_out):
        return F.cross_entropy(logits.reshape((-1, V)),
                               tgt_out.reshape((-1,)))

    step = make_train_step(m, opt, loss_fn, half_dtype=jnp.bfloat16,
                           loss_scale=1.0, grad_accum_steps=2)
    src = jnp.asarray(rng.integers(1, V, (8, 10)))
    tgt_in = jnp.concatenate(
        [jnp.zeros((8, 1), src.dtype), src[:, :-1]], axis=1)
    l0 = float(step((src, tgt_in), src))
    for _ in range(10):
        l = float(step((src, tgt_in), src))
    assert np.isfinite(l) and l < l0


def test_greedy_generate_matches_manual_loop(rng):
    """seq2seq_generate == the eager greedy loop (re-decode the growing
    target each step and argmax position t)."""
    from apex_tpu.models import seq2seq_generate

    m = _tiny()
    m.eval()
    src = jnp.asarray(rng.integers(1, V, (2, 8)))
    n_new = 5
    out = seq2seq_generate(m, src, n_new, bos_id=0)
    assert out.shape == (2, n_new)

    buf = np.zeros((2, n_new + 1), np.int64)
    for t in range(n_new):
        logits = np.asarray(m(src, jnp.asarray(buf)).value)
        buf[:, t + 1] = logits[:, t].argmax(-1)
    np.testing.assert_array_equal(np.asarray(out), buf[:, 1:])

    # compiled program reused for same config
    seq2seq_generate(m, src, n_new, bos_id=0)
    assert len(m._s2s_gen_cache) == 1

    # source padding flows into generation
    mask = np.ones((2, 8), np.int32)
    mask[:, 5:] = 0
    out_m = seq2seq_generate(m, src, n_new,
                             src_attention_mask=jnp.asarray(mask))
    assert out_m.shape == (2, n_new)


def test_generate_sampling_surface(rng):
    """Temperature/top-k sampling on seq2seq_generate: in-vocab tokens,
    key-dependent variation, validated params."""
    import jax
    import pytest
    from apex_tpu.models import seq2seq_generate

    m = _tiny()
    m.eval()
    src = jnp.asarray(rng.integers(1, V, (2, 8)))
    s1 = seq2seq_generate(m, src, 5, temperature=1.0,
                          key=jax.random.PRNGKey(1))
    s2 = seq2seq_generate(m, src, 5, temperature=1.0,
                          key=jax.random.PRNGKey(2))
    assert (np.asarray(s1) != np.asarray(s2)).any()
    assert int(jnp.max(s1)) < V and int(jnp.min(s1)) >= 0
    s3 = seq2seq_generate(m, src, 5, temperature=0.8, top_k=7,
                          key=jax.random.PRNGKey(1))
    assert s3.shape == (2, 5)
    with pytest.raises(ValueError, match="temperature"):
        seq2seq_generate(m, src, 2, temperature=-0.5)
    with pytest.raises(ValueError, match="top_k"):
        seq2seq_generate(m, src, 2, temperature=1.0, top_k=0,
                         key=jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="PRNG"):
        seq2seq_generate(m, src, 2, temperature=0.5)
