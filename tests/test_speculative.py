"""Speculative decoding (inference/speculative.py) and the chunked
cached forward it builds on (LlamaModel.decode_chunk): chunk logits vs
the training forward, chunk-prefilled generate vs the eager oracle, and
the exact-output guarantee — speculative output == target greedy decode
for any draft, including an int8-quantized or garbage draft."""
import numpy as np
import pytest

import jax
import jax.numpy as jnp

import apex_tpu.nn as nn
from apex_tpu.inference import quantize_int8, speculative_generate
from apex_tpu.models.gpt import generate
from apex_tpu.models.llama import LlamaModel, llama_tiny
from apex_tpu.nn.modules import Ctx


def _model(seed=0, **kw):
    nn.manual_seed(seed)
    return llama_tiny(**kw).eval()


def _greedy_oracle(model, prompt, n):
    """Eager full-forward argmax continuation."""
    cur = prompt
    for _ in range(n):
        logits = model(cur).value
        cur = jnp.concatenate(
            [cur, jnp.argmax(logits[:, -1], axis=-1)[:, None]], axis=1)
    return cur


def test_decode_chunk_matches_forward(rng):
    """Teacher-forced chunk scoring reproduces the training forward's
    logits at every position (the cache attention IS causal attention)."""
    model = _model()
    ids = jnp.asarray(rng.integers(0, 1000, (2, 12)))
    want = np.asarray(model(ids).value)
    ctx = Ctx(training=False)
    caches = model.init_caches(2, 16)
    got, _ = model.decode_chunk(ctx, ids, caches, jnp.int32(0))
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-4, atol=2e-4)


def test_decode_chunk_split_matches_whole(rng):
    """Scoring a sequence as two chunks (cache carried between them)
    equals scoring it as one chunk — the cache handoff is exact."""
    model = _model(seed=1)
    ids = jnp.asarray(rng.integers(0, 1000, (2, 10)))
    ctx = Ctx(training=False)
    whole, _ = model.decode_chunk(ctx, ids, model.init_caches(2, 12),
                                  jnp.int32(0))
    caches = model.init_caches(2, 12)
    l1, caches = model.decode_chunk(ctx, ids[:, :6], caches, jnp.int32(0))
    l2, _ = model.decode_chunk(ctx, ids[:, 6:], caches, jnp.int32(6))
    got = jnp.concatenate([l1, l2], axis=1)
    np.testing.assert_allclose(np.asarray(got), np.asarray(whole),
                               rtol=2e-4, atol=2e-4)


def test_prefill_matches_forward(rng):
    """The flash-path prefill produces the training forward's logits and
    leaves the caches equal to chunk-scoring the same tokens."""
    model = _model(seed=11)
    ids = jnp.asarray(rng.integers(0, 1000, (2, 9)))
    want = np.asarray(model(ids).value)
    ctx = Ctx(training=False)
    got, caches_p = model.prefill(ctx, ids, model.init_caches(2, 12))
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-4,
                               atol=2e-4)
    _, caches_c = model.decode_chunk(ctx, ids, model.init_caches(2, 12),
                                     jnp.int32(0))
    for (kp, vp), (kc, vc) in zip(caches_p, caches_c):
        np.testing.assert_allclose(np.asarray(kp), np.asarray(kc),
                                   rtol=2e-5, atol=2e-5)
        np.testing.assert_allclose(np.asarray(vp), np.asarray(vc),
                                   rtol=2e-5, atol=2e-5)


def test_generate_zero_new_tokens_keeps_shape(rng):
    """max_new_tokens=0 returns exactly the prompt (the prefill path
    must not append an unrequested token)."""
    model = _model(seed=12)
    prompt = jnp.asarray(rng.integers(0, 1000, (2, 6)))
    out = generate(model, prompt, max_new_tokens=0)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(prompt))


def test_generate_chunk_prefill_matches_oracle(rng):
    """generate() now prefills Llama prompts in one decode_chunk call;
    greedy output still equals the eager full-forward continuation."""
    model = _model(seed=2)
    prompt = jnp.asarray(rng.integers(0, 1000, (2, 7)))
    out = generate(model, prompt, max_new_tokens=6)
    want = _greedy_oracle(model, prompt, 6)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(want))


@pytest.mark.parametrize("k", [1, 3, 5])
def test_output_matches_target_greedy(rng, k):
    """The guarantee: speculative output is bit-identical to the
    target's own greedy decode, whatever the draft proposes."""
    target = _model(seed=3)
    draft = _model(seed=4, hidden=64, layers=1, heads=2, kv_heads=1)
    prompt = jnp.asarray(rng.integers(0, 1000, (2, 5)))
    want = generate(target, prompt, max_new_tokens=8)
    got = speculative_generate(target, draft, prompt, max_new_tokens=8,
                               k=k)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_output_exact_with_int8_draft(rng):
    """Quantizing the draft changes speed, never output."""
    target = _model(seed=5)
    draft = _model(seed=6, hidden=64, layers=1, heads=2, kv_heads=1)
    quantize_int8(draft, min_size=1)
    prompt = jnp.asarray(rng.integers(0, 1000, (1, 4)))
    want = generate(target, prompt, max_new_tokens=10)
    got = speculative_generate(target, draft, prompt, max_new_tokens=10,
                               k=4)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_perfect_draft_accepts_everything(rng):
    """Draft == target: every proposal is accepted, output still exact
    (exercises the all-accepted cache bookkeeping path)."""
    target = _model(seed=7)
    prompt = jnp.asarray(rng.integers(0, 1000, (2, 4)))
    want = generate(target, prompt, max_new_tokens=9)
    got = speculative_generate(target, target, prompt, max_new_tokens=9,
                               k=3)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_acceptance_stats(rng):
    """return_stats: a perfect draft advances k+1 per round (acceptance
    1.0); stats never change the emitted ids."""
    target = _model(seed=7)
    prompt = jnp.asarray(rng.integers(0, 1000, (2, 4)))
    plain = speculative_generate(target, target, prompt,
                                 max_new_tokens=9, k=3)
    ids, stats = speculative_generate(target, target, prompt,
                                      max_new_tokens=9, k=3,
                                      return_stats=True)
    np.testing.assert_array_equal(np.asarray(ids), np.asarray(plain))
    # token 1 comes from the prefill; the loop covers the other 8 in
    # two all-accepted rounds of k+1 = 4
    assert stats["rounds"] == 2
    assert stats["tokens_per_round"] == 4.0
    assert stats["draft_acceptance"] == 1.0
    # an adversarial draft accepts ~nothing: ~1 token per round
    draft = _model(seed=99, hidden=64, layers=1, heads=2, kv_heads=1)
    _, worst = speculative_generate(target, draft, prompt,
                                    max_new_tokens=9, k=3,
                                    return_stats=True)
    assert worst["rounds"] >= 2
    assert 0.0 <= worst["draft_acceptance"] <= 1.0


def test_gpt_family_prefill_and_speculative(rng):
    """The GPT family implements the same cache protocol: prefill logits
    match the training forward, and speculative output matches the
    target's greedy decode."""
    from apex_tpu.models.gpt import GptModel

    nn.manual_seed(0)
    target = GptModel(vocab_size=307, hidden=64, layers=2, heads=4,
                      max_positions=64, dropout=0.0).eval()
    nn.manual_seed(1)
    draft = GptModel(vocab_size=307, hidden=32, layers=1, heads=2,
                     max_positions=64, dropout=0.0).eval()
    ids = jnp.asarray(rng.integers(0, 307, (2, 8)))
    want = np.asarray(target(ids).value)
    got, _ = target.prefill(Ctx(training=False), ids,
                            target.init_caches(2, 16))
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-4,
                               atol=2e-4)
    base = generate(target, ids, max_new_tokens=7)
    spec = speculative_generate(target, draft, ids, max_new_tokens=7,
                                k=3)
    np.testing.assert_array_equal(np.asarray(spec), np.asarray(base))


def test_validation_errors(rng):
    target = _model(seed=8)
    draft = _model(seed=9)
    prompt = jnp.asarray(rng.integers(0, 1000, (1, 4)))
    with pytest.raises(ValueError, match="k must be >= 1"):
        speculative_generate(target, draft, prompt, 4, k=0)
    with pytest.raises(ValueError, match="max_positions"):
        speculative_generate(target, draft, prompt,
                             max_new_tokens=999, k=4)

    class NoChunk:
        pass

    with pytest.raises(ValueError, match="decode_chunk"):
        speculative_generate(NoChunk(), draft, prompt, 4)


def test_sampled_speculative_matches_target_distribution(rng):
    """Leviathan rejection sampling: the emitted DISTRIBUTION equals the
    target's own sampling.  Exact check: enumerate the target's true
    2-step marginal for a tiny vocab and compare the empirical marginal
    of the second generated token (which always comes from a rejection
    round, draft != target) over many keys."""
    nn.manual_seed(21)
    target = _model(seed=21, vocab_size=16, hidden=32, layers=1, heads=2,
                    kv_heads=1)
    nn.manual_seed(22)
    draft = _model(seed=22, vocab_size=16, hidden=32, layers=1, heads=2,
                   kv_heads=1)
    prompt = jnp.asarray(rng.integers(0, 16, (1, 4)))
    temp = 1.0

    # exact marginal of token 2: sum over token-1 choices
    base = np.asarray(jax.nn.softmax(
        target(prompt).value[0, -1].astype(jnp.float32) / temp))
    marg = np.zeros(16)
    for t1 in range(16):
        ext = jnp.concatenate(
            [prompt, jnp.full((1, 1), t1, prompt.dtype)], axis=1)
        p2 = np.asarray(jax.nn.softmax(
            target(ext).value[0, -1].astype(jnp.float32) / temp))
        marg += base[t1] * p2

    from apex_tpu.inference import speculative_generate
    counts = np.zeros(16)
    n_runs = 400
    for i in range(n_runs):
        out = speculative_generate(target, draft, prompt, 2, k=2,
                                   temperature=temp,
                                   key=jax.random.PRNGKey(1000 + i))
        counts[int(out[0, 5])] += 1
    emp = counts / n_runs
    tv = 0.5 * np.abs(emp - marg).sum()
    assert tv < 0.12, (tv, emp, marg)


def test_sampled_speculative_validation(rng):
    target = _model(seed=23)
    draft = _model(seed=24)
    prompt = jnp.asarray(rng.integers(0, 1000, (2, 4)))
    with pytest.raises(ValueError, match="needs a PRNG key"):
        speculative_generate(target, draft, prompt, 4, temperature=0.8)
    with pytest.raises(ValueError, match="batch 1"):
        speculative_generate(target, draft, prompt, 4, temperature=0.8,
                             key=jax.random.PRNGKey(0))
    with pytest.raises(ValueError, match="temperature"):
        speculative_generate(target, draft, prompt[:1], 4,
                             temperature=-1.0)


def test_k_larger_than_remaining_tokens(rng):
    """k >= max_new_tokens: rounds overshoot into the slack buffer and
    the clamp still emits exactly max_new_tokens, matching greedy."""
    target = _model(seed=30)
    draft = _model(seed=31, hidden=64, layers=1, heads=2, kv_heads=1)
    prompt = jnp.asarray(rng.integers(0, 1000, (1, 5)))
    want = generate(target, prompt, max_new_tokens=3)
    got = speculative_generate(target, draft, prompt, max_new_tokens=3,
                               k=8)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
