"""Step-cache regression tests (apex_tpu/runtime/step_cache.py).

Pins the three tentpole properties of the eager optimizer surface:
* ONE XLA compile per optimizer across many steps even under lr AND
  weight-decay schedules (hyperparameters are traced device scalars);
* numerics bitwise-identical to the pre-cache per-dtype-bucket dispatch
  (the old ``_adam_step`` per-bucket jit) across the fp32/bf16/fp16
  storage cross-product;
* buffer donation on params/optimizer state reflected as input→output
  aliasing in the lowered HLO.
"""
import functools
import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu import ops
from apex_tpu.nn import Parameter
from apex_tpu.optimizers import FusedAdam, FusedLAMB, FusedNovoGrad, FusedSGD
from apex_tpu.runtime import step_cache

SHAPES = [(7,), (5, 3)]


@pytest.fixture(autouse=True)
def _fresh_cache():
    step_cache.clear()
    step_cache.reset_stats()
    yield
    step_cache.clear()
    step_cache.reset_stats()


def _params(rng, dtypes=(jnp.float32,)):
    out = []
    for dtype in dtypes:
        for s in SHAPES:
            p = Parameter(jnp.asarray(rng.standard_normal(s), dtype))
            p.grad = jnp.asarray(rng.standard_normal(s), dtype)
            out.append(p)
    return out


def _regrad(params, rngs):
    for p in params:
        p.grad = jnp.asarray(rngs.standard_normal(p.shape), p.dtype)


# ---------------------------------------------------------------------------
# retrace regression: 1 compile across >= 10 scheduled steps
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("make_opt,kind,expected_compiles", [
    (lambda ps: FusedAdam(ps, lr=1e-2, weight_decay=0.01), "fused_adam", 1),
    (lambda ps: FusedLAMB(ps, lr=1e-2, weight_decay=0.01), "fused_lamb", 1),
    (lambda ps: FusedNovoGrad(ps, lr=1e-2, weight_decay=0.01),
     "fused_novograd", 1),
    # FusedSGD compiles exactly twice over its lifetime: the static
    # first_run flag flips False after the first step
    (lambda ps: FusedSGD(ps, lr=1e-2, momentum=0.9, weight_decay=0.01),
     "fused_sgd", 2),
])
def test_one_compile_under_lr_and_wd_schedule(rng, make_opt, kind,
                                              expected_compiles):
    params = _params(rng)
    opt = make_opt(params)
    rngs = np.random.default_rng(7)
    step_cache.reset_stats()
    for i in range(10):
        # cosine lr schedule AND a weight-decay schedule: both are traced
        # scalars, neither may retrace
        opt.param_groups[0]["lr"] = 1e-2 * 0.5 * (1 + math.cos(math.pi * i / 10))
        opt.param_groups[0]["weight_decay"] = 0.01 * (1 + i / 10.0)
        opt.step()
        _regrad(params, rngs)
    s = step_cache.stats()
    assert s["by_kind"][kind]["compiles"] == expected_compiles
    assert s["by_kind"][kind]["dispatches"] == 10
    for p in params:
        assert bool(jnp.isfinite(p.data.astype(jnp.float32)).all())


def test_jit_cache_agrees_with_stats(rng):
    """The cache key covers everything jit retraces on: the one cached
    program's internal jit cache holds exactly one entry after 10 steps."""
    params = _params(rng)
    opt = FusedAdam(params, lr=1e-2)
    rngs = np.random.default_rng(3)
    for i in range(10):
        opt.param_groups[0]["lr"] = 1e-2 / (i + 1)
        opt.step()
        _regrad(params, rngs)
    (entry,) = [e for e in step_cache.step_cache.entries()
                if e["kind"] == "fused_adam"]
    assert entry["fn"]._cache_size() == 1


# ---------------------------------------------------------------------------
# numerics: bitwise-identical to the pre-cache per-bucket dispatch
# ---------------------------------------------------------------------------

@functools.partial(jax.jit, static_argnames=("mode", "bias_correction"))
def _prebucket_adam_step(flag, lists, lr, step, beta1, beta2, eps, mode,
                         bias_correction, weight_decay):
    """The pre-cache dispatch shape — one jitted executable per dtype
    bucket (old fused_adam.py:15-24) — with satellite-1's traced-scalar fix
    applied (betas/eps/wd enter traced, as they now do everywhere)."""
    return ops.multi_tensor_adam(flag, lists, lr, beta1, beta2, eps, step,
                                 mode, bias_correction, weight_decay)


@functools.partial(
    jax.jit,
    static_argnames=("beta1", "beta2", "eps", "mode", "bias_correction",
                     "weight_decay"))
def _prebucket_adam_step_static(flag, lists, lr, step, beta1, beta2, eps,
                                mode, bias_correction, weight_decay):
    """The ORIGINAL pre-cache dispatch with static hyperparameters (the
    retracing bug satellite 1 removes).  Differs from the traced path by at
    most 1 ulp in the beta complements: ``1.0 - 0.9`` rounds differently
    computed in host double vs on-device f32."""
    return ops.multi_tensor_adam(flag, lists, lr, beta1, beta2, eps, step,
                                 mode, bias_correction, weight_decay)


def _run_prebucket_path(params0, grads0, n_steps, lr_of, wd, dtype,
                        static_hyper, betas=(0.9, 0.999), eps=1e-8):
    ps = [jnp.asarray(w, dtype) for w in params0]
    gs = [jnp.asarray(g, dtype) for g in grads0]
    ms = [jnp.zeros_like(p) for p in ps]
    vs = [jnp.zeros_like(p) for p in ps]
    flag = ops.zero_flag()
    rngs = np.random.default_rng(999)
    for i in range(n_steps):
        if static_hyper:
            _, ps, ms, vs = _prebucket_adam_step_static(
                flag, [gs, ps, ms, vs], jnp.asarray(lr_of(i), jnp.float32),
                jnp.asarray(i + 1, jnp.int32), betas[0], betas[1], eps, 1,
                True, wd)
        else:
            _, ps, ms, vs = _prebucket_adam_step(
                flag, [gs, ps, ms, vs], jnp.asarray(lr_of(i), jnp.float32),
                jnp.asarray(i + 1, jnp.int32),
                jnp.asarray(betas[0], jnp.float32),
                jnp.asarray(betas[1], jnp.float32),
                jnp.asarray(eps, jnp.float32), 1, True,
                jnp.asarray(wd, jnp.float32))
        gs = [jnp.asarray(rngs.standard_normal(p.shape), dtype) for p in ps]
    return ps


@pytest.mark.parametrize("dtype", [jnp.float32, jnp.bfloat16, jnp.float16])
def test_numerics_identical_to_prebucket_path(rng, dtype):
    ws = [rng.standard_normal(s).astype(np.float32) for s in SHAPES]
    gs = [rng.standard_normal(s).astype(np.float32) for s in SHAPES]
    lr_of = lambda i: 1e-2 * 0.5 * (1 + math.cos(math.pi * i / 6))  # noqa: E731

    params = []
    for w, g in zip(ws, gs):
        p = Parameter(jnp.asarray(w, dtype))
        p.grad = jnp.asarray(g, dtype)
        params.append(p)
    opt = FusedAdam(params, lr=1e-2, weight_decay=0.01)
    rngs = np.random.default_rng(999)
    for i in range(6):
        opt.param_groups[0]["lr"] = lr_of(i)
        opt.step()
        _regrad(params, rngs)

    # bitwise vs the per-bucket dispatch: folding every bucket into one
    # donated executable (plus the lax.cond skip) changes NOTHING numerically
    ref = _run_prebucket_path(ws, gs, 6, lr_of, 0.01, dtype,
                              static_hyper=False)
    for p, r in zip(params, ref):
        np.testing.assert_array_equal(np.asarray(p.data), np.asarray(r))

    # and within float tolerance of the original static-hyper dispatch (the
    # only delta is the documented 1-ulp beta-complement rounding)
    ref_static = _run_prebucket_path(ws, gs, 6, lr_of, 0.01, dtype,
                                     static_hyper=True)
    for p, r in zip(params, ref_static):
        np.testing.assert_allclose(
            np.asarray(p.data, np.float32), np.asarray(r, np.float32),
            rtol=1e-5, atol=1e-6)


def test_mixed_dtype_buckets_one_executable(rng):
    """fp32+bf16+fp16 params in one optimizer: still one compile, each
    bucket bitwise-identical to its own pre-cache dispatch."""
    dtypes = (jnp.float32, jnp.bfloat16, jnp.float16)
    params = _params(rng, dtypes)
    opt = FusedAdam(params, lr=1e-2, weight_decay=0.01)
    rngs = np.random.default_rng(5)
    step_cache.reset_stats()
    for _ in range(5):
        opt.step()
        _regrad(params, rngs)
    s = step_cache.stats()["by_kind"]["fused_adam"]
    assert s["compiles"] == 1 and s["dispatches"] == 5
    for p in params:
        assert p.dtype in dtypes
        assert bool(jnp.isfinite(p.data.astype(jnp.float32)).all())


# ---------------------------------------------------------------------------
# donation: input→output aliasing visible in the lowered HLO
# ---------------------------------------------------------------------------

def test_sgd_momentum_buffers_donated(rng):
    # the full donated-leaf aliasing census moved to the executor suite
    # (tests/test_executor.py::test_donation_alias_in_lowered_hlo) with
    # the policy itself; the per-optimizer probes below stay here
    step_cache.set_donation(True)
    try:
        params = _params(rng)
        opt = FusedSGD(params, lr=0.1, momentum=0.9)
        opt.step()
        entries = [e for e in step_cache.step_cache.entries()
                   if e["kind"] == "fused_sgd"]
        txt = entries[0]["fn"].lower(*entries[0]["example"]).as_text()
        assert txt.count("tf.aliasing_output") >= 2 * len(params)
    finally:
        step_cache.set_donation("auto")


def test_donation_auto_off_on_cpu(rng):
    """Under the cpu test backend the auto policy must NOT donate: XLA cpu
    accepts donate_argnums but degrades it to defensive copies (~2x step
    time), so the compiled program carries no aliasing."""
    assert step_cache.donation_enabled() is False
    params = _params(rng)
    opt = FusedAdam(params, lr=1e-2)
    opt.step()
    (entry,) = [e for e in step_cache.step_cache.entries()
                if e["kind"] == "fused_adam"]
    txt = entry["fn"].lower(*entry["example"]).as_text()
    assert "tf.aliasing_output" not in txt


# ---------------------------------------------------------------------------
# satellite: zero_grad drops grads on the fused path (no zeros_like churn)
# ---------------------------------------------------------------------------

def test_zero_grad_drops_grads_by_default(rng):
    for make in (lambda ps: FusedSGD(ps, lr=0.1),
                 lambda ps: FusedAdam(ps, lr=1e-3),
                 lambda ps: FusedLAMB(ps, lr=1e-3),
                 lambda ps: FusedNovoGrad(ps, lr=1e-3)):
        params = _params(rng)
        opt = make(params)
        opt.zero_grad()
        assert all(p.grad is None for p in params)


def test_zero_grad_explicit_false_still_zeroes(rng):
    params = _params(rng)
    opt = FusedSGD(params, lr=0.1)
    opt.zero_grad(set_to_none=False)
    for p in params:
        assert p.grad is not None
        np.testing.assert_array_equal(np.asarray(p.grad), 0.0)


# ---------------------------------------------------------------------------
# amp integration: fused master→model copy + deferred scale update
# ---------------------------------------------------------------------------

def _amp_reset():
    from apex_tpu.amp._amp_state import reset
    reset()


def _small_train(defer, steps=4, sabotage_at=None):
    import apex_tpu.nn as nn
    from apex_tpu import amp
    from apex_tpu.amp._amp_state import _amp_state

    _amp_reset()
    nn.manual_seed(7)
    model = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 4))
    opt = FusedAdam(list(model.parameters()), lr=1e-3)
    kw = {"defer_scale_update": True} if defer else {}
    model, opt = amp.initialize(model, opt, opt_level="O2", verbosity=0, **kw)
    crit = nn.CrossEntropyLoss()
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((8, 16)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 4, (8,)))
    losses = []
    for i in range(steps):
        out = model(x)
        loss = crit(out, y)
        with amp.scale_loss(loss, opt) as scaled:
            scaled.backward()
            if sabotage_at == i:
                p16 = opt._amp_stash.all_fp16_params[0]
                p16.grad = p16.grad.at[(0,) * p16.grad.ndim].set(np.inf)
        opt.step()
        opt.zero_grad()
        losses.append(float(loss))
    scaler = _amp_state.loss_scalers[0]
    _amp_reset()
    return model, opt, losses, scaler


def test_amp_O2_fuses_model_copy_into_step():
    """Under amp O2 the half model copies come out of the step executable —
    no separate master→model program is ever dispatched."""
    _, _, losses, _ = _small_train(defer=False)
    assert losses[-1] < losses[0]
    by_kind = step_cache.stats()["by_kind"]
    assert "amp_master_to_model" not in by_kind
    assert by_kind["fused_adam"]["dispatches"] == 4


def test_deferred_scale_update_trains():
    _, _, losses, scaler = _small_train(defer=True, steps=6)
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]
    # 6 clean steps: scale untouched, unskipped counted on device
    assert scaler.loss_scale() == 2.0 ** 16
    assert scaler._unskipped == 6


def test_deferred_overflow_skips_on_device_and_halves_scale():
    model, opt, _, scaler = _small_train(defer=True, steps=3, sabotage_at=2)
    assert scaler.loss_scale() == 2.0 ** 15
    # the skipped step must not advance the (device-side) step counter
    assert int(opt.param_groups[0]["step"]) == 2


def test_deferred_matches_sync_path_numerics():
    m_sync, opt_sync, losses_sync, _ = _small_train(defer=False, steps=4)
    params_sync = [np.asarray(p.data)
                   for p in opt_sync.param_groups[0]["params"]]
    m_def, opt_def, losses_def, _ = _small_train(defer=True, steps=4)
    params_def = [np.asarray(p.data)
                  for p in opt_def.param_groups[0]["params"]]
    np.testing.assert_allclose(losses_sync, losses_def, rtol=1e-6)
    for a, b in zip(params_sync, params_def):
        np.testing.assert_array_equal(a, b)


# ---------------------------------------------------------------------------
# stats / observability
# ---------------------------------------------------------------------------

def test_stats_counters(rng):
    params = _params(rng)
    opt = FusedAdam(params, lr=1e-2)
    rngs = np.random.default_rng(1)
    step_cache.reset_stats()
    for _ in range(3):
        opt.step()
        _regrad(params, rngs)
    s = step_cache.stats()
    assert s["compiles"] == 1
    assert s["dispatches"] == 3
    assert s["cache_hits"] == 2
    assert s["programs"] == 1
    # eager multi-tensor op calls happen only at trace time now: 1, not 3
    assert s["multi_tensor_calls"] == 1


def test_unscale_is_one_cached_program(rng):
    from apex_tpu.amp.scaler import LossScaler
    s = LossScaler(1024.0)
    step_cache.reset_stats()
    for _ in range(4):
        grads = [jnp.asarray(rng.standard_normal((8,)), jnp.float16),
                 jnp.asarray(rng.standard_normal((4, 4)), jnp.float16)]
        masters = [jax.ShapeDtypeStruct((8,), jnp.float32),
                   jax.ShapeDtypeStruct((4, 4), jnp.float32)]
        out = s.unscale(grads, masters)
        assert out[0].dtype == jnp.float32
    st = step_cache.stats()["by_kind"]["amp_unscale"]
    assert st["compiles"] == 1 and st["dispatches"] == 4
