"""chunked_lm_head_loss: the chunkwise vocab chain must be numerically
identical (up to summation order) to the materialized head+loss chain —
losses, dx (hidden grads), and d(head_weight) accumulated across
chunks; plus the output_hidden model wiring end-to-end."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.contrib.xentropy import (chunked_lm_head_loss,
                                       make_chunked_lm_loss,
                                       softmax_cross_entropy_loss)

E, V = 32, 97


def _oracle(hidden, w, labels, smoothing=0.0, padding_idx=-100,
            logical_vocab=None):
    logits = jnp.matmul(hidden, w.T.astype(hidden.dtype))
    if logical_vocab is not None and logical_vocab < w.shape[0]:
        cols = jax.lax.broadcasted_iota(jnp.int32, logits.shape,
                                        logits.ndim - 1)
        logits = jnp.where(cols < logical_vocab, logits,
                           jnp.asarray(-1e30, logits.dtype))
    return softmax_cross_entropy_loss(logits, labels, smoothing,
                                      padding_idx, True)


@pytest.mark.parametrize("n,chunk", [(24, 8), (25, 8), (24, 100), (7, 2)])
def test_matches_materialized_chain(rng, n, chunk):
    hidden = jnp.asarray(rng.standard_normal((n, E)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((V, E)) * 0.1, jnp.float32)
    labels = jnp.asarray(rng.integers(0, V, (n,)))

    def tot_chunked(h, ww):
        per = chunked_lm_head_loss(h, ww, labels, chunk_rows=chunk)
        return jnp.sum(per ** 2), per

    def tot_ref(h, ww):
        per = _oracle(h, ww, labels)
        return jnp.sum(per ** 2), per

    (_, per_c), (dh_c, dw_c) = jax.value_and_grad(
        tot_chunked, argnums=(0, 1), has_aux=True)(hidden, w)
    (_, per_r), (dh_r, dw_r) = jax.value_and_grad(
        tot_ref, argnums=(0, 1), has_aux=True)(hidden, w)
    np.testing.assert_allclose(np.asarray(per_c), np.asarray(per_r),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(dh_c), np.asarray(dh_r),
                               rtol=1e-4, atol=1e-6)
    np.testing.assert_allclose(np.asarray(dw_c), np.asarray(dw_r),
                               rtol=1e-4, atol=1e-6)


def test_leading_dims_and_padding_idx(rng):
    hidden = jnp.asarray(rng.standard_normal((2, 6, E)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((V, E)) * 0.1, jnp.float32)
    labels = np.asarray(rng.integers(0, V, (2, 6)))
    labels[0, 2] = -100
    labels = jnp.asarray(labels)
    per = chunked_lm_head_loss(hidden, w, labels, chunk_rows=4)
    assert per.shape == (2, 6)
    assert float(per[0, 2]) == 0.0
    ref = _oracle(hidden.reshape(-1, E), w, labels.reshape(-1))
    np.testing.assert_allclose(np.asarray(per).reshape(-1),
                               np.asarray(ref), rtol=1e-5, atol=1e-6)


def test_padded_head_smoothing_exact(rng):
    """Lane-padded head (logical_vocab < V) under smoothing: equals the
    unpadded table's loss exactly (mask-aware smoothing through the
    chunked path)."""
    v_pad = 128
    hidden = jnp.asarray(rng.standard_normal((10, E)), jnp.float32)
    w = jnp.asarray(rng.standard_normal((V, E)) * 0.1, jnp.float32)
    w_pad = jnp.concatenate(
        [w, jnp.asarray(rng.standard_normal((v_pad - V, E)) * 0.1,
                        jnp.float32)])
    labels = jnp.asarray(rng.integers(0, V, (10,)))
    ref = chunked_lm_head_loss(hidden, w, labels, smoothing=0.1)
    got = chunked_lm_head_loss(hidden, w_pad, labels, smoothing=0.1,
                               logical_vocab=V, chunk_rows=4)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)
    # pad table rows receive zero gradient
    dw = jax.grad(lambda ww: jnp.sum(chunked_lm_head_loss(
        hidden, ww, labels, smoothing=0.1, logical_vocab=V,
        chunk_rows=4)))(w_pad)
    assert np.all(np.asarray(dw[V:]) == 0.0)


def test_bf16_hidden(rng):
    hidden = jnp.asarray(rng.standard_normal((16, E)), jnp.bfloat16)
    w = jnp.asarray(rng.standard_normal((V, E)) * 0.1, jnp.bfloat16)
    labels = jnp.asarray(rng.integers(0, V, (16,)))
    per = chunked_lm_head_loss(hidden, w, labels, chunk_rows=8)
    ref = _oracle(hidden, w, labels)
    assert per.dtype == jnp.float32
    np.testing.assert_allclose(np.asarray(per), np.asarray(ref),
                               rtol=1e-3, atol=1e-3)
    dh, dw = jax.grad(lambda h, ww: jnp.sum(chunked_lm_head_loss(
        h, ww, labels, chunk_rows=8)), argnums=(0, 1))(hidden, w)
    assert dh.dtype == jnp.bfloat16 and dw.dtype == jnp.bfloat16


def test_gpt_output_hidden_train_step_parity(rng):
    """A GPT train step over output_hidden + make_chunked_lm_loss
    matches the logits-returning model + fused-xentropy step losses to
    near-f32 for several steps (same init, same batch)."""
    import apex_tpu.nn as nn
    from apex_tpu.models import GptModel
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.training import make_train_step
    from apex_tpu.contrib.xentropy import make_chunked_lm_loss

    def build(output_hidden):
        nn.manual_seed(7)
        m = GptModel(vocab_size=V, hidden=E, layers=2, heads=4,
                     max_positions=16, dropout=0.0, attn_dropout=0.0,
                     output_hidden=output_hidden)
        opt = FusedAdam(list(m.parameters()), lr=1e-3)
        return m, opt

    ids = jnp.asarray(rng.integers(0, V, (4, 16)))

    m1, o1 = build(False)

    def loss_logits(logits, ids_):
        flat = logits[:, :-1].reshape((-1, V))
        tgt = ids_[:, 1:].reshape((-1,))
        return jnp.mean(softmax_cross_entropy_loss(flat, tgt, 0.0, -1,
                                                   True))

    s1 = make_train_step(m1, o1, loss_logits, loss_scale=1.0)

    m2, o2 = build(True)
    s2 = make_train_step(m2, o2,
                         make_chunked_lm_loss(chunk_rows=16,
                                              padding_idx=-1),
                         loss_scale=1.0)
    for step in range(3):
        l1 = float(s1(ids, ids))
        l2 = float(s2(ids, ids))
        np.testing.assert_allclose(l2, l1, rtol=2e-5,
                                   err_msg=f"step {step}")


def test_llama_output_hidden_shapes(rng):
    import apex_tpu.nn as nn
    from apex_tpu.models import LlamaModel

    nn.manual_seed(3)
    m = LlamaModel(vocab_size=V, hidden=E, layers=1, heads=4, kv_heads=2,
                   intermediate=64, max_positions=16, output_hidden=True)
    ids = jnp.asarray(rng.integers(0, V, (2, 8)))
    hidden, w = m(ids).value if hasattr(m(ids), "value") else m(ids)
    assert hidden.shape == (2, 8, E)
    assert w.shape == (V, E)


def test_chunked_composes_with_remat_and_grad_accum(rng):
    """The chunked loss under jax.checkpoint composes with block remat
    and grad accumulation in one compiled step (nested checkpoints +
    scan-in-scan)."""
    import apex_tpu.nn as nn
    from apex_tpu.models import GptModel
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.training import make_train_step
    from apex_tpu.contrib.xentropy import make_chunked_lm_loss

    nn.manual_seed(4)
    m = GptModel(vocab_size=V, hidden=E, layers=2, heads=4,
                 max_positions=16, dropout=0.0, attn_dropout=0.0,
                 remat=True, output_hidden=True)
    opt = FusedAdam(list(m.parameters()), lr=1e-3)
    s = make_train_step(m, opt, make_chunked_lm_loss(chunk_rows=16,
                                                     padding_idx=-1),
                        half_dtype=jnp.bfloat16, loss_scale=1.0,
                        grad_accum_steps=2)
    ids = jnp.asarray(rng.integers(0, V, (4, 16)))
    losses = [float(s(ids, ids)) for _ in range(5)]
    assert all(np.isfinite(losses))
    assert losses[-1] < losses[0]
