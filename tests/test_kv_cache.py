"""Int8 KV cache (inference/quant.py QuantKV + cache_dtype="int8"):
per-position quantization bounds, decode-logit closeness on both LM
families, the speculative exactness guarantee over quantized caches,
and TP decode parity.  Long-context decode re-reads the whole cache
every token, so cache bytes are the traffic lever — same rationale as
weight-only int8 (the reference has no inference path, SURVEY.md §2)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh

import apex_tpu.nn as nn
from apex_tpu.inference import (QuantKV, kv_value, kv_write,
                                make_kv_cache, speculative_generate)
from apex_tpu.models import GptModel, generate
from apex_tpu.models.llama import LlamaModel
from apex_tpu.nn.modules import Ctx

V = 97


def _llama(**kw):
    nn.manual_seed(7)
    return LlamaModel(vocab_size=V, hidden=32, layers=2, heads=4,
                      kv_heads=2, max_positions=64, **kw)


def _gpt(**kw):
    nn.manual_seed(7)
    return GptModel(vocab_size=V, hidden=32, layers=2, heads=4,
                    max_positions=64, dropout=0.0, attn_dropout=0.0, **kw)


def test_kv_roundtrip_bound(rng):
    """Each written position quantizes against its own absmax: error
    <= absmax/254 per position (the quantize_tensor_int8 bound)."""
    cache = make_kv_cache((2, 4, 16, 8), "int8")
    assert isinstance(cache, QuantKV)
    new = jnp.asarray(rng.standard_normal((2, 4, 5, 8)), jnp.float32)
    cache = kv_write(cache, new, (0, 0, 3, 0))
    back = np.asarray(kv_value(cache))[:, :, 3:8]
    want = np.asarray(new)
    bound = np.abs(want).max(axis=-1, keepdims=True) / 254 + 1e-7
    assert (np.abs(back - want) <= bound).all()
    # unwritten slots stay zero
    assert (np.asarray(kv_value(cache))[:, :, :3] == 0).all()


def test_kv_plain_cache_passthrough(rng):
    """The helpers are transparent for plain caches (the default
    path's behavior is unchanged)."""
    cache = make_kv_cache((1, 2, 8, 4), jnp.bfloat16)
    assert cache.dtype == jnp.bfloat16
    new = jnp.asarray(rng.standard_normal((1, 2, 3, 4)), jnp.float32)
    cache = kv_write(cache, new, (0, 0, 0, 0))
    np.testing.assert_allclose(np.asarray(kv_value(cache))[:, :, :3],
                               np.asarray(new), rtol=1e-2, atol=1e-2)


@pytest.mark.parametrize("family", ["gpt", "llama"])
def test_int8_cache_decode_close(rng, family):
    """Quantized-cache correctness has two parts: (1) BOUNDED error —
    prefill logits over an int8 cache stay close to the fp32-cache
    logits; (2) SELF-CONSISTENCY — teacher-forced per-token decode over
    the int8 cache reproduces decode_chunk's logits (both read the
    QUANTIZED entries; prefill intentionally attends the fresh
    full-precision K/V and is slightly more accurate).
    Token-trajectory equality vs the fp cache is deliberately NOT
    asserted: tiny random models have near-tie argmax margins
    comparable to the quantization error, so one early flip cascades —
    real checkpoints have far larger margins."""
    m = (_gpt() if family == "gpt" else _llama())
    m.eval()
    prompt = jnp.asarray(rng.integers(0, V, (2, 6)))
    ctx = Ctx(training=False)
    # (1) bounded error vs the fp32 cache
    l8, _ = m.prefill(ctx, prompt, m.init_caches(2, 32, dtype="int8"))
    lf, _ = m.prefill(ctx, prompt, m.init_caches(2, 32))
    np.testing.assert_allclose(np.asarray(l8), np.asarray(lf),
                               rtol=0.1, atol=0.1)
    # (2) chunked == stepped within the quantized numerics
    want, _ = m.decode_chunk(ctx, prompt,
                             m.init_caches(2, 32, dtype="int8"),
                             jnp.int32(0))
    caches = m.init_caches(2, 32, dtype="int8")
    got = []
    for t in range(6):
        logits, caches = m.decode_step(ctx, prompt[:, t], caches,
                                       jnp.asarray(t))
        got.append(np.asarray(logits))
    np.testing.assert_allclose(np.stack(got, axis=1), np.asarray(want),
                               rtol=2e-4, atol=2e-4)
    # and generate() runs end-to-end over the int8 cache
    out = np.asarray(generate(m, prompt, 16, cache_dtype="int8"))
    assert out.shape == (2, 22)
    assert ((out >= 0) & (out < V)).all()


def test_int8_cache_speculative_exact(rng):
    """The greedy exactness guarantee is cache-dtype-invariant: the
    target scores drafts through the SAME quantized cache numerics its
    own decode uses, so speculative == generate holds bit-for-bit at
    cache_dtype="int8" too."""
    m = _llama()
    m.eval()
    nn.manual_seed(91)
    draft = LlamaModel(vocab_size=V, hidden=16, layers=1, heads=2,
                       max_positions=64)
    draft.eval()
    prompt = jnp.asarray(rng.integers(0, V, (1, 5)))
    want = np.asarray(generate(m, prompt, 12, cache_dtype="int8"))
    got = np.asarray(speculative_generate(m, draft, prompt, 12, k=3,
                                          cache_dtype="int8"))
    np.testing.assert_array_equal(got, want)


def test_int8_cache_tp_decode_matches_single_shard(rng):
    """TP decode with int8 caches: each device quantizes its own head
    shard's writes — identical values quantize identically, so the TP
    tokens still match the single-shard int8-cache decode exactly."""
    m_ref = _llama()
    m_ref.eval()
    m_tp = _llama(tp_axis="tp")
    m_tp.eval()
    for ps, pd in zip(m_ref.parameters(), m_tp.parameters()):
        pd.data = ps.data
    mesh = Mesh(np.array(jax.devices())[:2].reshape(2), ("tp",))
    prompt = jnp.asarray(rng.integers(0, V, (1, 5)))
    want = np.asarray(generate(m_ref, prompt, 10, cache_dtype="int8"))
    got = np.asarray(generate(m_tp, prompt, 10, cache_dtype="int8",
                              mesh=mesh))
    np.testing.assert_array_equal(got, want)


def test_kv_int8_spelling_normalized(rng):
    """cache_dtype=jnp.int8 and "int8" build the SAME quantized cache
    (a raw int8 cache would truncate float K/V to garbage; the jit
    cache also keys both spellings identically, so they must agree)."""
    c1 = make_kv_cache((1, 2, 4, 8), "int8")
    c2 = make_kv_cache((1, 2, 4, 8), jnp.int8)
    assert isinstance(c1, QuantKV) and isinstance(c2, QuantKV)
    m = _llama()
    m.eval()
    prompt = jnp.asarray(rng.integers(0, V, (1, 4)))
    a = np.asarray(generate(m, prompt, 6, cache_dtype="int8"))
    b = np.asarray(generate(m, prompt, 6, cache_dtype=jnp.int8))
    np.testing.assert_array_equal(a, b)
