"""``make_train_step(parallel=...)`` end to end: plan-parity with the
hand-specified knobs (the planner only drives tested primitives), the
step-cache 1-compile/1-dispatch-per-window invariant under a plan,
memory-model validation against XLA's memory_analysis, measured
refinement (auto_tune), and the zero-stage-0 pure-DP path."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import apex_tpu.nn as nn
from apex_tpu.nn import functional as F
from apex_tpu.optimizers import FusedAdam
from apex_tpu.parallel import auto
from apex_tpu.runtime import step_cache
from apex_tpu.training import make_train_step

V, S = 128, 16


def _gpt2_small_shaped(**kw):
    """GPT-2-small architecture at test scale (same topology: learned
    positions, pre-LN blocks, tied LM head; hidden/layers shrunk so the
    8-CPU-device suite stays fast)."""
    from apex_tpu.models import GptModel
    nn.manual_seed(11)
    return GptModel(**{**dict(vocab_size=V, hidden=32, layers=2, heads=4,
                              max_positions=S, dropout=0.0,
                              attn_dropout=0.0), **kw})


def _lm_batch(b=16):
    rng = np.random.default_rng(3)
    ids = jnp.asarray(rng.integers(0, V, (b, S)))
    return ids, jnp.asarray(np.roll(np.asarray(ids), -1, axis=1))


def _lm_loss(logits, tgt):
    return F.cross_entropy(logits.reshape((-1, V)), tgt.reshape((-1,)))


def _mlp():
    nn.manual_seed(0)
    model = nn.Sequential(nn.Linear(64, 512), nn.ReLU(),
                          nn.Linear(512, 512), nn.ReLU(),
                          nn.Linear(512, 8))
    opt = FusedAdam(list(model.parameters()), lr=1e-2)
    return model, opt


def _mlp_batch(b=64):
    rng = np.random.default_rng(1)
    return (jnp.asarray(rng.standard_normal((b, 64)), jnp.float32),
            jnp.asarray(rng.integers(0, 8, (b,))))


def test_auto_plan_parity_gpt2_small():
    """Acceptance: the planner's top plan under a memory cap trains the
    GPT-2-small-shaped model with loss parity to the SAME plan spelled
    out by hand through the explicit knobs, and step_cache.stats() pins
    1 compile + 1 dispatch per window."""
    ids, tgt = _lm_batch()
    m = _gpt2_small_shaped(hidden=64)
    opt = FusedAdam(list(m.parameters()), lr=1e-2)
    n_params = sum(int(np.prod(p.shape)) for p in m.parameters())
    # replicated state needs >= 20 bytes/param (masters 4 + Adam slots 8
    # + grad working set 8); 10 bytes/param admits only sharded plans
    cap = n_params * 10

    step_cache.reset_stats()
    step = make_train_step(m, opt, _lm_loss, half_dtype=None,
                           loss_scale=1.0, parallel="auto",
                           example_batch=(ids, tgt),
                           plan_options=dict(hbm_cap_bytes=cap))
    plan = step.plan
    assert plan.dp > 1 and plan.zero_stage >= 1
    assert step.plan_report is not None
    assert any("memory-infeasible" in r
               for _, r in step.plan_report.rejected)
    losses = [float(step(ids, tgt)) for _ in range(6)]
    assert np.isfinite(losses).all() and losses[-1] < losses[0]
    st = step_cache.stats()["by_kind"]["zero_train_step"]
    assert st["compiles"] == 1
    assert st["dispatches"] == 6        # one dispatch per window

    # the same plan, spelled out by hand through the explicit knobs
    m2 = _gpt2_small_shaped(hidden=64)
    opt2 = FusedAdam(list(m2.parameters()), lr=1e-2)
    kw = plan.step_kwargs(jax.devices())
    assert kw["zero_sharding"] and kw["zero_stage"] == plan.zero_stage
    hand = make_train_step(m2, opt2, _lm_loss, half_dtype=None,
                           loss_scale=1.0, **kw)
    hand_losses = [float(hand(ids, tgt)) for _ in range(6)]
    np.testing.assert_allclose(losses, hand_losses, rtol=1e-6, atol=1e-7)


def test_auto_plan_accum_window_dispatch():
    """A plan carrying K>1 keeps the one-executable window invariant:
    dispatches count windows, not microbatches."""
    x, y = _mlp_batch(b=32)
    model, opt = _mlp()
    plan = auto.Plan(dp=2, zero_stage=1, accum=4, n_devices=8)
    step_cache.reset_stats()
    step = make_train_step(model, opt, _loss_ce, half_dtype=None,
                           loss_scale=1.0, parallel=plan)
    for _ in range(3):
        loss = step(x, y)
    assert np.isfinite(float(loss))
    st = step_cache.stats()["by_kind"]["zero_train_step"]
    assert st["compiles"] == 1 and st["dispatches"] == 3


def _loss_ce(o, t):
    return F.cross_entropy(o, t)


def test_explicit_tp_plan_matches_unsharded_oracle():
    """parallel=Plan(dp=2, tp=4) drives the tested shard_map path: the
    per-step (global-mean) losses track the single-device oracle, and
    the wrapped program registers in the step cache under the plan."""
    ids, tgt = _lm_batch(b=8)

    m = _gpt2_small_shaped(tp_axis="tp")
    opt = FusedAdam(list(m.parameters()), lr=1e-2)
    plan = auto.Plan(dp=2, tp=4, tp_axis="tp", n_devices=8)
    step_cache.reset_stats()
    step = make_train_step(m, opt, _lm_loss, half_dtype=None,
                           loss_scale=1.0, parallel=plan)
    tp_losses = [float(step(ids, tgt)) for _ in range(4)]
    st = step_cache.stats()["by_kind"]["train_step"]
    assert st["compiles"] == 1 and st["dispatches"] == 4

    m2 = _gpt2_small_shaped()
    opt2 = FusedAdam(list(m2.parameters()), lr=1e-2)
    ref = make_train_step(m2, opt2, _lm_loss, half_dtype=None,
                          loss_scale=1.0)
    ref_losses = [float(ref(ids, tgt)) for _ in range(4)]
    np.testing.assert_allclose(tp_losses, ref_losses, rtol=3e-3,
                               atol=3e-3)
    assert tp_losses[-1] < tp_losses[0]


def test_zero_stage0_pure_dp_matches_single_device():
    """zero_stage=0 (what a dp-only zero=0 plan threads): replicated
    state, sharded batch — same losses as the plain jitted step."""
    x, y = _mlp_batch()
    model, opt = _mlp()
    ref = make_train_step(model, opt, _loss_ce, half_dtype=None,
                          loss_scale=1.0)
    ref_losses = [float(ref(x, y)) for _ in range(5)]

    model2, opt2 = _mlp()
    s0 = make_train_step(model2, opt2, _loss_ce, half_dtype=None,
                         loss_scale=1.0, zero_sharding=True, zero_stage=0)
    dp_losses = [float(s0(x, y)) for _ in range(5)]
    np.testing.assert_allclose(dp_losses, ref_losses, rtol=1e-5,
                               atol=1e-6)
    assert all(v.sharding.is_fully_replicated
               for v in s0.state.master_params)


@pytest.mark.parametrize("plan", [
    auto.Plan(dp=1, n_devices=8),
    auto.Plan(dp=1, accum=4, n_devices=8),
    auto.Plan(dp=8, zero_stage=0, n_devices=8),
    auto.Plan(dp=8, zero_stage=1, n_devices=8),
], ids=lambda p: p.name())
def test_memory_model_within_15pct_of_xla(plan):
    """Satellite acceptance: predicted per-device HBM within 15% of
    jax.jit(...).lower().compile().memory_analysis() for known configs
    (prediction extrapolates from probes at two SMALL batch sizes — it
    never sees the validated program)."""
    x, y = _mlp_batch()
    B = int(x.shape[0])
    model, opt = _mlp()
    prof = auto.profile_model(model, opt, _loss_ce,
                              (x[:8], y[:8]))      # probe at b=4/8
    predicted, _ = auto.predict_memory(plan, prof, auto.chip_spec(), B)

    m, o = _mlp()
    # donate_state=True: the HBM model prices the donated steady state
    # (the tpu/gpu production configuration); the default "auto" turns
    # donation off on this cpu backend, which would add the un-aliased
    # output buffers to XLA's measured footprint
    step = make_train_step(m, o, _loss_ce, half_dtype=None,
                           loss_scale=1.0, parallel=plan,
                           donate_state=True)
    step(x, y)
    if plan.dp > 1:
        shs = step._batch_shardings((x, y))
        comp = auto.compile_uncached(
            step._jitted(shs).lower(step.state, x, y))
    else:
        ent = [e for e in step_cache.step_cache.entries()
               if e["kind"] == "train_step"][-1]
        comp = auto.compile_uncached(
            ent["fn"].lower(*ent["example"]))
    measured = auto.measured_step_memory(comp)
    assert measured > 0
    assert abs(predicted - measured) / measured < 0.15, \
        (plan.name(), predicted, measured)


def test_auto_tune_reranks_by_measurement():
    """auto_tune=k compiles and times the top-k predicted plans through
    the real step and re-ranks by measurement."""
    x, y = _mlp_batch(b=32)
    model, opt = _mlp()
    step = make_train_step(model, opt, _loss_ce, half_dtype=None,
                           loss_scale=1.0, parallel="auto",
                           example_batch=(x, y), auto_tune=2)
    assert step.plan.measured_ms is not None
    measured = [p for p in step.plan_report.ranked
                if p.measured_ms is not None]
    assert len(measured) >= 2
    assert measured == sorted(measured, key=lambda p: p.measured_ms)
    assert np.isfinite(float(step(x, y)))


def test_parallel_owns_the_knobs():
    model, opt = _mlp()
    x, y = _mlp_batch(b=8)
    with pytest.raises(ValueError, match="owns the parallelism knobs"):
        make_train_step(model, opt, _loss_ce, parallel="auto",
                        example_batch=(x, y), axis_name="data")
    with pytest.raises(ValueError, match="owns gradient accumulation"):
        make_train_step(model, opt, _loss_ce, parallel="auto",
                        example_batch=(x, y), accum_steps=2)
    with pytest.raises(ValueError, match="example_batch"):
        make_train_step(model, opt, _loss_ce, parallel="auto")
    with pytest.raises(ValueError, match="'auto'"):
        make_train_step(model, opt, _loss_ce, parallel="fastest",
                        example_batch=(x, y))


def test_plan_capability_errors_at_apply():
    """A hand-built plan the model cannot run fails loudly at build, not
    deep inside tracing."""
    model, opt = _mlp()
    with pytest.raises(ValueError, match="without tp_axis"):
        make_train_step(model, opt, _loss_ce,
                        parallel=auto.Plan(dp=2, tp=4, tp_axis="tp",
                                           n_devices=8))
    with pytest.raises(ValueError, match="without sp_axis"):
        make_train_step(model, opt, _loss_ce,
                        parallel=auto.Plan(dp=4, sp=2, sp_axis="sp",
                                           n_devices=8))


def test_infeasible_everything_raises_with_report():
    model, opt = _mlp()
    x, y = _mlp_batch(b=8)
    with pytest.raises(RuntimeError, match="no feasible plan"):
        make_train_step(model, opt, _loss_ce, parallel="auto",
                        example_batch=(x, y),
                        plan_options=dict(hbm_cap_bytes=1024))


def test_abstract_example_batch():
    """example_batch may be ShapeDtypeStructs — nothing executes during
    planning (pure host-side lowering)."""
    model, opt = _mlp()
    x, y = _mlp_batch(b=16)
    ex = (jax.ShapeDtypeStruct(x.shape, x.dtype),
          jax.ShapeDtypeStruct(y.shape, y.dtype))
    step = make_train_step(model, opt, _loss_ce, half_dtype=None,
                           loss_scale=1.0, parallel="auto",
                           example_batch=ex)
    assert step.plan is not None
    assert np.isfinite(float(step(x, y)))
