"""SoftmaxCrossEntropyLoss vs reference cross entropy — mirrors the
reference's contrib xentropy test strategy (fused == unfused numerics
incl. label smoothing and padding_idx)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from apex_tpu.contrib.xentropy import (SoftmaxCrossEntropyLoss,
                                       softmax_cross_entropy_loss)
from apex_tpu.nn import functional as F


def _ref_losses(logits, labels, smoothing):
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), axis=-1)
    c = logits.shape[-1]
    onehot = jax.nn.one_hot(labels, c, dtype=jnp.float32)
    q = (1.0 - smoothing) * onehot + smoothing / c
    return -jnp.sum(q * logp, axis=-1)


@pytest.mark.parametrize("smoothing", [0.0, 0.1])
def test_forward(rng, smoothing):
    logits = jnp.asarray(rng.standard_normal((32, 50)), jnp.float32)
    labels = jnp.asarray(rng.integers(1, 50, (32,)))
    out = SoftmaxCrossEntropyLoss.apply(logits, labels, smoothing)
    ref = _ref_losses(logits, labels, smoothing)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


def test_padding_idx_masks_loss_and_grad(rng):
    logits = jnp.asarray(rng.standard_normal((8, 10)), jnp.float32)
    labels = jnp.asarray([0, 3, 0, 5, 1, 0, 2, 4])  # padding_idx=0 rows

    def total(lg):
        return jnp.sum(softmax_cross_entropy_loss(lg, labels, 0.1, 0))

    losses = softmax_cross_entropy_loss(logits, labels, 0.1, 0)
    assert np.all(np.asarray(losses)[np.asarray(labels) == 0] == 0.0)
    g = jax.grad(total)(logits)
    g = np.asarray(g)
    assert np.all(g[np.asarray(labels) == 0] == 0.0)
    assert np.any(g[np.asarray(labels) != 0] != 0.0)


def test_gradient_matches_reference(rng):
    logits = jnp.asarray(rng.standard_normal((16, 20)), jnp.float32)
    labels = jnp.asarray(rng.integers(1, 20, (16,)))

    def fused(lg):
        return jnp.sum(softmax_cross_entropy_loss(lg, labels, 0.2, -1) ** 2)

    def ref(lg):
        return jnp.sum(_ref_losses(lg, labels, 0.2) ** 2)

    gf = jax.grad(fused)(logits)
    gr = jax.grad(ref)(logits)
    np.testing.assert_allclose(np.asarray(gf), np.asarray(gr),
                               rtol=1e-4, atol=1e-5)


def test_half_to_float(rng):
    logits = jnp.asarray(rng.standard_normal((4, 12)), jnp.bfloat16)
    labels = jnp.asarray(rng.integers(1, 12, (4,)))
    out16 = softmax_cross_entropy_loss(logits, labels, 0.0, 0, False)
    out32 = softmax_cross_entropy_loss(logits, labels, 0.0, 0, True)
    assert out16.dtype == jnp.bfloat16
    assert out32.dtype == jnp.float32


@pytest.mark.parametrize("block", [3, 7, 32])
def test_row_blocked_matches_single_shot(rng, block, monkeypatch):
    """The memory-bounded row-blocked path (APEX_TPU_XENT_BLOCK_ROWS /
    auto-chunking at LM loss shapes) must be numerically identical to the
    single-shot path — blocks of 3 and 7 exercise the non-divisible
    remainder of 32 rows."""
    logits = jnp.asarray(rng.standard_normal((32, 50)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 50, (32,)))  # incl. padding rows

    def run():
        def total(lg):
            per = softmax_cross_entropy_loss(lg, labels, 0.1, 0)
            return jnp.sum(per ** 2), per
        (_, per), grad = jax.value_and_grad(total, has_aux=True)(logits)
        return np.asarray(per), np.asarray(grad)

    loss_ref, grad_ref = run()
    monkeypatch.setenv("APEX_TPU_XENT_BLOCK_ROWS", str(block))
    loss_blk, grad_blk = run()
    # scan-of-vmap and plain vmap fuse reductions differently → 1-ulp noise
    np.testing.assert_allclose(loss_blk, loss_ref, rtol=1e-6, atol=1e-6)
    np.testing.assert_allclose(grad_blk, grad_ref, rtol=1e-6, atol=1e-7)


def test_blocked_preserves_leading_dims(rng, monkeypatch):
    monkeypatch.setenv("APEX_TPU_XENT_BLOCK_ROWS", "2")
    logits = jnp.asarray(rng.standard_normal((4, 6, 10)), jnp.float32)
    labels = jnp.asarray(rng.integers(1, 10, (4, 6)))
    out = softmax_cross_entropy_loss(logits, labels, 0.1, -1)
    assert out.shape == (4, 6)
    ref = _ref_losses(logits, labels, 0.1)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)
    grad = jax.grad(lambda lg: jnp.sum(
        softmax_cross_entropy_loss(lg, labels, 0.1, -1)))(logits)
    assert grad.shape == logits.shape


def test_agrees_with_cross_entropy_mean(rng):
    logits = jnp.asarray(rng.standard_normal((16, 10)), jnp.float32)
    labels = jnp.asarray(rng.integers(1, 10, (16,)))
    per_sample = softmax_cross_entropy_loss(logits, labels, 0.1, -1)
    ce = F.cross_entropy(logits, labels, label_smoothing=0.1)
    np.testing.assert_allclose(float(jnp.mean(per_sample)), float(ce),
                               rtol=1e-5)


@pytest.mark.parametrize("shape,smoothing,pad", [
    ((32, 50), 0.0, 0), ((32, 50), 0.1, 0), ((17, 300), 0.2, -1),
    ((64, 2048), 0.0, -1), ((16, 2500), 0.1, 0),
])
def test_pallas_kernel_matches_jnp_path(rng, shape, smoothing, pad):
    """The fused Pallas kernel (interpret mode) vs the jnp fallback:
    losses, lse-residual behavior (via grads), dtype handling — across
    non-multiple vocab sizes (column padding) and both padding_idx
    conventions."""
    from apex_tpu.ops.pallas import force_mode

    n, c = shape
    logits = jnp.asarray(rng.standard_normal(shape), jnp.float32)
    labels = jnp.asarray(rng.integers(0, c, (n,)))
    if pad == 0:
        labels = labels.at[::5].set(0)  # padding rows

    def total(lg):
        per = softmax_cross_entropy_loss(lg, labels, smoothing, pad, True)
        return jnp.sum(per ** 2), per

    with force_mode("off"):
        (_, per_ref), g_ref = jax.value_and_grad(
            total, has_aux=True)(logits)
    with force_mode("interpret"):
        (_, per_k), g_k = jax.value_and_grad(total, has_aux=True)(logits)
    np.testing.assert_allclose(np.asarray(per_k), np.asarray(per_ref),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(g_k), np.asarray(g_ref),
                               rtol=1e-5, atol=1e-6)


def test_pallas_kernel_bf16_and_leading_dims(rng):
    from apex_tpu.ops.pallas import force_mode

    logits = jnp.asarray(rng.standard_normal((4, 6, 130)), jnp.bfloat16)
    labels = jnp.asarray(rng.integers(1, 130, (4, 6)))

    def total(lg):
        return jnp.sum(softmax_cross_entropy_loss(
            lg, labels, 0.1, -1, True) ** 2)

    with force_mode("off"):
        ref = jax.grad(total)(logits)
    with force_mode("interpret"):
        got = jax.grad(total)(logits)
    assert got.dtype == jnp.bfloat16
    np.testing.assert_allclose(np.asarray(got, np.float32),
                               np.asarray(ref, np.float32),
                               rtol=2e-2, atol=2e-3)


# ---------------------------------------------------------------------------
# Mask-aware smoothing: -1e30-masked columns (the pad_vocab_multiple /
# nucleus_filter convention) carry no smoothing mass, so a lane-padded
# head under smoothing > 0 equals the unpadded model exactly (round-4
# advisor finding: the plain s/C spread multiplied ~-1e30 log-probs in).
# ---------------------------------------------------------------------------

def _padded(logits, pad_cols):
    n = logits.shape[0]
    return jnp.concatenate(
        [logits, jnp.full((n, pad_cols), -1e30, logits.dtype)], axis=-1)


@pytest.mark.parametrize("smoothing", [0.1, 0.3])
def test_smoothing_ignores_masked_columns(rng, smoothing):
    logits = jnp.asarray(rng.standard_normal((16, 50)), jnp.float32)
    labels = jnp.asarray(rng.integers(1, 50, (16,)))
    padded = _padded(logits, 14)   # 50 -> 64, lane-padded

    def tot(lg):
        per = softmax_cross_entropy_loss(lg, labels, smoothing, -1)
        return jnp.sum(per ** 2)

    ref, g_ref = jax.value_and_grad(tot)(logits)
    got, g_pad = jax.value_and_grad(tot)(padded)
    np.testing.assert_allclose(float(got), float(ref), rtol=1e-5)
    # valid columns: identical grads; pad columns: exactly zero
    np.testing.assert_allclose(np.asarray(g_pad[:, :50]),
                               np.asarray(g_ref), rtol=1e-5, atol=1e-6)
    assert np.all(np.asarray(g_pad[:, 50:]) == 0.0)


def test_cross_entropy_label_smoothing_ignores_masked_columns(rng):
    logits = jnp.asarray(rng.standard_normal((16, 50)), jnp.float32)
    labels = jnp.asarray(rng.integers(0, 50, (16,)))
    padded = _padded(logits, 14)

    ref = F.cross_entropy(logits, labels, label_smoothing=0.1)
    got = F.cross_entropy(padded, labels, label_smoothing=0.1)
    np.testing.assert_allclose(float(got), float(ref), rtol=1e-5)

    g_ref = jax.grad(lambda lg: F.cross_entropy(
        lg, labels, label_smoothing=0.1))(logits)
    g_pad = jax.grad(lambda lg: F.cross_entropy(
        lg, labels, label_smoothing=0.1))(padded)
    np.testing.assert_allclose(np.asarray(g_pad[:, :50]),
                               np.asarray(g_ref), rtol=1e-5, atol=1e-6)
    assert np.all(np.asarray(g_pad[:, 50:]) == 0.0)


def test_smoothing_unmasked_semantics_unchanged(rng):
    """Plain (unmasked) inputs keep the reference s/C semantics
    bit-for-bit: mask-aware smoothing only engages below -1e29."""
    logits = jnp.asarray(rng.standard_normal((8, 33)) * 20, jnp.float32)
    labels = jnp.asarray(rng.integers(1, 33, (8,)))
    out = softmax_cross_entropy_loss(logits, labels, 0.2, -1)
    ref = _ref_losses(logits, labels, 0.2)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-6)


def test_pallas_kernel_mask_aware_smoothing(rng):
    """The kernel arm matches the jnp path's mask-aware smoothing (a
    round-4 review finding: it previously kept the plain s/C divisor,
    so interpret-mode runs of lane-padded heads diverged)."""
    from apex_tpu.ops.pallas import force_mode

    logits = jnp.asarray(rng.standard_normal((16, 50)), jnp.float32)
    labels = jnp.asarray(rng.integers(1, 50, (16,)))
    padded = _padded(logits, 14)

    def tot(lg):
        per = softmax_cross_entropy_loss(lg, labels, 0.1, -1)
        return jnp.sum(per ** 2), per

    with force_mode("off"):
        (_, per_ref), g_ref = jax.value_and_grad(
            tot, has_aux=True)(logits)
    with force_mode("interpret"):
        (_, per_k), g_k = jax.value_and_grad(tot, has_aux=True)(padded)
    np.testing.assert_allclose(np.asarray(per_k), np.asarray(per_ref),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(g_k[:, :50]), np.asarray(g_ref),
                               rtol=1e-5, atol=1e-6)
    assert np.all(np.asarray(g_k[:, 50:]) == 0.0)
