"""Llama family (models/llama.py): RoPE/RMSNorm/SwiGLU/GQA decoder —
logit parity vs transformers' LlamaForCausalLM (randomly initialized,
no download), causality, GQA vs expanded-MHA equivalence, KV-cache
decode parity, generate(), fused-step training, and remat parity."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import apex_tpu.nn as nn
from apex_tpu.models import LlamaModel, llama_from_hf, llama_tiny
from apex_tpu.models.gpt import generate
from apex_tpu.models.llama import apply_rope, rope_tables
from apex_tpu.nn import functional as F
from apex_tpu.nn.modules import Ctx


VOCAB = 211


def _ids(rng, b=2, s=13):
    return rng.integers(0, VOCAB, (b, s))


def _hf_llama(kv_heads=2, seed=0):
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    cfg = transformers.LlamaConfig(
        vocab_size=VOCAB, hidden_size=64, intermediate_size=112,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=kv_heads, max_position_embeddings=64,
        rms_norm_eps=1e-6, rope_theta=10000.0, attention_bias=False,
        tie_word_embeddings=False)
    torch.manual_seed(seed)
    m = transformers.LlamaForCausalLM(cfg)
    m.eval()
    return torch, m


@pytest.mark.parametrize("kv_heads", [4, 2, 1])
def test_hf_logit_parity(rng, kv_heads):
    """MHA (kv=heads), GQA (kv=2), and MQA (kv=1) all match HF's torch
    forward — RoPE convention, GQA grouping, SwiGLU, RMSNorm, untied
    head all on the line."""
    torch, hf = _hf_llama(kv_heads=kv_heads)
    ids = _ids(rng)
    with torch.no_grad():
        want = hf(torch.from_numpy(ids)).logits.numpy()
    model = llama_from_hf(hf)
    got = np.asarray(model(jnp.asarray(ids)).value)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def test_hf_from_bare_state_dict(rng):
    torch, hf = _hf_llama(kv_heads=2, seed=3)
    sd = {k: v.numpy() for k, v in hf.state_dict().items()}
    ids = _ids(rng, b=1, s=9)
    with torch.no_grad():
        want = hf(torch.from_numpy(ids)).logits.numpy()
    model = llama_from_hf(sd, heads=4)
    got = np.asarray(model(jnp.asarray(ids)).value)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)
    # geometry round-trip
    assert model.blocks[0].kv_heads == 2
    assert model.blocks[0].heads == 4


def test_rope_tables_shapes_and_rotation():
    """Position-0 rotation is identity; rotating by t then attending is
    equivalent to HF's rotate_half convention (checked structurally:
    norms preserved, dot products depend only on relative offset)."""
    pos = jnp.arange(8, dtype=jnp.int32)
    cos, sin = rope_tables(pos, 16)
    assert cos.shape == (8, 16) and sin.shape == (8, 16)
    x = jnp.asarray(np.random.default_rng(0).standard_normal((1, 1, 8, 16)),
                    jnp.float32)
    rot = apply_rope(x, cos, sin)
    # position 0: angle 0 -> identity
    np.testing.assert_allclose(np.asarray(rot[..., 0, :]),
                               np.asarray(x[..., 0, :]), rtol=1e-6)
    # rotation preserves per-position norms
    np.testing.assert_allclose(
        np.asarray(jnp.linalg.norm(rot, axis=-1)),
        np.asarray(jnp.linalg.norm(x, axis=-1)), rtol=1e-5)
    # relative property: <R_a q, R_b k> == <R_{a+d} q, R_{b+d} k>
    q = x[..., 0:1, :]
    k = x[..., 1:2, :]
    def dot_at(a, b):
        ca, sa = rope_tables(jnp.asarray([a]), 16)
        cb, sb = rope_tables(jnp.asarray([b]), 16)
        return float(jnp.sum(apply_rope(q, ca, sa)
                             * apply_rope(k, cb, sb)))
    assert abs(dot_at(2, 5) - dot_at(4, 7)) < 1e-3


def test_causality(rng):
    """Changing a future token never changes past logits."""
    nn.manual_seed(0)
    model = llama_tiny(vocab_size=VOCAB)
    model.eval()
    ids = _ids(rng, b=1, s=10)
    a = np.asarray(model(jnp.asarray(ids)).value)
    ids2 = ids.copy()
    ids2[0, -1] = (ids2[0, -1] + 7) % VOCAB
    b2 = np.asarray(model(jnp.asarray(ids2)).value)
    np.testing.assert_allclose(a[:, :-1], b2[:, :-1], rtol=1e-5, atol=1e-5)
    assert np.abs(a[:, -1] - b2[:, -1]).max() > 1e-4


def test_gqa_matches_expanded_mha(rng):
    """A GQA model equals the MHA model whose K/V weights are the
    group-expanded copies — the repeat is the exact semantics."""
    nn.manual_seed(1)
    gqa = llama_tiny(vocab_size=VOCAB, heads=4, kv_heads=2)
    nn.manual_seed(1)
    mha = llama_tiny(vocab_size=VOCAB, heads=4, kv_heads=4)
    d = gqa.blocks[0].head_dim
    for bg, bm in zip(gqa.blocks, mha.blocks):
        for pg, pm in zip(bg.parameters(), bm.parameters()):
            if pm.data.shape == pg.data.shape:
                pm.data = pg.data
        for name in ("k_proj", "v_proj"):
            w = getattr(bg, name).weight.data  # (2*d, E)
            getattr(bm, name).weight.data = jnp.repeat(
                w.reshape(2, d, -1), 2, axis=0).reshape(4 * d, -1)
    # remaining (embeddings, norms, head) already copied by seed equality
    gqa.eval(); mha.eval()
    ids = jnp.asarray(_ids(rng, b=2, s=8))
    np.testing.assert_allclose(np.asarray(gqa(ids).value),
                               np.asarray(mha(ids).value),
                               rtol=1e-5, atol=1e-5)


def test_decode_matches_forward(rng):
    """KV-cache decode (KVH-wide caches, grouped-query einsum, on-the-fly
    RoPE at position t) reproduces the full forward."""
    nn.manual_seed(2)
    model = llama_tiny(vocab_size=VOCAB)
    model.eval()
    ids = jnp.asarray(_ids(rng, b=2, s=11))
    full = np.asarray(model(ids).value)

    ctx = Ctx(env={id(p): p.data for p in model.parameters()},
              training=False)
    caches = model.init_caches(2, 11)
    got = []
    for t in range(11):
        logits, caches = model.decode_step(ctx, ids[:, t], caches,
                                           jnp.asarray(t))
        got.append(np.asarray(logits))
    np.testing.assert_allclose(np.stack(got, axis=1), full,
                               rtol=2e-4, atol=2e-4)


def test_generate_runs_llama(rng):
    """The shared generate() drives the Llama decode protocol; greedy
    matches argmax over decode_step logits."""
    nn.manual_seed(3)
    model = llama_tiny(vocab_size=VOCAB)
    model.eval()
    prompt = jnp.asarray(_ids(rng, b=2, s=5))
    out = np.asarray(generate(model, prompt, max_new_tokens=4))
    assert out.shape == (2, 9)
    assert (out[:, :5] == np.asarray(prompt)).all()
    assert (out >= 0).all() and (out < VOCAB).all()


def test_trains_under_fused_step(rng):
    """bf16 fused step + FusedAdam: loss decreases on a fixed batch
    (RMSNorm custom_vjp and RoPE through the full train path)."""
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.training import make_train_step

    nn.manual_seed(4)
    model = llama_tiny(vocab_size=VOCAB)
    model.train()
    opt = FusedAdam(list(model.parameters()), lr=3e-4)

    def lm_loss(logits, ids):
        flat = logits[:, :-1].reshape((-1, VOCAB))
        tgt = ids[:, 1:].reshape((-1,))
        return jnp.mean(F.cross_entropy(flat, tgt))

    step = make_train_step(model, opt, lm_loss, half_dtype=jnp.bfloat16,
                           loss_scale=1.0)
    ids = jnp.asarray(_ids(rng, b=4, s=16))
    l0 = float(step(ids, ids))
    for _ in range(12):
        l = float(step(ids, ids))
    assert np.isfinite(l) and l < l0


def test_remat_parity(rng):
    """remat=True is numerically identical (same loss/grads path as the
    GPT family's remat)."""
    ids = jnp.asarray(_ids(rng, b=2, s=12))
    outs = []
    for remat in (False, True):
        nn.manual_seed(5)
        model = llama_tiny(vocab_size=VOCAB, remat=remat)
        model.eval()
        outs.append(np.asarray(model(ids).value))
    np.testing.assert_allclose(outs[0], outs[1], rtol=1e-6, atol=1e-6)


def test_hf_decoupled_head_dim(rng):
    """Checkpoints whose head_dim != hidden/heads (Mistral-Nemo style)
    load and match — head_dim is inferred from q_proj's rows."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    cfg = transformers.LlamaConfig(
        vocab_size=VOCAB, hidden_size=64, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4,
        num_key_value_heads=2, head_dim=24,  # != 64/4
        max_position_embeddings=64, rms_norm_eps=1e-6,
        attention_bias=False, tie_word_embeddings=False)
    torch.manual_seed(9)
    hf = transformers.LlamaForCausalLM(cfg)
    hf.eval()
    ids = _ids(rng, b=2, s=10)
    with torch.no_grad():
        want = hf(torch.from_numpy(ids)).logits.numpy()
    model = llama_from_hf(hf)
    assert model.blocks[0].head_dim == 24
    got = np.asarray(model(jnp.asarray(ids)).value)
    np.testing.assert_allclose(got, want, rtol=2e-4, atol=2e-4)


def _llama_tp(**kw):
    nn.manual_seed(21)
    return LlamaModel(vocab_size=VOCAB, hidden=32, layers=2, heads=4,
                      kv_heads=2, intermediate=48, max_positions=64, **kw)


def test_tp_llama_forward_and_grads_match_unsharded(rng):
    """2-way TP over GQA heads: logits match the unsharded build, and
    psum-assembled tp_sharded_params grads equal the unsharded model's
    full gradients (the contract make_train_step(tp_axis=) relies on)."""
    from jax.sharding import Mesh, PartitionSpec as P

    ids = jnp.asarray(_ids(rng, b=2, s=8))
    w = jnp.asarray(np.random.default_rng(1).standard_normal(
        (2, 8, VOCAB)), jnp.float32)

    m_ref = _llama_tp()
    m_ref.eval()
    params_ref = list(m_ref.parameters())

    def ref_loss(vals):
        ctx = Ctx(env={id(p): v for p, v in zip(params_ref, vals)},
                  training=False)
        return jnp.sum(m_ref.forward(ctx, ids) * w)

    vals = [p.data for p in params_ref]
    ref_out = m_ref(ids).value
    ref_grads = jax.grad(ref_loss)(vals)

    m_tp = _llama_tp(tp_axis="tp")
    m_tp.eval()
    params_tp = list(m_tp.parameters())
    tp_ids_set = {id(p) for p in m_tp.tp_sharded_params()}
    mesh = Mesh(np.array(jax.devices())[:2].reshape(2), ("tp",))

    def tp_fwd(vals, ids):
        ctx = Ctx(env={id(p): v for p, v in zip(params_tp, vals)},
                  training=False)
        return m_tp.forward(ctx, ids)

    shard_fwd = jax.jit(jax.shard_map(
        tp_fwd, mesh=mesh, in_specs=(P(), P()), out_specs=P(),
        check_vma=False))
    np.testing.assert_allclose(np.asarray(shard_fwd(vals, ids)),
                               np.asarray(ref_out), rtol=2e-4, atol=2e-4)

    def tp_grads(vals, ids, w):
        def f(vals, ids, w):
            def loss(vals):
                return jnp.sum(tp_fwd(vals, ids) * w)
            gs = jax.grad(loss)(vals)
            return [jax.lax.psum(g, "tp") if id(p) in tp_ids_set else g
                    for p, g in zip(params_tp, gs)]
        return jax.jit(jax.shard_map(
            f, mesh=mesh, in_specs=(P(), P(), P()), out_specs=P(),
            check_vma=False))(vals, ids, w)

    for a, b in zip(ref_grads, tp_grads(vals, ids, w)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=3e-4, atol=3e-4)


def test_tp_llama_fused_step_loss_parity(rng):
    """make_train_step(tp_axis=) over a DPxTP mesh trains the TP Llama to
    the same losses as the unsharded fused step."""
    from jax.sharding import Mesh, PartitionSpec as P
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.training import make_train_step

    ids = jnp.asarray(_ids(rng, b=4, s=8))

    def lm_loss(logits, ids):
        flat = logits[:, :-1].reshape((-1, VOCAB))
        tgt = ids[:, 1:].reshape((-1,))
        return jnp.mean(F.cross_entropy(flat, tgt))

    m_ref = _llama_tp()
    m_ref.train()
    ref = make_train_step(m_ref, FusedAdam(list(m_ref.parameters()),
                                           lr=1e-3),
                          lm_loss, half_dtype=None, loss_scale=1.0)
    ref_losses = [float(ref(ids, ids)) for _ in range(3)]

    m_tp = _llama_tp(tp_axis="tp")
    m_tp.train()
    step = make_train_step(m_tp, FusedAdam(list(m_tp.parameters()),
                                           lr=1e-3),
                           lm_loss, half_dtype=None, loss_scale=1.0,
                           axis_name="data", tp_axis="tp")
    mesh = Mesh(np.array(jax.devices()).reshape(4, 2), ("data", "tp"))
    raw = step._step_fn

    def stepped(state, x, y):
        # the in-step loss is one data-shard's mean; pmean gives the
        # global-batch mean the unsharded oracle reports
        state, loss = raw(state, x, y)
        return state, jax.lax.pmean(loss, "data")

    def call(state, x, y):
        return jax.shard_map(
            stepped, mesh=mesh, in_specs=(P(), P("data"), P("data")),
            out_specs=(P(), P()), check_vma=False)(state, x, y)

    jitted = jax.jit(call)
    state = step.state
    tp_losses = []
    for _ in range(3):
        state, loss = jitted(state, ids, ids)
        tp_losses.append(float(loss))
    np.testing.assert_allclose(tp_losses, ref_losses, rtol=2e-4,
                               atol=2e-4)


def test_llama_sp_matches_unsharded_oracle(rng):
    """LlamaModel(sp_axis=...) under shard_map with the sequence sharded
    8-way: logits and parameter gradients match the unsharded model
    (ring attention with global causal offsets, global-position RoPE)."""
    import jax
    from jax.sharding import Mesh, PartitionSpec as P
    from apex_tpu.nn.modules import Ctx

    S_GLOBAL = 32
    V = 211
    ids = jnp.asarray(rng.integers(0, V, (2, S_GLOBAL)))
    w = jnp.asarray(rng.standard_normal((2, S_GLOBAL, V)), jnp.float32)

    def build(sp_axis):
        nn.manual_seed(5)
        return LlamaModel(vocab_size=V, hidden=64, layers=2, heads=4,
                          kv_heads=2, max_positions=S_GLOBAL,
                          sp_axis=sp_axis)

    m_ref = build(None)
    params_ref = list(m_ref.parameters())

    def ref_loss(vals):
        ctx = Ctx(env={id(p): v for p, v in zip(params_ref, vals)},
                  training=False)
        return jnp.sum(m_ref.forward(ctx, ids) * w)

    vals = [p.data for p in params_ref]
    ref_out = m_ref(ids).value
    ref_grads = jax.grad(ref_loss)(vals)

    m_sp = build("sp")
    params_sp = list(m_sp.parameters())
    mesh = Mesh(np.array(jax.devices()), ("sp",))

    def sp_fwd(vals, ids_l):
        ctx = Ctx(env={id(p): v for p, v in zip(params_sp, vals)},
                  training=False)
        return m_sp.forward(ctx, ids_l)

    shard_fwd = jax.jit(jax.shard_map(
        sp_fwd, mesh=mesh, in_specs=(P(), P(None, "sp")),
        out_specs=P(None, "sp", None), check_vma=False))
    sp_out = shard_fwd(vals, ids)
    np.testing.assert_allclose(np.asarray(sp_out), np.asarray(ref_out),
                               rtol=2e-4, atol=2e-4)

    def sp_loss(vals, ids, w):
        def f(vals, ids_l, w_l):
            out = sp_fwd(vals, ids_l)
            return jax.lax.psum(jnp.sum(out * w_l), "sp")
        shard = jax.shard_map(
            f, mesh=mesh,
            in_specs=(P(), P(None, "sp"), P(None, "sp", None)),
            out_specs=P(), check_vma=False)
        return shard(vals, ids, w)

    sp_grads = jax.jit(jax.grad(sp_loss))(vals, ids, w)
    for a, b in zip(ref_grads, sp_grads):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=4e-4, atol=4e-4)


def test_llama_sp_trains_through_fused_step(rng):
    """DP x SP 2-D mesh: the fused step trains a ring-SP Llama with the
    batch on 'data' and the sequence on 'sp'."""
    import jax
    from jax.sharding import Mesh, PartitionSpec as P
    from apex_tpu.nn import functional as F
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.training import make_train_step

    V = 211
    nn.manual_seed(0)
    model = LlamaModel(vocab_size=V, hidden=64, layers=2, heads=4,
                       kv_heads=2, max_positions=32, sp_axis="sp")
    opt = FusedAdam(list(model.parameters()), lr=1e-2)

    def lm_loss(logits, tgt):
        return F.cross_entropy(logits.reshape((-1, V)),
                               tgt.reshape((-1,)))

    step = make_train_step(model, opt, lm_loss, half_dtype=jnp.bfloat16,
                           loss_scale=1.0, axis_name=("data", "sp"))
    rng_np = np.random.default_rng(0)
    ids = jnp.asarray(rng_np.integers(0, V, (4, 32)))
    tgt = jnp.asarray(np.roll(np.asarray(ids), -1, axis=1))
    mesh = Mesh(np.array(jax.devices()).reshape(2, 4), ("data", "sp"))
    sharded = jax.jit(jax.shard_map(
        step._step_fn, mesh=mesh,
        in_specs=(P(), P("data", "sp"), P("data", "sp")),
        out_specs=(P(), P()), check_vma=False))
    state, l0 = sharded(step.state, ids, tgt)
    for _ in range(8):
        state, l = sharded(state, ids, tgt)
    assert np.isfinite(float(l)) and float(l) < float(l0)


def test_llama_sp_rejects_oversized_global_sequence(rng):
    import jax
    from jax.sharding import Mesh, PartitionSpec as P
    from apex_tpu.nn.modules import Ctx

    nn.manual_seed(0)
    m = LlamaModel(vocab_size=64, hidden=32, layers=1, heads=2,
                   max_positions=16, sp_axis="sp")
    params = list(m.parameters())
    ids = jnp.asarray(rng.integers(0, 64, (1, 32)))  # 32*8 > 16
    mesh = Mesh(np.array(jax.devices()), ("sp",))

    def f(vals, ids_l):
        ctx = Ctx(env={id(p): v for p, v in zip(params, vals)},
                  training=False)
        return m.forward(ctx, ids_l)

    with pytest.raises(ValueError, match="global sequence"):
        jax.jit(jax.shard_map(
            f, mesh=mesh, in_specs=(P(), P(None, "sp")),
            out_specs=P(None, "sp", None), check_vma=False))(
            [p.data for p in params], ids)


def test_size_presets_plumb_geometry():
    """Preset helpers merge caller overrides over the published
    geometry (shrunk here — full builds are multi-GB)."""
    from apex_tpu.models import llama_1b, llama_7b
    from apex_tpu.models.gpt import gpt2_large, gpt2_xl

    m = llama_1b(layers=1, vocab_size=64)
    assert m.hidden == 2048 and m.blocks[0].kv_heads == 8
    assert m.rope_theta == 500000.0
    m = llama_7b(layers=1, vocab_size=64)
    assert m.hidden == 4096 and m.blocks[0].heads == 32
    g = gpt2_large(layers=1, vocab_size=64, max_positions=16)
    assert g.hidden == 1280 and g.blocks[0].attn.num_heads == 20
    g = gpt2_xl(layers=1, vocab_size=64, max_positions=16)
    assert g.hidden == 1600 and g.blocks[0].attn.num_heads == 25


def test_sliding_window_decode_matches_mistral(rng):
    """Mistral parity beyond one window: a converted checkpoint with
    sliding_window=8 scored over 13 positions via decode_chunk (the
    banded cached path) reproduces transformers' banded forward."""
    torch = pytest.importorskip("torch")
    transformers = pytest.importorskip("transformers")
    from apex_tpu.models import llama_from_hf
    from apex_tpu.nn.modules import Ctx

    cfg = transformers.MistralConfig(
        vocab_size=151, hidden_size=64, num_hidden_layers=2,
        num_attention_heads=4, num_key_value_heads=2,
        intermediate_size=96, max_position_embeddings=64,
        sliding_window=8, rope_theta=10000.0, attention_dropout=0.0)
    torch.manual_seed(0)
    hf = transformers.MistralForCausalLM(cfg).eval()
    ids = rng.integers(0, 151, (2, 13))
    with torch.no_grad():
        want = hf(torch.from_numpy(ids)).logits.numpy()

    model = llama_from_hf(hf)
    assert model.sliding_window == 8
    # 13 > window: the full-sequence forward applies the band exactly
    # (the banded flash path — formerly this refused)
    got_fwd = np.asarray(model(jnp.asarray(ids)).value)
    np.testing.assert_allclose(got_fwd, want, rtol=3e-4, atol=3e-4)
    # the banded cached path scores it exactly too
    ctx = Ctx(training=False)
    got, _ = model.decode_chunk(ctx, jnp.asarray(ids),
                                model.init_caches(2, 16), jnp.int32(0))
    np.testing.assert_allclose(np.asarray(got), want, rtol=3e-4,
                               atol=3e-4)
    # prefill routes long prompts through the banded path too
    got2, _ = model.prefill(ctx, jnp.asarray(ids),
                            model.init_caches(2, 16))
    np.testing.assert_allclose(np.asarray(got2), want, rtol=3e-4,
                               atol=3e-4)


def test_sliding_window_generate(rng):
    """generate() over a windowed model: decode continues past the
    window (old keys fall out of view) and stays finite."""
    from apex_tpu.models import generate

    nn.manual_seed(0)
    model = llama_tiny(sliding_window=6, max_positions=64).eval()
    prompt = jnp.asarray(rng.integers(0, 1000, (1, 4)))
    out = generate(model, prompt, max_new_tokens=20)
    assert out.shape == (1, 24)
    # oracle: eager banded decode via decode_chunk over the full prefix
    from apex_tpu.nn.modules import Ctx
    ctx = Ctx(training=False)
    cur = prompt
    for _ in range(20):
        logits, _ = model.decode_chunk(
            ctx, cur, model.init_caches(1, cur.shape[1] + 1),
            jnp.int32(0))
        cur = jnp.concatenate(
            [cur, jnp.argmax(logits[:, -1], axis=-1)[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(cur))


def test_llama_decode_chunk_rejects_out_of_range_t0(rng):
    """Same bounds contract as GptModel.decode_chunk: a concrete t0
    whose chunk would clamp the cache write raises instead of silently
    corrupting prefix KV entries."""
    import pytest
    from apex_tpu.models.llama import llama_tiny
    from apex_tpu.nn.modules import Ctx

    m = llama_tiny()
    m.eval()
    toks = jnp.zeros((1, 8), jnp.int32)
    with pytest.raises(ValueError, match="out of range"):
        m.decode_chunk(Ctx(), toks, m.init_caches(1, 64), 60)
    with pytest.raises(ValueError, match="out of range"):
        m.decode_chunk(Ctx(), toks, m.init_caches(1, 64), -1)
    logits, _ = m.decode_chunk(Ctx(), toks, m.init_caches(1, 64), 56)
    assert logits.shape[1] == 8


def test_sliding_window_training_forward_multi_window(rng):
    """Training forward at S spanning MANY windows: fwd logits match
    the banded decode_chunk oracle, and grads are finite — the config
    that previously refused (training a Mistral-shape model at its
    real context length is the point of the banded kernel)."""
    from apex_tpu.models.llama import llama_tiny
    from apex_tpu.nn.modules import Ctx

    nn.manual_seed(3)
    m = llama_tiny(sliding_window=8, max_positions=64)
    m.eval()
    ids = jnp.asarray(rng.integers(0, 1000, (2, 40)))
    got = np.asarray(m(ids).value)
    want, _ = m.decode_chunk(Ctx(), ids, m.init_caches(2, 48),
                             jnp.int32(0))
    np.testing.assert_allclose(got, np.asarray(want), rtol=2e-4,
                               atol=2e-4)
    # gradient flow through the banded path
    m.train()
    logits = m(ids)
    labels = jnp.asarray(rng.integers(0, 1000, (2 * 40,)))
    loss = nn.CrossEntropyLoss()(logits.reshape((-1, 1000)), labels)
    loss.backward()
    assert all(p.grad is None or np.isfinite(np.asarray(p.grad)).all()
               for p in m.parameters())
