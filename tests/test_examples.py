"""Example scripts smoke tests (reference: examples/ are exercised by
tests/L1 clones; here the fast one runs directly)."""
import os
import subprocess
import sys

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_simple_distributed_example_runs():
    env = dict(os.environ, PYTHONPATH=REPO)
    script = os.path.join(REPO, "examples", "simple", "distributed",
                          "distributed_data_parallel.py")
    # force the CPU backend inside the subprocess: the axon TPU plugin
    # ignores the JAX_PLATFORMS env var (see tests/conftest.py)
    code = (f"import jax; jax.config.update('jax_platforms', 'cpu'); "
            f"import runpy; runpy.run_path({script!r}, "
            f"run_name='__main__')")
    out = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, timeout=300, env=env)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "final loss:" in out.stdout
    final = float(out.stdout.rsplit("final loss:", 1)[1].strip())
    assert final < 0.5


def test_bert_example_runs():
    env = dict(os.environ, PYTHONPATH=REPO)
    script = os.path.join(REPO, "examples", "bert", "main_amp.py")
    code = (f"import jax; jax.config.update('jax_platforms', 'cpu'); "
            f"import sys; sys.argv = ['main_amp.py', '--steps', '6', "
            f"'--batch', '4', '--seq-len', '32', '--layers', '2', "
            f"'--hidden', '64', '--heads', '4', '--print-freq', '2']; "
            f"import runpy; runpy.run_path({script!r}, "
            f"run_name='__main__')")
    out = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, timeout=300, env=env)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "final loss:" in out.stdout
    assert "seq/s" in out.stdout


def test_dcgan_fused_example_runs():
    env = dict(os.environ, PYTHONPATH=REPO)
    script = os.path.join(REPO, "examples", "dcgan", "main_amp.py")
    code = (f"import jax; jax.config.update('jax_platforms', 'cpu'); "
            f"import sys; sys.argv = ['main_amp.py', '--fused', "
            f"'--iters', '3', '--batch-size', '4', '--opt-level', 'O2']; "
            f"import runpy; runpy.run_path({script!r}, "
            f"run_name='__main__')")
    out = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, timeout=300, env=env)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "Loss_D" in out.stdout and "Loss_G" in out.stdout


def test_gpt_example_runs():
    env = dict(os.environ, PYTHONPATH=REPO)
    script = os.path.join(REPO, "examples", "gpt", "main_amp.py")
    code = (f"import jax; jax.config.update('jax_platforms', 'cpu'); "
            f"import sys; sys.argv = ['main_amp.py', '--steps', '6', "
            f"'--batch', '2', '--seq-len', '32', '--layers', '2', "
            f"'--hidden', '64', '--heads', '4', '--print-freq', '2']; "
            f"import runpy; runpy.run_path({script!r}, "
            f"run_name='__main__')")
    out = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, timeout=300, env=env)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "final loss:" in out.stdout


def test_gpt_sp_example_runs():
    """The long-context sequence-parallel example: 8-way ring on the
    virtual CPU mesh, remat on, loss finite and improving."""
    env = dict(os.environ, PYTHONPATH=REPO)
    env.pop("XLA_FLAGS", None)   # the script pins its own virtual mesh
    script = os.path.join(REPO, "examples", "gpt", "main_sp.py")
    out = subprocess.run(
        [sys.executable, script, "--devices", "8", "--seq-len", "128",
         "--steps", "12", "--layers", "2", "--hidden", "64", "--heads",
         "4", "--vocab", "97", "--batch", "2", "--lr", "1e-2",
         "--print-freq", "5"],
        capture_output=True, text=True, timeout=500, env=env)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "ring of 8" in out.stdout
    final = float(out.stdout.rsplit("final loss:", 1)[1].strip())
    import math
    # fresh random tokens each step: loss hovers near ln(vocab); just
    # prove the ring step runs and stays numerically sane
    assert math.isfinite(final) and final < math.log(97) + 1.0


def test_gpt_moe_example_runs():
    """The Switch-MoE example: 4 experts on the data axis of a virtual
    CPU mesh, top-2 routing, aux loss in the optimized loss."""
    env = dict(os.environ, PYTHONPATH=REPO)
    env.pop("XLA_FLAGS", None)   # the script pins its own virtual mesh
    script = os.path.join(REPO, "examples", "gpt", "main_moe.py")
    out = subprocess.run(
        [sys.executable, script, "--devices", "4", "--steps", "10",
         "--seq-len", "32", "--layers", "2", "--hidden", "64", "--heads",
         "4", "--vocab", "97", "--batch", "4", "--lr", "1e-2",
         "--top-k", "2", "--print-freq", "5"],
        capture_output=True, text=True, timeout=500, env=env)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "MoE blocks, top-2" in out.stdout
    final = float(out.stdout.rsplit("final loss:", 1)[1].strip())
    import math
    # loss includes the aux term (~aux_weight above the task loss)
    assert math.isfinite(final) and final < math.log(97) + 1.0


def test_gpt_tp_example_runs():
    """The data x tensor parallel example: (2, 4) mesh on the virtual CPU
    backend, Megatron head/MLP sharding, loss finite and sane."""
    env = dict(os.environ, PYTHONPATH=REPO)
    env.pop("XLA_FLAGS", None)   # the script pins its own virtual mesh
    script = os.path.join(REPO, "examples", "gpt", "main_tp.py")
    out = subprocess.run(
        [sys.executable, script, "--dp", "2", "--tp", "4", "--steps", "12",
         "--seq-len", "32", "--layers", "2", "--hidden", "64", "--heads",
         "4", "--vocab", "97", "--batch", "4", "--lr", "1e-2",
         "--print-freq", "5"],
        capture_output=True, text=True, timeout=500, env=env)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "mesh 2x4 (data x tp)" in out.stdout
    final = float(out.stdout.rsplit("final loss:", 1)[1].strip())
    import math
    assert math.isfinite(final) and final < math.log(97) + 1.0


def test_llama_example_runs():
    """Train + prefill generate + int8 self-draft speculative decode in
    one script; the script itself asserts speculative == greedy."""
    env = dict(os.environ, PYTHONPATH=REPO)
    script = os.path.join(REPO, "examples", "llama", "main.py")
    code = (f"import jax; jax.config.update('jax_platforms', 'cpu'); "
            f"import sys; sys.argv = ['main.py', '--steps', '6', "
            f"'--batch', '2', '--seq-len', '32', '--layers', '2', "
            f"'--hidden', '64', '--heads', '4', '--kv-heads', '2', "
            f"'--gen-tokens', '8', '--print-freq', '2']; "
            f"import runpy; runpy.run_path({script!r}, "
            f"run_name='__main__')")
    out = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, timeout=300, env=env)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "matches greedy exactly" in out.stdout


def test_llama_lora_example_runs():
    """LoRA fine-tune example: factors-only training, merge, and the
    merged-decode assertion inside the script."""
    env = dict(os.environ, PYTHONPATH=REPO)
    script = os.path.join(REPO, "examples", "llama", "main_lora.py")
    code = (f"import jax; jax.config.update('jax_platforms', 'cpu'); "
            f"import sys; sys.argv = ['main_lora.py', '--steps', '6', "
            f"'--batch', '2', '--seq-len', '32', '--layers', '2', "
            f"'--hidden', '64', '--rank', '4', '--print-freq', '2']; "
            f"import runpy; runpy.run_path({script!r}, "
            f"run_name='__main__')")
    out = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, timeout=300, env=env)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "merged: decode identical" in out.stdout
    assert "trainable:" in out.stdout


def test_llama_tp_serve_example_runs():
    """TP serving demo: sharded greedy decode bit-identical to
    single-shard, int8 under TP, and TP-target speculative decoding —
    the script itself asserts all three."""
    env = dict(os.environ, PYTHONPATH=REPO,
               XLA_FLAGS="--xla_force_host_platform_device_count=2")
    script = os.path.join(REPO, "examples", "llama", "main_tp_serve.py")
    code = (f"import jax; jax.config.update('jax_platforms', 'cpu'); "
            f"import sys; sys.argv = ['main_tp_serve.py', '--tp', '2', "
            f"'--new-tokens', '12', '--hidden', '64', '--layers', '2']; "
            f"import runpy; runpy.run_path({script!r}, "
            f"run_name='__main__')")
    out = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, timeout=300, env=env)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "bit-identical to single-shard: True" in out.stdout
    assert "exact match with tp int8 decode: True" in out.stdout
    assert "tp beam search (3 beams): bit-identical to single-shard: " \
        "True" in out.stdout


def test_imagenet_channels_last_example_runs(tmp_path):
    """The flagship example's NHWC arm: to_channels_last model + the
    layout-preserving prefetcher train end-to-end (tiny synthetic).
    ONE device: the eager DDP loop's per-op compiles desynchronize
    multi-device rendezvous on a single CPU core (40s timeout); DDP
    collectives are covered by the fused-step and distributed suites —
    this test is about the layout path."""
    env = dict(os.environ, PYTHONPATH=REPO,
               XLA_FLAGS="--xla_force_host_platform_device_count=1")
    script = os.path.join(REPO, "examples", "imagenet", "main_amp.py")
    code = (f"import jax; jax.config.update('jax_platforms', 'cpu'); "
            f"import sys; sys.argv = ['main_amp.py', '--synthetic', "
            f"'--channels-last', '-a', 'resnet18', '-b', '8', "
            f"'--image-size', '32', '--iters-per-epoch', '4', "
            f"'--print-freq', '2', "
            f"'--checkpoint', {str(tmp_path / 'ck.pkl')!r}]; "
            f"import runpy; runpy.run_path({script!r}, "
            f"run_name='__main__')")
    out = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, timeout=600, env=env)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "img/s" in out.stdout or "loss" in out.stdout.lower()


def test_gpt_session_example_runs():
    """The serving-session demo: multi-turn int8 chat with the one-shot
    exactness assertion inside the script."""
    env = dict(os.environ, PYTHONPATH=REPO)
    script = os.path.join(REPO, "examples", "gpt", "main_session.py")
    code = (f"import jax; jax.config.update('jax_platforms', 'cpu'); "
            f"import sys; sys.argv = ['main_session.py', '--turns', '2', "
            f"'--reply-tokens', '6', '--hidden', '64']; "
            f"import runpy; runpy.run_path({script!r}, "
            f"run_name='__main__')")
    out = subprocess.run(
        [sys.executable, "-c", code],
        capture_output=True, text=True, timeout=600, env=env)
    assert out.returncode == 0, out.stderr[-2000:]
    assert "equals one-shot decode of the history: True" in out.stdout
