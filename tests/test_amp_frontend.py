"""Properties / opt-level preset behavior (reference frontend.py:7-191) and
amp.state_dict round-trip (frontend.py:361-400)."""
import jax.numpy as jnp
import pytest

from apex_tpu import amp
from apex_tpu.amp import LossScaler, Properties
from apex_tpu.amp._amp_state import _amp_state
from apex_tpu.amp.frontend import opt_levels, resolve_dtype


def _props(level):
    return opt_levels[level](Properties())


def test_preset_O0():
    p = _props("O0")
    assert p.cast_model_type == jnp.float32
    assert p.patch_torch_functions is False
    assert p.master_weights is False
    assert p.loss_scale == 1.0


def test_preset_O1():
    p = _props("O1")
    assert p.cast_model_type is None
    assert p.patch_torch_functions is True
    assert p.keep_batchnorm_fp32 is None
    assert p.loss_scale == "dynamic"


def test_preset_O2():
    p = _props("O2")
    assert p.cast_model_type == jnp.float16
    assert p.keep_batchnorm_fp32 is True
    assert p.master_weights is True
    assert p.loss_scale == "dynamic"


def test_preset_O3():
    p = _props("O3")
    assert p.cast_model_type == jnp.float16
    assert p.keep_batchnorm_fp32 is False
    assert p.master_weights is False
    assert p.loss_scale == 1.0


def test_O1_rejects_cast_model_type():
    p = _props("O1")
    with pytest.raises(RuntimeError):
        p.cast_model_type = jnp.float16


def test_O1_rejects_keep_batchnorm():
    p = _props("O1")
    with pytest.raises(RuntimeError):
        p.keep_batchnorm_fp32 = True


def test_O2_accepts_bfloat16_override():
    p = _props("O2")
    p.cast_model_type = "bfloat16"
    assert p.cast_model_type == jnp.bfloat16


def test_keep_batchnorm_string_conversion():
    p = _props("O2")
    p.keep_batchnorm_fp32 = "False"
    assert p.keep_batchnorm_fp32 is False
    p.keep_batchnorm_fp32 = "True"
    assert p.keep_batchnorm_fp32 is True


def test_loss_scale_coerced_to_float():
    p = _props("O2")
    p.loss_scale = 128
    assert p.loss_scale == 128.0 and isinstance(p.loss_scale, float)
    p.loss_scale = "dynamic"
    assert p.loss_scale == "dynamic"


def test_resolve_dtype_aliases():
    assert resolve_dtype("bf16") == jnp.bfloat16
    assert resolve_dtype("float16") == jnp.float16
    assert resolve_dtype(jnp.float32) == jnp.float32
    import torch
    assert resolve_dtype(torch.float16) == jnp.float16
    assert resolve_dtype(torch.bfloat16) == jnp.bfloat16


def test_state_dict_roundtrip():
    _amp_state.loss_scalers = [LossScaler("dynamic"), LossScaler(128.0)]
    _amp_state.loss_scalers[0]._loss_scale = 2.0 ** 12
    _amp_state.loss_scalers[0]._unskipped = 7
    sd = amp.state_dict()
    assert sd["loss_scaler0"] == {"loss_scale": 2.0 ** 12, "unskipped": 7}
    assert sd["loss_scaler1"]["loss_scale"] == 128.0

    _amp_state.loss_scalers = [LossScaler("dynamic"), LossScaler("dynamic")]
    amp.load_state_dict(sd)
    assert _amp_state.loss_scalers[0].loss_scale() == 2.0 ** 12
    assert _amp_state.loss_scalers[0]._unskipped == 7
    assert _amp_state.loss_scalers[1].loss_scale() == 128.0


def test_load_state_dict_rejects_unexpected_keys():
    _amp_state.loss_scalers = [LossScaler("dynamic")]
    with pytest.raises(RuntimeError):
        amp.load_state_dict({"bogus": {}})
