"""jax-version compat shim (apex_tpu/compat.py).

The repository targets the modern jax surface (``jax.shard_map`` with
``check_vma``, ``jax.lax.axis_size``) but must run on jax 0.4.x, where
``shard_map`` lives in ``jax.experimental.shard_map`` (knob spelled
``check_rep``) and ``axis_size`` does not exist.  Everything goes through
the shim — the lint below enforces that no apex_tpu source file calls
``jax.shard_map`` directly — and ``compat.install()`` polyfills the
modern names onto the ``jax`` module so user code written against them
runs unchanged.
"""
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu import compat
from apex_tpu import lint as tpu_lint

PKG_ROOT = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "apex_tpu")


def _compat_findings():
    """One source of truth: the COMPAT-SHIM rule of the apex_tpu.lint
    engine (these tests used to be ad-hoc regex greps; they are now thin
    wrappers asserting the engine reports zero findings)."""
    return tpu_lint.run([PKG_ROOT], select=["COMPAT-SHIM"], baseline=None)


def test_lint_no_direct_jax_shard_map_references():
    """Every shard_map call site goes through apex_tpu.compat — a direct
    ``jax.shard_map`` reference is an AttributeError on jax 0.4.x."""
    bad = [f for f in _compat_findings().active()
           if "shard_map" in f.message]
    assert not bad, (
        "direct jax.shard_map references (use apex_tpu.compat.shard_map): "
        + "\n".join(f.format() for f in bad))


def test_lint_no_direct_lax_axis_size_references():
    bad = [f for f in _compat_findings().active()
           if "axis_size" in f.message]
    assert not bad, (
        "direct lax.axis_size references (use apex_tpu.compat.axis_size): "
        + "\n".join(f.format() for f in bad))


def test_lint_walk_covers_auto_planner():
    """The engine must actually SCAN the parallelism planner
    (parallel/auto.py drives shard_map through the compat shim; a lint
    that silently skipped it could not enforce the jax-0.4.37 invariant
    there)."""
    files = {os.path.relpath(p, PKG_ROOT)
             for p in _compat_findings().files}
    assert os.path.join("parallel", "auto.py") in files
    assert os.path.join("runtime", "step_cache.py") in files


def test_auto_planner_uses_compat_shard_map():
    """parallel/auto.py's explicit-axis wrap must resolve shard_map via
    apex_tpu.compat (the source-level lint above catches `jax.shard_map`
    spellings; this pins the positive side — the shim import is present
    and the module carries no direct jax.experimental.shard_map use)."""
    path = os.path.join(PKG_ROOT, "parallel", "auto.py")
    with open(path) as f:
        text = "\n".join(line.split("#", 1)[0]
                         for line in f.read().splitlines())
    assert "compat" in text and "compat.shard_map" in text
    assert "jax.experimental.shard_map" not in text


def _mesh():
    return Mesh(np.array(jax.devices()), ("data",))


def test_compat_shard_map_runs_with_check_vma():
    """The modern keyword surface works on this jax (0.4.x translates
    check_vma → check_rep; >= 0.5 forwards natively)."""
    mesh = _mesh()
    n = len(jax.devices())

    def body(x):
        return jax.lax.psum(x, "data")

    fn = compat.shard_map(body, mesh=mesh, in_specs=P("data"),
                          out_specs=P("data"), check_vma=False)
    x = jnp.arange(n, dtype=jnp.float32)
    out = np.asarray(jax.jit(fn)(x))
    np.testing.assert_allclose(out, np.full((n,), x.sum()))


def test_compat_axis_size_inside_shard_map():
    mesh = _mesh()
    n = len(jax.devices())

    def body(x):
        return x * compat.axis_size("data")

    fn = compat.shard_map(body, mesh=mesh, in_specs=P("data"),
                          out_specs=P("data"), check_vma=False)
    out = np.asarray(jax.jit(fn)(jnp.ones((n,), jnp.float32)))
    np.testing.assert_allclose(out, np.full((n,), float(n)))


def test_install_polyfills_modern_names():
    """Importing apex_tpu is enough for user code written against the
    modern jax API: jax.shard_map and jax.lax.axis_size both resolve
    (natively on >= 0.5, via the polyfill on 0.4.x)."""
    compat.install()        # idempotent
    assert callable(jax.shard_map)
    assert callable(jax.lax.axis_size)
    mesh = _mesh()
    n = len(jax.devices())

    fn = jax.shard_map(lambda x: jax.lax.psum(x, "data"), mesh=mesh,
                       in_specs=P("data"), out_specs=P("data"),
                       check_vma=False)
    out = np.asarray(jax.jit(fn)(jnp.ones((n,), jnp.float32)))
    np.testing.assert_allclose(out, np.full((n,), float(n)))


def test_polyfill_supports_curried_use():
    """The polyfilled jax.shard_map also works curried —
    ``jax.shard_map(mesh=..., ...) (f)`` — matching the functools.partial
    idiom some user code uses."""
    if compat.HAS_NATIVE_SHARD_MAP:
        pytest.skip("native jax.shard_map: currying is jax's own surface")
    mesh = _mesh()
    n = len(jax.devices())
    deco = jax.shard_map(mesh=mesh, in_specs=P("data"),
                         out_specs=P("data"), check_vma=False)
    fn = deco(lambda x: x + compat.axis_size("data"))
    out = np.asarray(jax.jit(fn)(jnp.zeros((n,), jnp.float32)))
    np.testing.assert_allclose(out, np.full((n,), float(n)))
