"""One-executable gradient accumulation (ISSUE 4 tentpole).

Pins the four acceptance properties of ``make_train_step(accum_steps=K)``:

* numerics: the K-microbatch on-device scan matches K eager
  ``scale_loss(delay_unscale=True)`` backwards + one ``optimizer.step()``
  — bitwise for FusedSGD fp32, within tolerance for FusedAdam and the
  bf16/fp16 master configurations (the eager surface accumulates in the
  model's half dtype where the scan accumulates fp32);
* overflow: a non-finite gradient in ANY single microbatch skips the
  WHOLE window and halves the dynamic scale exactly once;
* dispatch: one accumulation window is ONE cached XLA dispatch — 1
  compile and 1 dispatch per window in ``step_cache.stats()`` even under
  an on-device cosine lr schedule;
* ZeRO: ``zero_sharding=True`` + ``accum_steps`` matches the plain
  accumulated step (the reduce-scatter/all-gather pair fires once per
  window inside the same one program).

Plus the satellite guards: the delayed-unscale finalize at ``step()``
(no double-unscale, no scaled-gradient step), DDP's
``attach_optimizer`` one-exchange-per-window wiring, and the stacked
``(K, B, ...)`` data-pipeline path.
"""
import math

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import apex_tpu.nn as nn
from apex_tpu import amp
from apex_tpu.nn import functional as F
from apex_tpu.optimizers import FusedAdam, FusedSGD
from apex_tpu.runtime import step_cache
from apex_tpu.training import make_train_step

K, B, D, C = 4, 4, 6, 3


@pytest.fixture(autouse=True)
def _fresh_state():
    from apex_tpu.amp._amp_state import reset
    step_cache.clear()
    step_cache.reset_stats()
    reset()
    yield
    step_cache.clear()
    step_cache.reset_stats()
    reset()


def _block(seed=0):
    rng = np.random.default_rng(seed)
    xs = jnp.asarray(rng.standard_normal((K, B, D)), jnp.float32)
    ys = jnp.asarray(rng.integers(0, C, (K, B)))
    return xs, ys


def _model(seed=7):
    nn.manual_seed(seed)
    return nn.Sequential(nn.Linear(D, 8), nn.ReLU(), nn.Linear(8, C))


def _fused_masters(opt_cls, half, scale, lr=0.05, **kw):
    """One fused accum_steps=K step over the stacked block → fp32 masters."""
    xs, ys = _block()
    m = _model()
    opt = opt_cls(list(m.parameters()), lr=lr)
    step = make_train_step(m, opt, lambda o, t: F.cross_entropy(o, t),
                           half_dtype=half, loss_scale=scale,
                           accum_steps=K, accum_stacked=True, **kw)
    step(xs, ys)
    return [np.asarray(v, np.float32) for v in step.state.master_params]


def _eager_masters(opt_cls, half, scale, lr=0.05):
    """The reference pattern: K delayed backwards (loss/K) + one step."""
    xs, ys = _block()
    m = _model()
    opt = opt_cls(list(m.parameters()), lr=lr)
    m, opt = amp.initialize(m, opt, opt_level="O0" if half is None else "O2",
                            loss_scale=scale, verbosity=0)
    crit = nn.CrossEntropyLoss()
    for i in range(K):
        loss = crit(m(xs[i]), ys[i]) / K
        with amp.scale_loss(loss, opt, delay_unscale=(i < K - 1)) as sl:
            sl.backward()
    opt.step()
    return [np.asarray(p.data, np.float32)
            for g in opt.param_groups for p in g["params"]]


# ---------------------------------------------------------------------------
# (a) numerics vs the eager K-step reference
# ---------------------------------------------------------------------------

def test_accum_matches_eager_sgd_fp32_bitwise():
    fused = _fused_masters(FusedSGD, None, 1.0)
    eager = _eager_masters(FusedSGD, None, 1.0)
    for a, b in zip(fused, eager):
        assert np.array_equal(a, b)


@pytest.mark.parametrize("opt_cls,half,scale,tol", [
    (FusedSGD, jnp.bfloat16, 128.0, 2e-3),
    (FusedSGD, jnp.float16, 128.0, 2e-3),
    (FusedAdam, None, 1.0, 1e-5),
    (FusedAdam, jnp.bfloat16, 128.0, 5e-3),
    (FusedAdam, jnp.float16, 128.0, 5e-3),
], ids=["sgd-bf16", "sgd-fp16", "adam-fp32", "adam-bf16", "adam-fp16"])
def test_accum_matches_eager_within_tol(opt_cls, half, scale, tol):
    """Halves accumulate in half dtype on the eager surface and in fp32
    inside the scan, so parity is tolerance-bounded, not bitwise."""
    fused = _fused_masters(opt_cls, half, scale)
    eager = _eager_masters(opt_cls, half, scale)
    for a, b in zip(fused, eager):
        np.testing.assert_allclose(a, b, rtol=tol, atol=tol)


def test_flat_batch_equals_stacked_block():
    """accum_steps over a flat (K*B, ...) batch and accum_stacked over the
    pre-stacked (K, B, ...) block are the same program modulo the one
    reshape — numerics identical."""
    xs, ys = _block()

    def run(stacked):
        m = _model()
        opt = FusedSGD(list(m.parameters()), lr=0.05)
        step = make_train_step(m, opt,
                               lambda o, t: F.cross_entropy(o, t),
                               half_dtype=None, loss_scale=1.0,
                               accum_steps=K, accum_stacked=stacked)
        if stacked:
            step(xs, ys)
        else:
            step(xs.reshape(K * B, D), ys.reshape(K * B))
        return [np.asarray(v) for v in step.state.master_params]

    for a, b in zip(run(True), run(False)):
        assert np.array_equal(a, b)


# ---------------------------------------------------------------------------
# (b) overflow in any single microbatch
# ---------------------------------------------------------------------------

def test_overflow_in_one_microbatch_skips_window_halves_once():
    xs, ys = _block()
    # poison ONE microbatch: under fp16 with a 2**15 scale the scaled loss
    # overflows, so that microbatch's gradients are non-finite — the flag
    # must OR across the window
    xs = xs.at[2].set(xs[2] * 1e4)
    m = _model()
    opt = FusedSGD(list(m.parameters()), lr=0.05)
    step = make_train_step(m, opt, lambda o, t: F.cross_entropy(o, t),
                           half_dtype=jnp.float16, loss_scale="dynamic",
                           accum_steps=K, accum_stacked=True)
    before = [np.asarray(v) for v in step.state.master_params]
    scale0 = float(step.state.scaler.loss_scale)
    step(xs, ys)
    # whole window skipped: masters untouched, step counter not advanced
    for a, b in zip(before, step.state.master_params):
        assert np.array_equal(a, np.asarray(b))
    assert int(step.state.step) == 0
    assert int(step.state.scaler.overflow) == 1
    # the scale halves exactly ONCE for the window (not once per overflowed
    # microbatch)
    assert float(step.state.scaler.loss_scale) == scale0 / 2.0
    # a clean follow-up window applies and does not touch the scale again
    xs2, ys2 = _block(1)
    step(xs2, ys2)
    assert int(step.state.step) == 1
    assert float(step.state.scaler.loss_scale) == scale0 / 2.0


# ---------------------------------------------------------------------------
# (c) one compile, one dispatch per window
# ---------------------------------------------------------------------------

def test_one_compile_one_dispatch_per_window_under_cosine_lr():
    """The acceptance pin: a K=16 window is ONE cached XLA dispatch, and
    an on-device cosine lr schedule never retraces it."""
    rng = np.random.default_rng(0)
    xs = jnp.asarray(rng.standard_normal((16, 2, D)), jnp.float32)
    ys = jnp.asarray(rng.integers(0, C, (16, 2)))
    m = _model()
    opt = FusedSGD(list(m.parameters()), lr=0.1)

    def cosine(step_count):
        return 0.5 * (1.0 + jnp.cos(step_count / 100.0 * math.pi))

    step = make_train_step(m, opt, lambda o, t: F.cross_entropy(o, t),
                           half_dtype=None, loss_scale=1.0,
                           accum_steps=16, accum_stacked=True,
                           lr_schedule=cosine)
    step_cache.reset_stats()
    windows = 5
    for _ in range(windows):
        step(xs, ys)
    stats = step_cache.stats()["by_kind"]["train_step"]
    assert stats["compiles"] == 1
    assert stats["dispatches"] == windows
    assert stats["cache_hits"] == windows - 1


def test_k_joins_the_static_cache_key():
    """A K=2 and a K=4 window over byte-identical (K*B, ...) batches are
    different executables — K is part of the static key, so flipping K
    can never silently reuse the wrong program."""
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((8, D)), jnp.float32)
    y = jnp.asarray(rng.integers(0, C, (8,)))
    step_cache.reset_stats()
    for k in (2, 4):
        m = _model()
        opt = FusedSGD(list(m.parameters()), lr=0.05)
        step = make_train_step(m, opt,
                               lambda o, t: F.cross_entropy(o, t),
                               half_dtype=None, loss_scale=1.0,
                               accum_steps=k)
        step(x, y)
    stats = step_cache.stats()["by_kind"]["train_step"]
    assert stats["compiles"] == 2 and stats["dispatches"] == 2


# ---------------------------------------------------------------------------
# (d) ZeRO + accumulation
# ---------------------------------------------------------------------------

def test_zero_accum_numerics_parity_and_dispatch():
    xs, ys = _block()

    def build(zero):
        m = _model()
        opt = FusedAdam(list(m.parameters()), lr=1e-3)
        return make_train_step(m, opt,
                               lambda o, t: F.cross_entropy(o, t),
                               half_dtype=jnp.bfloat16, loss_scale=1.0,
                               accum_steps=K, accum_stacked=True,
                               zero_sharding=zero)

    plain = build(False)
    zstep = build(True)
    step_cache.reset_stats()
    for _ in range(3):
        plain(xs, ys)
        zstep(xs, ys)
    for a, b in zip(plain.state.master_params, zstep.state.master_params):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-6, atol=2e-6)
    # the whole K-microbatch ZeRO window is one dispatch of one program
    zstats = step_cache.stats()["by_kind"]["zero_train_step"]
    assert zstats["compiles"] == 1 and zstats["dispatches"] == 3


# ---------------------------------------------------------------------------
# data pipeline: stacked (K, B, ...) blocks
# ---------------------------------------------------------------------------

def test_prefetcher_stacked_blocks_feed_the_fused_step():
    from apex_tpu.runtime import DataPrefetcher

    rng = np.random.default_rng(0)
    batches = [(rng.standard_normal((B, D)).astype(np.float32),
                rng.integers(0, C, (B,))) for _ in range(2 * K + 1)]
    pre = DataPrefetcher(iter(batches), accum_steps=K)
    m = _model()
    opt = FusedSGD(list(m.parameters()), lr=0.05)
    step = make_train_step(m, opt, lambda o, t: F.cross_entropy(o, t),
                           half_dtype=None, loss_scale=1.0,
                           accum_steps=K, accum_stacked=True)
    n = 0
    for xb, yb in pre:
        assert xb.shape == (K, B, D) and yb.shape == (K, B)
        loss = step(xb, yb)
        assert np.isfinite(float(loss))
        n += 1
    # 2K+1 loader batches = 2 whole windows; the partial tail is dropped
    assert n == 2
    assert int(step.state.step) == 2


def test_prefetcher_accum_steps_validation():
    from apex_tpu.runtime import DataPrefetcher
    with pytest.raises(ValueError, match="accum_steps"):
        DataPrefetcher(iter([]), accum_steps=0)


# ---------------------------------------------------------------------------
# argument validation
# ---------------------------------------------------------------------------

def test_accum_steps_conflicts_and_stacked_validation():
    m = _model()
    opt = FusedSGD(list(m.parameters()), lr=0.05)
    loss_fn = lambda o, t: F.cross_entropy(o, t)  # noqa: E731
    with pytest.raises(ValueError, match="same\\s+knob"):
        make_train_step(m, opt, loss_fn, accum_steps=4, grad_accum_steps=2)
    with pytest.raises(ValueError, match="accum_stacked"):
        make_train_step(m, opt, loss_fn, accum_stacked=True)
    step = make_train_step(m, opt, loss_fn, half_dtype=None, loss_scale=1.0,
                           accum_steps=K, accum_stacked=True)
    with pytest.raises(ValueError, match="microbatch count"):
        step(jnp.zeros((K + 1, B, D)), jnp.zeros((K + 1, B), jnp.int32))


# ---------------------------------------------------------------------------
# eager surface: delayed-unscale guard + DDP boundary exchange
# ---------------------------------------------------------------------------

def _eager_setup(scale=128.0):
    m = _model()
    opt = FusedSGD(list(m.parameters()), lr=0.05)
    m, opt = amp.initialize(m, opt, opt_level="O2", loss_scale=scale,
                            verbosity=0)
    return m, opt, nn.CrossEntropyLoss(), _block()


def test_step_finalizes_all_delayed_window_no_double_unscale():
    """step() on an all-delayed window unscales exactly once — same result
    as the canonical final-non-delayed pattern, and the NEXT window is
    unaffected (the flag was cleared, nothing unscales twice)."""
    def run(all_delayed):
        m, opt, crit, (xs, ys) = _eager_setup()
        for i in range(K):
            delay = True if all_delayed else (i < K - 1)
            loss = crit(m(xs[i]), ys[i]) / K
            with amp.scale_loss(loss, opt, delay_unscale=delay) as sl:
                sl.backward()
        opt.step()
        opt.zero_grad()
        # follow-up single-batch window exercises the post-guard state
        loss = crit(m(xs[0]), ys[0])
        with amp.scale_loss(loss, opt) as sl:
            sl.backward()
        opt.step()
        return [np.asarray(p.data, np.float32)
                for g in opt.param_groups for p in g["params"]]

    for a, b in zip(run(True), run(False)):
        assert np.array_equal(a, b)


def test_step_finalize_overflow_skips_and_halves():
    m, opt, crit, (xs, ys) = _eager_setup(scale="dynamic")
    from apex_tpu.amp._amp_state import _amp_state
    scaler = _amp_state.loss_scalers[0]
    scale0 = scaler.loss_scale()
    before = [np.asarray(p.data, np.float32)
              for g in opt.param_groups for p in g["params"]]
    for i in range(K):
        loss = crit(m(xs[i]), ys[i]) / K
        with amp.scale_loss(loss, opt, delay_unscale=True) as sl:
            sl.backward()
    # poison one accumulated gradient: the finalize-unscale at step() must
    # flag it, skip the update, and halve the scale once
    opt._amp_lazy_init()
    stash = opt._amp_stash
    p0 = stash.all_fp16_params[0]
    p0.grad = jnp.full_like(p0.grad, jnp.inf)
    opt.step()
    after = [np.asarray(p.data, np.float32)
             for g in opt.param_groups for p in g["params"]]
    for a, b in zip(before, after):
        assert np.array_equal(a, b)
    assert scaler.loss_scale() == scale0 / 2.0


def test_ddp_attach_optimizer_one_exchange_per_window():
    from apex_tpu.parallel import DistributedDataParallel

    nn.manual_seed(3)
    m = nn.Linear(D, C)
    opt = FusedSGD(list(m.parameters()), lr=0.05)
    ddp = DistributedDataParallel(m, delay_allreduce=True)
    calls = []
    orig = ddp.allreduce_gradients
    ddp.allreduce_gradients = lambda: (calls.append(1), orig())[1]
    ddp.attach_optimizer(opt)
    crit = nn.CrossEntropyLoss()
    # microbatch size divisible by the 8-device test mesh (DDP shards the
    # incoming batch over the data axis)
    rng = np.random.default_rng(0)
    xs = jnp.asarray(rng.standard_normal((K, 16, D)), jnp.float32)
    ys = jnp.asarray(rng.integers(0, C, (K, 16)))
    for i in range(K):
        loss = crit(ddp(xs[i]), ys[i]) / K
        loss.backward()
    opt.step()
    assert calls == [1]          # one exchange for the K-microbatch window
    # attaching twice must not stack a second exchange
    ddp.attach_optimizer(opt)
    opt.zero_grad()
    loss = crit(ddp(xs[0]), ys[0])
    loss.backward()
    opt.step()
    assert calls == [1, 1]


def test_ddp_attach_requires_delay_allreduce():
    from apex_tpu.parallel import DistributedDataParallel

    nn.manual_seed(3)
    m = nn.Linear(D, C)
    opt = FusedSGD(list(m.parameters()), lr=0.05)
    with pytest.raises(ValueError, match="delay_allreduce"):
        DistributedDataParallel(m).attach_optimizer(opt)


def test_eager_accumulation_adds_no_per_param_dispatches():
    """The fused backward returns ``prev + new`` from the ONE compiled
    program: an accumulating backward is still exactly one executable
    (second call is a cache hit on the same jitted callable)."""
    from apex_tpu import autograd

    nn.manual_seed(5)
    m = nn.Linear(D, C)
    crit = nn.CrossEntropyLoss()
    xs, ys = _block()
    autograd._compiled_cache.clear()
    loss = crit(m(xs[0]), ys[0])
    loss.backward()
    g0 = [np.asarray(p.grad, np.float32) for p in m.parameters()]
    assert len(autograd._compiled_cache) == 1
    loss = crit(m(xs[1]), ys[1])
    loss.backward()          # accumulates inside the same cached program
    assert len(autograd._compiled_cache) == 1
    g1 = [np.asarray(p.grad, np.float32) for p in m.parameters()]
    for a, b in zip(g0, g1):
        assert not np.array_equal(a, b)   # it DID accumulate
