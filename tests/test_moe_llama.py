"""Mixtral-shape MoE in the Llama family (MoeLlamaBlock): routed SwiGLU
experts behind the RoPE/GQA attention, trained through the fused step
with the aux loss via Ctx.add_aux_loss — plus parameter-registry hygiene
(the dense SwiGLU must be fully replaced, not shadowed)."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import apex_tpu.nn as nn
from apex_tpu.models.llama import LlamaModel, MoeLlamaBlock
from apex_tpu.nn import functional as F
from apex_tpu.optimizers import FusedAdam
from apex_tpu.training import make_train_step

V, H, S = 211, 64, 16


def _mesh(n):
    return Mesh(np.array(jax.devices()[:n]), ("data",))


def _moe_llama(**kw):
    nn.manual_seed(7)
    return LlamaModel(vocab_size=V, hidden=H, layers=2, heads=4,
                      kv_heads=2, max_positions=32, moe_axis="data",
                      moe_num_experts=4, **kw)


def test_moe_block_replaces_dense_ffn():
    """The MoE block's registry holds router + stacked expert weights
    and NO dense SwiGLU (a shadowed dense copy would train as dead
    weight and bloat checkpoints)."""
    nn.manual_seed(0)
    blk = MoeLlamaBlock(H, 4, 2, 128, num_experts=4)
    names = [n for n, _ in blk.named_parameters()]
    flat = " ".join(names)
    assert "router" in flat and "wg" in flat
    assert "gate_proj" not in flat and "up_proj" not in flat \
        and "down_proj" not in flat
    assert blk.gate_proj is None
    # expert stacks carry the expert dim
    assert blk.wg.shape == (4, 128, H)
    assert blk.wd.shape == (4, H, 128)


def test_moe_llama_mixes_dense_and_moe_blocks():
    model = _moe_llama(moe_every=2)
    kinds = [type(b).__name__ for b in model.blocks]
    assert kinds == ["LlamaBlock", "MoeLlamaBlock"]


def _run_step(model, n_steps=15, half_dtype=None, loss_scale=1.0):
    opt = FusedAdam(list(model.parameters()), lr=1e-2)

    def lm_loss(logits, tgt):
        return F.cross_entropy(logits.reshape((-1, V)),
                               tgt.reshape((-1,)))

    step = make_train_step(model, opt, lm_loss, half_dtype=half_dtype,
                           loss_scale=loss_scale, axis_name="data")
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, V, (8, S)))
    tgt = jnp.asarray(np.roll(np.asarray(ids), -1, axis=1))
    sharded = jax.jit(jax.shard_map(
        step._step_fn, mesh=_mesh(4),
        in_specs=(P(), P("data"), P("data")),
        out_specs=(P(), P()), check_vma=False))
    state, l0 = sharded(step.state, ids, tgt)
    for _ in range(n_steps):
        state, l = sharded(state, ids, tgt)
    return float(l0), float(l)


def test_moe_llama_trains_through_fused_step():
    l0, l = _run_step(_moe_llama())
    assert np.isfinite(l) and l < l0


def test_moe_llama_trains_with_remat_bf16_top2():
    """Composition: remat (aux loss crossing checkpoint boundaries) +
    bf16 halves + dynamic scaling + top-2 routing."""
    l0, l = _run_step(_moe_llama(remat=True, moe_top_k=2),
                      half_dtype=jnp.bfloat16, loss_scale="dynamic",
                      n_steps=12)
    assert np.isfinite(l) and l < l0


def test_moe_llama_config_validation():
    with pytest.raises(ValueError, match="moe_num_experts"):
        LlamaModel(vocab_size=V, hidden=H, layers=2, heads=4,
                   moe_axis="data")
    with pytest.raises(ValueError, match="mutually exclusive"):
        LlamaModel(vocab_size=V, hidden=H, layers=2, heads=4,
                   moe_axis="data", moe_num_experts=4, tp_axis="tp")
    # moe_every out of range would silently build an all-dense "MoE"
    # model (or div-by-zero): loud instead
    for bad in (0, 3):
        with pytest.raises(ValueError, match="moe_every"):
            LlamaModel(vocab_size=V, hidden=H, layers=2, heads=4,
                       moe_axis="data", moe_num_experts=4,
                       moe_every=bad)
    # MoE decode and SP decode are each supported (under a mesh — see
    # the decode tests and tests/test_sp_decode.py); their COMPOSITION
    # is the remaining decode refusal
    sp_moe = LlamaModel(vocab_size=V, hidden=H, layers=2, heads=4,
                        kv_heads=2, sp_axis="sp", moe_axis="data",
                        moe_num_experts=4)
    with pytest.raises(NotImplementedError, match="sp_axis"):
        sp_moe.decode_step(None, jnp.zeros((1,), jnp.int32), [], 0)


def test_moe_llama_decode_matches_forward(rng):
    """The Mixtral serving path: cached decode under the expert mesh
    reproduces the training forward's logits (teacher-forced; capacity
    factor high enough that nothing drops, so routing is identical in
    the per-chunk and full-sequence dispatches)."""
    from apex_tpu.nn.modules import Ctx

    nn.manual_seed(9)
    model = LlamaModel(vocab_size=V, hidden=H, layers=2, heads=4,
                       kv_heads=2, max_positions=32, moe_axis="data",
                       moe_num_experts=4, moe_every=2,
                       moe_capacity_factor=8.0)
    model.eval()
    params = list(model.parameters())
    vals = [p.data for p in params]
    ids = jnp.asarray(rng.integers(0, V, (2, 10)))
    mesh = _mesh(4)

    def fwd(vals, ids):
        ctx = Ctx(env={id(p): v for p, v in zip(params, vals)},
                  training=False)
        return model.forward(ctx, ids)

    want = jax.jit(jax.shard_map(
        fwd, mesh=mesh, in_specs=(P(), P()), out_specs=P(),
        check_vma=False))(vals, ids)

    def stepped(vals, ids):
        ctx = Ctx(env={id(p): v for p, v in zip(params, vals)},
                  training=False)
        caches = model.init_caches(2, 16)
        outs = []
        for t in range(10):
            logits, caches = model.decode_step(ctx, ids[:, t], caches,
                                               jnp.asarray(t))
            outs.append(logits)
        return jnp.stack(outs, axis=1)

    got = jax.jit(jax.shard_map(
        stepped, mesh=mesh, in_specs=(P(), P()), out_specs=P(),
        check_vma=False))(vals, ids)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=3e-4, atol=3e-4)


def test_moe_llama_generate_under_mesh(rng):
    """generate(mesh=...) drives the MoE model end to end (prefill +
    scan of expert-routed decode steps in one compiled program)."""
    from apex_tpu.models.gpt import generate

    nn.manual_seed(10)
    model = LlamaModel(vocab_size=V, hidden=H, layers=2, heads=4,
                       kv_heads=2, max_positions=64, moe_axis="data",
                       moe_num_experts=4, moe_every=2,
                       moe_capacity_factor=8.0)
    model.eval()
    prompt = jnp.asarray(rng.integers(0, V, (2, 5)))
    out = np.asarray(generate(model, prompt, 10, mesh=_mesh(4)))
    assert out.shape == (2, 15)
    assert (out[:, :5] == np.asarray(prompt)).all()
    assert ((out >= 0) & (out < V)).all()
    # without the mesh: loud argument error, not an unbound-axis trace
    import pytest as _pytest
    with _pytest.raises(ValueError, match="mesh"):
        generate(model, prompt, 4)


def test_gpt_moe_decode_matches_forward(rng):
    """GPT-family MoE decode (MoeGptBlock inherits GptBlock's cached
    paths through the shared _ffn hook): teacher-forced decode under
    the expert mesh reproduces the forward at non-dropping capacity."""
    from apex_tpu.models import GptModel
    from apex_tpu.nn.modules import Ctx

    nn.manual_seed(4)
    m = GptModel(vocab_size=61, hidden=16, layers=2, heads=2,
                 max_positions=32, dropout=0.0, attn_dropout=0.0,
                 moe_axis="data", moe_num_experts=4,
                 moe_capacity_factor=8.0)
    m.eval()
    params = list(m.parameters())
    vals = [p.data for p in params]
    ids = jnp.asarray(rng.integers(0, 61, (2, 8)))
    mesh = _mesh(4)

    def fwd(vals, ids):
        ctx = Ctx(env={id(p): v for p, v in zip(params, vals)},
                  training=False)
        return m.forward(ctx, ids)

    want = jax.jit(jax.shard_map(
        fwd, mesh=mesh, in_specs=(P(), P()), out_specs=P(),
        check_vma=False))(vals, ids)

    def stepped(vals, ids):
        ctx = Ctx(env={id(p): v for p, v in zip(params, vals)},
                  training=False)
        caches = m.init_caches(2, 16)
        outs = []
        for t in range(8):
            logits, caches = m.decode_step(ctx, ids[:, t], caches,
                                           jnp.asarray(t))
            outs.append(logits)
        return jnp.stack(outs, axis=1)

    got = jax.jit(jax.shard_map(
        stepped, mesh=mesh, in_specs=(P(), P()), out_specs=P(),
        check_vma=False))(vals, ids)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=3e-4, atol=3e-4)


def test_gpt_moe_generate_under_mesh(rng):
    from apex_tpu.models import GptModel
    from apex_tpu.models.gpt import generate

    nn.manual_seed(5)
    m = GptModel(vocab_size=61, hidden=16, layers=2, heads=2,
                 max_positions=32, dropout=0.0, attn_dropout=0.0,
                 moe_axis="data", moe_num_experts=4,
                 moe_capacity_factor=8.0)
    m.eval()
    prompt = jnp.asarray(rng.integers(0, 61, (1, 4)))
    out = np.asarray(generate(m, prompt, 8, mesh=_mesh(4)))
    assert out.shape == (1, 12)
    assert ((out >= 0) & (out < 61)).all()
    with pytest.raises(ValueError, match="mesh"):
        generate(m, prompt, 4)


def test_moe_speculative_greedy_exact(rng):
    """Speculative decoding with an expert-routed target: the greedy
    exactness guarantee holds under the MoE mesh (verification chunks
    route through the same dispatch as the target's own decode)."""
    from apex_tpu.inference import speculative_generate
    from apex_tpu.models.gpt import generate

    nn.manual_seed(11)
    target = LlamaModel(vocab_size=V, hidden=32, layers=2, heads=4,
                        kv_heads=2, max_positions=64, moe_axis="data",
                        moe_num_experts=4, moe_every=2,
                        moe_capacity_factor=8.0)
    target.eval()
    nn.manual_seed(12)
    draft = LlamaModel(vocab_size=V, hidden=16, layers=1, heads=2,
                       max_positions=64)
    draft.eval()
    prompt = jnp.asarray(rng.integers(0, V, (1, 4)))
    mesh = _mesh(4)
    want = np.asarray(generate(target, prompt, 10, mesh=mesh))
    got = np.asarray(speculative_generate(target, draft, prompt, 10,
                                          k=3, mesh=mesh))
    np.testing.assert_array_equal(got, want)
