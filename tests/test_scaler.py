"""LossScaler dynamics vs the reference contract (apex/amp/scaler.py):
dynamic init min(max,2**16), halve on overflow, grow x2 after scale_window
clean steps, min/max clamps; static scaler never skips; unscale writes
1/scale * grad and sets the overflow flag on non-finite grads."""
import jax.numpy as jnp
import numpy as np

from apex_tpu.amp import (LossScaler, init_scaler_state, unscale_grads,
                          update_scale_state)


def test_dynamic_init_defaults():
    s = LossScaler("dynamic")
    assert s.dynamic
    assert s.loss_scale() == 2.0 ** 16


def test_dynamic_init_clamped_by_max():
    s = LossScaler("dynamic", max_loss_scale=2.0 ** 10)
    assert s.loss_scale() == 2.0 ** 10


def test_static_scale():
    s = LossScaler(128.0)
    assert not s.dynamic
    assert s.loss_scale() == 128.0
    # static never skips and never changes even with overflow flagged
    s._state = s._state._replace(overflow=jnp.ones((), jnp.int32))
    assert s.update_scale() is False
    assert s.loss_scale() == 128.0


def test_overflow_halves_and_resets_window():
    s = LossScaler("dynamic")
    s._state = s._state._replace(unskipped=jnp.asarray(1500, jnp.int32),
                                 overflow=jnp.ones((), jnp.int32))
    assert s.update_scale() is True
    assert s.loss_scale() == 2.0 ** 15
    assert s._unskipped == 0


def test_growth_after_scale_window():
    s = LossScaler("dynamic", scale_window=3)
    for _ in range(2):
        assert s.update_scale() is False
        assert s.loss_scale() == 2.0 ** 16
    s.update_scale()  # third clean step -> grow
    assert s.loss_scale() == 2.0 ** 17
    assert s._unskipped == 0


def test_growth_clamped_at_max():
    s = LossScaler("dynamic", scale_window=1, max_loss_scale=2.0 ** 16)
    s.update_scale()
    assert s.loss_scale() == 2.0 ** 16


def test_halving_clamped_at_min():
    s = LossScaler("dynamic", min_loss_scale=2.0 ** 16)
    s._state = s._state._replace(overflow=jnp.ones((), jnp.int32))
    s.update_scale()
    assert s.loss_scale() == 2.0 ** 16


def test_unscale_writes_master_grads():
    s = LossScaler(1024.0)
    model_grads = [jnp.full((8,), 1024.0, jnp.float16),
                   jnp.full((4, 4), 2.0 * 1024.0, jnp.float16)]
    masters = [jnp.zeros((8,), jnp.float32), jnp.zeros((4, 4), jnp.float32)]
    out = s.unscale(model_grads, masters)
    np.testing.assert_allclose(np.asarray(out[0]), 1.0)
    np.testing.assert_allclose(np.asarray(out[1]), 2.0)
    assert s.update_scale() is False


def test_unscale_detects_overflow_and_update_skips():
    s = LossScaler("dynamic")
    bad = [jnp.asarray([1.0, np.inf], jnp.float16)]
    masters = [jnp.zeros((2,), jnp.float32)]
    s.unscale(bad, masters)
    assert s.update_scale() is True
    assert s.loss_scale() == 2.0 ** 15
    # clear_overflow_state resets the flag
    s.clear_overflow_state()
    assert s.update_scale() is False


def test_unscale_with_stashed_accumulates():
    s = LossScaler(2.0)
    model = [jnp.asarray([4.0, 8.0], jnp.float16)]   # scaled by 2
    stashed = [jnp.asarray([1.0, 1.0], jnp.float32)]  # already unscaled
    masters = [jnp.zeros((2,), jnp.float32)]
    out = s.unscale_with_stashed(model, stashed, masters)
    np.testing.assert_allclose(np.asarray(out[0]), [3.0, 5.0])


def test_functional_state_roundtrip_under_jit():
    import jax

    @jax.jit
    def step(state, grads):
        state, masters = unscale_grads(state, grads)
        state, skip = update_scale_state(state, dynamic=True, scale_window=2000)
        return state, skip, masters

    state = init_scaler_state("dynamic")
    grads = [jnp.full((16,), 2.0 ** 16, jnp.float32)]
    state, skip, masters = step(state, grads)
    assert not bool(skip)
    assert float(state.loss_scale) == 2.0 ** 16
    np.testing.assert_allclose(np.asarray(masters[0]), 1.0)

    bad = [jnp.full((16,), np.nan, jnp.float32)]
    state, skip, _ = step(state, bad)
    assert bool(skip)
    assert float(state.loss_scale) == 2.0 ** 15
