"""Fused train step (apex_tpu.training.make_train_step): parity with the
imperative amp path, overflow skip, BN stats threading, and the shard_map DP
path on the 8-device CPU mesh."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import apex_tpu.nn as nn
from apex_tpu.nn import functional as F
from apex_tpu.optimizers import FusedAdam, FusedSGD
from apex_tpu.training import make_train_step


def _model():
    nn.manual_seed(42)
    return nn.Sequential(
        nn.Conv2d(3, 8, 3, padding=1), nn.BatchNorm2d(8), nn.ReLU(),
        nn.MaxPool2d(2), nn.Flatten(), nn.Linear(8 * 8 * 8, 10))


def _data(n=8, seed=0):
    rng = np.random.default_rng(seed)
    x = jnp.asarray(rng.standard_normal((n, 3, 16, 16)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 10, (n,)))
    return x, y


def _model_nobias():
    """bias=False variant: a conv bias feeding straight into BN has
    analytically-zero grad, and normalized updates (Adam's m/(sqrt(v)+eps),
    NovoGrad's g/||g||) amplify compilation-dependent float noise on such a
    param into O(lr) differences between eager and fused runs."""
    nn.manual_seed(42)
    return nn.Sequential(
        nn.Conv2d(3, 8, 3, padding=1, bias=False), nn.BatchNorm2d(8),
        nn.ReLU(), nn.MaxPool2d(2), nn.Flatten(),
        nn.Linear(8 * 8 * 8, 10))


def test_fused_step_trains():
    model = _model()
    opt = FusedAdam(list(model.parameters()), lr=1e-2)
    step = make_train_step(model, opt, lambda o, y: F.cross_entropy(o, y),
                           half_dtype=jnp.float16)
    x, y = _data()
    losses = [float(step(x, y)) for _ in range(10)]
    assert losses[-1] < losses[0]
    assert all(np.isfinite(losses))


def test_fused_matches_imperative_amp_O2():
    """Same model/seed: the fused step and the scale_loss imperative path
    must produce closely matching loss curves (the reference's L1 oracle —
    extension vs python build, tests/L1/common/compare.py:34-40)."""
    from apex_tpu import amp
    from apex_tpu.amp._amp_state import _amp_state

    x, y = _data()

    # imperative path
    _amp_state.opt_properties = None
    model_a = _model()
    opt_a = FusedSGD(list(model_a.parameters()), lr=0.05, momentum=0.9)
    model_a, opt_a = amp.initialize(model_a, opt_a, opt_level="O2",
                                    verbosity=0)
    crit = nn.CrossEntropyLoss()
    imp = []
    for _ in range(5):
        out = model_a(x)
        loss = crit(out, y)
        with amp.scale_loss(loss, opt_a) as sl:
            sl.backward()
        opt_a.step()
        opt_a.zero_grad()
        imp.append(float(loss))

    # fused path (same init via same seed)
    model_b = _model()
    opt_b = FusedSGD(list(model_b.parameters()), lr=0.05, momentum=0.9)
    step = make_train_step(model_b, opt_b,
                           lambda o, yy: F.cross_entropy(o, yy),
                           half_dtype=jnp.float16, loss_scale="dynamic")
    fused = [float(step(x, y)) for _ in range(5)]
    np.testing.assert_allclose(fused, imp, rtol=5e-3)


def test_fused_step_overflow_skips():
    model = _model()
    opt = FusedSGD(list(model.parameters()), lr=0.05)
    step = make_train_step(model, opt, lambda o, y: F.cross_entropy(o, y),
                           half_dtype=jnp.float16, loss_scale="dynamic")
    x, y = _data()
    step(x, y)
    masters_before = [np.asarray(m) for m in step.state.master_params]
    scale_before = float(step.state.scaler.loss_scale)
    bad = x.at[0, 0, 0, 0].set(np.inf)
    step(bad, y)
    for m, before in zip(step.state.master_params, masters_before):
        np.testing.assert_array_equal(np.asarray(m), before)
    assert float(step.state.scaler.loss_scale) == scale_before / 2


def test_fused_step_updates_bn_stats():
    model = _model()
    opt = FusedSGD(list(model.parameters()), lr=0.05)
    step = make_train_step(model, opt, lambda o, y: F.cross_entropy(o, y))
    x, y = _data()
    step(x, y)
    step.sync_to_objects()
    assert not np.allclose(np.asarray(model[1].running_mean.data), 0.0)
    assert int(np.asarray(model[1].num_batches_tracked.data)) == 1


def test_fused_step_param_groups_match_eager():
    """Two LR/WD groups: the fused step must apply each group's own
    hyperparameters (round 1 silently used group 0 for everything) and match
    the eager optimizer.step() path."""
    def _grouped(model):
        ps = list(model.parameters())
        return [{"params": ps[:2], "lr": 0.05, "weight_decay": 1e-2},
                {"params": ps[2:], "lr": 0.005}]

    x, y = _data()
    crit = nn.CrossEntropyLoss()

    model_a = _model()
    opt_a = FusedSGD(_grouped(model_a), lr=0.01, momentum=0.9)
    for _ in range(4):
        out = model_a(x)
        loss = crit(out, y)
        loss.backward()
        opt_a.step()
        opt_a.zero_grad()

    model_b = _model()
    opt_b = FusedSGD(_grouped(model_b), lr=0.01, momentum=0.9)
    step = make_train_step(model_b, opt_b,
                           lambda o, yy: F.cross_entropy(o, yy),
                           loss_scale=1.0)
    for _ in range(4):
        step(x, y)

    for pa, mb in zip(model_a.parameters(), step.state.master_params):
        np.testing.assert_allclose(np.asarray(pa.data), np.asarray(mb),
                                   rtol=1e-5, atol=1e-6)


def test_fused_step_adam_param_groups_match_eager():
    x, y = _data()
    crit = nn.CrossEntropyLoss()

    _model = _model_nobias

    def _grouped(model):
        ps = list(model.parameters())
        return [{"params": ps[:2], "lr": 1e-2, "betas": (0.8, 0.95)},
                {"params": ps[2:], "lr": 1e-3, "weight_decay": 1e-2}]

    model_a = _model()
    opt_a = FusedAdam(_grouped(model_a), lr=1e-2)
    for _ in range(3):
        out = model_a(x)
        loss = crit(out, y)
        loss.backward()
        opt_a.step()
        opt_a.zero_grad()

    model_b = _model()
    opt_b = FusedAdam(_grouped(model_b), lr=1e-2)
    step = make_train_step(model_b, opt_b,
                           lambda o, yy: F.cross_entropy(o, yy),
                           loss_scale=1.0)
    for _ in range(3):
        step(x, y)

    for pa, mb in zip(model_a.parameters(), step.state.master_params):
        np.testing.assert_allclose(np.asarray(pa.data), np.asarray(mb),
                                   rtol=1e-5, atol=1e-6)


def test_fused_step_novograd():
    """FusedNovoGrad in the fused path (raised TypeError in round 1),
    including the first-step running-norm seeding, vs the eager step."""
    from apex_tpu.optimizers import FusedNovoGrad

    x, y = _data()
    crit = nn.CrossEntropyLoss()

    _model = _model_nobias

    model_a = _model()
    opt_a = FusedNovoGrad(list(model_a.parameters()), lr=1e-2)
    for _ in range(3):
        out = model_a(x)
        loss = crit(out, y)
        loss.backward()
        opt_a.step()
        opt_a.zero_grad()

    model_b = _model()
    opt_b = FusedNovoGrad(list(model_b.parameters()), lr=1e-2)
    step = make_train_step(model_b, opt_b,
                           lambda o, yy: F.cross_entropy(o, yy),
                           loss_scale=1.0)
    for _ in range(3):
        step(x, y)

    for pa, mb in zip(model_a.parameters(), step.state.master_params):
        np.testing.assert_allclose(np.asarray(pa.data), np.asarray(mb),
                                   rtol=1e-5, atol=1e-6)


def test_fused_step_frozen_params_stay_fixed():
    """Model params not held by the optimizer are frozen, torch-style."""
    model = _model()
    ps = list(model.parameters())
    opt = FusedSGD(ps[2:], lr=0.05)
    step = make_train_step(model, opt, lambda o, y: F.cross_entropy(o, y),
                           loss_scale=1.0)
    x, y = _data()
    before = [np.asarray(m) for m in step.state.master_params[:2]]
    step(x, y)
    for b, a in zip(before, step.state.master_params[:2]):
        np.testing.assert_array_equal(b, np.asarray(a))
    assert not np.allclose(np.asarray(step.state.master_params[-1]),
                           np.asarray(ps[-1].data))


def test_fused_step_rejects_foreign_params():
    model = _model()
    other = _model()
    opt = FusedSGD(list(other.parameters()), lr=0.05)
    with pytest.raises(ValueError, match="not one of"):
        make_train_step(model, opt, lambda o, y: F.cross_entropy(o, y))


def test_fused_step_rejects_unsupported_optimizer():
    from apex_tpu.parallel import LARC
    model = _model()
    opt = LARC(FusedSGD(list(model.parameters()), lr=0.05))
    with pytest.raises(TypeError, match="supported:"):
        make_train_step(model, opt, lambda o, y: F.cross_entropy(o, y))


def test_fused_step_compile_time_recorded_and_bounded():
    """Compile cost must be visible (VERDICT round 1: both gates died in
    compile with no visibility) and small for a tiny model."""
    model = _model()
    opt = FusedSGD(list(model.parameters()), lr=0.05)
    step = make_train_step(model, opt, lambda o, y: F.cross_entropy(o, y),
                           half_dtype=jnp.bfloat16, loss_scale="dynamic")
    assert step.compile_s is None
    x, y = _data()
    step(x, y)
    assert step.compile_s is not None
    assert step.compile_s < 60.0, (
        f"tiny-model fused step took {step.compile_s:.1f}s to compile")


def test_fused_step_ddp_on_mesh():
    """shard_map DP over the 8-device CPU mesh: replicated state, sharded
    batch; parity with single-device on the same global batch."""
    from jax.sharding import Mesh, PartitionSpec as P
    shard_map = jax.shard_map

    n_dev = len(jax.devices())
    assert n_dev == 8, f"test harness expects 8 CPU devices, got {n_dev}"
    mesh = Mesh(np.array(jax.devices()), ("data",))

    x, y = _data(16)

    # BN-free model: plain (non-sync) BN computes local per-shard stats, so
    # exact parity with a single-device run requires no BN (SyncBatchNorm is
    # the cross-shard-stats variant — see parallel tests)
    def _model():
        nn.manual_seed(42)
        return nn.Sequential(
            nn.Conv2d(3, 8, 3, padding=1), nn.ReLU(),
            nn.MaxPool2d(2), nn.Flatten(), nn.Linear(8 * 8 * 8, 10))

    model_a = _model()
    opt_a = FusedSGD(list(model_a.parameters()), lr=0.05, momentum=0.9)
    single = make_train_step(model_a, opt_a,
                             lambda o, yy: F.cross_entropy(o, yy))
    single_losses = [float(single(x, y)) for _ in range(3)]

    model_b = _model()
    opt_b = FusedSGD(list(model_b.parameters()), lr=0.05, momentum=0.9)
    ddp = make_train_step(model_b, opt_b,
                          lambda o, yy: F.cross_entropy(o, yy),
                          axis_name="data")
    sharded = jax.jit(shard_map(
        ddp._step_fn, mesh=mesh,
        in_specs=(P(), P("data"), P("data")), out_specs=(P(), P()),
        check_vma=False))
    ddp_losses = []
    state = ddp.state
    for _ in range(3):
        state, loss = sharded(state, x, y)
        # per-shard mean losses differ from global mean only through shard
        # sizes here (equal) — loss is replicated mean of shard mean? No:
        # out_specs P() replicates; value is the first shard's local loss.
        ddp_losses.append(float(jnp.mean(loss)))
    ddp.state = state

    # parameters after 3 steps must match the single-device run closely
    for a, b in zip(single.state.master_params, ddp.state.master_params):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=2e-3, atol=2e-5)


def test_fused_step_with_dropout():
    """Models containing Dropout must train through the fused step: the
    step derives a per-step PRNG key from the step counter (regression —
    the Ctx used to be built keyless and dropout raised)."""
    nn.manual_seed(5)
    model = nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Dropout(0.5),
                          nn.Linear(32, 4))
    opt = FusedSGD(list(model.parameters()), lr=0.1, momentum=0.9)
    step = make_train_step(model, opt,
                           lambda o, yy: F.cross_entropy(o, yy),
                           loss_scale=1.0)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((8, 8)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 4, (8,)))
    losses = [float(step(x, y)) for _ in range(6)]
    assert all(np.isfinite(losses))
    # different steps see different dropout masks: with lr>0 the loss
    # sequence must not be constant
    assert len({round(l, 6) for l in losses}) > 1


def test_fused_step_dropout_under_dp():
    """Dropout under shard_map DP: the step folds the replica index into the
    dropout key, so shards draw independent masks and the step compiles
    (axis_index is only valid inside the mapped context)."""
    from jax.sharding import Mesh, PartitionSpec as P
    shard_map = jax.shard_map

    nn.manual_seed(5)
    model = nn.Sequential(nn.Linear(8, 32), nn.ReLU(), nn.Dropout(0.5),
                          nn.Linear(32, 4))
    opt = FusedSGD(list(model.parameters()), lr=0.05, momentum=0.9)
    step = make_train_step(model, opt,
                           lambda o, yy: F.cross_entropy(o, yy),
                           loss_scale=1.0, axis_name="data")
    mesh = Mesh(np.array(jax.devices()[:4]), ("data",))
    sharded = jax.jit(shard_map(
        step._step_fn, mesh=mesh,
        in_specs=(P(), P("data"), P("data")), out_specs=(P(), P()),
        check_vma=False))
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((16, 8)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 4, (16,)))
    state, loss = sharded(step.state, x, y)
    state, loss2 = sharded(state, x, y)
    assert np.isfinite(float(jnp.mean(loss)))
    assert np.isfinite(float(jnp.mean(loss2)))


def test_grad_accum_matches_full_batch():
    """K microbatches inside the step == the full-batch step: same params
    after N steps (fp32 model, dropout off — exact up to summation order)."""
    import numpy as np
    from apex_tpu.nn import functional as F
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.training import make_train_step

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((16, 12)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 5, (16,)))

    results = {}
    for accum in (1, 4):
        nn.manual_seed(7)
        model = nn.Sequential(nn.Linear(12, 32), nn.ReLU(),
                              nn.Linear(32, 5))
        opt = FusedAdam(list(model.parameters()), lr=1e-2)
        step = make_train_step(model, opt,
                               lambda o, t: F.cross_entropy(o, t),
                               half_dtype=None, loss_scale=1.0,
                               grad_accum_steps=accum)
        for _ in range(5):
            loss = step(x, y)
        step.sync_to_objects()
        results[accum] = ([np.asarray(p.data) for p in model.parameters()],
                          float(loss))

    assert abs(results[1][1] - results[4][1]) < 1e-5
    for a, b in zip(results[1][0], results[4][0]):
        np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-6)


def test_grad_accum_bn_stats_thread_sequentially():
    """BatchNorm running stats under accumulation see K sequential
    microbatch updates per step (the semantics of K separate forwards)."""
    import numpy as np
    from apex_tpu.nn import functional as F
    from apex_tpu.optimizers import FusedSGD
    from apex_tpu.training import make_train_step

    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((8, 6)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 3, (8,)))

    def build():
        nn.manual_seed(2)
        m = nn.Sequential(nn.Linear(6, 6), nn.BatchNorm1d(6),
                          nn.Linear(6, 3))
        return m, FusedSGD(list(m.parameters()), lr=0.0)  # stats only

    # accumulated: one step of K=2 microbatches
    m_acc, opt = build()
    step = make_train_step(m_acc, opt, lambda o, t: F.cross_entropy(o, t),
                           half_dtype=None, loss_scale=1.0,
                           grad_accum_steps=2)
    step(x, y)
    step.sync_to_objects()

    # reference: two eager forwards on the two halves (lr=0, same params)
    m_ref, _ = build()
    m_ref.train()
    m_ref(x[:4])
    m_ref(x[4:])

    bn_acc = [m for m in m_acc.modules()
              if isinstance(m, nn.BatchNorm1d)][0]
    bn_ref = [m for m in m_ref.modules()
              if isinstance(m, nn.BatchNorm1d)][0]
    np.testing.assert_allclose(np.asarray(bn_acc.running_mean.data),
                               np.asarray(bn_ref.running_mean.data),
                               rtol=1e-5, atol=1e-6)
    np.testing.assert_allclose(np.asarray(bn_acc.running_var.data),
                               np.asarray(bn_ref.running_var.data),
                               rtol=1e-5, atol=1e-6)


def test_grad_accum_rejects_indivisible_batch():
    import pytest
    from apex_tpu.nn import functional as F
    from apex_tpu.optimizers import FusedSGD
    from apex_tpu.training import make_train_step

    nn.manual_seed(0)
    m = nn.Linear(4, 2)
    opt = FusedSGD(list(m.parameters()), lr=0.1)
    step = make_train_step(m, opt, lambda o, t: F.cross_entropy(o, t),
                           half_dtype=None, loss_scale=1.0,
                           grad_accum_steps=3)
    with pytest.raises(ValueError, match="divisible"):
        step(jnp.zeros((8, 4)), jnp.zeros((8,), jnp.int32))


def test_grad_accum_under_dp():
    """Accumulation composes with shard_map DP: the psum happens once per
    step after the scan, and replicas stay in sync."""
    import numpy as np
    from jax.sharding import Mesh, PartitionSpec as P
    from apex_tpu.nn import functional as F
    from apex_tpu.optimizers import FusedSGD
    from apex_tpu.training import make_train_step

    nn.manual_seed(4)
    m = nn.Sequential(nn.Linear(6, 8), nn.ReLU(), nn.Linear(8, 3))
    opt = FusedSGD(list(m.parameters()), lr=0.05)
    step = make_train_step(m, opt, lambda o, t: F.cross_entropy(o, t),
                           half_dtype=None, loss_scale=1.0,
                           axis_name="data", grad_accum_steps=2)
    rng = np.random.default_rng(3)
    x = jnp.asarray(rng.standard_normal((16, 6)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 3, (16,)))
    mesh = Mesh(np.array(jax.devices()), ("data",))
    sharded = jax.jit(jax.shard_map(
        step._step_fn, mesh=mesh,
        in_specs=(P(), P("data"), P("data")), out_specs=(P(), P()),
        check_vma=False))
    state, loss0 = sharded(step.state, x, y)
    state, loss1 = sharded(state, x, y)
    assert np.isfinite(float(loss1)) and float(loss1) < float(loss0)
    # replicated state leaves must be identical across shards (psum'd once)
    assert int(state.step) == 2


def test_grad_accum_broadcasts_non_batch_elements():
    """Scalars / per-step constants in the batch are broadcast to every
    microbatch instead of rejected."""
    import numpy as np
    from apex_tpu.nn import functional as F
    from apex_tpu.optimizers import FusedSGD
    from apex_tpu.training import make_train_step

    nn.manual_seed(0)
    m = nn.Linear(4, 3)
    opt = FusedSGD(list(m.parameters()), lr=0.1)

    def weighted_loss(out, t, w):
        return F.cross_entropy(out, t) * w

    step = make_train_step(m, opt, weighted_loss, half_dtype=None,
                           loss_scale=1.0, grad_accum_steps=2)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((8, 4)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 3, (8,)))
    w = jnp.asarray(0.5, jnp.float32)      # scalar: broadcast, not split
    loss = step(x, y, w)
    assert np.isfinite(float(loss))


def test_lr_schedule_on_device():
    """lr_schedule scales each group's base lr from the traced step
    counter — the compiled step's updates shrink as the schedule decays,
    with no recompile between steps."""
    import numpy as np
    from apex_tpu.nn import functional as F
    from apex_tpu.optimizers import FusedSGD, warmup_linear
    from apex_tpu.training import make_train_step

    sched = warmup_linear(warmup_steps=2, total_steps=10)
    nn.manual_seed(0)
    m = nn.Linear(4, 3)
    opt = FusedSGD(list(m.parameters()), lr=1.0)  # big lr: moves visible
    step = make_train_step(m, opt, lambda o, t: F.cross_entropy(o, t),
                           half_dtype=None, loss_scale=1.0,
                           lr_schedule=sched, donate_state=False)
    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((8, 4)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 3, (8,)))

    # match against a manual run with per-step constant lrs
    deltas = []
    state = step.state
    prev = np.asarray(state.master_params[0])
    for i in range(4):
        state, _ = step._step_fn(state, x, y)
        cur = np.asarray(state.master_params[0])
        deltas.append(np.abs(cur - prev).max())
        prev = cur
    # warmup: step1 uses sched(1)=0.5, step2 sched(2)=1.0, then decay
    mult = [float(sched(jnp.asarray(i, jnp.int32))) for i in (1, 2, 3, 4)]
    assert mult[0] == 0.5 and mult[1] == 1.0
    assert mult[2] > mult[3]  # decaying
    # the realized update magnitudes follow the multiplier ordering
    assert deltas[1] > deltas[0]


def test_schedule_factories_shapes():
    import numpy as np
    from apex_tpu.optimizers import (step_decay, warmup_cosine,
                                     warmup_linear, warmup_poly)

    for factory in (lambda: warmup_linear(10, 100),
                    lambda: warmup_cosine(10, 100),
                    lambda: warmup_poly(10, 100, power=2.0)):
        s = factory()
        assert abs(float(s(jnp.asarray(10))) - 1.0) < 1e-6
        assert float(s(jnp.asarray(5))) == 0.5
        assert float(s(jnp.asarray(100))) <= 1e-6
        assert float(s(jnp.asarray(200))) <= 1e-6  # clamped past the end

    sd = step_decay([30, 60], [0.1, 0.01])
    assert float(sd(jnp.asarray(10))) == 1.0
    assert abs(float(sd(jnp.asarray(30))) - 0.1) < 1e-7
    assert abs(float(sd(jnp.asarray(90))) - 0.01) < 1e-7
    import pytest
    with pytest.raises(ValueError, match="warmup"):
        warmup_linear(100, 100)


def test_lr_schedule_applies_to_adam_and_lamb():
    """The schedule multiplier must reach every fused optimizer's kernel
    (a silent no-op for Adam/LAMB once shipped as exactly that bug)."""
    import numpy as np
    from apex_tpu.nn import functional as F
    from apex_tpu.optimizers import FusedAdam, FusedLAMB, FusedNovoGrad
    from apex_tpu.training import make_train_step

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((8, 4)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 3, (8,)))

    for opt_cls in (FusedAdam, FusedLAMB, FusedNovoGrad):
        def one_step_delta(schedule):
            nn.manual_seed(0)
            m = nn.Linear(4, 3)
            opt = opt_cls(list(m.parameters()), lr=1e-2)
            step = make_train_step(
                m, opt, lambda o, t: F.cross_entropy(o, t),
                half_dtype=None, loss_scale=1.0, lr_schedule=schedule,
                donate_state=False)
            before = np.asarray(step.state.master_params[0])
            state, _ = step._step_fn(step.state, x, y)
            return np.abs(np.asarray(state.master_params[0]) - before).max()

        full = one_step_delta(None)
        tenth = one_step_delta(lambda s: jnp.asarray(0.1, jnp.float32))
        assert tenth < full * 0.5, \
            f"{opt_cls.__name__}: schedule multiplier ignored " \
            f"(delta {tenth} vs {full})"


def test_schedule_accepts_python_int():
    from apex_tpu.optimizers import step_decay, warmup_cosine, warmup_linear
    assert float(warmup_linear(10, 100)(5)) == 0.5
    assert abs(float(warmup_cosine(10, 100)(10)) - 1.0) < 1e-6
    assert float(step_decay([5], [0.1])(1)) == 1.0
    import pytest
    with pytest.raises(ValueError, match="ascending"):
        step_decay([60, 30], [0.01, 0.1])
