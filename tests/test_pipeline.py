"""Pipeline parallelism (parallel/pipeline.py) vs the sequential oracle on
the CPU mesh: forward equality over the fill/drain schedule, gradient
equality through jax.grad (ppermute transposes give the backward), and
composition with extra microbatches."""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.parallel import pipeline_apply

D, MICRO = 8, 4


def _mesh(n):
    return Mesh(np.array(jax.devices()[:n]), ("pp",))


def _stage_fn(params, x):
    w, b = params
    return jnp.tanh(x @ w[0] + b[0])


def _params(rng, n_stages):
    w = jnp.asarray(rng.standard_normal((n_stages, D, D)) * 0.5, jnp.float32)
    b = jnp.asarray(rng.standard_normal((n_stages, D)) * 0.1, jnp.float32)
    return w, b


def _oracle(w, b, xs):
    def apply_all(x):
        for i in range(w.shape[0]):
            x = jnp.tanh(x @ w[i] + b[i])
        return x
    return jnp.stack([apply_all(xs[i]) for i in range(xs.shape[0])])


def _run_pipeline(mesh):
    fn = functools.partial(pipeline_apply, _stage_fn, axis_name="pp")

    def f(w, b, xs):
        return fn((w, b), xs)

    shard = jax.shard_map(f, mesh=mesh,
                          in_specs=(P("pp"), P("pp"), P()),
                          out_specs=P(), check_vma=False)
    return shard


@pytest.mark.parametrize("n_stages,n_micro", [(4, 4), (4, 8), (8, 4)])
def test_pipeline_forward_matches_sequential(rng, n_stages, n_micro):
    mesh = _mesh(n_stages)
    w, b = _params(rng, n_stages)
    xs = jnp.asarray(rng.standard_normal((n_micro, MICRO, D)), jnp.float32)
    got = jax.jit(_run_pipeline(mesh))(w, b, xs)
    want = _oracle(w, b, xs)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_pipeline_grads_match_sequential(rng):
    n_stages = 4
    mesh = _mesh(n_stages)
    w, b = _params(rng, n_stages)
    xs = jnp.asarray(rng.standard_normal((6, MICRO, D)), jnp.float32)
    w_out = jnp.asarray(rng.standard_normal(xs.shape), jnp.float32)
    shard = _run_pipeline(mesh)

    def pipe_loss(w, b, xs):
        return jnp.sum(shard(w, b, xs) * w_out)

    def ref_loss(w, b, xs):
        return jnp.sum(_oracle(w, b, xs) * w_out)

    g_pipe = jax.jit(jax.grad(pipe_loss, argnums=(0, 1)))(w, b, xs)
    g_ref = jax.grad(ref_loss, argnums=(0, 1))(w, b, xs)
    for a, bb in zip(g_pipe, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(bb),
                                   rtol=3e-5, atol=3e-5)
