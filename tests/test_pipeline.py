"""Pipeline parallelism (parallel/pipeline.py) vs the sequential oracle on
the CPU mesh: forward equality over the fill/drain schedule, gradient
equality through jax.grad (ppermute transposes give the backward), and
composition with extra microbatches."""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.parallel import pipeline_apply

D, MICRO = 8, 4


def _mesh(n):
    return Mesh(np.array(jax.devices()[:n]), ("pp",))


def _stage_fn(params, x):
    w, b = params
    return jnp.tanh(x @ w[0] + b[0])


def _stack_stage_fn(params, x):
    # PipelinedStack hands each device its UNWRAPPED stage params (the
    # stack slices with keepdims=False), unlike the shard_map-sharded
    # convention of _stage_fn where the leading dim survives as size 1
    w, b = params
    return jnp.tanh(x @ w + b)


def _params(rng, n_stages):
    w = jnp.asarray(rng.standard_normal((n_stages, D, D)) * 0.5, jnp.float32)
    b = jnp.asarray(rng.standard_normal((n_stages, D)) * 0.1, jnp.float32)
    return w, b


def _oracle(w, b, xs):
    def apply_all(x):
        for i in range(w.shape[0]):
            x = jnp.tanh(x @ w[i] + b[i])
        return x
    return jnp.stack([apply_all(xs[i]) for i in range(xs.shape[0])])


def _run_pipeline(mesh):
    fn = functools.partial(pipeline_apply, _stage_fn, axis_name="pp")

    def f(w, b, xs):
        return fn((w, b), xs)

    shard = jax.shard_map(f, mesh=mesh,
                          in_specs=(P("pp"), P("pp"), P()),
                          out_specs=P(), check_vma=False)
    return shard


@pytest.mark.parametrize("n_stages,n_micro", [(4, 4), (4, 8), (8, 4)])
def test_pipeline_forward_matches_sequential(rng, n_stages, n_micro):
    mesh = _mesh(n_stages)
    w, b = _params(rng, n_stages)
    xs = jnp.asarray(rng.standard_normal((n_micro, MICRO, D)), jnp.float32)
    got = jax.jit(_run_pipeline(mesh))(w, b, xs)
    want = _oracle(w, b, xs)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=2e-5, atol=2e-5)


def test_pipeline_grads_match_sequential(rng):
    n_stages = 4
    mesh = _mesh(n_stages)
    w, b = _params(rng, n_stages)
    xs = jnp.asarray(rng.standard_normal((6, MICRO, D)), jnp.float32)
    w_out = jnp.asarray(rng.standard_normal(xs.shape), jnp.float32)
    shard = _run_pipeline(mesh)

    def pipe_loss(w, b, xs):
        return jnp.sum(shard(w, b, xs) * w_out)

    def ref_loss(w, b, xs):
        return jnp.sum(_oracle(w, b, xs) * w_out)

    g_pipe = jax.jit(jax.grad(pipe_loss, argnums=(0, 1)))(w, b, xs)
    g_ref = jax.grad(ref_loss, argnums=(0, 1))(w, b, xs)
    for a, bb in zip(g_pipe, g_ref):
        np.testing.assert_allclose(np.asarray(a), np.asarray(bb),
                                   rtol=3e-5, atol=3e-5)


def test_pipelined_stack_step_matches_dense_oracle(rng):
    """PipelinedStack through make_train_step(tp_axis="pp"): the pipeline's
    microbatch axis is the gradient-accumulation unit — per-step losses
    and parameters track a dense sequential run of the same stages on the
    full batch (mean-reduction loss decomposes over microbatches)."""
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.parallel import PipelinedStack
    from apex_tpu.training import make_train_step

    n_stages, n_micro, b = 4, 4, 16
    mesh = _mesh(n_stages)
    w, bias = _params(rng, n_stages)
    x = jnp.asarray(rng.standard_normal((b, D)), jnp.float32)
    y = jnp.asarray(rng.standard_normal((b, D)), jnp.float32)

    def loss_fn(out, y):
        return jnp.mean((out - y) ** 2)

    # dense oracle: same stacked params trained sequentially
    class Dense:
        def __init__(self):
            from apex_tpu.nn.parameter import Parameter
            self._w = Parameter(w)
            self._b = Parameter(bias)
            self.training = True

        def parameters(self):
            return [self._w, self._b]

        def buffers(self):
            return []

        def modules(self):
            return []

        def forward(self, ctx, x):
            wv, bv = ctx.value(self._w), ctx.value(self._b)
            for i in range(n_stages):
                x = jnp.tanh(x @ wv[i] + bv[i])
            return x

    dense = Dense()
    opt_d = FusedAdam(dense.parameters(), lr=1e-2)
    step_d = make_train_step(dense, opt_d, loss_fn, half_dtype=None,
                             loss_scale=1.0)
    ref_losses = [float(step_d(x, y)) for _ in range(8)]

    stack = PipelinedStack(_stack_stage_fn, (w, bias), "pp",
                           n_micro=n_micro)
    opt_p = FusedAdam(stack.parameters(), lr=1e-2)
    step_p = make_train_step(stack, opt_p, loss_fn, half_dtype=None,
                             loss_scale=1.0, tp_axis="pp")
    sharded = jax.jit(jax.shard_map(
        step_p._step_fn, mesh=mesh, in_specs=(P(), P(), P()),
        out_specs=(P(), P()), check_vma=False))
    state, losses = step_p.state, []
    for _ in range(8):
        state, l = sharded(state, x, y)
        losses.append(float(l))
    np.testing.assert_allclose(losses, ref_losses, rtol=2e-4, atol=2e-4)


def test_pipelined_stack_remat_matches_no_remat(rng):
    """remat_stage=True recomputes stage internals in backward without
    changing the numbers."""
    from apex_tpu.parallel import PipelinedStack

    n_stages, n_micro, b = 4, 4, 8
    mesh = _mesh(n_stages)
    w, bias = _params(rng, n_stages)
    x = jnp.asarray(rng.standard_normal((b, D)), jnp.float32)
    w_out = jnp.asarray(rng.standard_normal((b, D)), jnp.float32)

    from apex_tpu.nn.modules import Ctx

    outs = []
    for remat in (False, True):
        stack = PipelinedStack(_stack_stage_fn, (w, bias), "pp",
                               n_micro=n_micro, remat_stage=remat)
        ps = stack.parameters()

        def f(vals, x):
            def loss(vals):
                ctx = Ctx(env={id(p): v for p, v in zip(ps, vals)})
                return jnp.sum(stack.forward(ctx, x) * w_out)
            l, g = jax.value_and_grad(loss)(vals)
            return l, [jax.lax.psum(gi, "pp") for gi in g]

        l, g = jax.jit(jax.shard_map(
            f, mesh=mesh, in_specs=(P(), P()), out_specs=P(),
            check_vma=False))([p.data for p in ps], x)
        outs.append((float(l), [np.asarray(gi) for gi in g]))
    (l0, g0), (l1, g1) = outs
    np.testing.assert_allclose(l1, l0, rtol=1e-6)
    for a, bb in zip(g0, g1):
        np.testing.assert_allclose(a, bb, rtol=1e-5, atol=1e-6)


def test_pipeline_rejects_shape_changing_stage(rng):
    mesh = _mesh(4)
    w, bias = _params(rng, 4)
    xs = jnp.asarray(rng.standard_normal((4, MICRO, D)), jnp.float32)

    def bad_stage(params, x):
        return jnp.concatenate([x, x], axis=-1)   # widens the activation

    def f(w, b, xs):
        return pipeline_apply(bad_stage, (w, b), xs, "pp")

    with pytest.raises(ValueError, match="share one activation"):
        jax.jit(jax.shard_map(
            f, mesh=mesh, in_specs=(P("pp"), P("pp"), P()),
            out_specs=P(), check_vma=False))(w, bias, xs)


def test_pipelined_stack_rejects_indivisible_batch(rng):
    from apex_tpu.nn.modules import Ctx
    from apex_tpu.parallel import PipelinedStack

    mesh = _mesh(4)
    w, bias = _params(rng, 4)
    stack = PipelinedStack(_stack_stage_fn, (w, bias), "pp", n_micro=3)
    x = jnp.asarray(rng.standard_normal((8, D)), jnp.float32)  # 8 % 3

    def f(x):
        return stack.forward(Ctx(), x)

    with pytest.raises(ValueError, match="n_micro"):
        jax.jit(jax.shard_map(
            f, mesh=mesh, in_specs=P(), out_specs=P(),
            check_vma=False))(x)
