"""ZeRO-style optimizer-state sharding (parallel/zero.py): sharded-state
numerics vs the single-replica oracle, sharding placement, and the memory
diagnostic — on the 8-device CPU mesh."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

import apex_tpu.nn as nn
from apex_tpu.nn import functional as F
from apex_tpu.optimizers import FusedAdam
from apex_tpu.parallel import ZeroTrainStep, zero_state_sharding
from apex_tpu.training import make_train_step


def _build(lr=1e-2):
    nn.manual_seed(11)
    model = nn.Sequential(nn.Linear(16, 64), nn.ReLU(),
                          nn.Linear(64, 8))
    opt = FusedAdam(list(model.parameters()), lr=lr)
    return model, opt


def _batch(rng, n=32):
    x = jnp.asarray(rng.standard_normal((n, 16)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 8, (n,)))
    return x, y


def test_zero_matches_unsharded(rng):
    """K steps under ZeRO sharding == K steps of the plain jitted step."""
    x, y = _batch(rng)

    model, opt = _build()
    ref = make_train_step(model, opt, lambda o, t: F.cross_entropy(o, t),
                          half_dtype=None, loss_scale=1.0)
    for _ in range(5):
        ref_loss = ref(x, y)
    ref.sync_to_objects()
    ref_params = [np.asarray(p.data) for p in model.parameters()]

    model2, opt2 = _build()
    step = make_train_step(model2, opt2,
                           lambda o, t: F.cross_entropy(o, t),
                           half_dtype=None, loss_scale=1.0,
                           donate_state=False)
    mesh = Mesh(np.array(jax.devices()), ("data",))
    zstep = ZeroTrainStep(step, mesh)
    for _ in range(5):
        z_loss = zstep(x, y)
    zstep.sync_to_objects()
    z_params = [np.asarray(p.data) for p in model2.parameters()]

    assert abs(float(ref_loss) - float(z_loss)) < 1e-5
    for a, b in zip(ref_params, z_params):
        np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-6)


def test_zero_state_is_sharded(rng):
    """Masters and optimizer slots with divisible dim 0 are sharded over
    the axis; scalars and small tensors replicate; the per-device
    footprint diagnostic reflects the win."""
    model, opt = _build()
    step = make_train_step(model, opt, lambda o, t: F.cross_entropy(o, t),
                           half_dtype=None, loss_scale=1.0,
                           donate_state=False)
    mesh = Mesh(np.array(jax.devices()), ("data",))
    zstep = ZeroTrainStep(step, mesh)
    x, y = _batch(rng)
    zstep(x, y)

    n = mesh.shape["data"]
    # Linear(16,64).weight: (64,16) -> dim0 64 % 8 == 0: sharded
    w0 = zstep.state.master_params[0]
    assert w0.sharding.shard_shape(w0.shape)[0] == w0.shape[0] // n
    m0 = zstep.state.opt_state["m"][0]
    assert m0.sharding.shard_shape(m0.shape)[0] == m0.shape[0] // n
    # the scalar step counter replicates
    assert zstep.state.step.sharding.is_fully_replicated

    replicated = sum(
        int(np.prod(leaf.shape)) * leaf.dtype.itemsize
        for leaf in jax.tree.leaves((zstep.state.master_params,
                                     zstep.state.opt_state)))
    per_device = zstep.shard_sizes()
    assert per_device < replicated / 2  # most tensors shard 8-way


def test_zero_sharding_spec_shapes():
    """zero_state_sharding replicates what cannot shard (odd dims,
    scalars) and shards the rest."""
    model, opt = _build()
    step = make_train_step(model, opt, lambda o, t: F.cross_entropy(o, t),
                           half_dtype=None, loss_scale=1.0,
                           donate_state=False)
    mesh = Mesh(np.array(jax.devices()), ("data",))
    sh = zero_state_sharding(step.state, mesh)
    # bias of Linear(16,64): (64,) -> sharded; scaler scalars replicated
    assert sh.master_params[1].spec == P("data")
    assert sh.scaler.loss_scale.spec == P()
    assert all(s.spec == P() for s in sh.stats) or not sh.stats


def test_zero_requires_raw_step():
    with pytest.raises(ValueError, match="_raw_step_fn"):
        class Fake:
            pass
        ZeroTrainStep(Fake(), Mesh(np.array(jax.devices()), ("data",)))


def test_zero_with_half_and_dynamic_scale(rng):
    """bf16 model copies + dynamic scaler under ZeRO: trains, scale state
    replicated, loss decreases."""
    nn.manual_seed(3)
    model = nn.Sequential(nn.Linear(16, 64), nn.GELU(), nn.Linear(64, 8))
    opt = FusedAdam(list(model.parameters()), lr=5e-3)
    step = make_train_step(model, opt, lambda o, t: F.cross_entropy(o, t),
                           half_dtype=jnp.bfloat16, loss_scale="dynamic",
                           donate_state=False)
    mesh = Mesh(np.array(jax.devices()), ("data",))
    zstep = ZeroTrainStep(step, mesh)
    x, y = _batch(rng, n=64)
    l0 = float(zstep(x, y))
    for _ in range(15):
        l = float(zstep(x, y))
    assert np.isfinite(l) and l < l0
    # half model copies replicate (they feed every shard's forward)
    mp = [v for v in zstep.state.model_params if v is not None]
    assert mp and all(v.sharding.is_fully_replicated for v in mp)


def test_zero_rejects_donating_step():
    model, opt = _build()
    # force donation: the default is "auto", which resolves to False on
    # the cpu backend (step_cache's donation policy)
    step = make_train_step(model, opt, lambda o, t: F.cross_entropy(o, t),
                           half_dtype=None, loss_scale=1.0,
                           donate_state=True)
    with pytest.raises(ValueError, match="donate_state=False"):
        ZeroTrainStep(step, Mesh(np.array(jax.devices()), ("data",)))


def test_zero_rejects_axis_name_step():
    model, opt = _build()
    step = make_train_step(model, opt, lambda o, t: F.cross_entropy(o, t),
                           half_dtype=None, loss_scale=1.0,
                           axis_name="data")
    with pytest.raises(ValueError, match="WITHOUT axis_name"):
        ZeroTrainStep(step, Mesh(np.array(jax.devices()), ("data",)))


def test_zero_broadcasts_scalar_tail_args(rng):
    """Scalar loss_fn tail args replicate instead of crashing on a forced
    P(axis) placement."""
    nn.manual_seed(0)
    model = nn.Sequential(nn.Linear(16, 32), nn.ReLU(), nn.Linear(32, 8))
    opt = FusedAdam(list(model.parameters()), lr=1e-2)

    def weighted(out, t, w):
        return F.cross_entropy(out, t) * w

    step = make_train_step(model, opt, weighted, half_dtype=None,
                           loss_scale=1.0, donate_state=False)
    mesh = Mesh(np.array(jax.devices()), ("data",))
    zstep = ZeroTrainStep(step, mesh)
    x, y = _batch(rng)
    loss = zstep(x, y, jnp.asarray(0.5, jnp.float32))
    assert np.isfinite(float(loss))


def test_zero_hlo_contains_sharded_update_collectives(rng):
    """The compiled ZeRO step must actually partition the update: params
    all-gather for the forward, and the gradient reduction lands in
    shards (true reduce-scatter on TPU; the CPU backend lowers it as
    all-reduce + dynamic-slice)."""
    model, opt = _build()
    step = make_train_step(model, opt, lambda o, t: F.cross_entropy(o, t),
                           half_dtype=None, loss_scale=1.0,
                           donate_state=False)
    mesh = Mesh(np.array(jax.devices()), ("data",))
    zstep = ZeroTrainStep(step, mesh)
    x, y = _batch(rng)
    shs = zstep._batch_shardings((x, y))
    hlo = zstep._jitted(shs).lower(zstep.state, x, y).compile().as_text()
    assert hlo.count("all-gather") > 0, "no param all-gather in ZeRO HLO"
    scattered = hlo.count("reduce-scatter") > 0 or (
        hlo.count("all-reduce") > 0 and hlo.count("dynamic-slice") > 0)
    assert scattered, "gradient reduction is not sharded in ZeRO HLO"


def test_zero_composes_with_accum_and_schedule(rng):
    """The memory levers stack: ZeRO sharding over a step built with
    grad_accum_steps and an lr schedule — numerics match the plain step
    with the same config."""
    from apex_tpu.optimizers import warmup_linear

    def build_step():
        nn.manual_seed(13)
        model = nn.Sequential(nn.Linear(16, 64), nn.ReLU(),
                              nn.Linear(64, 8))
        opt = FusedAdam(list(model.parameters()), lr=1e-2)
        return model, make_train_step(
            model, opt, lambda o, t: F.cross_entropy(o, t),
            half_dtype=None, loss_scale=1.0, grad_accum_steps=2,
            lr_schedule=warmup_linear(2, 20), donate_state=False)

    x, y = _batch(rng)
    m_ref, ref = build_step()
    for _ in range(4):
        ref_loss = ref(x, y)
    ref.sync_to_objects()
    ref_params = [np.asarray(p.data) for p in m_ref.parameters()]

    m_z, step = build_step()
    zstep = ZeroTrainStep(step, Mesh(np.array(jax.devices()), ("data",)))
    for _ in range(4):
        z_loss = zstep(x, y)
    zstep.sync_to_objects()
    z_params = [np.asarray(p.data) for p in m_z.parameters()]

    assert abs(float(ref_loss) - float(z_loss)) < 1e-5
    for a, b in zip(ref_params, z_params):
        np.testing.assert_allclose(a, b, rtol=2e-5, atol=2e-6)


def test_make_train_step_zero_sharding_api(rng):
    """One API path (VERDICT r2 #8): make_train_step(zero_sharding=True)
    returns the ZeRO-wrapped step directly — trains, masters sharded,
    and the compiled HLO carries the GSPMD-derived ZeRO collective
    pattern: reduce-scatter where the backend forms it, otherwise its
    unfused equivalent (all-reduce + dynamic-slice into the shard-shaped
    masters — the CPU partitioner does not run the reduce-scatter
    creator pass)."""
    nn.manual_seed(0)
    model = nn.Sequential(nn.Linear(16, 64), nn.GELU(), nn.Linear(64, 8))
    opt = FusedAdam(list(model.parameters()), lr=5e-3)
    step = make_train_step(model, opt,
                           lambda o, t: F.cross_entropy(o, t),
                           half_dtype=jnp.bfloat16, loss_scale="dynamic",
                           zero_sharding=True)
    x, y = _batch(rng, n=64)
    l0 = float(step(x, y))
    for _ in range(10):
        l = float(step(x, y))
    assert np.isfinite(l) and l < l0

    n = len(jax.devices())
    w0 = step.state.master_params[0]
    assert w0.sharding.shard_shape(w0.shape)[0] == w0.shape[0] // n

    shs = step._batch_shardings((x, y))
    txt = step._jitted(shs).lower(step.state, x, y).compile().as_text()
    has_rs = "reduce-scatter" in txt
    has_unfused = "all-reduce" in txt and "dynamic-slice" in txt
    assert has_rs or has_unfused, "no sharded gradient exchange in HLO"
    assert "all-gather" in txt, "updated masters never gather back"


def test_zero_sharding_rejects_axis_name():
    model, opt = _build()
    with pytest.raises(ValueError, match="excludes axis_name"):
        make_train_step(model, opt, lambda o, t: F.cross_entropy(o, t),
                        axis_name="data", zero_sharding=True)

def test_zero_stage3_matches_stage1(rng):
    """Stage 3 (sharded half model copies) must be a pure layout change:
    same losses and synced-back params as stage 1 on the identical bf16
    config.  (Stage 1 is itself anchored to the plain unsharded step by
    test_zero_matches_unsharded; comparing 3-vs-1 isolates exactly what
    stage 3 changes.  A direct bf16 3-vs-plain comparison is NOT stable:
    partitioning reorders bf16 reductions and 5 Adam steps amplify a
    one-ulp gradient difference ~10x on single elements.)"""
    x, y = _batch(rng, n=64)

    def build_zero(stage):
        nn.manual_seed(7)
        model = nn.Sequential(nn.Linear(16, 64), nn.GELU(),
                              nn.Linear(64, 8))
        opt = FusedAdam(list(model.parameters()), lr=5e-3)
        step = make_train_step(model, opt,
                               lambda o, t: F.cross_entropy(o, t),
                               half_dtype=jnp.bfloat16, loss_scale=1.0,
                               zero_sharding=True, zero_stage=stage)
        return model, step

    model1, z1 = build_zero(1)
    model3, z3 = build_zero(3)
    for _ in range(5):
        l1 = z1(x, y)
        l3 = z3(x, y)
    assert abs(float(l1) - float(l3)) < 1e-6
    z1.sync_to_objects()
    z3.sync_to_objects()
    for a, b in zip(model1.parameters(), model3.parameters()):
        np.testing.assert_allclose(np.asarray(a.data, np.float32),
                                   np.asarray(b.data, np.float32),
                                   rtol=1e-5, atol=1e-6)


def test_zero_stage3_shards_half_copies(rng):
    """Stage 3 places the half model copies sharded (where dim 0
    divides), and the per-device footprint diagnostic shrinks vs the
    same model under stage 1."""
    def build_zero(stage):
        nn.manual_seed(7)
        model = nn.Sequential(nn.Linear(16, 64), nn.GELU(),
                              nn.Linear(64, 8))
        opt = FusedAdam(list(model.parameters()), lr=5e-3)
        return make_train_step(model, opt,
                               lambda o, t: F.cross_entropy(o, t),
                               half_dtype=jnp.bfloat16, loss_scale=1.0,
                               zero_sharding=True, zero_stage=stage)

    x, y = _batch(rng, n=64)
    z1, z3 = build_zero(1), build_zero(3)
    z1(x, y)
    z3(x, y)

    n = len(jax.devices())
    # Linear(16,64) bf16 half weight: (64,16) -> sharded 8-way on dim 0
    mp3 = [v for v in z3.state.model_params if v is not None]
    assert mp3, "bf16 run must materialize half copies"
    w = mp3[0]
    assert w.sharding.shard_shape(w.shape)[0] == w.shape[0] // n
    # stage 1 keeps them replicated
    mp1 = [v for v in z1.state.model_params if v is not None]
    assert all(v.sharding.is_fully_replicated for v in mp1)
    assert z3.shard_sizes() < z1.shard_sizes()


def test_zero_stage3_hlo_gathers_params(rng):
    """Stage 3's compiled step must gather sharded params at use:
    STRICTLY more all-gathers than stage 1 (which only gathers updated
    masters back to replicated halves; stage 3 additionally gathers at
    forward/backward use sites — measured 17 vs 12 on this model on the
    CPU partitioner), and the sharded gradient exchange is still
    present.  The strict inequality is what fails if param_shard
    silently degenerates to stage-1 sharding."""
    def build_zero(stage):
        nn.manual_seed(7)
        model = nn.Sequential(nn.Linear(16, 64), nn.GELU(),
                              nn.Linear(64, 8))
        opt = FusedAdam(list(model.parameters()), lr=5e-3)
        return make_train_step(model, opt,
                               lambda o, t: F.cross_entropy(o, t),
                               half_dtype=jnp.bfloat16, loss_scale=1.0,
                               zero_sharding=True, zero_stage=stage)

    x, y = _batch(rng, n=64)
    texts = {}
    for stage in (1, 3):
        z = build_zero(stage)
        shs = z._batch_shardings((x, y))
        texts[stage] = (z._jitted(shs).lower(z.state, x, y)
                        .compile().as_text())
    assert texts[3].count("all-gather") > texts[1].count("all-gather")
    scattered = texts[3].count("reduce-scatter") > 0 or (
        texts[3].count("all-reduce") > 0
        and texts[3].count("dynamic-slice") > 0)
    assert scattered, "stage-3 gradient reduction is not sharded"


def test_zero_stage_validation():
    model, opt = _build()
    with pytest.raises(ValueError, match="zero_stage must be 1"):
        make_train_step(model, opt, lambda o, t: F.cross_entropy(o, t),
                        zero_sharding=True, zero_stage=2)


def test_zero_default_mesh_derives_from_ambient_context(rng):
    """The default zero_mesh must come from the active mesh context, not
    unconditionally from ALL jax.devices(): a step built inside
    ``with Mesh(...):`` on a dp x tp submesh shards over THAT mesh's
    data axis (replicating over tp), so the state lands only on devices
    the step runs on."""
    model, opt = _build()
    mesh = Mesh(np.array(jax.devices()).reshape(2, 4), ("data", "tp"))
    with mesh:
        step = make_train_step(model, opt,
                               lambda o, t: F.cross_entropy(o, t),
                               half_dtype=None, loss_scale=1.0,
                               zero_sharding=True)
    assert step.mesh is mesh
    x, y = _batch(rng)
    assert np.isfinite(float(step(x, y)))
    # Linear(16,64).weight shards 2-way (the ambient data axis), NOT the
    # 8-way a silently rebuilt global 1-D mesh would produce
    w0 = step.state.master_params[0]
    assert w0.sharding.shard_shape(w0.shape)[0] == w0.shape[0] // 2


def test_zero_default_mesh_ambient_mismatch_errors():
    """A genuine mismatch — ambient mesh without the zero axis — is a
    loud error naming the fix, not a silent global-mesh fallback."""
    model, opt = _build()
    mesh = Mesh(np.array(jax.devices()).reshape(2, 4), ("x", "y"))
    with mesh:
        with pytest.raises(ValueError, match="do not include zero_axis"):
            make_train_step(model, opt,
                            lambda o, t: F.cross_entropy(o, t),
                            zero_sharding=True)
