"""Test harness config.

Tests run on CPU with 8 virtual devices so mesh/collective code paths are
exercised without TPU hardware (SURVEY.md §4: the reference tests distributed
behavior single-node with --nproc_per_node=2; our analogue is an 8-device
virtual mesh).  Must run before jax is imported anywhere.
"""
import os

# force-override: the environment presets JAX_PLATFORMS=axon (the TPU tunnel)
os.environ["JAX_PLATFORMS"] = "cpu"
prev = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in prev:
    os.environ["XLA_FLAGS"] = (
        prev + " --xla_force_host_platform_device_count=8").strip()

# Hermetic calibration ledger: a developer machine's warm
# ~/.cache/apex_tpu/kernel_ledger.json must not steer kernel dispatch
# (or planner re-ranking) inside the test suite.  Tests that WANT a warm
# ledger point the process ledger at their own tmp file explicitly.
os.environ.setdefault(
    "APEX_TPU_LEDGER",
    os.path.join("/tmp", f"apex_tpu_test_ledger_{os.getpid()}.json"))

import jax  # noqa: E402

# The axon TPU plugin ignores the JAX_PLATFORMS env var; the config update
# does stick (verified: without it jax.devices() is the TPU even with
# JAX_PLATFORMS=cpu in the environment).
jax.config.update("jax_platforms", "cpu")

# Persistent XLA compilation cache: since the jax.shard_map compat shim
# (apex_tpu/compat.py) the model/inference suites genuinely COMPILE their
# 8-way shard_map programs instead of failing on import, which dominates
# suite wall time.  The cache (keyed on the lowered HLO, so code changes
# invalidate naturally) makes repeat runs skip identical compiles; only
# compiles over 0.5s are stored to keep cold-run overhead negligible.
try:
    jax.config.update(
        "jax_compilation_cache_dir",
        os.environ.get("APEX_TPU_TEST_CC_DIR", "/tmp/apex_tpu_test_xla_cache"))
    jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)
except Exception:   # older/newer jax without these knobs: run uncached
    pass

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(1234)


@pytest.fixture
def reset_amp():
    """Clear global amp state (shared by the e2e and L1 suites)."""
    from apex_tpu.amp._amp_state import reset as _r
    _r()
    return _r
