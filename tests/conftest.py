"""Test harness config.

Tests run on CPU with 8 virtual devices so mesh/collective code paths are
exercised without TPU hardware (SURVEY.md §4: the reference tests distributed
behavior single-node with --nproc_per_node=2; our analogue is an 8-device
virtual mesh).  Must run before jax is imported anywhere.
"""
import os

# force-override: the environment presets JAX_PLATFORMS=axon (the TPU tunnel)
os.environ["JAX_PLATFORMS"] = "cpu"
prev = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in prev:
    os.environ["XLA_FLAGS"] = (
        prev + " --xla_force_host_platform_device_count=8").strip()

import jax  # noqa: E402

# The axon TPU plugin ignores the JAX_PLATFORMS env var; the config update
# does stick (verified: without it jax.devices() is the TPU even with
# JAX_PLATFORMS=cpu in the environment).
jax.config.update("jax_platforms", "cpu")

import numpy as np  # noqa: E402
import pytest  # noqa: E402


@pytest.fixture
def rng():
    return np.random.default_rng(1234)


@pytest.fixture
def reset_amp():
    """Clear global amp state (shared by the e2e and L1 suites)."""
    from apex_tpu.amp._amp_state import reset as _r
    _r()
    return _r
