"""1F1B pipeline schedule (parallel/pipeline.py) vs the dense oracle on
the CPU mesh: schedule-table invariants, the ring-buffer memory bound,
direct gradient equality against jax.grad of the sequential stack, and
the fused train step tracking both the dense run and the GPipe step."""
import functools

import jax
import jax.numpy as jnp
import numpy as np
import pytest
from jax.sharding import Mesh, PartitionSpec as P

from apex_tpu.parallel import (PipelinedStack, build_1f1b_schedule,
                               make_pipeline_train_step,
                               pipeline_1f1b_grads, ring_slots)

D, MICRO = 8, 4


def _mesh(n):
    return Mesh(np.array(jax.devices()[:n]), ("pp",))


def _stage_fn(params, x):
    w, b = params
    return jnp.tanh(x @ w + b)


def _params(rng, n_stages):
    w = jnp.asarray(rng.standard_normal((n_stages, D, D)) * 0.5, jnp.float32)
    b = jnp.asarray(rng.standard_normal((n_stages, D)) * 0.1, jnp.float32)
    return w, b


def _dense_apply(w, b, x):
    for i in range(w.shape[0]):
        x = jnp.tanh(x @ w[i] + b[i])
    return x


@pytest.mark.parametrize("n,m", [(1, 4), (2, 3), (4, 4), (4, 9), (8, 2)])
def test_schedule_tables_invariants(n, m):
    """Every (stage, microbatch) forwards and backwards exactly once; a
    stage's input arrives exactly one tick before it forwards it; a
    cotangent arrives exactly one tick before it backwards it; backward
    never precedes the same microbatch's forward at that stage."""
    fwd, bwd = build_1f1b_schedule(n, m)
    assert fwd.shape == bwd.shape == (m + 2 * (n - 1), n)
    tf = np.full((n, m), -1)
    tb = np.full((n, m), -1)
    for t in range(fwd.shape[0]):
        for s in range(n):
            if fwd[t, s] >= 0:
                assert tf[s, fwd[t, s]] == -1
                tf[s, fwd[t, s]] = t
            if bwd[t, s] >= 0:
                assert tb[s, bwd[t, s]] == -1
                tb[s, bwd[t, s]] = t
    assert (tf >= 0).all() and (tb >= 0).all()
    for s in range(n):
        for mb in range(m):
            if s > 0:
                assert tf[s, mb] == tf[s - 1, mb] + 1
            if s < n - 1:
                assert tb[s, mb] == tb[s + 1, mb] + 1
            assert tb[s, mb] >= tf[s, mb]
            # the 1F1B residency bound: input live from forward tick to
            # backward tick, bounded independent of m
            assert tb[s, mb] - tf[s, mb] <= 2 * (n - 1)


def test_ring_slots_bounded_independent_of_microbatches():
    assert ring_slots(4, 64) == 7          # 2n-1, NOT m + n - 1
    assert ring_slots(4, 3) == 3           # never more slots than batches
    assert ring_slots(1, 16) == 1
    # the bound the GPipe scan pays instead grows with n_micro
    assert ring_slots(4, 64) < 64 + 4 - 1


@pytest.mark.parametrize("n_stages,n_micro", [(1, 4), (4, 4), (4, 9),
                                              (8, 3)])
def test_1f1b_grads_match_dense_oracle(rng, n_stages, n_micro):
    mesh = _mesh(n_stages)
    w, b = _params(rng, n_stages)
    xs = jnp.asarray(rng.standard_normal((n_micro, MICRO, D)), jnp.float32)
    ys = jnp.asarray(rng.standard_normal((n_micro, MICRO, D)), jnp.float32)

    def loss_fn(y, yref):
        return jnp.mean((y - yref) ** 2)

    def run(w, b, xs, ys):
        i = jax.lax.axis_index("pp")
        local = jax.tree.map(
            lambda a: jax.lax.dynamic_index_in_dim(a, i, 0, keepdims=False),
            (w, b))
        loss, g = pipeline_1f1b_grads(_stage_fn, local, xs, ys, loss_fn,
                                      "pp")
        g = jax.tree.map(
            lambda gi, full: jax.lax.psum(
                jax.lax.dynamic_update_index_in_dim(
                    jnp.zeros(full.shape, jnp.float32), gi, i, 0), "pp"),
            g, (w, b))
        return loss, g

    loss, (gw, gb) = jax.jit(jax.shard_map(
        run, mesh=mesh, in_specs=(P(), P(), P(), P()),
        out_specs=(P(), (P(), P())), check_vma=False))(w, b, xs, ys)

    def ref(w, b):
        per = [loss_fn(_dense_apply(w, b, xs[i]), ys[i])
               for i in range(n_micro)]
        return sum(per) / n_micro

    want, (gw_r, gb_r) = jax.value_and_grad(ref, argnums=(0, 1))(w, b)
    np.testing.assert_allclose(float(loss), float(want), rtol=1e-5)
    np.testing.assert_allclose(np.asarray(gw), np.asarray(gw_r),
                               rtol=3e-5, atol=3e-5)
    np.testing.assert_allclose(np.asarray(gb), np.asarray(gb_r),
                               rtol=3e-5, atol=3e-5)


def test_1f1b_cotangent_scale_scales_grads_not_loss(rng):
    mesh = _mesh(4)
    w, b = _params(rng, 4)
    xs = jnp.asarray(rng.standard_normal((4, MICRO, D)), jnp.float32)
    ys = jnp.asarray(rng.standard_normal((4, MICRO, D)), jnp.float32)

    def loss_fn(y, yref):
        return jnp.mean((y - yref) ** 2)

    def run(scale, w, b, xs, ys):
        i = jax.lax.axis_index("pp")
        local = jax.tree.map(
            lambda a: jax.lax.dynamic_index_in_dim(a, i, 0, keepdims=False),
            (w, b))
        loss, g = pipeline_1f1b_grads(_stage_fn, local, xs, ys, loss_fn,
                                      "pp", cotangent_scale=scale)
        return loss, jax.lax.psum(jnp.sum(jnp.abs(g[0])), "pp")

    f = jax.jit(jax.shard_map(
        functools.partial(run), mesh=mesh,
        in_specs=(P(), P(), P(), P(), P()), out_specs=(P(), P()),
        check_vma=False), static_argnums=())
    l1, g1 = f(jnp.float32(1.0), w, b, xs, ys)
    l128, g128 = f(jnp.float32(128.0), w, b, xs, ys)
    np.testing.assert_allclose(float(l1), float(l128), rtol=1e-6)
    np.testing.assert_allclose(float(g128), 128.0 * float(g1), rtol=1e-4)


def test_1f1b_step_matches_dense_and_gpipe(rng):
    """make_pipeline_train_step(schedule='1f1b') trains identically to a
    dense sequential run of the same stages (mean-reduction loss) and to
    the GPipe-schedule step."""
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.training import make_train_step

    n_stages, n_micro, batch = 4, 4, 16
    mesh = _mesh(n_stages)
    w, bias = _params(rng, n_stages)
    x = jnp.asarray(rng.standard_normal((batch, D)), jnp.float32)
    y = jnp.asarray(rng.standard_normal((batch, D)), jnp.float32)

    def loss_fn(out, y):
        return jnp.mean((out - y) ** 2)

    class Dense:
        def __init__(self):
            from apex_tpu.nn.parameter import Parameter
            self._w = Parameter(w)
            self._b = Parameter(bias)
            self.training = True

        def parameters(self):
            return [self._w, self._b]

        def buffers(self):
            return []

        def modules(self):
            return []

        def forward(self, ctx, x):
            return _dense_apply(ctx.value(self._w), ctx.value(self._b), x)

    dense = Dense()
    step_d = make_train_step(dense, FusedAdam(dense.parameters(), lr=1e-2),
                             loss_fn, half_dtype=None, loss_scale=1.0)
    ref_losses = [float(step_d(x, y)) for _ in range(8)]

    losses = {}
    for schedule in ("1f1b", "gpipe"):
        stack = PipelinedStack(_stage_fn, (w, bias), "pp", n_micro=n_micro)
        step = make_pipeline_train_step(
            stack, FusedAdam(stack.parameters(), lr=1e-2), loss_fn,
            schedule=schedule, half_dtype=None, loss_scale=1.0)
        sharded = jax.jit(jax.shard_map(
            step._step_fn, mesh=mesh, in_specs=(P(), P(), P()),
            out_specs=(P(), P()), check_vma=False))
        state, ls = step.state, []
        for _ in range(8):
            state, l = sharded(state, x, y)
            ls.append(float(l))
        losses[schedule] = ls
    np.testing.assert_allclose(losses["1f1b"], ref_losses,
                               rtol=2e-4, atol=2e-4)
    np.testing.assert_allclose(losses["gpipe"], ref_losses,
                               rtol=2e-4, atol=2e-4)


def test_1f1b_step_bf16_dynamic_scale_converges(rng):
    """The 1F1B step composes with amp: bf16 stage compute + dynamic loss
    scaling, loss decreasing over steps."""
    from apex_tpu.optimizers import FusedSGD

    n_stages, n_micro, batch = 4, 8, 32
    mesh = _mesh(n_stages)
    w, bias = _params(rng, n_stages)
    x = jnp.asarray(rng.standard_normal((batch, D)), jnp.float32)
    y = jnp.asarray(np.tanh(rng.standard_normal((batch, D))), jnp.float32)

    def loss_fn(out, y):
        return jnp.mean((out.astype(jnp.float32) - y) ** 2)

    stack = PipelinedStack(_stage_fn, (w, bias), "pp", n_micro=n_micro)
    step = make_pipeline_train_step(
        stack, FusedSGD(stack.parameters(), lr=0.05, momentum=0.9),
        loss_fn, half_dtype=jnp.bfloat16)
    sharded = jax.jit(jax.shard_map(
        step._step_fn, mesh=mesh, in_specs=(P(), P(), P()),
        out_specs=(P(), P()), check_vma=False))
    state = step.state
    losses = []
    for _ in range(30):
        state, l = sharded(state, x, y)
        losses.append(float(l))
    assert losses[-1] < 0.5 * losses[0]


def test_1f1b_rejects_remat_stack_and_bad_schedule(rng):
    from apex_tpu.optimizers import FusedAdam

    w, bias = _params(rng, 4)
    stack = PipelinedStack(_stage_fn, (w, bias), "pp", n_micro=4,
                           remat_stage=True)
    with pytest.raises(ValueError, match="remat_stage=False"):
        make_pipeline_train_step(
            stack, FusedAdam(stack.parameters(), lr=1e-2),
            lambda o, y: jnp.mean((o - y) ** 2), schedule="1f1b")
    stack2 = PipelinedStack(_stage_fn, (w, bias), "pp", n_micro=4)
    with pytest.raises(ValueError, match="gpipe.*1f1b|1f1b.*gpipe"):
        make_pipeline_train_step(
            stack2, FusedAdam(stack2.parameters(), lr=1e-2),
            lambda o, y: jnp.mean((o - y) ** 2), schedule="2f2b")
