"""L1 cross-product: {O0..O3} × loss_scale × keep_batchnorm_fp32, Pallas
build vs pure-XLA build (reference: tests/L1/run_test.sh:22-110 looping the
same product over extensions-installed vs Python-only builds, with
compare.py:34-40 asserting iteration-for-iteration loss equality).

'interpret' runs the real Pallas kernel logic through the interpreter (the
"extensions" build on CPU); 'off' is the jnp fallback ("Python-only").
Both see identical data/init, so their loss curves must agree elementwise
to float tolerance, every iteration, in every configuration.
"""
import os
import sys

import numpy as np
import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "common"))
from main_amp import run_config  # noqa: E402

LOSS_SCALES = [None, 1.0, 128.0, "dynamic"]


def _configs():
    out = []
    for opt_level in ("O0", "O1", "O2", "O3"):
        for ls in LOSS_SCALES:
            kbf_options = [None]
            if opt_level in ("O2", "O3"):
                kbf_options = [None, True, False]
            for kbf in kbf_options:
                out.append((opt_level, ls, kbf))
    return out


@pytest.mark.parametrize("opt_level,loss_scale,kbf", _configs())
def test_pallas_vs_python_build_loss_parity(opt_level, loss_scale, kbf):
    python_build = run_config(opt_level, loss_scale, kbf, pallas="off")
    pallas_build = run_config(opt_level, loss_scale, kbf,
                              pallas="interpret")
    assert len(python_build) == len(pallas_build) == 3
    assert all(np.isfinite(python_build)), (opt_level, loss_scale, kbf)
    np.testing.assert_allclose(
        pallas_build, python_build, rtol=2e-3, atol=2e-4,
        err_msg=f"loss curves diverge for {(opt_level, loss_scale, kbf)}")


def test_mixed_precision_tracks_fp32_baseline():
    """All opt levels start from identical init/data, so iteration-0 loss
    matches O0 closely and trajectories stay in the same neighborhood
    (reference compare.py's cross-run check against stored baselines)."""
    base = run_config("O0")
    for opt_level in ("O1", "O2", "O3"):
        got = run_config(opt_level)
        np.testing.assert_allclose(got[0], base[0], rtol=5e-2)
        assert abs(got[-1] - base[-1]) < 0.5 * max(1.0, abs(base[-1]))
