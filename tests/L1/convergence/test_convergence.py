"""Convergence oracle for BASELINE.md config 1 (VERDICT r2 #5): training
must reach a STATED accuracy, not merely run steps.

Two layers of evidence:
1. the committed ``curves.json`` artifact — ResNet-18 (CIFAR stem),
   150 steps of batch 64 on the synthetic CIFAR stand-in, fp32 and
   imperative amp-O1 arms (``run_convergence.py``) — is validated
   against the accuracy target and the fp32/amp agreement oracle
   (reference: tests/L1/common/compare.py:34-40 compares builds; here
   the same check compares precision modes);
2. a LIVE reduced-scale run (narrow ResNet stem) re-proves in-suite
   that the pipeline trains to accuracy from scratch in ~a minute.
"""
import json
import os
import sys

import jax.numpy as jnp
import numpy as np
import pytest

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))

from synth_cifar import make_split  # noqa: E402

ART = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                   "curves.json")

# the stated target: both arms must classify >= 85% of held-out samples
# (observed ~0.95+ at 150 steps; 10-class chance is 10%)
TARGET_ACC = 0.85


@pytest.fixture(scope="module")
def artifact():
    if not os.path.exists(ART):
        pytest.skip("curves.json not generated yet (run "
                    "run_convergence.py)")
    with open(ART) as f:
        return json.load(f)


def test_artifact_reaches_accuracy_target(artifact):
    for arm in ("fp32", "amp_o1"):
        acc = artifact["arms"][arm]["final_acc"]
        assert acc >= TARGET_ACC, (arm, acc)


def test_artifact_amp_tracks_fp32(artifact):
    """The amp-O1 loss curve must track fp32 — same oracle the reference
    applies across builds, applied across precision modes.  Identical
    data/seeds, so curves stay close in the mean."""
    f32 = np.asarray(artifact["arms"]["fp32"]["losses"])
    o1 = np.asarray(artifact["arms"]["amp_o1"]["losses"])
    assert f32.shape == o1.shape
    # fp16 arithmetic drifts the trajectories; the mean gap over the run
    # and the final values must stay small
    assert np.abs(f32 - o1).mean() < 0.15, np.abs(f32 - o1).mean()
    assert abs(f32[-1] - o1[-1]) < 0.3, (f32[-1], o1[-1])


def test_live_convergence_smoke():
    """From-scratch mini run: a narrow conv net on the same data
    pipeline trains to >= 70% held-out accuracy in-suite."""
    import apex_tpu.nn as nn
    from apex_tpu.nn import functional as F
    from apex_tpu.nn.modules import Ctx
    from apex_tpu.optimizers import FusedSGD
    from apex_tpu.training import make_train_step

    nn.manual_seed(0)
    model = nn.Sequential(
        nn.Conv2d(3, 16, 3, padding=1), nn.BatchNorm2d(16), nn.ReLU(),
        nn.MaxPool2d(2),
        nn.Conv2d(16, 32, 3, padding=1), nn.BatchNorm2d(32), nn.ReLU(),
        nn.AdaptiveAvgPool2d((1, 1)), nn.Flatten(), nn.Linear(32, 10))
    opt = FusedSGD(list(model.parameters()), lr=0.1, momentum=0.9)
    step = make_train_step(model, opt,
                           lambda o, t: F.cross_entropy(o, t),
                           half_dtype=jnp.bfloat16, loss_scale=1.0)

    xtr, ytr = make_split(40 * 64, seed=11)
    for i in range(40):
        s = slice(i * 64, (i + 1) * 64)
        step(jnp.asarray(xtr[s]), jnp.asarray(ytr[s]))
    step.sync_to_objects()

    xte, yte = make_split(256, seed=12)
    model.eval()
    params = [p for p in model.parameters() if p is not None]
    env = {id(p): p.data for p in params}
    env.update({id(b): b.data for b in model.buffers()})
    ctx = Ctx(env=env, training=False)
    # the O2-style step keeps model copies in bf16; cast eval inputs the
    # way the step casts training inputs
    logits = model.forward(ctx, jnp.asarray(xte, jnp.bfloat16))
    acc = float(jnp.mean((jnp.argmax(logits, -1)
                          == jnp.asarray(yte)).astype(jnp.float32)))
    assert acc >= 0.70, acc
