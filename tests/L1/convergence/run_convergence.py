"""BASELINE.md config 1 convergence run (VERDICT r2 #5): ResNet-18
(CIFAR stem) on the synthetic CIFAR stand-in, trained under both fp32
and amp O1, curves + final held-out accuracy written to ``curves.json``.

The amp-O1 arm uses the TRUE imperative path (``amp.initialize`` +
``scale_loss``/``backward`` — config 1's semantics, reference
examples/simple); the fp32 arm uses the fused step.  Both must reach the
accuracy target and their loss curves must track each other — the
reference's cross-build oracle (tests/L1/common/compare.py:34-40)
applied to precision modes.

Run (CPU, ~30-60 min):  python run_convergence.py [--steps 150]
The committed ``curves.json`` is validated by ``test_convergence.py``.
"""
import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", "..", ".."))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))


def main():
    ap = argparse.ArgumentParser()
    ap.add_argument("--steps", type=int, default=150)
    ap.add_argument("--batch", type=int, default=64)
    ap.add_argument("--eval-n", type=int, default=512)
    ap.add_argument("--out", default=os.path.join(
        os.path.dirname(os.path.abspath(__file__)), "curves.json"))
    args = ap.parse_args()

    import jax
    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import numpy as np

    import apex_tpu.nn as nn
    from apex_tpu import amp
    from apex_tpu.models import resnet18
    from apex_tpu.nn import functional as F
    from apex_tpu.nn.modules import Ctx
    from apex_tpu.optimizers import FusedSGD
    from apex_tpu.training import make_train_step
    from synth_cifar import make_split

    xtr, ytr = make_split(args.steps * args.batch, seed=1)
    xte, yte = make_split(args.eval_n, seed=2)

    def batches():
        for i in range(args.steps):
            s = slice(i * args.batch, (i + 1) * args.batch)
            yield jnp.asarray(xtr[s]), jnp.asarray(ytr[s])

    def accuracy(model):
        model.eval()
        params = [p for p in model.parameters() if p is not None]
        buffers = list(model.buffers())
        env = {id(p): p.data for p in params}
        env.update({id(b): b.data for b in buffers})
        correct = 0
        for i in range(0, args.eval_n, 128):
            ctx = Ctx(env=env, training=False)
            logits = model.forward(ctx, jnp.asarray(xte[i:i + 128]))
            correct += int(jnp.sum(jnp.argmax(logits, -1)
                                   == jnp.asarray(yte[i:i + 128])))
        model.train()
        return correct / args.eval_n

    results = {"steps": args.steps, "batch": args.batch,
               "eval_n": args.eval_n, "arms": {}}

    # --- fp32 arm: fused step ---
    t0 = time.perf_counter()
    nn.manual_seed(0)
    m = resnet18(num_classes=10, small_input=True)
    opt = FusedSGD(list(m.parameters()), lr=0.05, momentum=0.9,
                   weight_decay=5e-4)
    step = make_train_step(m, opt, lambda o, t: F.cross_entropy(o, t),
                           half_dtype=None, loss_scale=1.0)
    losses = []
    for x, y in batches():
        losses.append(float(step(x, y)))
    step.sync_to_objects()
    acc = accuracy(m)
    results["arms"]["fp32"] = {
        "losses": losses, "final_acc": acc,
        "wall_s": round(time.perf_counter() - t0, 1)}
    print(f"fp32: final loss {losses[-1]:.4f}, acc {acc:.3f}", flush=True)

    # --- amp O1 arm: the imperative reference path ---
    t0 = time.perf_counter()
    from apex_tpu.amp._amp_state import reset as _amp_reset
    _amp_reset()
    nn.manual_seed(0)
    m1 = resnet18(num_classes=10, small_input=True)
    opt1 = FusedSGD(list(m1.parameters()), lr=0.05, momentum=0.9,
                    weight_decay=5e-4)
    m1, opt1 = amp.initialize(m1, opt1, opt_level="O1", verbosity=0)
    crit = nn.CrossEntropyLoss()
    losses1 = []
    for x, y in batches():
        out = m1(x)
        loss = crit(out, y)
        opt1.zero_grad()
        with amp.scale_loss(loss, opt1) as scaled:
            scaled.backward()
        opt1.step()
        losses1.append(float(loss))
    acc1 = accuracy(m1)
    results["arms"]["amp_o1"] = {
        "losses": losses1, "final_acc": acc1,
        "wall_s": round(time.perf_counter() - t0, 1)}
    print(f"amp O1: final loss {losses1[-1]:.4f}, acc {acc1:.3f}",
          flush=True)

    with open(args.out, "w") as f:
        json.dump(results, f)
    print(f"wrote {args.out}")


if __name__ == "__main__":
    main()
