"""Synthetic CIFAR-10 stand-in for the convergence runs (BASELINE.md
config 1).  The environment has no egress, so the real CIFAR archive
cannot be fetched; this generator produces a 10-class 32x32x3 image task
that still requires learned (not linearly separable) conv features:

* each class is a fixed low-frequency prototype (4x4 noise, bilinearly
  upsampled to 32x32) drawn once from a pinned seed;
* each sample applies a random circular shift of up to +-6 px in both
  spatial dims (so per-pixel linear classifiers fail — the decision
  needs shift-tolerant features) plus N(0, 0.6) pixel noise.

Deterministic given (seed, n): the test and the artifact script see the
same data.  Reference analogue: tests/L1/common/compare.py trains real
CIFAR/ImageNet epochs; the oracle here is the same — a stated accuracy
reached — with the dataset swapped for lack of egress.
"""
from __future__ import annotations

import numpy as np


def _prototypes(n_classes=10, size=32, seed=7):
    rng = np.random.default_rng(seed)
    coarse = rng.standard_normal((n_classes, 3, 4, 4)).astype(np.float32)
    # bilinear upsample 4x4 -> 32x32 per channel
    xs = np.linspace(0, 3, size)
    x0 = np.clip(np.floor(xs).astype(int), 0, 2)
    frac = (xs - x0).astype(np.float32)
    rows = (coarse[:, :, x0, :] * (1 - frac)[None, None, :, None]
            + coarse[:, :, x0 + 1, :] * frac[None, None, :, None])
    protos = (rows[:, :, :, x0] * (1 - frac)[None, None, None, :]
              + rows[:, :, :, x0 + 1] * frac[None, None, None, :])
    return protos * 2.0


def make_split(n, seed):
    """→ (images (n, 3, 32, 32) float32, labels (n,) int32)."""
    protos = _prototypes()
    rng = np.random.default_rng(seed)
    labels = rng.integers(0, 10, (n,)).astype(np.int32)
    imgs = protos[labels]
    sh = rng.integers(-6, 7, (n, 2))
    out = np.empty_like(imgs)
    for i in range(n):
        out[i] = np.roll(imgs[i], (sh[i, 0], sh[i, 1]), axis=(1, 2))
    out += rng.standard_normal(out.shape).astype(np.float32) * 0.6
    return out, labels
