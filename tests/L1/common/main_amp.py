"""Instrumented training loop for the L1 cross-product harness (reference:
tests/L1/common/main_amp.py — a clone of the ImageNet example that saves
per-iteration loss for cross-build comparison).

``run_config`` trains a small conv+BN+linear net on deterministic synthetic
data under a given (opt_level, loss_scale, keep_batchnorm_fp32, pallas
build) and returns the loss trajectory.  The reference compares an
extensions-installed run against a Python-only run
(tests/L1/run_test.sh:22-110); the TPU analogue compares the Pallas-kernel
build ('interpret' on CPU) against the pure-XLA fallback ('off'), same
oracle: iteration-for-iteration loss agreement (compare.py:34-40).
"""
from __future__ import annotations

import jax.numpy as jnp
import numpy as np

import apex_tpu.nn as nn
from apex_tpu import amp
from apex_tpu.ops.pallas import force_mode
from apex_tpu.optimizers import FusedSGD


def build_model():
    nn.manual_seed(42)
    return nn.Sequential(
        nn.Conv2d(3, 8, 3, padding=1), nn.BatchNorm2d(8), nn.ReLU(),
        nn.Conv2d(8, 16, 3, stride=2, padding=1), nn.BatchNorm2d(16),
        nn.ReLU(), nn.Flatten(), nn.Linear(16 * 4 * 4, 10))


def synthetic_batches(iters=3, batch=8):
    rng = np.random.default_rng(1234)
    return [(jnp.asarray(rng.standard_normal((batch, 3, 8, 8)),
                         jnp.float32),
             jnp.asarray(rng.integers(0, 10, (batch,))))
            for _ in range(iters)]


def _reset_amp():
    from apex_tpu.amp._amp_state import reset as _r
    _r()


def run_config(opt_level, loss_scale=None, keep_batchnorm_fp32=None,
               pallas="off", iters=3):
    """→ list of per-iteration losses (floats)."""
    with force_mode(pallas):
        _reset_amp()
        model = build_model()
        opt = FusedSGD(list(model.parameters()), lr=0.05, momentum=0.9)
        kwargs = {}
        if loss_scale is not None:
            kwargs["loss_scale"] = loss_scale
        if keep_batchnorm_fp32 is not None:
            kwargs["keep_batchnorm_fp32"] = keep_batchnorm_fp32
        model, opt = amp.initialize(model, opt, opt_level=opt_level,
                                    verbosity=0, **kwargs)
        crit = nn.CrossEntropyLoss()
        losses = []
        for x, y in synthetic_batches(iters):
            out = model(x)
            loss = crit(out, y)
            with amp.scale_loss(loss, opt) as scaled:
                scaled.backward()
            opt.step()
            opt.zero_grad()
            losses.append(float(loss))
        return losses
