"""Checkpoint/resume (utils/checkpoint.py): the reference's documented
three-part {model, optimizer, amp} workflow — save mid-training, restore
into fresh objects after amp.initialize with the same opt_level, and the
resumed run must continue exactly like the uninterrupted one
(reference README.md:59-99 'bitwise accurate' claim)."""
import os

import jax.numpy as jnp
import numpy as np
import pytest

import apex_tpu.nn as nn
from apex_tpu import amp
from apex_tpu.optimizers import FusedSGD
from apex_tpu.utils import load_checkpoint, save_checkpoint


@pytest.fixture(autouse=True)
def _fresh_amp_state():
    from apex_tpu.amp._amp_state import reset
    reset()
    yield
    reset()


def _model():
    nn.manual_seed(21)
    return nn.Sequential(nn.Linear(12, 24), nn.ReLU(), nn.Linear(24, 3))


def _data(seed=0):
    rng = np.random.default_rng(seed)
    return (jnp.asarray(rng.standard_normal((8, 12)), jnp.float32),
            jnp.asarray(rng.integers(0, 3, (8,))))


def _step(model, opt, x, y):
    loss = nn.CrossEntropyLoss()(model(x), y)
    with amp.scale_loss(loss, opt) as scaled:
        scaled.backward()
    opt.step()
    opt.zero_grad()
    return float(loss)


def test_resume_continues_identically(tmp_path):
    from apex_tpu.amp._amp_state import reset
    x, y = _data()
    path = os.path.join(tmp_path, "ckpt.pkl")

    # uninterrupted run: 6 steps
    model = _model()
    opt = FusedSGD(list(model.parameters()), lr=0.1, momentum=0.9)
    model, opt = amp.initialize(model, opt, opt_level="O2", verbosity=0)
    base = [_step(model, opt, x, y) for _ in range(6)]

    # interrupted run: 3 steps, save, fresh objects, restore, 3 more
    reset()
    model = _model()
    opt = FusedSGD(list(model.parameters()), lr=0.1, momentum=0.9)
    model, opt = amp.initialize(model, opt, opt_level="O2", verbosity=0)
    first = [_step(model, opt, x, y) for _ in range(3)]
    save_checkpoint(path, model=model.state_dict(),
                    optimizer=opt.state_dict(), amp=amp.state_dict(),
                    step=3)

    reset()
    model = _model()
    opt = FusedSGD(list(model.parameters()), lr=0.1, momentum=0.9)
    model, opt = amp.initialize(model, opt, opt_level="O2", verbosity=0)
    ckpt = load_checkpoint(path)
    assert ckpt["step"] == 3
    model.load_state_dict(ckpt["model"])
    opt.load_state_dict(ckpt["optimizer"])
    amp.load_state_dict(ckpt["amp"])
    rest = [_step(model, opt, x, y) for _ in range(3)]

    # pre-save and the first resumed step reproduce exactly; later steps
    # drift at fp16 rounding scale because O2 masters are lazily re-derived
    # from the fp16 model params after restore — the reference's documented
    # workflow has the same property (exact fp32-master resume is the
    # legacy FP16_Optimizer.state_dict feature, carried in fp16_utils)
    np.testing.assert_allclose(first + rest[:1], base[:4],
                               rtol=1e-6, atol=1e-7)
    np.testing.assert_allclose(rest[1:], base[4:], rtol=2e-4, atol=1e-5)


def test_arrays_come_back_as_host_numpy(tmp_path):
    path = os.path.join(tmp_path, "c.pkl")
    save_checkpoint(path, tree={"a": jnp.ones((3,)), "n": 7,
                                "nested": [jnp.zeros((2, 2))]})
    out = load_checkpoint(path)["tree"]
    assert isinstance(out["a"], np.ndarray)
    assert out["n"] == 7
    assert isinstance(out["nested"][0], np.ndarray)


def test_save_checkpoint_is_atomic_and_validated(tmp_path):
    """The legacy surface rides the one resilience write path: tmp+rename
    (a kill mid-write preserves the previous file), manifested content,
    and a typed error — not garbage state dicts — on corruption."""
    from apex_tpu.runtime import chaos
    from apex_tpu.utils import CheckpointCorruptError

    path = os.path.join(tmp_path, "c.pkl")
    save_checkpoint(path, epoch=1)
    with chaos.session() as c:
        c.on("ckpt.mid_write", action="kill")
        with pytest.raises(chaos.ChaosKilled):
            save_checkpoint(path, epoch=2)
    assert load_checkpoint(path)["epoch"] == 1    # previous copy intact

    blob = bytearray(open(path, "rb").read())
    blob[-5] ^= 0xFF
    open(path, "wb").write(bytes(blob))
    with pytest.raises(CheckpointCorruptError):
        load_checkpoint(path)


def test_zero_grad_set_to_none_resume_exact_fused_adam(tmp_path):
    """Regression: ``zero_grad(set_to_none=True)`` (the fused-path default
    since PR 1 — grads dropped to None between steps, not zeroed) must not
    perturb save→kill→restore: an O1 FusedAdam run under dynamic loss
    scaling resumes EXACTLY (O1 keeps fp32 params, so unlike O2 there is
    no lazily re-derived master to round-trip through fp16)."""
    from apex_tpu.amp._amp_state import reset
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.runtime import chaos
    from apex_tpu.runtime.resilience import CheckpointManager

    def make():
        reset()
        nn.manual_seed(21)
        m = nn.Sequential(nn.Linear(12, 24), nn.ReLU(), nn.Linear(24, 3))
        opt = FusedAdam(list(m.parameters()), lr=0.01)
        return amp.initialize(m, opt, opt_level="O1", verbosity=0)

    x, y = _data()

    def one_step(m, opt):
        loss = nn.CrossEntropyLoss()(m(x), y)
        with amp.scale_loss(loss, opt) as scaled:
            scaled.backward()
        opt.step()
        opt.zero_grad(set_to_none=True)
        for p in opt.param_groups[0]["params"]:
            assert p.grad is None          # set_to_none really dropped them
        return float(loss)

    model, opt = make()
    base = [one_step(model, opt) for _ in range(6)]

    mgr = CheckpointManager(str(tmp_path / "run"))
    model, opt = make()
    first = [one_step(model, opt) for _ in range(3)]
    mgr.save(3, model=model.state_dict(), optimizer=opt.state_dict(),
             amp=amp.state_dict())
    # the NEXT save dies mid-write (chaos preemption): step-3 must survive
    with chaos.session() as c:
        c.on("ckpt.mid_write", action="kill")
        with pytest.raises(chaos.ChaosKilled):
            mgr.save(4, model=model.state_dict(),
                     optimizer=opt.state_dict(), amp=amp.state_dict())

    model, opt = make()                    # process restart
    step, ckpt = mgr.restore_or_initialize()
    assert step == 3
    model.load_state_dict(ckpt["model"])
    opt.load_state_dict(ckpt["optimizer"])
    amp.load_state_dict(ckpt["amp"])
    rest = [one_step(model, opt) for _ in range(3)]
    np.testing.assert_array_equal(first + rest, base)


def _fused_step(zero=False):
    import apex_tpu.nn as nn
    from apex_tpu.nn import functional as F
    from apex_tpu.optimizers import FusedAdam
    from apex_tpu.training import make_train_step

    nn.manual_seed(1)
    m = nn.Sequential(nn.Linear(16, 64), nn.GELU(), nn.Linear(64, 8))
    opt = FusedAdam(list(m.parameters()), lr=5e-3)
    return make_train_step(m, opt, lambda o, t: F.cross_entropy(o, t),
                           half_dtype=jnp.bfloat16, loss_scale="dynamic",
                           zero_sharding=zero)


@pytest.mark.parametrize("zero", [False, True])
def test_train_state_checkpoint_exact_resume(tmp_path, zero):
    """save_train_state/restore_train_state (orbax): the fused step's
    full device state round-trips and resume losses are bit-identical —
    incl. the ZeRO case, where the sharded masters restore SHARDED (no
    gather/re-scatter)."""
    import jax
    from apex_tpu.utils import restore_train_state, save_train_state

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((64, 16)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 8, (64,)))

    s1 = _fused_step(zero)
    for _ in range(5):
        s1(x, y)
    path = str(tmp_path / "ckpt")
    save_train_state(path, s1)
    ref = [float(s1(x, y)) for _ in range(3)]

    s2 = _fused_step(zero)
    restore_train_state(path, s2)
    if zero:
        w0 = s2.state.master_params[0]
        n = len(jax.devices())
        assert w0.sharding.shard_shape(w0.shape)[0] == w0.shape[0] // n
    got = [float(s2(x, y)) for _ in range(3)]
    np.testing.assert_array_equal(got, ref)


def test_async_saver_overlaps_and_restores_exactly(tmp_path):
    """AsyncTrainStateSaver: save returns while orbax writes in the
    background; training continues on the live state, the snapshot is
    unaffected, and restore resumes bit-identically from the saved
    step."""
    from apex_tpu.utils import AsyncTrainStateSaver, restore_train_state

    rng = np.random.default_rng(1)
    x = jnp.asarray(rng.standard_normal((64, 16)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 8, (64,)))

    s1 = _fused_step(False)
    for _ in range(4):
        s1(x, y)
    path = str(tmp_path / "async_ckpt")
    with AsyncTrainStateSaver() as saver:
        saver.save(path, s1)
        post_save = [float(s1(x, y)) for _ in range(3)]  # trains while writing
        saver.wait()

    s2 = _fused_step(False)
    restore_train_state(path, s2)
    got = [float(s2(x, y)) for _ in range(3)]
    np.testing.assert_array_equal(got, post_save)


def test_async_saver_second_save_serializes(tmp_path):
    """Two saves to two paths: the second blocks on the first (one
    in-flight write), and BOTH checkpoints restore their respective
    training points bit-identically."""
    from apex_tpu.utils import AsyncTrainStateSaver, restore_train_state

    rng = np.random.default_rng(2)
    x = jnp.asarray(rng.standard_normal((32, 16)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 8, (32,)))
    s1 = _fused_step(False)
    s1(x, y)
    with AsyncTrainStateSaver() as saver:
        saver.save(str(tmp_path / "a"), s1)
        a_ref = [float(s1(x, y)) for _ in range(2)]   # advances past "a"
        saver.save(str(tmp_path / "b"), s1)           # issued mid-flight
        b_ref = [float(s1(x, y)) for _ in range(2)]   # advances past "b"
    s_a = _fused_step(False)
    restore_train_state(str(tmp_path / "a"), s_a)
    np.testing.assert_array_equal([float(s_a(x, y)) for _ in range(2)],
                                  a_ref)
    s_b = _fused_step(False)
    restore_train_state(str(tmp_path / "b"), s_b)
    np.testing.assert_array_equal([float(s_b(x, y)) for _ in range(2)],
                                  b_ref)
