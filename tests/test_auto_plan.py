"""The analytical parallelism planner (parallel/auto.py): profile
measurement from XLA cost analysis, plan enumeration, memory-feasibility
pruning with stated reasons (no silent pruning), roofline ranking on
CPU-measurable scenarios, and describe() diagnostics."""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import apex_tpu.nn as nn
from apex_tpu.nn import functional as F
from apex_tpu.optimizers import FusedAdam, FusedSGD
from apex_tpu.parallel import auto


def _build(hidden=512):
    nn.manual_seed(0)
    model = nn.Sequential(nn.Linear(64, hidden), nn.ReLU(),
                          nn.Linear(hidden, hidden), nn.ReLU(),
                          nn.Linear(hidden, 8))
    opt = FusedAdam(list(model.parameters()), lr=1e-2)
    return model, opt


def _loss(o, t):
    return F.cross_entropy(o, t)


def _batch(rng, b=64):
    x = jnp.asarray(rng.standard_normal((b, 64)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 8, (b,)))
    return x, y


@pytest.fixture(scope="module")
def profiled():
    rng = np.random.default_rng(7)
    model, opt = _build()
    batch = _batch(rng)
    prof = auto.profile_model(model, opt, _loss, batch)
    return model, opt, batch, prof


def test_chip_spec_cpu_is_shared_host():
    spec = auto.chip_spec(jax.devices())
    assert spec.name == "cpu" and spec.shared_host


def test_profile_measures_from_xla(profiled):
    _, _, _, prof = profiled
    assert prof.source == "xla"
    assert prof.flops_per_example > 0
    assert prof.act_bytes_per_example > 0
    assert prof.hbm_bytes_per_example > 0
    assert prof.n_params == sum(
        int(np.prod(s)) for s in prof.param_shapes)
    assert prof.slots_per_param == 2        # Adam: m + v
    assert prof.tp_axis is None and prof.sp_axis is None


def test_profile_slots_for_sgd():
    model, _ = _build(hidden=32)
    opt = FusedSGD(list(model.parameters()), lr=0.1)
    rng = np.random.default_rng(0)
    prof = auto.profile_model(model, opt, _loss, _batch(rng, 8))
    assert prof.slots_per_param == 1


def test_enumeration_covers_mesh_factorizations():
    plans = list(auto.enumerate_plans(8, global_batch=64))
    meshes = {(p.dp, p.sp, p.tp) for p in plans}
    assert (8, 1, 1) in meshes and (1, 1, 8) in meshes \
        and (2, 2, 2) in meshes and (1, 8, 1) in meshes
    assert (2, 1, 1) in meshes          # partial mesh (idle devices)
    assert {p.zero_stage for p in plans if p.dp == 8 and p.tp == 1
            and p.sp == 1} == {0, 1, 3}
    assert {p.accum for p in plans if (p.dp, p.sp, p.tp) == (8, 1, 1)
            and p.zero_stage == 0} == {1, 2, 4, 8}
    # ZeRO stays on dp-only meshes (the GSPMD path excludes tp/sp axes)
    assert all(p.zero_stage == 0 for p in plans if p.tp > 1 or p.sp > 1)


def test_no_silent_pruning(profiled):
    """Every enumerated plan is either ranked feasible or rejected WITH a
    reason — the two lists partition the candidate space."""
    model, opt, batch, prof = profiled
    rep = auto.plan_training(model, opt, _loss, batch, profile=prof)
    n_enumerated = len(list(auto.enumerate_plans(
        len(jax.devices()), global_batch=rep.global_batch)))
    assert len(rep.ranked) + len(rep.rejected) == n_enumerated
    assert all(isinstance(r, str) and r for _, r in rep.rejected)


def test_capability_rejections_have_reasons(profiled):
    model, opt, batch, prof = profiled
    rep = auto.plan_training(model, opt, _loss, batch, profile=prof)
    tp_reasons = [r for p, r in rep.rejected if p.tp > 1]
    sp_reasons = [r for p, r in rep.rejected if p.sp > 1 and p.tp == 1]
    assert tp_reasons and all("tp_axis" in r for r in tp_reasons)
    assert sp_reasons and all("sp_axis" in r for r in sp_reasons)


def test_batch_divisibility_rejected_with_reason():
    model, opt = _build(hidden=32)
    rng = np.random.default_rng(0)
    batch = _batch(rng, b=12)           # 12 % 8 != 0
    rep = auto.plan_training(model, opt, _loss, batch)
    bad = [r for p, r in rep.rejected if p.dp == 8]
    assert bad and all("not divisible" in r for r in bad)


def test_memory_infeasible_rejected_with_breakdown(profiled):
    """A cap below the replicated state forces memory rejections whose
    reason states the predicted need and its component breakdown."""
    model, opt, batch, prof = profiled
    cap = prof.param_bytes_fp32         # masters alone fill it
    rep = auto.plan_training(model, opt, _loss, batch, profile=prof,
                             hbm_cap_bytes=cap)
    mem_rejects = [(p, r) for p, r in rep.rejected
                   if "memory-infeasible" in r]
    assert mem_rejects
    p, r = mem_rejects[0]
    assert "MiB/device > cap" in r and "masters" in r and "acts" in r
    assert p.predicted_hbm is not None and p.predicted_hbm > cap
    # the replicated single-device plan specifically must be among them
    assert any(p.dp == 1 and p.zero_stage == 0 for p, _ in mem_rejects)
    # and ZeRO plans survive
    assert rep.best is not None and rep.best.zero_stage >= 1


def test_scenario_memory_order_replicated_vs_zero3(profiled):
    """ISSUE scenario: memory-infeasible replicated plan vs ZeRO-3 — the
    predicted order (replicated loses) matches the measured per-device
    footprint order from XLA's memory_analysis of the real programs."""
    from apex_tpu.training import make_train_step

    model, opt, batch, prof = profiled
    spec = auto.chip_spec()
    x, y = batch
    B = int(x.shape[0])
    rep_plan = auto.Plan(dp=1, n_devices=8)
    z3_plan = auto.Plan(dp=8, zero_stage=3, n_devices=8)
    pred_rep, _ = auto.predict_memory(rep_plan, prof, spec, B)
    pred_z3, _ = auto.predict_memory(z3_plan, prof, spec, B)
    assert pred_z3 < pred_rep

    def measured(plan):
        m, o = _build()
        # donate_state=True: the memory ordering under test is that of
        # the donated steady state the HBM model prices (the "auto"
        # default resolves to no-donation on this cpu backend)
        step = make_train_step(m, o, _loss, half_dtype=None,
                               loss_scale=1.0, parallel=plan,
                               donate_state=True)
        step(x, y)
        if plan.dp > 1:
            shs = step._batch_shardings((x, y))
            comp = auto.compile_uncached(
            step._jitted(shs).lower(step.state, x, y))
        else:
            from apex_tpu.runtime.step_cache import step_cache
            ent = [e for e in step_cache.entries()
                   if e["kind"] == "train_step"][-1]
            comp = auto.compile_uncached(
            ent["fn"].lower(*ent["example"]))
        return auto.measured_step_memory(comp)

    meas_rep, meas_z3 = measured(rep_plan), measured(z3_plan)
    assert meas_z3 < meas_rep
    # a cap between them rejects exactly the replicated plan
    cap = (meas_rep + meas_z3) / 2
    assert auto.predict_memory(rep_plan, prof, spec, B)[0] > cap * 0.85
    assert auto.predict_memory(z3_plan, prof, spec, B)[0] < cap * 1.15


def test_scenario_dp1_vs_dp8_predicted_matches_measured(profiled):
    """On the shared-host CPU mesh, spreading a fixed global batch over
    8 virtual devices buys no compute and adds collectives: the cost
    model predicts dp1 faster, and measurement agrees (margin ~2x)."""
    model, opt, batch, prof = profiled
    spec = auto.chip_spec()
    B = int(batch[0].shape[0])
    p1 = auto.Plan(dp=1, n_devices=8)
    p8 = auto.Plan(dp=8, zero_stage=1, n_devices=8)
    pred1, _, _ = auto.predict_time(p1, prof, spec, B)
    pred8, _, _ = auto.predict_time(p8, prof, spec, B)
    assert pred1 < pred8

    def measure(plan):
        m, o = _build()
        return auto.measure_plan(plan, m, o, _loss, batch, steps=5,
                                 half_dtype=None, loss_scale=1.0)

    assert measure(p1) < measure(p8)


def test_scenario_accum_overhead_predicted_matches_measured(profiled):
    """K=8 microbatching at the same global batch costs scan overhead and
    K x weight re-reads: predicted slower than K=1, measured slower."""
    model, opt, batch, prof = profiled
    spec = auto.chip_spec()
    B = int(batch[0].shape[0])
    k1 = auto.Plan(dp=1, accum=1, n_devices=8)
    k8 = auto.Plan(dp=1, accum=8, n_devices=8)
    pred1, _, _ = auto.predict_time(k1, prof, spec, B)
    pred8, _, _ = auto.predict_time(k8, prof, spec, B)
    assert pred1 < pred8

    def measure(plan):
        m, o = _build()
        return auto.measure_plan(plan, m, o, _loss, batch, steps=5,
                                 half_dtype=None, loss_scale=1.0)

    assert measure(k1) < measure(k8)


def test_tpu_spec_inverts_dp_preference(profiled):
    """Same model, same batch, priced for a real chip (per-device peaks,
    ICI instead of host memcpys): dp=8 beats dp=1 — the shared-host
    inversion is a property of the CPU test mesh, not of the model.
    (At the test's tiny batch even a v5e prefers dp=1: the grad
    all-reduce costs more than the compute it spreads — the batch-size
    plateau inversion the round-5 benches measured.)"""
    _, _, batch, prof = profiled
    spec = auto.CHIPS["v5e"]
    B = 8192
    pred1, _, _ = auto.predict_time(auto.Plan(dp=1, n_devices=8), prof,
                                    spec, B)
    pred8, _, _ = auto.predict_time(
        auto.Plan(dp=8, zero_stage=1, n_devices=8), prof, spec, B)
    assert pred8 < pred1


def test_chunked_loss_lever_priced(profiled):
    """With a vocab head, chunked_loss=None enumerates both settings and
    the chunked twin predicts strictly less activation memory."""
    from apex_tpu.models import GptModel

    nn.manual_seed(1)
    model = GptModel(vocab_size=512, hidden=32, layers=2, heads=4,
                     max_positions=32, dropout=0.0, attn_dropout=0.0)
    opt = FusedAdam(list(model.parameters()), lr=1e-3)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(0, 512, (8, 32)))
    tgt = jnp.asarray(np.roll(np.asarray(ids), -1, axis=1))

    def lm_loss(logits, tgt):
        return F.cross_entropy(logits.reshape((-1, 512)),
                               tgt.reshape((-1,)))

    rep = auto.plan_training(model, opt, lm_loss, (ids, tgt),
                             chunked_loss=None)
    by_key = {}
    for p in rep.ranked:
        # group by everything except the chunked flag (element 5):
        # v3 remat/offload variants must pair with their own twin
        by_key.setdefault(p.key()[:5] + p.key()[6:], {})[p.chunked_loss] = p
    pairs = [v for v in by_key.values() if len(v) == 2]
    assert pairs, "chunked/unchunked twins must both be priced"
    assert all(v[True].predicted_hbm < v[False].predicted_hbm
               for v in pairs)
    chunked_best = [p for p in rep.ranked if p.chunked_loss][0]
    assert "chunked" in chunked_best.describe()


def test_plan_step_kwargs_mapping():
    devs = jax.devices()
    z = auto.Plan(dp=4, zero_stage=1, accum=2, n_devices=8)
    kw = z.step_kwargs(devs)
    assert kw["zero_sharding"] and kw["zero_stage"] == 1
    assert kw["accum_steps"] == 2
    assert tuple(kw["zero_mesh"].shape.values()) == (4,)
    t = auto.Plan(dp=2, tp=4, tp_axis="tp", n_devices=8)
    kw = t.step_kwargs()
    assert kw["axis_name"] == "data" and kw["tp_axis"] == "tp"
    assert "zero_sharding" not in kw


def test_describe_contents(profiled):
    model, opt, batch, prof = profiled
    rep = auto.plan_training(model, opt, _loss, batch, profile=prof,
                             hbm_cap_bytes=prof.param_bytes_fp32 * 4)
    text = rep.describe()
    assert "chosen:" in text and "rejected" in text
    assert "memory-infeasible" in text        # reasons are printed
    best = rep.best.describe()
    assert "predicted" in best and "ms/step" in best
    assert "knobs:" in best
    z = [p for p in rep.ranked if p.dp > 1 and p.zero_stage >= 1]
    if z:
        d = z[0].describe()
        assert "reduce-scatter" in d and "all-gather" in d


def test_static_plan_key():
    from apex_tpu.runtime import step_cache
    assert step_cache.static_plan_key(None) is None
    p = auto.Plan(dp=4, zero_stage=3, accum=2, n_devices=8)
    assert step_cache.static_plan_key(p) == (4, 1, 1, 3, 2, False)
    # prediction fields do not change the structural identity
    q = dataclasses.replace(p, predicted_ms=1.0, predicted_hbm=7)
    assert step_cache.static_plan_key(q) == step_cache.static_plan_key(p)
