"""apex_tpu.serve: the paged KV pool's alloc/free/leak invariants under
random admit/finish/preempt churn, packing determinism on a seeded
Poisson trace, the recompile-free-decode property pinned through
``step_cache.stats()``, prefill-chunking's latency interleave, and
bitwise greedy parity against ``inference.DecodeSession``."""
import numpy as np
import pytest

import jax.numpy as jnp

from apex_tpu import nn
from apex_tpu.inference.rolling import window_retired_blocks
from apex_tpu.inference.session import DecodeSession, PagedSession
from apex_tpu.models.gpt import GptModel
from apex_tpu.observe import registry as obs
from apex_tpu.runtime import step_cache as sc
from apex_tpu.serve import (BlockPool, NULL_BLOCK, Request, Scheduler,
                            ServeEngine, blocks_for, bucket)
from apex_tpu.serve.scheduler import DECODE

pytestmark = pytest.mark.serve


@pytest.fixture(scope="module")
def model():
    nn.manual_seed(6)
    m = GptModel(vocab_size=73, hidden=32, layers=2, heads=4,
                 max_positions=96, dropout=0.0, attn_dropout=0.0)
    m.eval()
    return m


# ---------------------------------------------------------------------------
# host-side units: buckets, pool accounting
# ---------------------------------------------------------------------------


def test_bucket_and_blocks_for():
    assert [bucket(n) for n in (1, 2, 3, 4, 5, 9)] == [1, 2, 4, 4, 8, 16]
    assert bucket(9, cap=8) == 8
    assert blocks_for(0, 4) == 0
    assert blocks_for(1, 4) == 1
    assert blocks_for(4, 4) == 1
    assert blocks_for(5, 4) == 2


def test_pool_alloc_is_all_or_nothing():
    pool = BlockPool(num_blocks=8, block_size=4)
    ids = pool.alloc(7)
    assert ids is not None and len(ids) == 7
    assert NULL_BLOCK not in ids          # block 0 is never handed out
    assert pool.alloc(1) is None
    assert pool.in_use == 7 and pool.free_count == 0
    pool.free(ids[:3])
    # shortfall refuses whole: nothing taken, accounting unchanged
    assert pool.alloc(4) is None
    assert pool.free_count == 3
    got = pool.alloc(3)
    assert sorted(got) == sorted(ids[:3])
    pool.free(got)
    pool.free(ids[3:])
    pool.check_no_leaks()


def test_pool_double_free_and_foreign_free_raise():
    pool = BlockPool(num_blocks=8, block_size=4)
    ids = pool.alloc(2)
    pool.free(ids)
    with pytest.raises(ValueError):
        pool.free(ids)                    # double free
    with pytest.raises(ValueError):
        pool.free([NULL_BLOCK])           # the null block is not held


# ---------------------------------------------------------------------------
# churn: 500 requests of random admit/finish/preempt, zero leaked blocks
# ---------------------------------------------------------------------------


def _sim_tok(position):
    """Deterministic stand-in for a generated token (host-only sims
    never dispatch the model)."""
    return (position * 7 + 3) % 70 + 1


def _sim_prefill_tick(sched):
    """Advance the oldest prefilling session one chunk, mirroring
    ``ServeEngine._prefill_chunk`` at the scheduler level (including
    the chain commit after the position advance)."""
    s = sched.next_prefill()
    if s is None:
        return
    s.position += min(sched.prefill_chunk, s.prefill_remaining)
    sched.note_commit(s)
    if s.prefill_remaining > 0:
        return
    s.state = DECODE
    if s.emit_on_prefill:
        tok = _sim_tok(s.position)
        s.out.append(tok)
        s.pending_tok = tok
        if s.finished():
            sched.finish(s)


def _sim_decode_tick(sched):
    """One packed decode tick, mirroring ``_ensure_decode_blocks`` +
    ``_decode_tick``: grow-or-preempt, then advance every survivor."""
    preempted = []
    for s in list(sched.decode_sessions()):
        if s.state != DECODE:
            continue                      # preempted below us
        while not sched.grow(s, s.position + 1):
            victim = sched.preempt_for(s)
            preempted.append(victim.rid)
            if victim is s:
                break
    live = sched.decode_sessions()
    packed = sched.pack_decode(live) if live else None
    for s in list(live):
        s.position += 1
        tok = _sim_tok(s.position)
        s.out.append(tok)
        s.pending_tok = tok
        sched.note_commit(s)
        if s.finished():
            sched.finish(s)
    return preempted, packed


def _pool_books_balance(sched):
    """Refcount bookkeeping: every table occurrence of a block is one
    live reference (shared prefix blocks appear in SEVERAL tables), the
    held set matches the pool's, and held + free + cached covers the
    whole pool."""
    from collections import Counter
    occ = Counter(b for s in sched.sessions
                  for b in s.table + s.draft_table if b != NULL_BLOCK)
    for b, n in occ.items():
        assert sched.pool.refcount(b) == n, \
            f"block {b}: {n} table occurrences, refcount " \
            f"{sched.pool.refcount(b)}"
    assert len(occ) == sched.pool.in_use
    assert sched.pool.in_use + sched.pool.free_count == \
        sched.pool.capacity


def test_scheduler_churn_500_requests_zero_leaks():
    """500 requests of random admit/finish/preempt churn WITH the
    prefix cache live: half the prompts repeat a handful of shared
    templates, so admissions adopt shared blocks, full-chain hits fork
    copy-on-write, finishes retire committed blocks to the cached tier,
    and allocation pressure evicts them — and the books still balance
    to zero leaks."""
    rng = np.random.default_rng(0)
    pool = BlockPool(num_blocks=48, block_size=4)
    sched = Scheduler(pool, max_batch=8, prefill_chunk=8,
                      max_prefill_backlog=64, max_positions=96)
    n = 500
    templates = [[int(t) for t in rng.integers(1, 70, ln)]
                 for ln in (4, 8, 8, 11)]
    reqs = []
    for i in range(n):
        if rng.random() < 0.5:            # shared-prefix traffic
            base = templates[int(rng.integers(len(templates)))]
            ext = [] if rng.random() < 0.3 \
                else [int(t) for t in rng.integers(1, 70,
                                                   int(rng.integers(1, 4)))]
            prompt = base + ext
        else:
            prompt = [int(t) for t in
                      rng.integers(1, 70, int(rng.integers(1, 12)))]
        reqs.append(Request(f"r{i}", prompt, int(rng.integers(1, 9))))
    done_before = set()
    shared_adoptions = cow = 0
    i = tick = 0
    while i < n or sched.has_work():
        tick += 1
        assert tick < 100_000, "churn sim failed to drain"
        for _ in range(int(rng.integers(0, 3))):
            if i < n:
                sched.submit(reqs[i])
                i += 1
        for s in sched.admit():
            shared_adoptions += s.committed_blocks
            cow += sched.complete_cow(s)  # host-only: no copy dispatch
        _sim_prefill_tick(sched)
        _sim_decode_tick(sched)
        # extra adversarial churn: evict someone at random
        if sched.sessions and rng.random() < 0.05:
            sched.preempt_for(sched.sessions[0])
        if tick % 50 == 0:
            _pool_books_balance(sched)
        for s in list(sched.sessions):
            assert s.rid not in done_before
    # the trace is not degenerate: blocks were shared, forked, evicted
    assert shared_adoptions > 50
    assert cow > 0
    assert pool.cache_evictions > 0
    pool.check_no_leaks()
    assert pool.in_use == 0
    assert pool.free_exact + pool.cached_count == pool.capacity


# ---------------------------------------------------------------------------
# packing determinism: a seeded Poisson trace replays to the byte
# ---------------------------------------------------------------------------


def _drive_trace(seed, n=60):
    """Host-only serve loop over a seeded Poisson arrival trace,
    recording every scheduling decision (admissions, preemptions, and
    the packed decode operands — the arrays that become program
    operands)."""
    rng = np.random.default_rng(seed)
    pool = BlockPool(num_blocks=32, block_size=4)
    sched = Scheduler(pool, max_batch=4, prefill_chunk=8,
                      max_prefill_backlog=32, max_positions=96)
    lens = rng.integers(1, 10, n)
    news = rng.integers(1, 6, n)
    prompts = [[int(t) for t in rng.integers(1, 70, int(l))] for l in lens]
    arrive = np.cumsum(rng.poisson(1.0, n))
    decisions = []
    i = tick = 0
    while i < n or sched.has_work():
        assert tick < 50_000
        while i < n and arrive[i] <= tick:
            sched.submit(Request(f"r{i}", prompts[i], int(news[i])))
            i += 1
        admitted = sched.admit()
        if admitted:
            decisions.append(("admit", tick, tuple(s.rid for s in admitted)))
        _sim_prefill_tick(sched)
        preempted, packed = _sim_decode_tick(sched)
        if preempted:
            decisions.append(("preempt", tick, tuple(preempted)))
        if packed is not None:
            b, nb, toks, poss, tables = packed
            decisions.append(("pack", tick, b, nb, tuple(toks),
                              tuple(poss), tuple(map(tuple, tables))))
        tick += 1
    pool.check_no_leaks()
    return decisions


def test_packing_determinism_under_poisson_trace():
    first = _drive_trace(seed=7)
    second = _drive_trace(seed=7)
    assert first == second
    # the trace is not degenerate: it packed and bucketed for real
    packs = [d for d in first if d[0] == "pack"]
    assert packs and {d[2] for d in packs} >= {1, 2}
    assert _drive_trace(seed=8) != first


# ---------------------------------------------------------------------------
# engine: recompile-free decode, prefill interleave, parity, preemption
# ---------------------------------------------------------------------------


def test_decode_recompile_free_after_warmup(model):
    sc.reset_stats()
    sc.clear()
    eng = ServeEngine(model, num_blocks=64, block_size=8, max_batch=4,
                      prefill_chunk=4)
    eng.run([Request(f"a{i}", [2 + i, 5, 7, 11], 6) for i in range(8)])
    warm = sc.kind_stats("decode_step")
    assert warm["compiles"] >= 1
    # bucket bound: occupancy buckets {1,2,4} x one table bucket
    assert warm["compiles"] <= 6
    # same shape profile again: every decode dispatch re-hits the cache
    eng.run([Request(f"b{i}", [3 + i, 9, 4, 2], 6) for i in range(8)])
    again = sc.kind_stats("decode_step")
    assert again["compiles"] == warm["compiles"]
    assert again["dispatches"] > warm["dispatches"]
    assert again["cache_hits"] > warm["cache_hits"]
    eng.block_pool.check_no_leaks()


def test_prefill_chunking_interleaves_decode(model):
    """A 32-token prompt prefilling 2 tokens/tick must not stall a
    short request's decode: the short request keeps emitting one token
    per tick and finishes long before the long prompt's first token —
    the latency bound chunked prefill exists to provide."""
    obs.get_registry().clear_events()
    eng = ServeEngine(model, num_blocks=64, block_size=8, max_batch=4,
                      prefill_chunk=2, max_prefill_backlog=64)
    short = Request("short", [5, 9], 6)
    long_ = Request("long", list(range(1, 33)), 4)
    out = eng.run([short, long_], arrivals=[0, 1])
    assert len(out["short"]) == 6 and len(out["long"]) == 4
    ticks = {(e["rid"], e["phase"]): e["tick"]
             for e in obs.events("serve.request")}
    # one decode token per tick from the first token on, no stall:
    # first_token's tick also decodes (prefill completes, then the
    # decode pass runs in the same tick), so 6 tokens span 4 ticks
    assert ticks[("short", "done")] - ticks[("short", "first_token")] == 4
    assert ticks[("short", "done")] < ticks[("long", "first_token")]
    eng.block_pool.check_no_leaks()


def test_engine_greedy_parity_vs_decode_session(model):
    prompts = [[5, 9, 11, 3], [7, 2], [1, 2, 3, 4, 5, 6, 7, 8, 9]]
    max_new = 6
    base = {}
    for i, p in enumerate(prompts):
        s = DecodeSession(model, batch=1)
        s.append(jnp.asarray([p], jnp.int32))
        base[f"r{i}"] = [int(t) for t in np.asarray(s.generate(max_new))[0]]
    eng = ServeEngine(model, num_blocks=64, block_size=8, max_batch=4,
                      prefill_chunk=4)
    out = eng.run([Request(f"r{i}", p, max_new)
                   for i, p in enumerate(prompts)])
    assert out == base                    # bitwise greedy parity
    eng.block_pool.check_no_leaks()


def test_int8_pool_parity(model):
    s8 = DecodeSession(model, batch=1, cache_dtype="int8")
    s8.append(jnp.asarray([[5, 9, 11, 3]], jnp.int32))
    base = [int(t) for t in np.asarray(s8.generate(5))[0]]
    eng = ServeEngine(model, num_blocks=64, block_size=8, max_batch=4,
                      prefill_chunk=4, cache_dtype="int8")
    out = eng.run([Request("a", [5, 9, 11, 3], 5),
                   Request("b", [7, 2], 5)])
    assert out["a"] == base
    eng.block_pool.check_no_leaks()


def test_preemption_recompute_parity_and_no_leaks(model):
    """A pool too small for the live set forces preemption; every
    request still finishes, recompute reproduces the exact greedy
    continuation, and the drained pool holds zero blocks."""
    obs.get_registry().reset()
    eng = ServeEngine(model, num_blocks=9, block_size=4, max_batch=4,
                      prefill_chunk=4)
    out = eng.run([Request(f"r{i}", [3 + i, 5, 7], 8) for i in range(6)])
    assert sorted(out) == [f"r{i}" for i in range(6)]
    assert all(len(v) == 8 for v in out.values())
    assert obs.counter("serve.preemptions").value > 0
    s = DecodeSession(model, batch=1)
    s.append(jnp.asarray([[3, 5, 7]], jnp.int32))
    assert out["r0"] == [int(t) for t in np.asarray(s.generate(8))[0]]
    eng.block_pool.check_no_leaks()


def test_paged_session_multi_turn_parity(model):
    ds = DecodeSession(model, batch=1)
    ds.append(jnp.asarray([[5, 9, 11, 3]], jnp.int32))
    t1 = np.asarray(ds.generate(5))
    ds.append(jnp.asarray([[8, 8, 2]], jnp.int32))
    t2 = np.asarray(ds.generate(4))
    eng = ServeEngine(model, num_blocks=64, block_size=8, max_batch=4,
                      prefill_chunk=4)
    with PagedSession(eng) as ps:
        ps.append([5, 9, 11, 3])
        assert (np.asarray(ps.generate(5)) == t1).all()
        ps.append([8, 8, 2])
        assert (np.asarray(ps.generate(4)) == t2).all()
    eng.block_pool.check_no_leaks()


# ---------------------------------------------------------------------------
# sliding window, admission validation, metrics schema
# ---------------------------------------------------------------------------


def test_window_retired_blocks_closed_form():
    assert window_retired_blocks(0, 8, 4) == 0
    assert window_retired_blocks(8, 8, 4) == 0
    assert window_retired_blocks(12, 8, 4) == 1
    assert window_retired_blocks(20, 8, 4) == 3
    assert window_retired_blocks(20, None, 4) == 0


def test_windowed_engine_retires_blocks(model):
    eng = ServeEngine(model, num_blocks=32, block_size=4, max_batch=2,
                      prefill_chunk=4, window=8)
    out = eng.run([Request("w", list(range(1, 20)), 10)])
    assert len(out["w"]) == 10
    eng.block_pool.check_no_leaks()


def test_submit_rejects_never_fit_requests(model):
    eng = ServeEngine(model, num_blocks=4, block_size=4, max_batch=2,
                      prefill_chunk=4)
    with pytest.raises(ValueError):     # exceeds the whole pool
        eng.submit(Request("big", list(range(1, 30)), 8))
    with pytest.raises(ValueError):     # exceeds model positions
        eng.submit(Request("long", [1] * 90, 20))
    assert not eng.scheduler.has_work()


def test_metrics_snapshot_schema(model):
    eng = ServeEngine(model, num_blocks=64, block_size=8, max_batch=2,
                      prefill_chunk=4)
    eng.run([Request("m", [5, 9], 3)])
    m = eng.metrics()
    assert m["pool_occupancy"] == 0.0 and m["queue_depth"] == 0
    for kind in ("decode", "prefill"):
        assert set(m[kind]) == {"compiles", "cache_hits", "dispatches"}
        assert m[kind]["dispatches"] >= 1


def test_close_returns_all_live_blocks(model):
    """close() mid-run returns every live session's blocks — target
    AND draft tables — so check_no_leaks holds even with sessions
    still decoding; the context-manager form does the same."""
    from apex_tpu.inference import make_self_draft
    eng = ServeEngine(model, num_blocks=48, block_size=8, max_batch=4,
                      prefill_chunk=4, draft=make_self_draft(model))
    for i, p in enumerate([[5, 9, 11, 3], [7, 2], [12, 30, 4]]):
        eng.submit(Request(f"c{i}", p, 12))
    for _ in range(4):                    # mid-flight: live sessions
        eng.step()
    assert eng.scheduler.sessions         # something is decoding
    assert eng.block_pool.in_use > 0
    eng.close()                           # runs check_no_leaks itself
    assert eng.block_pool.in_use == 0
    assert not eng.scheduler.has_work()

    with ServeEngine(model, num_blocks=32, block_size=8, max_batch=2,
                     prefill_chunk=4) as eng2:
        eng2.submit(Request("cm", [3, 4, 5], 8))
        eng2.step()
        eng2.step()
        assert eng2.block_pool.in_use > 0
    assert eng2.block_pool.in_use == 0
