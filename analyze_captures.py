"""Summarize on-chip capture results (measurements.jsonl +
diagnose_gpt1024.jsonl) into a markdown report.

Run after `auto_capture.sh` has drained (or partially drained):

    python analyze_captures.py            # prints the report
    python analyze_captures.py --update   # writes it into BENCH_HISTORY.md
                                          # (REPLACES this round's block —
                                          # idempotent, one summary per round)

What it computes:
- per-metric best row (latest non-null value), with the previous
  round's reference number and the delta where one exists;
- the kernel A/B table grouped by kernel, flagging rows <1.0x and the
  S=512 dispatch-threshold verdict (should APEX_TPU_FLASH_MIN_SK move?);
- decode ladder: plain -> int8 -> int8+kv-int8 -> speculative ratios;
- the GPT-1024 diagnosis outcomes (which probe attributed the hang).
"""
import argparse
import json
import os

HERE = os.path.dirname(os.path.abspath(__file__))

# round-3 reference captures (BENCH_HISTORY.md) for deltas
R3 = {
    "resnet50_imagenet_images_per_sec_per_chip_ampO2": 2310.8,
    "bert_base_mlm_seq128_sequences_per_sec_per_chip_ampO2": 866.2,
    "gpt2_small_causal_lm_seq128_sequences_per_sec_per_chip_ampO2": 705.4,
    "gpt2_small_causal_lm_seq1024_sequences_per_sec_per_chip_ampO2": 75.8,
}

# round-4 final captures (BENCH_HISTORY round-4 report) — the deltas the
# round-5 analyzer should report against.  Swap R3 -> R4 below when the
# next round starts collecting.
R4 = {
    "resnet50_imagenet_images_per_sec_per_chip_ampO2": 2368.6,
    "bert_base_mlm_seq128_sequences_per_sec_per_chip_ampO2": 1177.9,
    "gpt2_small_causal_lm_seq128_sequences_per_sec_per_chip_ampO2": 921.2,
    "gpt2_small_causal_lm_seq1024_sequences_per_sec_per_chip_ampO2": 73.8,
    "llama_125m_causal_lm_seq128_sequences_per_sec_per_chip_ampO2": 1359.5,
    "llama_125m_causal_lm_seq2048_sequences_per_sec_per_chip_ampO2": 41.7,
    "seq2seq_base_seq128_sequences_per_sec_per_chip_ampO2": 1947.9,
    "dcgan64_multi_loss_images_per_sec_per_chip_ampO1": 29178.2,
    "llama_125m_greedy_decode_tokens_per_sec_per_chip": 12620.6,
    "gpt2_small_greedy_decode_tokens_per_sec_per_chip": 5779.2,
    "pallas_kernel_speedup_vs_xla": 1.093,
}


def _load_jsonl(path):
    rows = []
    if not os.path.exists(path):
        return rows
    with open(path) as f:
        for line in f:
            line = line.strip()
            if not line:
                continue
            try:
                rows.append(json.loads(line))
            except json.JSONDecodeError:
                continue
    return rows


def report():
    out = ["# On-chip capture summary", ""]
    rows = _load_jsonl(os.path.join(HERE, "measurements.jsonl"))
    if not rows:
        return "\n".join(out + ["(measurements.jsonl empty or missing)"])

    # ---- headline metrics: last non-null value per metric
    best = {}
    for r in rows:
        if r.get("value") is not None and r.get("metric"):
            best[r["metric"]] = r
    if best:
        out += ["## Headline metrics", "",
                "| metric | value | unit | vs r4 | mfu |", "|---|---|---|---|---|"]
        for m, r in sorted(best.items()):
            if m in ("pallas_kernel_ab", "mlp_fused_vs_unfused_ab"):
                continue
            r3 = R4.get(m)
            delta = (f"{(r['value'] / r3 - 1) * 100:+.1f}%"
                     if r3 else "—")
            out.append(f"| {m} | {r['value']} | {r.get('unit', '')} "
                       f"| {delta} | {r.get('mfu', '—')} |")
        out.append("")

    # ---- kernel A/B rows
    ab = [r for r in rows if r.get("metric") == "pallas_kernel_ab"
          and r.get("speedup")]
    if ab:
        out += ["## Kernel A/B (pallas vs xla, fwd+bwd)", "",
                "| kernel | shape | pallas ms | xla ms | speedup |",
                "|---|---|---|---|---|"]
        losses = []
        for r in ab:
            flag = "" if r["speedup"] >= 1.0 else "  **<1.0x**"
            out.append(f"| {r.get('kernel')} | {r.get('shape')} "
                       f"| {r.get('pallas_ms')} | {r.get('xla_ms')} "
                       f"| {r['speedup']}{flag} |")
            if r["speedup"] < 1.0:
                losses.append(r)
        out.append("")
        s512 = [r for r in ab if "S512" in str(r.get("shape", ""))]
        if s512:
            v = s512[-1]["speedup"]
            out.append(
                f"S=512 threshold row: {v}x — "
                + ("flash wins at 512; consider LOWERING "
                   "APEX_TPU_FLASH_MIN_SK below 512." if v > 1.05 else
                   "flash loses at 512; consider RAISING "
                   "APEX_TPU_FLASH_MIN_SK." if v < 0.95 else
                   "threshold is placed about right."))
            out.append("")
        if losses:
            out.append(f"{len(losses)} row(s) below 1.0x — candidates for "
                       f"dispatch rerouting or retirement notes.")
            out.append("")

    # ---- decode ladder
    dec = {}
    for r in rows:
        m = r.get("metric", "")
        if "decode" in m and r.get("value") is not None:
            dec[m] = r["value"]
    if dec:
        out += ["## Decode ladder (tokens/sec/chip)", ""]
        plain = dec.get("gpt2_small_greedy_decode_tokens_per_sec_per_chip")
        for m, v in sorted(dec.items()):
            rel = f"  ({v / plain:.2f}x plain)" if plain and v else ""
            out.append(f"- {m}: {v}{rel}")
        out.append("")

    # ---- round-4 A/B verdicts (channels-last conv layout; rolling
    # window cache) — explicit ratio lines when both arms landed
    nhwc = best.get("resnet50_imagenet_nhwc_images_per_sec_per_chip_ampO2")
    # batch-matched NCHW arm (bench retries smaller batches on failure,
    # so the two arms can land at different batch sizes)
    nchw = None
    if nhwc:
        for r in rows:
            if (r.get("metric") ==
                    "resnet50_imagenet_images_per_sec_per_chip_ampO2"
                    and r.get("value") is not None
                    and r.get("batch") == nhwc.get("batch")):
                nchw = r
    if nchw and nhwc and nchw.get("value"):
        r = nhwc["value"] / nchw["value"]
        out += ["## Channels-last A/B", "",
                f"NHWC {nhwc['value']} vs NCHW {nchw['value']} img/s "
                f"(batch {nhwc.get('batch')}) = {r:.3f}x — "
                + ("adopt NHWC as the headline path (and re-profile)."
                   if r > 1.03 else
                   "layout change does not pay on this model/compiler; "
                   "keep NCHW headline, document the finding."
                   if r < 0.97 else "within noise; keep NCHW default."),
                ""]
    # every windowed arm (any window size / quantization flavor),
    # each against its config-matched full-cache sibling (same
    # int8/kv-int8 flavor, batch, and prompt)
    win_rows = [r for m, r in sorted(best.items())
                if "_decode" in m and "_window" in m
                and m.startswith("llama_125m_greedy_decode")]
    ab_lines = []
    for win in win_rows:
        sibling = win["metric"].split("_window")[0] \
            + "_tokens_per_sec_per_chip"
        full = None
        for r in rows:
            if (r.get("metric") == sibling
                    and r.get("value") is not None
                    and r.get("batch") == win.get("batch")
                    and r.get("prompt_len") == win.get("prompt_len")):
                full = r
        if full and full.get("value"):
            ab_lines.append(
                f"- window={win.get('window')} arm {win['value']} vs "
                f"full-cache {full['value']} tok/s (batch "
                f"{win.get('batch')}, prompt {win.get('prompt_len')}) "
                f"= {win['value'] / full['value']:.2f}x")
    if ab_lines:
        out += ["## Rolling-cache decode A/B", "", *ab_lines,
                "(expected >1 when the KV term dominates; see the "
                "batch/prompt sizing note in auto_capture.sh)", ""]

    # ---- GPT-1024 diagnosis
    diag = _load_jsonl(os.path.join(HERE, "diagnose_gpt1024.jsonl"))
    if diag:
        out += ["## GPT seq-1024 hang diagnosis", ""]
        for r in diag:
            out.append(f"- {r.get('probe')}: {r.get('result')}")
        out.append("")
    return "\n".join(out)


ROUND = 5


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--update", action="store_true",
                    help="write the report into BENCH_HISTORY.md, "
                         "replacing this round's block if present "
                         "(idempotent — one summary per round)")
    ap.add_argument("--round", type=int, default=ROUND)
    args = ap.parse_args()
    text = report()
    print(text)
    if args.update:
        path = os.path.join(HERE, "BENCH_HISTORY.md")
        begin = f"<!-- capture-summary:r{args.round} begin -->"
        end = f"<!-- capture-summary:r{args.round} end -->"
        block = (f"{begin}\n# On-chip capture summary (round "
                 f"{args.round})\n\n" + text.split("\n", 2)[2] + f"\n{end}\n")
        cur = open(path).read() if os.path.exists(path) else ""
        if begin in cur and end in cur:
            head, rest = cur.split(begin, 1)
            _, tail = rest.split(end, 1)
            cur = head + block + tail.lstrip("\n")
            action = "replaced"
        else:
            cur = cur.rstrip("\n") + "\n\n" + block
            action = "appended"
        open(path, "w").write(cur)
        print(f"\n({action} round-{args.round} block in BENCH_HISTORY.md)")
