#!/bin/bash
# Poll the axon tunnel; exit 0 the moment a 64x64 matmul fetch succeeds.
# One probe every ~5 min (each failed probe holds a client for <=75s).
while true; do
  if timeout 75 python -c "
import jax, jax.numpy as jnp
x = jnp.ones((64, 64)); print('probe ok:', float(jnp.sum(x @ x)))
" 2>/dev/null; then
    date -u +"tunnel healthy at %H:%M:%S UTC"
    exit 0
  fi
  date -u +"probe failed at %H:%M:%S UTC; sleeping 240s"
  sleep 240
done
