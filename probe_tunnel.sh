#!/bin/bash
# Tunnel health for the axon TPU: a 64x64 matmul value fetch (the
# round-2/3 wedge signature is this fetch hanging).  THE one probe
# implementation — bench orchestration scripts call this rather than
# carrying their own copies.
#
#   probe_tunnel.sh          probe once; exit 0 healthy / 1 wedged
#   probe_tunnel.sh -w [N]   poll every ~4 min until healthy (exit 0)
#                            or N attempts exhausted (exit 1; default
#                            unlimited)
probe_once() {
  timeout 75 python -c "
import jax, jax.numpy as jnp
x = jnp.ones((64, 64)); print('probe ok:', float(jnp.sum(x @ x)))
" 2>/dev/null
}

if [ "${1:-}" != "-w" ]; then
  probe_once
  exit $?
fi

max="${2:-0}"; n=0
while true; do
  if probe_once; then
    date -u +"tunnel healthy at %H:%M:%S UTC"
    exit 0
  fi
  n=$((n + 1))
  [ "$max" -gt 0 ] && [ "$n" -ge "$max" ] && exit 1
  date -u +"probe failed at %H:%M:%S UTC; sleeping 240s"
  sleep 240
done
