#!/bin/bash
# Round-5 extras beyond measure_rest.sh — run manually after it drains
# (separate file because a RUNNING measure_rest.sh must not be edited:
# bash reads scripts incrementally).
set -u
LOG="${MEASURE_LOG:-measurements.jsonl}"
cd "$(dirname "$0")"
bash probe_tunnel.sh -w || exit 1
run() {
  echo "=== $* ===" >&2
  timeout 1700 python bench.py "$@" 2>>"$LOG.err" | tee -a "$LOG"
}
run 32 --bert --seq-len 512 --no-kernels   # gathered head at long seq
run --gpt --gpt-size medium --no-kernels   # 355M family point
run --bert --attn-dropout 0.1 --no-kernels # historical recipe re-check
run --gpt --attn-dropout 0.1 --no-kernels
run 16 --llama --seq-len 1024 --no-kernels
run 8 --llama --seq-len 2048 --no-kernels
echo "extras done" >&2
