#!/bin/bash
# One-shot measurement matrix for a healthy TPU tunnel: each config runs
# as its own bench.py process (own watchdog, own diagnostic JSON line on
# failure).  Appends raw JSON lines to MEASURE_LOG (default
# measurements.jsonl) for transfer into BENCH_HISTORY.md.
set -u
LOG="${MEASURE_LOG:-measurements.jsonl}"
cd "$(dirname "$0")"

probe() {
  timeout 75 python -c "
import jax, jax.numpy as jnp
x = jnp.ones((64, 64)); print('probe ok:', float(jnp.sum(x @ x)))
" 2>/dev/null
}

if ! probe; then
  echo "tunnel not healthy; aborting" >&2
  exit 1
fi

run() {
  echo "=== $* ===" >&2
  timeout 700 python bench.py "$@" 2>>"$LOG.err" | tee -a "$LOG"
}

run                                   # resnet50 headline + kernels
run --nhwc --no-kernels               # channels-last A/B arm
run --bert
run --gpt
run --llama
run --vit
run 16 --gpt --seq-len 512            # b16: the measured MFU peak (r5)
run 16 --llama --seq-len 512
run 16 --gpt --seq-len 1024
run 8 --gpt --seq-len 2048 --remat
run --gpt --loss-mode fused --no-kernels    # vocab-chain A/B anchor arm
run --kernels-timing --budget-s 1600  # variance-controlled (5 reps)
run --gpt-decode
run --gpt-decode --int8
run --gpt-decode --int8 --kv-int8
run --llama-decode
run 16 --llama-decode --seq-len 512
run 16 --llama-decode --seq-len 512 --window 128
run --spec-decode
run --seq2seq
run --dcgan
run --profile                         # resnet per-op time attribution
run --profile --gpt                   # gpt per-op time attribution
run --sweep 96,128,192,256            # resnet batch/MFU sweet spot
run --gpt --sweep 32,64,128           # gpt batch/MFU sweet spot
echo "done; results in $LOG" >&2
