"""Headline benchmark: ImageNet ResNet-50, amp-O2-equivalent fused train step,
images/sec on one chip (BASELINE.md config 2; measurement method mirrors
examples/imagenet/main_amp.py:390-397 — world_size*batch/avg_step_time).

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline is measured against 800 img/s/chip — the commonly reported V100
Apex-O2 ResNet-50 number (the reference repo itself publishes no figure,
BASELINE.md).
"""
import json
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, "/root/repo")

import apex_tpu.nn as nn  # noqa: E402
from apex_tpu.models import resnet50  # noqa: E402
from apex_tpu.nn import functional as F  # noqa: E402
from apex_tpu.optimizers import FusedSGD  # noqa: E402
from apex_tpu.training import make_train_step  # noqa: E402

V100_APEX_O2_IMGS_PER_SEC = 800.0


def main():
    batch = int(sys.argv[1]) if len(sys.argv) > 1 else 128
    nn.manual_seed(0)
    model = resnet50(num_classes=1000)
    opt = FusedSGD(list(model.parameters()), lr=0.1, momentum=0.9,
                   weight_decay=1e-4)
    step = make_train_step(
        model, opt, lambda out, y: F.cross_entropy(out, y),
        half_dtype=jnp.bfloat16, loss_scale=1.0)

    rng = np.random.default_rng(0)
    x = jnp.asarray(rng.standard_normal((batch, 3, 224, 224)), jnp.float32)
    y = jnp.asarray(rng.integers(0, 1000, (batch,)))

    # warmup / compile.  NOTE: jax.block_until_ready is a no-op on the
    # experimental axon platform — only an actual device->host fetch
    # synchronizes, so we time the loop against a trailing scalar fetch of
    # the final state (which data-depends on every step).
    for _ in range(3):
        loss = step(x, y)
    float(jnp.sum(step.state.master_params[0]))

    iters = 30
    t0 = time.perf_counter()
    for _ in range(iters):
        loss = step(x, y)
    float(jnp.sum(step.state.master_params[0]))
    dt = (time.perf_counter() - t0) / iters

    imgs_per_sec = batch / dt
    print(json.dumps({
        "metric": "resnet50_imagenet_images_per_sec_per_chip_ampO2",
        "value": round(imgs_per_sec, 1),
        "unit": "images/sec/chip",
        "vs_baseline": round(imgs_per_sec / V100_APEX_O2_IMGS_PER_SEC, 3),
    }))


if __name__ == "__main__":
    main()
